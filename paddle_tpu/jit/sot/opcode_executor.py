"""SOT opcode executor: a CPython 3.12 bytecode VM for graph capture.

Reference analog: `python/paddle/jit/sot/opcode_translator/executor/
opcode_executor.py` (the frame simulator) + `guard.py` (the guard table)
+ the resume-function machinery in `pycode_generator.py`. The TPU-native
re-design collapses those ~35k LoC onto the substrate this framework
already has — eager ops are jax-traceable — so the VM's job is ONLY the
Python-level semantics the tracer cannot see:

* **concretization points**: `bool(t)` / `float(t)` / `int(t)` /
  `len(t)` on a Tensor and tensor-conditioned jumps. In CONCRETE mode
  (capture) the real value is available: the VM records the outcome and
  keeps simulating — the graph does not break. In TRACED mode (inside
  `jax.jit`) the recorded outcome is injected as a compile-time constant
  and the branch tensor is emitted as a guard output, so the compiled
  program checks its own branch assumptions every call (the reference's
  resume-function chain becomes outcome-specialized whole programs).
* **guard sources**: every LOAD_DEREF / LOAD_GLOBAL of a non-callable
  value is recorded with a snapshot, so closure-cell or global mutation
  invalidates the cache entry (the reference's GuardedFunctions).
* **bytecode-only features**: exception tables (try/except/finally on
  3.12 has no SETUP_* opcodes), `with`, loops over concrete iterables,
  inner MAKE_FUNCTION closures — all simulated faithfully; anything
  outside the supported subset raises SotUnsupported and the caller
  falls back (translate.py decides eager vs AST).

Simulation depth: the VM simulates the TOP frame; calls execute natively
(nested tensor ops are traced anyway — the jit sees through them). A
concretization INSIDE a nested call is caught by the scalar-conversion
hook the VM installs for the duration of run()
(`core.tensor.set_scalar_capture_hook`), so a helper doing `int(x)` or
`bool(x)` records/guards exactly like top-frame code instead of silently
baking.
"""
from __future__ import annotations

import dis
import operator
import sys
import types
from typing import Any, Dict, List, Optional

from ...core.tensor import Tensor


class SotUnsupported(Exception):
    """Bytecode/feature outside the VM subset — caller should fall back."""


class GuardViolated(Exception):
    pass


class _Null:
    """The PUSH_NULL sentinel (CPython's internal NULL)."""

    __slots__ = ()

    def __repr__(self):
        return "<NULL>"


NULL = _Null()


_BINARY_OPS = {
    0: operator.add, 1: operator.and_, 2: operator.floordiv,
    3: operator.lshift, 4: operator.matmul, 5: operator.mul,
    6: operator.mod, 7: operator.or_, 8: operator.pow, 9: operator.rshift,
    10: operator.sub, 11: operator.truediv, 12: operator.xor,
    # inplace variants: same function — the VM works on values, and
    # Tensors implement __iadd__ as functional rebind anyway
    13: operator.iadd, 14: operator.iand, 15: operator.ifloordiv,
    16: operator.ilshift, 17: operator.imatmul, 18: operator.imul,
    19: operator.imod, 20: operator.ior, 21: operator.ipow,
    22: operator.irshift, 23: operator.isub, 24: operator.itruediv,
    25: operator.ixor,
}

_COMPARES = {
    "<": operator.lt, "<=": operator.le, "==": operator.eq,
    "!=": operator.ne, ">": operator.gt, ">=": operator.ge,
}

_INTRINSIC_1 = {
    1: lambda v: print(v),       # INTRINSIC_PRINT (interactive only)
    2: None,                     # INTRINSIC_IMPORT_STAR — unsupported
    5: operator.pos,             # INTRINSIC_UNARY_POSITIVE
    6: list,                     # INTRINSIC_LIST_TO_TUPLE (tuple())
}

_SCALAR_BUILTINS = (bool, float, int, len)


class Capture:
    """What a concrete VM pass learned: branch outcomes in encounter
    order + guard sources (closure/global snapshots)."""

    def __init__(self):
        self.outcomes: List[Any] = []       # concrete python scalars
        self.guard_cells: List[tuple] = []  # (kind, name, snapshot)
        self.break_tensors_spec: List[str] = []  # op names, for debugging

    def record_outcome(self, val, tensor, why: str):
        self.outcomes.append(val)
        self.break_tensors_spec.append(why)
        return val


class OpcodeExecutor:
    """Simulate one code object. mode="concrete": real values, outcomes
    recorded into `capture`. mode="traced": tensors are tracer-backed,
    concretizations consume capture.outcomes and append the branch tensor
    to `guard_outputs` (checked against the recorded outcome at runtime).
    """

    def __init__(self, fn, capture: Capture, mode: str = "concrete"):
        # bound methods: remember the receiver BEFORE unwrapping __func__
        self._self_obj = getattr(fn, "__self__", None)
        if not isinstance(fn, types.FunctionType):
            fn = getattr(fn, "__func__", None) or fn
        if not isinstance(fn, types.FunctionType):
            raise SotUnsupported(f"not a plain function: {fn!r}")
        self.fn = fn
        self.code = fn.__code__
        if self.code.co_flags & (0x20 | 0x80 | 0x200):
            # generator / coroutine / async generator
            raise SotUnsupported("generator/coroutine frames")
        self.capture = capture
        self.mode = mode
        self.guard_outputs: List[Any] = []   # traced branch tensors
        self._outcome_idx = 0
        bc = dis.Bytecode(self.code)
        self.instructions = list(bc)
        self.by_offset = {i.offset: idx
                          for idx, i in enumerate(self.instructions)}
        self.exc_table = list(getattr(bc, "exception_entries", []))

    # -- concretization ---------------------------------------------------
    #
    # * top-frame ``float(t)`` stays SYMBOLIC (a 0-d tensor): python
    #   arithmetic on it keeps tracing — no value baked, no per-value
    #   recompile (torch's SymFloat idea).
    # * ``bool(t)`` / jumps record the BRANCH outcome; the compiled
    #   program re-emits the branch tensor and the runtime check compares
    #   bool(value), so any same-path input reuses the program.
    # * ``int(t)`` (and ``float(t)`` reached through Tensor.__float__ in
    #   NESTED calls, where python forces a real float) record the exact
    #   value; a changed value recaptures. Float guards compare with a
    #   small tolerance — eager vs XLA may differ in the last ulp and an
    #   exact compare would recapture every call.
    #
    # Nested-call conversions are caught by the core.tensor scalar hook
    # installed for the duration of run(), so a helper doing ``int(t)``
    # guards exactly like top-frame code.

    def _record_or_inject(self, tensor, to, why):
        if self.mode == "concrete":
            # bypass the hook for the real conversion (we ARE the hook)
            val = _raw_convert(tensor, to)
            return self.capture.record_outcome((to.__name__, val), tensor,
                                               why)[1]
        if self._outcome_idx >= len(self.capture.outcomes):
            raise SotUnsupported("traced pass hit an unrecorded branch")
        kind, val = self.capture.outcomes[self._outcome_idx]
        if kind != to.__name__:
            raise SotUnsupported(
                f"traced pass diverged: expected {kind}, hit {to.__name__}")
        self._outcome_idx += 1
        self.guard_outputs.append(tensor)
        return val

    def _concretize(self, tensor, to, why):
        if to is float:
            import numpy as _np

            if int(_np.prod(tensor.shape)) != 1:
                raise TypeError("only 1-element tensors convert to float")
            out = tensor.reshape([])
            if not _np.issubdtype(_np.dtype(str(out._data.dtype)),
                                  _np.floating):
                out = out.astype("float32")
            return out
        return self._record_or_inject(tensor, to, why)

    def _scalarize(self, v, to, why):
        if isinstance(v, Tensor):
            return self._record_or_inject(v, to, why)
        return to(v)

    def _hook(self, tensor, to):
        """core.tensor scalar-conversion hook: a nested call concretized a
        tensor. Python forces the real type here, so even float() records
        an exact-value outcome."""
        return self._record_or_inject(tensor, to, f"nested_{to.__name__}")

    # -- frame setup ------------------------------------------------------

    def run(self, *args, **kwargs):
        code = self.code
        fn = self.fn
        if self._self_obj is not None:
            args = (self._self_obj,) + args
        # bind arguments (positional + defaults + kwonly); *args/**kwargs
        narg = code.co_argcount
        nkwonly = code.co_kwonlyargcount
        varnames = code.co_varnames
        local: Dict[str, Any] = {}
        pos = list(args)
        has_varargs = bool(code.co_flags & 0x04)
        has_varkw = bool(code.co_flags & 0x08)
        for i in range(narg):
            name = varnames[i]
            if i < len(pos):
                local[name] = pos[i]
            elif name in kwargs:
                local[name] = kwargs.pop(name)
            else:
                defaults = fn.__defaults__ or ()
                j = i - (narg - len(defaults))
                if j < 0:
                    raise TypeError(f"missing argument {name!r}")
                local[name] = defaults[j]
        extra = tuple(pos[narg:])
        if has_varargs:
            local[varnames[narg + nkwonly]] = extra
        elif extra:
            raise TypeError("too many positional arguments")
        for i in range(narg, narg + nkwonly):
            name = varnames[i]
            if name in kwargs:
                local[name] = kwargs.pop(name)
            else:
                kwd = fn.__kwdefaults__ or {}
                if name not in kwd:
                    raise TypeError(f"missing kwonly argument {name!r}")
                local[name] = kwd[name]
        if has_varkw:
            local[varnames[narg + nkwonly + has_varargs]] = dict(kwargs)
        elif kwargs:
            raise TypeError(f"unexpected kwargs {list(kwargs)}")
        # cells: MAKE_CELL creates them; freevars come from __closure__
        cells: Dict[str, Any] = {}
        closure = fn.__closure__ or ()
        for name, cell in zip(code.co_freevars, closure):
            cells[name] = cell
        from ...core import tensor as _tensor_mod

        prev_hook = _tensor_mod.set_scalar_capture_hook(self._hook)
        try:
            return self._execute(local, cells)
        finally:
            _tensor_mod.set_scalar_capture_hook(prev_hook)

    # -- main loop --------------------------------------------------------

    def _execute(self, local, cells):
        stack: List[Any] = []
        blocks: List[Any] = []  # exception handler state
        fn = self.fn
        glb = fn.__globals__
        idx = 0
        kw_names: tuple = ()
        instrs = self.instructions
        n = len(instrs)

        def jump_to(offset):
            nonlocal idx
            idx = self.by_offset[offset]

        while idx < n:
            ins = instrs[idx]
            op = ins.opname
            arg = ins.arg
            val = ins.argval
            idx += 1
            try:
                # ---- loads / stores ----
                if op in ("RESUME", "NOP", "CACHE", "PRECALL",
                          "MAKE_CELL", "COPY_FREE_VARS", "EXTENDED_ARG"):
                    if op == "MAKE_CELL":
                        cells[val] = types.CellType(local.get(val))
                    continue
                if op == "LOAD_CONST":
                    stack.append(val)
                elif op == "RETURN_CONST":
                    return val
                elif op in ("LOAD_FAST", "LOAD_FAST_CHECK"):
                    if val in cells:
                        stack.append(cells[val])  # closure slot (3.12)
                    elif val in local:
                        stack.append(local[val])
                    else:
                        raise UnboundLocalError(val)
                elif op == "LOAD_FAST_AND_CLEAR":
                    stack.append(local.pop(val, NULL))
                elif op == "STORE_FAST":
                    v = stack.pop()
                    if val in cells:
                        cells[val].cell_contents = v
                    else:
                        local[val] = v
                elif op == "DELETE_FAST":
                    del local[val]
                elif op == "LOAD_GLOBAL":
                    if arg & 1:
                        stack.append(NULL)
                    name = val
                    if name in glb:
                        v = glb[name]
                        src = "global"
                    elif name in glb.get("__builtins__", {}) if isinstance(
                            glb.get("__builtins__"), dict) else hasattr(
                            glb.get("__builtins__", object()), name):
                        bi = glb.get("__builtins__")
                        v = (bi[name] if isinstance(bi, dict)
                             else getattr(bi, name))
                        src = "builtin"
                    else:
                        import builtins

                        v = getattr(builtins, name)
                        src = "builtin"
                    if self.mode == "concrete" and src == "global" \
                            and not callable(v) \
                            and not isinstance(v, types.ModuleType):
                        self.capture.guard_cells.append(
                            ("global", name, _snapshot(v)))
                    stack.append(v)
                elif op == "STORE_GLOBAL":
                    glb[val] = stack.pop()
                elif op == "LOAD_DEREF":
                    cell = cells.get(val)
                    if cell is None:
                        raise SotUnsupported(f"unbound deref {val}")
                    v = cell.cell_contents
                    # guard FREE variables only: cellvars are frame-local
                    # state this very frame recreates (guarding them would
                    # never validate — check_guard sees co_freevars)
                    if self.mode == "concrete" and not callable(v) \
                            and val in self.code.co_freevars:
                        self.capture.guard_cells.append(
                            ("deref", val, _snapshot(v)))
                    stack.append(v)
                elif op == "STORE_DEREF":
                    v = stack.pop()
                    if val in cells:
                        cells[val].cell_contents = v
                    else:
                        cells[val] = types.CellType(v)
                elif op == "LOAD_CLOSURE":
                    stack.append(cells[val])
                elif op == "LOAD_ATTR":
                    obj = stack.pop()
                    if arg & 1:
                        # method form: CPython pushes (unbound, self) or
                        # (NULL, attr). Bound-method + NULL is equivalent
                        # under our CALL and needs no descriptor peeking.
                        stack.append(NULL)
                        stack.append(getattr(obj, val))
                    else:
                        stack.append(getattr(obj, val))
                elif op == "STORE_ATTR":
                    obj = stack.pop()
                    v = stack.pop()
                    setattr(obj, val, v)
                elif op == "LOAD_NAME":
                    if val in local:
                        stack.append(local[val])
                    else:
                        import builtins

                        stack.append(glb.get(val, getattr(builtins, val,
                                                          None)))
                # ---- stack ops ----
                elif op == "POP_TOP":
                    stack.pop()
                elif op == "PUSH_NULL":
                    stack.append(NULL)
                elif op == "COPY":
                    stack.append(stack[-arg])
                elif op == "SWAP":
                    stack[-1], stack[-arg] = stack[-arg], stack[-1]
                # ---- build / unpack ----
                elif op == "BUILD_TUPLE":
                    items = _popn(stack, arg)
                    stack.append(tuple(items))
                elif op == "BUILD_LIST":
                    stack.append(_popn(stack, arg))
                elif op == "BUILD_SET":
                    stack.append(set(_popn(stack, arg)))
                elif op == "BUILD_MAP":
                    items = _popn(stack, 2 * arg)
                    stack.append({items[2 * i]: items[2 * i + 1]
                                  for i in range(arg)})
                elif op == "BUILD_CONST_KEY_MAP":
                    keys = stack.pop()
                    vals = _popn(stack, arg)
                    stack.append(dict(zip(keys, vals)))
                elif op == "BUILD_SLICE":
                    items = _popn(stack, arg)
                    stack.append(slice(*items))
                elif op == "BUILD_STRING":
                    items = _popn(stack, arg)
                    stack.append("".join(items))
                elif op == "FORMAT_VALUE":
                    flags = arg
                    spec = stack.pop() if flags & 0x04 else ""
                    v = stack.pop()
                    conv = flags & 0x03
                    if conv == 1:
                        v = str(v)
                    elif conv == 2:
                        v = repr(v)
                    elif conv == 3:
                        v = ascii(v)
                    stack.append(format(v, spec))
                elif op == "LIST_EXTEND":
                    seq = stack.pop()
                    stack[-arg].extend(seq)
                elif op == "LIST_APPEND":
                    v = stack.pop()
                    stack[-arg].append(v)
                elif op == "SET_UPDATE":
                    seq = stack.pop()
                    stack[-arg].update(seq)
                elif op == "SET_ADD":
                    v = stack.pop()
                    stack[-arg].add(v)
                elif op == "MAP_ADD":
                    v = stack.pop()
                    k = stack.pop()
                    stack[-arg][k] = v
                elif op in ("DICT_UPDATE", "DICT_MERGE"):
                    other = stack.pop()
                    stack[-arg].update(other)
                elif op == "UNPACK_SEQUENCE":
                    seq = stack.pop()
                    items = list(seq)
                    if len(items) != arg:
                        raise ValueError("unpack length mismatch")
                    stack.extend(reversed(items))
                elif op == "UNPACK_EX":
                    seq = list(stack.pop())
                    before = arg & 0xFF
                    after = arg >> 8
                    mid = seq[before:len(seq) - after]
                    out = seq[:before] + [mid] + (seq[len(seq) - after:]
                                                  if after else [])
                    stack.extend(reversed(out))
                # ---- operators ----
                elif op == "BINARY_OP":
                    b = stack.pop()
                    a = stack.pop()
                    stack.append(_BINARY_OPS[arg](a, b))
                elif op == "BINARY_SUBSCR":
                    k = stack.pop()
                    obj = stack.pop()
                    stack.append(obj[k])
                elif op == "STORE_SUBSCR":
                    k = stack.pop()
                    obj = stack.pop()
                    v = stack.pop()
                    obj[k] = v
                elif op == "DELETE_SUBSCR":
                    k = stack.pop()
                    obj = stack.pop()
                    del obj[k]
                elif op == "BINARY_SLICE":
                    end = stack.pop()
                    start = stack.pop()
                    obj = stack.pop()
                    stack.append(obj[start:end])
                elif op == "STORE_SLICE":
                    end = stack.pop()
                    start = stack.pop()
                    obj = stack.pop()
                    v = stack.pop()
                    obj[start:end] = v
                elif op == "UNARY_NEGATIVE":
                    stack.append(-stack.pop())
                elif op == "UNARY_INVERT":
                    stack.append(~stack.pop())
                elif op == "UNARY_NOT":
                    v = stack.pop()
                    stack.append(not self._scalarize(v, bool, "not"))
                elif op == "COMPARE_OP":
                    b = stack.pop()
                    a = stack.pop()
                    cmp = val if isinstance(val, str) else val
                    stack.append(_COMPARES[cmp](a, b))
                elif op == "IS_OP":
                    b = stack.pop()
                    a = stack.pop()
                    stack.append((a is not b) if arg else (a is b))
                elif op == "CONTAINS_OP":
                    b = stack.pop()
                    a = stack.pop()
                    r = a in b
                    stack.append((not r) if arg else r)
                elif op == "CALL_INTRINSIC_1":
                    f = _INTRINSIC_1.get(arg)
                    if f is None:
                        raise SotUnsupported(f"intrinsic {arg}")
                    v = stack.pop()
                    stack.append(tuple(v) if arg == 6 else f(v))
                # ---- calls ----
                elif op == "KW_NAMES":
                    kw_names = val
                elif op == "CALL":
                    nargs = arg
                    kwn = kw_names
                    kw_names = ()
                    args_ = _popn(stack, nargs)
                    b = stack.pop()
                    a = stack.pop()
                    if a is NULL:
                        callee, callargs = b, args_
                    else:
                        callee, callargs = a, [b] + args_
                    kwargs_ = {}
                    if kwn:
                        kwvals = callargs[len(callargs) - len(kwn):]
                        callargs = callargs[:len(callargs) - len(kwn)]
                        kwargs_ = dict(zip(kwn, kwvals))
                    stack.append(self._call(callee, callargs, kwargs_))
                elif op == "CALL_FUNCTION_EX":
                    kwargs_ = stack.pop() if arg & 1 else {}
                    args_ = stack.pop()
                    callee = stack.pop()
                    if stack and stack[-1] is NULL:
                        stack.pop()
                    stack.append(self._call(callee, list(args_),
                                            dict(kwargs_)))
                elif op == "MAKE_FUNCTION":
                    code_obj = stack.pop()
                    closure = stack.pop() if arg & 0x08 else None
                    ann = stack.pop() if arg & 0x04 else None
                    kwd = stack.pop() if arg & 0x02 else None
                    dflt = stack.pop() if arg & 0x01 else None
                    f = types.FunctionType(code_obj, glb,
                                           code_obj.co_name, dflt,
                                           closure)
                    if kwd:
                        f.__kwdefaults__ = kwd
                    stack.append(f)
                elif op == "RETURN_VALUE":
                    return stack.pop()
                # ---- jumps / loops ----
                elif op == "JUMP_FORWARD" or op == "JUMP_BACKWARD" \
                        or op == "JUMP_BACKWARD_NO_INTERRUPT":
                    jump_to(val)
                elif op == "POP_JUMP_IF_TRUE":
                    v = stack.pop()
                    if self._scalarize(v, bool, "jump_if_true"):
                        jump_to(val)
                elif op == "POP_JUMP_IF_FALSE":
                    v = stack.pop()
                    if not self._scalarize(v, bool, "jump_if_false"):
                        jump_to(val)
                elif op == "POP_JUMP_IF_NONE":
                    if stack.pop() is None:
                        jump_to(val)
                elif op == "POP_JUMP_IF_NOT_NONE":
                    if stack.pop() is not None:
                        jump_to(val)
                elif op == "GET_ITER":
                    stack.append(iter(stack.pop()))
                elif op == "FOR_ITER":
                    it = stack[-1]
                    try:
                        stack.append(next(it))
                    except StopIteration:
                        stack.append(NULL)  # consumed by END_FOR
                        jump_to(val)
                elif op == "END_FOR":
                    stack.pop()
                    stack.pop()
                # ---- exceptions (3.12 zero-cost try) ----
                elif op == "PUSH_EXC_INFO":
                    v = stack.pop()
                    blocks.append(sys.exc_info()[1])
                    stack.append(blocks[-1] if blocks[-1] is not None
                                 else None)
                    stack.append(v)
                elif op == "CHECK_EXC_MATCH":
                    etype = stack.pop()
                    exc = stack[-1]
                    stack.append(isinstance(exc, etype))
                elif op == "POP_EXCEPT":
                    if blocks:
                        blocks.pop()
                    stack.pop()
                elif op == "RERAISE":
                    exc = stack.pop()
                    if arg:
                        stack.pop()  # saved lasti — meaningless to the VM
                    raise exc
                elif op == "RAISE_VARARGS":
                    if arg == 0:
                        raise SotUnsupported("bare raise outside handler")
                    elif arg == 1:
                        exc = stack.pop()
                        raise exc if isinstance(exc, BaseException) \
                            else exc()
                    else:
                        cause = stack.pop()
                        exc = stack.pop()
                        exc = exc if isinstance(exc, BaseException) else exc()
                        exc.__cause__ = cause
                        raise exc
                elif op == "LOAD_ASSERTION_ERROR":
                    stack.append(AssertionError)
                # ---- with ----
                elif op == "BEFORE_WITH":
                    mgr = stack.pop()
                    exitfn = type(mgr).__exit__.__get__(mgr)
                    enter = type(mgr).__enter__.__get__(mgr)
                    stack.append(exitfn)
                    stack.append(enter())
                elif op == "WITH_EXCEPT_START":
                    exc = stack[-1]
                    exitfn = stack[-4]
                    stack.append(exitfn(type(exc), exc,
                                        exc.__traceback__))
                else:
                    raise SotUnsupported(f"opcode {op}")
            except SotUnsupported:
                raise
            except BaseException as e:  # noqa: BLE001 — route via exc table
                handler = self._find_handler(ins.offset)
                if handler is None:
                    raise
                h_offset, depth, lasti = handler
                del stack[depth:]
                if lasti:
                    stack.append(ins.offset)
                stack.append(e)
                jump_to(h_offset)
        raise SotUnsupported("fell off the end of the bytecode")

    def _find_handler(self, offset):
        for entry in self.exc_table:
            if entry.start <= offset < entry.end:
                return entry.target, entry.depth, entry.lasti
        return None

    def _call(self, callee, args, kwargs):
        # top-frame float()/len() on a Tensor: float stays symbolic (we
        # control the return value here, unlike Tensor.__float__), len is
        # static shape. bool()/int() flow through the dunders, where the
        # scalar hook records them like any nested concretization.
        if len(args) == 1 and isinstance(args[0], Tensor) and not kwargs:
            if callee is len:
                return len(args[0])
            if callee is float:
                return self._concretize(args[0], float, "float")
        if callee is NULL:
            raise SotUnsupported("call through NULL")
        return callee(*args, **kwargs)


def _raw_convert(tensor, to):
    """Convert without re-entering the capture hook (we ARE the hook)."""
    from ...core import tensor as _tensor_mod

    prev = _tensor_mod.set_scalar_capture_hook(None)
    try:
        return to(tensor)
    finally:
        _tensor_mod.set_scalar_capture_hook(prev)


def _snapshot(v):
    """Guard snapshot: by value for simple immutables, by buffer identity
    for tensors (rebinding OR in-place rebind changes id(v._data), so a
    same-shape replacement cannot silently reuse the baked constant), by
    object identity otherwise (reference guard.py: value vs id guards)."""
    if isinstance(v, (int, float, bool, str, bytes, type(None))):
        return ("value", v)
    if isinstance(v, Tensor):
        return ("tensor", id(v), id(v._data))
    return ("id", id(v))


def observed_outcome_key(outcomes, guard_vals):
    """The outcome vector a compiled run ACTUALLY took, derived from its
    guard outputs. Only trustworthy up to (and including) the first
    divergence — values after a flipped branch were computed along the
    wrong path — so callers use it as a cache-lookup HINT whose pick is
    re-validated by its own guards, never as truth."""
    out = []
    for (kind, expected), v in zip(outcomes, guard_vals):
        if kind == "bool":
            out.append((kind, bool(v)))
        elif kind == "int":
            out.append((kind, int(v)))
        else:
            out.append((kind, float(v)))
    return tuple(out)


def branch_guards_ok(outcomes, guard_vals) -> bool:
    """Compare a compiled run's branch tensors against the recorded
    outcomes. Floats tolerate last-ulp eager-vs-XLA drift; an exact
    compare would recapture on every call."""
    for (kind, expected), v in zip(outcomes, guard_vals):
        if kind == "bool":
            ok = bool(v) == expected
        elif kind == "int":
            ok = int(v) == expected
        else:  # float
            a = float(v)
            ok = abs(a - expected) <= 1e-6 * (1.0 + abs(expected))
        if not ok:
            return False
    return True


def check_guard(kind, name, snap, fn):
    """Re-evaluate one guard source against the live function."""
    if kind == "deref":
        code = fn.__code__
        closure = fn.__closure__ or ()
        cellmap = dict(zip(code.co_freevars, closure))
        cell = cellmap.get(name)
        if cell is None:
            return False
        cur = cell.cell_contents
    elif kind == "global":
        if name not in fn.__globals__:
            return False
        cur = fn.__globals__[name]
    else:
        return False
    return _snapshot(cur) == snap


def _popn(stack, n):
    if n == 0:
        return []
    items = stack[-n:]
    del stack[-n:]
    return items

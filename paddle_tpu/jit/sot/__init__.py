"""SOT: symbolic opcode translation (bytecode capture VM).

Reference: `python/paddle/jit/sot/translate.py:31` symbolic_translate,
`opcode_translator/executor/opcode_executor.py` (frame simulation),
`guard.py` (guard table), `pycode_generator.py` (resume functions).

TPU-native architecture (see opcode_executor.py for the full story): the
VM simulates the function's bytecode twice —

1. **concrete pass** (first call / after a guard miss): real tensors,
   eager dispatch, full Python semantics. Tensor→scalar uses are
   recorded as branch outcomes; closure/global reads become guards.
   The pass's outputs ARE that call's results (eager parity).
2. **traced pass** (compilation): the same bytecode re-simulated inside
   `jax.jit` with the recorded outcomes injected, producing ONE
   outcome-specialized XLA program per (input signature × branch path).
   Branch tensors are extra outputs; every compiled call re-checks them
   against the recorded outcomes, so a flipped branch falls back to one
   concrete pass and picks (or captures) the program for the new path —
   the role of the reference's resume-function chain.

`symbolic_translate` wraps a plain function in this machinery;
`paddle.jit.to_static` uses the same VM as its rescue path when direct
tracing graph-breaks (jit/api.py `_build_sot`).
"""
from __future__ import annotations

import functools
from typing import Any, Dict, List, Tuple

import jax

from ...core.tensor import Tensor
from .opcode_executor import (  # noqa: F401
    Capture,
    GuardViolated,
    OpcodeExecutor,
    SotUnsupported,
    branch_guards_ok,
    check_guard,
    observed_outcome_key,
    _snapshot,
)

__all__ = ["symbolic_translate", "SotUnsupported", "Capture",
           "OpcodeExecutor", "branch_guards_ok", "check_guard",
           "observed_outcome_key"]


class SotFunction:
    """Guarded, self-caching compiled wrapper for a plain function
    (tensor-in/tensor-out; Layer state goes through jit/api.py instead).
    """

    def __init__(self, fn):
        self._fn = getattr(fn, "__func__", fn)
        self._bound_self = getattr(fn, "__self__", None)
        # sig -> {"capture": Capture, "programs": {outcome_key: jitted}}
        self._cache: Dict[Any, Dict[str, Any]] = {}
        functools.update_wrapper(self, self._fn)

    # -- capture / compile -------------------------------------------------

    def _sig(self, flat):
        return tuple(
            (tuple(x._data.shape), str(x._data.dtype))
            if isinstance(x, Tensor) else ("py", repr(x))
            for x in flat)

    def _concrete_pass(self, args, kwargs):
        cap = Capture()
        fn = (self._fn.__get__(self._bound_self)
              if self._bound_self is not None else self._fn)
        out = OpcodeExecutor(fn, cap, "concrete").run(*args, **kwargs)
        return cap, out

    def _compile(self, cap, treedef, const_leaves, tensor_slots):
        fn = (self._fn.__get__(self._bound_self)
              if self._bound_self is not None else self._fn)

        def kernel(arrays):
            leaves = list(const_leaves)
            for slot, arr in zip(tensor_slots, arrays):
                leaves[slot] = Tensor._from_data(arr)
            args2, kw2 = jax.tree.unflatten(treedef, leaves)
            ex = OpcodeExecutor(fn, cap, "traced")
            out = ex.run(*args2, **kw2)
            out_arrays = jax.tree.map(
                lambda t: t._data if isinstance(t, Tensor) else t, out,
                is_leaf=lambda x: isinstance(x, Tensor))
            return out_arrays, [g._data for g in ex.guard_outputs]

        return jax.jit(kernel)

    # -- call --------------------------------------------------------------

    def __call__(self, *args, **kwargs):
        flat, treedef = jax.tree.flatten(
            (args, kwargs), is_leaf=lambda x: isinstance(x, Tensor))
        tensor_slots = [i for i, v in enumerate(flat)
                        if isinstance(v, Tensor)]
        const_leaves = [None if i in tensor_slots else v
                        for i, v in enumerate(flat)]
        arrays = [flat[i]._data for i in tensor_slots]
        sig = self._sig(flat)
        state = self._cache.get(sig)
        fn = (self._fn.__get__(self._bound_self)
              if self._bound_self is not None else self._fn)

        def capture_now():
            cap, out = self._concrete_pass(args, kwargs)
            key = tuple(cap.outcomes)
            st = self._cache.setdefault(sig,
                                        {"capture": cap, "programs": {}})
            st["capture"] = cap
            if key not in st["programs"]:
                st["programs"][key] = self._compile(
                    cap, treedef, const_leaves, tensor_slots)
            return out

        if state is None:
            return capture_now()
        if any(isinstance(f, Tensor) and not f.stop_gradient for f in flat):
            # the compiled path returns detached outputs (no GradNode is
            # built here — the full grad plumbing lives in jit/api.py's
            # to_static integration); differentiable inputs always take
            # the concrete pass so the eager tape carries gradients
            return capture_now()
        cap = state["capture"]
        for kind, name, snap in cap.guard_cells:
            if not check_guard(kind, name, snap, fn):
                # closure/global mutated: whole entry invalid
                del self._cache[sig]
                return capture_now()
        program = state["programs"].get(tuple(cap.outcomes))
        if program is None:
            return capture_now()
        try:
            out_arrays, guard_vals = program(arrays)
        except Exception:  # noqa: BLE001 — traced-pass capture gap
            # (e.g. an unrecorded concretization): eager is always valid
            del self._cache[sig]
            return OpcodeExecutor(fn, Capture(), "concrete").run(
                *args, **kwargs)
        if not branch_guards_ok(cap.outcomes, guard_vals):
            # a branch flipped. The observed outcomes are a lookup HINT
            # (trustworthy only up to the first divergence): if that path
            # is already compiled, run it and validate against ITS OWN
            # key — alternating inputs then never pay an eager pass.
            hint = observed_outcome_key(cap.outcomes, guard_vals)
            alt = state["programs"].get(hint)
            if alt is not None:
                out_arrays, guard_vals2 = alt(arrays)
                if branch_guards_ok(list(hint), guard_vals2):
                    return jax.tree.map(
                        lambda a: Tensor._from_data(a)
                        if hasattr(a, "dtype") else a, out_arrays)
            # one concrete pass serves this call + captures the new path
            return capture_now()
        return jax.tree.map(
            lambda a: Tensor._from_data(a) if hasattr(a, "dtype") else a,
            out_arrays)

    @property
    def program_count(self):
        return sum(len(s["programs"]) for s in self._cache.values())


def symbolic_translate(fn, training: bool = False, **kwargs):
    """Reference: sot/translate.py symbolic_translate(fn) -> callable."""
    if isinstance(fn, SotFunction):
        return fn
    return SotFunction(fn)

"""paddle.jit.sot parity surface.

Reference: python/paddle/jit/sot/translate.py:31 — `symbolic_translate`
wraps a function so its execution is captured opcode-by-opcode with
guards and graph breaks. Here the same contract is served by the
dy2static AST converter (jit/dy2static): data-dependent control flow
compiles, anything else graph-breaks to eager. This module maps the SOT
entry points onto that machinery so SOT-style callers work unchanged.
"""
from __future__ import annotations

import functools

from ..dy2static import TransformError, transform_function

__all__ = ["symbolic_translate"]


def symbolic_translate(fn, training: bool = False, **kwargs):
    """Reference: sot/translate.py symbolic_translate(fn) -> callable.

    Returns the AST-converted function (control flow lowered to XLA
    select / lax.while_loop when traced); untransformable functions run
    unchanged — the graph-break behavior then lives at the to_static
    layer that traces them.
    """
    try:
        out = transform_function(fn)
    except TransformError:
        return fn

    @functools.wraps(fn)
    def wrapper(*args, **kw):
        return out(*args, **kw)

    return wrapper

"""Optimizer base + built-ins.

Analog of the reference's `python/paddle/optimizer/optimizer.py:127` Optimizer
and its 16 subclasses. Updates are pure jnp expressions over the param/grad
arrays (XLA fuses each param update into one kernel); accumulators are plain
jax arrays keyed by parameter name, so the whole optimizer state is a pytree
ready for jitted/sharded training steps and for checkpointing.
"""
from __future__ import annotations

import time
import warnings
from typing import Dict, List, Optional

import jax
import jax.numpy as jnp
import numpy as np

from ..core import async_engine, flags
from ..core.tensor import Parameter, Tensor
from ..nn.clip import ClipGradBase
from ..ops.dispatch import no_grad
from .lr import LRScheduler


class _ParamProxy:
    """Stand-in handed to _update/_apply_decay during fused tracing: carries
    the traced data array plus the identity attrs those methods read."""

    __slots__ = ("_data", "name", "optimize_attr")

    def __init__(self, data, name, lr_mult):
        self._data = data
        self.name = name
        self.optimize_attr = {"learning_rate": lr_mult}


# Pre-step hooks: callables fired at the top of every Optimizer.step().
# The DataParallel reducer registers its drain here, so outstanding hook-
# issued bucket collectives are waited on exactly at the step boundary
# instead of a post-backward barrier. Registration is module-global and
# idempotent by function identity.
_pre_step_hooks: List = []


def register_pre_step_hook(fn):
    if fn not in _pre_step_hooks:
        _pre_step_hooks.append(fn)
    return fn


class Optimizer:
    # Whether the math in _update is elementwise over the flat parameter
    # buffer — the condition for the ZeRO-1 sharded update (each rank may
    # update only its contiguous shard). Lamb's per-PARAM trust ratio and
    # LBFGS's closure-driven line search are not; they fall back to the
    # replicated update under FLAGS_dp_shard_update.
    _flat_shardable = True

    def __init__(
        self,
        learning_rate=0.001,
        parameters=None,
        weight_decay=None,
        grad_clip=None,
        name=None,
    ):
        self._learning_rate = learning_rate
        self._parameter_list: Optional[List[Parameter]] = (
            list(parameters) if parameters is not None else None
        )
        self._weight_decay = weight_decay
        self._grad_clip: Optional[ClipGradBase] = grad_clip
        self._accumulators: Dict[str, Dict[str, jnp.ndarray]] = {}
        self._step_count = 0
        # fused-step machinery: one donated executable per param-group
        # signature; a signature fuses from its SECOND occurrence (the first
        # runs the plain loop, which materializes accumulators with their
        # python-side init expressions). Any trace/runtime failure (e.g.
        # RAdam's host-side rho_t branch) disables fusion for this instance.
        self._fused_cache: Dict[tuple, object] = {}
        self._fused_seen: set = set()
        self._fused_disabled = False

    # -- lr ------------------------------------------------------------------
    def get_lr(self) -> float:
        if isinstance(self._learning_rate, LRScheduler):
            return float(self._learning_rate())
        return float(self._learning_rate)

    def set_lr(self, value):
        self._learning_rate = value

    def set_lr_scheduler(self, scheduler):
        self._learning_rate = scheduler

    # -- state ---------------------------------------------------------------
    def _acc(self, param: Parameter, name: str, init=None):
        store = self._accumulators.setdefault(param.name, {})
        if name not in store:
            store[name] = jnp.zeros_like(param._data) if init is None else init
        return store[name]

    def _set_acc(self, param: Parameter, name: str, value):
        self._accumulators.setdefault(param.name, {})[name] = value

    def state_dict(self):
        out = {}
        for pname, accs in self._accumulators.items():
            for aname, arr in accs.items():
                out[f"{pname}.{aname}"] = Tensor._from_data(arr)
        out["@step"] = self._step_count
        if isinstance(self._learning_rate, LRScheduler):
            out["LR_Scheduler"] = self._learning_rate.state_dict()
        return out

    def set_state_dict(self, state):
        for key, val in state.items():
            if key == "@step":
                self._step_count = int(val)
            elif key == "LR_Scheduler":
                if isinstance(self._learning_rate, LRScheduler):
                    self._learning_rate.set_state_dict(val)
            elif "." in key:
                pname, aname = key.rsplit(".", 1)
                arr = val._data if isinstance(val, Tensor) else jnp.asarray(np.asarray(val))
                self._accumulators.setdefault(pname, {})[aname] = arr

    load_state_dict = set_state_dict

    # -- stepping ------------------------------------------------------------
    def _collect_params_grads(self):
        params = self._parameter_list or []
        pg = []
        for p in params:
            if not p.trainable:
                continue
            pg.append((p, p._grad))
        return pg

    def _apply_decay(self, param, grad, lr):
        """L2 regularization folded into the gradient (reference: optimizer
        regularization append). AdamW overrides with decoupled decay."""
        wd = self._weight_decay
        if wd is None or isinstance(wd, str):
            return grad
        coeff = float(wd)
        return grad + coeff * param._data

    @no_grad()
    def step(self):
        for hook in _pre_step_hooks:
            hook()
        pg = self._collect_params_grads()
        if self._grad_clip is not None:
            pg = self._grad_clip(pg)
        lr = self.get_lr()
        pg = [(p, g) for p, g in pg if g is not None]
        if not pg:
            self._step_count += 1
            return
        t0 = time.perf_counter()
        if self._fused_disabled or not flags.flag_value("fused_optimizer"):
            self._eager_step(pg, lr)
            mode = "eager"
        else:
            mode = self._try_fused(pg, lr)
        from ..observability import emit as _obs_emit

        _obs_emit("optimizer.step", dur_s=time.perf_counter() - t0,
                  mode=mode, optimizer=type(self).__name__, params=len(pg))
        self._step_count += 1
        # step boundary for the pipeline: enqueue this step's param buffers;
        # blocks the host only once > FLAGS_eager_async_depth are in flight
        async_engine.mark_step([p._data for p, _ in pg],
                               tag=f"{type(self).__name__}.step")

    def _eager_step(self, pg, lr):
        for p, g in pg:
            plr = lr * getattr(p, "optimize_attr", {}).get("learning_rate", 1.0)
            g = self._apply_decay(p, g, plr)
            p._data = self._update(p, g, plr)

    # -- fused stepping ------------------------------------------------------
    def _fused_key(self, pg):
        try:
            parts = []
            for p, g in pg:
                mult = getattr(p, "optimize_attr", {}).get("learning_rate", 1.0)
                parts.append((p.name, tuple(p._data.shape), str(p._data.dtype),
                              tuple(g.shape), str(g.dtype), float(mult)))
            accs = tuple(sorted(
                (pn, an, tuple(a.shape), str(a.dtype))
                for pn, store in self._accumulators.items()
                for an, a in store.items()))
            return (tuple(parts), accs)
        except Exception:  # noqa: BLE001 — unkeyable group: stay eager
            return None

    def _try_fused(self, pg, lr):
        """Apply this step via the fused donated executable, warming up or
        falling back to the plain per-parameter loop as needed. Returns the
        execution mode actually taken (the optimizer.step metric label)."""
        key = self._fused_key(pg)
        if key is None:
            self._eager_step(pg, lr)
            return "fallback_unkeyable"
        if key not in self._fused_seen:
            # warmup occurrence: the plain loop materializes accumulators
            # (their init expressions are host-side) and validates _update
            self._fused_seen.add(key)
            self._eager_step(pg, lr)
            return "warmup"
        try:
            fn = self._fused_cache.get(key)
            if fn is None:
                fn = self._build_fused(pg)
                self._fused_cache[key] = fn
            param_arrs = [p._data for p, _ in pg]
            grad_arrs = [jnp.asarray(g) for _, g in pg]
            with warnings.catch_warnings():
                # CPU/unshardable buffers make XLA decline the donation with
                # a warning; the update is still correct, just not in-place
                warnings.simplefilter("ignore")
                new_params, new_accs = fn(
                    param_arrs, grad_arrs, self._accumulators,
                    jnp.float32(lr), jnp.int32(self._step_count))
            for (p, _), arr in zip(pg, new_params):
                p._data = arr
            self._accumulators = new_accs
            return "fused"
        except Exception:  # noqa: BLE001 — host-side control flow in
            # _update (RAdam's rho_t branch, LBFGS) cannot trace; run this
            # instance eagerly forever
            self._fused_disabled = True
            self._fused_cache.clear()
            self._eager_step(pg, lr)
            return "fallback_error"

    def _build_fused(self, pg):
        """One executable for the whole parameter group: the per-parameter
        _update loop is traced ONCE (step count and lr enter as traced
        scalars, accumulators as a donated pytree) so every later step is a
        single dispatch with buffer reuse instead of len(params) dispatches."""
        names = [p.name for p, _ in pg]
        mults = [getattr(p, "optimize_attr", {}).get("learning_rate", 1.0)
                 for p, _ in pg]

        def fused(param_arrs, grad_arrs, accs, lr, step_count):
            saved_accs = self._accumulators
            saved_step = self._step_count
            self._accumulators = jax.tree.map(lambda a: a, accs)
            self._step_count = step_count
            try:
                new_params = []
                for name, mult, p_arr, g_arr in zip(names, mults, param_arrs,
                                                    grad_arrs):
                    proxy = _ParamProxy(p_arr, name, mult)
                    plr = lr * mult
                    g = self._apply_decay(proxy, g_arr, plr)
                    new_params.append(self._update(proxy, g, plr))
                return new_params, self._accumulators
            finally:
                self._accumulators = saved_accs
                self._step_count = saved_step

        return jax.jit(fused, donate_argnums=(0, 2))

    def _update(self, param, grad, lr):
        raise NotImplementedError

    def clear_grad(self, set_to_zero=True):
        for p in self._parameter_list or []:
            p.clear_grad()

    clear_gradients = clear_grad

    def minimize(self, loss, startup_program=None, parameters=None, no_grad_set=None):
        from ..static import in_static_mode

        if in_static_mode() and getattr(loss, "_program", None) is not None:
            # static graph: register the train objective on the program;
            # the Executor compiles value_and_grad(replay)+update as one
            # step (reference: append_backward + optimizer ops)
            prog = loss._program
            prog._loss_id = loss._var_id
            prog._optimizer = self
            return None, None
        loss.backward()
        self.step()
        return None, None


class SGD(Optimizer):
    """Reference: python/paddle/optimizer/sgd.py."""

    def _update(self, param, grad, lr):
        return param._data - lr * grad.astype(param._data.dtype)


class Momentum(Optimizer):
    def __init__(self, learning_rate=0.001, momentum=0.9, parameters=None, use_nesterov=False,
                 weight_decay=None, grad_clip=None, name=None):
        super().__init__(learning_rate, parameters, weight_decay, grad_clip, name)
        self._momentum = momentum
        self._nesterov = use_nesterov

    def _update(self, param, grad, lr):
        v = self._acc(param, "velocity")
        v = self._momentum * v + grad
        self._set_acc(param, "velocity", v)
        if self._nesterov:
            return param._data - lr * (grad + self._momentum * v)
        return param._data - lr * v


class Adam(Optimizer):
    """Reference: python/paddle/optimizer/adam.py."""

    def __init__(self, learning_rate=0.001, beta1=0.9, beta2=0.999, epsilon=1e-8,
                 parameters=None, weight_decay=None, grad_clip=None, lazy_mode=False,
                 multi_precision=False, name=None):
        super().__init__(learning_rate, parameters, weight_decay, grad_clip, name)
        self._beta1 = beta1
        self._beta2 = beta2
        self._epsilon = epsilon

    def _update(self, param, grad, lr):
        t = self._step_count + 1
        g32 = grad.astype(jnp.float32)
        m = self._acc(param, "moment1", jnp.zeros(param._data.shape, jnp.float32))
        v = self._acc(param, "moment2", jnp.zeros(param._data.shape, jnp.float32))
        m = self._beta1 * m + (1 - self._beta1) * g32
        v = self._beta2 * v + (1 - self._beta2) * jnp.square(g32)
        self._set_acc(param, "moment1", m)
        self._set_acc(param, "moment2", v)
        mhat = m / (1 - self._beta1**t)
        vhat = v / (1 - self._beta2**t)
        upd = lr * mhat / (jnp.sqrt(vhat) + self._epsilon)
        return (param._data.astype(jnp.float32) - upd).astype(param._data.dtype)


class AdamW(Adam):
    """Decoupled weight decay (reference: python/paddle/optimizer/adamw.py)."""

    def __init__(self, learning_rate=0.001, beta1=0.9, beta2=0.999, epsilon=1e-8,
                 parameters=None, weight_decay=0.01, lr_ratio=None, apply_decay_param_fun=None,
                 grad_clip=None, multi_precision=False, name=None):
        super().__init__(learning_rate, beta1, beta2, epsilon, parameters, None, grad_clip, name=name)
        self._coeff = weight_decay
        self._apply_decay_param_fun = apply_decay_param_fun

    def _apply_decay(self, param, grad, lr):
        return grad  # decay applied decoupled in _update

    def _update(self, param, grad, lr):
        decay = self._coeff
        if self._apply_decay_param_fun is not None and not self._apply_decay_param_fun(param.name):
            decay = 0.0
        out = super()._update(param, grad, lr)
        if decay:
            out = out - (lr * decay) * param._data.astype(out.dtype)
        return out


class Adagrad(Optimizer):
    def __init__(self, learning_rate, epsilon=1e-6, parameters=None, weight_decay=None,
                 grad_clip=None, initial_accumulator_value=0.0, name=None):
        super().__init__(learning_rate, parameters, weight_decay, grad_clip, name)
        self._epsilon = epsilon
        self._init_acc = initial_accumulator_value

    def _update(self, param, grad, lr):
        acc = self._acc(param, "moment", jnp.full(param._data.shape, self._init_acc, jnp.float32))
        acc = acc + jnp.square(grad.astype(jnp.float32))
        self._set_acc(param, "moment", acc)
        return (param._data.astype(jnp.float32) - lr * grad.astype(jnp.float32) / (jnp.sqrt(acc) + self._epsilon)).astype(param._data.dtype)


class RMSProp(Optimizer):
    def __init__(self, learning_rate, rho=0.95, epsilon=1e-6, momentum=0.0, centered=False,
                 parameters=None, weight_decay=None, grad_clip=None, name=None):
        super().__init__(learning_rate, parameters, weight_decay, grad_clip, name)
        self._rho = rho
        self._epsilon = epsilon
        self._momentum = momentum
        self._centered = centered

    def _update(self, param, grad, lr):
        g32 = grad.astype(jnp.float32)
        ms = self._acc(param, "mean_square", jnp.zeros(param._data.shape, jnp.float32))
        ms = self._rho * ms + (1 - self._rho) * jnp.square(g32)
        self._set_acc(param, "mean_square", ms)
        if self._centered:
            mg = self._acc(param, "mean_grad", jnp.zeros(param._data.shape, jnp.float32))
            mg = self._rho * mg + (1 - self._rho) * g32
            self._set_acc(param, "mean_grad", mg)
            denom = jnp.sqrt(ms - jnp.square(mg) + self._epsilon)
        else:
            denom = jnp.sqrt(ms + self._epsilon)
        mom = self._acc(param, "momentum", jnp.zeros(param._data.shape, jnp.float32))
        mom = self._momentum * mom + lr * g32 / denom
        self._set_acc(param, "momentum", mom)
        return (param._data.astype(jnp.float32) - mom).astype(param._data.dtype)


class Adadelta(Optimizer):
    def __init__(self, learning_rate=0.001, epsilon=1e-6, rho=0.95, parameters=None,
                 weight_decay=None, grad_clip=None, name=None):
        super().__init__(learning_rate, parameters, weight_decay, grad_clip, name)
        self._epsilon = epsilon
        self._rho = rho

    def _update(self, param, grad, lr):
        g32 = grad.astype(jnp.float32)
        avg_sq = self._acc(param, "avg_squared_grad", jnp.zeros(param._data.shape, jnp.float32))
        avg_upd = self._acc(param, "avg_squared_update", jnp.zeros(param._data.shape, jnp.float32))
        avg_sq = self._rho * avg_sq + (1 - self._rho) * jnp.square(g32)
        update = -jnp.sqrt(avg_upd + self._epsilon) / jnp.sqrt(avg_sq + self._epsilon) * g32
        avg_upd = self._rho * avg_upd + (1 - self._rho) * jnp.square(update)
        self._set_acc(param, "avg_squared_grad", avg_sq)
        self._set_acc(param, "avg_squared_update", avg_upd)
        return (param._data.astype(jnp.float32) + lr * update).astype(param._data.dtype)


class Adamax(Optimizer):
    def __init__(self, learning_rate=0.001, beta1=0.9, beta2=0.999, epsilon=1e-8,
                 parameters=None, weight_decay=None, grad_clip=None, name=None):
        super().__init__(learning_rate, parameters, weight_decay, grad_clip, name)
        self._beta1, self._beta2, self._epsilon = beta1, beta2, epsilon

    def _update(self, param, grad, lr):
        t = self._step_count + 1
        g32 = grad.astype(jnp.float32)
        m = self._acc(param, "moment", jnp.zeros(param._data.shape, jnp.float32))
        u = self._acc(param, "inf_norm", jnp.zeros(param._data.shape, jnp.float32))
        m = self._beta1 * m + (1 - self._beta1) * g32
        u = jnp.maximum(self._beta2 * u, jnp.abs(g32))
        self._set_acc(param, "moment", m)
        self._set_acc(param, "inf_norm", u)
        return (param._data.astype(jnp.float32) - lr / (1 - self._beta1**t) * m / (u + self._epsilon)).astype(param._data.dtype)


class Lamb(Optimizer):
    """Reference: python/paddle/optimizer/lamb.py."""

    # trust ratio is a per-PARAMETER norm — wrong over a fused flat buffer
    _flat_shardable = False

    def __init__(self, learning_rate=0.001, lamb_weight_decay=0.01, beta1=0.9, beta2=0.999,
                 epsilon=1e-6, parameters=None, grad_clip=None, exclude_from_weight_decay_fn=None,
                 name=None):
        super().__init__(learning_rate, parameters, None, grad_clip, name)
        self._beta1, self._beta2, self._epsilon = beta1, beta2, epsilon
        self._lamb_wd = lamb_weight_decay
        self._exclude_fn = exclude_from_weight_decay_fn

    def _update(self, param, grad, lr):
        t = self._step_count + 1
        g32 = grad.astype(jnp.float32)
        p32 = param._data.astype(jnp.float32)
        m = self._acc(param, "moment1", jnp.zeros(param._data.shape, jnp.float32))
        v = self._acc(param, "moment2", jnp.zeros(param._data.shape, jnp.float32))
        m = self._beta1 * m + (1 - self._beta1) * g32
        v = self._beta2 * v + (1 - self._beta2) * jnp.square(g32)
        self._set_acc(param, "moment1", m)
        self._set_acc(param, "moment2", v)
        mhat = m / (1 - self._beta1**t)
        vhat = v / (1 - self._beta2**t)
        r = mhat / (jnp.sqrt(vhat) + self._epsilon)
        wd = 0.0 if (self._exclude_fn is not None and self._exclude_fn(param)) else self._lamb_wd
        r = r + wd * p32
        w_norm = jnp.sqrt(jnp.sum(jnp.square(p32)))
        r_norm = jnp.sqrt(jnp.sum(jnp.square(r)))
        trust = jnp.where((w_norm > 0) & (r_norm > 0), w_norm / r_norm, 1.0)
        return (p32 - lr * trust * r).astype(param._data.dtype)


class LBFGS(Optimizer):
    """Limited-memory BFGS (reference: python/paddle/optimizer/lbfgs.py).

    `step(closure)` re-evaluates the loss/gradients as needed: two-loop
    recursion over the last `history_size` (s, y) pairs, strong-Wolfe or
    fixed-step line search. All state is host-driven (L-BFGS is inherently
    sequential); the closure's forward/backward is the compiled part.
    """

    _flat_shardable = False  # closure-driven line search over real params

    def __init__(self, learning_rate=1.0, max_iter=20, max_eval=None,
                 tolerance_grad=1e-7, tolerance_change=1e-9, history_size=100,
                 line_search_fn=None, parameters=None, weight_decay=None,
                 grad_clip=None, name=None):
        super().__init__(learning_rate, parameters, weight_decay, grad_clip,
                         name)
        self._max_iter = max_iter
        self._max_eval = max_eval if max_eval is not None else max_iter * 5 // 4
        self._tol_grad = tolerance_grad
        self._tol_change = tolerance_change
        self._history = history_size
        self._line_search = line_search_fn
        self._s, self._y = [], []
        self._prev_flat_grad = None
        self._prev_loss = None

    # flatten helpers -------------------------------------------------------
    def _flat(self, arrs):
        return jnp.concatenate([a.reshape(-1).astype(jnp.float32)
                                for a in arrs])

    def _gather_grads(self):
        return self._flat([p._grad if p._grad is not None
                           else jnp.zeros(p._data.shape) for p in self._params])

    def _assign_flat(self, flat):
        off = 0
        for p in self._params:
            n = int(np.prod(p._data.shape)) if p._data.shape else 1
            p._data = flat[off:off + n].reshape(p._data.shape).astype(
                p._data.dtype)
            off += n

    def _direction(self, flat_grad):
        q = flat_grad
        alphas = []
        for s, y in zip(reversed(self._s), reversed(self._y)):
            rho = 1.0 / jnp.maximum(jnp.vdot(y, s), 1e-10)
            a = rho * jnp.vdot(s, q)
            alphas.append((a, rho, s, y))
            q = q - a * y
        if self._y:
            y_last, s_last = self._y[-1], self._s[-1]
            gamma = jnp.vdot(s_last, y_last) / jnp.maximum(
                jnp.vdot(y_last, y_last), 1e-10)
            q = q * gamma
        for a, rho, s, y in reversed(alphas):
            b = rho * jnp.vdot(y, q)
            q = q + s * (a - b)
        return -q

    def step(self, closure=None):
        if closure is None:
            raise ValueError("LBFGS.step requires a closure that "
                             "re-evaluates the model and returns the loss")
        loss = closure()
        lr = self.get_lr() if hasattr(self, "get_lr") else self._learning_rate
        lr = float(lr if not hasattr(lr, "get_lr") else lr.get_lr())
        n_eval = 1
        for _ in range(self._max_iter):
            flat_grad = self._gather_grads()
            if float(jnp.abs(flat_grad).max()) <= self._tol_grad:
                break
            if self._prev_flat_grad is not None:
                y = flat_grad - self._prev_flat_grad
                s = self._last_step
                if float(jnp.vdot(y, s)) > 1e-10:
                    self._s.append(s)
                    self._y.append(y)
                    if len(self._s) > self._history:
                        self._s.pop(0)
                        self._y.pop(0)
            d = self._direction(flat_grad)
            x0 = self._flat([p._data for p in self._params])
            t = lr if self._y else min(1.0, 1.0 / max(
                float(jnp.abs(flat_grad).sum()), 1e-10)) * lr
            if self._line_search == "strong_wolfe":
                t, loss, n_ls = self._strong_wolfe(closure, x0, d, t, loss,
                                                   flat_grad)
                n_eval += n_ls
            else:
                self._assign_flat(x0 + t * d)
                self.clear_grad()
                loss = closure()
                n_eval += 1
            self._last_step = self._flat(
                [p._data for p in self._params]) - x0
            self._prev_flat_grad = flat_grad
            if self._prev_loss is not None and abs(
                    float(loss.numpy()) - self._prev_loss) < self._tol_change:
                self._prev_loss = float(loss.numpy())
                break
            self._prev_loss = float(loss.numpy())
            if n_eval >= self._max_eval:
                break
        self._step_count += 1
        return loss

    def _strong_wolfe(self, closure, x0, d, t, f0, g0, c1=1e-4, c2=0.9,
                      max_ls=10):
        """Backtracking satisfying Armijo + curvature (compact variant of
        the reference's _strong_wolfe)."""
        f0v = float(f0.numpy())
        gtd0 = float(jnp.vdot(g0, d))
        n_eval = 0
        best_t, best_loss = t, f0
        for _ in range(max_ls):
            self._assign_flat(x0 + t * d)
            self.clear_grad()
            loss = closure()
            n_eval += 1
            fv = float(loss.numpy())
            g = self._gather_grads()
            gtd = float(jnp.vdot(g, d))
            if fv <= f0v + c1 * t * gtd0 and abs(gtd) <= c2 * abs(gtd0):
                return t, loss, n_eval
            best_t, best_loss = t, loss
            t *= 0.5
        return best_t, best_loss, n_eval


class ASGD(Optimizer):
    """Averaged SGD (reference: python/paddle/optimizer/asgd.py): keeps a
    running average of the last `t_half`-window gradients (the reference's
    simplified d/y-register formulation)."""

    def __init__(self, learning_rate=0.001, batch_num=1, parameters=None,
                 weight_decay=None, grad_clip=None, multi_precision=False,
                 name=None):
        super().__init__(learning_rate, parameters, weight_decay, grad_clip,
                         name)
        self._batch_num = max(1, int(batch_num))

    def _update(self, param, grad, lr):
        g32 = grad.astype(jnp.float32)
        n = self._batch_num
        d = self._acc(param, "d", jnp.zeros(param._data.shape, jnp.float32))
        # ys holds the window's gradient slots; rotate through them
        idx = self._step_count % n
        ys = self._acc(param, "ys",
                       jnp.zeros((n, *param._data.shape), jnp.float32))
        old = ys[idx]
        d = d - old + g32
        ys = ys.at[idx].set(g32)
        self._set_acc(param, "d", d)
        self._set_acc(param, "ys", ys)
        return (param._data.astype(jnp.float32) - lr * d / n).astype(
            param._data.dtype)


class NAdam(Optimizer):
    """Reference: python/paddle/optimizer/nadam.py (Adam + Nesterov
    momentum schedule mu_t = beta1 * (1 - 0.5 * 0.96^(0.004 t)))."""

    def __init__(self, learning_rate=0.001, beta1=0.9, beta2=0.999,
                 epsilon=1e-8, momentum_decay=0.004, parameters=None,
                 weight_decay=None, grad_clip=None, name=None):
        super().__init__(learning_rate, parameters, weight_decay, grad_clip,
                         name)
        self._beta1, self._beta2, self._epsilon = beta1, beta2, epsilon
        self._psi = momentum_decay

    def _update(self, param, grad, lr):
        t = self._step_count + 1
        g32 = grad.astype(jnp.float32)
        m = self._acc(param, "moment1",
                      jnp.zeros(param._data.shape, jnp.float32))
        v = self._acc(param, "moment2",
                      jnp.zeros(param._data.shape, jnp.float32))
        mu_t = self._beta1 * (1.0 - 0.5 * 0.96 ** (self._psi * t))
        mu_next = self._beta1 * (1.0 - 0.5 * 0.96 ** (self._psi * (t + 1)))
        prod = self._acc(param, "mu_product",
                         jnp.ones((), jnp.float32))
        prod_t = prod * mu_t
        m = self._beta1 * m + (1 - self._beta1) * g32
        v = self._beta2 * v + (1 - self._beta2) * jnp.square(g32)
        self._set_acc(param, "moment1", m)
        self._set_acc(param, "moment2", v)
        self._set_acc(param, "mu_product", prod_t)
        m_hat = (mu_next * m / (1 - prod_t * mu_next)
                 + (1 - mu_t) * g32 / (1 - prod_t))
        v_hat = v / (1 - self._beta2 ** t)
        upd = lr * m_hat / (jnp.sqrt(v_hat) + self._epsilon)
        return (param._data.astype(jnp.float32) - upd).astype(
            param._data.dtype)


class RAdam(Optimizer):
    """Rectified Adam (reference: python/paddle/optimizer/radam.py):
    falls back to SGD-with-momentum while the variance estimate's
    rectification term rho_t <= 4."""

    def __init__(self, learning_rate=0.001, beta1=0.9, beta2=0.999,
                 epsilon=1e-8, parameters=None, weight_decay=None,
                 grad_clip=None, name=None):
        super().__init__(learning_rate, parameters, weight_decay, grad_clip,
                         name)
        self._beta1, self._beta2, self._epsilon = beta1, beta2, epsilon

    def _update(self, param, grad, lr):
        t = self._step_count + 1
        g32 = grad.astype(jnp.float32)
        m = self._acc(param, "moment1",
                      jnp.zeros(param._data.shape, jnp.float32))
        v = self._acc(param, "moment2",
                      jnp.zeros(param._data.shape, jnp.float32))
        m = self._beta1 * m + (1 - self._beta1) * g32
        v = self._beta2 * v + (1 - self._beta2) * jnp.square(g32)
        self._set_acc(param, "moment1", m)
        self._set_acc(param, "moment2", v)
        rho_inf = 2.0 / (1 - self._beta2) - 1.0
        beta2_t = self._beta2 ** t
        rho_t = rho_inf - 2.0 * t * beta2_t / (1 - beta2_t)
        m_hat = m / (1 - self._beta1 ** t)
        if rho_t > 5.0:  # reference radam.py: rectify only when rho_t > 5
            r = ((rho_t - 4) * (rho_t - 2) * rho_inf
                 / ((rho_inf - 4) * (rho_inf - 2) * rho_t)) ** 0.5
            v_hat = jnp.sqrt(v / (1 - beta2_t))
            upd = lr * r * m_hat / (v_hat + self._epsilon)
        else:
            upd = lr * m_hat
        return (param._data.astype(jnp.float32) - upd).astype(
            param._data.dtype)


class Rprop(Optimizer):
    """Resilient backprop (reference: python/paddle/optimizer/rprop.py):
    per-weight step sizes grown/shrunk by the gradient sign agreement;
    full-batch algorithm."""

    def __init__(self, learning_rate=0.001, learning_rate_range=(1e-5, 50.0),
                 parameters=None, etas=(0.5, 1.2), grad_clip=None,
                 name=None):
        super().__init__(learning_rate, parameters, None, grad_clip, name)
        self._lr_min, self._lr_max = learning_rate_range
        self._eta_neg, self._eta_pos = etas

    def _update(self, param, grad, lr):
        g32 = grad.astype(jnp.float32)
        prev = self._acc(param, "prev_grad",
                         jnp.zeros(param._data.shape, jnp.float32))
        steps = self._acc(param, "step_size",
                          jnp.full(param._data.shape, float(lr), jnp.float32))
        sign = jnp.sign(prev * g32)
        factor = jnp.where(sign > 0, self._eta_pos,
                           jnp.where(sign < 0, self._eta_neg, 1.0))
        steps = jnp.clip(steps * factor, self._lr_min, self._lr_max)
        # sign change: zero the gradient for this step (classic Rprop-)
        g_eff = jnp.where(sign < 0, 0.0, g32)
        self._set_acc(param, "prev_grad", g_eff)
        self._set_acc(param, "step_size", steps)
        upd = steps * jnp.sign(g_eff)
        return (param._data.astype(jnp.float32) - upd).astype(
            param._data.dtype)

"""paddle.optimizer-compatible API (reference: python/paddle/optimizer)."""
from . import lr  # noqa: F401
from .optimizer import (  # noqa: F401
    ASGD,
    LBFGS,
    SGD,
    Adadelta,
    Adagrad,
    Adam,
    Adamax,
    AdamW,
    Lamb,
    Momentum,
    NAdam,
    Optimizer,
    RAdam,
    RMSProp,
    Rprop,
)

"""paddle.linalg namespace parity.

Reference: python/paddle/linalg.py re-exporting tensor/linalg.py — here the
ops live in the registry (ops/kernels/linalg.py, XLA-lowered) and this
module provides the namespace with paddle argument conventions.
"""
from __future__ import annotations

from ..ops.dispatch import OPS as _OPS

cholesky = _OPS["cholesky"]
cholesky_solve = _OPS["cholesky_solve"]
cond = _OPS["cond"]
corrcoef = _OPS["corrcoef"]
cov = _OPS["cov"]
det = _OPS["det"]
eig = _OPS["eig"]
eigh = _OPS["eigh"]
eigvalsh = _OPS["eigvalsh"]
householder_product = _OPS["householder_product"]
inv = _OPS["inverse"]
lstsq = _OPS["lstsq"]
lu = _OPS["lu"]
matrix_power = _OPS["matrix_power"]
matrix_rank = _OPS["matrix_rank"]
multi_dot = _OPS["multi_dot"]
norm = _OPS["norm"]
pinv = _OPS["pinv"]
qr = _OPS["qr"]
slogdet = _OPS["slogdet"]
solve = _OPS["solve"]
svd = _OPS["svd"]
triangular_solve = _OPS["triangular_solve"]


def eigvals(x):
    vals, _ = eig(x)
    return vals


def matmul(x, y, transpose_x=False, transpose_y=False):
    return _OPS["matmul"](x, y, transpose_x, transpose_y)


def vector_norm(x, p=2.0, axis=None, keepdim=False):
    return _OPS["p_norm"](x, p, -1 if axis is None else axis, keepdim)


def matrix_norm(x, p="fro", axis=(-2, -1), keepdim=False):
    import jax.numpy as jnp

    from ..ops.dispatch import call_op

    def kernel(x):
        return jnp.linalg.norm(x, ord=p, axis=tuple(axis), keepdims=keepdim)

    return call_op("matrix_norm", kernel, (x,), {})


def svdvals(x):
    _, s, _ = svd(x)
    return s


def matrix_transpose(x):
    return _OPS["transpose"](
        x, list(range(x.ndim - 2)) + [x.ndim - 1, x.ndim - 2])


def matrix_exp(x):
    import jax.scipy.linalg as jsl

    from ..ops.dispatch import call_op

    return call_op("matrix_exp", lambda a: jsl.expm(a), (x,), {})


def pca_lowrank(x, q=None, center=True, niter=2):
    import jax.numpy as jnp

    from ..core.tensor import Tensor
    from ..ops.dispatch import call_op

    def kernel(a):
        m, n = a.shape[-2:]
        k = q if q is not None else min(6, m, n)
        if center:
            a = a - jnp.mean(a, axis=-2, keepdims=True)
        u, s, vt = jnp.linalg.svd(a, full_matrices=False)
        return u[..., :k], s[..., :k], jnp.swapaxes(vt, -2, -1)[..., :k]

    return call_op("pca_lowrank", kernel, (x,), {})


# round-5 tail: factor helpers shared with the tensor compat surface
def cholesky_inverse(x, upper=False, name=None):
    from ..tensor.compat_ext import cholesky_inverse as _ci

    return _ci(x, upper)


def ormqr(x, tau, y, left=True, transpose=False, name=None):
    from ..tensor.compat_ext import ormqr as _o

    return _o(x, tau, y, left, transpose)


def svd_lowrank(x, q=6, niter=2, M=None, name=None):
    from ..tensor.compat_ext import svd_lowrank as _s

    return _s(x, q, niter, M)


def lu_unpack(x, y, unpack_ludata=True, unpack_pivots=True, name=None):
    """Reference signature: lu_unpack(x, y) where x is the packed LU and
    y the pivots."""
    return _OPS["lu_unpack"](x, y, unpack_ludata, unpack_pivots)


def vecdot(x, y, axis=-1, name=None):
    """Reference: linalg vecdot — sum(conj(x) * y) along `axis`. Routed
    through call_op so autograd/AMP see it like the rest of the module."""
    import jax.numpy as jnp

    from ..ops.dispatch import call_op

    def kernel(a, b):
        return jnp.sum(jnp.conj(a) * b, axis=axis)

    return call_op("vecdot", kernel, (x, y), {})

"""nn.functional — the functional NN API.

Analog of `python/paddle/nn/functional/*` (reference). Thin wrappers mapping
paddle signatures onto the op registry (`paddle_tpu.ops`). The round-5
tail (pool/conv wrappers, loss compositions, in-place spellings) lives in
extra.py and is star-imported at the END of this module (it imports names
from here).
"""
from __future__ import annotations

import numpy as np

from ... import _C_ops
from ...core.tensor import Tensor

# Re-export elementwise activations straight from the op registry ------------
relu = _C_ops.relu
relu6 = _C_ops.relu6
leaky_relu = _C_ops.leaky_relu
prelu = _C_ops.prelu
elu = _C_ops.elu
selu = _C_ops.selu
celu = _C_ops.celu
gelu = _C_ops.gelu
silu = _C_ops.silu
swish = _C_ops.swish
mish = _C_ops.mish
hardswish = _C_ops.hardswish
hardsigmoid = _C_ops.hardsigmoid
hardtanh = _C_ops.hardtanh
hardshrink = _C_ops.hardshrink
softshrink = _C_ops.softshrink
tanhshrink = _C_ops.tanhshrink
softplus = _C_ops.softplus
softsign = _C_ops.softsign
thresholded_relu = _C_ops.thresholded_relu
log_sigmoid = _C_ops.log_sigmoid
sigmoid = _C_ops.sigmoid
tanh = _C_ops.tanh
softmax = _C_ops.softmax
log_softmax = _C_ops.log_softmax
gumbel_softmax = _C_ops.gumbel_softmax
maxout = _C_ops.maxout
glu = _C_ops.glu
swiglu = _C_ops.swiglu

linear = _C_ops.linear
embedding_op = _C_ops.embedding
conv1d = _C_ops.conv1d
conv2d = _C_ops.conv2d
conv3d = _C_ops.conv3d
conv2d_transpose = _C_ops.conv2d_transpose
conv3d_transpose = _C_ops.conv3d_transpose
max_pool1d = _C_ops.max_pool1d
avg_pool1d = _C_ops.avg_pool1d
max_pool2d = _C_ops.max_pool2d
avg_pool2d = _C_ops.avg_pool2d
adaptive_avg_pool2d = _C_ops.adaptive_avg_pool2d
adaptive_max_pool2d = _C_ops.adaptive_max_pool2d
pad = _C_ops.pad
unfold = _C_ops.unfold
pixel_shuffle = _C_ops.pixel_shuffle
one_hot = _C_ops.one_hot
layer_norm = _C_ops.layer_norm
rms_norm = _C_ops.rms_norm
group_norm = _C_ops.group_norm
instance_norm = _C_ops.instance_norm
local_response_norm = _C_ops.local_response_norm
scaled_dot_product_attention = _C_ops.scaled_dot_product_attention


def embedding(x, weight, padding_idx=None, sparse=False, name=None):
    return embedding_op(x, weight, padding_idx, sparse)


def dropout(x, p=0.5, axis=None, training=True, mode="upscale_in_train", name=None):
    if axis is not None:
        raise NotImplementedError("dropout axis is not supported yet")
    return _C_ops.dropout(x, p, training, mode)


def dropout2d(x, p=0.5, training=True, data_format="NCHW", name=None):
    return _C_ops.dropout(x, p, training, "upscale_in_train")


def batch_norm(
    x,
    running_mean,
    running_var,
    weight=None,
    bias=None,
    training=False,
    momentum=0.9,
    epsilon=1e-05,
    data_format="NCHW",
    use_global_stats=None,
    name=None,
):
    """Functional batch_norm. In training mode the caller (the BatchNorm layer)
    is responsible for updating running stats from the returned batch stats."""
    if training and not use_global_stats:
        out, _, _ = _C_ops.batch_norm_train(x, weight, bias, epsilon, data_format)
        return out
    return _C_ops.batch_norm_infer(x, running_mean, running_var, weight, bias, epsilon, data_format)


def normalize(x, p=2, axis=1, epsilon=1e-12, name=None):
    norm = _C_ops.p_norm(x, float(p), axis, True, epsilon)
    return _C_ops.divide(x, _C_ops.maximum(norm, _C_ops.full_like(norm, epsilon)))


def cosine_similarity(x1, x2, axis=1, eps=1e-8):
    dot = _C_ops.sum(_C_ops.multiply(x1, x2), axis)
    n1 = _C_ops.sqrt(_C_ops.sum(_C_ops.multiply(x1, x1), axis))
    n2 = _C_ops.sqrt(_C_ops.sum(_C_ops.multiply(x2, x2), axis))
    denom = _C_ops.maximum(_C_ops.multiply(n1, n2), _C_ops.full_like(n1, eps * eps))
    return _C_ops.divide(dot, denom)


def interpolate(
    x, size=None, scale_factor=None, mode="nearest", align_corners=False, data_format="NCHW", name=None
):
    if size is None:
        h = x.shape[2] if data_format == "NCHW" else x.shape[1]
        w = x.shape[3] if data_format == "NCHW" else x.shape[2]
        sf = scale_factor if isinstance(scale_factor, (list, tuple)) else [scale_factor, scale_factor]
        size = [int(h * sf[0]), int(w * sf[1])]
    size = [int(s) for s in size]
    if mode == "nearest":
        return _C_ops.interpolate_nearest(x, size, data_format)
    if mode in ("bilinear", "linear"):
        return _C_ops.interpolate_bilinear(x, size, align_corners, data_format)
    raise NotImplementedError(f"interpolate mode {mode}")


upsample = interpolate


# ---- losses ----------------------------------------------------------------
def _reduce(loss, reduction):
    if reduction == "mean":
        return _C_ops.mean(loss)
    if reduction == "sum":
        return _C_ops.sum(loss)
    return loss


def cross_entropy(
    input,
    label,
    weight=None,
    ignore_index=-100,
    reduction="mean",
    soft_label=False,
    axis=-1,
    use_softmax=True,
    label_smoothing=0.0,
    name=None,
):
    """Reference: python/paddle/nn/functional/loss.py cross_entropy →
    softmax_with_cross_entropy kernel."""
    if label_smoothing > 0.0:
        n = input.shape[axis]
        if not soft_label:
            label = one_hot(label, n)
            soft_label = True
        smooth = _C_ops.scale(label, 1.0 - label_smoothing, label_smoothing / n)
        label = smooth
    if not use_softmax:
        logp = _C_ops.log(input)
        if soft_label:
            loss = _C_ops.scale(_C_ops.sum(_C_ops.multiply(label, logp), axis, None, True), -1.0)
        else:
            return nll_loss(_C_ops.log(input), label, weight, ignore_index, reduction)
    else:
        loss = _C_ops.softmax_with_cross_entropy(input, label, soft_label, ignore_index, axis)
    if weight is not None and not soft_label:
        w = _C_ops.reshape(_C_ops.gather(weight, _C_ops.reshape(label, [-1])), loss.shape)
        loss = _C_ops.multiply(loss, w)
        if reduction == "mean":
            return _C_ops.divide(_C_ops.sum(loss), _C_ops.sum(w))
    if reduction == "mean" and not soft_label and ignore_index >= 0:
        valid = _C_ops.cast(_C_ops.not_equal(label, _C_ops.full_like(label, ignore_index)), "float32")
        return _C_ops.divide(_C_ops.sum(loss), _C_ops.maximum(_C_ops.sum(valid), _C_ops.full([], 1.0)))
    return _reduce(loss, reduction)


def nll_loss(input, label, weight=None, ignore_index=-100, reduction="mean", name=None):
    return _C_ops.nll_loss(input, label, weight, ignore_index, reduction)


def mse_loss(input, label, reduction="mean", name=None):
    return _reduce(_C_ops.square(_C_ops.subtract(input, label)), reduction)


def l1_loss(input, label, reduction="mean", name=None):
    return _reduce(_C_ops.abs(_C_ops.subtract(input, label)), reduction)


def smooth_l1_loss(input, label, reduction="mean", delta=1.0, name=None):
    return _reduce(_C_ops.huber_loss(input, label, delta), reduction)


def binary_cross_entropy(input, label, weight=None, reduction="mean", name=None):
    eps = 1e-12
    loss = _C_ops.scale(
        _C_ops.add(
            _C_ops.multiply(label, _C_ops.log(_C_ops.clip(input, eps, 1.0))),
            _C_ops.multiply(
                _C_ops.scale(label, -1.0, 1.0),
                _C_ops.log(_C_ops.clip(_C_ops.scale(input, -1.0, 1.0), eps, 1.0)),
            ),
        ),
        -1.0,
    )
    if weight is not None:
        loss = _C_ops.multiply(loss, weight)
    return _reduce(loss, reduction)


def binary_cross_entropy_with_logits(
    logit, label, weight=None, reduction="mean", pos_weight=None, name=None
):
    loss = _C_ops.bce_with_logits(logit, label, weight, pos_weight)
    return _reduce(loss, reduction)


def kl_div(input, label, reduction="mean", log_target=False, name=None):
    return _C_ops.kl_div(input, label, reduction, log_target)


def softmax_with_cross_entropy(logits, label, soft_label=False, ignore_index=-100, axis=-1, **kw):
    return _C_ops.softmax_with_cross_entropy(logits, label, soft_label, ignore_index, axis)


def square_error_cost(input, label):
    return _C_ops.square(_C_ops.subtract(input, label))


def margin_ranking_loss(input, other, label, margin=0.0, reduction="mean", name=None):
    out = _C_ops.relu(
        _C_ops.add(
            _C_ops.multiply(_C_ops.scale(label, -1.0), _C_ops.subtract(input, other)),
            _C_ops.full([], margin),
        )
    )
    return _reduce(out, reduction)


def flatten(x, start_axis=0, stop_axis=-1, name=None):
    return _C_ops.flatten(x, start_axis, stop_axis)


# Context-parallel attention (long-context first-class; SURVEY.md §7)
from ...ops.ring_attention import (  # noqa: E402, F401
    ring_attention,
    ring_attention_shard,
    sep_attention_shard,
)


# ---- sampling / detection / sequence (vision_ops kernels) -----------------
def grid_sample(x, grid, mode="bilinear", padding_mode="zeros",
                align_corners=True, name=None):
    return _C_ops.grid_sample(x, grid, mode=mode, padding_mode=padding_mode,
                              align_corners=align_corners)


def affine_grid(theta, out_shape, align_corners=True, name=None):
    return _C_ops.affine_grid(theta, tuple(int(v) for v in out_shape),
                              align_corners=align_corners)


def ctc_loss(log_probs, labels, input_lengths, label_lengths, blank=0,
             reduction="mean", norm_by_times=False):
    """Reference: python/paddle/nn/functional/loss.py ctc_loss (warpctc)."""
    nll = _C_ops.ctc_loss(log_probs, labels, input_lengths, label_lengths,
                          blank=blank, norm_by_times=norm_by_times)
    if reduction == "mean":
        return (nll / label_lengths.astype("float32")).mean()
    if reduction == "sum":
        return nll.sum()
    return nll


def pixel_unshuffle(x, downscale_factor, data_format="NCHW", name=None):
    return _C_ops.pixel_unshuffle(x, downscale_factor=downscale_factor,
                                  data_format=data_format)


def channel_shuffle(x, groups, data_format="NCHW", name=None):
    return _C_ops.channel_shuffle(x, groups=groups, data_format=data_format)


def temporal_shift(x, seg_num, shift_ratio=0.25, data_format="NCHW",
                   name=None):
    return _C_ops.temporal_shift(x, seg_num=seg_num, shift_ratio=shift_ratio,
                                 data_format=data_format)


def max_pool2d_with_index(x, kernel_size, stride=None, padding=0,
                          global_pooling=False, name=None):
    return _C_ops.max_pool2d_with_index(
        x, kernel_size, stride=stride, padding=padding,
        global_pooling=global_pooling)


from .extra import *  # noqa: F401,F403,E402  (round-5 functional tail)

"""nn.functional tail (reference: python/paddle/nn/functional/*) — the
names the reference exports that are op re-exports, pool/conv wrappers, or
pure-Python loss compositions. Imported * into nn.functional.
"""
from __future__ import annotations

import numpy as np

from ... import _C_ops
from ...core.tensor import Tensor
from ...ops.dispatch import OPS

__all__ = [
    # op re-exports
    "bilinear", "class_center_sample", "flashmask_attention", "fold",
    "fractional_max_pool2d", "fractional_max_pool3d", "gather_tree",
    "hsigmoid_loss", "label_smooth", "log_loss", "lp_pool2d",
    "margin_cross_entropy", "rrelu", "sequence_mask", "sparse_attention",
    "adaptive_avg_pool1d", "adaptive_avg_pool3d", "adaptive_max_pool1d",
    "adaptive_max_pool3d",
    # wrappers / compositions
    "avg_pool3d", "max_pool3d", "max_unpool1d", "max_unpool2d",
    "max_unpool3d", "lp_pool1d", "conv1d_transpose", "zeropad2d",
    "alpha_dropout", "feature_alpha_dropout", "dropout3d", "dice_loss",
    "npair_loss", "pairwise_distance", "cosine_embedding_loss",
    "gaussian_nll_loss", "hinge_embedding_loss",
    "multi_label_soft_margin_loss", "multi_margin_loss",
    "poisson_nll_loss", "soft_margin_loss", "sigmoid_focal_loss",
    "triplet_margin_loss", "triplet_margin_with_distance_loss",
    "rnnt_loss", "adaptive_log_softmax_with_loss",
    "flash_attn_qkvpacked", "flash_attn_varlen_qkvpacked",
    # in-place activation spellings
    "elu_", "hardtanh_", "leaky_relu_", "relu_", "softmax_", "tanh_",
    "thresholded_relu_",
]

# -- op re-exports -----------------------------------------------------------
bilinear = _C_ops.bilinear
class_center_sample = _C_ops.class_center_sample
flashmask_attention = _C_ops.flashmask_attention
fold = _C_ops.fold
fractional_max_pool2d = _C_ops.fractional_max_pool2d
fractional_max_pool3d = _C_ops.fractional_max_pool3d
gather_tree = _C_ops.gather_tree
hsigmoid_loss = _C_ops.hsigmoid_loss
label_smooth = _C_ops.label_smooth
log_loss = _C_ops.log_loss
lp_pool2d = _C_ops.lp_pool2d
margin_cross_entropy = _C_ops.margin_cross_entropy
rrelu = _C_ops.rrelu
sequence_mask = _C_ops.sequence_mask
sparse_attention = _C_ops.sparse_attention
# the four new pool kernels resolve via the live registry so this module
# imports during the manifest-regeneration bootstrap (gen_op_manifest
# imports the package BEFORE the YAML gains the new entries); the YAML
# entry + generated binding exist too — set equality is test-enforced
def adaptive_avg_pool1d(x, output_size, name=None):
    return OPS["adaptive_avg_pool1d"](x, output_size)


def adaptive_avg_pool3d(x, output_size, data_format="NCDHW", name=None):
    return OPS["adaptive_avg_pool3d"](x, output_size, data_format)


def adaptive_max_pool1d(x, output_size, return_mask=False, name=None):
    return OPS["adaptive_max_pool1d"](x, output_size, return_mask)


def adaptive_max_pool3d(x, output_size, return_mask=False,
                        data_format="NCDHW", name=None):
    return OPS["adaptive_max_pool3d"](x, output_size, return_mask,
                                      data_format)


def _reduce(loss, reduction):
    if reduction == "mean":
        return OPS["mean"](loss)
    if reduction == "sum":
        return OPS["sum"](loss)
    return loss


# -- pooling / conv wrappers -------------------------------------------------

def max_pool3d(x, kernel_size, stride=None, padding=0, ceil_mode=False,
               return_mask=False, data_format="NCDHW", name=None):
    if return_mask:
        out = OPS["max_pool3d_with_index"](x, kernel_size, stride, padding)
        return out
    return OPS["pool3d"](x, kernel_size, stride, padding,
                         pooling_type="max", ceil_mode=ceil_mode)


def avg_pool3d(x, kernel_size, stride=None, padding=0, ceil_mode=False,
               exclusive=True, divisor_override=None, data_format="NCDHW",
               name=None):
    out = OPS["pool3d"](x, kernel_size, stride, padding,
                        pooling_type="avg", ceil_mode=ceil_mode,
                        count_include_pad=not exclusive)
    if divisor_override is not None:
        k = kernel_size if isinstance(kernel_size, (list, tuple)) \
            else [kernel_size] * 3
        out = out * (float(np.prod(k)) / float(divisor_override))
    return out


def max_unpool2d(x, indices, kernel_size, stride=None, padding=0,
                 output_size=None, data_format="NCHW", name=None):
    return OPS["unpool"](x, indices, kernel_size, stride, padding,
                         output_size, data_format)


def max_unpool3d(x, indices, kernel_size, stride=None, padding=0,
                 output_size=None, data_format="NCDHW", name=None):
    return OPS["unpool3d"](x, indices, kernel_size, stride, padding,
                           output_size, data_format)


def max_unpool1d(x, indices, kernel_size, stride=None, padding=0,
                 output_size=None, data_format="NCL", name=None):
    x4 = OPS["unsqueeze"](x, 2)
    idx4 = OPS["unsqueeze"](indices, 2)
    if output_size is not None:
        output_size = [1, list(output_size)[-1]]
    out = OPS["unpool"](x4, idx4, [1, kernel_size],
                        [1, stride or kernel_size], [0, padding],
                        output_size, "NCHW")
    return OPS["squeeze"](out, 2)


def lp_pool1d(x, norm_type, kernel_size, stride=None, padding=0,
              ceil_mode=False, data_format="NCL", name=None):
    x4 = OPS["unsqueeze"](x, 2)
    out = OPS["lp_pool2d"](x4, norm_type, [1, kernel_size],
                           [1, stride or kernel_size], [0, padding],
                           ceil_mode)
    return OPS["squeeze"](out, 2)


def conv1d_transpose(x, weight, bias=None, stride=1, padding=0,
                     output_padding=0, groups=1, dilation=1,
                     output_size=None, data_format="NCL", name=None):
    """x [N, C, L], weight [C, C_out/groups, K] — via the 2-D transposed
    conv on a height-1 image."""
    x4 = OPS["unsqueeze"](x, 2)
    w4 = OPS["unsqueeze"](weight, 2)

    def two(v):
        return [1, v] if isinstance(v, int) else [1, list(v)[0]]

    out = OPS["conv2d_transpose"](
        x4, w4, bias, stride=two(stride),
        padding=[0, padding if isinstance(padding, int)
                 else list(padding)[0]],
        output_padding=two(output_padding) if output_padding else 0,
        dilation=two(dilation), groups=groups, data_format="NCHW")
    out = OPS["squeeze"](out, 2)
    if output_size is not None:
        want = list(output_size)[-1]
        out = OPS["slice"](out, [2], [0], [want])
    return out


def zeropad2d(x, padding, data_format="NCHW", name=None):
    if isinstance(padding, int):
        padding = [padding] * 4
    return OPS["pad"](x, list(padding), mode="constant", value=0.0,
                      data_format=data_format)


# -- dropout variants --------------------------------------------------------

def alpha_dropout(x, p=0.5, training=True, name=None):
    """SELU-preserving dropout (reference: functional/common.py
    alpha_dropout): keeps self-normalizing statistics by replacing dropped
    units with alpha' and applying an affine correction."""
    if not training or p == 0.0:
        return x
    alpha = 1.6732632423543772
    scale = 1.0507009873554805
    alpha_p = -alpha * scale
    keep = 1.0 - p
    a = (keep + alpha_p ** 2 * keep * (1 - keep)) ** -0.5
    b = -a * alpha_p * (1 - keep)
    mask = OPS["cast"](
        OPS["bernoulli"](OPS["full_like"](x, keep)), x.dtype)
    return (x * mask + alpha_p * (1.0 - mask)) * a + b


def feature_alpha_dropout(x, p=0.5, training=True, name=None):
    """alpha_dropout with a per-channel mask (channel axis 1)."""
    if not training or p == 0.0:
        return x
    shape = list(x.shape)
    mask_shape = shape[:2] + [1] * (len(shape) - 2)
    alpha = 1.6732632423543772
    scale = 1.0507009873554805
    alpha_p = -alpha * scale
    keep = 1.0 - p
    a = (keep + alpha_p ** 2 * keep * (1 - keep)) ** -0.5
    b = -a * alpha_p * (1 - keep)
    ones = OPS["full"](mask_shape, keep, x.dtype)
    mask = OPS["cast"](OPS["bernoulli"](ones), x.dtype)
    return (x * mask + alpha_p * (1.0 - mask)) * a + b


def dropout3d(x, p=0.5, training=True, data_format="NCDHW", name=None):
    """Drops whole 3-D channels (reference functional/common.py); the
    channel axis follows data_format."""
    if not training or p == 0.0:
        return x
    shape = list(x.shape)
    if data_format == "NDHWC":
        mask_shape = [shape[0], 1, 1, 1, shape[-1]]
    else:
        mask_shape = shape[:2] + [1, 1, 1]
    ones = OPS["full"](mask_shape, 1.0 - p, x.dtype)
    mask = OPS["cast"](OPS["bernoulli"](ones), x.dtype)
    return x * mask / (1.0 - p)


# -- losses ------------------------------------------------------------------

def dice_loss(input, label, epsilon=1e-05, name=None):
    """reference: functional/loss.py dice_loss — input [N, ..., C] probs,
    label [N, ..., 1] int."""
    label_oh = OPS["squeeze"](OPS["one_hot"](label, input.shape[-1]), -2)
    axes = list(range(1, len(input.shape)))
    inter = OPS["sum"](input * label_oh, axes)
    union = OPS["sum"](input, axes) + OPS["sum"](label_oh, axes)
    dice = (2.0 * inter + epsilon) / (union + epsilon)
    return OPS["mean"](1.0 - dice)


def npair_loss(anchor, positive, labels, l2_reg=0.002):
    """reference: functional/loss.py npair_loss."""
    reg = l2_reg * (OPS["mean"](OPS["sum"](anchor * anchor, 1))
                    + OPS["mean"](OPS["sum"](positive * positive, 1))) * 0.25
    sim = OPS["matmul"](anchor, positive, transpose_y=True)
    lab = OPS["reshape"](labels, [-1, 1])
    tgt = OPS["cast"](OPS["equal"](lab, OPS["reshape"](labels, [1, -1])),
                      sim.dtype)
    tgt = tgt / OPS["sum"](tgt, -1, keepdim=True)
    from . import softmax_with_cross_entropy  # late: sibling module

    ce = softmax_with_cross_entropy(sim, tgt, soft_label=True)
    return OPS["mean"](ce) + reg


def pairwise_distance(x, y, p=2.0, epsilon=1e-06, keepdim=False, name=None):
    return OPS["dist_elementwise"](x, y, p, epsilon, keepdim) \
        if "dist_elementwise" in OPS else _pnorm_lastdim(x - y, p, epsilon,
                                                         keepdim)


def _pnorm_lastdim(d, p, eps, keepdim):
    a = OPS["abs"](d) + eps
    if p == float("inf"):
        return OPS["max"](a, -1, keepdim)
    return OPS["pow"](OPS["sum"](OPS["pow"](a, p), -1, keepdim=keepdim),
                      1.0 / p)


def cosine_embedding_loss(input1, input2, label, margin=0.0,
                          reduction="mean", name=None):
    from . import cosine_similarity

    cos = cosine_similarity(input1, input2, axis=1)
    pos = 1.0 - cos
    neg = OPS["clip"](cos - margin, 0.0, float("inf"))
    lab64 = OPS["cast"](label, "int64")
    is_pos = OPS["equal"](lab64, OPS["full_like"](lab64, 1))
    loss = OPS["where"](is_pos, pos, neg)
    return _reduce(loss, reduction)


def gaussian_nll_loss(input, label, variance, full=False, epsilon=1e-06,
                      reduction="mean", name=None):
    var = OPS["clip"](variance, epsilon, float("inf"))
    loss = 0.5 * (OPS["log"](var) + (input - label) ** 2 / var)
    if full:
        loss = loss + 0.5 * float(np.log(2 * np.pi))
    return _reduce(loss, reduction)


def hinge_embedding_loss(input, label, margin=1.0, reduction="mean",
                         name=None):
    lab = OPS["cast"](label, input.dtype)
    pos = input
    neg = OPS["clip"](margin - input, 0.0, float("inf"))
    loss = OPS["where"](OPS["equal"](lab, OPS["full_like"](lab, 1.0)),
                        pos, neg)
    return _reduce(loss, reduction)


def multi_label_soft_margin_loss(input, label, weight=None,
                                 reduction="mean", name=None):
    lab = OPS["cast"](label, input.dtype)
    loss = -(lab * OPS["log_sigmoid"](input)
             + (1.0 - lab) * OPS["log_sigmoid"](-input))
    if weight is not None:
        loss = loss * weight
    loss = OPS["mean"](loss, -1)
    return _reduce(loss, reduction)


def multi_margin_loss(input, label, p=1, margin=1.0, weight=None,
                      reduction="mean", name=None):
    C = input.shape[1]
    correct = OPS["take_along_axis"](input, OPS["reshape"](label, [-1, 1]),
                                     1)
    m = OPS["clip"](margin - correct + input, 0.0, float("inf"))
    if p != 1:
        m = OPS["pow"](m, float(p))
    oh = OPS["one_hot"](label, C)
    m = m * (1.0 - oh)
    if weight is not None:
        # per-sample weight w[y_i], broadcast over the class axis
        m = m * OPS["reshape"](OPS["gather"](weight, label), [-1, 1])
    loss = OPS["sum"](m, 1) / float(C)
    return _reduce(loss, reduction)


def poisson_nll_loss(input, label, log_input=True, full=False,
                     epsilon=1e-08, reduction="mean", name=None):
    if log_input:
        loss = OPS["exp"](input) - label * input
    else:
        loss = input - label * OPS["log"](input + epsilon)
    if full:
        big = label > 1.0
        stirling = (label * OPS["log"](OPS["clip"](label, 1e-12,
                                                   float("inf")))
                    - label + 0.5 * OPS["log"](
                        OPS["clip"](2 * np.pi * label, 1e-12, float("inf"))))
        loss = loss + OPS["where"](big, stirling,
                                   OPS["zeros_like"](stirling))
    return _reduce(loss, reduction)


def soft_margin_loss(input, label, reduction="mean", name=None):
    lab = OPS["cast"](label, input.dtype)
    loss = OPS["log"](1.0 + OPS["exp"](-lab * input))
    return _reduce(loss, reduction)


def sigmoid_focal_loss(logit, label, normalizer=None, alpha=0.25,
                       gamma=2.0, reduction="sum", name=None):
    """reference: functional/loss.py sigmoid_focal_loss (RetinaNet)."""
    p = OPS["sigmoid"](logit)
    lab = OPS["cast"](label, logit.dtype)
    ce = -(lab * OPS["log_sigmoid"](logit)
           + (1.0 - lab) * OPS["log_sigmoid"](-logit))
    p_t = p * lab + (1.0 - p) * (1.0 - lab)
    a_t = alpha * lab + (1.0 - alpha) * (1.0 - lab)
    loss = a_t * OPS["pow"](1.0 - p_t, gamma) * ce
    if normalizer is not None:
        loss = loss / normalizer
    return _reduce(loss, reduction)


def triplet_margin_loss(input, positive, negative, margin=1.0, p=2.0,
                        epsilon=1e-06, swap=False, reduction="mean",
                        name=None):
    dp = pairwise_distance(input, positive, p, epsilon)
    dn = pairwise_distance(input, negative, p, epsilon)
    if swap:
        dn2 = pairwise_distance(positive, negative, p, epsilon)
        dn = OPS["minimum"](dn, dn2)
    loss = OPS["clip"](dp - dn + margin, 0.0, float("inf"))
    return _reduce(loss, reduction)


def triplet_margin_with_distance_loss(input, positive, negative,
                                      distance_function=None, margin=1.0,
                                      swap=False, reduction="mean",
                                      name=None):
    dist = distance_function or (lambda a, b: pairwise_distance(a, b))
    dp = dist(input, positive)
    dn = dist(input, negative)
    if swap:
        dn = OPS["minimum"](dn, dist(positive, negative))
    loss = OPS["clip"](dp - dn + margin, 0.0, float("inf"))
    return _reduce(loss, reduction)


def rnnt_loss(input, label, input_lengths, label_lengths, blank=0,
              fastemit_lambda=0.001, reduction="mean", name=None):
    loss = OPS["warprnnt"](input, label, input_lengths, label_lengths,
                           blank, fastemit_lambda)
    return _reduce(loss, reduction)


def adaptive_log_softmax_with_loss(input, label, head_weight, tail_weights,
                                   cutoffs, head_bias=None, name=None):
    """Adaptive softmax (reference: functional/loss.py
    adaptive_log_softmax_with_loss; Grave et al. 2017): frequent classes in
    the head, rare classes in down-projected tail clusters appended to the
    head as cluster logits. Returns (per-sample negative outputs, scalar
    loss) like the reference."""
    import jax
    import jax.numpy as jnp

    x = input._data if isinstance(input, Tensor) else jnp.asarray(input)
    y = label._data if isinstance(label, Tensor) else jnp.asarray(label)
    hw = head_weight._data if isinstance(head_weight, Tensor) \
        else jnp.asarray(head_weight)
    hb = None if head_bias is None else (
        head_bias._data if isinstance(head_bias, Tensor)
        else jnp.asarray(head_bias))
    shortlist = cutoffs[0]
    n_clusters = len(cutoffs) - 1 if len(cutoffs) > 1 else 0

    head_logits = x @ hw
    if hb is not None:
        head_logits = head_logits + hb
    head_log = jax.nn.log_softmax(head_logits, axis=-1)
    # shortlist part: gather per-sample
    in_short = y < shortlist
    short_ll = jnp.take_along_axis(
        head_log, jnp.clip(y, 0, shortlist - 1)[:, None], 1)[:, 0]
    ll = jnp.where(in_short, short_ll, 0.0)
    bounds = list(cutoffs)
    for ci in range(n_clusters):
        lo = bounds[ci]
        hi = bounds[ci + 1]
        tw = tail_weights[ci]
        w1 = tw[0]._data if isinstance(tw[0], Tensor) else jnp.asarray(tw[0])
        w2 = tw[1]._data if isinstance(tw[1], Tensor) else jnp.asarray(tw[1])
        tail_log = jax.nn.log_softmax((x @ w1) @ w2, axis=-1)
        in_c = (y >= lo) & (y < hi)
        idx = jnp.clip(y - lo, 0, hi - lo - 1)
        c_ll = head_log[:, shortlist + ci] \
            + jnp.take_along_axis(tail_log, idx[:, None], 1)[:, 0]
        ll = jnp.where(in_c, c_ll, ll)
    out = Tensor._from_data(ll)
    loss = Tensor._from_data(-jnp.mean(ll))
    return out, loss


# -- packed flash-attention wrappers ----------------------------------------

def flash_attn_qkvpacked(qkv, dropout=0.0, causal=False, return_softmax=False,
                         name=None):
    """qkv [B, S, 3, H, D] packed (reference: incubate flash_attn
    qkvpacked entry) → unpack and run the flash kernel."""
    q = qkv[:, :, 0]
    k = qkv[:, :, 1]
    v = qkv[:, :, 2]
    return OPS["flash_attn"](q, k, v, dropout=dropout, causal=causal,
                             return_softmax=return_softmax)


def flash_attn_varlen_qkvpacked(qkv, cu_seqlens_q, cu_seqlens_k,
                                max_seqlen_q, max_seqlen_k, scale=None,
                                dropout=0.0, causal=False,
                                return_softmax=False, name=None):
    """qkv [total_tokens, 3, H, D] packed varlen."""
    q = qkv[:, 0]
    k = qkv[:, 1]
    v = qkv[:, 2]
    return OPS["flash_attn_unpadded"](
        q, k, v, cu_seqlens_q, cu_seqlens_k,
        max_seqlen_q=max_seqlen_q, max_seqlen_k=max_seqlen_k, scale=scale,
        dropout=dropout, causal=causal, return_softmax=return_softmax)


# -- in-place activation spellings ------------------------------------------

def _inplace(fn):
    def wrapper(x, *args, **kwargs):
        return x._rebind(fn(x, *args, **kwargs))

    return wrapper


relu_ = _inplace(OPS["relu"])
tanh_ = _inplace(OPS["tanh"])
elu_ = _inplace(OPS["elu"])
hardtanh_ = _inplace(OPS["hardtanh"])
leaky_relu_ = _inplace(OPS["leaky_relu"])
softmax_ = _inplace(OPS["softmax"])
thresholded_relu_ = _inplace(OPS["thresholded_relu"])

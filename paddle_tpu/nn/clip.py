"""Gradient clipping (reference: python/paddle/nn/clip.py —
ClipGradByGlobalNorm et al., consumed by Optimizer)."""
from __future__ import annotations

import jax.numpy as jnp


class ClipGradBase:
    def __call__(self, params_grads):
        raise NotImplementedError


class ClipGradByValue(ClipGradBase):
    def __init__(self, max, min=None):
        self.max = max
        self.min = -max if min is None else min

    def __call__(self, params_grads):
        return [
            (p, None if g is None else jnp.clip(g, self.min, self.max)) for p, g in params_grads
        ]


class ClipGradByNorm(ClipGradBase):
    def __init__(self, clip_norm):
        self.clip_norm = float(clip_norm)

    def __call__(self, params_grads):
        out = []
        for p, g in params_grads:
            if g is None:
                out.append((p, g))
                continue
            norm = jnp.sqrt(jnp.sum(jnp.square(g.astype(jnp.float32))))
            factor = jnp.minimum(self.clip_norm / jnp.maximum(norm, 1e-12), 1.0)
            out.append((p, (g.astype(jnp.float32) * factor).astype(g.dtype)))
        return out


class ClipGradByGlobalNorm(ClipGradBase):
    """Reference: ClipGradByGlobalNorm (nn/clip.py) — the hybrid-parallel-aware
    variant lives in distributed.fleet (sums per-group partial norms)."""

    def __init__(self, clip_norm=1.0, group_name="default_group", auto_skip_clip=False):
        self.clip_norm = float(clip_norm)

    def _global_norm(self, grads):
        sq = [jnp.sum(jnp.square(g.astype(jnp.float32))) for g in grads if g is not None]
        if not sq:
            return None
        total = sq[0]
        for s in sq[1:]:
            total = total + s
        return jnp.sqrt(total)

    def __call__(self, params_grads):
        gn = self._global_norm([g for p, g in params_grads if p_needs_clip(p)])
        if gn is None:
            return params_grads
        factor = self.clip_norm / jnp.maximum(gn, self.clip_norm)
        out = []
        for p, g in params_grads:
            if g is None or not p_needs_clip(p):
                out.append((p, g))
            else:
                out.append((p, (g.astype(jnp.float32) * factor).astype(g.dtype)))
        return out


def p_needs_clip(p):
    return getattr(p, "need_clip", True)


def clip_grad_norm_(parameters, max_norm, norm_type=2.0, error_if_nonfinite=False):
    """torch-style utility also exposed by paddle.nn.utils."""
    grads = [p._grad for p in parameters if p._grad is not None]
    if not grads:
        return None
    if norm_type == float("inf"):
        total = jnp.max(jnp.stack([jnp.max(jnp.abs(g)) for g in grads]))
    else:
        total = jnp.power(
            sum(jnp.sum(jnp.power(jnp.abs(g.astype(jnp.float32)), norm_type)) for g in grads),
            1.0 / norm_type,
        )
    factor = jnp.minimum(max_norm / jnp.maximum(total, 1e-6), 1.0)
    for p in parameters:
        if p._grad is not None:
            p._grad = (p._grad.astype(jnp.float32) * factor).astype(p._grad.dtype)
    return total

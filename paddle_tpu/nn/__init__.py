"""paddle.nn-compatible layer library (reference: python/paddle/nn/__init__.py)."""
from . import functional  # noqa: F401
from . import initializer  # noqa: F401
from .clip import ClipGradByGlobalNorm, ClipGradByNorm, ClipGradByValue  # noqa: F401
from .layer.activation import (  # noqa: F401
    CELU,
    ELU,
    GELU,
    GLU,
    SELU,
    Hardshrink,
    Hardsigmoid,
    Hardswish,
    Hardtanh,
    LeakyReLU,
    LogSigmoid,
    LogSoftmax,
    Maxout,
    Mish,
    PReLU,
    ReLU,
    ReLU6,
    Sigmoid,
    SiLU,
    Silu,
    Softmax,
    Softplus,
    Softshrink,
    Softsign,
    Swish,
    Tanh,
    Tanhshrink,
    ThresholdedReLU,
)
from .layer.common import (  # noqa: F401
    CosineSimilarity,
    Dropout,
    Dropout2D,
    Embedding,
    Flatten,
    Identity,
    Linear,
    Pad2D,
    Upsample,
    ZeroPad2D,
)
from .layer.container import LayerDict, LayerList, ParameterList, Sequential  # noqa: F401
from .layer.conv import Conv1D, Conv2D, Conv2DTranspose, Conv3D  # noqa: F401
from .layer.layers import Layer  # noqa: F401
from .layer.loss import (  # noqa: F401
    BCELoss,
    BCEWithLogitsLoss,
    CrossEntropyLoss,
    KLDivLoss,
    L1Loss,
    MarginRankingLoss,
    MSELoss,
    NLLLoss,
    SmoothL1Loss,
    CTCLoss,
)
from .layer.norm import (  # noqa: F401
    BatchNorm,
    BatchNorm1D,
    BatchNorm2D,
    BatchNorm3D,
    GroupNorm,
    InstanceNorm1D,
    InstanceNorm2D,
    InstanceNorm3D,
    LayerNorm,
    LocalResponseNorm,
    RMSNorm,
    SyncBatchNorm,
)
from .layer.transformer import (  # noqa: F401
    MultiHeadAttention,
    Transformer,
    TransformerDecoder,
    TransformerDecoderLayer,
    TransformerEncoder,
    TransformerEncoderLayer,
)
from .layer.rnn import (  # noqa: F401
    RNN,
    BiRNN,
    GRU,
    GRUCell,
    LSTM,
    LSTMCell,
    RNNCellBase,
    SimpleRNN,
    SimpleRNNCell,
)
from .layer.pooling import (  # noqa: F401
    AdaptiveAvgPool2D,
    AdaptiveMaxPool2D,
    AvgPool1D,
    AvgPool2D,
    MaxPool1D,
    MaxPool2D,
)
from .param_attr import ParamAttr  # noqa: F401
from .layer.extra import *  # noqa: F401,F403,E402  (round-5 layer tail)

"""Weight initializers.

Analog of `python/paddle/nn/initializer/*` (reference); each initializer maps
(shape, dtype) -> a jax array, using the global splittable PRNG.
"""
from __future__ import annotations

import math

import jax
import jax.numpy as jnp
import numpy as np

from ...core import dtype as dtype_mod, rng


def _fans(shape):
    shape = list(shape)
    if len(shape) == 0:
        return 1, 1
    if len(shape) == 1:
        return shape[0], shape[0]
    if len(shape) == 2:
        return shape[0], shape[1]
    receptive = int(np.prod(shape[2:]))
    # conv weight [out_c, in_c, *k] (paddle layout)
    fan_in = shape[1] * receptive
    fan_out = shape[0] * receptive
    return fan_in, fan_out


class Initializer:
    def __call__(self, shape, dtype="float32"):
        raise NotImplementedError


class Constant(Initializer):
    def __init__(self, value=0.0):
        self.value = value

    def __call__(self, shape, dtype="float32"):
        return jnp.full(shape, self.value, dtype_mod.to_np(dtype))


class Normal(Initializer):
    def __init__(self, mean=0.0, std=1.0):
        self.mean, self.std = mean, std

    def __call__(self, shape, dtype="float32"):
        return self.mean + self.std * jax.random.normal(rng.next_key(), shape, dtype_mod.to_np(dtype))


class TruncatedNormal(Initializer):
    def __init__(self, mean=0.0, std=1.0, a=-2.0, b=2.0):
        self.mean, self.std, self.a, self.b = mean, std, a, b

    def __call__(self, shape, dtype="float32"):
        z = jax.random.truncated_normal(rng.next_key(), self.a, self.b, shape, dtype_mod.to_np(dtype))
        return self.mean + self.std * z


class Uniform(Initializer):
    def __init__(self, low=-1.0, high=1.0):
        self.low, self.high = low, high

    def __call__(self, shape, dtype="float32"):
        return jax.random.uniform(rng.next_key(), shape, dtype_mod.to_np(dtype), self.low, self.high)


class XavierNormal(Initializer):
    def __init__(self, fan_in=None, fan_out=None, gain=1.0):
        self.fan_in, self.fan_out, self.gain = fan_in, fan_out, gain

    def __call__(self, shape, dtype="float32"):
        fi, fo = _fans(shape)
        fi = self.fan_in or fi
        fo = self.fan_out or fo
        std = self.gain * math.sqrt(2.0 / (fi + fo))
        return std * jax.random.normal(rng.next_key(), shape, dtype_mod.to_np(dtype))


class XavierUniform(Initializer):
    def __init__(self, fan_in=None, fan_out=None, gain=1.0):
        self.fan_in, self.fan_out, self.gain = fan_in, fan_out, gain

    def __call__(self, shape, dtype="float32"):
        fi, fo = _fans(shape)
        fi = self.fan_in or fi
        fo = self.fan_out or fo
        limit = self.gain * math.sqrt(6.0 / (fi + fo))
        return jax.random.uniform(rng.next_key(), shape, dtype_mod.to_np(dtype), -limit, limit)


class KaimingNormal(Initializer):
    def __init__(self, fan_in=None, negative_slope=0.0, nonlinearity="relu"):
        self.fan_in, self.negative_slope, self.nonlinearity = fan_in, negative_slope, nonlinearity

    def __call__(self, shape, dtype="float32"):
        fi, _ = _fans(shape)
        fi = self.fan_in or fi
        gain = math.sqrt(2.0 / (1 + self.negative_slope**2)) if self.nonlinearity in ("relu", "leaky_relu") else 1.0
        std = gain / math.sqrt(fi)
        return std * jax.random.normal(rng.next_key(), shape, dtype_mod.to_np(dtype))


class KaimingUniform(Initializer):
    def __init__(self, fan_in=None, negative_slope=0.0, nonlinearity="relu"):
        self.fan_in, self.negative_slope, self.nonlinearity = fan_in, negative_slope, nonlinearity

    def __call__(self, shape, dtype="float32"):
        fi, _ = _fans(shape)
        fi = self.fan_in or fi
        gain = math.sqrt(2.0 / (1 + self.negative_slope**2)) if self.nonlinearity in ("relu", "leaky_relu") else 1.0
        limit = gain * math.sqrt(3.0 / fi)
        return jax.random.uniform(rng.next_key(), shape, dtype_mod.to_np(dtype), -limit, limit)


class Assign(Initializer):
    def __init__(self, value):
        self.value = value

    def __call__(self, shape, dtype="float32"):
        arr = np.asarray(self.value, dtype_mod.to_np(dtype))
        if list(arr.shape) != list(shape):
            arr = arr.reshape(shape)
        return jnp.asarray(arr)


class Orthogonal(Initializer):
    def __init__(self, gain=1.0):
        self.gain = gain

    def __call__(self, shape, dtype="float32"):
        return self.gain * jax.nn.initializers.orthogonal()(rng.next_key(), shape, dtype_mod.to_np(dtype))


class Dirac(Initializer):
    def __init__(self, groups=1):
        self.groups = groups

    def __call__(self, shape, dtype="float32"):
        arr = np.zeros(shape, dtype_mod.to_np(dtype))
        oc, ic = shape[0], shape[1]
        k_center = [s // 2 for s in shape[2:]]
        for i in range(min(oc, ic * self.groups)):
            idx = (i, i % ic, *k_center)
            arr[idx] = 1.0
        return jnp.asarray(arr)


# paddle exposes both class names and short aliases
constant = Constant
normal = Normal
uniform = Uniform
xavier_normal = XavierNormal
xavier_uniform = XavierUniform
kaiming_normal = KaimingNormal
kaiming_uniform = KaimingUniform


def calculate_gain(nonlinearity, param=None):
    mapping = {
        "sigmoid": 1.0,
        "linear": 1.0,
        "conv1d": 1.0,
        "conv2d": 1.0,
        "conv3d": 1.0,
        "tanh": 5.0 / 3.0,
        "relu": math.sqrt(2.0),
        "leaky_relu": math.sqrt(2.0 / (1 + (param if param is not None else 0.01) ** 2)),
        "selu": 3.0 / 4.0,
    }
    return mapping[nonlinearity]

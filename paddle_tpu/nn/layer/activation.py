"""Activation layers (reference: python/paddle/nn/layer/activation.py)."""
from __future__ import annotations

from .. import functional as F
from ..param_attr import ParamAttr
from .layers import Layer


def _simple(fn_name, cls_name, **fixed):
    fn = getattr(F, fn_name)

    class _Act(Layer):
        def __init__(self, name=None, **kw):
            super().__init__()
            self._kw = {**fixed, **{k: v for k, v in kw.items() if k != "name"}}

        def forward(self, x):
            return fn(x, **self._kw)

    _Act.__name__ = cls_name
    _Act.__qualname__ = cls_name
    return _Act


ReLU = _simple("relu", "ReLU")
ReLU6 = _simple("relu6", "ReLU6")
Sigmoid = _simple("sigmoid", "Sigmoid")
Tanh = _simple("tanh", "Tanh")
GELU = _simple("gelu", "GELU")
Silu = _simple("silu", "Silu")
SiLU = Silu
Mish = _simple("mish", "Mish")
Swish = _simple("swish", "Swish")
Hardswish = _simple("hardswish", "Hardswish")
Hardsigmoid = _simple("hardsigmoid", "Hardsigmoid")
Softsign = _simple("softsign", "Softsign")
Tanhshrink = _simple("tanhshrink", "Tanhshrink")
LogSigmoid = _simple("log_sigmoid", "LogSigmoid")


class LeakyReLU(Layer):
    def __init__(self, negative_slope=0.01, name=None):
        super().__init__()
        self.negative_slope = negative_slope

    def forward(self, x):
        return F.leaky_relu(x, self.negative_slope)


class ELU(Layer):
    def __init__(self, alpha=1.0, name=None):
        super().__init__()
        self.alpha = alpha

    def forward(self, x):
        return F.elu(x, self.alpha)


class CELU(Layer):
    def __init__(self, alpha=1.0, name=None):
        super().__init__()
        self.alpha = alpha

    def forward(self, x):
        return F.celu(x, self.alpha)


class SELU(Layer):
    def __init__(self, scale=1.0507009873554805, alpha=1.6732632423543772, name=None):
        super().__init__()
        self.scale, self.alpha = scale, alpha

    def forward(self, x):
        return F.selu(x, self.scale, self.alpha)


class PReLU(Layer):
    def __init__(self, num_parameters=1, init=0.25, weight_attr=None, data_format="NCHW", name=None):
        super().__init__()
        from .. import initializer as I

        self._weight = self.create_parameter(
            [num_parameters], ParamAttr._to_attr(weight_attr), self._dtype,
            default_initializer=I.Constant(init),
        )
        self._data_format = data_format

    def forward(self, x):
        w = self._weight
        if w.size > 1 and x.ndim > 1:
            shape = [1] * x.ndim
            c_axis = 1 if self._data_format == "NCHW" else x.ndim - 1
            shape[c_axis] = w.size
            w = w.reshape(shape)
        return F.prelu(x, w)


class Softmax(Layer):
    def __init__(self, axis=-1, name=None):
        super().__init__()
        self.axis = axis

    def forward(self, x):
        return F.softmax(x, self.axis)


class LogSoftmax(Layer):
    def __init__(self, axis=-1, name=None):
        super().__init__()
        self.axis = axis

    def forward(self, x):
        return F.log_softmax(x, self.axis)


class Softplus(Layer):
    def __init__(self, beta=1.0, threshold=20.0, name=None):
        super().__init__()
        self.beta, self.threshold = beta, threshold

    def forward(self, x):
        return F.softplus(x, self.beta, self.threshold)


class Hardshrink(Layer):
    def __init__(self, threshold=0.5, name=None):
        super().__init__()
        self.threshold = threshold

    def forward(self, x):
        return F.hardshrink(x, self.threshold)


class Softshrink(Layer):
    def __init__(self, threshold=0.5, name=None):
        super().__init__()
        self.threshold = threshold

    def forward(self, x):
        return F.softshrink(x, self.threshold)


class Hardtanh(Layer):
    def __init__(self, min=-1.0, max=1.0, name=None):
        super().__init__()
        self.min, self.max = min, max

    def forward(self, x):
        return F.hardtanh(x, self.min, self.max)


class ThresholdedReLU(Layer):
    def __init__(self, threshold=1.0, value=0.0, name=None):
        super().__init__()
        self.threshold, self.value = threshold, value

    def forward(self, x):
        return F.thresholded_relu(x, self.threshold, self.value)


class Maxout(Layer):
    def __init__(self, groups, axis=1, name=None):
        super().__init__()
        self.groups, self.axis = groups, axis

    def forward(self, x):
        return F.maxout(x, self.groups, self.axis)


class GLU(Layer):
    def __init__(self, axis=-1, name=None):
        super().__init__()
        self.axis = axis

    def forward(self, x):
        return F.glu(x, self.axis)

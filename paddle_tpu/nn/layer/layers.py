"""Layer: the module base class.

Analog of the reference's `paddle.nn.Layer`
(`python/paddle/nn/layer/layers.py:354`): parameter/sublayer/buffer
registries, forward hooks, state_dict with structured names, train/eval mode,
`.to()` device/dtype movement. Parameters are eager Tensors over PJRT
buffers; the functional view (`functional_state` / `load_functional_state`)
is the bridge jit/static training uses to run a Layer as a pure function.
"""
from __future__ import annotations

import collections
from typing import Callable, Dict, Iterator, List, Optional, Tuple

import numpy as np

from ...core import dtype as dtype_mod
from ...core.tensor import Parameter, Tensor
from ...ops.dispatch import OPS, no_grad


class HookRemoveHelper:
    def __init__(self, hooks, key):
        self._hooks = hooks
        self._key = key

    def remove(self):
        self._hooks.pop(self._key, None)


class Layer:
    def __init__(self, name_scope=None, dtype="float32"):
        self.training = True
        self._dtype = dtype
        self._parameters: Dict[str, Optional[Parameter]] = collections.OrderedDict()
        self._sub_layers: Dict[str, "Layer"] = collections.OrderedDict()
        self._buffers: Dict[str, Optional[Tensor]] = collections.OrderedDict()
        self._non_persistable_buffer_names = set()
        self._forward_pre_hooks = collections.OrderedDict()
        self._forward_post_hooks = collections.OrderedDict()
        self._hook_id = 0
        self._name_scope = name_scope or self.__class__.__name__.lower()

    # -- attribute routing ---------------------------------------------------
    def __setattr__(self, name, value):
        params = self.__dict__.get("_parameters")
        layers = self.__dict__.get("_sub_layers")
        buffers = self.__dict__.get("_buffers")
        if isinstance(value, Parameter):
            if params is None:
                raise RuntimeError("call Layer.__init__ before assigning parameters")
            params[name] = value
            layers is not None and layers.pop(name, None)
            buffers is not None and buffers.pop(name, None)
            self.__dict__.pop(name, None)
        elif isinstance(value, Layer):
            if layers is None:
                raise RuntimeError("call Layer.__init__ before assigning sublayers")
            layers[name] = value
            params is not None and params.pop(name, None)
            self.__dict__.pop(name, None)
        elif params is not None and name in params:
            if value is None:
                params[name] = None
            elif isinstance(value, Tensor):
                params[name] = Parameter.from_tensor(value)
            else:
                raise TypeError(f"cannot assign {type(value)} to parameter {name!r}")
        elif buffers is not None and name in buffers:
            buffers[name] = value
        else:
            object.__setattr__(self, name, value)

    def __getattr__(self, name):
        for store in ("_parameters", "_sub_layers", "_buffers"):
            d = self.__dict__.get(store)
            if d is not None and name in d:
                return d[name]
        raise AttributeError(f"'{type(self).__name__}' object has no attribute '{name}'")

    def __delattr__(self, name):
        for store in ("_parameters", "_sub_layers", "_buffers"):
            d = self.__dict__.get(store)
            if d is not None and name in d:
                del d[name]
                return
        object.__delattr__(self, name)

    def __dir__(self):
        return list(super().__dir__()) + list(self._parameters) + list(self._sub_layers) + list(self._buffers)

    # -- registration --------------------------------------------------------
    def add_parameter(self, name: str, parameter: Optional[Parameter]):
        self._parameters[name] = parameter
        return parameter

    def add_sublayer(self, name: str, sublayer: "Layer"):
        self._sub_layers[name] = sublayer
        return sublayer

    def register_buffer(self, name: str, tensor: Optional[Tensor], persistable: bool = True):
        if tensor is not None and not isinstance(tensor, Tensor):
            tensor = Tensor(tensor)
        if tensor is not None:
            # static-graph recording reads buffers as named mutable state
            # (not baked consts) — see static/graph.py GraphRecorder
            tensor._is_buffer = True
        self._buffers[name] = tensor
        if not persistable:
            self._non_persistable_buffer_names.add(name)
        return tensor

    def create_parameter(
        self,
        shape,
        attr=None,
        dtype=None,
        is_bias=False,
        default_initializer=None,
    ) -> Parameter:
        from .. import initializer as I

        dtype = dtype or self._dtype or dtype_mod.get_default_dtype()
        init = default_initializer
        name = None
        learning_rate = 1.0
        trainable = True
        if attr is not None and attr is not False:
            from ..param_attr import ParamAttr

            if isinstance(attr, ParamAttr):
                init = attr.initializer or init
                name = attr.name
                learning_rate = attr.learning_rate
                trainable = attr.trainable
            elif isinstance(attr, I.Initializer):
                init = attr
        if init is None:
            init = I.Constant(0.0) if is_bias else I.XavierNormal()
        data = init(shape, dtype)
        p = Parameter(data, name=name, trainable=trainable)
        p.optimize_attr["learning_rate"] = learning_rate
        return p

    def create_tensor(self, name=None, dtype=None):
        return Tensor(np.zeros([0], dtype_mod.to_np(dtype or self._dtype)))

    # -- iteration -----------------------------------------------------------
    def named_members(self, get_fn, prefix="", include_sublayers=True, seen=None):
        seen = seen if seen is not None else set()
        for name, member in get_fn(self):
            if member is None or id(member) in seen:
                continue
            seen.add(id(member))
            yield (f"{prefix}.{name}" if prefix else name), member
        if include_sublayers:
            for lname, layer in self._sub_layers.items():
                if layer is None:
                    continue
                sub_prefix = f"{prefix}.{lname}" if prefix else lname
                yield from layer.named_members(get_fn, sub_prefix, True, seen)

    def parameters(self, include_sublayers=True) -> List[Parameter]:
        return [p for _, p in self.named_parameters(include_sublayers=include_sublayers)]

    def named_parameters(self, prefix="", include_sublayers=True) -> Iterator[Tuple[str, Parameter]]:
        yield from self.named_members(
            lambda l: l._parameters.items(), prefix, include_sublayers
        )

    def buffers(self, include_sublayers=True) -> List[Tensor]:
        return [b for _, b in self.named_buffers(include_sublayers=include_sublayers)]

    def named_buffers(self, prefix="", include_sublayers=True):
        yield from self.named_members(lambda l: l._buffers.items(), prefix, include_sublayers)

    def children(self) -> Iterator["Layer"]:
        for _, l in self.named_children():
            yield l

    def named_children(self):
        for name, l in self._sub_layers.items():
            if l is not None:
                yield name, l

    def sublayers(self, include_self=False) -> List["Layer"]:
        out = []
        for _, l in self.named_sublayers(include_self=include_self):
            out.append(l)
        return out

    def named_sublayers(self, prefix="", include_self=False, layers_set=None):
        layers_set = layers_set if layers_set is not None else set()
        if include_self and id(self) not in layers_set:
            layers_set.add(id(self))
            yield prefix, self
        for name, l in self._sub_layers.items():
            if l is None or id(l) in layers_set:
                continue
            layers_set.add(id(l))
            sub_prefix = f"{prefix}.{name}" if prefix else name
            yield sub_prefix, l
            yield from l.named_sublayers(sub_prefix, False, layers_set)

    def apply(self, fn: Callable):
        for l in self.children():
            l.apply(fn)
        fn(self)
        return self

    # -- mode ----------------------------------------------------------------
    def train(self):
        self.training = True
        for l in self.sublayers():
            l.training = True
        return self

    def eval(self):
        self.training = False
        for l in self.sublayers():
            l.training = False
        return self

    # -- state dict ----------------------------------------------------------
    def state_dict(self, destination=None, include_sublayers=True, use_hook=True):
        dest = destination if destination is not None else collections.OrderedDict()
        for name, p in self.named_parameters(include_sublayers=include_sublayers):
            dest[name] = p
        for name, b in self.named_buffers(include_sublayers=include_sublayers):
            short = name.rsplit(".", 1)[-1]
            owner = self._locate(name)
            if owner is not None and short in owner._non_persistable_buffer_names:
                continue
            dest[name] = b
        return dest

    def _locate(self, qualified_name):
        parts = qualified_name.split(".")[:-1]
        layer = self
        for p in parts:
            layer = layer._sub_layers.get(p)
            if layer is None:
                return None
        return layer

    def set_state_dict(self, state_dict, use_structured_name=True):
        own = self.state_dict()
        missing, unexpected = [], []
        for name, target in own.items():
            if name in state_dict:
                value = state_dict[name]
                arr = value._data if isinstance(value, Tensor) else np.asarray(value)
                target.set_value(Tensor(arr))
            else:
                missing.append(name)
        for name in state_dict:
            if name not in own:
                unexpected.append(name)
        return missing, unexpected

    set_dict = set_state_dict
    load_dict = set_state_dict

    # -- device/dtype movement -----------------------------------------------
    def to(self, device=None, dtype=None, blocking=None):
        def _convert(t: Tensor):
            if t is None:
                return
            new = t.to(device=device, dtype=dtype) if (device or dtype) else t
            t._data = new._data

        for _, p in self.named_parameters():
            _convert(p)
        for _, b in self.named_buffers():
            _convert(b)
        if dtype is not None:
            self._dtype = str(dtype_mod.DType(dtype))
            for l in self.sublayers(include_self=True):
                l._dtype = self._dtype
        return self

    def astype(self, dtype):
        return self.to(dtype=dtype)

    def float(self):
        return self.to(dtype="float32")

    def bfloat16(self):
        return self.to(dtype="bfloat16")

    def half(self):
        return self.to(dtype="float16")

    # -- hooks ---------------------------------------------------------------
    def register_forward_pre_hook(self, hook):
        self._hook_id += 1
        self._forward_pre_hooks[self._hook_id] = hook
        return HookRemoveHelper(self._forward_pre_hooks, self._hook_id)

    def register_forward_post_hook(self, hook):
        self._hook_id += 1
        self._forward_post_hooks[self._hook_id] = hook
        return HookRemoveHelper(self._forward_post_hooks, self._hook_id)

    # -- call ----------------------------------------------------------------
    def forward(self, *inputs, **kwargs):
        raise NotImplementedError

    def __call__(self, *inputs, **kwargs):
        for hook in self._forward_pre_hooks.values():
            result = hook(self, inputs)
            if result is not None:
                inputs = result if isinstance(result, tuple) else (result,)
        outputs = self.forward(*inputs, **kwargs)
        for hook in self._forward_post_hooks.values():
            result = hook(self, inputs, outputs)
            if result is not None:
                outputs = result
        return outputs

    # -- functional bridge (jit/static training) -----------------------------
    def functional_state(self):
        """Return ({name: param_array}, {name: buffer_array}) — pure pytrees."""
        params = {n: p._data for n, p in self.named_parameters()}
        bufs = {n: (b._data if b is not None else None) for n, b in self.named_buffers()}
        return params, bufs

    def load_functional_state(self, params=None, buffers=None):
        """Install arrays back into the layer (used after a jitted step)."""
        if params:
            own = dict(self.named_parameters())
            for n, arr in params.items():
                if n in own:
                    own[n]._data = arr
        if buffers:
            ownb = dict(self.named_buffers())
            for n, arr in buffers.items():
                if n in ownb and arr is not None and ownb[n] is not None:
                    ownb[n]._data = arr
        return self

    def full_name(self):
        return self._name_scope

    def extra_repr(self):
        return ""

    def __repr__(self):
        extra = self.extra_repr()
        lines = []
        for name, l in self._sub_layers.items():
            sub = repr(l).split("\n")
            sub = [sub[0]] + ["  " + s for s in sub[1:]]
            lines.append(f"  ({name}): " + "\n".join(sub))
        main = f"{type(self).__name__}({extra}"
        if lines:
            return main + "\n" + "\n".join(lines) + "\n)"
        return main + ")"

    def clear_gradients(self):
        for p in self.parameters():
            p.clear_grad()

"""Transformer layers (reference: python/paddle/nn/layer/transformer.py,
1,750 LoC): MultiHeadAttention (+ Cache/StaticCache incremental decoding,
`transformer.py:132`), TransformerEncoderLayer/Encoder (`:568/:786`),
TransformerDecoderLayer/Decoder (`:928/:1213`), Transformer (`:1432`).

TPU notes: attention runs as plain batched einsum-style matmuls + softmax —
under jit, XLA fuses the mask/softmax chain and maps the matmuls onto the
MXU; the hot fused path for big models is the Pallas flash kernel in the
hybrid engine, while this nn API keeps the reference's exact semantics
(arbitrary masks, caches, cross-attention, per-head dropout)."""
from __future__ import annotations

import collections

import numpy as np

from ... import _C_ops
from ...core.tensor import Tensor
from .. import functional as F
from ..param_attr import ParamAttr
from .common import Dropout, Linear
from .container import LayerList
from .layers import Layer
from .norm import LayerNorm

__all__ = [
    "MultiHeadAttention", "TransformerEncoderLayer", "TransformerEncoder",
    "TransformerDecoderLayer", "TransformerDecoder", "Transformer",
]


def _convert_attention_mask(attn_mask, dtype):
    """bool mask (True = keep) -> additive float mask (reference
    transformer.py:103)."""
    if attn_mask is None:
        return None
    if str(attn_mask.dtype) in ("bool", "paddle.bool"):
        return (1.0 - attn_mask.astype(dtype)) * -1e9
    return attn_mask.astype(dtype)


class MultiHeadAttention(Layer):
    """Reference transformer.py:132."""

    Cache = collections.namedtuple("Cache", ["k", "v"])
    StaticCache = collections.namedtuple("StaticCache", ["k", "v"])

    def __init__(self, embed_dim, num_heads, dropout=0.0, kdim=None,
                 vdim=None, need_weights=False, weight_attr=None,
                 bias_attr=None):
        super().__init__()
        if embed_dim <= 0 or num_heads <= 0:
            raise ValueError("embed_dim and num_heads must be positive")
        self.embed_dim = embed_dim
        self.kdim = kdim if kdim is not None else embed_dim
        self.vdim = vdim if vdim is not None else embed_dim
        self.num_heads = num_heads
        self.dropout = dropout
        self.need_weights = need_weights
        self.head_dim = embed_dim // num_heads
        if self.head_dim * num_heads != embed_dim:
            raise ValueError("embed_dim must be divisible by num_heads")
        self.q_proj = Linear(embed_dim, embed_dim, weight_attr, bias_attr)
        self.k_proj = Linear(self.kdim, embed_dim, weight_attr, bias_attr)
        self.v_proj = Linear(self.vdim, embed_dim, weight_attr, bias_attr)
        self.out_proj = Linear(embed_dim, embed_dim, weight_attr, bias_attr)

    def _prepare_qkv(self, query, key, value, cache=None):
        q = self.q_proj(query)
        B, Tq = q.shape[0], q.shape[1]
        q = q.reshape([B, Tq, self.num_heads, self.head_dim]).transpose(
            [0, 2, 1, 3])
        if isinstance(cache, self.StaticCache):
            k, v = cache.k, cache.v
        else:
            k, v = self.compute_kv(key, value)
        if isinstance(cache, self.Cache):
            k = _C_ops.concat([cache.k, k], axis=2)
            v = _C_ops.concat([cache.v, v], axis=2)
            cache = self.Cache(k, v)
        return (q, k, v) if cache is None else (q, k, v, cache)

    def compute_kv(self, key, value):
        k = self.k_proj(key)
        v = self.v_proj(value)
        B, Tk = k.shape[0], k.shape[1]
        k = k.reshape([B, Tk, self.num_heads, self.head_dim]).transpose(
            [0, 2, 1, 3])
        v = v.reshape([B, Tk, self.num_heads, self.head_dim]).transpose(
            [0, 2, 1, 3])
        return k, v

    def gen_cache(self, key, value=None, type=Cache):
        if type == MultiHeadAttention.StaticCache:
            k, v = self.compute_kv(key, value)
            return self.StaticCache(k, v)
        if value is None:  # incremental_state with shape hint
            k = _C_ops.full([key.shape[0], self.num_heads, 0, self.head_dim],
                            0.0, "float32")
            return self.Cache(k, k)
        return self.Cache(key, value)

    def forward(self, query, key=None, value=None, attn_mask=None, cache=None):
        key = query if key is None else key
        value = key if value is None else value
        if cache is None:
            q, k, v = self._prepare_qkv(query, key, value, cache)
        else:
            q, k, v, cache = self._prepare_qkv(query, key, value, cache)
        # scaled dot-product: [B, H, Tq, hd] x [B, H, Tk, hd]
        product = _C_ops.matmul(q, k, transpose_y=True) * (
            self.head_dim ** -0.5)
        attn_mask_f = _convert_attention_mask(attn_mask, product.dtype)
        if attn_mask_f is not None:
            product = product + attn_mask_f
        weights = F.softmax(product, axis=-1)
        if self.dropout:
            weights = F.dropout(weights, self.dropout,
                                training=self.training,
                                mode="upscale_in_train")
        out = _C_ops.matmul(weights, v)            # [B, H, Tq, hd]
        out = out.transpose([0, 2, 1, 3])
        out = out.reshape([out.shape[0], out.shape[1], self.embed_dim])
        out = self.out_proj(out)
        outs = [out]
        if self.need_weights:
            outs.append(weights)
        if cache is not None:
            outs.append(cache)
        return out if len(outs) == 1 else tuple(outs)


class TransformerEncoderLayer(Layer):
    """Reference transformer.py:568."""

    def __init__(self, d_model, nhead, dim_feedforward, dropout=0.1,
                 activation="relu", attn_dropout=None, act_dropout=None,
                 normalize_before=False, weight_attr=None, bias_attr=None,
                 layer_norm_eps=1e-5):
        super().__init__()
        self._config = dict(
            d_model=d_model, nhead=nhead, dim_feedforward=dim_feedforward,
            dropout=dropout, activation=activation, attn_dropout=attn_dropout,
            act_dropout=act_dropout, normalize_before=normalize_before,
            weight_attr=weight_attr, bias_attr=bias_attr,
            layer_norm_eps=layer_norm_eps)
        attn_dropout = dropout if attn_dropout is None else attn_dropout
        act_dropout = dropout if act_dropout is None else act_dropout
        self.normalize_before = normalize_before
        self.self_attn = MultiHeadAttention(
            d_model, nhead, dropout=attn_dropout,
            weight_attr=weight_attr, bias_attr=bias_attr)
        self.linear1 = Linear(d_model, dim_feedforward, weight_attr, bias_attr)
        self.dropout = Dropout(act_dropout, mode="upscale_in_train")
        self.linear2 = Linear(dim_feedforward, d_model, weight_attr, bias_attr)
        self.norm1 = LayerNorm(d_model, epsilon=layer_norm_eps)
        self.norm2 = LayerNorm(d_model, epsilon=layer_norm_eps)
        self.dropout1 = Dropout(dropout, mode="upscale_in_train")
        self.dropout2 = Dropout(dropout, mode="upscale_in_train")
        self.activation = getattr(F, activation)

    def forward(self, src, src_mask=None, cache=None):
        residual = src
        if self.normalize_before:
            src = self.norm1(src)
        if cache is None:
            src = self.self_attn(src, src, src, src_mask)
        else:
            src, incremental_cache = self.self_attn(src, src, src, src_mask,
                                                    cache)
        src = residual + self.dropout1(src)
        if not self.normalize_before:
            src = self.norm1(src)
        residual = src
        if self.normalize_before:
            src = self.norm2(src)
        src = self.linear2(self.dropout(self.activation(self.linear1(src))))
        src = residual + self.dropout2(src)
        if not self.normalize_before:
            src = self.norm2(src)
        return src if cache is None else (src, incremental_cache)

    def gen_cache(self, src):
        return self.self_attn.gen_cache(src, type=MultiHeadAttention.Cache)


class TransformerEncoder(Layer):
    """Reference transformer.py:786."""

    def __init__(self, encoder_layer, num_layers, norm=None):
        super().__init__()
        self.layers = LayerList([
            encoder_layer if i == 0
            else type(encoder_layer)(**encoder_layer._config)
            for i in range(num_layers)])
        self.num_layers = num_layers
        self.norm = norm

    def forward(self, src, src_mask=None, cache=None):
        output = src
        new_caches = []
        for i, mod in enumerate(self.layers):
            if cache is None:
                output = mod(output, src_mask=src_mask)
            else:
                output, new_cache = mod(output, src_mask=src_mask,
                                        cache=cache[i])
                new_caches.append(new_cache)
        if self.norm is not None:
            output = self.norm(output)
        return output if cache is None else (output, new_caches)

    def gen_cache(self, src):
        return [layer.gen_cache(src) for layer in self.layers]


class TransformerDecoderLayer(Layer):
    """Reference transformer.py:928 (self-attn + cross-attn + FFN)."""

    def __init__(self, d_model, nhead, dim_feedforward, dropout=0.1,
                 activation="relu", attn_dropout=None, act_dropout=None,
                 normalize_before=False, weight_attr=None, bias_attr=None,
                 layer_norm_eps=1e-5):
        super().__init__()
        self._config = dict(
            d_model=d_model, nhead=nhead, dim_feedforward=dim_feedforward,
            dropout=dropout, activation=activation, attn_dropout=attn_dropout,
            act_dropout=act_dropout, normalize_before=normalize_before,
            weight_attr=weight_attr, bias_attr=bias_attr,
            layer_norm_eps=layer_norm_eps)
        attn_dropout = dropout if attn_dropout is None else attn_dropout
        act_dropout = dropout if act_dropout is None else act_dropout
        self.normalize_before = normalize_before
        self.self_attn = MultiHeadAttention(
            d_model, nhead, dropout=attn_dropout,
            weight_attr=weight_attr, bias_attr=bias_attr)
        self.cross_attn = MultiHeadAttention(
            d_model, nhead, dropout=attn_dropout,
            weight_attr=weight_attr, bias_attr=bias_attr)
        self.linear1 = Linear(d_model, dim_feedforward, weight_attr, bias_attr)
        self.dropout = Dropout(act_dropout, mode="upscale_in_train")
        self.linear2 = Linear(dim_feedforward, d_model, weight_attr, bias_attr)
        self.norm1 = LayerNorm(d_model, epsilon=layer_norm_eps)
        self.norm2 = LayerNorm(d_model, epsilon=layer_norm_eps)
        self.norm3 = LayerNorm(d_model, epsilon=layer_norm_eps)
        self.dropout1 = Dropout(dropout, mode="upscale_in_train")
        self.dropout2 = Dropout(dropout, mode="upscale_in_train")
        self.dropout3 = Dropout(dropout, mode="upscale_in_train")
        self.activation = getattr(F, activation)

    def forward(self, tgt, memory, tgt_mask=None, memory_mask=None,
                cache=None):
        residual = tgt
        if self.normalize_before:
            tgt = self.norm1(tgt)
        if cache is None:
            tgt = self.self_attn(tgt, tgt, tgt, tgt_mask)
        else:
            tgt, incremental_cache = self.self_attn(tgt, tgt, tgt, tgt_mask,
                                                    cache[0])
        tgt = residual + self.dropout1(tgt)
        if not self.normalize_before:
            tgt = self.norm1(tgt)
        residual = tgt
        if self.normalize_before:
            tgt = self.norm2(tgt)
        if cache is None:
            tgt = self.cross_attn(tgt, memory, memory, memory_mask)
        else:
            tgt, static_cache = self.cross_attn(tgt, memory, memory,
                                                memory_mask, cache[1])
        tgt = residual + self.dropout2(tgt)
        if not self.normalize_before:
            tgt = self.norm2(tgt)
        residual = tgt
        if self.normalize_before:
            tgt = self.norm3(tgt)
        tgt = self.linear2(self.dropout(self.activation(self.linear1(tgt))))
        tgt = residual + self.dropout3(tgt)
        if not self.normalize_before:
            tgt = self.norm3(tgt)
        if cache is None:
            return tgt
        return tgt, (incremental_cache, static_cache)

    def gen_cache(self, memory):
        incremental_cache = self.self_attn.gen_cache(
            memory, type=MultiHeadAttention.Cache)
        static_cache = self.cross_attn.gen_cache(
            memory, memory, type=MultiHeadAttention.StaticCache)
        return incremental_cache, static_cache


class TransformerDecoder(Layer):
    """Reference transformer.py:1213."""

    def __init__(self, decoder_layer, num_layers, norm=None):
        super().__init__()
        self.layers = LayerList([
            decoder_layer if i == 0
            else type(decoder_layer)(**decoder_layer._config)
            for i in range(num_layers)])
        self.num_layers = num_layers
        self.norm = norm

    def forward(self, tgt, memory, tgt_mask=None, memory_mask=None,
                cache=None):
        output = tgt
        new_caches = []
        for i, mod in enumerate(self.layers):
            if cache is None:
                output = mod(output, memory, tgt_mask=tgt_mask,
                             memory_mask=memory_mask)
            else:
                output, new_cache = mod(output, memory, tgt_mask=tgt_mask,
                                        memory_mask=memory_mask,
                                        cache=cache[i])
                new_caches.append(new_cache)
        if self.norm is not None:
            output = self.norm(output)
        return output if cache is None else (output, new_caches)

    def gen_cache(self, memory, do_zip=False):
        cache = [layer.gen_cache(memory) for layer in self.layers]
        if do_zip:
            cache = list(zip(*cache))
        return cache


class Transformer(Layer):
    """Full encoder-decoder (reference transformer.py:1432)."""

    def __init__(self, d_model=512, nhead=8, num_encoder_layers=6,
                 num_decoder_layers=6, dim_feedforward=2048, dropout=0.1,
                 activation="relu", attn_dropout=None, act_dropout=None,
                 normalize_before=False, weight_attr=None, bias_attr=None,
                 custom_encoder=None, custom_decoder=None):
        super().__init__()
        if custom_encoder is not None:
            self.encoder = custom_encoder
        else:
            encoder_layer = TransformerEncoderLayer(
                d_model, nhead, dim_feedforward, dropout, activation,
                attn_dropout, act_dropout, normalize_before,
                weight_attr, bias_attr)
            encoder_norm = LayerNorm(d_model)
            self.encoder = TransformerEncoder(
                encoder_layer, num_encoder_layers, encoder_norm)
        if custom_decoder is not None:
            self.decoder = custom_decoder
        else:
            decoder_layer = TransformerDecoderLayer(
                d_model, nhead, dim_feedforward, dropout, activation,
                attn_dropout, act_dropout, normalize_before,
                weight_attr, bias_attr)
            decoder_norm = LayerNorm(d_model)
            self.decoder = TransformerDecoder(
                decoder_layer, num_decoder_layers, decoder_norm)
        self.d_model = d_model
        self.nhead = nhead

    def forward(self, src, tgt, src_mask=None, tgt_mask=None,
                memory_mask=None):
        memory = self.encoder(src, src_mask=src_mask)
        output = self.decoder(tgt, memory, tgt_mask=tgt_mask,
                              memory_mask=memory_mask)
        return output

    def generate_square_subsequent_mask(self, length):
        """Causal additive mask: 0 on/below diagonal, -inf above
        (reference transformer.py:1674)."""
        mask = np.triu(np.full((length, length), -np.inf, np.float32), k=1)
        return Tensor(mask)

"""Recurrent layers (reference: python/paddle/nn/layer/rnn.py, 2,236 LoC).

API parity: SimpleRNNCell/LSTMCell/GRUCell (`rnn.py:811,:1050,:1250`), the
generic RNN/BiRNN wrappers (`rnn.py:320,:450`), and SimpleRNN/LSTM/GRU over
RNNBase (`rnn.py:1514` — cudnn fused path at `:1730` `_C_ops.rnn`).

TPU-first: the packaged SimpleRNN/LSTM/GRU layers always dispatch the whole
(layers x directions x time) recurrence to the fused `rnn` op
(ops/kernels/rnn_ops.py — `lax.scan` with the input projection hoisted into
one MXU-sized matmul), the XLA analog of the reference's cudnn kernel. The
generic RNN(cell) wrapper keeps the reference's dygraph python loop so
arbitrary user cells work.
"""
from __future__ import annotations

import math

import numpy as np

from ... import _C_ops
from ...core.tensor import Tensor
from .. import functional as F
from .. import initializer as I
from ..param_attr import ParamAttr
from .container import LayerList
from .layers import Layer

__all__ = [
    "RNNCellBase", "SimpleRNNCell", "LSTMCell", "GRUCell",
    "RNN", "BiRNN", "SimpleRNN", "LSTM", "GRU",
]


def _stdv_uniform(hidden_size):
    stdv = 1.0 / math.sqrt(hidden_size)
    return I.Uniform(-stdv, stdv)


class RNNCellBase(Layer):
    """Base for single-step cells (reference rnn.py:692)."""

    def get_initial_states(self, batch_ref, shape=None, dtype=None,
                           init_value=0.0, batch_dim_idx=0):
        batch = batch_ref.shape[batch_dim_idx]
        shape = shape or self.state_shape
        if isinstance(shape[0], (list, tuple)):
            return tuple(
                _C_ops.full([batch] + list(s), init_value,
                            dtype or "float32") for s in shape)
        return _C_ops.full([batch] + list(shape), init_value,
                           dtype or "float32")


class SimpleRNNCell(RNNCellBase):
    """h' = act(W_ih x + b_ih + W_hh h + b_hh). Reference rnn.py:811."""

    def __init__(self, input_size, hidden_size, activation="tanh",
                 weight_ih_attr=None, weight_hh_attr=None,
                 bias_ih_attr=None, bias_hh_attr=None, name=None):
        super().__init__()
        if hidden_size <= 0:
            raise ValueError("hidden_size must be positive")
        if activation not in ("tanh", "relu"):
            raise ValueError(
                "activation for SimpleRNNCell should be tanh or relu, "
                f"but get {activation}")
        self.input_size = input_size
        self.hidden_size = hidden_size
        self.activation = activation
        init = _stdv_uniform(hidden_size)
        self.weight_ih = self.create_parameter(
            [hidden_size, input_size], ParamAttr._to_attr(weight_ih_attr),
            default_initializer=init)
        self.weight_hh = self.create_parameter(
            [hidden_size, hidden_size], ParamAttr._to_attr(weight_hh_attr),
            default_initializer=init)
        self.bias_ih = (None if bias_ih_attr is False else
                        self.create_parameter(
                            [hidden_size], ParamAttr._to_attr(bias_ih_attr),
                            is_bias=True, default_initializer=init))
        self.bias_hh = (None if bias_hh_attr is False else
                        self.create_parameter(
                            [hidden_size], ParamAttr._to_attr(bias_hh_attr),
                            is_bias=True, default_initializer=init))

    def forward(self, inputs, states=None):
        if states is None:
            states = self.get_initial_states(inputs)
        pre_h = states
        h = _C_ops.matmul(inputs, self.weight_ih, transpose_y=True)
        if self.bias_ih is not None:
            h = h + self.bias_ih
        h = h + _C_ops.matmul(pre_h, self.weight_hh, transpose_y=True)
        if self.bias_hh is not None:
            h = h + self.bias_hh
        h = _C_ops.tanh(h) if self.activation == "tanh" else F.relu(h)
        return h, h

    @property
    def state_shape(self):
        return (self.hidden_size,)

    def extra_repr(self):
        s = "{input_size}, {hidden_size}"
        if self.activation != "tanh":
            s += ", activation={activation}"
        return s.format(**self.__dict__)


class LSTMCell(RNNCellBase):
    """Gate order [i, f, g, o] (reference rnn.py:1118). States (h, c)."""

    def __init__(self, input_size, hidden_size,
                 weight_ih_attr=None, weight_hh_attr=None,
                 bias_ih_attr=None, bias_hh_attr=None,
                 proj_size=0, name=None):
        super().__init__()
        if hidden_size <= 0:
            raise ValueError("hidden_size must be positive")
        if proj_size < 0:
            raise ValueError("proj_size must be >= 0")
        if proj_size >= hidden_size:
            raise ValueError("proj_size must be smaller than hidden_size")
        self.input_size = input_size
        self.hidden_size = hidden_size
        self.proj_size = proj_size
        out_size = proj_size or hidden_size
        init = _stdv_uniform(hidden_size)
        self.weight_ih = self.create_parameter(
            [4 * hidden_size, input_size], ParamAttr._to_attr(weight_ih_attr),
            default_initializer=init)
        self.weight_hh = self.create_parameter(
            [4 * hidden_size, out_size], ParamAttr._to_attr(weight_hh_attr),
            default_initializer=init)
        if proj_size:
            self.weight_ho = self.create_parameter(
                [proj_size, hidden_size], None, default_initializer=init)
        else:
            self.weight_ho = None
        self.bias_ih = (None if bias_ih_attr is False else
                        self.create_parameter(
                            [4 * hidden_size], ParamAttr._to_attr(bias_ih_attr),
                            is_bias=True, default_initializer=init))
        self.bias_hh = (None if bias_hh_attr is False else
                        self.create_parameter(
                            [4 * hidden_size], ParamAttr._to_attr(bias_hh_attr),
                            is_bias=True, default_initializer=init))

    def forward(self, inputs, states=None):
        if states is None:
            states = self.get_initial_states(inputs)
        pre_h, pre_c = states
        gates = _C_ops.matmul(inputs, self.weight_ih, transpose_y=True)
        if self.bias_ih is not None:
            gates = gates + self.bias_ih
        gates = gates + _C_ops.matmul(pre_h, self.weight_hh, transpose_y=True)
        if self.bias_hh is not None:
            gates = gates + self.bias_hh
        i, f, g, o = _C_ops.split(gates, 4, axis=-1)
        i = _C_ops.sigmoid(i)
        f = _C_ops.sigmoid(f)
        o = _C_ops.sigmoid(o)
        c = f * pre_c + i * _C_ops.tanh(g)
        h = o * _C_ops.tanh(c)
        if self.weight_ho is not None:
            h = _C_ops.matmul(h, self.weight_ho, transpose_y=True)
        return h, (h, c)

    @property
    def state_shape(self):
        return ((self.proj_size or self.hidden_size,), (self.hidden_size,))

    def extra_repr(self):
        return "{input_size}, {hidden_size}".format(**self.__dict__)


class GRUCell(RNNCellBase):
    """Gate order [r, z, c]; reset applied after the recurrent matmul
    (reference rnn.py:1316-1324)."""

    def __init__(self, input_size, hidden_size,
                 weight_ih_attr=None, weight_hh_attr=None,
                 bias_ih_attr=None, bias_hh_attr=None, name=None):
        super().__init__()
        if hidden_size <= 0:
            raise ValueError("hidden_size must be positive")
        self.input_size = input_size
        self.hidden_size = hidden_size
        init = _stdv_uniform(hidden_size)
        self.weight_ih = self.create_parameter(
            [3 * hidden_size, input_size], ParamAttr._to_attr(weight_ih_attr),
            default_initializer=init)
        self.weight_hh = self.create_parameter(
            [3 * hidden_size, hidden_size], ParamAttr._to_attr(weight_hh_attr),
            default_initializer=init)
        self.bias_ih = (None if bias_ih_attr is False else
                        self.create_parameter(
                            [3 * hidden_size], ParamAttr._to_attr(bias_ih_attr),
                            is_bias=True, default_initializer=init))
        self.bias_hh = (None if bias_hh_attr is False else
                        self.create_parameter(
                            [3 * hidden_size], ParamAttr._to_attr(bias_hh_attr),
                            is_bias=True, default_initializer=init))

    def forward(self, inputs, states=None):
        if states is None:
            states = self.get_initial_states(inputs)
        pre_h = states
        x_gates = _C_ops.matmul(inputs, self.weight_ih, transpose_y=True)
        if self.bias_ih is not None:
            x_gates = x_gates + self.bias_ih
        h_gates = _C_ops.matmul(pre_h, self.weight_hh, transpose_y=True)
        if self.bias_hh is not None:
            h_gates = h_gates + self.bias_hh
        x_r, x_z, x_c = _C_ops.split(x_gates, 3, axis=-1)
        h_r, h_z, h_c = _C_ops.split(h_gates, 3, axis=-1)
        r = _C_ops.sigmoid(x_r + h_r)
        z = _C_ops.sigmoid(x_z + h_z)
        c = _C_ops.tanh(x_c + r * h_c)
        h = (pre_h - c) * z + c
        return h, h

    @property
    def state_shape(self):
        return (self.hidden_size,)

    def extra_repr(self):
        return "{input_size}, {hidden_size}".format(**self.__dict__)


class RNN(Layer):
    """Wraps a cell to run over a sequence (reference rnn.py:320) — the
    dygraph python loop, so ANY user cell works."""

    def __init__(self, cell, is_reverse=False, time_major=False):
        super().__init__()
        self.cell = cell
        if not hasattr(self.cell, "call") and not hasattr(self.cell, "forward"):
            raise TypeError("cell must have a forward method")
        self.is_reverse = is_reverse
        self.time_major = time_major

    def forward(self, inputs, initial_states=None, sequence_length=None,
                **kwargs):
        batch_index = 1 if self.time_major else 0
        time_axis = 0 if self.time_major else 1
        if initial_states is None:
            initial_states = self.cell.get_initial_states(
                inputs, batch_dim_idx=batch_index)
        T = inputs.shape[time_axis]
        steps = range(T - 1, -1, -1) if self.is_reverse else range(T)
        states = initial_states
        outputs = []
        if sequence_length is not None:
            seq = sequence_length
            if not isinstance(seq, Tensor):
                seq = Tensor(np.asarray(seq))
        for t in steps:
            x_t = inputs[t] if self.time_major else inputs[:, t]
            out, new_states = self.cell(x_t, states, **kwargs)
            if sequence_length is not None:
                valid = (seq > t).astype(out.dtype).unsqueeze(-1)
                out = out * valid
                new_states = _map_structure(
                    lambda ns, s: ns * valid + s * (1.0 - valid),
                    new_states, states)
            outputs.append(out)
            states = new_states
        if self.is_reverse:
            outputs = outputs[::-1]
        out = _C_ops.stack(outputs, axis=time_axis)
        return out, states


def _map_structure(fn, a, b):
    if isinstance(a, (tuple, list)):
        return type(a)(_map_structure(fn, x, y) for x, y in zip(a, b))
    return fn(a, b)


class BiRNN(Layer):
    """Forward + backward cells over a sequence (reference rnn.py:450)."""

    def __init__(self, cell_fw, cell_bw, time_major=False):
        super().__init__()
        self.cell_fw = cell_fw
        self.cell_bw = cell_bw
        self.time_major = time_major
        self._fw = RNN(cell_fw, is_reverse=False, time_major=time_major)
        self._bw = RNN(cell_bw, is_reverse=True, time_major=time_major)

    def forward(self, inputs, initial_states=None, sequence_length=None,
                **kwargs):
        if initial_states is None:
            states_fw = states_bw = None
        else:
            states_fw, states_bw = initial_states
        out_fw, st_fw = self._fw(inputs, states_fw, sequence_length, **kwargs)
        out_bw, st_bw = self._bw(inputs, states_bw, sequence_length, **kwargs)
        out = _C_ops.concat([out_fw, out_bw], axis=-1)
        return out, (st_fw, st_bw)


class RNNBase(LayerList):
    """Multi-layer (bi)directional recurrence dispatching to the fused `rnn`
    op (reference rnn.py:1514; fused path :1730). Parameters are exposed with
    the reference's flat names (weight_ih_l{k}[_reverse], ...)."""

    def __init__(self, mode, input_size, hidden_size, num_layers=1,
                 direction="forward", time_major=False, dropout=0.0,
                 activation="tanh", weight_ih_attr=None, weight_hh_attr=None,
                 bias_ih_attr=None, bias_hh_attr=None, proj_size=0):
        super().__init__()
        bidirectional = direction in ("bidirect", "bidirectional")
        if not bidirectional and direction != "forward":
            raise ValueError(
                "direction should be forward or bidirect (or bidirectional), "
                f"received direction = {direction}")
        if mode == "LSTM" and proj_size:
            raise NotImplementedError(
                "proj_size on the fused path is not implemented; use "
                "RNN(LSTMCell(..., proj_size=...)) for projections")
        self.mode = mode
        self.input_size = input_size
        self.hidden_size = hidden_size
        self.num_layers = num_layers
        self.time_major = time_major
        self.dropout = dropout
        self.activation = activation
        self.num_directions = 2 if bidirectional else 1
        self.proj_size = proj_size
        G = {"LSTM": 4, "GRU": 3}.get(mode, 1)
        self._has_bias_ih = bias_ih_attr is not False
        self._has_bias_hh = bias_hh_attr is not False
        init = _stdv_uniform(hidden_size)
        self._flat_names = []
        for layer in range(num_layers):
            for d in range(self.num_directions):
                suffix = "_reverse" if d == 1 else ""
                in_sz = (input_size if layer == 0
                         else hidden_size * self.num_directions)
                w_ih = self.create_parameter(
                    [G * hidden_size, in_sz], ParamAttr._to_attr(weight_ih_attr),
                    default_initializer=init)
                w_hh = self.create_parameter(
                    [G * hidden_size, hidden_size],
                    ParamAttr._to_attr(weight_hh_attr),
                    default_initializer=init)
                names = [f"weight_ih_l{layer}{suffix}",
                         f"weight_hh_l{layer}{suffix}"]
                self.add_parameter(names[0], w_ih)
                self.add_parameter(names[1], w_hh)
                if self._has_bias_ih:
                    b = self.create_parameter(
                        [G * hidden_size], ParamAttr._to_attr(bias_ih_attr),
                        is_bias=True, default_initializer=init)
                    names.append(f"bias_ih_l{layer}{suffix}")
                    self.add_parameter(names[-1], b)
                if self._has_bias_hh:
                    b = self.create_parameter(
                        [G * hidden_size], ParamAttr._to_attr(bias_hh_attr),
                        is_bias=True, default_initializer=init)
                    names.append(f"bias_hh_l{layer}{suffix}")
                    self.add_parameter(names[-1], b)
                self._flat_names.extend(names)
        # the reference keeps could_use_cudnn; our fused XLA path is always
        # usable (it is the cudnn analog), recorded for API compat
        self.could_use_cudnn = True
        self.state_components = 2 if mode == "LSTM" else 1

    def _weight_list(self):
        """Bundles [w_ih, w_hh, b_ih|None, b_hh|None] per (layer, direction)."""
        bundles = []
        it = iter(self._flat_names)
        for _ in range(self.num_layers * self.num_directions):
            w_ih = self._parameters[next(it)]
            w_hh = self._parameters[next(it)]
            b_ih = self._parameters[next(it)] if self._has_bias_ih else None
            b_hh = self._parameters[next(it)] if self._has_bias_hh else None
            bundles.append([w_ih, w_hh, b_ih, b_hh])
        return bundles

    def forward(self, inputs, initial_states=None, sequence_length=None):
        batch_index = 1 if self.time_major else 0
        B = inputs.shape[batch_index]
        LD = self.num_layers * self.num_directions
        if initial_states is None:
            zero = _C_ops.full([LD, B, self.hidden_size], 0.0, inputs.dtype)
            initial_states = ((zero, zero) if self.mode == "LSTM" else zero)
        if self.mode == "LSTM":
            init_h, init_c = initial_states
        else:
            init_h, init_c = initial_states, None
        mask = None
        if self.dropout > 0.0 and self.training and self.num_layers > 1:
            # scaled masks via the registered dropout op so paddle.seed /
            # the framework Generator governs them (and they trace cleanly)
            T = inputs.shape[0 if self.time_major else 1]
            feat = self.hidden_size * self.num_directions
            ones = _C_ops.full([self.num_layers - 1, T, B, feat], 1.0,
                               inputs.dtype)
            mask = _C_ops.dropout(ones, p=self.dropout, training=True,
                                  mode="upscale_in_train")
        seq = None
        if sequence_length is not None:
            seq = (sequence_length if isinstance(sequence_length, Tensor)
                   else Tensor(np.asarray(sequence_length)))
        res = _C_ops.rnn(
            inputs, init_h, init_c, self._weight_list(), seq, mask,
            mode=self.mode, num_layers=self.num_layers,
            is_bidirec=self.num_directions == 2,
            time_major=self.time_major, activation=self.activation)
        if self.mode == "LSTM":
            out, h_n, c_n = res
            return out, (h_n, c_n)
        out, h_n = res
        return out, h_n

    def extra_repr(self):
        s = "{input_size}, {hidden_size}"
        if self.num_layers != 1:
            s += ", num_layers={num_layers}"
        if self.time_major:
            s += ", time_major=True"
        if self.dropout:
            s += ", dropout={dropout}"
        return s.format(**self.__dict__)


class SimpleRNN(RNNBase):
    """Reference rnn.py:1860 (mode RNN_TANH / RNN_RELU)."""

    def __init__(self, input_size, hidden_size, num_layers=1,
                 direction="forward", time_major=False, dropout=0.0,
                 activation="tanh", weight_ih_attr=None, weight_hh_attr=None,
                 bias_ih_attr=None, bias_hh_attr=None, name=None):
        if activation not in ("tanh", "relu"):
            raise ValueError("activation should be tanh or relu")
        super().__init__("RNN_TANH" if activation == "tanh" else "RNN_RELU",
                         input_size, hidden_size, num_layers, direction,
                         time_major, dropout, activation,
                         weight_ih_attr, weight_hh_attr,
                         bias_ih_attr, bias_hh_attr)


class LSTM(RNNBase):
    """Reference rnn.py:1975."""

    def __init__(self, input_size, hidden_size, num_layers=1,
                 direction="forward", time_major=False, dropout=0.0,
                 weight_ih_attr=None, weight_hh_attr=None,
                 bias_ih_attr=None, bias_hh_attr=None, proj_size=0, name=None):
        super().__init__("LSTM", input_size, hidden_size, num_layers,
                         direction, time_major, dropout, "tanh",
                         weight_ih_attr, weight_hh_attr,
                         bias_ih_attr, bias_hh_attr, proj_size)


class GRU(RNNBase):
    """Reference rnn.py:2115."""

    def __init__(self, input_size, hidden_size, num_layers=1,
                 direction="forward", time_major=False, dropout=0.0,
                 weight_ih_attr=None, weight_hh_attr=None,
                 bias_ih_attr=None, bias_hh_attr=None, name=None):
        super().__init__("GRU", input_size, hidden_size, num_layers,
                         direction, time_major, dropout, "tanh",
                         weight_ih_attr, weight_hh_attr,
                         bias_ih_attr, bias_hh_attr)

"""Common layers (reference: python/paddle/nn/layer/common.py)."""
from __future__ import annotations

from ... import _C_ops
from .. import functional as F
from ..param_attr import ParamAttr
from .layers import Layer


class Linear(Layer):
    """Reference: python/paddle/nn/layer/common.py Linear — weight [in, out]."""

    def __init__(self, in_features, out_features, weight_attr=None, bias_attr=None, name=None):
        super().__init__()
        self.in_features = in_features
        self.out_features = out_features
        from .. import initializer as I

        self.weight = self.create_parameter(
            [in_features, out_features], ParamAttr._to_attr(weight_attr), self._dtype
        )
        if bias_attr is not False:
            self.bias = self.create_parameter(
                [out_features], ParamAttr._to_attr(bias_attr), self._dtype, is_bias=True
            )
        else:
            self.bias = None

    def forward(self, x):
        return F.linear(x, self.weight, self.bias)

    def extra_repr(self):
        return f"in_features={self.in_features}, out_features={self.out_features}"


class Embedding(Layer):
    """Reference: python/paddle/nn/layer/common.py Embedding."""

    def __init__(
        self, num_embeddings, embedding_dim, padding_idx=None, sparse=False, weight_attr=None, name=None
    ):
        super().__init__()
        self._num_embeddings = num_embeddings
        self._embedding_dim = embedding_dim
        self._padding_idx = (
            None if padding_idx is None else (padding_idx if padding_idx >= 0 else num_embeddings + padding_idx)
        )
        from .. import initializer as I

        attr = ParamAttr._to_attr(weight_attr)
        self.weight = self.create_parameter(
            [num_embeddings, embedding_dim], attr, self._dtype, default_initializer=I.Normal(0.0, 1.0) if attr is None else None
        )
        if self._padding_idx is not None:
            self.weight._data = self.weight._data.at[self._padding_idx].set(0.0)

    def forward(self, x):
        return F.embedding(x, self.weight, self._padding_idx, False)

    def extra_repr(self):
        return f"{self._num_embeddings}, {self._embedding_dim}"


class Dropout(Layer):
    def __init__(self, p=0.5, axis=None, mode="upscale_in_train", name=None):
        super().__init__()
        self.p = p
        self.axis = axis
        self.mode = mode

    def forward(self, x):
        return F.dropout(x, self.p, self.axis, self.training, self.mode)

    def extra_repr(self):
        return f"p={self.p}"


class Dropout2D(Layer):
    def __init__(self, p=0.5, data_format="NCHW", name=None):
        super().__init__()
        self.p = p
        self.data_format = data_format

    def forward(self, x):
        return F.dropout2d(x, self.p, self.training, self.data_format)


class Flatten(Layer):
    def __init__(self, start_axis=1, stop_axis=-1):
        super().__init__()
        self.start_axis = start_axis
        self.stop_axis = stop_axis

    def forward(self, x):
        return _C_ops.flatten(x, self.start_axis, self.stop_axis)


class Identity(Layer):
    def __init__(self, *args, **kwargs):
        super().__init__()

    def forward(self, x):
        return x


class Upsample(Layer):
    def __init__(
        self, size=None, scale_factor=None, mode="nearest", align_corners=False, data_format="NCHW", name=None
    ):
        super().__init__()
        self.size = size
        self.scale_factor = scale_factor
        self.mode = mode
        self.align_corners = align_corners
        self.data_format = data_format

    def forward(self, x):
        return F.interpolate(x, self.size, self.scale_factor, self.mode, self.align_corners, self.data_format)


class Pad2D(Layer):
    def __init__(self, padding, mode="constant", value=0.0, data_format="NCHW", name=None):
        super().__init__()
        self.padding = padding if isinstance(padding, (list, tuple)) else [padding] * 4
        self.mode = mode
        self.value = value
        self.data_format = data_format

    def forward(self, x):
        return _C_ops.pad(x, list(self.padding), self.mode, self.value, self.data_format)


class ZeroPad2D(Pad2D):
    def __init__(self, padding, data_format="NCHW", name=None):
        super().__init__(padding, "constant", 0.0, data_format, name)


class CosineSimilarity(Layer):
    def __init__(self, axis=1, eps=1e-8):
        super().__init__()
        self.axis = axis
        self.eps = eps

    def forward(self, x1, x2):
        return F.cosine_similarity(x1, x2, self.axis, self.eps)

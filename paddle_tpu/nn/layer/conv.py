"""Convolution layers (reference: python/paddle/nn/layer/conv.py)."""
from __future__ import annotations

import numpy as np

from .. import functional as F
from .. import initializer as I
from ..param_attr import ParamAttr
from .layers import Layer


def _ntuple(v, n):
    return list(v) if isinstance(v, (list, tuple)) else [v] * n


class _ConvNd(Layer):
    def __init__(
        self,
        in_channels,
        out_channels,
        kernel_size,
        nd,
        stride=1,
        padding=0,
        dilation=1,
        groups=1,
        padding_mode="zeros",
        weight_attr=None,
        bias_attr=None,
        data_format="NCHW",
    ):
        super().__init__()
        self._in_channels = in_channels
        self._out_channels = out_channels
        self._kernel_size = _ntuple(kernel_size, nd)
        self._stride = _ntuple(stride, nd)
        self._padding = padding
        self._dilation = _ntuple(dilation, nd)
        self._groups = groups
        self._data_format = data_format
        self._nd = nd
        filter_shape = [out_channels, in_channels // groups] + self._kernel_size
        fan_in = (in_channels // groups) * int(np.prod(self._kernel_size))
        bound = 1.0 / np.sqrt(fan_in)
        self.weight = self.create_parameter(
            filter_shape,
            ParamAttr._to_attr(weight_attr),
            self._dtype,
            default_initializer=I.KaimingUniform(nonlinearity="leaky_relu", negative_slope=np.sqrt(5.0)),
        )
        if bias_attr is not False:
            self.bias = self.create_parameter(
                [out_channels],
                ParamAttr._to_attr(bias_attr),
                self._dtype,
                is_bias=True,
                default_initializer=I.Uniform(-bound, bound) if bias_attr is None else None,
            )
        else:
            self.bias = None

    def extra_repr(self):
        return (
            f"{self._in_channels}, {self._out_channels}, kernel_size={self._kernel_size}, "
            f"stride={self._stride}, padding={self._padding}"
        )


class Conv1D(_ConvNd):
    def __init__(self, in_channels, out_channels, kernel_size, stride=1, padding=0, dilation=1,
                 groups=1, padding_mode="zeros", weight_attr=None, bias_attr=None, data_format="NCL"):
        super().__init__(in_channels, out_channels, kernel_size, 1, stride, padding, dilation,
                         groups, padding_mode, weight_attr, bias_attr, data_format)

    def forward(self, x):
        return F.conv1d(x, self.weight, self.bias, self._stride, self._padding, self._dilation,
                        self._groups, self._data_format)


class Conv2D(_ConvNd):
    def __init__(self, in_channels, out_channels, kernel_size, stride=1, padding=0, dilation=1,
                 groups=1, padding_mode="zeros", weight_attr=None, bias_attr=None, data_format="NCHW"):
        super().__init__(in_channels, out_channels, kernel_size, 2, stride, padding, dilation,
                         groups, padding_mode, weight_attr, bias_attr, data_format)

    def forward(self, x):
        return F.conv2d(x, self.weight, self.bias, self._stride, self._padding, self._dilation,
                        self._groups, self._data_format)


class Conv3D(_ConvNd):
    def __init__(self, in_channels, out_channels, kernel_size, stride=1, padding=0, dilation=1,
                 groups=1, padding_mode="zeros", weight_attr=None, bias_attr=None, data_format="NCDHW"):
        super().__init__(in_channels, out_channels, kernel_size, 3, stride, padding, dilation,
                         groups, padding_mode, weight_attr, bias_attr, data_format)

    def forward(self, x):
        return F.conv3d(x, self.weight, self.bias, self._stride, self._padding, self._dilation,
                        self._groups, self._data_format)


class Conv2DTranspose(Layer):
    def __init__(self, in_channels, out_channels, kernel_size, stride=1, padding=0, output_padding=0,
                 dilation=1, groups=1, weight_attr=None, bias_attr=None, data_format="NCHW"):
        super().__init__()
        self._stride = _ntuple(stride, 2)
        self._padding = padding
        self._output_padding = output_padding
        self._dilation = _ntuple(dilation, 2)
        self._groups = groups
        self._data_format = data_format
        kernel_size = _ntuple(kernel_size, 2)
        filter_shape = [in_channels, out_channels // groups] + kernel_size
        self.weight = self.create_parameter(filter_shape, ParamAttr._to_attr(weight_attr), self._dtype)
        if bias_attr is not False:
            self.bias = self.create_parameter([out_channels], ParamAttr._to_attr(bias_attr), self._dtype, is_bias=True)
        else:
            self.bias = None

    def forward(self, x, output_size=None):
        output_padding = self._output_padding
        if output_size is not None:
            # derive output_padding so the result matches the requested size
            if isinstance(output_size, int):
                output_size = [output_size, output_size]
            spatial = x.shape[2:4] if self._data_format == "NCHW" else x.shape[1:3]
            k = self.weight.shape[2:4]
            p = _ntuple(self._padding, 2)
            output_padding = [
                output_size[i]
                - ((spatial[i] - 1) * self._stride[i] - 2 * p[i] + self._dilation[i] * (k[i] - 1) + 1)
                for i in range(2)
            ]
        return F.conv2d_transpose(x, self.weight, self.bias, self._stride, self._padding,
                                  output_padding, self._dilation, self._groups, self._data_format)

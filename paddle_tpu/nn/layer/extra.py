"""nn Layer tail (reference: python/paddle/nn/layer/*) — the Layer classes
the reference exports that wrap the round-5 functional tail: 1D/3D pools,
unpools, dropout variants, loss modules, padding, upsampling, seq decoding.
"""
from __future__ import annotations

import numpy as np

from ... import _C_ops
from .. import functional as F
from ..initializer import XavierNormal
from .layers import Layer

__all__ = [
    "AdaptiveAvgPool1D", "AdaptiveAvgPool3D", "AdaptiveMaxPool1D",
    "AdaptiveMaxPool3D", "AdaptiveLogSoftmaxWithLoss", "AlphaDropout",
    "AvgPool3D", "MaxPool3D", "BeamSearchDecoder", "Bilinear",
    "ChannelShuffle", "Conv1DTranspose", "Conv3DTranspose",
    "CosineEmbeddingLoss", "Dropout3D", "FeatureAlphaDropout", "Fold",
    "FractionalMaxPool2D", "FractionalMaxPool3D", "GaussianNLLLoss",
    "HSigmoidLoss", "HingeEmbeddingLoss", "LPPool1D", "LPPool2D",
    "MaxUnPool1D", "MaxUnPool2D", "MaxUnPool3D",
    "MultiLabelSoftMarginLoss", "MultiMarginLoss", "Pad1D", "Pad3D",
    "PairwiseDistance", "ParameterDict", "PixelShuffle", "PixelUnshuffle",
    "PoissonNLLLoss", "RNNTLoss", "RReLU", "SoftMarginLoss", "Softmax2D",
    "SpectralNorm", "TripletMarginLoss", "TripletMarginWithDistanceLoss",
    "Unflatten", "Unfold", "UpsamplingBilinear2D", "UpsamplingNearest2D",
    "ZeroPad1D", "ZeroPad3D", "dynamic_decode",
]


# -- pooling -----------------------------------------------------------------

class AdaptiveAvgPool1D(Layer):
    def __init__(self, output_size, name=None):
        super().__init__()
        self.output_size = output_size

    def forward(self, x):
        return F.adaptive_avg_pool1d(x, self.output_size)


class AdaptiveAvgPool3D(Layer):
    def __init__(self, output_size, data_format="NCDHW", name=None):
        super().__init__()
        self.output_size = output_size
        self.data_format = data_format

    def forward(self, x):
        return F.adaptive_avg_pool3d(x, self.output_size, self.data_format)


class AdaptiveMaxPool1D(Layer):
    def __init__(self, output_size, return_mask=False, name=None):
        super().__init__()
        self.output_size = output_size
        self.return_mask = return_mask

    def forward(self, x):
        return F.adaptive_max_pool1d(x, self.output_size, self.return_mask)


class AdaptiveMaxPool3D(Layer):
    def __init__(self, output_size, return_mask=False, name=None):
        super().__init__()
        self.output_size = output_size
        self.return_mask = return_mask

    def forward(self, x):
        return F.adaptive_max_pool3d(x, self.output_size, self.return_mask)


class MaxPool3D(Layer):
    def __init__(self, kernel_size, stride=None, padding=0, ceil_mode=False,
                 return_mask=False, data_format="NCDHW", name=None):
        super().__init__()
        self.args = (kernel_size, stride, padding, ceil_mode, return_mask,
                     data_format)

    def forward(self, x):
        return F.max_pool3d(x, *self.args)


class AvgPool3D(Layer):
    def __init__(self, kernel_size, stride=None, padding=0, ceil_mode=False,
                 exclusive=True, divisor_override=None, data_format="NCDHW",
                 name=None):
        super().__init__()
        self.args = (kernel_size, stride, padding, ceil_mode, exclusive,
                     divisor_override, data_format)

    def forward(self, x):
        return F.avg_pool3d(x, *self.args)


class LPPool1D(Layer):
    def __init__(self, norm_type, kernel_size, stride=None, padding=0,
                 ceil_mode=False, data_format="NCL", name=None):
        super().__init__()
        self.args = (norm_type, kernel_size, stride, padding, ceil_mode,
                     data_format)

    def forward(self, x):
        return F.lp_pool1d(x, *self.args)


class LPPool2D(Layer):
    def __init__(self, norm_type, kernel_size, stride=None, padding=0,
                 ceil_mode=False, data_format="NCHW", name=None):
        super().__init__()
        self.args = (norm_type, kernel_size, stride, padding, ceil_mode)

    def forward(self, x):
        return F.lp_pool2d(x, *self.args)


class FractionalMaxPool2D(Layer):
    def __init__(self, output_size, kernel_size=None, random_u=None,
                 return_mask=False, name=None):
        super().__init__()
        self.output_size = output_size
        self.kernel_size = kernel_size
        self.random_u = random_u
        self.return_mask = return_mask

    def forward(self, x):
        return F.fractional_max_pool2d(x, self.output_size,
                                       kernel_size=self.kernel_size,
                                       random_u=self.random_u,
                                       return_mask=self.return_mask)


class FractionalMaxPool3D(Layer):
    def __init__(self, output_size, kernel_size=None, random_u=None,
                 return_mask=False, name=None):
        super().__init__()
        self.output_size = output_size
        self.kernel_size = kernel_size
        self.random_u = random_u
        self.return_mask = return_mask

    def forward(self, x):
        return F.fractional_max_pool3d(x, self.output_size,
                                       kernel_size=self.kernel_size,
                                       random_u=self.random_u,
                                       return_mask=self.return_mask)


class MaxUnPool1D(Layer):
    def __init__(self, kernel_size, stride=None, padding=0,
                 data_format="NCL", output_size=None, name=None):
        super().__init__()
        self.args = (kernel_size, stride, padding, output_size, data_format)

    def forward(self, x, indices):
        k, s, p, out, fmt = self.args
        return F.max_unpool1d(x, indices, k, s, p, out, fmt)


class MaxUnPool2D(Layer):
    def __init__(self, kernel_size, stride=None, padding=0,
                 data_format="NCHW", output_size=None, name=None):
        super().__init__()
        self.args = (kernel_size, stride, padding, output_size, data_format)

    def forward(self, x, indices):
        k, s, p, out, fmt = self.args
        return F.max_unpool2d(x, indices, k, s, p, out, fmt)


class MaxUnPool3D(Layer):
    def __init__(self, kernel_size, stride=None, padding=0,
                 data_format="NCDHW", output_size=None, name=None):
        super().__init__()
        self.args = (kernel_size, stride, padding, output_size, data_format)

    def forward(self, x, indices):
        k, s, p, out, fmt = self.args
        return F.max_unpool3d(x, indices, k, s, p, out, fmt)


# -- conv --------------------------------------------------------------------

class Conv1DTranspose(Layer):
    def __init__(self, in_channels, out_channels, kernel_size, stride=1,
                 padding=0, output_padding=0, groups=1, dilation=1,
                 weight_attr=None, bias_attr=None, data_format="NCL"):
        super().__init__()
        k = kernel_size if isinstance(kernel_size, int) else kernel_size[0]
        init = XavierNormal()
        self.weight = self.create_parameter(
            [in_channels, out_channels // groups, k],
            default_initializer=init)
        self.bias = None if bias_attr is False else self.create_parameter(
            [out_channels], default_initializer=None, is_bias=True)
        self._args = (stride, padding, output_padding, groups, dilation,
                      data_format)

    def forward(self, x, output_size=None):
        s, p, op, g, d, fmt = self._args
        return F.conv1d_transpose(x, self.weight, self.bias, s, p, op, g, d,
                                  output_size, fmt)


class Conv3DTranspose(Layer):
    def __init__(self, in_channels, out_channels, kernel_size, stride=1,
                 padding=0, output_padding=0, groups=1, dilation=1,
                 weight_attr=None, bias_attr=None, data_format="NCDHW"):
        super().__init__()
        k = ([kernel_size] * 3 if isinstance(kernel_size, int)
             else list(kernel_size))
        self.weight = self.create_parameter(
            [in_channels, out_channels // groups, *k],
            default_initializer=XavierNormal())
        self.bias = None if bias_attr is False else self.create_parameter(
            [out_channels], default_initializer=None, is_bias=True)
        self._args = (stride, padding, output_padding, groups, dilation,
                      data_format)

    def forward(self, x, output_size=None):
        s, p, op, g, d, fmt = self._args
        return _C_ops.conv3d_transpose(x, self.weight, self.bias, strides=s,
                                       paddings=p, output_padding=op,
                                       output_size=output_size, groups=g,
                                       dilations=d, data_format=fmt)


# -- simple wrappers ---------------------------------------------------------

class Bilinear(Layer):
    def __init__(self, in1_features, in2_features, out_features,
                 weight_attr=None, bias_attr=None, name=None):
        super().__init__()
        self.weight = self.create_parameter(
            [out_features, in1_features, in2_features],
            default_initializer=XavierNormal())
        self.bias = None if bias_attr is False else self.create_parameter(
            [1, out_features], default_initializer=None, is_bias=True)

    def forward(self, x1, x2):
        return F.bilinear(x1, x2, self.weight, self.bias)


class ChannelShuffle(Layer):
    def __init__(self, groups, data_format="NCHW", name=None):
        super().__init__()
        self.groups = groups
        self.data_format = data_format

    def forward(self, x):
        return F.channel_shuffle(x, self.groups, self.data_format)


class PixelShuffle(Layer):
    def __init__(self, upscale_factor, data_format="NCHW", name=None):
        super().__init__()
        self.factor = upscale_factor
        self.data_format = data_format

    def forward(self, x):
        return F.pixel_shuffle(x, self.factor)


class PixelUnshuffle(Layer):
    def __init__(self, downscale_factor, data_format="NCHW", name=None):
        super().__init__()
        self.factor = downscale_factor
        self.data_format = data_format

    def forward(self, x):
        return F.pixel_unshuffle(x, self.factor, self.data_format)


class Fold(Layer):
    def __init__(self, output_sizes, kernel_sizes, strides=1, paddings=0,
                 dilations=1, name=None):
        super().__init__()
        self.args = (output_sizes, kernel_sizes, strides, paddings,
                     dilations)

    def forward(self, x):
        return F.fold(x, *self.args)


class Unfold(Layer):
    def __init__(self, kernel_sizes, strides=1, paddings=0, dilations=1,
                 name=None):
        super().__init__()
        self.args = (kernel_sizes, strides, paddings, dilations)

    def forward(self, x):
        return F.unfold(x, *self.args)


class Unflatten(Layer):
    def __init__(self, axis, shape, name=None):
        super().__init__()
        self.axis = axis
        self.shape = shape

    def forward(self, x):
        from ... import unflatten

        return unflatten(x, self.axis, self.shape)


class Softmax2D(Layer):
    """Softmax over the channel axis of NCHW inputs."""

    def forward(self, x):
        return F.softmax(x, axis=-3)


class RReLU(Layer):
    def __init__(self, lower=1.0 / 8.0, upper=1.0 / 3.0, name=None):
        super().__init__()
        self.lower = lower
        self.upper = upper

    def forward(self, x):
        return F.rrelu(x, self.lower, self.upper,
                       is_test=not self.training)


class SpectralNorm(Layer):
    """Standalone spectral-norm layer (reference: nn/layer/norm.py
    SpectralNorm): returns the weight normalized by its largest singular
    value via power iteration; u/v are persistent power-iteration state."""

    def __init__(self, weight_shape, dim=0, power_iters=1, epsilon=1e-12,
                 dtype="float32"):
        super().__init__()
        self.dim = dim
        self.power_iters = power_iters
        self.epsilon = epsilon
        h = weight_shape[dim]
        w = int(np.prod(weight_shape)) // h
        from ... import randn

        self.register_buffer("weight_u", randn([h], dtype))
        self.register_buffer("weight_v", randn([w], dtype))

    def forward(self, weight):
        return _C_ops.spectral_norm(weight, self.weight_u, self.weight_v,
                                    self.dim, self.power_iters, self.epsilon)


class AlphaDropout(Layer):
    def __init__(self, p=0.5, name=None):
        super().__init__()
        self.p = p

    def forward(self, x):
        return F.alpha_dropout(x, self.p, self.training)


class FeatureAlphaDropout(Layer):
    def __init__(self, p=0.5, name=None):
        super().__init__()
        self.p = p

    def forward(self, x):
        return F.feature_alpha_dropout(x, self.p, self.training)


class Dropout3D(Layer):
    def __init__(self, p=0.5, data_format="NCDHW", name=None):
        super().__init__()
        self.p = p
        self.data_format = data_format

    def forward(self, x):
        return F.dropout3d(x, self.p, self.training, self.data_format)


# -- padding -----------------------------------------------------------------

class Pad1D(Layer):
    def __init__(self, padding, mode="constant", value=0.0,
                 data_format="NCL", name=None):
        super().__init__()
        self.padding = ([padding] * 2 if isinstance(padding, int)
                        else list(padding))
        self.mode = mode
        self.value = value
        self.data_format = data_format

    def forward(self, x):
        return _C_ops.pad(x, self.padding, self.mode, self.value,
                          self.data_format)


class Pad3D(Layer):
    def __init__(self, padding, mode="constant", value=0.0,
                 data_format="NCDHW", name=None):
        super().__init__()
        self.padding = ([padding] * 6 if isinstance(padding, int)
                        else list(padding))
        self.mode = mode
        self.value = value
        self.data_format = data_format

    def forward(self, x):
        return _C_ops.pad3d(x, self.padding, self.mode, self.value,
                            self.data_format)


class ZeroPad1D(Pad1D):
    def __init__(self, padding, data_format="NCL", name=None):
        super().__init__(padding, "constant", 0.0, data_format)


class ZeroPad3D(Pad3D):
    def __init__(self, padding, data_format="NCDHW", name=None):
        super().__init__(padding, "constant", 0.0, data_format)


# -- upsampling --------------------------------------------------------------

class UpsamplingNearest2D(Layer):
    def __init__(self, size=None, scale_factor=None, data_format="NCHW",
                 name=None):
        super().__init__()
        self.size = size
        self.scale_factor = scale_factor
        self.data_format = data_format

    def forward(self, x):
        return F.interpolate(x, self.size, self.scale_factor, "nearest",
                             data_format=self.data_format)


class UpsamplingBilinear2D(Layer):
    def __init__(self, size=None, scale_factor=None, data_format="NCHW",
                 name=None):
        super().__init__()
        self.size = size
        self.scale_factor = scale_factor
        self.data_format = data_format

    def forward(self, x):
        return F.interpolate(x, self.size, self.scale_factor, "bilinear",
                             align_corners=True,
                             data_format=self.data_format)


# -- distance / losses -------------------------------------------------------

class PairwiseDistance(Layer):
    def __init__(self, p=2.0, epsilon=1e-06, keepdim=False, name=None):
        super().__init__()
        self.args = (p, epsilon, keepdim)

    def forward(self, x, y):
        return F.pairwise_distance(x, y, *self.args)


def _loss_layer(name, fn, arg_names, defaults):
    """Build a Layer class delegating to a functional loss — the reference's
    loss modules are exactly this shape."""

    class _Loss(Layer):
        def __init__(self, **kwargs):
            super().__init__()
            bad = set(kwargs) - set(arg_names) - {"name"}
            if bad:
                raise TypeError(f"{name}: unexpected args {sorted(bad)}")
            kwargs.pop("name", None)
            self.kwargs = {**defaults, **kwargs}

        def forward(self, *inputs):
            return fn(*inputs, **self.kwargs)

    _Loss.__name__ = name
    _Loss.__qualname__ = name
    return _Loss


CosineEmbeddingLoss = _loss_layer(
    "CosineEmbeddingLoss", F.cosine_embedding_loss,
    ["margin", "reduction"], {"margin": 0.0, "reduction": "mean"})
GaussianNLLLoss = _loss_layer(
    "GaussianNLLLoss", F.gaussian_nll_loss,
    ["full", "epsilon", "reduction"],
    {"full": False, "epsilon": 1e-06, "reduction": "mean"})
HingeEmbeddingLoss = _loss_layer(
    "HingeEmbeddingLoss", F.hinge_embedding_loss,
    ["margin", "reduction"], {"margin": 1.0, "reduction": "mean"})
MultiLabelSoftMarginLoss = _loss_layer(
    "MultiLabelSoftMarginLoss", F.multi_label_soft_margin_loss,
    ["weight", "reduction"], {"weight": None, "reduction": "mean"})
MultiMarginLoss = _loss_layer(
    "MultiMarginLoss", F.multi_margin_loss,
    ["p", "margin", "weight", "reduction"],
    {"p": 1, "margin": 1.0, "weight": None, "reduction": "mean"})
PoissonNLLLoss = _loss_layer(
    "PoissonNLLLoss", F.poisson_nll_loss,
    ["log_input", "full", "epsilon", "reduction"],
    {"log_input": True, "full": False, "epsilon": 1e-08,
     "reduction": "mean"})
SoftMarginLoss = _loss_layer(
    "SoftMarginLoss", F.soft_margin_loss, ["reduction"],
    {"reduction": "mean"})
TripletMarginLoss = _loss_layer(
    "TripletMarginLoss", F.triplet_margin_loss,
    ["margin", "p", "epsilon", "swap", "reduction"],
    {"margin": 1.0, "p": 2.0, "epsilon": 1e-06, "swap": False,
     "reduction": "mean"})
TripletMarginWithDistanceLoss = _loss_layer(
    "TripletMarginWithDistanceLoss", F.triplet_margin_with_distance_loss,
    ["distance_function", "margin", "swap", "reduction"],
    {"distance_function": None, "margin": 1.0, "swap": False,
     "reduction": "mean"})
RNNTLoss = _loss_layer(
    "RNNTLoss", F.rnnt_loss,
    ["blank", "fastemit_lambda", "reduction"],
    {"blank": 0, "fastemit_lambda": 0.001, "reduction": "mean"})


class HSigmoidLoss(Layer):
    def __init__(self, feature_size, num_classes, weight_attr=None,
                 bias_attr=None, is_custom=False, is_sparse=False,
                 name=None):
        super().__init__()
        self.num_classes = num_classes
        self.weight = self.create_parameter(
            [num_classes - 1, feature_size],
            default_initializer=XavierNormal())
        self.bias = None if bias_attr is False else self.create_parameter(
            [num_classes - 1], default_initializer=None, is_bias=True)

    def forward(self, input, label, path_table=None, path_code=None):
        return F.hsigmoid_loss(input, label, self.num_classes, self.weight,
                               self.bias, path_table, path_code)


class AdaptiveLogSoftmaxWithLoss(Layer):
    """Adaptive softmax module (reference: nn/layer/loss.py
    AdaptiveLogSoftmaxWithLoss): head covers the shortlist + one logit per
    tail cluster; each tail cluster is a down-projected two-matrix
    factorization."""

    def __init__(self, in_features, n_classes, cutoffs, div_value=4.0,
                 head_bias=False, name=None):
        super().__init__()
        cutoffs = list(cutoffs)
        if any(c <= 0 or c >= n_classes for c in cutoffs) or \
                sorted(set(cutoffs)) != cutoffs:
            raise ValueError("cutoffs must be unique, increasing, and in "
                             "(0, n_classes)")
        self.cutoffs = cutoffs + [n_classes]
        self.shortlist = cutoffs[0]
        self.n_clusters = len(self.cutoffs) - 1
        head_out = self.shortlist + self.n_clusters
        self.head_weight = self.create_parameter(
            [in_features, head_out], default_initializer=XavierNormal())
        self.head_bias = self.create_parameter(
            [head_out], default_initializer=None, is_bias=True) \
            if head_bias else None
        self.tail_weights = []
        for ci in range(self.n_clusters):
            hsz = max(1, int(in_features / (div_value ** (ci + 1))))
            osz = self.cutoffs[ci + 1] - self.cutoffs[ci]
            w1 = self.create_parameter([in_features, hsz],
                                       default_initializer=XavierNormal())
            w2 = self.create_parameter([hsz, osz],
                                       default_initializer=XavierNormal())
            self.add_parameter(f"tail_{ci}_w1", w1)
            self.add_parameter(f"tail_{ci}_w2", w2)
            self.tail_weights.append((w1, w2))

    def forward(self, input, label):
        return F.adaptive_log_softmax_with_loss(
            input, label, self.head_weight, self.tail_weights,
            [self.shortlist] + self.cutoffs[1:], self.head_bias)


# -- containers --------------------------------------------------------------

class ParameterDict(Layer):
    """Dict-style parameter container (reference: nn/layer/container.py)."""

    def __init__(self, parameters=None):
        super().__init__()
        if parameters:
            for k, v in (parameters.items()
                         if isinstance(parameters, dict) else parameters):
                self.add_parameter(str(k), v)

    def __getitem__(self, key):
        return self._parameters[str(key)]

    def __setitem__(self, key, value):
        self.add_parameter(str(key), value)

    def __len__(self):
        return len(self._parameters)

    def __iter__(self):
        return iter(self._parameters)

    def keys(self):
        return self._parameters.keys()

    def values(self):
        return self._parameters.values()

    def items(self):
        return self._parameters.items()

    def update(self, parameters):
        for k, v in (parameters.items()
                     if isinstance(parameters, dict) else parameters):
            self.add_parameter(str(k), v)


# -- sequence decoding -------------------------------------------------------

class BeamSearchDecoder:
    """Beam-search decoder over an RNN cell (reference:
    nn/decode.py BeamSearchDecoder). Host-driven: `dynamic_decode` steps the
    cell, expands beams with the `beam_search` op semantics (top-k over
    accumulated log-probs), and backtracks with `gather_tree`."""

    def __init__(self, cell, start_token, end_token, beam_size,
                 embedding_fn=None, output_fn=None):
        self.cell = cell
        self.start_token = int(start_token)
        self.end_token = int(end_token)
        self.beam_size = int(beam_size)
        self.embedding_fn = embedding_fn
        self.output_fn = output_fn


def dynamic_decode(decoder, inits=None, max_step_num=32, **kwargs):
    """Greedy-per-beam decode loop (reference: nn/decode.py
    dynamic_decode). Returns (ids [B, T_out, beam], final_state)."""
    import jax.numpy as jnp

    from ...core.tensor import Tensor

    cell = decoder.cell
    beam = decoder.beam_size
    state = inits
    # infer batch from the initial state pytree
    leaves = [s for s in (state if isinstance(state, (list, tuple))
                          else [state]) if s is not None]
    B = int(np.asarray(leaves[0].shape)[0]) if leaves else 1
    tok = Tensor._from_data(jnp.full((B * beam,), decoder.start_token,
                                     jnp.int64))

    def tile(s):
        if s is None:
            return None
        arr = s._data if isinstance(s, Tensor) else jnp.asarray(s)
        arr = jnp.repeat(arr, beam, axis=0)
        return Tensor._from_data(arr)

    state = [tile(s) for s in state] if isinstance(state, (list, tuple)) \
        else tile(state)
    log_probs = jnp.zeros((B * beam,), jnp.float32)
    ids = []
    finished = jnp.zeros((B * beam,), bool)
    for _ in range(max_step_num):
        inp = decoder.embedding_fn(tok) if decoder.embedding_fn else tok
        out, state = cell(inp, state)
        logits = decoder.output_fn(out) if decoder.output_fn else out
        lp = jax_log_softmax(logits)
        nxt = jnp.argmax(lp, axis=-1)
        step_lp = jnp.max(lp, axis=-1)
        log_probs = log_probs + jnp.where(finished, 0.0, step_lp)
        nxt = jnp.where(finished, decoder.end_token, nxt)
        finished = finished | (nxt == decoder.end_token)
        ids.append(nxt)
        tok = Tensor._from_data(nxt.astype(jnp.int64))
        if bool(finished.all()):
            break
    seq = jnp.stack(ids, axis=0).reshape(len(ids), B, beam)
    return Tensor._from_data(jnp.transpose(seq, (1, 0, 2))), state


def jax_log_softmax(logits):
    import jax
    import jax.numpy as jnp

    from ...core.tensor import Tensor

    arr = logits._data if isinstance(logits, Tensor) else jnp.asarray(logits)
    return jax.nn.log_softmax(arr, axis=-1)

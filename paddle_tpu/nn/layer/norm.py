"""Normalization layers (reference: python/paddle/nn/layer/norm.py).

BatchNorm keeps running stats as non-trainable buffers and updates them in
the forward pass under no_grad (the reference does it inside the CUDA kernel;
here it is two fused XLA ops)."""
from __future__ import annotations

import numpy as np

from ... import _C_ops
from ...core.tensor import Tensor
from ...ops.dispatch import no_grad
from .. import functional as F
from .. import initializer as I
from ..param_attr import ParamAttr
from .layers import Layer


class _BatchNormBase(Layer):
    def __init__(
        self,
        num_features,
        momentum=0.9,
        epsilon=1e-5,
        weight_attr=None,
        bias_attr=None,
        data_format="NCHW",
        use_global_stats=None,
        name=None,
    ):
        super().__init__()
        self._num_features = num_features
        self._momentum = momentum
        self._epsilon = epsilon
        self._data_format = data_format
        self._use_global_stats = use_global_stats
        if weight_attr is not False:
            self.weight = self.create_parameter(
                [num_features], ParamAttr._to_attr(weight_attr), self._dtype,
                default_initializer=I.Constant(1.0),
            )
        else:
            self.weight = None
        if bias_attr is not False:
            self.bias = self.create_parameter(
                [num_features], ParamAttr._to_attr(bias_attr), self._dtype, is_bias=True
            )
        else:
            self.bias = None
        self.register_buffer("_mean", Tensor(np.zeros([num_features], np.float32)))
        self.register_buffer("_variance", Tensor(np.ones([num_features], np.float32)))

    def forward(self, x):
        training = self.training and not self._use_global_stats
        if training:
            out, batch_mean, batch_var = _C_ops.batch_norm_train(
                x, self.weight, self.bias, self._epsilon, self._data_format
            )
            with no_grad():
                m = self._momentum
                # Tensor-op arithmetic (not raw ._data) so static recording
                # captures the update; buffer_assign registers the write as
                # a tape state output (MeanOut/VarianceOut semantics)
                from ...ops.dispatch import buffer_assign

                buffer_assign(self._mean,
                              self._mean * m + batch_mean * (1 - m))
                buffer_assign(self._variance,
                              self._variance * m + batch_var * (1 - m))
            return out
        return _C_ops.batch_norm_infer(
            x, self._mean, self._variance, self.weight, self.bias, self._epsilon, self._data_format
        )

    def extra_repr(self):
        return f"num_features={self._num_features}, momentum={self._momentum}, epsilon={self._epsilon}"


class BatchNorm(_BatchNormBase):
    pass


class BatchNorm1D(_BatchNormBase):
    pass


class BatchNorm2D(_BatchNormBase):
    pass


class BatchNorm3D(_BatchNormBase):
    pass


class SyncBatchNorm(_BatchNormBase):
    """Cross-replica BN. On TPU the batch stats allreduce happens via
    jax.lax.pmean inside shard_map/pjit programs; eager falls back to local
    stats (reference: python/paddle/nn/layer/norm.py SyncBatchNorm)."""

    @classmethod
    def convert_sync_batchnorm(cls, layer):
        for name, sub in list(layer._sub_layers.items()):
            if isinstance(sub, _BatchNormBase) and not isinstance(sub, SyncBatchNorm):
                new = SyncBatchNorm(
                    sub._num_features, sub._momentum, sub._epsilon, data_format=sub._data_format
                )
                if sub.weight is not None:
                    new.weight.set_value(sub.weight)
                if sub.bias is not None:
                    new.bias.set_value(sub.bias)
                new._mean.set_value(sub._mean)
                new._variance.set_value(sub._variance)
                layer._sub_layers[name] = new
            else:
                cls.convert_sync_batchnorm(sub)
        return layer


class LayerNorm(Layer):
    def __init__(self, normalized_shape, epsilon=1e-5, weight_attr=None, bias_attr=None, name=None):
        super().__init__()
        if isinstance(normalized_shape, int):
            normalized_shape = [normalized_shape]
        self._normalized_shape = list(normalized_shape)
        self._epsilon = epsilon
        if weight_attr is not False:
            self.weight = self.create_parameter(
                self._normalized_shape, ParamAttr._to_attr(weight_attr), self._dtype,
                default_initializer=I.Constant(1.0),
            )
        else:
            self.weight = None
        if bias_attr is not False:
            self.bias = self.create_parameter(
                self._normalized_shape, ParamAttr._to_attr(bias_attr), self._dtype, is_bias=True
            )
        else:
            self.bias = None

    def forward(self, x):
        begin = x.ndim - len(self._normalized_shape)
        return F.layer_norm(x, self.weight, self.bias, self._epsilon, begin)

    def extra_repr(self):
        return f"normalized_shape={self._normalized_shape}, epsilon={self._epsilon}"


class RMSNorm(Layer):
    """TPU-first RMSNorm (reference exposes it as incubate fused_rms_norm)."""

    def __init__(self, normalized_shape, epsilon=1e-6, weight_attr=None, name=None):
        super().__init__()
        if isinstance(normalized_shape, int):
            normalized_shape = [normalized_shape]
        self._epsilon = epsilon
        self.weight = self.create_parameter(
            list(normalized_shape), ParamAttr._to_attr(weight_attr), self._dtype,
            default_initializer=I.Constant(1.0),
        )

    def forward(self, x):
        return F.rms_norm(x, self.weight, None, self._epsilon)


class GroupNorm(Layer):
    def __init__(self, num_groups, num_channels, epsilon=1e-5, weight_attr=None, bias_attr=None,
                 data_format="NCHW", name=None):
        super().__init__()
        self._num_groups = num_groups
        self._epsilon = epsilon
        self._data_format = data_format
        if weight_attr is not False:
            self.weight = self.create_parameter(
                [num_channels], ParamAttr._to_attr(weight_attr), self._dtype,
                default_initializer=I.Constant(1.0),
            )
        else:
            self.weight = None
        if bias_attr is not False:
            self.bias = self.create_parameter(
                [num_channels], ParamAttr._to_attr(bias_attr), self._dtype, is_bias=True
            )
        else:
            self.bias = None

    def forward(self, x):
        return F.group_norm(x, self.weight, self.bias, self._epsilon, self._num_groups, self._data_format)


class InstanceNorm2D(Layer):
    def __init__(self, num_features, epsilon=1e-5, momentum=0.9, weight_attr=None, bias_attr=None,
                 data_format="NCHW", name=None):
        super().__init__()
        self._epsilon = epsilon
        if weight_attr is not False:
            self.scale = self.create_parameter(
                [num_features], ParamAttr._to_attr(weight_attr), self._dtype,
                default_initializer=I.Constant(1.0),
            )
        else:
            self.scale = None
        if bias_attr is not False:
            self.bias = self.create_parameter(
                [num_features], ParamAttr._to_attr(bias_attr), self._dtype, is_bias=True
            )
        else:
            self.bias = None

    def forward(self, x):
        return F.instance_norm(x, self.scale, self.bias, self._epsilon)


InstanceNorm1D = InstanceNorm2D
InstanceNorm3D = InstanceNorm2D


class LocalResponseNorm(Layer):
    def __init__(self, size, alpha=1e-4, beta=0.75, k=1.0, data_format="NCHW", name=None):
        super().__init__()
        self.size, self.alpha, self.beta, self.k = size, alpha, beta, k
        self.data_format = data_format

    def forward(self, x):
        return F.local_response_norm(x, self.size, self.alpha, self.beta, self.k, self.data_format)

"""Runtime flag registry.

TPU-native analog of the reference's exported gflags
(`paddle/common/flags.h:38` PD_DEFINE_* macros; 184 exported flags in
`paddle/common/flags.cc`). Flags are registered with a default, overridable
by a ``FLAGS_<name>`` environment variable at import time, and readable /
writable at runtime through ``get_flags`` / ``set_flags`` — the same user
surface the reference exposes via pybind
(`paddle/fluid/pybind/global_value_getter_setter.cc`).
"""
from __future__ import annotations

import difflib
import os
from typing import Any, Dict, Iterable, Optional

_REGISTRY: Dict[str, dict] = {}
# change watchers: fn(name, value) called after every set_flags update —
# lets hot paths cache flag values instead of dict-looking-up per call
# (the observability emit() fast path relies on this)
_WATCHERS: list = []


def on_change(fn):
    _WATCHERS.append(fn)
    return fn


def _coerce(value, proto):
    if isinstance(proto, bool):
        if isinstance(value, str):
            return value.lower() in ("1", "true", "yes", "on")
        return bool(value)
    if isinstance(proto, int) and not isinstance(proto, bool):
        return int(value)
    if isinstance(proto, float):
        return float(value)
    return value


def define_flag(name: str, default: Any, help: str = "", env: bool = True):
    """Register a flag. Env var FLAGS_<name> overrides the default."""
    value = default
    if env:
        ev = os.environ.get(f"FLAGS_{name}")
        if ev is not None:
            value = _coerce(ev, default)
    _REGISTRY[name] = {"default": default, "value": value, "help": help}
    return value


def _unknown_flag(key: str) -> ValueError:
    msg = f"Flag FLAGS_{key} is not registered"
    close = difflib.get_close_matches(key, list(_REGISTRY), n=3, cutoff=0.6)
    if close:
        msg += "; did you mean " + ", ".join(f"FLAGS_{c}" for c in close) + "?"
    return ValueError(msg)


def get_flags(flags) -> Dict[str, Any]:
    """paddle.get_flags parity."""
    if isinstance(flags, str):
        flags = [flags]
    out = {}
    for f in flags:
        key = f[6:] if f.startswith("FLAGS_") else f
        if key not in _REGISTRY:
            raise _unknown_flag(key)
        out[f"FLAGS_{key}"] = _REGISTRY[key]["value"]
    return out


def set_flags(flags: Dict[str, Any]):
    """paddle.set_flags parity."""
    for f, v in flags.items():
        key = f[6:] if f.startswith("FLAGS_") else f
        if key not in _REGISTRY:
            raise _unknown_flag(key)
        _REGISTRY[key]["value"] = _coerce(v, _REGISTRY[key]["default"])
        for fn in _WATCHERS:
            fn(key, _REGISTRY[key]["value"])


def flag_value(name: str):
    return _REGISTRY[name]["value"]


def all_flags() -> Iterable[str]:
    return _REGISTRY.keys()


# Core flags (analogs of the reference's most-used exported flags) -----------
define_flag("check_nan_inf", False, "Scan op outputs for NaN/Inf after each eager op")
define_flag("benchmark", False, "Synchronize after each op for timing")
define_flag("use_bf16_default", True, "Prefer bf16 in AMP autocast on TPU")
define_flag("eager_delete_tensor_gb", 0.0, "Kept for API parity; PJRT owns memory")
define_flag("tpu_allow_cpu_fallback", True, "Allow 'tpu' place to map to CPU XLA when no TPU")
define_flag("jit_cache_size", 4096, "Max cached compiled executables per op signature")
define_flag("log_level", 0, "VLOG-style verbosity tier")
define_flag("eager_async_depth", 2,
            "Max training steps in flight before dispatch backpressures; "
            "0 = fully synchronous eager execution (debugging)")
define_flag("eager_dispatch_cache", True,
            "Signature-keyed cache of jitted forward+vjp executables on the "
            "eager dispatch hot path (KernelFactory-cache analog)")
define_flag("fused_optimizer", True,
            "Fuse Optimizer.step's per-parameter update loop into one "
            "buffer-donated cached executable per parameter-group signature")

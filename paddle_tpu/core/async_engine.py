"""Pipelined eager execution: the in-flight step queue and its sync points.

The eager hot loop used to pay a host<->chip round trip every step: dispatch
step N, block on `float(loss)`, dispatch step N+1. JAX dispatch is already
asynchronous underneath (PJRT enqueues and returns), so the framework's job
is (a) to NOT force a premature sync, (b) to bound how far the host may run
ahead of the chip, and (c) to make the points where values DO materialize
explicit and observable.

Reference analog: the dygraph async executor / GC queue depth
(FLAGS_max_inplace_grad_add-style pacing) + DeviceContext::Wait. Here:

- ``mark_step(arrays, tag)`` is called at step boundaries (Optimizer.step);
  it enqueues the step's output buffers. When more than
  ``FLAGS_eager_async_depth`` steps are in flight the OLDEST is waited on
  (backpressure), so host run-ahead — and therefore live HBM for activation
  buffers — stays bounded.
- ``scalar_fetch(arr, tag)`` is the D2H sync point behind
  ``Tensor.numpy()/.item()/__float__``: it blocks only on the requested
  array (values are immutable, so that is fully coherent), retires any
  already-finished steps from the queue, and shows up in the profiler as a
  ``fetch::<tag>`` span so sync stalls are attributable.
- ``FLAGS_eager_async_depth = 0`` disables pipelining: every step mark
  blocks immediately (the old synchronous behavior, for debugging).
- The static-graph recorder (``program_guard``) forces sync mode: a tape
  being recorded must observe program order.
- ``synchronize()`` drains everything (paddle.device.synchronize analog).
"""
from __future__ import annotations

import threading
from collections import deque
from typing import Any, Iterable, Optional

from . import flags

_lock = threading.Lock()
_queue: deque = deque()  # (tag, [arrays]) step groups in dispatch order

_stats = {
    "steps_marked": 0,
    "backpressure_waits": 0,
    "sync_fetches": 0,
    "drains": 0,
    "max_depth_seen": 0,
}


def depth() -> int:
    """Effective pipeline depth. 0 = synchronous (flag, or a static-graph
    recording in progress — a tape must observe program order)."""
    from ..ops import dispatch

    if dispatch.get_static_recorder() is not None:
        return 0
    return max(0, int(flags.flag_value("eager_async_depth")))


def in_flight() -> int:
    return len(_queue)


def stats() -> dict:
    out = dict(_stats)
    out["in_flight"] = len(_queue)
    out["depth"] = depth()
    return out


def reset_stats():
    for k in _stats:
        _stats[k] = 0


def _block_on(arrays: Iterable[Any]):
    for a in arrays:
        try:
            if a.is_deleted():
                # donated away (fused optimizer in-place update): the buffer
                # was consumed by a YOUNGER computation, so it is past ready
                continue
            a.block_until_ready()
        except Exception:  # noqa: BLE001 — deleted between check and wait,
            pass           # or a non-array leaked in: never fail a sync


def _is_ready(a) -> bool:
    try:
        return a.is_deleted() or bool(a.is_ready())
    except Exception:  # noqa: BLE001
        return True


def mark_step(arrays: Iterable[Any], tag: str = "step"):
    """Note a completed step dispatch. Blocks on the oldest in-flight step
    once more than ``depth()`` are outstanding (or immediately at depth 0)."""
    arrays = [a for a in arrays if hasattr(a, "block_until_ready")]
    d = depth()
    if d == 0:
        _block_on(arrays)
        _stats["steps_marked"] += 1
        return
    with _lock:
        _queue.append((tag, arrays))
        _stats["steps_marked"] += 1
        overflow = []
        while len(_queue) > d:
            overflow.append(_queue.popleft())
        _stats["max_depth_seen"] = max(_stats["max_depth_seen"], len(_queue))
    for tag_o, arrs in overflow:
        _stats["backpressure_waits"] += 1
        _with_span(f"wait::{tag_o}", _block_on, arrs)


def _retire_ready():
    """Pop already-finished steps off the head of the queue (non-blocking)."""
    with _lock:
        while _queue and all(_is_ready(a) for a in _queue[0][1]):
            _queue.popleft()


def _with_span(name: str, fn, *args):
    from ..ops.dispatch import _op_profiling

    if _op_profiling[0]:
        from ..profiler import RecordEvent

        with RecordEvent(name):
            return fn(*args)
    return fn(*args)


def scalar_fetch(arr, tag: str = "tensor"):
    """The D2H sync point: block until ``arr`` is computed, under a
    ``fetch::<tag>`` profiler span. Only the requested value is waited on —
    younger in-flight steps keep running; already-finished steps retire."""
    if not hasattr(arr, "block_until_ready") or hasattr(arr, "_trace"):
        return arr  # tracer or non-array: preserve the eager error path
    _stats["sync_fetches"] += 1
    _with_span(f"fetch::{tag}", _block_on, (arr,))
    if _queue:
        _retire_ready()
    return arr


def drain():
    """Block until every in-flight step completes and clear the queue."""
    with _lock:
        groups = list(_queue)
        _queue.clear()
    _stats["drains"] += 1
    for _tag, arrs in groups:
        _block_on(arrs)


def synchronize():
    """paddle.synchronize: drain the pipeline, then fence the device."""
    import jax

    drain()
    (jax.device_put(0) + 0).block_until_ready()

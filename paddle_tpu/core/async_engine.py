"""Pipelined eager execution: the in-flight step queue and its sync points.

The eager hot loop used to pay a host<->chip round trip every step: dispatch
step N, block on `float(loss)`, dispatch step N+1. JAX dispatch is already
asynchronous underneath (PJRT enqueues and returns), so the framework's job
is (a) to NOT force a premature sync, (b) to bound how far the host may run
ahead of the chip, and (c) to make the points where values DO materialize
explicit and observable.

Reference analog: the dygraph async executor / GC queue depth
(FLAGS_max_inplace_grad_add-style pacing) + DeviceContext::Wait. Here:

- ``mark_step(arrays, tag)`` is called at step boundaries (Optimizer.step);
  it enqueues the step's output buffers. When more than
  ``FLAGS_eager_async_depth`` steps are in flight the OLDEST is waited on
  (backpressure), so host run-ahead — and therefore live HBM for activation
  buffers — stays bounded.
- ``scalar_fetch(arr, tag)`` is the D2H sync point behind
  ``Tensor.numpy()/.item()/__float__``: it blocks only on the requested
  array (values are immutable, so that is fully coherent), retires any
  already-finished steps from the queue, and shows up in the profiler as a
  ``fetch::<tag>`` span so sync stalls are attributable.
- ``FLAGS_eager_async_depth = 0`` disables pipelining: every step mark
  blocks immediately (the old synchronous behavior, for debugging).
- The static-graph recorder (``program_guard``) forces sync mode: a tape
  being recorded must observe program order.
- ``synchronize()`` drains everything (paddle.device.synchronize analog).
"""
from __future__ import annotations

import time
import threading
from collections import deque
from typing import Any, Iterable, Optional

from . import flags

_lock = threading.Lock()
_queue: deque = deque()  # (tag, [arrays]) step groups in dispatch order

# counters live in the unified metrics registry (observability.emit is the
# only writer); stats() is a view translating to the legacy key names
_STATS_METRICS = {
    "steps_marked": "paddle_eager_steps_marked_total",
    "backpressure_waits": "paddle_eager_backpressure_waits_total",
    "sync_fetches": "paddle_eager_sync_fetches_total",
    "drains": "paddle_eager_drains_total",
    "max_depth_seen": "paddle_eager_inflight_depth_max",
}


from ..observability import emit as _emit  # noqa: E402

# chaos choke point: installed by distributed/fault_tolerance/chaos.py only
# while FLAGS_chaos_spec is active — (tag) -> None, may stall a fetch
_chaos_hook = [None]


def set_chaos_hook(fn):
    _chaos_hook[0] = fn


def depth() -> int:
    """Effective pipeline depth. 0 = synchronous (flag, or a static-graph
    recording in progress — a tape must observe program order)."""
    from ..ops import dispatch

    if dispatch.get_static_recorder() is not None:
        return 0
    return max(0, int(flags.flag_value("eager_async_depth")))


def in_flight() -> int:
    return len(_queue)


def stats() -> dict:
    """Pipeline counters: a view over the metrics registry."""
    from ..observability import registry

    reg = registry()
    out = {k: int(reg.value(name)) for k, name in _STATS_METRICS.items()}
    out["in_flight"] = len(_queue)
    out["depth"] = depth()
    return out


def reset_stats():
    from ..observability import registry

    reg = registry()
    for name in _STATS_METRICS.values():
        m = reg.get(name)
        if m is not None:
            m.reset()
    # the stall histogram feeds p50/p99 in summaries; reset alongside
    h = reg.get("paddle_fetch_stall_seconds")
    if h is not None:
        h.reset()


def _block_on(arrays: Iterable[Any]):
    for a in arrays:
        try:
            if a.is_deleted():
                # donated away (fused optimizer in-place update): the buffer
                # was consumed by a YOUNGER computation, so it is past ready
                continue
            a.block_until_ready()
        except Exception:  # noqa: BLE001 — deleted between check and wait,
            pass           # or a non-array leaked in: never fail a sync


def _is_ready(a) -> bool:
    try:
        return a.is_deleted() or bool(a.is_ready())
    except Exception:  # noqa: BLE001
        return True


def mark_step(arrays: Iterable[Any], tag: str = "step"):
    """Note a completed step dispatch. Blocks on the oldest in-flight step
    once more than ``depth()`` are outstanding (or immediately at depth 0)."""
    arrays = [a for a in arrays if hasattr(a, "block_until_ready")]
    d = depth()
    if d == 0:
        t0 = time.perf_counter()
        _block_on(arrays)
        _emit("async.enqueue", tag=tag, depth=0)
        _emit("async.sync_wait", dur_s=time.perf_counter() - t0,
              tag=tag, n_buffers=len(arrays))
        return
    with _lock:
        _queue.append((tag, arrays))
        overflow = []
        while len(_queue) > d:
            overflow.append(_queue.popleft())
        n = len(_queue)
    _emit("async.enqueue", tag=tag, depth=n)
    for tag_o, arrs in overflow:
        t0 = time.perf_counter()
        _with_span(f"wait::{tag_o}", _block_on, arrs)
        _emit("async.backpressure", dur_s=time.perf_counter() - t0,
              tag=tag_o, n_buffers=len(arrs))


def _retire_ready():
    """Pop already-finished steps off the head of the queue (non-blocking)."""
    retired = 0
    with _lock:
        while _queue and all(_is_ready(a) for a in _queue[0][1]):
            _queue.popleft()
            retired += 1
        n = len(_queue)
    if retired:
        _emit("async.depth", depth=n)


def _with_span(name: str, fn, *args):
    from ..ops.dispatch import _op_profiling

    if _op_profiling[0]:
        from ..profiler import RecordEvent

        with RecordEvent(name):
            return fn(*args)
    return fn(*args)


def scalar_fetch(arr, tag: str = "tensor"):
    """The D2H sync point: block until ``arr`` is computed, under a
    ``fetch::<tag>`` profiler span. Only the requested value is waited on —
    younger in-flight steps keep running; already-finished steps retire.

    Every fetch lands in the ``paddle_fetch_stall_seconds`` histogram and
    the flight recorder with the blocked buffer's identity (tag = the op
    that produced it, plus shape/dtype), so a slow eager loop can be
    attributed to the exact value that forced the host to wait."""
    if not hasattr(arr, "block_until_ready") or hasattr(arr, "_trace"):
        return arr  # tracer or non-array: preserve the eager error path
    ch = _chaos_hook[0]
    if ch is not None:
        ch(tag)
    was_ready = _is_ready(arr)
    t0 = time.perf_counter()
    _with_span(f"fetch::{tag}", _block_on, (arr,))
    _emit("async.fetch_stall", dur_s=time.perf_counter() - t0, tag=tag,
          shape=tuple(getattr(arr, "shape", ())),
          dtype=str(getattr(arr, "dtype", "")),
          was_ready=was_ready, in_flight=len(_queue))
    if _queue:
        _retire_ready()
    return arr


def p2p_transfer(arr, put, tag: str = "p2p", trace=None):
    """Issue an async device-to-device copy (pipeline stage handoff).

    ``put`` maps the source buffer onto the destination placement —
    ``jax.device_put`` under PJRT enqueues the copy and returns
    immediately, so the caller's next dispatch (stage k's forward of
    microbatch i+1) overlaps this transfer of microbatch i. The consumer
    only blocks when it dereferences the returned in-flight buffer. Every
    handoff lands in ``paddle_eager_p2p_transfers_total`` with its issue
    latency, so transfer pressure is attributable per tag.

    ``trace``: optional ``(trace_id, parent_span_id)`` context from the
    caller (the pipeline runtime's batch span): the issue interval is
    additionally recorded as a ``pp.p2p`` span, so per-hop latency shows
    up inside the merged chrome trace next to the stage spans."""
    t0 = time.perf_counter()
    out = put(arr)
    dur = time.perf_counter() - t0
    _emit("async.p2p", dur_s=dur, tag=tag,
          nbytes=int(getattr(arr, "nbytes", 0) or 0),
          in_flight=len(_queue))
    if trace is not None:
        from ..observability import tracing as _tr
        _tr.record_span("pp.p2p", trace[0], trace[1], int(t0 * 1e9), dur,
                        tag=tag)
    return out


def wait_for(arrays: Iterable[Any], tag: str = "wait"):
    """Block until the given buffers are computed, under a ``fetch::<tag>``
    span with an ``async.fetch_stall``-style record — the attribution point
    the DataParallel reducer drains its outstanding bucket collectives
    through at step boundaries. Returns the exposed wait seconds."""
    arrays = [a for a in arrays if hasattr(a, "block_until_ready")]
    t0 = time.perf_counter()
    _with_span(f"fetch::{tag}", _block_on, arrays)
    dur = time.perf_counter() - t0
    _emit("async.fetch_stall", dur_s=dur, tag=tag, shape=(), dtype="",
          was_ready=dur < 1e-5, in_flight=len(_queue))
    if _queue:
        _retire_ready()
    return dur


def abort_in_flight(reason: str = "") -> int:
    """Drop every queued step WITHOUT waiting on its buffers.

    The elastic runtime calls this when the world is reconfigured: steps
    dispatched in the old epoch may reference collectives that will never
    complete (their mesh includes a dead rank), so waiting — what
    ``drain()`` does — could block forever. The buffers are simply
    forgotten; PJRT retires or poisons them on its own. Returns how many
    in-flight steps were discarded."""
    with _lock:
        n = len(_queue)
        _queue.clear()
    _emit("async.abort", n_steps=n, reason=reason)
    _emit("async.depth", depth=0)
    return n


def drain():
    """Block until every in-flight step completes and clear the queue."""
    with _lock:
        groups = list(_queue)
        _queue.clear()
    t0 = time.perf_counter()
    for _tag, arrs in groups:
        _block_on(arrs)
    _emit("async.drain", dur_s=time.perf_counter() - t0, n_steps=len(groups))
    _emit("async.depth", depth=0)


def synchronize():
    """paddle.synchronize: drain the pipeline, then fence the device."""
    import jax

    drain()
    (jax.device_put(0) + 0).block_until_ready()

"""Device placement.

Analog of the reference's Place hierarchy (`paddle/common/place.h` — CPUPlace /
GPUPlace / XPUPlace / CustomPlace) re-targeted at TPU: the framework's places
are ``tpu`` (a PJRT TPU device) and ``cpu`` (XLA-CPU), with ``tpu``
transparently falling back to XLA-CPU when no TPU is attached (the fake-device
testing strategy the reference implements with `custom_cpu` plugins — see
SURVEY.md §4 "Fake-backend strategy").
"""
from __future__ import annotations

import threading

from . import flags


class Place:
    __slots__ = ("device_type", "device_id")

    def __init__(self, device_type: str, device_id: int = 0):
        if ":" in device_type:
            device_type, _, idx = device_type.partition(":")
            device_id = int(idx)
        device_type = device_type.lower()
        if device_type == "gpu":  # compat: treat gpu requests as the accelerator
            device_type = "tpu"
        if device_type not in ("cpu", "tpu"):
            raise ValueError(f"Unsupported device type: {device_type!r} (use 'cpu' or 'tpu')")
        self.device_type = device_type
        self.device_id = device_id

    def is_cpu_place(self):
        return self.device_type == "cpu"

    def is_tpu_place(self):
        return self.device_type == "tpu"

    # reference-API compat: code written for GPU targets the accelerator
    # here (Place("gpu", i) normalizes to tpu), so a "gpu place" question
    # means "is this the accelerator" — must answer True or ported code
    # silently takes its CPU fallback branch.
    def is_gpu_place(self):
        return self.device_type == "tpu"

    def __repr__(self):
        return f"Place({self.device_type}:{self.device_id})"

    def __eq__(self, other):
        if isinstance(other, str):
            try:
                other = Place(other)
            except ValueError:
                return False
        return (
            isinstance(other, Place)
            and self.device_type == other.device_type
            and self.device_id == other.device_id
        )

    def __hash__(self):
        return hash((self.device_type, self.device_id))


def CPUPlace():
    return Place("cpu", 0)


def TPUPlace(device_id: int = 0):
    return Place("tpu", device_id)


def CUDAPlace(device_id: int = 0):
    """Reference-compat: code written for GPU runs on the accelerator
    (Place("gpu", i) already normalizes to the tpu device)."""
    return Place("gpu", device_id)


def CUDAPinnedPlace():
    """Reference-compat: pinned host staging memory maps to plain host
    memory (PJRT handles the staging buffers)."""
    return Place("cpu", 0)


_state = threading.local()


def _default_place() -> Place:
    import jax

    backend = jax.default_backend()
    if backend == "cpu":
        return Place("cpu", 0)
    return Place("tpu", 0)


def set_device(device) -> Place:
    """paddle.set_device parity (reference: python/paddle/device/__init__.py)."""
    p = device if isinstance(device, Place) else Place(device)
    _state.place = p
    return p


def get_device() -> str:
    p = current_place()
    return f"{p.device_type}:{p.device_id}"


def current_place() -> Place:
    p = getattr(_state, "place", None)
    if p is None:
        p = _default_place()
        _state.place = p
    return p


def jax_device(place: Place | None = None):
    """Resolve a Place to a concrete jax.Device (with CPU fallback for 'tpu')."""
    import jax

    place = place or current_place()
    if place.device_type == "cpu":
        return jax.local_devices(backend="cpu")[0]
    devs = [d for d in jax.devices() if d.platform != "cpu"]
    if not devs:
        if flags.flag_value("tpu_allow_cpu_fallback"):
            return jax.local_devices(backend="cpu")[0]
        raise RuntimeError("No TPU device available and cpu fallback disabled")
    return devs[min(place.device_id, len(devs) - 1)]


def is_compiled_with_cuda() -> bool:
    return False


def is_compiled_with_xpu() -> bool:
    return False


def is_compiled_with_tpu() -> bool:
    return True

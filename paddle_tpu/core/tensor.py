"""The eager Tensor.

TPU-native analog of the reference's eager Tensor (`paddle/phi/core/dense_tensor.h:37`
DenseTensor + `paddle/fluid/eager/autograd_meta.h` AutogradMeta + the pybind
eager Tensor type `paddle/fluid/pybind/eager.cc`): a `jax.Array` living in
PJRT-managed HBM plus autograd metadata (stop_gradient, grad, producer
GradNode). Allocation, streams and memcpy from the reference's
AllocatorFacade collapse into PJRT buffer management; `numpy()`/`item()` are
the D2H path.

Most of the `paddle.Tensor` method surface (reference: python/paddle/tensor/*)
is patched on by :mod:`paddle_tpu.tensor` at import time via
:func:`register_tensor_method`.
"""
from __future__ import annotations

import itertools
from typing import Any, List, Optional

import numpy as np

from . import dtype as dtype_mod
from .dtype import DType
from .place import Place, current_place, jax_device

_name_counter = itertools.count()
_ops_cache = {}

# SOT capture hook (jit/sot): while a capture/traced pass is active, every
# tensor→python-scalar conversion routes here so concretizations in NESTED
# calls are recorded/guarded exactly like top-frame ones. None = inactive.
_scalar_capture_hook = None


def set_scalar_capture_hook(hook):
    """Install (or clear with None) the scalar-conversion capture hook.
    Returns the previous hook so callers can nest/restore."""
    global _scalar_capture_hook
    prev = _scalar_capture_hook
    _scalar_capture_hook = hook
    return prev


def _ops():
    """Late import of the op namespace to break the core<->ops cycle."""
    mod = _ops_cache.get("ops")
    if mod is None:
        from .. import _C_ops as mod

        _ops_cache["ops"] = mod
    return mod


class Tensor:
    __slots__ = (
        "_data",
        "stop_gradient",
        "_grad",
        "_grad_node",
        "_out_index",
        "name",
        "persistable",
        "_backward_hooks",
        "_grad_final_hooks",
        "is_parameter",
        "trainable",
        "_dist_mesh",
        "_dist_partials",
        # static-graph mode (paddle_tpu/static): placeholder marker, tape
        # variable id, owning Program, layer keep-alives for static.nn
        "_is_placeholder",
        "_var_id",
        "_program",
        "_is_buffer",
        "_fc_layer",
        "_emb_layer",
        "__weakref__",
    )

    def __init__(self, data=None, dtype=None, place=None, stop_gradient=True, name=None):
        import jax.numpy as jnp

        if data is None:
            data = jnp.zeros([], dtype_mod.to_np(dtype or dtype_mod.get_default_dtype()))
        self._data = _coerce_array(data, dtype, place)
        self.stop_gradient = stop_gradient
        self._grad = None
        self._grad_node = None
        self._out_index = 0
        self.name = name or f"generated_tensor_{next(_name_counter)}"
        self.persistable = False
        self.is_parameter = False
        self.trainable = True
        self._dist_mesh = None
        self._dist_partials = ()
        self._backward_hooks: List = []
        self._grad_final_hooks: List = []

    # -- construction --------------------------------------------------------
    @classmethod
    def _from_data(cls, data, stop_gradient=True, name=None):
        t = object.__new__(cls)
        t._data = data
        t.stop_gradient = stop_gradient
        t._grad = None
        t._grad_node = None
        t._out_index = 0
        t.name = name or f"generated_tensor_{next(_name_counter)}"
        t.persistable = False
        t.is_parameter = False
        t.trainable = True
        t._dist_mesh = None
        t._dist_partials = ()
        t._backward_hooks = []
        t._grad_final_hooks = []
        return t

    # -- metadata ------------------------------------------------------------
    @property
    def shape(self) -> List[int]:
        return list(self._data.shape)

    @property
    def ndim(self) -> int:
        return self._data.ndim

    ndimension = ndim

    # -- DistTensor surface (reference: dist_tensor.h:39, dist_attr.h:81) ----
    def is_dist(self) -> bool:
        return self._dist_mesh is not None

    @property
    def process_mesh(self):
        return self._dist_mesh

    @property
    def placements(self):
        if self._dist_mesh is None:
            return None
        from ..distributed.auto_parallel.placement import spec_to_placements

        sh = getattr(self._data, "sharding", None)
        spec = getattr(sh, "spec", None)
        if spec is None:
            from ..distributed.auto_parallel.placement import Replicate

            return [Replicate() for _ in self._dist_mesh.dim_names]
        return spec_to_placements(spec, self._dist_mesh.dim_names,
                                  self._dist_partials)

    @property
    def size(self) -> int:
        return int(np.prod(self._data.shape)) if self._data.shape else 1

    @property
    def dtype(self) -> DType:
        return dtype_mod.from_jax(self._data.dtype)

    @property
    def place(self) -> Place:
        dev = getattr(self._data, "devices", None)
        if dev:
            d = next(iter(self._data.devices()))
            kind = "cpu" if d.platform == "cpu" else "tpu"
            return Place(kind, d.id)
        return current_place()  # tracer: report the ambient place

    @property
    def is_leaf(self) -> bool:
        return self._grad_node is None

    @property
    def grad(self) -> Optional["Tensor"]:
        if self._grad is None:
            return None
        g = Tensor._from_data(self._grad, stop_gradient=True, name=self.name + "@GRAD")
        return g

    @grad.setter
    def grad(self, value):
        self._grad = None if value is None else (value._data if isinstance(value, Tensor) else value)

    def _wrap_grad(self, g):
        return Tensor._from_data(g, stop_gradient=True)

    # -- autograd ------------------------------------------------------------
    def backward(self, grad_tensor=None, retain_graph=False):
        from ..autograd import engine

        engine.run_backward([self], [grad_tensor], retain_graph=retain_graph)

    def clear_grad(self):
        self._grad = None

    clear_gradient = clear_grad

    def register_hook(self, hook):
        """Reference: eager hooks (paddle/fluid/eager/hooks.h)."""
        if self._grad_node is not None:
            self._grad_node.out_hooks.setdefault(self._out_index, []).append(hook)
            node, idx = self._grad_node, self._out_index

            class _Handle:
                def remove(self_h):
                    try:
                        node.out_hooks[idx].remove(hook)
                    except (KeyError, ValueError):
                        pass

            return _Handle()
        self._backward_hooks.append(hook)
        hooks = self._backward_hooks

        class _Handle:
            def remove(self_h):
                try:
                    hooks.remove(hook)
                except ValueError:
                    pass

        return _Handle()

    def register_grad_final_hook(self, hook):
        """Fires ``hook(self)`` inside ``run_backward`` once THIS leaf's grad
        has received its last contribution of the pass — the primitive the
        DataParallel reducer builds bucket-ready notifications on (reference:
        the EagerReducer's GradNodeAccumulation reduce hooks)."""
        self._grad_final_hooks.append(hook)
        hooks = self._grad_final_hooks

        class _Handle:
            def remove(self_h):
                try:
                    hooks.remove(hook)
                except ValueError:
                    pass

        return _Handle()

    def detach(self) -> "Tensor":
        t = Tensor._from_data(self._data, stop_gradient=True, name=self.name)
        t._dist_mesh = self._dist_mesh
        t._dist_partials = self._dist_partials
        return t

    def detach_(self):
        self._grad_node = None
        self.stop_gradient = True
        return self

    # -- host transfer -------------------------------------------------------
    def numpy(self) -> np.ndarray:
        # The pipeline's D2H sync point: blocks only on THIS buffer (values
        # are immutable, so that is coherent) and retires finished in-flight
        # steps; shows up as a fetch::<op> profiler span. item()/tolist()/
        # __float__/__bool__/__format__ all funnel through here.
        from . import async_engine

        node = self._grad_node
        async_engine.scalar_fetch(
            self._data, node.name if node is not None else "tensor")
        return np.asarray(self._data)

    def item(self, *args):
        if args:
            return self.numpy().item(*args)
        return self.numpy().item()

    def tolist(self):
        return self.numpy().tolist()

    def __array__(self, dtype=None):
        a = self.numpy()
        return a.astype(dtype) if dtype is not None else a

    # -- device transfer -----------------------------------------------------
    def to(self, *args, **kwargs):
        import jax

        device = None
        dtype = None
        for a in args:
            if isinstance(a, (Place, str)) and not _is_dtype_like(a):
                device = a
            else:
                dtype = a
        device = kwargs.get("device", device)
        dtype = kwargs.get("dtype", dtype)
        data = self._data
        if dtype is not None:
            data = data.astype(dtype_mod.to_np(dtype))
        if device is not None:
            p = device if isinstance(device, Place) else Place(device)
            data = jax.device_put(data, jax_device(p))
        out = Tensor._from_data(data, stop_gradient=self.stop_gradient, name=self.name)
        if device is None:
            out._dist_mesh = self._dist_mesh
            out._dist_partials = self._dist_partials
        return out

    def cpu(self):
        return self.to(Place("cpu"))

    def tpu(self, device_id=0):
        return self.to(Place("tpu", device_id))

    cuda = tpu  # compat: accelerator transfer

    def pin_memory(self):
        return self.cpu()

    # -- in-place data rebind (functional under the hood) --------------------
    def _rebind(self, other: "Tensor"):
        """Adopt another tensor's value+grad-node (functional in-place)."""
        self._data = other._data
        self._grad_node = other._grad_node
        self._out_index = other._out_index
        if other._grad_node is not None:
            self.stop_gradient = False
        return self

    def set_value(self, value):
        arr = value._data if isinstance(value, Tensor) else _coerce_array(value, self.dtype, None)
        if tuple(arr.shape) != tuple(self._data.shape):
            raise ValueError(
                f"set_value shape mismatch: {list(arr.shape)} vs {self.shape}"
            )
        self._data = arr.astype(self._data.dtype)
        return self

    def copy_(self, other, blocking=True):
        return self.set_value(other)

    # -- misc ----------------------------------------------------------------
    def clone(self):
        return _ops().assign(self)

    def astype(self, dtype):
        return _ops().cast(self, dtype)

    def cast(self, dtype):
        return _ops().cast(self, dtype)

    def numel(self):
        return self.size

    def element_size(self):
        return self.dtype.itemsize

    def dim(self):
        return self.ndim

    def rank(self):
        return self.ndim

    @property
    def T(self):
        return _ops().transpose(self, list(range(self.ndim))[::-1])

    def __len__(self):
        if self.ndim == 0:
            raise TypeError("len() of a 0-d tensor")
        return self._data.shape[0]

    def __iter__(self):
        for i in range(len(self)):
            yield self[i]

    def __bool__(self):
        if self.size != 1:
            raise ValueError(
                "The truth value of a Tensor with more than one element is ambiguous"
            )
        if _scalar_capture_hook is not None:
            return _scalar_capture_hook(self, bool)
        return bool(self.numpy())

    def __int__(self):
        if _scalar_capture_hook is not None:
            return _scalar_capture_hook(self, int)
        return int(self.item())

    def __float__(self):
        if _scalar_capture_hook is not None:
            return _scalar_capture_hook(self, float)
        return float(self.item())

    def __index__(self):
        if _scalar_capture_hook is not None:
            return _scalar_capture_hook(self, int)
        return int(self.item())

    def __format__(self, spec):
        if self.size == 1:
            return format(self.numpy().item(), spec)
        return format(str(self), spec)

    def __repr__(self):
        try:
            vals = np.array2string(
                np.asarray(self._data), precision=8, separator=", ", threshold=100
            )
        except Exception:
            vals = f"<{type(self._data).__name__}>"
        return (
            f"Tensor(shape={self.shape}, dtype={self.dtype.name}, "
            f"place={self.place}, stop_gradient={self.stop_gradient},\n"
            f"       {vals})"
        )

    __hash__ = object.__hash__

    # -- indexing ------------------------------------------------------------
    def __getitem__(self, idx):
        return _ops().getitem(self, idx)

    def __setitem__(self, idx, value):
        self._rebind(_ops().setitem(self, value, idx))

    # -- arithmetic dunders (delegate to the op library) ---------------------
    def __add__(self, o):
        return _ops().add(self, o)

    def __radd__(self, o):
        return _ops().add(o, self)

    def __sub__(self, o):
        return _ops().subtract(self, o)

    def __rsub__(self, o):
        return _ops().subtract(o, self)

    def __mul__(self, o):
        return _ops().multiply(self, o)

    def __rmul__(self, o):
        return _ops().multiply(o, self)

    def __truediv__(self, o):
        return _ops().divide(self, o)

    def __rtruediv__(self, o):
        return _ops().divide(o, self)

    def __floordiv__(self, o):
        return _ops().floor_divide(self, o)

    def __rfloordiv__(self, o):
        return _ops().floor_divide(o, self)

    def __mod__(self, o):
        return _ops().remainder(self, o)

    def __rmod__(self, o):
        return _ops().remainder(o, self)

    def __pow__(self, o):
        return _ops().pow(self, o)

    def __rpow__(self, o):
        return _ops().elementwise_rpow(self, o)

    def __neg__(self):
        return _ops().scale(self, -1.0)

    def __abs__(self):
        return _ops().abs(self)

    def __matmul__(self, o):
        return _ops().matmul(self, o)

    def __rmatmul__(self, o):
        return _ops().matmul(o, self)

    def __eq__(self, o):
        return _ops().equal(self, o)

    def __ne__(self, o):
        return _ops().not_equal(self, o)

    def __lt__(self, o):
        return _ops().less_than(self, o)

    def __le__(self, o):
        return _ops().less_equal(self, o)

    def __gt__(self, o):
        return _ops().greater_than(self, o)

    def __ge__(self, o):
        return _ops().greater_equal(self, o)

    def __invert__(self):
        return _ops().logical_not(self)

    def __and__(self, o):
        return _ops().logical_and(self, o) if self.dtype == "bool" else _ops().bitwise_and(self, o)

    def __or__(self, o):
        return _ops().logical_or(self, o) if self.dtype == "bool" else _ops().bitwise_or(self, o)

    def __xor__(self, o):
        return _ops().logical_xor(self, o) if self.dtype == "bool" else _ops().bitwise_xor(self, o)


class Parameter(Tensor):
    """A trainable Tensor (reference: EagerParamBase, python/paddle/base/framework.py)."""

    __slots__ = ("optimize_attr", "regularizer", "need_clip",
                 "is_distributed", "sequence_parallel", "split_axis")

    def __init__(self, data, name=None, trainable=True):
        super().__init__(data, name=name, stop_gradient=not trainable)
        self.is_parameter = True
        self.persistable = True
        self.trainable = trainable
        self.optimize_attr = {"learning_rate": 1.0}
        self.regularizer = None
        self.need_clip = True
        self.is_distributed = False
        self.sequence_parallel = False
        self.split_axis = None

    @classmethod
    def from_tensor(cls, t: Tensor, name=None, trainable=True):
        p = cls.__new__(cls)
        p._data = t._data if isinstance(t, Tensor) else t
        p.stop_gradient = not trainable
        p._grad = None
        p._grad_node = None
        p._out_index = 0
        p.name = name or f"param_{next(_name_counter)}"
        p.persistable = True
        p.is_parameter = True
        p.trainable = trainable
        p._dist_mesh = getattr(t, "_dist_mesh", None)
        p._dist_partials = getattr(t, "_dist_partials", ())
        p._backward_hooks = []
        p._grad_final_hooks = []
        p.optimize_attr = {"learning_rate": 1.0}
        p.regularizer = None
        p.need_clip = True
        p.is_distributed = False
        p.sequence_parallel = False
        p.split_axis = None
        return p


def _is_dtype_like(x) -> bool:
    if isinstance(x, DType):
        return True
    if isinstance(x, str):
        try:
            DType(x)
            return True
        except TypeError:
            return False
    return False


def _coerce_array(data, dtype=None, place=None):
    import jax
    import jax.numpy as jnp

    if isinstance(data, Tensor):
        data = data._data
    if isinstance(data, (jnp.ndarray, jax.Array)) or hasattr(data, "aval"):
        arr = data
        if dtype is not None:
            arr = arr.astype(dtype_mod.to_np(dtype))
        return arr
    np_arr = np.asarray(data)
    if dtype is not None:
        np_arr = np_arr.astype(dtype_mod.to_np(dtype))
    elif np_arr.dtype == np.float64:
        np_arr = np_arr.astype(dtype_mod.to_np(dtype_mod.get_default_dtype()))
    dev = jax_device(place if isinstance(place, Place) else (Place(place) if place else None))
    return jax.device_put(np_arr, dev)


def to_tensor(data, dtype=None, place=None, stop_gradient=True) -> Tensor:
    """``paddle.to_tensor`` parity."""
    return Tensor(data, dtype=dtype, place=place, stop_gradient=stop_gradient)


def register_tensor_method(name, fn=None):
    """Patch a method onto Tensor (reference pattern: python/paddle/tensor/__init__.py
    attaching the tensor method library onto the pybind type)."""
    if fn is None:

        def deco(f):
            setattr(Tensor, name, f)
            return f

        return deco
    setattr(Tensor, name, fn)
    return fn


# Register Tensor as a jax pytree so jitted functions can take/return Tensors.
import jax.tree_util as _jtu


def _tensor_flatten(t: Tensor):
    return (t._data,), (t.stop_gradient,)


def _tensor_unflatten(aux, children):
    t = Tensor._from_data(children[0])
    t.stop_gradient = aux[0]
    return t


_jtu.register_pytree_node(Tensor, _tensor_flatten, _tensor_unflatten)
_jtu.register_pytree_node(
    Parameter,
    _tensor_flatten,
    lambda aux, ch: Tensor._from_data(ch[0], stop_gradient=aux[0]),
)

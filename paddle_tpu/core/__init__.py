"""Core runtime: dtype/place/flags/enforce/rng/Tensor (SURVEY.md §2.1 analogs)."""

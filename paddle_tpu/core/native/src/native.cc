// paddle_tpu native runtime: TCPStore, shared-memory ring buffer, tracer.
//
// TPU-native re-implementation of the reference's native runtime services
// (not a translation):
//  - TCPStore: rendezvous KV store w/ blocking wait + counters (reference:
//    paddle/phi/core/distributed/store/tcp_store.h:121 — master socket
//    server + clients; used by launch/init_parallel_env bootstrap).
//  - ShmRing: POSIX shared-memory SPSC byte ring for DataLoader
//    worker→parent batch transfer (reference: the mmap'd shared memory of
//    python/paddle/io/dataloader_iter.py worker pool + data_feed.cc).
//  - Tracer: host RecordEvent span collector exported as chrome-trace
//    (reference: paddle/fluid/platform/profiler/ HostTracer +
//    chrometracing_logger.cc).
//
// Plain C ABI for ctypes binding (no pybind11 in this image).
//
// Build: g++ -O2 -fPIC -shared -pthread -lrt native.cc -o libpaddle_tpu_native.so

#include <arpa/inet.h>
#include <fcntl.h>
#include <netinet/in.h>
#include <netinet/tcp.h>
#include <pthread.h>
#include <sys/mman.h>
#include <sys/socket.h>
#include <sys/stat.h>
#include <unistd.h>

#include <atomic>
#include <chrono>
#include <condition_variable>
#include <cstdint>
#include <cstdio>
#include <cstring>
#include <deque>
#include <map>
#include <mutex>
#include <string>
#include <thread>
#include <vector>

extern "C" {

// ===========================================================================
// TCPStore
// ===========================================================================
// Wire protocol: [1 byte op][u32 keylen][key][u64 vallen][val]
//   op: 0=SET 1=GET(blocking til present, 2s poll) 2=ADD(i64 delta)
//       3=WAIT(present?) 4=DELETE 5=PING
//       6=ADD_TOKEN(val = i64 delta + idempotency token bytes; the server
//         remembers token->result so a retried call after an ambiguous
//         failure returns the recorded result instead of re-adding)
// Reply: [u64 vallen][val] (ADD replies the new counter as i64; WAIT replies
// 1 byte 0/1)

namespace {

struct StoreServer {
  int listen_fd = -1;
  std::thread accept_thread;
  std::atomic<bool> stop{false};
  std::mutex mu;
  std::condition_variable cv;
  std::map<std::string, std::string> kv;
  // ADD_TOKEN dedup: applied token -> result, FIFO-bounded (a token only
  // needs to survive its own retry window)
  std::map<std::string, int64_t> applied;
  std::deque<std::string> applied_order;
  std::vector<std::thread> workers;
};

constexpr size_t kTokenWindow = 4096;

bool read_all(int fd, void* buf, size_t n) {
  char* p = static_cast<char*>(buf);
  while (n > 0) {
    ssize_t r = ::recv(fd, p, n, 0);
    if (r <= 0) return false;
    p += r;
    n -= static_cast<size_t>(r);
  }
  return true;
}

bool write_all(int fd, const void* buf, size_t n) {
  const char* p = static_cast<const char*>(buf);
  while (n > 0) {
    ssize_t r = ::send(fd, p, n, MSG_NOSIGNAL);
    if (r <= 0) return false;
    p += r;
    n -= static_cast<size_t>(r);
  }
  return true;
}

void serve_client(StoreServer* s, int fd) {
  for (;;) {
    uint8_t op;
    if (!read_all(fd, &op, 1)) break;
    uint32_t klen;
    if (!read_all(fd, &klen, 4)) break;
    std::string key(klen, '\0');
    if (klen && !read_all(fd, &key[0], klen)) break;
    uint64_t vlen;
    if (!read_all(fd, &vlen, 8)) break;
    std::string val(vlen, '\0');
    if (vlen && !read_all(fd, &val[0], vlen)) break;

    if (op == 0) {  // SET
      {
        std::lock_guard<std::mutex> lk(s->mu);
        s->kv[key] = val;
      }
      s->cv.notify_all();
      uint64_t zero = 0;
      if (!write_all(fd, &zero, 8)) break;
    } else if (op == 1) {  // GET (blocking)
      std::unique_lock<std::mutex> lk(s->mu);
      s->cv.wait(lk, [&] { return s->stop.load() || s->kv.count(key); });
      if (s->stop.load()) break;
      // Copy while holding the lock: a concurrent SET/ADD/DELETE on this key
      // would invalidate a reference's buffer once we unlock.
      std::string v = s->kv[key];
      lk.unlock();
      uint64_t n = v.size();
      if (!write_all(fd, &n, 8) || !write_all(fd, v.data(), v.size())) break;
    } else if (op == 2) {  // ADD
      int64_t delta = 0;
      memcpy(&delta, val.data(), std::min<size_t>(8, val.size()));
      int64_t now;
      {
        std::lock_guard<std::mutex> lk(s->mu);
        int64_t cur = 0;
        auto it = s->kv.find(key);
        if (it != s->kv.end() && it->second.size() >= 8)
          memcpy(&cur, it->second.data(), 8);
        now = cur + delta;
        std::string nv(8, '\0');
        memcpy(&nv[0], &now, 8);
        s->kv[key] = nv;
      }
      s->cv.notify_all();
      uint64_t n = 8;
      if (!write_all(fd, &n, 8) || !write_all(fd, &now, 8)) break;
    } else if (op == 6) {  // ADD_TOKEN: val = i64 delta + token bytes
      int64_t delta = 0;
      memcpy(&delta, val.data(), std::min<size_t>(8, val.size()));
      std::string token = val.size() > 8 ? val.substr(8) : std::string();
      int64_t now;
      {
        std::lock_guard<std::mutex> lk(s->mu);
        auto a = token.empty() ? s->applied.end() : s->applied.find(token);
        if (a != s->applied.end()) {
          now = a->second;  // replayed call: return the recorded result
        } else {
          int64_t cur = 0;
          auto it = s->kv.find(key);
          if (it != s->kv.end() && it->second.size() >= 8)
            memcpy(&cur, it->second.data(), 8);
          now = cur + delta;
          std::string nv(8, '\0');
          memcpy(&nv[0], &now, 8);
          s->kv[key] = nv;
          if (!token.empty()) {
            s->applied.emplace(token, now);
            s->applied_order.push_back(token);
            while (s->applied_order.size() > kTokenWindow) {
              s->applied.erase(s->applied_order.front());
              s->applied_order.pop_front();
            }
          }
        }
      }
      s->cv.notify_all();
      uint64_t n = 8;
      if (!write_all(fd, &n, 8) || !write_all(fd, &now, 8)) break;
    } else if (op == 3) {  // WAIT/check
      uint8_t present;
      {
        std::lock_guard<std::mutex> lk(s->mu);
        present = s->kv.count(key) ? 1 : 0;
      }
      uint64_t n = 1;
      if (!write_all(fd, &n, 8) || !write_all(fd, &present, 1)) break;
    } else if (op == 4) {  // DELETE
      {
        std::lock_guard<std::mutex> lk(s->mu);
        s->kv.erase(key);
      }
      uint64_t zero = 0;
      if (!write_all(fd, &zero, 8)) break;
    } else if (op == 5) {  // PING
      uint64_t zero = 0;
      if (!write_all(fd, &zero, 8)) break;
    } else {
      break;
    }
  }
  ::close(fd);
}

}  // namespace

void* pts_server_start(int port) {
  int fd = ::socket(AF_INET, SOCK_STREAM, 0);
  if (fd < 0) return nullptr;
  int one = 1;
  setsockopt(fd, SOL_SOCKET, SO_REUSEADDR, &one, sizeof(one));
  sockaddr_in addr{};
  addr.sin_family = AF_INET;
  addr.sin_addr.s_addr = htonl(INADDR_ANY);
  addr.sin_port = htons(static_cast<uint16_t>(port));
  if (::bind(fd, reinterpret_cast<sockaddr*>(&addr), sizeof(addr)) != 0 ||
      ::listen(fd, 128) != 0) {
    ::close(fd);
    return nullptr;
  }
  auto* s = new StoreServer();
  s->listen_fd = fd;
  s->accept_thread = std::thread([s] {
    for (;;) {
      int cfd = ::accept(s->listen_fd, nullptr, nullptr);
      if (cfd < 0) {
        if (s->stop.load()) return;
        continue;
      }
      int one = 1;
      setsockopt(cfd, IPPROTO_TCP, TCP_NODELAY, &one, sizeof(one));
      s->workers.emplace_back(serve_client, s, cfd);
    }
  });
  return s;
}

void pts_server_stop(void* handle) {
  auto* s = static_cast<StoreServer*>(handle);
  if (!s) return;
  s->stop.store(true);
  s->cv.notify_all();
  ::shutdown(s->listen_fd, SHUT_RDWR);
  ::close(s->listen_fd);
  if (s->accept_thread.joinable()) s->accept_thread.join();
  for (auto& t : s->workers)
    if (t.joinable()) t.detach();  // blocked GETs die with process
  delete s;
}

struct StoreClient {
  int fd = -1;
  std::mutex mu;
};

void* pts_client_connect(const char* host, int port, int timeout_ms) {
  auto deadline = std::chrono::steady_clock::now() +
                  std::chrono::milliseconds(timeout_ms);
  for (;;) {
    int fd = ::socket(AF_INET, SOCK_STREAM, 0);
    if (fd < 0) return nullptr;
    sockaddr_in addr{};
    addr.sin_family = AF_INET;
    addr.sin_port = htons(static_cast<uint16_t>(port));
    if (inet_pton(AF_INET, host, &addr.sin_addr) != 1) {
      ::close(fd);
      return nullptr;
    }
    if (::connect(fd, reinterpret_cast<sockaddr*>(&addr), sizeof(addr)) == 0) {
      int one = 1;
      setsockopt(fd, IPPROTO_TCP, TCP_NODELAY, &one, sizeof(one));
      auto* c = new StoreClient();
      c->fd = fd;
      return c;
    }
    ::close(fd);
    if (std::chrono::steady_clock::now() > deadline) return nullptr;
    std::this_thread::sleep_for(std::chrono::milliseconds(50));
  }
}

static bool request(StoreClient* c, uint8_t op, const char* key,
                    const void* val, uint64_t vlen, std::string* reply) {
  std::lock_guard<std::mutex> lk(c->mu);
  uint32_t klen = static_cast<uint32_t>(strlen(key));
  if (!write_all(c->fd, &op, 1) || !write_all(c->fd, &klen, 4) ||
      !write_all(c->fd, key, klen) || !write_all(c->fd, &vlen, 8))
    return false;
  if (vlen && !write_all(c->fd, val, vlen)) return false;
  uint64_t rlen;
  if (!read_all(c->fd, &rlen, 8)) return false;
  reply->resize(rlen);
  if (rlen && !read_all(c->fd, &(*reply)[0], rlen)) return false;
  return true;
}

int pts_set(void* handle, const char* key, const void* data, uint64_t len) {
  std::string r;
  return request(static_cast<StoreClient*>(handle), 0, key, data, len, &r)
             ? 0 : -1;
}

// Blocking get; returns value length or -1. Caller passes a buffer.
int64_t pts_get(void* handle, const char* key, void* buf, uint64_t maxlen) {
  std::string r;
  if (!request(static_cast<StoreClient*>(handle), 1, key, nullptr, 0, &r))
    return -1;
  uint64_t n = std::min<uint64_t>(r.size(), maxlen);
  memcpy(buf, r.data(), n);
  return static_cast<int64_t>(r.size());
}

int64_t pts_add(void* handle, const char* key, int64_t delta) {
  std::string r;
  if (!request(static_cast<StoreClient*>(handle), 2, key, &delta, 8, &r) ||
      r.size() < 8)
    return INT64_MIN;
  int64_t v;
  memcpy(&v, r.data(), 8);
  return v;
}

int64_t pts_add_token(void* handle, const char* key, int64_t delta,
                      const char* token, uint64_t token_len) {
  std::string payload(8, '\0');
  memcpy(&payload[0], &delta, 8);
  payload.append(token, token_len);
  std::string r;
  if (!request(static_cast<StoreClient*>(handle), 6, key, payload.data(),
               payload.size(), &r) ||
      r.size() < 8)
    return INT64_MIN;
  int64_t v;
  memcpy(&v, r.data(), 8);
  return v;
}

int pts_check(void* handle, const char* key) {
  std::string r;
  if (!request(static_cast<StoreClient*>(handle), 3, key, nullptr, 0, &r) ||
      r.empty())
    return -1;
  return r[0] ? 1 : 0;
}

int pts_delete(void* handle, const char* key) {
  std::string r;
  return request(static_cast<StoreClient*>(handle), 4, key, nullptr, 0, &r)
             ? 0 : -1;
}

void pts_client_close(void* handle) {
  auto* c = static_cast<StoreClient*>(handle);
  if (!c) return;
  ::close(c->fd);
  delete c;
}

// ===========================================================================
// ShmRing: SPSC byte ring in POSIX shared memory (process-shared mutex+cv)
// ===========================================================================

struct ShmHeader {
  pthread_mutex_t mu;
  pthread_cond_t not_full;
  pthread_cond_t not_empty;
  uint64_t capacity;   // data bytes
  uint64_t head;       // write offset
  uint64_t tail;       // read offset
  uint64_t used;       // bytes in ring
  uint32_t closed;
};

struct ShmRing {
  ShmHeader* h = nullptr;
  char* data = nullptr;
  size_t total = 0;
  std::string name;
  bool owner = false;
};

void* shmring_create(const char* name, uint64_t capacity) {
  size_t total = sizeof(ShmHeader) + capacity;
  shm_unlink(name);
  int fd = shm_open(name, O_CREAT | O_EXCL | O_RDWR, 0600);
  if (fd < 0) return nullptr;
  if (ftruncate(fd, static_cast<off_t>(total)) != 0) {
    ::close(fd);
    shm_unlink(name);
    return nullptr;
  }
  void* mem = mmap(nullptr, total, PROT_READ | PROT_WRITE, MAP_SHARED, fd, 0);
  ::close(fd);
  if (mem == MAP_FAILED) return nullptr;
  auto* h = static_cast<ShmHeader*>(mem);
  memset(h, 0, sizeof(ShmHeader));
  h->capacity = capacity;
  pthread_mutexattr_t ma;
  pthread_mutexattr_init(&ma);
  pthread_mutexattr_setpshared(&ma, PTHREAD_PROCESS_SHARED);
  pthread_mutex_init(&h->mu, &ma);
  pthread_condattr_t ca;
  pthread_condattr_init(&ca);
  pthread_condattr_setpshared(&ca, PTHREAD_PROCESS_SHARED);
  pthread_cond_init(&h->not_full, &ca);
  pthread_cond_init(&h->not_empty, &ca);
  auto* r = new ShmRing();
  r->h = h;
  r->data = static_cast<char*>(mem) + sizeof(ShmHeader);
  r->total = total;
  r->name = name;
  r->owner = true;
  return r;
}

void* shmring_attach(const char* name) {
  int fd = shm_open(name, O_RDWR, 0600);
  if (fd < 0) return nullptr;
  struct stat st;
  if (fstat(fd, &st) != 0) {
    ::close(fd);
    return nullptr;
  }
  void* mem = mmap(nullptr, static_cast<size_t>(st.st_size),
                   PROT_READ | PROT_WRITE, MAP_SHARED, fd, 0);
  ::close(fd);
  if (mem == MAP_FAILED) return nullptr;
  auto* r = new ShmRing();
  r->h = static_cast<ShmHeader*>(mem);
  r->data = static_cast<char*>(mem) + sizeof(ShmHeader);
  r->total = static_cast<size_t>(st.st_size);
  r->name = name;
  return r;
}

static void ring_write(ShmRing* r, const char* p, uint64_t n) {
  uint64_t cap = r->h->capacity;
  uint64_t head = r->h->head;
  uint64_t first = std::min(n, cap - head);
  memcpy(r->data + head, p, first);
  if (n > first) memcpy(r->data, p + first, n - first);
  r->h->head = (head + n) % cap;
  r->h->used += n;
}

static void ring_read(ShmRing* r, char* p, uint64_t n) {
  uint64_t cap = r->h->capacity;
  uint64_t tail = r->h->tail;
  uint64_t first = std::min(n, cap - tail);
  memcpy(p, r->data + tail, first);
  if (n > first) memcpy(p + first, r->data, n - first);
  r->h->tail = (tail + n) % cap;
  r->h->used -= n;
}

// Push one message [u64 len][payload]; blocks while full. 0 ok, -1 closed.
int shmring_push(void* handle, const void* data, uint64_t len) {
  auto* r = static_cast<ShmRing*>(handle);
  uint64_t need = len + 8;
  if (need > r->h->capacity) return -2;
  pthread_mutex_lock(&r->h->mu);
  while (r->h->capacity - r->h->used < need && !r->h->closed)
    pthread_cond_wait(&r->h->not_full, &r->h->mu);
  if (r->h->closed) {
    pthread_mutex_unlock(&r->h->mu);
    return -1;
  }
  ring_write(r, reinterpret_cast<const char*>(&len), 8);
  ring_write(r, static_cast<const char*>(data), len);
  pthread_cond_signal(&r->h->not_empty);
  pthread_mutex_unlock(&r->h->mu);
  return 0;
}

// Pop one message into buf; returns payload length, -1 closed+empty,
// -2 buffer too small (message left in place).
int64_t shmring_pop(void* handle, void* buf, uint64_t maxlen) {
  auto* r = static_cast<ShmRing*>(handle);
  pthread_mutex_lock(&r->h->mu);
  while (r->h->used == 0 && !r->h->closed)
    pthread_cond_wait(&r->h->not_empty, &r->h->mu);
  if (r->h->used == 0 && r->h->closed) {
    pthread_mutex_unlock(&r->h->mu);
    return -1;
  }
  uint64_t len;
  uint64_t save_tail = r->h->tail;
  uint64_t save_used = r->h->used;
  ring_read(r, reinterpret_cast<char*>(&len), 8);
  if (len > maxlen) {
    r->h->tail = save_tail;
    r->h->used = save_used;
    pthread_mutex_unlock(&r->h->mu);
    return -2;
  }
  ring_read(r, static_cast<char*>(buf), len);
  pthread_cond_signal(&r->h->not_full);
  pthread_mutex_unlock(&r->h->mu);
  return static_cast<int64_t>(len);
}

void shmring_close(void* handle) {
  auto* r = static_cast<ShmRing*>(handle);
  if (!r) return;
  pthread_mutex_lock(&r->h->mu);
  r->h->closed = 1;
  pthread_cond_broadcast(&r->h->not_empty);
  pthread_cond_broadcast(&r->h->not_full);
  pthread_mutex_unlock(&r->h->mu);
}

void shmring_free(void* handle) {
  auto* r = static_cast<ShmRing*>(handle);
  if (!r) return;
  bool owner = r->owner;
  std::string name = r->name;
  munmap(r->h, r->total);
  if (owner) shm_unlink(name.c_str());
  delete r;
}

// ===========================================================================
// Tracer: RecordEvent spans → chrome trace JSON
// ===========================================================================

namespace {

struct Span {
  std::string name;
  uint64_t tid;
  uint64_t start_ns;
  uint64_t end_ns;
};

std::mutex g_trace_mu;
std::vector<Span> g_spans;
std::atomic<bool> g_trace_on{false};

uint64_t now_ns() {
  return static_cast<uint64_t>(
      std::chrono::duration_cast<std::chrono::nanoseconds>(
          std::chrono::steady_clock::now().time_since_epoch())
          .count());
}

}  // namespace

void trace_enable(int on) { g_trace_on.store(on != 0); }
int trace_enabled() { return g_trace_on.load() ? 1 : 0; }
uint64_t trace_now_ns() { return now_ns(); }

void trace_record(const char* name, uint64_t tid, uint64_t start_ns,
                  uint64_t end_ns) {
  if (!g_trace_on.load()) return;
  std::lock_guard<std::mutex> lk(g_trace_mu);
  g_spans.push_back(Span{name, tid, start_ns, end_ns});
}

uint64_t trace_span_count() {
  std::lock_guard<std::mutex> lk(g_trace_mu);
  return g_spans.size();
}

void trace_clear() {
  std::lock_guard<std::mutex> lk(g_trace_mu);
  g_spans.clear();
}

// Chrome-trace JSON (reference: chrometracing_logger.cc output format)
int trace_dump_json(const char* path, int pid) {
  std::lock_guard<std::mutex> lk(g_trace_mu);
  FILE* f = fopen(path, "w");
  if (!f) return -1;
  fprintf(f, "{\"traceEvents\":[");
  for (size_t i = 0; i < g_spans.size(); ++i) {
    const Span& s = g_spans[i];
    std::string esc;
    esc.reserve(s.name.size());
    for (char c : s.name) {
      if (c == '"' || c == '\\') esc.push_back('\\');
      if (static_cast<unsigned char>(c) >= 0x20) esc.push_back(c);
    }
    fprintf(f,
            "%s{\"name\":\"%s\",\"ph\":\"X\",\"pid\":%d,\"tid\":%llu,"
            "\"ts\":%.3f,\"dur\":%.3f}",
            i ? "," : "", esc.c_str(), pid,
            static_cast<unsigned long long>(s.tid), s.start_ns / 1000.0,
            (s.end_ns - s.start_ns) / 1000.0);
  }
  fprintf(f, "]}");
  fclose(f);
  return 0;
}

}  // extern "C"

"""Native runtime bindings (ctypes over the C ABI in src/native.cc).

The .so is built lazily on first import with g++ (cached by source hash in
~/.cache/paddle_tpu). Every consumer has a pure-Python fallback, so an
environment without a toolchain still works — `available()` reports which
path is live (mirrors how the reference gates native fast paths behind
build flags).
"""
from __future__ import annotations

import ctypes
import hashlib
import os
import subprocess
import threading

_SRC = os.path.join(os.path.dirname(__file__), "src", "native.cc")
_lock = threading.Lock()
_lib = None
_tried = False


def _build_and_load():
    with open(_SRC, "rb") as f:
        digest = hashlib.sha256(f.read()).hexdigest()[:16]
    cache = os.environ.get(
        "PADDLE_TPU_NATIVE_CACHE",
        os.path.join(os.path.expanduser("~"), ".cache", "paddle_tpu"))
    os.makedirs(cache, exist_ok=True)
    so = os.path.join(cache, f"libpaddle_tpu_native_{digest}.so")
    if not os.path.exists(so):
        tmp = so + f".tmp{os.getpid()}"
        cmd = ["g++", "-O2", "-std=c++17", "-fPIC", "-shared", "-pthread",
               _SRC, "-o", tmp, "-lrt"]
        subprocess.run(cmd, check=True, capture_output=True, timeout=180)
        os.replace(tmp, so)
    lib = ctypes.CDLL(so)
    # signatures
    lib.pts_server_start.restype = ctypes.c_void_p
    lib.pts_server_start.argtypes = [ctypes.c_int]
    lib.pts_server_stop.argtypes = [ctypes.c_void_p]
    lib.pts_client_connect.restype = ctypes.c_void_p
    lib.pts_client_connect.argtypes = [ctypes.c_char_p, ctypes.c_int,
                                       ctypes.c_int]
    lib.pts_client_close.argtypes = [ctypes.c_void_p]
    lib.pts_set.restype = ctypes.c_int
    lib.pts_set.argtypes = [ctypes.c_void_p, ctypes.c_char_p, ctypes.c_char_p,
                            ctypes.c_uint64]
    lib.pts_get.restype = ctypes.c_int64
    lib.pts_get.argtypes = [ctypes.c_void_p, ctypes.c_char_p, ctypes.c_void_p,
                            ctypes.c_uint64]
    lib.pts_add.restype = ctypes.c_int64
    lib.pts_add.argtypes = [ctypes.c_void_p, ctypes.c_char_p, ctypes.c_int64]
    lib.pts_add_token.restype = ctypes.c_int64
    lib.pts_add_token.argtypes = [ctypes.c_void_p, ctypes.c_char_p,
                                  ctypes.c_int64, ctypes.c_char_p,
                                  ctypes.c_uint64]
    lib.pts_check.restype = ctypes.c_int
    lib.pts_check.argtypes = [ctypes.c_void_p, ctypes.c_char_p]
    lib.pts_delete.restype = ctypes.c_int
    lib.pts_delete.argtypes = [ctypes.c_void_p, ctypes.c_char_p]
    lib.shmring_create.restype = ctypes.c_void_p
    lib.shmring_create.argtypes = [ctypes.c_char_p, ctypes.c_uint64]
    lib.shmring_attach.restype = ctypes.c_void_p
    lib.shmring_attach.argtypes = [ctypes.c_char_p]
    lib.shmring_push.restype = ctypes.c_int
    lib.shmring_push.argtypes = [ctypes.c_void_p, ctypes.c_char_p,
                                 ctypes.c_uint64]
    lib.shmring_pop.restype = ctypes.c_int64
    lib.shmring_pop.argtypes = [ctypes.c_void_p, ctypes.c_void_p,
                                ctypes.c_uint64]
    lib.shmring_close.argtypes = [ctypes.c_void_p]
    lib.shmring_free.argtypes = [ctypes.c_void_p]
    lib.trace_enable.argtypes = [ctypes.c_int]
    lib.trace_enabled.restype = ctypes.c_int
    lib.trace_now_ns.restype = ctypes.c_uint64
    lib.trace_record.argtypes = [ctypes.c_char_p, ctypes.c_uint64,
                                 ctypes.c_uint64, ctypes.c_uint64]
    lib.trace_span_count.restype = ctypes.c_uint64
    lib.trace_dump_json.restype = ctypes.c_int
    lib.trace_dump_json.argtypes = [ctypes.c_char_p, ctypes.c_int]
    return lib


def get_lib():
    global _lib, _tried
    if _lib is not None or _tried:
        return _lib
    with _lock:
        if _lib is None and not _tried:
            _tried = True
            try:
                _lib = _build_and_load()
            except Exception:
                _lib = None
    return _lib


def available() -> bool:
    return get_lib() is not None


# ---------------------------------------------------------------------------
# Pythonic wrappers
# ---------------------------------------------------------------------------

class NativeStoreServer:
    def __init__(self, port: int):
        lib = get_lib()
        if lib is None:
            raise RuntimeError("native library unavailable")
        self._lib = lib
        self._h = lib.pts_server_start(port)
        if not self._h:
            raise OSError(f"TCPStore server failed to bind port {port}")
        self.port = port

    def stop(self):
        if self._h:
            self._lib.pts_server_stop(self._h)
            self._h = None

    def __del__(self):
        try:
            self.stop()
        except Exception:
            pass


class NativeStoreClient:
    def __init__(self, host: str, port: int, timeout_ms: int = 30000):
        lib = get_lib()
        if lib is None:
            raise RuntimeError("native library unavailable")
        self._lib = lib
        self._h = lib.pts_client_connect(host.encode(), port, timeout_ms)
        if not self._h:
            raise ConnectionError(f"cannot connect TCPStore {host}:{port}")

    def set(self, key: str, value: bytes):
        if self._lib.pts_set(self._h, key.encode(), value, len(value)) != 0:
            raise IOError("TCPStore set failed")

    def get(self, key: str, max_len: int = 1 << 20) -> bytes:
        buf = ctypes.create_string_buffer(max_len)
        n = self._lib.pts_get(self._h, key.encode(), buf, max_len)
        if n < 0:
            raise IOError("TCPStore get failed")
        if n > max_len:
            buf = ctypes.create_string_buffer(n)
            n = self._lib.pts_get(self._h, key.encode(), buf, n)
        return buf.raw[:n]

    def add(self, key: str, delta: int) -> int:
        v = self._lib.pts_add(self._h, key.encode(), delta)
        if v == -(2 ** 63):
            raise IOError("TCPStore add failed")
        return v

    def add_token(self, key: str, delta: int, token: bytes) -> int:
        """ADD with a per-call idempotency token (see store.py): replaying
        the same token returns the recorded result instead of re-adding."""
        v = self._lib.pts_add_token(self._h, key.encode(), delta, token,
                                    len(token))
        if v == -(2 ** 63):
            raise IOError("TCPStore add failed")
        return v

    def check(self, key: str) -> bool:
        return self._lib.pts_check(self._h, key.encode()) == 1

    def delete(self, key: str):
        self._lib.pts_delete(self._h, key.encode())

    def close(self):
        if self._h:
            self._lib.pts_client_close(self._h)
            self._h = None

    def __del__(self):
        try:
            self.close()
        except Exception:
            pass


class ShmRing:
    """SPSC shared-memory message ring (DataLoader worker→parent channel)."""

    def __init__(self, name: str, capacity: int = 1 << 24, create: bool = True):
        lib = get_lib()
        if lib is None:
            raise RuntimeError("native library unavailable")
        self._lib = lib
        self.name = name
        if create:
            self._h = lib.shmring_create(name.encode(), capacity)
        else:
            self._h = lib.shmring_attach(name.encode())
        if not self._h:
            raise OSError(f"shm ring {'create' if create else 'attach'} "
                          f"failed for {name}")
        self._owner = create

    def push(self, data: bytes):
        rc = self._lib.shmring_push(self._h, data, len(data))
        if rc == -1:
            raise EOFError("ring closed")
        if rc == -2:
            raise ValueError("message larger than ring capacity")

    def pop(self, max_len: int = 1 << 24) -> bytes:
        buf = ctypes.create_string_buffer(max_len)
        n = self._lib.shmring_pop(self._h, buf, max_len)
        if n == -1:
            raise EOFError("ring closed")
        if n == -2:
            raise ValueError("pop buffer too small")
        return buf.raw[:n]

    def close(self):
        if self._h:
            self._lib.shmring_close(self._h)

    def free(self):
        if self._h:
            self._lib.shmring_free(self._h)
            self._h = None

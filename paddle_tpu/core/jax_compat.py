"""Version shims for the installed JAX.

The codebase targets the modern `jax.shard_map` entry point
(`check_vma=`, partial-manual `axis_names=`). Older releases only ship
`jax.experimental.shard_map.shard_map`, whose signature spells the same
options as `check_rep=` and the inverted `auto=` (axes NOT listed are
manual there, auto here). Installing the adapter on the `jax` module
keeps every call site on the one modern spelling.
"""
from __future__ import annotations

import jax


def _shard_map_adapter(f, mesh=None, in_specs=None, out_specs=None,
                       axis_names=None, check_vma=None, **kwargs):
    from jax.experimental.shard_map import shard_map as _legacy
    if check_vma is not None:
        kwargs.setdefault("check_rep", check_vma)
    if axis_names is not None:
        auto = frozenset(mesh.axis_names) - frozenset(axis_names)
        if auto:
            kwargs.setdefault("auto", auto)
    return _legacy(f, mesh=mesh, in_specs=in_specs, out_specs=out_specs,
                   **kwargs)


def _axis_size_adapter(axis_name):
    # jax.core.axis_frame(name) returns the bound size (raising NameError
    # when unbound), which is exactly lax.axis_size's contract.
    import math

    if isinstance(axis_name, (tuple, list)):
        return math.prod(jax.core.axis_frame(a) for a in axis_name)
    return jax.core.axis_frame(axis_name)


def install() -> None:
    if not hasattr(jax, "shard_map"):
        jax.shard_map = _shard_map_adapter
    if not hasattr(jax.lax, "axis_size"):
        jax.lax.axis_size = _axis_size_adapter


install()

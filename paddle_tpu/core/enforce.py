"""Error enforcement utilities.

Analog of the reference's enforce macros (`paddle/phi/core/enforce.h`,
PADDLE_ENFORCE_*): raise rich, typed errors with an error-summary header.
"""
from __future__ import annotations


# distress hook injected by paddle_tpu.observability (kept injectable so
# this module stays dependency-free): fn(exc_type_name, message) — may
# dump the flight recorder under FLAGS_dump_on_enforce
_distress_hook = [None]


def set_distress_hook(fn):
    _distress_hook[0] = fn


class EnforceNotMet(RuntimeError):
    """Base framework error (reference: phi::enforce::EnforceNotMet)."""

    def __init__(self, *args):
        super().__init__(*args)
        hook = _distress_hook[0]
        if hook is not None:
            hook(type(self).__name__, str(args[0]) if args else "")


class InvalidArgumentError(EnforceNotMet, ValueError):
    pass


class NotFoundError(EnforceNotMet, KeyError):
    pass


class OutOfRangeError(EnforceNotMet, IndexError):
    pass


class UnimplementedError(EnforceNotMet, NotImplementedError):
    pass


class UnavailableError(EnforceNotMet):
    pass


class DataLossError(EnforceNotMet):
    """Persisted data failed an integrity check (truncated/corrupted file,
    CRC mismatch). Reference: phi error code DATALOSS."""


def enforce(cond, msg: str = "Enforce condition failed", *args, exc=InvalidArgumentError):
    if not cond:
        raise exc(msg % args if args else msg)


def enforce_eq(a, b, msg: str = ""):
    if a != b:
        raise InvalidArgumentError(f"Expected {a!r} == {b!r}. {msg}")


def enforce_gt(a, b, msg: str = ""):
    if not a > b:
        raise InvalidArgumentError(f"Expected {a!r} > {b!r}. {msg}")


def not_implemented(what: str):
    raise UnimplementedError(what)

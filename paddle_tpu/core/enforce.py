"""Error enforcement utilities.

Analog of the reference's enforce macros (`paddle/phi/core/enforce.h`,
PADDLE_ENFORCE_*): raise rich, typed errors with an error-summary header.
"""
from __future__ import annotations


class EnforceNotMet(RuntimeError):
    """Base framework error (reference: phi::enforce::EnforceNotMet)."""


class InvalidArgumentError(EnforceNotMet, ValueError):
    pass


class NotFoundError(EnforceNotMet, KeyError):
    pass


class OutOfRangeError(EnforceNotMet, IndexError):
    pass


class UnimplementedError(EnforceNotMet, NotImplementedError):
    pass


class UnavailableError(EnforceNotMet):
    pass


def enforce(cond, msg: str = "Enforce condition failed", *args, exc=InvalidArgumentError):
    if not cond:
        raise exc(msg % args if args else msg)


def enforce_eq(a, b, msg: str = ""):
    if a != b:
        raise InvalidArgumentError(f"Expected {a!r} == {b!r}. {msg}")


def enforce_gt(a, b, msg: str = ""):
    if not a > b:
        raise InvalidArgumentError(f"Expected {a!r} > {b!r}. {msg}")


def not_implemented(what: str):
    raise UnimplementedError(what)

"""Random number generation.

Analog of the reference's `phi::Generator` (`paddle/phi/core/generator.h`)
built on JAX's splittable PRNG: a global Generator holds a key that is split
on every consumption — functional, reproducible, and trace-friendly (a traced
key can be installed via `scoped_rng_key`, which is how jitted programs thread
randomness as an explicit input instead of a captured constant).
"""
from __future__ import annotations

import contextlib
import threading

import jax
import numpy as np


class Generator:
    """Lazy key materialization: creating a jax PRNG key initializes the XLA
    backend, and that must NOT happen at `import paddle_tpu` time — a worker
    has to be able to call jax.distributed.initialize() (multi-process
    bootstrap) after importing the framework."""

    def __init__(self, seed: int = 0):
        self._seed = seed
        self._key = None
        self._lock = threading.Lock()

    @property
    def _key_live(self):
        if self._key is None:
            self._key = jax.random.key(self._seed)
        return self._key

    def manual_seed(self, seed: int):
        # stays lazy: paddle.seed() before init_parallel_env() must not
        # materialize the backend (it would block jax.distributed.initialize)
        self._seed = int(seed)
        self._key = None
        return self

    def initial_seed(self):
        return self._seed

    def split(self, n: int = 1):
        with self._lock:
            keys = jax.random.split(self._key_live, n + 1)
            self._key = keys[0]
            return keys[1] if n == 1 else keys[1:]

    def get_state(self):
        return jax.random.key_data(self._key_live)

    def set_state(self, state):
        self._key = jax.random.wrap_key_data(np.asarray(state))


default_generator = Generator(0)
_tls = threading.local()


def seed(s: int):
    """paddle.seed parity."""
    default_generator.manual_seed(s)
    return default_generator


# Monotonic count of global-stream key consumptions. The dispatch cache
# probes this around a kernel's first eager run: a kernel that drew from the
# generator is impure (jitting it would freeze the key as a constant) and
# must never be cached. next_key() is the single chokepoint for that stream.
_consumed = [0]


def consumption_count() -> int:
    return _consumed[0]


def next_key():
    """Get a fresh PRNG key: the scoped (traced) key if installed, else global."""
    _consumed[0] += 1
    stack = getattr(_tls, "scoped", None)
    if stack:
        key, count = stack[-1]
        sub = jax.random.fold_in(key, count)
        stack[-1] = (key, count + 1)
        return sub
    return default_generator.split()


@contextlib.contextmanager
def scoped_rng_key(key):
    """Install a (possibly traced) key for ops executed in this scope."""
    stack = getattr(_tls, "scoped", None)
    if stack is None:
        stack = _tls.scoped = []
    stack.append((key, 0))
    try:
        yield
    finally:
        stack.pop()


def get_rng_state():
    return [default_generator.get_state()]


def set_rng_state(states):
    default_generator.set_state(states[0])


def seed_or_next(op_seed: int):
    """The op-level seeding rule shared by every random kernel: a nonzero
    per-op seed gives a fixed key (reproducible op), seed=0 draws from the
    global generator stream (paddle.seed-controlled)."""
    import jax

    return jax.random.key(op_seed) if op_seed else next_key()

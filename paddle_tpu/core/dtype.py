"""Data type system.

TPU-native analog of the reference's phi DataType enum
(`paddle/phi/common/data_type.h`) — here a thin wrapper over numpy/jax
dtypes so that a ``DType`` compares equal to its string name, its numpy
dtype, and itself, which is what user code written against the reference
expects (``x.dtype == paddle.float32`` / ``x.dtype == 'float32'``).
"""
from __future__ import annotations

import numpy as np

_CANONICAL = {
    "bool": np.dtype(np.bool_),
    "uint8": np.dtype(np.uint8),
    "int8": np.dtype(np.int8),
    "int16": np.dtype(np.int16),
    "int32": np.dtype(np.int32),
    "int64": np.dtype(np.int64),
    "float16": np.dtype(np.float16),
    "float32": np.dtype(np.float32),
    "float64": np.dtype(np.float64),
    "complex64": np.dtype(np.complex64),
    "complex128": np.dtype(np.complex128),
}


class DType:
    """A framework dtype. Compares equal to name strings and numpy dtypes."""

    __slots__ = ("name", "np_dtype")
    _registry: dict = {}

    def __new__(cls, name):
        if isinstance(name, DType):
            return name
        key = cls._canonical_name(name)
        inst = cls._registry.get(key)
        if inst is None:
            inst = object.__new__(cls)
            inst.name = key
            inst.np_dtype = _np_for(key)
            cls._registry[key] = inst
        return inst

    @staticmethod
    def _canonical_name(name) -> str:
        if isinstance(name, str):
            n = name
        else:
            n = np.dtype(name).name  # handles np dtypes, python types
        if n in _EXTENDED:
            return n
        if n not in _CANONICAL:
            # things like 'float' / 'int'
            n = np.dtype(n).name
        if n not in _CANONICAL and n not in _EXTENDED:
            raise TypeError(f"Unsupported dtype: {name!r}")
        return n

    def __repr__(self):
        return f"paddle.{self.name}"

    def __str__(self):
        return self.name

    def __hash__(self):
        return hash(self.name)

    def __eq__(self, other):
        if isinstance(other, DType):
            return self.name == other.name
        if isinstance(other, str):
            try:
                return self.name == DType._canonical_name(other)
            except TypeError:
                return False
        try:
            return self.name == DType._canonical_name(other)
        except (TypeError, ValueError):
            return NotImplemented

    def __ne__(self, other):
        eq = self.__eq__(other)
        if eq is NotImplemented:
            return eq
        return not eq

    @property
    def is_floating_point(self):
        return self.name in ("float16", "bfloat16", "float32", "float64",
                             "float8_e4m3fn", "float8_e5m2")

    @property
    def is_complex(self):
        return self.name in ("complex64", "complex128")

    @property
    def is_integer(self):
        return self.name in ("uint8", "int8", "int16", "int32", "int64")

    @property
    def itemsize(self):
        return self.np_dtype.itemsize


# ml_dtypes-backed names (TPU low-precision family; fp8 feeds the fp8 gemm
# kernels registered in ops/kernels/tail_r5d.py)
_EXTENDED = ("bfloat16", "float8_e4m3fn", "float8_e5m2")


def _np_for(name: str):
    if name in _EXTENDED:
        import ml_dtypes

        return np.dtype(getattr(ml_dtypes, name))
    return _CANONICAL[name]


# Canonical instances --------------------------------------------------------
bool_ = DType("bool")
uint8 = DType("uint8")
int8 = DType("int8")
int16 = DType("int16")
int32 = DType("int32")
int64 = DType("int64")
float16 = DType("float16")
bfloat16 = DType("bfloat16")
float32 = DType("float32")
float64 = DType("float64")
complex64 = DType("complex64")
complex128 = DType("complex128")
float8_e4m3fn = DType("float8_e4m3fn")
float8_e5m2 = DType("float8_e5m2")

_DEFAULT_DTYPE = float32


def set_default_dtype(d):
    """paddle.set_default_dtype parity (reference: python/paddle/framework/framework.py)."""
    global _DEFAULT_DTYPE
    d = DType(d)
    if d not in (float16, bfloat16, float32, float64):
        raise TypeError(
            "set_default_dtype only supports [float16, bfloat16, float32, float64]"
            f", but received {d}"
        )
    _DEFAULT_DTYPE = d


def get_default_dtype():
    return _DEFAULT_DTYPE.name


def to_np(d) -> np.dtype:
    return DType(d).np_dtype


def is_floating_dtype(dt) -> bool:
    """True for float dtypes INCLUDING bfloat16 (np.issubdtype says False for
    ml_dtypes.bfloat16 — use this helper everywhere instead)."""
    import jax.numpy as jnp

    return bool(jnp.issubdtype(np.dtype(dt), jnp.floating))


def is_inexact_dtype(dt) -> bool:
    """Float or complex, bfloat16-aware."""
    import jax.numpy as jnp

    return bool(jnp.issubdtype(np.dtype(dt), jnp.inexact))


def from_jax(jd) -> DType:
    return DType(np.dtype(jd).name if np.dtype(jd).name != "bfloat16" else "bfloat16")

"""paddle_tpu — a TPU-native deep learning framework with PaddlePaddle's capabilities.

Blueprint: /root/repo/SURVEY.md (structural analysis of the reference).
The public surface mirrors `paddle.*` (reference: python/paddle/__init__.py)
while the implementation is an idiomatic XLA/PJRT/Pallas stack.
"""
from __future__ import annotations

import os as _os

import jax as _jax

# An explicit JAX_PLATFORMS env must win over any platform a sitecustomize
# pinned via jax.config.update (config beats env in jax). Spawned worker
# processes (DataLoader, launch, multi-process tests) rely on inheriting
# JAX_PLATFORMS=cpu to avoid touching the real TPU tunnel.
if _os.environ.get("JAX_PLATFORMS"):
    try:
        if _jax.config.jax_platforms != _os.environ["JAX_PLATFORMS"]:
            _jax.config.update("jax_platforms", _os.environ["JAX_PLATFORMS"])
    except Exception:
        pass

# Paddle dtype semantics need int64 (default integer dtype). float64 stays out
# of the compute path via default-dtype coercion in to_tensor, so TPU (no f64)
# is safe.
_jax.config.update("jax_enable_x64", True)

from .core import jax_compat as _jax_compat  # noqa: E402  (installs jax.shard_map shim)

# Core types ------------------------------------------------------------------
from .core.dtype import (  # noqa: F401
    DType,
    bfloat16,
    bool_,
    complex64,
    complex128,
    float8_e4m3fn,
    float8_e5m2,
    float16,
    float32,
    float64,
    get_default_dtype,
    int8,
    int16,
    int32,
    int64,
    set_default_dtype,
    uint8,
)
from .core.dtype import DType as dtype  # noqa: F401
from .core.place import (  # noqa: F401
    CPUPlace,
    CUDAPinnedPlace,
    CUDAPlace,
    Place,
    TPUPlace,
    get_device,
    is_compiled_with_cuda,
    is_compiled_with_tpu,
    is_compiled_with_xpu,
    set_device,
)
from .core.flags import get_flags, set_flags  # noqa: F401
from .core.rng import get_rng_state, seed, set_rng_state  # noqa: F401
from .core.rng import get_rng_state as get_cuda_rng_state  # noqa: F401
from .core.rng import set_rng_state as set_cuda_rng_state  # noqa: F401

bool = bool_  # noqa: A001 — paddle.bool is the dtype, as in the reference


def __getattr__(name):
    # lazy: paddle.DataParallel without importing distributed at package load
    if name == "DataParallel":
        from .distributed.parallel import DataParallel

        return DataParallel
    raise AttributeError(f"module 'paddle_tpu' has no attribute {name!r}")
from .core.tensor import Parameter, Tensor, to_tensor  # noqa: F401
from .ops.dispatch import (  # noqa: F401
    enable_grad,
    is_grad_enabled,
    no_grad,
    set_grad_enabled,
)
from .autograd.engine import grad  # noqa: F401

# Op library → module-level functions (paddle.add, paddle.matmul, ...).
# Sourced from the YAML-generated binding surface (ops/generated_bindings),
# NOT the raw registry: an op without an ops.yaml entry is not public.
from .ops.dispatch import OPS as _OPS
from .ops import generated_bindings as _gen_bindings
from . import tensor as _tensor_methods  # noqa: F401  (patches Tensor methods)
from . import _C_ops  # noqa: F401

_globals = globals()
for _name in _gen_bindings.__all__:
    if _name not in _globals:
        _globals[_name] = getattr(_gen_bindings, _name)
del _name


# Creation / random wrappers with paddle signatures ---------------------------
def rand(shape, dtype=None):
    return _OPS["uniform"](shape, dtype, 0.0, 1.0)


def randn(shape, dtype=None):
    return _OPS["gaussian"](shape, 0.0, 1.0, dtype)


def normal(mean=0.0, std=1.0, shape=None):
    if shape is None:
        if isinstance(mean, Tensor):
            shape = mean.shape
        elif isinstance(std, Tensor):
            shape = std.shape
        else:
            shape = [1]
    return _OPS["gaussian"](shape, mean, std, None)


def ones_like(x, dtype=None):
    return _OPS["ones_like"](x, dtype)


def zeros_like(x, dtype=None):
    return _OPS["zeros_like"](x, dtype)


def clone(x):
    return _OPS["assign"](x)


def numel(x):
    return to_tensor(x.size, dtype="int64")


def shape(x):
    return to_tensor(x.shape, dtype="int32")


def is_tensor(x):
    return isinstance(x, Tensor)


def get_default_device():  # convenience
    from .core.place import current_place

    return current_place()


def in_dynamic_mode():
    from .jit.api import in_to_static_trace

    return not in_to_static_trace()


def device_count():
    import jax

    try:
        devs = [d for d in jax.devices() if d.platform != "cpu"]
        return len(devs) or jax.device_count()
    except RuntimeError:
        return 0


def synchronize():
    """Block until all dispatched device work completes (analog of
    DeviceContext Wait): drains the in-flight step pipeline, then fences
    the device."""
    from .core import async_engine

    async_engine.synchronize()


# Subpackages (populated as the framework grows; see SURVEY.md §7 build plan) -
from . import observability  # noqa: F401, E402  (flight recorder + metrics)

# SIGUSR1 -> flight-recorder dump: a hung process can be inspected with
# `kill -USR1 <pid>` (no-op when not installable, e.g. non-main thread)
observability.install_signal_handler()

from . import autograd  # noqa: F401, E402
from . import nn  # noqa: F401, E402
from . import optimizer  # noqa: F401, E402
from . import jit  # noqa: F401, E402
from . import amp  # noqa: F401, E402
from . import io  # noqa: F401, E402
from . import metric  # noqa: F401, E402
from . import static  # noqa: F401, E402
from .static import enable_static, disable_static  # noqa: F401, E402
from . import audio, hub, text, utils, version  # noqa: F401, E402
from . import vision  # noqa: F401, E402
from . import distributed  # noqa: F401, E402
from . import incubate  # noqa: F401, E402
from . import profiler  # noqa: F401, E402
from . import linalg  # noqa: F401, E402
from . import fft  # noqa: F401, E402
from . import signal  # noqa: F401, E402
from . import distribution  # noqa: F401, E402
from . import geometric  # noqa: F401, E402  (registers graph/segment ops)
from . import sparse  # noqa: F401, E402
from . import pir  # noqa: F401, E402
from . import inference  # noqa: F401, E402
from . import device  # noqa: F401, E402
from . import quantization  # noqa: F401, E402
from . import framework  # noqa: F401, E402
from .framework.io_api import load, save  # noqa: F401, E402
from .hapi.model import Model  # noqa: F401, E402
from . import hapi  # noqa: F401, E402

# Reference __all__ parity tail: compositions/aliases that aren't phi ops
# (numpy-style stacks/splits, predicates, in-place functional spellings,
# dlpack, utilities) — see tensor/compat_ext.py.
from .tensor import compat_ext as _compat_ext  # noqa: E402

for _name in _compat_ext.__all__:
    if _name not in _globals:
        _globals[_name] = getattr(_compat_ext, _name)
del _name
from .hapi.summary import flops, summary  # noqa: F401, E402
from .nn import ParamAttr  # noqa: F401, E402

__version__ = "0.1.0"

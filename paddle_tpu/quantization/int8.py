"""True int8 deployment: quantised weights + XLA int8 arithmetic.

Reference: the static PTQ deploy path (python/paddle/quantization/
quantize.py + paddle/fluid/contrib int8 passes) where calibrated models are
rewritten with int8 weights and quantized kernels. TPU-native: the MXU
multiplies int8 at double rate, and XLA reaches it through a plain
`dot_general` with int8 operands and `preferred_element_type=int32` — no
custom kernels needed. So conversion here is a layer swap:

* ``Int8Linear`` — weights stored int8 (per-output-channel scales), the
  activation statically quantised with the calibrated scale, int8×int8→
  int32 matmul, one fused rescale, fp bias add.
* ``Int8Conv2D`` — weight-only int8 (stored int8 + per-channel scales,
  dequantised into the conv): conv arithmetic stays fp, memory/bandwidth
  drops 4x. (Full int8 conv needs a quantised im2col layout decision XLA
  makes differently per backend; weight-only is the robust cross-backend
  win.)

Layers are inference-only: outputs carry stop_gradient=True, and the int8
buffers live in state_dict so `jit.save`/Predictor export them.
"""
from __future__ import annotations

from typing import Optional

import jax
import jax.numpy as jnp
import numpy as np

from ..core.tensor import Tensor
from ..nn.layer.layers import Layer
from ..nn import functional as F

QMAX = 127.0


def _quantize_weight(w: np.ndarray, axis: Optional[int]):
    """w (float) -> (w_q int8, scale float32 per-channel along `axis`
    or scalar when axis is None)."""
    if axis is None:
        s = np.maximum(np.max(np.abs(w)), 1e-8).astype(np.float32)
        wq = np.clip(np.round(w / s * QMAX), -QMAX, QMAX).astype(np.int8)
        return wq, np.float32(s)
    red = tuple(i for i in range(w.ndim) if i != axis % w.ndim)
    s = np.maximum(np.max(np.abs(w), axis=red), 1e-8).astype(np.float32)
    shape = [1] * w.ndim
    shape[axis % w.ndim] = -1
    wq = np.clip(np.round(w / s.reshape(shape) * QMAX), -QMAX,
                 QMAX).astype(np.int8)
    return wq, s


class Int8Linear(Layer):
    """Deployed linear: int8 weight [in, out], per-out-channel scales,
    statically quantised activation, int32-accumulated MXU matmul."""

    def __init__(self, in_features: int, out_features: int,
                 act_scale: float, per_channel: bool = True):
        super().__init__()
        self.in_features = in_features
        self.out_features = out_features
        self.per_channel = per_channel
        self.register_buffer("weight_int8", Tensor(
            np.zeros((in_features, out_features), np.int8)))
        self.register_buffer("weight_scale", Tensor(
            np.ones((out_features,) if per_channel else (), np.float32)))
        self.register_buffer("act_scale", Tensor(
            np.asarray(act_scale, np.float32)))
        self.bias = None  # replaced at convert time if the source had one

    @classmethod
    def from_float(cls, lin, act_scale: float, per_channel: bool = True):
        w = np.asarray(lin.weight._data, np.float32)
        m = cls(w.shape[0], w.shape[1], act_scale, per_channel)
        wq, s = _quantize_weight(w, 1 if per_channel else None)
        m.weight_int8._data = jnp.asarray(wq)
        m.weight_scale._data = jnp.asarray(s)
        if lin.bias is not None:
            m.register_buffer("bias_fp", Tensor(
                np.asarray(lin.bias._data, np.float32)))
            m.bias = m.bias_fp
        return m

    def forward(self, x):
        xd = x._data
        s_x = self.act_scale._data
        xq = jnp.clip(jnp.round(xd / s_x * QMAX), -QMAX, QMAX).astype(
            jnp.int8)
        acc = jnp.matmul(xq, self.weight_int8._data,
                         preferred_element_type=jnp.int32)
        scale = (s_x * self.weight_scale._data) / (QMAX * QMAX)
        y = acc.astype(jnp.float32) * scale
        if self.bias is not None:
            y = y + self.bias._data
        out = Tensor._from_data(y.astype(xd.dtype))
        out.stop_gradient = True
        return out

    def extra_repr(self):
        return (f"in_features={self.in_features}, "
                f"out_features={self.out_features}, int8, "
                f"per_channel={self.per_channel}")


class Int8Conv2D(Layer):
    """Weight-only int8 conv: int8 storage + per-out-channel scales,
    dequantised into a standard conv (XLA fuses the dequant multiply
    into the convolution's filter read)."""

    def __init__(self, src, per_channel: bool = True):
        super().__init__()
        self.per_channel = per_channel
        self._stride = src._stride
        self._padding = src._padding
        self._dilation = src._dilation
        self._groups = src._groups
        self._data_format = src._data_format
        w = np.asarray(src.weight._data, np.float32)
        wq, s = _quantize_weight(w, 0 if per_channel else None)
        self.register_buffer("weight_int8", Tensor(wq))
        self.register_buffer("weight_scale", Tensor(np.asarray(s)))
        if src.bias is not None:
            self.register_buffer("bias_fp", Tensor(
                np.asarray(src.bias._data, np.float32)))
            self.bias = self.bias_fp
        else:
            self.bias = None

    def forward(self, x):
        wq = self.weight_int8._data.astype(jnp.float32)
        s = self.weight_scale._data
        if self.per_channel:
            s = s.reshape((-1,) + (1,) * (wq.ndim - 1))
        w = Tensor._from_data((wq * (s / QMAX)).astype(x._data.dtype))
        out = F.conv2d(x, w, self.bias, self._stride, self._padding,
                       self._dilation, self._groups, self._data_format)
        out.stop_gradient = True
        return out


def convert_to_int8(model: Layer, per_channel: bool = True) -> Layer:
    """Swap calibrated QuantedLayer wrappers for int8 deploy layers.

    Weight scales are recomputed from the weights themselves (per-channel
    absmax — weights need no calibration data); ACTIVATION scales come
    from the PTQ observers, so `PTQ.quantize` + calibration batches must
    have run. A linear without an observed act scale raises; a conv is
    weight-only and converts regardless.
    """
    from . import QuantedLayer
    from ..nn.layer.common import Linear
    from ..nn.layer.conv import Conv2D

    def swap(m: Layer):
        for name, child in list(m._sub_layers.items()):
            if isinstance(child, QuantedLayer):
                inner = child._inner
                if isinstance(inner, Linear):
                    act_q = child.act_quanter
                    s = float(np.asarray(act_q.scales()._data)) \
                        if act_q is not None else 0.0
                    if s <= 0.0:
                        raise RuntimeError(
                            f"layer {name!r}: no activation scale observed; "
                            f"run calibration batches through the PTQ-"
                            f"quantized model before convert_to_int8")
                    m._sub_layers[name] = Int8Linear.from_float(
                        inner, s, per_channel)
                elif isinstance(inner, Conv2D):
                    m._sub_layers[name] = Int8Conv2D(inner, per_channel)
                else:
                    swap(child)
            else:
                swap(child)
        return m

    out = swap(model)
    out.eval()
    return out

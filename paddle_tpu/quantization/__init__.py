"""paddle.quantization parity — QAT (fake-quant) + PTQ (observer) flows.

Reference: python/paddle/quantization/{config,qat,ptq}.py + imperative
quant-aware layers. TPU-native: fake-quant is a quant-dequant composition
with a straight-through estimator (the round sits behind stop_gradient, so
backward sees identity) — XLA fuses it into the surrounding matmul; int8
inference itself rides XLA's native int8 dot support when converted.
"""
from __future__ import annotations

from typing import Dict, List, Optional, Type

import jax
import jax.numpy as jnp
import numpy as np

from ..core.tensor import Tensor
from ..nn.layer.layers import Layer
from ..ops.dispatch import register_op
from .. import ops


@register_op(name="fake_quantize_dequantize_abs_max")
def _fake_qdq(x, scale, bit_length=8):
    """Quant-dequant with straight-through gradient (reference:
    fake_quantize_dequantize kernels)."""
    import jax

    qmax = float(2 ** (bit_length - 1) - 1)
    s = jnp.maximum(scale, 1e-8)
    q = jnp.clip(jnp.round(x / s * qmax), -qmax, qmax) * s / qmax
    return x + jax.lax.stop_gradient(q - x)


class BaseQuanter(Layer):
    def scales(self):
        raise NotImplementedError


class FakeQuanterWithAbsMaxObserver(BaseQuanter):
    """Moving-average absmax observer + fake quant (reference:
    quantization/quanters/abs_max.py)."""

    def __init__(self, moving_rate: float = 0.9, bit_length: int = 8,
                 dtype="float32", name=None):
        super().__init__()
        self.moving_rate = moving_rate
        self.bit_length = bit_length
        self.register_buffer("_scale", Tensor(np.ones((), np.float32)))
        self._initialized = False

    def forward(self, x):
        # Observe only in eager training: the host-side absmax concretizes the
        # value, which would break tracing/export (jit.save, to_static) of an
        # eval/converted model, where the scale is frozen anyway. Training
        # under a trace cannot observe — fail loudly rather than silently
        # freezing the scale at its init value.
        if self.training:
            if isinstance(x._data, jax.core.Tracer):
                raise RuntimeError(
                    "FakeQuanterWithAbsMaxObserver cannot observe scales "
                    "inside jit/to_static while in train mode; run QAT "
                    "training eagerly or call .eval() before tracing")
            absmax = float(np.asarray(jnp.max(jnp.abs(x._data))))
            if not self._initialized:
                new = absmax
                self._initialized = True
            else:
                cur = float(self._scale.numpy())
                new = self.moving_rate * cur + (1 - self.moving_rate) * absmax
            self._scale._data = jnp.asarray(np.float32(new))
        return ops.get_op("fake_quantize_dequantize_abs_max")(
            x, self._scale, self.bit_length)

    def scales(self):
        return self._scale


class AbsmaxObserver(BaseQuanter):
    """PTQ calibration observer: records running absmax, passes through."""

    def __init__(self, quant_bits: int = 8, **kw):
        super().__init__()
        self.bit_length = quant_bits
        self.register_buffer("_scale", Tensor(np.zeros((), np.float32)))

    def forward(self, x):
        # Calibration is an eager pass (PTQ runs eval-mode batches through the
        # observers); under tracing just pass through with the frozen scale.
        if not isinstance(x._data, jax.core.Tracer):
            absmax = float(np.asarray(jnp.max(jnp.abs(x._data))))
            self._scale._data = jnp.maximum(self._scale._data,
                                            jnp.asarray(np.float32(absmax)))
        return x

    def scales(self):
        return self._scale


class QuantConfig:
    """Reference: quantization/config.py."""

    def __init__(self, activation: Optional[BaseQuanter] = None,
                 weight: Optional[BaseQuanter] = None):
        self._global_activation = activation
        self._global_weight = weight
        self._layer_configs: Dict[Type, Dict] = {}

    def add_type_config(self, layer_type, activation=None, weight=None):
        types = layer_type if isinstance(layer_type, (list, tuple)) \
            else [layer_type]
        for t in types:
            self._layer_configs[t] = {"activation": activation,
                                      "weight": weight}

    def _for_layer(self, layer):
        cfg = self._layer_configs.get(type(layer))
        if cfg:
            return cfg["activation"], cfg["weight"]
        return self._global_activation, self._global_weight


def _clone_quanter(q):
    if q is None:
        return None
    return type(q)(**{k: v for k, v in {
        "moving_rate": getattr(q, "moving_rate", None),
        "bit_length": getattr(q, "bit_length", None),
        "quant_bits": getattr(q, "bit_length", None),
    }.items() if v is not None and k in type(q).__init__.__code__.co_varnames})


class QuantedLayer(Layer):
    """Wraps a Linear/Conv2D with activation+weight fake quant."""

    def __init__(self, inner: Layer, act_quanter, weight_quanter):
        super().__init__()
        self._inner = inner
        self.act_quanter = act_quanter
        self.weight_quanter = weight_quanter

    def forward(self, x):
        if self.act_quanter is not None:
            x = self.act_quanter(x)
        if self.weight_quanter is not None and hasattr(self._inner, "weight"):
            w = self._inner.weight
            saved = w._data
            wq = self.weight_quanter(
                Tensor._from_data(w._data))
            self._inner.weight._data = wq._data
            try:
                return self._inner(x)
            finally:
                self._inner.weight._data = saved
        return self._inner(x)


_DEFAULT_QUANTABLE = None


def _quantable_types():
    global _DEFAULT_QUANTABLE
    if _DEFAULT_QUANTABLE is None:
        from ..nn.layer.common import Linear
        from ..nn.layer.conv import Conv2D

        _DEFAULT_QUANTABLE = (Linear, Conv2D)
    return _DEFAULT_QUANTABLE


def _swap_quantable(model: Layer, config: QuantConfig):
    for name, child in list(model._sub_layers.items()):
        if isinstance(child, _quantable_types()):
            act, wt = config._for_layer(child)
            model._sub_layers[name] = QuantedLayer(
                child, _clone_quanter(act), _clone_quanter(wt))
        else:
            _swap_quantable(child, config)
    return model


class QAT:
    """Quantization-aware training (reference: quantization/qat.py)."""

    def __init__(self, config: QuantConfig):
        self._config = config

    def quantize(self, model: Layer, inplace: bool = True) -> Layer:
        model.train()
        return _swap_quantable(model, self._config)

    def convert(self, model: Layer, inplace: bool = True) -> Layer:
        model.eval()
        return model


class PTQ:
    """Post-training quantization (reference: quantization/ptq.py):
    instrument with observers, run calibration batches, then freeze."""

    def __init__(self, config: Optional[QuantConfig] = None):
        self._config = config or QuantConfig(
            activation=AbsmaxObserver(), weight=AbsmaxObserver())

    def quantize(self, model: Layer, inplace: bool = True) -> Layer:
        model.eval()
        return _swap_quantable(model, self._config)

    def convert(self, model: Layer, inplace: bool = True) -> Layer:
        """Replace observers with fixed fake-quant at observed scales."""
        def freeze(m: Layer):
            for name, child in list(m._sub_layers.items()):
                if isinstance(child, QuantedLayer):
                    for qname in ("act_quanter", "weight_quanter"):
                        q = getattr(child, qname)
                        if isinstance(q, AbsmaxObserver):
                            fixed = FakeQuanterWithAbsMaxObserver(
                                bit_length=q.bit_length)
                            fixed._scale._data = q._scale._data
                            fixed._initialized = True
                            fixed.eval()
                            setattr(child, qname, fixed)
                else:
                    freeze(child)
        freeze(model)
        model.eval()
        return model


def quanter(name):  # decorator registry parity
    def deco(cls):
        return cls
    return deco


from .observers import (AVGObserver, AbsMaxChannelWiseWeightObserver,  # noqa: E402
                        BaseObserver, HistObserver, MSEObserver,
                        PercentileObserver)
from .int8 import Int8Conv2D, Int8Linear, convert_to_int8  # noqa: E402

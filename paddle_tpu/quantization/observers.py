"""PTQ calibration observers beyond plain absmax.

Reference: python/paddle/quantization/observers/ — abs_max.py, avg.py,
hist.py, kl.py, mse.py plus the channel-wise weight observer in
quanters/channel_wise_abs_max.py. Each observer watches activations (or a
weight) during eager calibration batches and produces a scale; under a
trace it is a pass-through with whatever it has observed so far, so a
converted model exports cleanly.

TPU note: observers run on HOST during calibration (tiny reductions, a few
batches), so numpy histograms are fine; only the resulting SCALE enters the
compiled int8 graph.
"""
from __future__ import annotations

from typing import Optional

import jax
import jax.numpy as jnp
import numpy as np

from ..core.tensor import Tensor
from ..nn.layer.layers import Layer


class BaseObserver(Layer):
    """Shared machinery: collect per-batch stats eagerly, expose scales()."""

    def __init__(self, quant_bits: int = 8):
        super().__init__()
        self.bit_length = quant_bits
        self.register_buffer("_scale", Tensor(np.zeros((), np.float32)))

    def _observe(self, arr: np.ndarray) -> None:
        raise NotImplementedError

    def _finalize(self) -> Optional[float]:
        """Optional deferred scale computation (hist/KL-style observers)."""
        return None

    def forward(self, x):
        if not isinstance(x._data, jax.core.Tracer):
            self._observe(np.asarray(x._data, dtype=np.float32))
        return x

    def scales(self):
        fin = self._finalize()
        if fin is not None:
            self._scale._data = jnp.asarray(np.float32(fin))
        return self._scale

    def quant_axis(self):
        return -1  # per-tensor


class AVGObserver(BaseObserver):
    """Scale = mean of per-batch absmax (reference: observers/avg.py)."""

    def __init__(self, quant_bits: int = 8, **kw):
        super().__init__(quant_bits)
        self._sum = 0.0
        self._n = 0

    def _observe(self, arr):
        self._sum += float(np.max(np.abs(arr))) if arr.size else 0.0
        self._n += 1

    def _finalize(self):
        return self._sum / self._n if self._n else None


class PercentileObserver(BaseObserver):
    """Scale = percentile of |x| over all calibration data (reference:
    hist observer's percentile mode, observers/hist.py)."""

    def __init__(self, quant_bits: int = 8, percentile: float = 99.99, **kw):
        super().__init__(quant_bits)
        self.percentile = percentile
        self._samples = []

    def _observe(self, arr):
        a = np.abs(arr).ravel()
        if a.size > 4096:  # bounded memory: per-batch subsample
            a = np.partition(a, a.size - 4096)[-4096:]
        self._samples.append(a)

    def _finalize(self):
        if not self._samples:
            return None
        allv = np.concatenate(self._samples)
        return float(np.percentile(allv, self.percentile))


class HistObserver(BaseObserver):
    """Histogram observer (reference: observers/hist.py): accumulate an
    |x| histogram across batches, pick the scale covering `percent` of
    mass. The histogram range grows by rebinning when a batch exceeds it."""

    def __init__(self, quant_bits: int = 8, bins_count: int = 2048,
                 percent: float = 0.999, **kw):
        super().__init__(quant_bits)
        self.bins_count = bins_count
        self.percent = percent
        self._hist = None
        self._hi = None

    def _observe(self, arr):
        a = np.abs(arr).ravel()
        if a.size == 0:
            return
        mx = float(a.max())
        if self._hist is None:
            self._hi = max(mx, 1e-8)
            self._hist, _ = np.histogram(a, bins=self.bins_count,
                                         range=(0.0, self._hi))
            return
        if mx > self._hi:
            # rebin the old histogram into the wider range (factor-of-2
            # growth keeps old bin edges aligned with new ones)
            new_hi = self._hi
            while new_hi < mx:
                new_hi *= 2.0
            factor = int(round(new_hi / self._hi))
            old = self._hist.astype(np.float64)
            grouped = old.reshape(self.bins_count // factor, factor).sum(1) \
                if self.bins_count % factor == 0 else None
            fresh = np.zeros(self.bins_count, np.float64)
            if grouped is not None:
                fresh[: grouped.size] = grouped
            else:  # non-divisible: linear redistribution
                idx = (np.arange(self.bins_count) / factor).astype(int)
                np.add.at(fresh, idx, old)
            self._hist = fresh
            self._hi = new_hi
        h, _ = np.histogram(a, bins=self.bins_count, range=(0.0, self._hi))
        self._hist = self._hist + h

    def _finalize(self):
        if self._hist is None:
            return None
        cdf = np.cumsum(self._hist)
        total = cdf[-1]
        if total == 0:
            return None
        k = int(np.searchsorted(cdf, self.percent * total))
        k = min(k, self.bins_count - 1)
        return (k + 0.5) * self._hi / self.bins_count


class MSEObserver(BaseObserver):
    """Scale minimising quantisation MSE over a shrink grid (reference:
    observers/mse.py)."""

    def __init__(self, quant_bits: int = 8, steps: int = 64, **kw):
        super().__init__(quant_bits)
        self.steps = steps
        self._samples = []

    def _observe(self, arr):
        a = arr.ravel()
        if a.size > 8192:
            a = np.random.RandomState(0).choice(a, 8192, replace=False)
        self._samples.append(a)

    def _finalize(self):
        if not self._samples:
            return None
        x = np.concatenate(self._samples)
        absmax = float(np.max(np.abs(x)))
        if absmax == 0.0:
            return None
        qmax = 2 ** (self.bit_length - 1) - 1
        best_s, best_mse = absmax, np.inf
        for i in range(1, self.steps + 1):
            s = absmax * i / self.steps
            q = np.clip(np.round(x / s * qmax), -qmax, qmax) * (s / qmax)
            mse = float(np.mean((x - q) ** 2))
            if mse < best_mse:
                best_mse, best_s = mse, s
        return best_s


class AbsMaxChannelWiseWeightObserver(BaseObserver):
    """Per-channel |w|max for WEIGHTS (reference:
    quanters/channel_wise_abs_max.py). `quant_axis` is the output-channel
    axis of the weight layout: 1 for Linear [in, out], 0 for Conv
    [out, in, kh, kw]."""

    def __init__(self, quant_bits: int = 8, quant_axis: int = 1, **kw):
        super().__init__(quant_bits)
        self._axis = quant_axis
        self._absmax = None

    def _observe(self, arr):
        axes = tuple(i for i in range(arr.ndim) if i != self._axis % arr.ndim)
        cur = np.max(np.abs(arr), axis=axes)
        self._absmax = cur if self._absmax is None else np.maximum(
            self._absmax, cur)

    def _finalize(self):
        return None  # scales() below returns the vector directly

    def scales(self):
        if self._absmax is not None:
            self._scale._data = jnp.asarray(self._absmax.astype(np.float32))
        return self._scale

    def quant_axis(self):
        return self._axis

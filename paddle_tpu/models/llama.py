"""Functional LLaMA-family decoder — the flagship model of the framework.

Role in the framework (SURVEY.md §6/§7): the reference's headline benchmark is
LLaMA-13B trained through fleet hybrid parallel (BASELINE.json config 4, built
in model code on top of fleet primitives: mp_layers.py ColumnParallelLinear /
RowParallelLinear / VocabParallelEmbedding, pipeline_parallel.py schedules).
Here the flagship is a pure-functional JAX model: a params pytree + jittable
forward/loss, designed so the hybrid-parallel engine
(paddle_tpu.distributed.hybrid) can shard the SAME pytree over a
('dp','pp','tp') mesh with shard_map — layers are stacked on a leading axis
(lax.scan-able, pp-splittable), and every projection is written so tp sharding
of its output/input dim is valid.

TPU-first choices: bf16 compute / f32 master params, static shapes, scan over
stacked layer params (one compiled block body, not L unrolled layers), GQA,
RoPE computed in f32, optional MoE (top-k routing; the hybrid engine dispatches
tokens with all_to_all over the ep axis).
"""
from __future__ import annotations

import dataclasses
import math
from typing import Any, Dict, Optional

import jax
import jax.numpy as jnp
from jax import lax


@dataclasses.dataclass(frozen=True)
class LlamaConfig:
    vocab_size: int = 32000
    hidden_size: int = 4096
    intermediate_size: int = 11008
    num_layers: int = 32
    num_heads: int = 32
    num_kv_heads: int = 32
    max_seq_len: int = 2048
    rope_theta: float = 10000.0
    rms_eps: float = 1e-5
    # MoE: 0 = dense MLP. When >0, every layer's MLP is a top-k gated MoE.
    num_experts: int = 0
    top_k: int = 2
    dtype: Any = jnp.bfloat16
    param_dtype: Any = jnp.float32

    @property
    def head_dim(self) -> int:
        return self.hidden_size // self.num_heads

    def num_params(self) -> int:
        d, f, v = self.hidden_size, self.intermediate_size, self.vocab_size
        hd = self.head_dim
        attn = d * self.num_heads * hd + 2 * d * self.num_kv_heads * hd + self.num_heads * hd * d
        if self.num_experts:
            mlp = self.num_experts * 3 * d * f + d * self.num_experts
        else:
            mlp = 3 * d * f
        per_layer = attn + mlp + 2 * d
        return v * d + self.num_layers * per_layer + d + d * v

    def flops_per_token(self) -> int:
        """Approximate training FLOPs/token (fwd+bwd ≈ 6·N_active)."""
        d, f = self.hidden_size, self.intermediate_size
        hd = self.head_dim
        attn = d * self.num_heads * hd + 2 * d * self.num_kv_heads * hd + self.num_heads * hd * d
        mlp = 3 * d * f * (min(self.top_k, self.num_experts) if self.num_experts else 1)
        dense = self.num_layers * (attn + mlp) + 2 * self.hidden_size * self.vocab_size
        return 6 * dense


# Predefined sizes (the reference's headline configs; LLaMA-7B/13B per
# BASELINE.json config 4).
CONFIGS = {
    "llama-test": LlamaConfig(vocab_size=256, hidden_size=64, intermediate_size=128,
                              num_layers=4, num_heads=4, num_kv_heads=2, max_seq_len=128),
    "llama-7b": LlamaConfig(hidden_size=4096, intermediate_size=11008, num_layers=32,
                            num_heads=32, num_kv_heads=32),
    "llama-13b": LlamaConfig(hidden_size=5120, intermediate_size=13824, num_layers=40,
                             num_heads=40, num_kv_heads=40),
}


def init_params(cfg: LlamaConfig, key: jax.Array) -> Dict[str, Any]:
    """Build the parameter pytree. Block params are stacked on a leading
    num_layers axis so the forward is a lax.scan and the pipeline engine can
    reshape to [pp, layers_per_stage, ...]."""
    d, f, v = cfg.hidden_size, cfg.intermediate_size, cfg.vocab_size
    hd, nh, nkv, L = cfg.head_dim, cfg.num_heads, cfg.num_kv_heads, cfg.num_layers
    pt = cfg.param_dtype
    keys = jax.random.split(key, 10)

    def normal(k, shape, scale=0.02):
        return (scale * jax.random.normal(k, shape, jnp.float32)).astype(pt)

    blocks = {
        "wq": normal(keys[0], (L, d, nh * hd)),
        "wk": normal(keys[1], (L, d, nkv * hd)),
        "wv": normal(keys[2], (L, d, nkv * hd)),
        "wo": normal(keys[3], (L, nh * hd, d)),
        "attn_norm": jnp.ones((L, d), pt),
        "mlp_norm": jnp.ones((L, d), pt),
    }
    if cfg.num_experts:
        e = cfg.num_experts
        blocks["router"] = normal(keys[4], (L, d, e))
        blocks["w1"] = normal(keys[5], (L, e, d, f))
        blocks["w3"] = normal(keys[6], (L, e, d, f))
        blocks["w2"] = normal(keys[7], (L, e, f, d))
    else:
        blocks["w1"] = normal(keys[5], (L, d, f))
        blocks["w3"] = normal(keys[6], (L, d, f))
        blocks["w2"] = normal(keys[7], (L, f, d))
    return {
        "embed": normal(keys[8], (v, d)),
        "blocks": blocks,
        "final_norm": jnp.ones((d,), pt),
        "lm_head": normal(keys[9], (d, v)),
    }


def rms_norm(x: jax.Array, w: jax.Array, eps: float) -> jax.Array:
    x32 = x.astype(jnp.float32)
    x32 = x32 * lax.rsqrt(jnp.mean(x32 * x32, axis=-1, keepdims=True) + eps)
    return (x32 * w.astype(jnp.float32)).astype(x.dtype)


def rope_cos_sin(positions: jax.Array, head_dim: int, theta: float):
    """positions [T] int → (cos, sin) [T, head_dim/2] in f32."""
    inv_freq = 1.0 / (theta ** (jnp.arange(0, head_dim, 2, dtype=jnp.float32) / head_dim))
    angles = positions.astype(jnp.float32)[:, None] * inv_freq[None, :]
    return jnp.cos(angles), jnp.sin(angles)


def apply_rope(x: jax.Array, cos: jax.Array, sin: jax.Array) -> jax.Array:
    """x [B, T, H, hd]; rotate-half convention, f32 math."""
    x32 = x.astype(jnp.float32)
    x1, x2 = jnp.split(x32, 2, axis=-1)
    c = cos[None, :, None, :]
    s = sin[None, :, None, :]
    out = jnp.concatenate([x1 * c - x2 * s, x2 * c + x1 * s], axis=-1)
    return out.astype(x.dtype)


def attention(q: jax.Array, k: jax.Array, v: jax.Array, impl: str = "auto") -> jax.Array:
    """Causal MHA/GQA. q [B,T,H,hd], k/v [B,T,KV,hd] → [B,T,H,hd].

    impl: 'auto' uses the Pallas flash kernel on TPU when available, else the
    XLA einsum path (which XLA fuses well on its own).
    """
    if impl == "flash":
        # explicit request: no silent fallback — unsupported shapes raise
        from ..ops.pallas import flash_attention as _fa

        return _fa.flash_attention(q, k, v, causal=True)
    if impl == "auto":
        try:
            from ..ops.pallas import flash_attention as _fa

            if (_fa.available() and q.shape[1] == k.shape[1]
                    and _fa.supported(q.shape, k.shape)):
                return _fa.flash_attention(q, k, v, causal=True)
        except ImportError:
            pass
    B, T, H, hd = q.shape
    KV = k.shape[2]
    if KV != H:
        k = jnp.repeat(k, H // KV, axis=2)
        v = jnp.repeat(v, H // KV, axis=2)
    scale = 1.0 / (hd ** 0.5)
    scores = jnp.einsum("bthd,bshd->bhts", q, k).astype(jnp.float32) * scale
    mask = jnp.tril(jnp.ones((T, T), bool))
    scores = jnp.where(mask[None, None], scores, -1e30)
    probs = jax.nn.softmax(scores, axis=-1).astype(q.dtype)
    return jnp.einsum("bhts,bshd->bthd", probs, v)


def moe_mlp(x: jax.Array, lp: Dict[str, jax.Array], cfg: LlamaConfig) -> jax.Array:
    """Dense (compute-all-experts) MoE for the single-device path. The hybrid
    engine replaces this with an all_to_all token dispatch over the ep axis."""
    gate = jax.nn.softmax(
        (x.astype(jnp.float32) @ lp["router"].astype(jnp.float32)), axis=-1)
    topw, topi = lax.top_k(gate, cfg.top_k)
    topw = topw / jnp.sum(topw, axis=-1, keepdims=True)
    # combine weights [B, T, E]
    comb = jnp.sum(jax.nn.one_hot(topi, cfg.num_experts, dtype=gate.dtype)
                   * topw[..., None], axis=-2)
    h = jnp.einsum("btd,edf->btef", x, lp["w1"].astype(x.dtype))
    g = jnp.einsum("btd,edf->btef", x, lp["w3"].astype(x.dtype))
    h = jax.nn.silu(h) * g
    out = jnp.einsum("btef,efd->bted", h, lp["w2"].astype(x.dtype))
    return jnp.einsum("bted,bte->btd", out, comb.astype(x.dtype))


def ffn(h: jax.Array, lp: Dict[str, jax.Array], impl: str = "stock") -> jax.Array:
    """SwiGLU FFN body over normed activations h [..., d].

    impl: 'stock' is the three-matmul XLA path; 'pallas' routes supported
    shapes through the one-launch fused kernel (ops/pallas/fused_ffn.py)
    and falls back to stock otherwise, mirroring attention's 'auto'.
    """
    if impl == "pallas":
        try:
            from ..ops.pallas import fused_ffn as _ff

            rows = math.prod(h.shape[:-1])
            d, f = lp["w1"].shape
            if _ff.supported(rows, d, f):
                return _ff.fused_ffn(h, lp["w1"].astype(h.dtype),
                                     lp["w3"].astype(h.dtype),
                                     lp["w2"].astype(h.dtype))
        except ImportError:
            pass
    gate = jax.nn.silu(h @ lp["w1"].astype(h.dtype)) * (h @ lp["w3"].astype(h.dtype))
    return gate @ lp["w2"].astype(h.dtype)


def block(x: jax.Array, lp: Dict[str, jax.Array], cfg: LlamaConfig,
          cos: jax.Array, sin: jax.Array, attn_impl: str = "auto",
          ffn_impl: str = "stock") -> jax.Array:
    """One transformer block; lp leaves have the layer axis already indexed."""
    B, T, d = x.shape
    hd, nh, nkv = cfg.head_dim, cfg.num_heads, cfg.num_kv_heads
    h = rms_norm(x, lp["attn_norm"], cfg.rms_eps)
    q = (h @ lp["wq"].astype(h.dtype)).reshape(B, T, nh, hd)
    k = (h @ lp["wk"].astype(h.dtype)).reshape(B, T, nkv, hd)
    v = (h @ lp["wv"].astype(h.dtype)).reshape(B, T, nkv, hd)
    q = apply_rope(q, cos, sin)
    k = apply_rope(k, cos, sin)
    o = attention(q, k, v, impl=attn_impl).reshape(B, T, nh * hd)
    x = x + o @ lp["wo"].astype(o.dtype)
    h = rms_norm(x, lp["mlp_norm"], cfg.rms_eps)
    if cfg.num_experts:
        x = x + moe_mlp(h, lp, cfg)
    else:
        x = x + ffn(h, lp, impl=ffn_impl)
    return x


def forward(params: Dict[str, Any], tokens: jax.Array, cfg: LlamaConfig,
            attn_impl: str = "auto", ffn_impl: str = "stock") -> jax.Array:
    """tokens [B, T] int32 → logits [B, T, vocab] (f32)."""
    x = jnp.take(params["embed"], tokens, axis=0).astype(cfg.dtype)
    T = tokens.shape[1]
    cos, sin = rope_cos_sin(jnp.arange(T), cfg.head_dim, cfg.rope_theta)

    def body(carry, lp):
        return block(carry, lp, cfg, cos, sin, attn_impl, ffn_impl), None

    x, _ = lax.scan(body, x, params["blocks"])
    x = rms_norm(x, params["final_norm"], cfg.rms_eps)
    return (x @ params["lm_head"].astype(x.dtype)).astype(jnp.float32)


def loss_fn(params: Dict[str, Any], tokens: jax.Array, targets: jax.Array,
            cfg: LlamaConfig, attn_impl: str = "auto",
            ffn_impl: str = "stock") -> jax.Array:
    """Next-token cross entropy, mean over tokens."""
    logits = forward(params, tokens, cfg, attn_impl, ffn_impl)
    logz = jax.scipy.special.logsumexp(logits, axis=-1)
    true = jnp.take_along_axis(logits, targets[..., None], axis=-1)[..., 0]
    return jnp.mean(logz - true)

"""Flagship model zoo (functional, shard_map-ready).

The Layer-based zoo lives in paddle_tpu.vision.models; this package holds the
pure-functional flagship models used by the hybrid-parallel engine, the graft
entry point and bench.py.
"""
from . import llama  # noqa: F401
from .llama import LlamaConfig  # noqa: F401

"""Elastic pipeline parallelism: survive stage death without a restart.

The dp axis got in-job elasticity in :mod:`.runtime` (TTL-leased
heartbeats, epoch-fenced collectives, ZeRO-1 reshard). This module is the
pp-axis companion — MPMD pipeline training (PAPERS.md: "Scaling Deep
Learning Training with MPMD Pipeline Parallelism", arXiv 2412.14374) makes
per-stage failure domains the norm, so a dead pipeline-stage replica must
shrink the pipeline, not kill the job.

Protocol (``ElasticPipelineRuntime``):

- **Detection** — one TTL heartbeat lease per physical stage group
  (:class:`~.membership.LocalMembership` over ``P_phys`` "ranks"). The
  guard installed into the pipeline dispatcher renews every live lease
  before each action; a stage replica that stops renewing mid-microbatch
  (a chaos ``pipeline:rank_dead``, or a real controller death in the
  multi-controller deployment) is declared dead by beat freshness alone.
- **Fence** — every :meth:`PipelineEngine.run` is stamped with the elastic
  epoch; each dispatch and P2P hop re-checks the stamp, so when the guard
  bumps the epoch the in-flight ``_send``/``_recv`` and stage executables
  raise :class:`EpochChangedError` at an action boundary instead of
  hanging on a dead stage's buffers. Grads/buffers only commit after the
  LAST action, so the abort drains the 1F1B queue to a consistent step
  boundary: model state is exactly the previous optimizer step.
- **Reconfigure** — epoch bump -> ``async_engine.abort_in_flight`` ->
  choose the largest feasible degree <= surviving stage groups ->
  re-express the layer stack through the stage-stacked blocks layout and
  :meth:`CheckpointManager.reshard_pp` (pure reshapes — bitwise, including
  every per-param optimizer accumulator stacked alongside) -> rebuild the
  engine at the new degree and re-validate its schedule from data
  (``validate()`` + ``simulate()``) before resuming.
- **Replay** — the caller-facing :meth:`ElasticPipelineRuntime.run`
  catches the fence, restores the RNG stream to the window start and
  replays the whole aborted accumulation window on the new engine. Because
  the abort left state at the previous step boundary and the migration is
  bitwise, the post-reconfigure losses are bit-exact vs an uninterrupted
  run that downscaled cleanly at the same boundary
  (:meth:`reshard_to` — the gate ``tools/elastic_pp_smoke.py`` checks
  ``loss_gap == 0.0``).

Scope (v1): physical stages only (``num_virtual_pipeline_stages == 1``),
homogeneous evenly-partitioned block stacks (the same contract as
``reshard_pp``/``hybrid.stack_pipeline``), and no layer buffers. ZeRO-1
flat bucket accumulators (``_dp_flat_b*``) are per-world pseudo-params and
are NOT migrated online — they re-initialize on the new engine's dp groups
(the per-param state that seeds them travels bitwise; the 3D
pp-shrink + dp-shrink checkpoint path is covered by ``reshard_pp`` tests).

Single-controller note: as with the dp axis, "stage replicas" are leases
of one process — drills revoke leases rather than kill OS processes, and
the machinery exercised (fence, abort, reshard, schedule rebuild, replay)
is exactly what per-stage controllers need.
"""
from __future__ import annotations

import time
from typing import Callable, List, Optional, Tuple

import jax
import jax.numpy as jnp
import numpy as np

from ...core import async_engine, flags, rng
from ...nn.layer.layers import Layer
from ...observability import emit as _emit
from ..fault_tolerance import chaos
from ..fault_tolerance.checkpoint_manager import CheckpointManager
from ..pipeline import runtime as pp_runtime
from ..pipeline import schedule as pschedule
from . import epoch as _epoch
from .epoch import EpochChangedError
from .membership import LocalMembership

flags.define_flag(
    "elastic_pp", False,
    "Enable elastic pipeline parallelism: per-stage TTL heartbeat leases, "
    "epoch-fenced pipeline runs, and on stage death an in-job reconfigure "
    "that reshards the layer stack to the surviving pp degree (bitwise, "
    "via reshard_pp) and replays the aborted accumulation window")


class ElasticPipelineError(RuntimeError):
    """The pipeline cannot be made elastic or reconfigured: heterogeneous
    block stack, layer buffers, virtual stages, or no feasible surviving
    degree. Raised eagerly at construction where possible so a job learns
    it is not elastic before the first failure, not during one."""


def maybe_start_pp(factory: Callable, pp: int,
                   **kw) -> Optional["ElasticPipelineRuntime"]:
    """The ``FLAGS_elastic_pp`` opt-in: build and start an
    :class:`ElasticPipelineRuntime` when the flag is on, else ``None``.
    ``factory(pp)`` must build a fresh ``(PipelineEngine, optimizer)`` (or
    a bare engine) at the given degree — it is re-invoked at every
    reconfiguration and its fresh-initialized state is overwritten with
    the bitwise-migrated stack."""
    if not flags.flag_value("elastic_pp"):
        return None
    return ElasticPipelineRuntime(factory, pp, **kw).start()


def _stage_param_rows(engine) -> List[List[List]]:
    """Per stage, the param lists of its param-bearing layers, in layer
    order — the stage-major flat view of the repeating block stack.
    Validates the elastic-pp contract: no buffers, every stage holds the
    same number of param layers, every param layer has the same param
    signature (so the stack restacks through ``reshard_pp``)."""
    rows = []
    for st in engine.stages:
        if st.buffers:
            raise ElasticPipelineError(
                f"elastic pp does not migrate layer buffers; stage "
                f"{st.index} holds {len(st.buffers)}")
        stage_rows = []
        for layer in st.layers:
            if isinstance(layer, Layer):
                ps = [p for _, p in layer.named_parameters()]
                if ps:
                    stage_rows.append(ps)
        rows.append(stage_rows)
    counts = {len(r) for r in rows}
    if len(counts) != 1 or 0 in counts:
        raise ElasticPipelineError(
            f"stages hold unequal param-layer counts {sorted(counts)}; "
            "elastic pp needs a homogeneous, evenly-partitioned block "
            "stack (the reshard_pp stage-stacked layout)")
    sig = None
    for stage_rows in rows:
        for params in stage_rows:
            s = [(tuple(p._data.shape), str(p._data.dtype)) for p in params]
            if sig is None:
                sig = s
            elif s != sig:
                raise ElasticPipelineError(
                    f"param-bearing layers are not homogeneous ({s} vs "
                    f"{sig}); elastic pp reshards through the stage-stacked "
                    "blocks layout, which needs identical repeating blocks")
    return rows


class ElasticPipelineRuntime:
    """One coordinator per pipeline-trained job. Wire it around the engine
    factory (NOT a prebuilt engine — the factory is how the runtime
    rebuilds at a new degree)::

        def factory(pp):
            model = PipelineLayer(layers=descs(), loss_fn=loss, num_stages=pp)
            engine = PipelineEngine(model, accumulate_steps=M)
            opt = paddle.optimizer.Adam(parameters=model.parameters())
            return engine, opt

        ert = ElasticPipelineRuntime(factory, pp=4).start()
        ...
        loss = ert.run(x, y, train=True)   # fenced + auto-replayed
        ert.optimizer.step(); ert.optimizer.clear_grad()

    ``ert.engine`` / ``ert.optimizer`` are swapped in place by a
    reconfiguration — always read them through the runtime.
    """

    def __init__(self, factory: Callable, pp: int, *, membership=None,
                 ttl: Optional[float] = None, min_pp: int = 1,
                 max_replays: int = 3):
        self.factory = factory
        self.min_pp = int(min_pp)
        self.max_replays = int(max_replays)
        if ttl is None:
            try:  # shared with the dp axis; defined by .runtime when loaded
                ttl = flags.flag_value("elastic_ttl")
            except KeyError:
                ttl = 6.0
        self.ttl = float(ttl)
        self.engine, self.optimizer = self._build(int(pp))
        if self.engine.P != self.engine.P_phys:
            raise ElasticPipelineError(
                "elastic pp supports physical stages only "
                f"(num_virtual_pipeline_stages == 1); got P={self.engine.P} "
                f"over P_phys={self.engine.P_phys} groups")
        rows = _stage_param_rows(self.engine)  # contract check, eagerly
        self._n_block_layers = sum(len(r) for r in rows)
        self._world = self.engine.P_phys
        self.membership = membership or LocalMembership(self._world,
                                                        ttl=self.ttl)
        self._started = False
        self._in_reconfigure = False
        self._prev_guard = None
        self._prev_kill = None
        self.reconfigurations = 0
        self.replays = 0

    # -- lifecycle ---------------------------------------------------------

    def start(self) -> "ElasticPipelineRuntime":
        """Install the dispatcher guard and the chaos rank-kill hook.
        Idempotent."""
        if self._started:
            return self
        self._started = True
        self._prev_guard = pp_runtime.set_elastic_guard(self._guard)
        self._prev_kill = chaos.set_rank_kill_hook(self._on_rank_dead)
        _emit("elastic.event", event="pp_start", world=self._world,
              ttl=self.ttl)
        return self

    def stop(self):
        """Restore the previous hooks and release the stage leases."""
        if not self._started:
            return
        self._started = False
        pp_runtime.set_elastic_guard(self._prev_guard)
        chaos.set_rank_kill_hook(self._prev_kill)
        self._prev_guard = self._prev_kill = None
        try:
            self.membership.close()
        except Exception:  # noqa: BLE001 — best-effort lease release
            pass

    close = stop

    def __enter__(self):
        return self.start()

    def __exit__(self, *exc):
        self.stop()

    # -- failure detection -------------------------------------------------

    def _on_rank_dead(self, victim: int, site: str):
        """chaos ``rank_dead``: a ``pipeline``-site victim names a STAGE
        replica — revoke its lease so the next dispatch's guard sees the
        lapsed beat. Other sites belong to the dp-axis runtime and are
        forwarded down the hook chain."""
        if site != "pipeline":
            prev = self._prev_kill
            if callable(prev):
                prev(victim, site)
            return
        _emit("elastic.event", event="stage_dead", victim=int(victim),
              site=site)
        self.membership.kill(int(victim), immediate=True)

    def _guard(self, phase: str, stage: int, microbatch: int):
        """Installed into the pipeline dispatcher while started: renew the
        surviving leases, and when one lapsed, reconfigure and fence the
        run. Death is judged by beat freshness alone — the guard never
        needs to be told WHO died, only that a lease went stale."""
        if self._in_reconfigure:
            return
        self.membership.beat()
        live = self.membership.live()
        if len(live) >= self._world:
            return
        dead = sorted(set(range(self._world)) - set(live))
        self._reconfigure(dead, reason=f"stage_dead:{phase}"
                                       f"@s{stage}m{microbatch}")
        raise EpochChangedError(
            f"pipeline stage replica(s) {dead} died; reconfigured to "
            f"pp={self.engine.P_phys} (epoch {_epoch.current()}) — replay "
            f"the accumulation window on the new engine")

    # -- reconfiguration ---------------------------------------------------

    def _feasible_degree(self, survivors: int) -> Optional[int]:
        """Largest pp degree that the block stack divides into, bounded by
        the surviving group count and ``min_pp``."""
        for d in range(min(survivors, self._world), 0, -1):
            if self._n_block_layers % d == 0 and d >= self.min_pp:
                return d
        return None

    def _reconfigure(self, dead: List[int], reason: str):
        survivors = self._world - len(dead)
        new_pp = self._feasible_degree(survivors)
        if new_pp is None:
            _emit("elastic.event", event="refuse", live=survivors,
                  min=self.min_pp, reason=reason)
            raise ElasticPipelineError(
                f"no feasible pipeline degree <= {survivors} surviving "
                f"groups (layers={self._n_block_layers}, "
                f"min_pp={self.min_pp})")
        self._do_reshard(new_pp, dead=dead, reason=reason)

    def reshard_to(self, new_pp: int,
                   reason: str = "planned") -> "pp_runtime.PipelineEngine":
        """Planned epoch-fenced re-partition at a step boundary — the same
        protocol as a death reconfigure minus the death (and what an
        uninterrupted run that downscaled cleanly looks like; the smoke
        gate compares a drill against exactly this). Returns the new
        engine."""
        new_pp = int(new_pp)
        if new_pp == self._world:
            return self.engine
        if new_pp < 1 or self._n_block_layers % new_pp:
            raise ElasticPipelineError(
                f"cannot re-partition {self._n_block_layers} block layers "
                f"to pp={new_pp}")
        self._do_reshard(new_pp, dead=[], reason=reason)
        return self.engine

    def _do_reshard(self, new_pp: int, dead: List[int], reason: str):
        """Epoch bump -> abort in-flight async work -> bitwise stage-state
        migration through reshard_pp -> fresh engine/optimizer at the new
        degree, schedule re-validated from data -> swap + fresh leases."""
        t0 = time.perf_counter()
        old_pp = self._world
        self._in_reconfigure = True
        try:
            new_epoch = _epoch.bump()
            aborted = async_engine.abort_in_flight(
                reason=f"elastic_pp:{reason}")
            state, acc_names, step_count = self._collect()
            state = CheckpointManager.reshard_pp(state, new_pp)
            engine, optimizer = self._build(new_pp)
            # schedules-as-data: prove the rebuilt schedule before resuming
            pschedule.validate(engine.actions, engine.P, engine.M,
                               schedule=engine.schedule)
            engine.schedule_stats = pschedule.simulate(
                engine.actions, engine.P, groups=engine.P_phys)
            self._install(engine, optimizer, state, acc_names, step_count)
            self.engine, self.optimizer = engine, optimizer
            self._world = engine.P_phys
            try:
                self.membership.close()
            except Exception:  # noqa: BLE001 — stale leases die with the TTL
                pass
            self.membership = LocalMembership(self._world, ttl=self.ttl)
            self.reconfigurations += 1
            dur = time.perf_counter() - t0
            _emit("elastic.reconfigure", dur_s=dur, world=new_pp,
                  old_world=old_pp, lost=dead, epoch=new_epoch,
                  aborted_async=aborted, reason=reason, axis="pp")
            print(f"[elastic] pipeline reconfigured: pp {old_pp} -> "
                  f"{new_pp} (dead stages {dead}, epoch {new_epoch}, "
                  f"{dur * 1e3:.0f} ms) reason={reason}", flush=True)
        finally:
            self._in_reconfigure = False

    # -- state migration ---------------------------------------------------

    def _build(self, pp: int) -> Tuple["pp_runtime.PipelineEngine", object]:
        out = self.factory(pp)
        if isinstance(out, tuple):
            engine, optimizer = out[0], (out[1] if len(out) > 1 else None)
        else:
            engine, optimizer = out, None
        return engine, optimizer

    def _collect(self):
        """The live engine's param stack (and every per-param optimizer
        accumulator) as a stage-stacked ``{"blocks": ...}`` pytree with
        ``[pp, L/pp, ...]`` leaves — the reshard_pp layout. Host copies
        via numpy are bitwise; ZeRO-1 flat bucket pseudo-params
        (``_dp_flat_b*``) are per-world and intentionally left behind."""
        rows = _stage_param_rows(self.engine)
        k = len(rows[0][0])
        blocks = {}
        for j in range(k):
            blocks[f"p{j}"] = np.stack([
                np.stack([np.asarray(params[j]._data)
                          for params in stage_rows])
                for stage_rows in rows])
        inner = getattr(self.optimizer, "inner", self.optimizer)
        accs = getattr(inner, "_accumulators", None) or {}
        acc_names: List[List[str]] = []
        for j in range(k):
            names = None
            for stage_rows in rows:
                for params in stage_rows:
                    have = sorted((accs.get(params[j].name) or {}).keys())
                    if names is None:
                        names = have
                    elif have != names:
                        raise ElasticPipelineError(
                            f"optimizer accumulators are not uniform across "
                            f"the block stack for param slot {j} ({have} "
                            f"vs {names})")
            acc_names.append(names or [])
            for an in acc_names[j]:
                blocks[f"p{j}.acc.{an}"] = np.stack([
                    np.stack([np.asarray(accs[params[j].name][an])
                              for params in stage_rows])
                    for stage_rows in rows])
        step_count = int(getattr(inner, "_step_count", 0) or 0)
        return {"blocks": blocks}, acc_names, step_count

    def _install(self, engine, optimizer, state, acc_names, step_count):
        """Overwrite the fresh engine's params (and seed its optimizer's
        accumulators, re-keyed positionally to the new param names) with
        the resharded stack, placed on each stage's devices. device_put of
        host arrays is bitwise for every fixed-width dtype."""
        rows = _stage_param_rows(engine)
        blocks = state["blocks"]
        inner = getattr(optimizer, "inner", optimizer)
        for s, stage_rows in enumerate(rows):
            repl = engine.stages[s].repl
            for l, params in enumerate(stage_rows):
                for j, p in enumerate(params):
                    p._data = jax.device_put(
                        jnp.asarray(blocks[f"p{j}"][s][l]), repl)
                    if inner is None:
                        continue
                    for an in acc_names[j]:
                        inner._accumulators.setdefault(p.name, {})[an] = \
                            jax.device_put(jnp.asarray(
                                blocks[f"p{j}.acc.{an}"][s][l]), repl)
        if inner is not None and any(acc_names):
            inner._step_count = step_count

    # -- the fenced, replaying run -----------------------------------------

    def run(self, inputs, labels, train: bool = True, **kw):
        """Epoch-fenced ``engine.run`` with microbatch-window replay: a
        world change mid-window aborts at an action boundary (state stays
        at the previous step), the RNG stream is rewound to the window
        start, and the whole accumulation window replays on the new
        engine — so the returned loss is the one an uninterrupted run at
        the new degree would have produced."""
        replays = 0
        while True:
            self.membership.beat()
            rng_state = rng.get_rng_state()
            try:
                return self.engine.run(inputs, labels, train=train, **kw)
            except EpochChangedError:
                rng.set_rng_state(rng_state)
                replays += 1
                self.replays += 1
                _emit("elastic.event", event="pp_replay", replays=replays)
                if replays > self.max_replays:
                    raise

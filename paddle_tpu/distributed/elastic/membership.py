"""Membership + failure detection for the elastic runtime.

Two implementations of the same small surface — ``live()``, ``beat()``,
``kill()``, ``revive()`` — so the runtime code is identical in the
single-controller CPU simulation and in a real store-backed multi-node job:

* :class:`LocalMembership` — in-process TTL leases, one per virtual rank.
  The single-controller test mode runs all N ranks in one process, so
  their "heartbeats" live in a dict; chaos ``rank_dead`` kills a lease the
  same way a dead process would stop refreshing an etcd lease.
* :class:`StoreMembership` — TTL-leased heartbeat keys on the TCPStore,
  absorbing the ``fleet.elastic.manager.ElasticManager`` mechanics
  (atomic slot allocation via ``add``, beat keys younger than ``ttl`` =
  live, every node running the same pure ``live()`` so survivors agree
  on the new world without a consensus round).

A rank id here is the rank's position in the ORIGINAL (launch-time)
world; the runtime maps live rank ids to devices when it rebuilds the
group, so survivors keep their relative order across a reconfiguration.
"""
from __future__ import annotations

import threading
import time
from typing import Dict, List, Optional

from ..fleet.elastic.manager import ElasticManager


def live_by_beat(beats: Dict[int, float], ttl: float,
                 now: Optional[float] = None) -> List[int]:
    """THE liveness judgment, as a pure function: a member is live iff its
    last beat is at most ``ttl`` seconds old. Both membership classes and
    the serving router's replica health checks
    (inference/serving/replica.py) run this same function, so "dead"
    means the same thing for a training rank and a serving replica."""
    if now is None:
        now = time.monotonic()
    return sorted(m for m, t in beats.items() if now - t <= ttl)


class LocalMembership:
    """TTL-leased membership for the single-controller simulation.

    Every virtual rank holds a lease refreshed by :meth:`beat` (the
    training loop ticks it once per step, standing in for each rank's
    heartbeat thread). ``kill(rank)`` revokes the lease — immediately by
    default (modeling a deleted etcd lease / closed connection), or
    silently (``immediate=False``) so death is only discovered when the
    TTL lapses, like a wedged host.
    """

    def __init__(self, world_size: int, ttl: float = 1.0):
        self.ttl = float(ttl)
        self._lock = threading.Lock()
        now = time.monotonic()
        self._beats: Dict[int, float] = {r: now for r in range(world_size)}
        self._alive = set(range(world_size))

    def beat(self, rank: Optional[int] = None):
        now = time.monotonic()
        with self._lock:
            ranks = self._alive if rank is None else [rank]
            for r in ranks:
                if r in self._alive:
                    self._beats[r] = now

    def kill(self, rank: int, immediate: bool = True):
        with self._lock:
            self._alive.discard(rank)
            if immediate:
                self._beats.pop(rank, None)

    def revive(self, rank: int):
        with self._lock:
            self._alive.add(rank)
            self._beats[rank] = time.monotonic()

    def live(self) -> List[int]:
        # liveness is judged by beat freshness alone: a silently-killed
        # rank (wedged host) keeps its stale beat until the TTL lapses,
        # an immediate kill (revoked lease) has no beat at all
        with self._lock:
            return live_by_beat(self._beats, self.ttl)

    def snapshot(self) -> dict:
        now = time.monotonic()
        with self._lock:
            return {
                "live": live_by_beat(self._beats, self.ttl, now),
                "ttl": self.ttl,
                "beat_age_s": {
                    str(r): round(now - t, 3)
                    for r, t in sorted(self._beats.items())},
            }

    def close(self):
        pass


class StoreMembership:
    """TTL-leased heartbeat keys on the TCPStore (ElasticManager engine).

    One instance per rank process. Registration claims a slot with the
    store's atomic ``add``; a daemon thread refreshes the beat key. The
    live set is recomputed from the store on every call, so all survivors
    run the same pure function and agree on the new world.
    """

    def __init__(self, store, job_id: str = "default", nnodes: str = "1:64",
                 node_id: Optional[str] = None, ttl: float = 6.0,
                 rank: int = 0):
        self._mgr = ElasticManager(store, job_id, nnodes=nnodes,
                                   node_id=node_id or f"rank{rank}", ttl=ttl)
        self.ttl = self._mgr.ttl
        self._mgr.register()

    def beat(self, rank: Optional[int] = None):
        self._mgr._beat()

    def kill(self, rank: int, immediate: bool = True):
        """Revoke a peer's lease (chaos / fencing a known-dead rank).

        With ``immediate`` the beat key is deleted so every survivor sees
        the death on its next poll instead of after a TTL.
        """
        if not immediate:
            return
        for _, node in self._mgr.live_nodes():
            if node == f"rank{rank}" or node.endswith(f":{rank}"):
                try:
                    self._mgr.store.delete_key(self._mgr._key("beat", node))
                except Exception:
                    pass

    def revive(self, rank: int):
        # a real rejoin is a fresh registration by the restarted process;
        # nothing to do on the survivor side
        self._mgr._beat()

    def live(self) -> List[int]:
        return [slot for slot, _ in self._mgr.live_nodes()]

    def snapshot(self) -> dict:
        live = self._mgr.live_nodes()
        return {"live": [s for s, _ in live],
                "nodes": [n for _, n in live],
                "ttl": self.ttl}

    def close(self):
        self._mgr.exit()

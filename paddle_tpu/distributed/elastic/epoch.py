"""Group-generation (epoch) fencing for collectives.

Every :class:`~paddle_tpu.distributed.collective.Group` is stamped with the
epoch that was current when it was built. An elastic reconfiguration bumps
the epoch, which makes every pre-existing group *stale*: the collective
retry wrapper refuses to issue on a stale group and refuses to retry a
failed collective across an epoch boundary — both raise
:class:`EpochChangedError` so the training loop can re-run the step on the
post-reconfiguration world instead of silently mixing results from two
different worlds.

Kept dependency-free (observability only) so ``collective.py`` can import
it without a cycle.
"""
from __future__ import annotations

import threading

_lock = threading.Lock()
_epoch = [0]


class EpochChangedError(RuntimeError):
    """The world was reconfigured under this collective.

    Deliberately NOT a TimeoutError/ConnectionError: the collective retry
    wrapper treats those as retryable, while an epoch change must surface
    to the training loop (re-issue the whole step on the new group).
    """


def current() -> int:
    return _epoch[0]


def bump() -> int:
    """Advance the group generation. Called only by the elastic runtime
    (and tests) at the start of a reconfiguration."""
    with _lock:
        _epoch[0] += 1
        e = _epoch[0]
    from ...observability import emit
    emit("elastic.event", event="epoch_bump", epoch=e)
    return e


def check(stamp: int, what: str = "collective"):
    """Raise EpochChangedError if `stamp` is no longer the current epoch."""
    cur = _epoch[0]
    if stamp != cur:
        raise EpochChangedError(
            f"{what} belongs to epoch {stamp} but the world was "
            f"reconfigured (current epoch {cur}); rebuild the group and "
            f"re-run the step on the new world")


def _reset_for_tests():
    with _lock:
        _epoch[0] = 0

"""ElasticRuntime: in-job world reconfiguration without a restart.

Ties the elastic pieces into one coordinator (reference frame: the
fleet elastic controller in `fleet/elastic/manager.py`, PyTorch's
torelastic rendezvous, and the in-job recovery loops of
fault-tolerant training systems):

- **Failure detection** — a :class:`~.membership.LocalMembership` /
  :class:`~.membership.StoreMembership` tracks TTL-leased heartbeats.
  Two independent signals resolve to the same verdict ("the world
  changed"): a missed heartbeat observed by the comm-watchdog's
  ``elastic`` ladder stage, and a collective timeout whose retry
  wrapper consults :func:`maybe_reconfigure` through
  ``collective.set_world_changed_hook``.
- **Epoch fencing** — every reconfiguration bumps the group
  generation (:mod:`.epoch`); stale groups refuse to issue, in-flight
  async work is aborted (``async_engine.abort_in_flight``), and the
  collective retry wrapper raises :class:`EpochChangedError` instead
  of retrying across the fence.
- **Reconfiguration** — survivors agree on the live set, a new
  :class:`~..collective.Group` over the surviving devices replaces the
  default group, the DP reducer's bucket plans and flat-buffer
  executables are rebuilt for the new world size, and ZeRO-1 optimizer
  state is resharded in place (``ShardedUpdate.reshard``) — falling
  back to the checkpoint manager's last-good snapshot when in-place
  state is unusable.
- **Rejoin** — a restarted rank re-registers (heartbeats resume); the
  grow is deferred to the next step boundary (checkpoint manager
  step-boundary hook) so a rank is only re-admitted between steps,
  after catching up from the latest checkpoint.

Single-controller note: under the CPU/TPU single-controller runtime all
"ranks" are devices of one process, so kill/rejoin drills manipulate
heartbeat leases rather than OS processes — the reconfiguration
machinery (epoch fence, group rebuild, reshard, metrics) is exactly
what a multi-controller deployment exercises.

This runtime covers the DP axis. Pipeline-stage death (the pp axis) is
handled by the companion coordinator in :mod:`.pipeline`
(``FLAGS_elastic_pp``), which reuses the same TTL-lease membership and
epoch fence to abort, reshard and replay a 1F1B accumulation window.
"""
from __future__ import annotations

import threading
import time
from typing import List, Optional

from ...core import flags
from ...core import async_engine
from ...observability import emit as _emit
from .. import collective as coll
from .. import comm_watchdog as cw
from ..fault_tolerance import chaos
from . import epoch as _epoch
from .membership import LocalMembership, StoreMembership

flags.define_flag("elastic", False,
                  "Enable the elastic training runtime: heartbeat failure "
                  "detection, epoch-fenced collectives and in-job world "
                  "reconfiguration (replaces the fleet ElasticManager "
                  "restart loop)")
flags.define_flag("elastic_heartbeat_interval", 2.0,
                  "Seconds between elastic heartbeats (store mode beats at "
                  "ttl/3 regardless; local mode uses this)")
flags.define_flag("elastic_ttl", 6.0,
                  "Heartbeat lease TTL in seconds: a rank whose beat is "
                  "older than this is declared dead "
                  "(was PADDLE_ELASTIC_TTL)")
flags.define_flag("elastic_min_nnodes", 1,
                  "Smallest world size reconfiguration may shrink to; "
                  "below this the runtime refuses and escalation proceeds "
                  "to restart")
flags.define_flag("elastic_max_nnodes", 0,
                  "Largest world size rejoin may grow to (0 = the launch "
                  "world size)")


def maybe_start(model=None, optimizer=None, checkpoint_manager=None,
                group=None, **kw) -> Optional["ElasticRuntime"]:
    """The ``FLAGS_elastic`` opt-in: build and start an
    :class:`ElasticRuntime` when the flag is on, else return ``None``.
    Trainer integrations call this once after wiring model/optimizer so
    a flag flip is all it takes to go elastic."""
    if not flags.flag_value("elastic"):
        return None
    return ElasticRuntime(model=model, optimizer=optimizer,
                          checkpoint_manager=checkpoint_manager,
                          group=group, **kw).start()


class ElasticRuntime:
    """One coordinator per training job. Wire it up after building the
    model/optimizer/checkpoint-manager:

        runtime = ElasticRuntime(model=dp_model, optimizer=sharded_opt,
                                 checkpoint_manager=cm, group=g)
        runtime.start()
        ...
        try:
            loss = train_step(...)
        except EpochChangedError:
            optimizer.clear_grad()   # world changed mid-step: re-run
            continue
        cm.on_step(loss)             # step boundary: deferred grows apply
    """

    def __init__(self, model=None, optimizer=None, checkpoint_manager=None,
                 group: Optional[coll.Group] = None,
                 membership=None, ttl: Optional[float] = None,
                 min_world: Optional[int] = None,
                 max_world: Optional[int] = None):
        self.model = model                      # DataParallel (or None)
        self.optimizer = optimizer              # ShardedUpdate / Optimizer
        self.checkpoint_manager = checkpoint_manager
        self.group = group if group is not None else coll.get_group(0)
        self._launch_world = getattr(self.group, "nranks", 1) \
            if self.group is not None else 1
        ttl = float(flags.flag_value("elastic_ttl") if ttl is None else ttl)
        self.ttl = ttl
        self.min_world = int(flags.flag_value("elastic_min_nnodes")
                             if min_world is None else min_world)
        mx = int(flags.flag_value("elastic_max_nnodes")
                 if max_world is None else max_world)
        self.max_world = mx if mx > 0 else self._launch_world
        self.membership = membership or LocalMembership(
            self._launch_world, ttl=ttl)
        self._lock = threading.RLock()
        self._started = False
        self._prev_hooks = {}
        self._pending_grow = False
        self._fleet_pub = None   # lazy FleetPublisher (store mode only)
        self.reconfigurations = 0
        self.rejoins = 0

    # -- lifecycle ---------------------------------------------------------

    def start(self) -> "ElasticRuntime":
        """Register the failure-detection hooks. Idempotent."""
        with self._lock:
            if self._started:
                return self
            self._started = True
            self._prev_hooks = {
                "elastic": cw.set_elastic_hook(self._watchdog_elastic),
                "membership": cw.set_membership_fn(self.membership_snapshot),
                "world_changed": coll.set_world_changed_hook(
                    self._on_collective_failure),
                "live_world": coll.set_live_world_fn(
                    lambda: len(self.membership.live())),
                "rank_kill": chaos.set_rank_kill_hook(self._chaos_kill),
            }
            from ..fault_tolerance import checkpoint_manager as _cm_mod

            self._prev_hooks["step_boundary"] = \
                _cm_mod.set_step_boundary_hook(self.note_step)
            _emit("elastic.event", event="start",
                  world=self._launch_world, ttl=self.ttl)
            _emit("elastic.world", world=len(self.membership.live()))
        return self

    def stop(self):
        """Unregister every hook (restoring whatever was there before)."""
        with self._lock:
            if not self._started:
                return
            self._started = False
            prev = self._prev_hooks
            cw.set_elastic_hook(prev.get("elastic"))
            cw.set_membership_fn(prev.get("membership"))
            coll.set_world_changed_hook(prev.get("world_changed"))
            coll.set_live_world_fn(prev.get("live_world"))
            chaos.set_rank_kill_hook(prev.get("rank_kill"))
            from ..fault_tolerance import checkpoint_manager as _cm_mod

            _cm_mod.set_step_boundary_hook(prev.get("step_boundary"))
            self._prev_hooks = {}
            try:
                self.membership.close()
            except Exception:  # noqa: BLE001 — best-effort lease release
                pass

    close = stop

    def __enter__(self):
        return self.start()

    def __exit__(self, *exc):
        self.stop()

    # -- failure-detection entry points ------------------------------------

    def membership_snapshot(self) -> dict:
        snap = self.membership.snapshot()
        snap["world"] = getattr(self.group, "nranks", 1)
        snap["epoch"] = _epoch.current()
        return snap

    def _chaos_kill(self, victim: int, site: str):
        """chaos ``rank_dead`` landed: revoke the victim's lease so the
        next verdict (watchdog stage or collective-failure hook) sees a
        changed world.

        ``pipeline``-site deaths name a STAGE replica, not a dp rank —
        they belong to the pp-axis runtime (:mod:`.pipeline`), so they are
        forwarded down the hook chain instead of killing a dp lease that
        happens to share the victim's number."""
        if site == "pipeline":
            prev = self._prev_hooks.get("rank_kill")
            if callable(prev):
                prev(victim, site)
            return
        _emit("elastic.event", event="rank_dead", victim=victim, site=site)
        self.membership.kill(victim, immediate=True)

    def _watchdog_elastic(self) -> bool:
        """The watchdog ladder's ``elastic`` stage: a collective has hung
        past the retry stage — check membership and reconfigure if the
        world shrank. True tells the ladder the hung task can be retired
        (the blocked call unwinds through the epoch fence)."""
        return self.maybe_reconfigure(reason="watchdog")

    def _on_collective_failure(self, op: str, gid: int, rank: int,
                               exc: BaseException) -> bool:
        """Collective retry wrapper verdict: did this failure mean the
        world changed? True aborts the retry with EpochChangedError."""
        return self.maybe_reconfigure(reason=f"collective:{op}")

    # -- reconfiguration ---------------------------------------------------

    def maybe_reconfigure(self, reason: str = "manual") -> bool:
        """Compare the live membership against the current group; if a
        rank's lease lapsed, run the shrink protocol. Returns True when a
        reconfiguration ran (the epoch was bumped)."""
        with self._lock:
            live = self.membership.live()
            cur = list(getattr(self.group, "ranks", range(
                getattr(self.group, "nranks", 1))))
            if live == cur:
                return False
            lost = sorted(set(cur) - set(live))
            if not lost:
                # grow-only change: defer to the step boundary
                self._pending_grow = True
                return False
            if len(live) < max(1, self.min_world):
                _emit("elastic.event", event="refuse",
                      live=len(live), min=self.min_world, reason=reason)
                return False
            self._reconfigure(live, lost=lost, reason=reason)
            return True

    def _reconfigure(self, live: List[int], lost: List[int], reason: str):
        """The shrink/grow protocol (caller holds the lock):
        epoch bump -> abort queued async work -> survivors barrier
        (store mode) -> new group over the live devices -> DP rebind ->
        ZeRO-1 reshard -> publish."""
        t0 = time.perf_counter()
        old_world = getattr(self.group, "nranks", 1)
        new_epoch = _epoch.bump()
        aborted = async_engine.abort_in_flight(reason=f"elastic:{reason}")
        self._survivor_barrier(new_epoch, live)
        g = coll.new_group(live)       # stamped with the NEW epoch
        coll.replace_default_group(g)
        self.group = g
        self._reshard(g)               # also rebinds the DP model
        self._pending_grow = False
        self.reconfigurations += 1
        dur = time.perf_counter() - t0
        _emit("elastic.reconfigure", dur_s=dur, world=len(live),
              old_world=old_world, lost=lost, epoch=new_epoch,
              aborted_async=aborted, reason=reason)
        print(f"[elastic] reconfigured: world {old_world} -> {len(live)} "
              f"(lost ranks {lost}, epoch {new_epoch}, "
              f"{dur * 1e3:.0f} ms) reason={reason}", flush=True)

    def _survivor_barrier(self, new_epoch: int, live: List[int]):
        """Store-backed survivors' barrier: every survivor checks in under
        the new epoch before the group is rebuilt. Local membership (one
        controller) has nothing to agree on — skip."""
        mgr = getattr(self.membership, "_mgr", None)
        if mgr is None:
            return
        try:
            store = mgr.store
            key = f"{mgr.prefix}/reconf/{new_epoch}"
            store.barrier(key, timeout=self.ttl * 4,
                          world_size=len(live))
        except Exception as e:  # noqa: BLE001 — a survivor that cannot
            # reach the store will be caught by its own watchdog; the
            # reconfiguration proceeds on this side
            _emit("elastic.event", event="barrier_error",
                  error=f"{type(e).__name__}: {e}")

    def _reshard(self, g: coll.Group):
        """ZeRO-1 optimizer-state reshard for the new world.

        Preferred path: ``ShardedUpdate.reshard`` slices/re-pads the
        flat accumulators in place AND rebinds the model's group (it
        needs the old bucket plan, so the model must not be rebound
        first). Fallback (plain optimizer, or reshard failure): roll
        back to the checkpoint manager's last-good snapshot, drop any
        stale flat-bucket accumulators (they re-initialize at the new
        padded size), and rebind the model."""
        opt = self.optimizer
        reshard = getattr(opt, "reshard", None) if opt is not None else None
        if callable(reshard):
            try:
                reshard(g)
                return
            except Exception as e:  # noqa: BLE001 — fall through to the
                # checkpoint path; training correctness beats speed here
                _emit("elastic.event", event="reshard_error",
                      error=f"{type(e).__name__}: {e}")
        cm = self.checkpoint_manager
        if cm is not None:
            restored = None
            try:
                restored = cm.restore_last_good()
            except Exception as e:  # noqa: BLE001
                _emit("elastic.event", event="restore_error",
                      error=f"{type(e).__name__}: {e}")
            _emit("elastic.event", event="state_restore", step=restored)
        inner = getattr(opt, "inner", opt)
        accs = getattr(inner, "_accumulators", None)
        if accs:
            # flat pseudo-param state is padded for the OLD world size;
            # without an in-place reshard it can only be re-initialized
            for pn in [k for k in accs if k.startswith("_dp_flat_b")]:
                del accs[pn]
            for cache in ("_fused_cache", "_fused_seen"):
                c = getattr(inner, cache, None)
                if c is not None:
                    c.clear()
        if self.model is not None and hasattr(self.model, "rebind_group"):
            self.model.rebind_group(g)

    # -- rejoin ------------------------------------------------------------

    def rejoin(self, rank: int) -> bool:
        """A restarted rank is back: revive its lease and schedule the
        grow for the next step boundary. Returns False when the grow
        would exceed ``max_world``."""
        with self._lock:
            live = set(self.membership.live())
            if rank not in live and len(live) >= self.max_world:
                _emit("elastic.event", event="rejoin_refused", rank=rank,
                      max=self.max_world)
                return False
            self.membership.revive(rank)
            self._pending_grow = True
            self.rejoins += 1
            _emit("elastic.event", event="rejoin", rank=rank)
            return True

    def _maybe_publish_fleet(self):
        """Push this rank's metrics snapshot to the store on the fleet
        cadence (FLAGS_fleet_metrics_interval), riding the same step
        boundary as the heartbeat — any rank (or an external aggregator)
        can then merge the snapshots into ``fleet_summary()``. Local
        membership has no store: nothing to publish to."""
        mgr = getattr(self.membership, "_mgr", None)
        if mgr is None:
            return
        if self._fleet_pub is None:
            from ...observability import fleet as _fleet

            rank = mgr._slot if mgr._slot is not None else 0
            self._fleet_pub = _fleet.FleetPublisher(
                mgr.store, rank, role="trainer")
        try:
            self._fleet_pub.maybe_publish()
        except Exception as e:  # noqa: BLE001 — metrics export must never
            # take down a training step; the watchdog owns store outages
            _emit("elastic.event", event="fleet_publish_error",
                  error=f"{type(e).__name__}: {e}")

    def note_step(self, step: int):
        """Step-boundary hook (wired to the checkpoint manager): apply a
        deferred grow — rejoining ranks are only admitted here, never
        mid-step."""
        with self._lock:
            self.membership.beat()
            self._maybe_publish_fleet()
            if not self._pending_grow:
                return
            live = self.membership.live()
            cur = list(getattr(self.group, "ranks", range(
                getattr(self.group, "nranks", 1))))
            if live == cur:
                self._pending_grow = False
                return
            if len(live) > self.max_world:
                live = live[:self.max_world]
            grown = sorted(set(live) - set(cur))
            self._reconfigure(live, lost=sorted(set(cur) - set(live)),
                              reason=f"step_boundary:grow={grown}")

"""TPU-native elastic training runtime.

Turns rank failure from a job-killer into a bounded in-job reconfiguration:

* :mod:`.epoch` — group-generation fencing for collectives (imported by
  ``collective.py``; must stay dependency-free).
* :mod:`.membership` — TTL-leased heartbeat membership (in-process for the
  single-controller simulation, TCPStore-backed for real jobs).
* :mod:`.runtime` — :class:`ElasticRuntime`: failure verdicts, world
  reconfiguration (epoch bump → queue flush → new group → DP rebind →
  ZeRO-1 reshard), and step-boundary rejoin.
* :mod:`.pipeline` — :class:`ElasticPipelineRuntime`: the pp-axis
  counterpart (``FLAGS_elastic_pp``): stage-death detection via the same
  TTL leases, epoch-fenced pipeline runs, bitwise re-partition of the
  layer stack to the surviving degree, and accumulation-window replay.

Everything except ``epoch`` is imported lazily: ``collective.py`` imports
this package at module-init time, and ``runtime`` imports ``collective``
back — eager imports here would cycle.
"""
from .epoch import EpochChangedError  # noqa: F401 — dependency-free

_LAZY = {
    "LocalMembership": "membership",
    "StoreMembership": "membership",
    "ElasticRuntime": "runtime",
    "maybe_start": "runtime",
    "ElasticPipelineRuntime": "pipeline",
    "ElasticPipelineError": "pipeline",
    "maybe_start_pp": "pipeline",
    "epoch": None,
    "membership": None,
    "runtime": None,
    "pipeline": None,
}

__all__ = ["EpochChangedError", "ElasticRuntime", "LocalMembership",
           "StoreMembership", "maybe_start", "ElasticPipelineRuntime",
           "ElasticPipelineError", "maybe_start_pp", "epoch", "membership",
           "runtime", "pipeline"]


def __getattr__(name):
    if name in _LAZY:
        import importlib

        mod_name = _LAZY[name] or name
        mod = importlib.import_module(f".{mod_name}", __name__)
        return mod if _LAZY[name] is None else getattr(mod, name)
    raise AttributeError(f"module {__name__!r} has no attribute {name!r}")

"""TCPStore — rendezvous KV store for multi-process bootstrap.

Reference: `paddle/phi/core/distributed/store/tcp_store.h:121` (master socket
server + clients) exposed as `paddle.distributed.TCPStore`. The native C++
server/client lives in paddle_tpu/core/native/src/native.cc; a pure-Python
socket implementation with the same wire protocol is the fallback when the
toolchain is unavailable.

Used by the launcher (paddle_tpu.distributed.launch) for rank assignment and
by `init_parallel_env` multi-host bootstrap alongside the PJRT coordination
service.
"""
from __future__ import annotations

import os
import socket
import struct
import threading
import time
from collections import OrderedDict
from typing import Optional

from ..core import flags
from ..core.native import (NativeStoreClient, NativeStoreServer,
                           available as _native_available)
from ..observability import emit as _emit

flags.define_flag("store_retries", 2,
                  "Bounded reconnect+retry attempts for idempotent TCPStore "
                  "ops after a transport error; 0 disables. get/check/wait "
                  "and set are value-idempotent; add rides a per-call "
                  "idempotency token the server deduplicates, so a replayed "
                  "increment returns the recorded result instead of "
                  "double-counting")
flags.define_flag("store_retry_backoff", 0.05,
                  "Base seconds for exponential backoff between TCPStore "
                  "retries (doubles per attempt)")

# chaos choke point: installed by distributed/fault_tolerance/chaos.py only
# while FLAGS_chaos_spec is active — (op_name) -> 'drop' | 'garble' | None
_chaos_hook = [None]


def set_chaos_hook(fn):
    _chaos_hook[0] = fn


_OP_NAMES = {0: "set", 1: "get", 2: "add", 3: "check", 4: "delete",
             5: "ping", 6: "add"}  # 6 = ADD_TOKEN: add w/ idempotency token

# server-side dedup: how many applied idempotency tokens to remember (FIFO;
# a token only needs to survive its own retry window)
_TOKEN_WINDOW = 4096

# replies larger than this are corruption, not data — the server frames
# every reply with a <Q length, and a garbled frame shows up here first
_MAX_PLAUSIBLE_REPLY = 1 << 30


class _PyStoreServer:
    """Pure-Python server speaking the native wire protocol."""

    def __init__(self, port: int):
        self._kv = {}
        self._applied = OrderedDict()  # idempotency token -> ADD result
        self._cv = threading.Condition()
        self._stop = False
        self._sock = socket.socket(socket.AF_INET, socket.SOCK_STREAM)
        self._sock.setsockopt(socket.SOL_SOCKET, socket.SO_REUSEADDR, 1)
        self._sock.bind(("0.0.0.0", port))
        self.port = self._sock.getsockname()[1]
        self._sock.listen(128)
        self._thread = threading.Thread(target=self._accept, daemon=True)
        self._thread.start()

    def _accept(self):
        while not self._stop:
            try:
                conn, _ = self._sock.accept()
            except OSError:
                return
            threading.Thread(target=self._serve, args=(conn,),
                             daemon=True).start()

    def _read(self, conn, n):
        buf = b""
        while len(buf) < n:
            chunk = conn.recv(n - len(buf))
            if not chunk:
                raise ConnectionError("eof")
            buf += chunk
        return buf

    def _serve(self, conn):
        try:
            while True:
                op = self._read(conn, 1)[0]
                klen = struct.unpack("<I", self._read(conn, 4))[0]
                key = self._read(conn, klen).decode()
                vlen = struct.unpack("<Q", self._read(conn, 8))[0]
                val = self._read(conn, vlen) if vlen else b""
                if op == 0:  # SET
                    with self._cv:
                        self._kv[key] = val
                        self._cv.notify_all()
                    conn.sendall(struct.pack("<Q", 0))
                elif op == 1:  # GET blocking
                    with self._cv:
                        while key not in self._kv and not self._stop:
                            self._cv.wait(0.1)
                        v = self._kv.get(key, b"")
                    conn.sendall(struct.pack("<Q", len(v)) + v)
                elif op == 2:  # ADD
                    delta = struct.unpack("<q", val[:8])[0]
                    with self._cv:
                        cur = struct.unpack(
                            "<q", self._kv.get(key, b"\0" * 8)[:8])[0]
                        now = cur + delta
                        self._kv[key] = struct.pack("<q", now)
                        self._cv.notify_all()
                    conn.sendall(struct.pack("<Q", 8) + struct.pack("<q", now))
                elif op == 6:  # ADD_TOKEN: val = <q delta + idempotency token
                    delta = struct.unpack("<q", val[:8])[0]
                    token = val[8:]
                    with self._cv:
                        if token and token in self._applied:
                            now = self._applied[token]  # replayed: no-op
                        else:
                            cur = struct.unpack(
                                "<q", self._kv.get(key, b"\0" * 8)[:8])[0]
                            now = cur + delta
                            self._kv[key] = struct.pack("<q", now)
                            if token:
                                self._applied[token] = now
                                while len(self._applied) > _TOKEN_WINDOW:
                                    self._applied.popitem(last=False)
                        self._cv.notify_all()
                    conn.sendall(struct.pack("<Q", 8) + struct.pack("<q", now))
                elif op == 3:  # CHECK
                    with self._cv:
                        p = b"\x01" if key in self._kv else b"\x00"
                    conn.sendall(struct.pack("<Q", 1) + p)
                elif op == 4:  # DELETE
                    with self._cv:
                        self._kv.pop(key, None)
                    conn.sendall(struct.pack("<Q", 0))
                elif op == 5:  # PING
                    conn.sendall(struct.pack("<Q", 0))
                else:
                    return
        except (ConnectionError, OSError):
            pass
        finally:
            conn.close()

    def stop(self):
        self._stop = True
        try:
            self._sock.close()
        except OSError:
            pass


class _PyStoreClient:
    def __init__(self, host: str, port: int, timeout_ms: int = 30000):
        self._host = host
        self._port = port
        self._lock = threading.Lock()
        deadline = time.time() + timeout_ms / 1000.0
        last = None
        while True:
            try:
                self._connect()
                return
            except OSError as e:
                last = e
                if time.time() > deadline:
                    raise ConnectionError(
                        f"cannot connect TCPStore {host}:{port}") from last
                time.sleep(0.05)

    def _connect(self):
        self._sock = socket.create_connection((self._host, self._port),
                                              timeout=5)
        self._sock.setsockopt(socket.IPPROTO_TCP, socket.TCP_NODELAY, 1)
        self._sock.settimeout(None)

    def _reconnect(self):
        """Drop the (possibly poisoned) socket and dial a fresh one — one
        dropped recv must not poison the client forever."""
        with self._lock:
            try:
                self._sock.close()
            except OSError:
                pass
            self._connect()

    def _read(self, n):
        buf = b""
        while len(buf) < n:
            chunk = self._sock.recv(n - len(buf))
            if not chunk:
                raise ConnectionError("eof")
            buf += chunk
        return buf

    def _req(self, op: int, key: str, val: bytes = b"") -> bytes:
        fault = None
        ch = _chaos_hook[0]
        if ch is not None:
            fault = ch(_OP_NAMES.get(op, str(op)))
            if fault == "drop":
                try:
                    self._sock.close()
                except OSError:
                    pass
                raise ConnectionError(
                    "[chaos] injected TCPStore connection drop")
        with self._lock:
            k = key.encode()
            self._sock.sendall(bytes([op]) + struct.pack("<I", len(k)) + k
                               + struct.pack("<Q", len(val)) + val)
            rlen = struct.unpack("<Q", self._read(8))[0]
            if fault == "garble":
                rlen |= 1 << 40  # corrupt the frame length in flight
            if rlen > _MAX_PLAUSIBLE_REPLY:
                # desynced/corrupt frame: poison the socket so a retry
                # reconnects instead of reading garbage as data
                try:
                    self._sock.close()
                except OSError:
                    pass
                raise ConnectionError(
                    f"TCPStore reply length {rlen} is implausible "
                    f"(corrupt frame)")
            return self._read(rlen) if rlen else b""

    def set(self, key, value):
        self._req(0, key, value)

    def get(self, key, max_len=1 << 20):
        return self._req(1, key)

    def add(self, key, delta):
        return struct.unpack("<q", self._req(2, key,
                                             struct.pack("<q", delta)))[0]

    def add_token(self, key, delta, token: bytes):
        """ADD with a per-call idempotency token: the server applies the
        increment once and records token -> result, so a retried call after
        an ambiguous failure returns the recorded result."""
        return struct.unpack(
            "<q", self._req(6, key, struct.pack("<q", delta) + token))[0]

    def check(self, key):
        return self._req(3, key) == b"\x01"

    def delete(self, key):
        self._req(4, key)

    def close(self):
        # serialize with an in-flight _req when possible: closing mid-request
        # turns the requester's recv into a spurious ConnectionError on
        # another thread. Bounded acquire — a thread stuck in a BLOCKING get
        # must still be interruptible by close (no deadlock).
        acquired = self._lock.acquire(timeout=0.5)
        try:
            self._sock.close()
        except OSError:
            pass
        finally:
            if acquired:
                self._lock.release()


class TCPStore:
    """paddle.distributed.TCPStore parity: master hosts the server; every
    process is a client. `wait`/`barrier` build on blocking get + counters."""

    def __init__(self, host: str, port: int, is_master: bool = False,
                 world_size: int = 1, timeout: float = 900.0,
                 use_native: Optional[bool] = None):
        self.host = host
        self.port = port
        self.is_master = is_master
        self.world_size = world_size
        native = _native_available() if use_native is None else use_native
        self._server = None
        if is_master:
            if native:
                try:
                    self._server = NativeStoreServer(port)
                except OSError:
                    native = False
                    self._server = _PyStoreServer(port)
            else:
                self._server = _PyStoreServer(port)
        if native:
            try:
                self._client = NativeStoreClient(host, port,
                                                 int(timeout * 1000))
            except (RuntimeError, ConnectionError):
                self._client = _PyStoreClient(host, port, int(timeout * 1000))
        else:
            self._client = _PyStoreClient(host, port, int(timeout * 1000))
        self.native = isinstance(self._client, NativeStoreClient)
        self._timeout_ms = int(timeout * 1000)
        self._barrier_gen = 0

    def _reconnect(self):
        c = self._client
        if hasattr(c, "_reconnect"):
            c._reconnect()
        else:
            # native client has no reconnect entry point: rebuild it
            self._client = type(c)(self.host, self.port, self._timeout_ms)

    def _retry_idempotent(self, opname: str, fn):
        """Bounded reconnect+retry with backoff, for idempotent ops only:
        get/check/wait and set are value-idempotent, and add goes through
        ADD_TOKEN (the server deduplicates the per-call token, so a replay
        can't double-count)."""
        retries = max(0, int(flags.flag_value("store_retries")))
        attempt = 0
        while True:
            try:
                return fn()
            except (ConnectionError, OSError) as e:
                attempt += 1
                if attempt > retries:
                    raise
                _emit("store.retry", op=opname, attempt=attempt,
                      error=f"{type(e).__name__}: {e}")
                time.sleep(float(flags.flag_value("store_retry_backoff"))
                           * (2 ** (attempt - 1)))
                try:
                    self._reconnect()
                except (ConnectionError, OSError):
                    pass  # next attempt surfaces the failure

    def set(self, key: str, value) -> None:
        # last-writer-wins makes set value-idempotent: replaying the same
        # write after an ambiguous failure converges to the same state
        if isinstance(value, str):
            value = value.encode()
        value = bytes(value)
        self._retry_idempotent("set", lambda: self._client.set(key, value))

    def get(self, key: str) -> bytes:
        return self._retry_idempotent("get", lambda: self._client.get(key))

    def add(self, key: str, amount: int = 1) -> int:
        add_token = getattr(self._client, "add_token", None)
        if add_token is None:
            # client without token support (e.g. a stale native lib):
            # replaying could double-count, so don't retry
            return self._client.add(key, amount)
        token = os.urandom(16)  # per-call identity survives the retry window
        return self._retry_idempotent(
            "add", lambda: self._client.add_token(key, amount, token))

    def check(self, key: str) -> bool:
        return self._retry_idempotent("check",
                                      lambda: self._client.check(key))

    def delete_key(self, key: str):
        self._client.delete(key)

    def wait(self, key: str, timeout: float = 300.0):
        from .comm_watchdog import comm_task
        from .env import get_rank

        deadline = time.time() + timeout
        with comm_task("store.wait", rank=get_rank(), extra=f"key={key!r}"):
            while not self.check(key):
                if time.time() > deadline:
                    raise TimeoutError(f"TCPStore wait({key!r}) timed out")
                time.sleep(0.02)

    def barrier(self, key: str = "_barrier", timeout: float = 300.0,
                world_size: Optional[int] = None):
        # per-generation keys make the barrier reusable (every rank calls
        # barrier the same number of times, so generations stay aligned).
        # `world_size` overrides the launch-time count — after an elastic
        # shrink the barrier must count the CURRENT world, not wait for a
        # rank that is never coming back.
        ws = int(world_size) if world_size else self.world_size
        gen = self._barrier_gen
        self._barrier_gen += 1
        n = self.add(f"{key}/{gen}/count", 1)
        if n >= ws:
            self.set(f"{key}/{gen}/done", b"1")
        self.wait(f"{key}/{gen}/done", timeout)

    def stop(self):
        if self._client is not None:
            self._client.close()
            self._client = None
        if self._server is not None:
            self._server.stop()
            self._server = None

"""Generic compiled hybrid engine — dp x pp x tp for ANY Layer.

VERDICT r3 Weak #4 / task #2: the high-MFU compiled engine
(`distributed/hybrid.py`) was flagship-only — every entry took a
LlamaConfig. This module generalizes the same architecture to arbitrary
`nn.Layer`s (reference: fleet/model.py:32's model-agnostic wrapper
selection, meta_parallel/pipeline_parallel.py:255):

- **functionalize**: a stateful Layer becomes a pure
  `apply(params, buffers, x) -> (y, new_buffers)` by swapping traced
  arrays into the parameter/buffer Tensors for the duration of the trace
  (BN running stats update by `._data` reassignment, so the new values
  are captured as traced outputs — the same mechanism static-graph mode
  records).
- **tp via GSPMD**: params are annotated with NamedShardings from
  name/shape rules (Megatron column/row alternation on Linear, feature-dim
  sharding on Embedding, out-channel on Conv); XLA inserts the
  collectives. No layer rewrite needed — this is the scaling-book recipe
  (annotate, compile, let GSPMD do comms).
- **dp + pp manually, tp auto**: the train step is a `jax.shard_map`
  with `axis_names={'dp','pp'}` — dp batch split and the GPipe microbatch
  rotation (`lax.ppermute` in a `lax.scan`, differentiated through) are
  per-device code, while the 'tp' mesh axis stays in GSPMD's hands
  (partial-manual shard_map). Heterogeneous pipeline stages are dispatched
  with `lax.switch` on the device's stage index; stage params are
  pp-replicated (each pp rank's grads for foreign stages are zero and the
  cross-stage psum reassembles them).

The flagship LLaMA keeps its hand-optimized engine (hybrid.py); this one
trades a little memory (pp replication) for total generality.
"""
from __future__ import annotations

import functools
from typing import Any, Callable, Dict, List, Optional, Sequence, Tuple

import jax
import jax.numpy as jnp
import numpy as np
from jax import lax
from jax.sharding import Mesh, NamedSharding, PartitionSpec as P

from ..core.tensor import Tensor
from .hybrid import AdamWConfig, _adamw_update

__all__ = ["functionalize", "generic_tp_specs", "GenericHybridEngine"]


# --------------------------------------------------------------------------
# Functionalization
# --------------------------------------------------------------------------

def functionalize(layer):
    """Layer → (apply, params, buffers): pure function + initial pytrees.

    apply(params, buffers, *inputs, training=True) → (outputs, new_buffers)
    where params/buffers are {name: jnp.ndarray} dicts and outputs are raw
    arrays (Tensor leaves unwrapped).
    """
    param_ts: Dict[str, Tensor] = dict(layer.named_parameters())
    buffer_ts: Dict[str, Tensor] = {
        n: b for n, b in layer.named_buffers() if b is not None}
    params0 = {n: t._data for n, t in param_ts.items()}
    buffers0 = {n: t._data for n, t in buffer_ts.items()}

    def apply(params, buffers, *inputs):
        old_p = {n: t._data for n, t in param_ts.items()}
        old_b = {n: t._data for n, t in buffer_ts.items()}
        try:
            for n, t in param_ts.items():
                t._data = params[n]
            for n, t in buffer_ts.items():
                t._data = buffers[n]
            args = [x if isinstance(x, Tensor) else Tensor._from_data(x)
                    for x in inputs]
            out = layer(*args)
            new_buffers = {n: t._data for n, t in buffer_ts.items()}
            leaves = jax.tree.leaves(
                out, is_leaf=lambda x: isinstance(x, Tensor))
            unwrapped = [x._data if isinstance(x, Tensor) else x
                         for x in leaves]
            out_arr = unwrapped[0] if len(unwrapped) == 1 else tuple(unwrapped)
            return out_arr, new_buffers
        finally:
            for n, t in param_ts.items():
                t._data = old_p[n]
            for n, t in buffer_ts.items():
                t._data = old_b[n]

    return apply, params0, buffers0


# --------------------------------------------------------------------------
# TP sharding rules (name/shape based — GSPMD makes any assignment correct;
# the rules just pick layouts that minimize resharding)
# --------------------------------------------------------------------------

def generic_tp_specs(layer, tp: int, axis: str = "tp") -> Dict[str, P]:
    """PartitionSpec per parameter name. Megatron sandwich on Linears
    (alternate column/row), feature-dim on Embedding, out-channel on Conv;
    anything non-divisible stays replicated."""
    from ..nn.layer.common import Linear, Embedding

    specs: Dict[str, P] = {}
    col_next = True
    for lname, sub in [("", layer)] + list(layer.named_sublayers()):
        prefix = lname + "." if lname else ""
        cls = type(sub).__name__
        if isinstance(sub, Linear) or cls == "Linear":
            w = getattr(sub, "weight", None)
            if w is None:
                continue
            din, dout = w.shape
            if col_next and dout % tp == 0:
                specs[prefix + "weight"] = P(None, axis)
                if getattr(sub, "bias", None) is not None:
                    specs[prefix + "bias"] = P(axis)
                col_next = False
            elif not col_next and din % tp == 0:
                specs[prefix + "weight"] = P(axis, None)
                col_next = True
            # else: leave replicated, keep parity state
        elif isinstance(sub, Embedding) or cls == "Embedding":
            w = getattr(sub, "weight", None)
            if w is not None and w.shape[1] % tp == 0:
                specs[prefix + "weight"] = P(None, axis)
        elif cls.startswith("Conv"):
            w = getattr(sub, "weight", None)
            if w is not None and len(w.shape) >= 2 and w.shape[0] % tp == 0:
                specs[prefix + "weight"] = P(axis)
                if getattr(sub, "bias", None) is not None:
                    specs[prefix + "bias"] = P(axis)
    return specs


# --------------------------------------------------------------------------
# Engine
# --------------------------------------------------------------------------

class GenericHybridEngine:
    """Compiled dp×pp×tp train/eval steps for an arbitrary Layer.

    model: any `nn.Layer`; a `PipelineLayer` enables pp>1 (stages =
    its segmentation; inter-stage activations must share one shape).
    loss_fn: callable(output, label) -> scalar (framework or jnp ops).
    """

    def __init__(self, model, mesh: Mesh, loss_fn: Callable,
                 hp: Optional[AdamWConfig] = None,
                 num_microbatches: int = 1):
        self.model = model
        self.mesh = mesh
        self.loss_fn = loss_fn
        self.hp = hp or AdamWConfig()
        self.M = num_microbatches
        axes = dict(zip(mesh.axis_names, mesh.devices.shape))
        self.dp = axes.get("dp", 1)
        self.pp = axes.get("pp", 1)
        self.tp = axes.get("tp", axes.get("mp", 1))
        self._tp_axis = "tp" if "tp" in axes else ("mp" if "mp" in axes else None)

        from .fleet.meta_parallel.parallel_layers.pp_layers import PipelineLayer

        if self.pp > 1:
            if not isinstance(model, PipelineLayer):
                raise ValueError("pp>1 needs a PipelineLayer-segmented model")
            if model.get_num_stages() != self.pp:
                raise ValueError(
                    f"model has {model.get_num_stages()} stages but mesh "
                    f"pp={self.pp}")
            self._stages = [model.get_stage_layers(s) for s in range(self.pp)]
        else:
            self._stages = None

        self._param_ts = dict(model.named_parameters())
        self._buffer_ts = {n: b for n, b in model.named_buffers()
                           if b is not None}
        params0 = {n: t._data for n, t in self._param_ts.items()}
        buffers0 = {n: t._data for n, t in self._buffer_ts.items()}
        tp_specs = (generic_tp_specs(model, self.tp, self._tp_axis)
                    if self.tp > 1 and self._tp_axis else {})
        self._detect_uniform_stages()
        put = lambda x, s: jax.device_put(x, NamedSharding(mesh, s))
        stack_sharded = self._stack_sharded
        if self._pp_stacked:
            # Uniform stages: stage params live stage-stacked on a leading
            # pp axis (the flagship layout, hybrid.py shard_params) — each
            # pp rank stores ONLY its stage's slice, restoring PP's memory
            # benefit (r4 Weak #3). Non-stage params stay replicated.
            self._specs = {}
            self.params = {}
            for i, n0 in enumerate(self._stage_pnames[0]):
                base = tp_specs.get(n0, P())
                self._specs[n0] = P("pp", *base)
                self.params[n0] = stack_sharded(
                    [params0[self._stage_pnames[s][i]]
                     for s in range(self.pp)], self._specs[n0])
            for n in params0:
                if n not in self._stage_param_set:
                    self._specs[n] = tp_specs.get(n, P())
                    self.params[n] = put(params0[n], self._specs[n])
            self._bspecs = {}
            self.buffers = {}
            for i, n0 in enumerate(self._stage_bnames[0]):
                self._bspecs[n0] = P("pp")
                self.buffers[n0] = stack_sharded(
                    [buffers0[self._stage_bnames[s][i]]
                     for s in range(self.pp)], self._bspecs[n0])
            for n in buffers0:
                if n not in self._stage_buffer_set:
                    self._bspecs[n] = P()
                    self.buffers[n] = put(buffers0[n], P())
        else:
            self._specs = {n: tp_specs.get(n, P()) for n in params0}
            self._bspecs = {n: P() for n in buffers0}
            self.params = {n: put(v, self._specs[n])
                           for n, v in params0.items()}
            self.buffers = {n: put(v, P()) for n, v in buffers0.items()}
        self.opt_state = {
            "m": {n: put(jnp.zeros(v.shape, jnp.float32), self._specs[n])
                  for n, v in self.params.items()},
            "v": {n: put(jnp.zeros(v.shape, jnp.float32), self._specs[n])
                  for n, v in self.params.items()},
            "step": jnp.zeros((), jnp.int32),
        }
        self._train_step = None
        self._eval_step = None
        self._loss_history: List[float] = []

    def _stack_sharded(self, pieces, spec):
        """Assemble a pp-stacked global array shard-by-shard: a jnp.stack
        would transiently materialize the FULL cross-stage stack on one
        device — the exact replica this layout exists to avoid."""
        pieces = [np.asarray(p) for p in pieces]
        shape = (len(pieces),) + pieces[0].shape

        def cb(idx):
            s0 = idx[0].start or 0
            s1 = idx[0].stop if idx[0].stop is not None else len(pieces)
            return np.stack([pieces[s][tuple(idx[1:])]
                             for s in range(s0, s1)])

        return jax.make_array_from_callback(
            shape, NamedSharding(self.mesh, spec), cb)

    def _detect_uniform_stages(self):
        """Stages are uniform when every stage is the same sequence of
        Layer types with identical local param/buffer shapes and no tensor
        shared across stages. Then one stage's CODE computes every stage's
        function (only the values differ), so the per-device program drops
        the all-stages lax.switch and params stack over pp. Reference
        layout: meta_parallel/parallel_layers/pp_layers.py:258 — each rank
        holds only its segment."""
        from ..nn import Layer

        self._pp_stacked = False
        if self._stages is None:
            return
        sigs = []
        seen_ids: set = set()
        for st in self._stages:
            sig = []
            ids = set()
            for fn in st:
                if not isinstance(fn, Layer):
                    return  # bare callables: can't prove uniformity
                sig.append((
                    type(fn).__name__,
                    tuple((k, tuple(p.shape), str(p.dtype))
                          for k, p in fn.named_parameters()),
                    tuple((k, tuple(b.shape))
                          for k, b in fn.named_buffers() if b is not None),
                ))
                ids |= {id(p) for _, p in fn.named_parameters()}
                ids |= {id(b) for _, b in fn.named_buffers()
                        if b is not None}
            if seen_ids & ids:
                return  # tied tensors across stages: stacking impossible
            seen_ids |= ids
            sigs.append(tuple(sig))
        if not all(s == sigs[0] for s in sigs[1:]):
            return
        id2p = {id(t): n for n, t in self._param_ts.items()}
        id2b = {id(t): n for n, t in self._buffer_ts.items()}
        self._stage_pnames = [
            [id2p[id(p)] for fn in st for _, p in fn.named_parameters()]
            for st in self._stages]
        self._stage_bnames = [
            [id2b[id(b)] for fn in st for _, b in fn.named_buffers()
             if b is not None]
            for st in self._stages]
        self._stage_param_set = {n for ns in self._stage_pnames for n in ns}
        self._stage_buffer_set = {n for ns in self._stage_bnames for n in ns}
        self._pp_stacked = True

    # -- pure per-shard programs ----------------------------------------
    def _swap(self, params, buffers):
        for n, t in self._param_ts.items():
            t._data = params[n]
        for n, t in self._buffer_ts.items():
            t._data = buffers[n]

    def _restore(self, snap_p, snap_b):
        for n, t in self._param_ts.items():
            t._data = snap_p[n]
        for n, t in self._buffer_ts.items():
            t._data = snap_b[n]

    def _run_layers(self, layers, x):
        t = x if isinstance(x, Tensor) else Tensor._from_data(x)
        for fn in layers:
            t = fn(t)
        return t._data if isinstance(t, Tensor) else t

    def _loss_arr(self, y, labels):
        out = self.loss_fn(Tensor._from_data(y), Tensor._from_data(labels))
        return (out._data if isinstance(out, Tensor) else out).astype(jnp.float32)

    def _shard_loss_stacked(self, params, buffers, x, labels):
        """Uniform-stage pp: ONE stage program per device (no lax.switch),
        stage params/buffers arriving as [1, ...] slices of the pp-stacked
        leading axis. Stage 0's layer objects execute every rank's stage —
        uniformity means only the VALUES differ."""
        M, pp = self.M, self.pp
        snap_p = {n: t._data for n, t in self._param_ts.items()}
        snap_b = {n: t._data for n, t in self._buffer_ts.items()}
        try:
            # swap local stage slices into stage-0's tensors
            for n in self._stage_pnames[0]:
                self._param_ts[n]._data = params[n][0]
            for n in self.params:
                if n not in self._stage_param_set:
                    self._param_ts[n]._data = params[n]
            stage = lax.axis_index("pp")
            Bloc = x.shape[0]
            Bm = Bloc // M
            xm = x.reshape(M, Bm, *x.shape[1:])
            lm = labels.reshape(M, Bm, *labels.shape[1:])
            bshape = jax.eval_shape(
                lambda a: self._run_layers(self._stages[0], a),
                jax.ShapeDtypeStruct(xm.shape[1:], x.dtype))
            if (bshape.shape, bshape.dtype) != (xm.shape[1:], x.dtype):
                raise ValueError(
                    "uniform pipeline stages must map activations to the "
                    f"same shape/dtype (stage maps {xm.shape[1:]}/{x.dtype}"
                    f" -> {bshape.shape}/{bshape.dtype})")
            is_last = stage == pp - 1

            def pipe_step(carry, t):
                x_in, buf_vals, acc = carry
                m = jnp.clip(t - stage, 0, M - 1)
                active = (t - stage >= 0) & (t - stage < M)
                for n in self._stage_bnames[0]:
                    self._buffer_ts[n]._data = buf_vals[n][0]
                xin = jnp.where(stage == 0, xm[m], x_in)
                y = self._run_layers(self._stages[0], xin)
                new_b = dict(buf_vals)
                for n in self._stage_bnames[0]:
                    upd = self._buffer_ts[n]._data[None]
                    new_b[n] = jnp.where(active, upd, buf_vals[n])
                # loss only on the last stage's active ticks: lax.cond,
                # not a where-mask — intermediate activations may lie
                # outside loss_fn's domain (log/sqrt) and 0*NaN from a
                # masked where still poisons the cotangents
                lval = lax.cond(active & is_last,
                                lambda: self._loss_arr(y, lm[m]),
                                lambda: jnp.float32(0.0))
                acc = acc + lval
                y_send = lax.ppermute(
                    y, "pp", [(i, (i + 1) % pp) for i in range(pp)])
                return (y_send, new_b, acc), None

            x_init = jnp.zeros(bshape.shape, bshape.dtype)
            (_, new_buffers, loss_sum), _ = lax.scan(
                pipe_step, (x_init, buffers, jnp.float32(0.0)),
                jnp.arange(M + pp - 1))
            loss_sum = lax.psum(loss_sum, "pp")
            return loss_sum / (M * self.dp), new_buffers
        finally:
            self._restore(snap_p, snap_b)

    def _shard_loss(self, params, buffers, x, labels):
        """Per-(dp,pp)-shard loss; tp stays global (GSPMD). Returns
        (loss, new_buffers)."""
        if self.pp > 1 and self._pp_stacked:
            return self._shard_loss_stacked(params, buffers, x, labels)
        M, pp = self.M, self.pp
        snap_p = {n: t._data for n, t in self._param_ts.items()}
        snap_b = {n: t._data for n, t in self._buffer_ts.items()}
        try:
            self._swap(params, buffers)
            if pp == 1:
                Bloc = x.shape[0]
                xm = x.reshape(M, Bloc // M, *x.shape[1:])
                lm = labels.reshape(M, Bloc // M, *labels.shape[1:])

                def mb(carry, i):
                    buf_vals, acc = carry
                    for n, t in self._buffer_ts.items():
                        t._data = buf_vals[n]
                    y = self._run_layers(
                        self.model.run_function
                        if hasattr(self.model, "run_function")
                        else [self.model], xm[i])
                    new_b = {n: t._data for n, t in self._buffer_ts.items()}
                    return (new_b, acc + self._loss_arr(y, lm[i])), None

                (new_buffers, loss_sum), _ = _py_scan(mb, (buffers, 0.0),
                                                      range(M))
                return loss_sum / (M * self.dp), new_buffers

            # pp > 1: GPipe rotation with lax.switch over heterogeneous
            # stages. Uniform-shape contract: stages 0..pp-2 all emit the
            # boundary activation (stage 0's output shape); the LAST stage
            # may change shape freely (a classifier head) because its loss
            # is computed INSIDE its branch and only the scalar leaves it —
            # the branch ships zeros(bshape) around the ring to satisfy
            # lax.switch's uniform output type (stage 0 ignores its x_in).
            stage = lax.axis_index("pp")
            Bloc = x.shape[0]
            Bm = Bloc // M
            xm = x.reshape(M, Bm, *x.shape[1:])
            lm = labels.reshape(M, Bm, *labels.shape[1:])
            bshape = jax.eval_shape(
                lambda a: self._run_layers(self._stages[0], a),
                jax.ShapeDtypeStruct(xm.shape[1:], x.dtype))

            def make_branch(s):
                def branch(x_in, buf_vals, m):
                    for n, t in self._buffer_ts.items():
                        t._data = buf_vals[n]
                    xin = xm[m] if s == 0 else x_in
                    y = self._run_layers(self._stages[s], xin)
                    new_b = {n: t._data for n, t in self._buffer_ts.items()}
                    if s == pp - 1:
                        lval = self._loss_arr(y, lm[m])
                        y_out = jnp.zeros(bshape.shape, bshape.dtype)
                    else:
                        lval = jnp.float32(0.0)
                        y_out = y.astype(bshape.dtype)
                    return y_out, new_b, lval
                return branch

            branches = [make_branch(s) for s in range(pp)]

            def pipe_step(carry, t):
                x_in, buf_vals, acc = carry
                m = jnp.clip(t - stage, 0, M - 1)
                active = (t - stage >= 0) & (t - stage < M)
                y, new_b, lmb = lax.switch(stage, branches, x_in, buf_vals, m)
                # bubble ticks run garbage microbatches — keep their buffer
                # pollution and loss out
                new_b = {n: jnp.where(active, new_b[n], buf_vals[n])
                         for n in buf_vals}
                acc = acc + jnp.where(active, lmb, 0.0)
                y_send = lax.ppermute(
                    y, "pp", [(i, (i + 1) % pp) for i in range(pp)])
                return (y_send, new_b, acc), None

            x_init = jnp.zeros(bshape.shape, bshape.dtype)
            (_, new_buffers, loss_sum), _ = lax.scan(
                pipe_step, (x_init, buffers, jnp.float32(0.0)),
                jnp.arange(M + pp - 1))
            # only the last stage accumulated a nonzero loss
            loss_sum = lax.psum(loss_sum, "pp")
            return loss_sum / (M * self.dp), new_buffers
        finally:
            self._restore(snap_p, snap_b)

    # -- step builders ---------------------------------------------------
    def _manual_pspecs(self):
        """Per-name manual-axes view of the param/buffer layouts: stacked
        names carry P('pp') on the leading axis (each rank's slice), the
        rest are replicated over the manual axes (tp stays GSPMD)."""
        if self._pp_stacked:
            pspec = {n: (P("pp") if n in self._stage_param_set else P())
                     for n in self._specs}
            bspec = {n: (P("pp") if n in self._stage_buffer_set else P())
                     for n in self.buffers}
        else:
            pspec = {n: P() for n in self._specs}
            bspec = {n: P() for n in self.buffers}
        return pspec, bspec

    def _build_train(self):
        hp = self.hp
        manual = frozenset(a for a in ("dp", "pp") if a in self.mesh.axis_names)
        stacked_p = self._stage_param_set if self._pp_stacked else frozenset()
        stacked_b = (self._stage_buffer_set if self._pp_stacked
                     else frozenset())

        def per_shard(params, opt, buffers, x, labels, lr):
            def lossf(p):
                loss, new_b = self._shard_loss(p, buffers, x, labels)
                return loss, new_b

            (loss, new_buffers), grads = jax.value_and_grad(
                lossf, has_aux=True)(params)
            if "dp" in manual:
                # dp shards each saw 1/dp of the batch (loss pre-scaled)
                grads = {n: lax.psum(g, "dp") for n, g in grads.items()}
                loss = lax.psum(loss, "dp")
            if "pp" in manual:
                # replicated params: psum reassembles per-stage grads
                # (zeros on foreign pp ranks). Stacked params already hold
                # exactly their own stage's grads — no pp sync.
                grads = {n: (g if n in stacked_p else lax.psum(g, "pp"))
                         for n, g in grads.items()}
                new_buffers = {
                    n: (v if n in stacked_b
                        else buffers[n] + lax.psum(v - buffers[n], "pp"))
                    for n, v in new_buffers.items()}
            if "dp" in manual:
                # dp ranks saw different data: average the running stats
                new_buffers = {n: lax.pmean(v, "dp")
                               for n, v in new_buffers.items()}
            # global grad-norm²: stacked slices are pp-local partials,
            # replicated grads are already identical on every pp rank
            sq_stacked = sum(jnp.sum(g.astype(jnp.float32) ** 2)
                             for n, g in grads.items() if n in stacked_p)
            sq_rep = sum(jnp.sum(g.astype(jnp.float32) ** 2)
                         for n, g in grads.items() if n not in stacked_p)
            if stacked_p and "pp" in manual:
                sq_stacked = lax.psum(sq_stacked, "pp")
            sq = sq_stacked + sq_rep
            new_params, new_opt = _adamw_update(params, grads, opt, hp, sq,
                                                lr=lr)
            return new_params, new_opt, new_buffers, loss

        pspec, bspec = self._manual_pspecs()
        opt_spec = {"m": pspec, "v": pspec, "step": P()}
        data_spec = P("dp") if "dp" in self.mesh.axis_names else P()
        f = jax.shard_map(
            per_shard, mesh=self.mesh,
            in_specs=(pspec, opt_spec, bspec, data_spec, data_spec, P()),
            out_specs=(pspec, opt_spec, bspec, P()),
            axis_names=manual, check_vma=False)
        return jax.jit(f, donate_argnums=(0, 1, 2))

    def _build_eval(self):
        manual = frozenset(a for a in ("dp", "pp") if a in self.mesh.axis_names)

        def per_shard(params, buffers, x, labels):
            loss, _ = self._shard_loss(params, buffers, x, labels)
            if "dp" in manual:
                loss = lax.psum(loss, "dp")
            return loss

        pspec, bspec = self._manual_pspecs()
        data_spec = P("dp") if "dp" in self.mesh.axis_names else P()
        f = jax.shard_map(per_shard, mesh=self.mesh,
                          in_specs=(pspec, bspec, data_spec, data_spec),
                          out_specs=P(), axis_names=manual, check_vma=False)
        return jax.jit(f)

    # -- public API ------------------------------------------------------
    def train_batch(self, x, labels, lr: Optional[float] = None) -> float:
        """One compiled hybrid step over the global batch; returns loss.
        lr: optional current learning rate (an LR schedule feeds the same
        compiled program — the lr is a traced scalar input)."""
        if self._train_step is None:
            self._train_step = self._build_train()
        x = x._data if isinstance(x, Tensor) else jnp.asarray(x)
        labels = (labels._data if isinstance(labels, Tensor)
                  else jnp.asarray(labels))
        lr_v = jnp.float32(self.hp.lr if lr is None else lr)
        self.params, self.opt_state, self.buffers, loss = self._train_step(
            self.params, self.opt_state, self.buffers, x, labels, lr_v)
        val = float(loss)
        self._loss_history.append(val)
        return val

    def eval_batch(self, x, labels) -> float:
        """Loss-only step. The model's train/eval mode at FIRST call is
        baked into the compiled program (jit traces once) — call
        model.eval() before the first eval_batch if BN/dropout should run
        in inference mode."""
        if self._eval_step is None:
            self._eval_step = self._build_eval()
        x = x._data if isinstance(x, Tensor) else jnp.asarray(x)
        labels = (labels._data if isinstance(labels, Tensor)
                  else jnp.asarray(labels))
        return float(self._eval_step(self.params, self.buffers, x, labels))

    def sync_to_layer(self):
        """Write the engine's params/buffers back into the Layer's Tensors
        (for state_dict / save / eager eval). Stacked entries unstack onto
        each stage's original tensors."""
        if self._pp_stacked:
            for i, n0 in enumerate(self._stage_pnames[0]):
                arr = self.params[n0]
                for s in range(self.pp):
                    self._param_ts[self._stage_pnames[s][i]]._data = arr[s]
            for i, n0 in enumerate(self._stage_bnames[0]):
                arr = self.buffers[n0]
                for s in range(self.pp):
                    self._buffer_ts[self._stage_bnames[s][i]]._data = arr[s]
            for n, t in self._param_ts.items():
                if n in self.params and n not in self._stage_param_set:
                    t._data = self.params[n]
            for n, t in self._buffer_ts.items():
                if n in self.buffers and n not in self._stage_buffer_set:
                    t._data = self.buffers[n]
            return
        for n, t in self._param_ts.items():
            t._data = self.params[n]
        for n, t in self._buffer_ts.items():
            t._data = self.buffers[n]

    def refresh_from_layer(self):
        """Re-seed the engine's device copies from the Layer's CURRENT
        Tensors (the inverse of sync_to_layer) — used when another engine
        or eager code updated the layer since this engine was built."""
        put = lambda x, s: jax.device_put(x, NamedSharding(self.mesh, s))
        if self._pp_stacked:
            self.params = {}
            for i, n0 in enumerate(self._stage_pnames[0]):
                self.params[n0] = self._stack_sharded(
                    [self._param_ts[self._stage_pnames[s][i]]._data
                     for s in range(self.pp)], self._specs[n0])
            for n, t in self._param_ts.items():
                if n in self._specs and n not in self._stage_param_set:
                    self.params[n] = put(t._data, self._specs[n])
            self.buffers = {}
            for i, n0 in enumerate(self._stage_bnames[0]):
                self.buffers[n0] = self._stack_sharded(
                    [self._buffer_ts[self._stage_bnames[s][i]]._data
                     for s in range(self.pp)], self._bspecs[n0])
            for n, t in self._buffer_ts.items():
                if n in self._bspecs and n not in self._stage_buffer_set:
                    self.buffers[n] = put(t._data, P())
            return
        self.params = {n: put(t._data, self._specs[n])
                       for n, t in self._param_ts.items()}
        self.buffers = {n: put(t._data, P())
                        for n, t in self._buffer_ts.items()}


def _py_scan(f, init, xs):
    """Host-unrolled scan (microbatch loops are short and static)."""
    carry = init
    for i in xs:
        carry, _ = f(carry, i)
    return carry, None

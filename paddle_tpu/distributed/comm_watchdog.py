"""Communication watchdog: hang detection for eager collectives and store
waits.

Reference analog: `CommTaskManager` + `NCCLCommTask::IsTimeout`
(`paddle/phi/core/distributed/comm_task_manager.h:37`,
`nccl_comm_task.h:53`) — a background thread watches every in-flight
collective; on timeout it dumps rank/op/shape/elapsed diagnostics and
aborts the process so the launcher can restart the pod instead of the job
hanging forever.

TPU-native shape: collectives here are blocking XLA executables (or
TCPStore waits), so the watchdog wraps the *dispatch sites* — the
`comm_task(...)` context manager registers a task before the blocking call
and retires it after. `FLAGS_comm_timeout` (seconds, 0 = disabled) governs
expiry; `FLAGS_comm_watchdog_abort` chooses SIGABRT (production, lets the
launcher restart) vs. a diagnostic-only report (tests observing output).
"""
from __future__ import annotations

import contextlib
import itertools
import os
import signal
import sys
import threading
import time
from typing import Dict, Optional

from ..core import flags

flags.define_flag("comm_timeout", 0.0,
                  "Seconds before an in-flight collective/store wait is "
                  "declared hung (0 disables the comm watchdog)")
flags.define_flag("comm_watchdog_abort", True,
                  "On comm timeout: abort the process (SIGABRT) after "
                  "dumping diagnostics; False = dump only")

_counter = itertools.count()

# the most recently ISSUED collective (op, group_id, rank) — kept even for
# retired tasks so a timeout report can say what the runtime last did
# (comm_task records it whether or not the watchdog is armed)
_last_issued = [None]


def note_issue(op: str, group_id, rank):
    _last_issued[0] = (op, group_id, rank)


def last_issued():
    return _last_issued[0]


class CommTask:
    __slots__ = ("id", "op", "group_id", "rank", "shape", "dtype", "start",
                 "timeout", "extra")

    def __init__(self, op, group_id, rank, shape, dtype, timeout, extra=""):
        self.id = next(_counter)
        self.op = op
        self.group_id = group_id
        self.rank = rank
        self.shape = shape
        self.dtype = dtype
        self.start = time.monotonic()
        self.timeout = timeout
        self.extra = extra

    def describe(self) -> str:
        elapsed = time.monotonic() - self.start
        return (f"op={self.op} group={self.group_id} rank={self.rank} "
                f"shape={self.shape} dtype={self.dtype} "
                f"elapsed={elapsed:.1f}s timeout={self.timeout:.1f}s"
                + (f" {self.extra}" if self.extra else ""))


class CommTaskManager:
    """Singleton watchdog (reference comm_task_manager.h:37)."""

    def __init__(self):
        self._tasks: Dict[int, CommTask] = {}
        self._lock = threading.Lock()
        self._thread: Optional[threading.Thread] = None
        self._fired = False

    def _ensure_thread(self):
        with self._lock:
            if self._thread is None or not self._thread.is_alive():
                self._thread = threading.Thread(target=self._loop,
                                                daemon=True,
                                                name="comm-watchdog")
                self._thread.start()

    def start_task(self, op, group_id, rank, shape, dtype,
                   timeout=None, extra="") -> Optional[int]:
        t = timeout if timeout is not None else float(
            flags.flag_value("comm_timeout") or 0.0)
        if t <= 0:
            return None
        task = CommTask(op, group_id, rank, shape, dtype, t, extra)
        with self._lock:
            self._tasks[task.id] = task
        self._ensure_thread()
        return task.id

    def end_task(self, task_id: Optional[int]):
        if task_id is None:
            return
        with self._lock:
            self._tasks.pop(task_id, None)

    def in_flight(self):
        with self._lock:
            return list(self._tasks.values())

    def _loop(self):
        idle_since = None
        while True:
            time.sleep(0.2)
            now = time.monotonic()
            expired = []
            with self._lock:
                if not self._tasks:
                    # park the thread once nothing is in flight for a while
                    # (_ensure_thread restarts it on the next start_task)
                    idle_since = idle_since or now
                    if now - idle_since > 5.0:
                        self._thread = None
                        return
                    continue
                idle_since = None
                for task in self._tasks.values():
                    if now - task.start > task.timeout:
                        expired.append(task)
                for task in expired:
                    self._tasks.pop(task.id, None)
            if expired:
                # every expiry is reported; _fired only guards double-ABORT
                self._report_and_maybe_abort(expired)

    def _report_and_maybe_abort(self, expired):
        lines = ["[comm watchdog] COLLECTIVE TIMEOUT — probable hang. "
                 "In-flight communication exceeded FLAGS_comm_timeout:"]
        for task in expired:
            lines.append("  TIMED OUT: " + task.describe())
        for task in self.in_flight():
            lines.append("  also in flight: " + task.describe())
        last = _last_issued[0]
        if last is not None:
            lines.append(f"  last issued collective: op={last[0]} "
                         f"group={last[1]} rank={last[2]}")
        # hang-time post-mortem: serialize the flight recorder + metrics
        # BEFORE any abort so the artifact survives the SIGABRT
        dump_path = ""
        try:
            from .. import observability

            observability.emit("watchdog.timeout",
                               ops=[t.op for t in expired])
            dump_path = observability.dump_distress(
                "comm_watchdog_timeout",
                extra={"timed_out": [t.describe() for t in expired],
                       "last_issued": list(last) if last else None})
        except Exception:  # noqa: BLE001 — diagnostics must not mask a hang
            pass
        if dump_path:
            lines.append(f"  flight recorder dumped to: {dump_path}")
        msg = "\n".join(lines)
        print(msg, file=sys.stderr, flush=True)
        if flags.flag_value("comm_watchdog_abort") and not self._fired:
            self._fired = True
            # SIGABRT, like the NCCL watchdog: the launcher's pod watcher
            # sees the non-zero exit and applies its restart policy
            os.kill(os.getpid(), signal.SIGABRT)


_manager = CommTaskManager()


def comm_task_manager() -> CommTaskManager:
    return _manager


@contextlib.contextmanager
def comm_task(op: str, group_id=0, rank=0, shape=(), dtype="", extra=""):
    """Wrap a blocking communication call site."""
    note_issue(op, group_id, rank)
    tid = _manager.start_task(op, group_id, rank, shape, dtype, extra=extra)
    try:
        yield
    finally:
        _manager.end_task(tid)

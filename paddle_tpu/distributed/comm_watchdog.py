"""Communication watchdog: hang detection for eager collectives and store
waits.

Reference analog: `CommTaskManager` + `NCCLCommTask::IsTimeout`
(`paddle/phi/core/distributed/comm_task_manager.h:37`,
`nccl_comm_task.h:53`) — a background thread watches every in-flight
collective; on timeout it dumps rank/op/shape/elapsed diagnostics and
aborts the process so the launcher can restart the pod instead of the job
hanging forever.

TPU-native shape: collectives here are blocking XLA executables (or
TCPStore waits), so the watchdog wraps the *dispatch sites* — the
`comm_task(...)` context manager registers a task before the blocking call
and retires it after. `FLAGS_comm_timeout` (seconds, 0 = disabled) governs
expiry; `FLAGS_comm_watchdog_abort` chooses SIGABRT (production, lets the
launcher restart) vs. a diagnostic-only report (tests observing output).
"""
from __future__ import annotations

import contextlib
import itertools
import os
import signal
import sys
import threading
import time
from typing import Dict, Optional

from ..core import flags

flags.define_flag("comm_timeout", 0.0,
                  "Seconds before an in-flight collective/store wait is "
                  "declared hung (0 disables the comm watchdog)")
flags.define_flag("comm_watchdog_abort", True,
                  "On comm timeout: abort the process (SIGABRT) after "
                  "dumping diagnostics; False = dump only")
flags.define_flag("watchdog_policy", "",
                  "Comm-watchdog escalation ladder: comma-separated stages "
                  "from {warn,dump,retry,elastic,restart,abort}, applied one "
                  "per successive expiry of the same hung task (the task is "
                  "re-armed between stages; 'retry' also doubles its "
                  "timeout; 'elastic' asks the elastic runtime to resolve "
                  "the hang into an in-job world reconfiguration). Empty = "
                  "legacy single-shot report honoring "
                  "FLAGS_comm_watchdog_abort")

# tpu-lint TPL009 cross-checks this ladder against watchdog_policy drills:
# a stage no policy drill reaches (or a policy naming an unknown stage)
# fails the lint gate.
_STAGES = ("warn", "dump", "retry", "elastic", "restart", "abort")

_counter = itertools.count()

# gang-restart hook for the ladder's 'restart' stage — collective.py
# registers its store-barrier rendezvous here at import time (the watchdog
# must not import collective: collective imports this module)
_restart_hook = [None]


def set_restart_hook(fn):
    _restart_hook[0] = fn


# elastic-reconfigure hook for the ladder's 'elastic' stage — fn() -> bool,
# registered by the ElasticRuntime. True = the hang resolved to a world
# change and was reconfigured away, so the hung task is retired; False/None
# = membership is intact (or no runtime), fall through to the next stage.
_elastic_hook = [None]


def set_elastic_hook(fn):
    prev = _elastic_hook[0]
    _elastic_hook[0] = fn
    return prev


# live-membership provider for distress dumps — fn() -> dict snapshot,
# registered by the ElasticRuntime
_membership_fn = [None]


def set_membership_fn(fn):
    prev = _membership_fn[0]
    _membership_fn[0] = fn
    return prev


def _membership_snapshot():
    fn = _membership_fn[0]
    if fn is None:
        return None
    try:
        return fn()
    except Exception:  # noqa: BLE001 — diagnostics never mask a hang
        return None


# pipeline in-flight provider for distress dumps — fn() -> dict (schedule
# name, per-stage last-completed (microbatch, phase), outstanding P2P
# wires), registered by PipelineEngine.run around each batch. Read from
# the watchdog thread while the engine is mid-dispatch, so providers must
# return plain python structures without touching device state.
_pipeline_fn = [None]


def set_pipeline_fn(fn):
    prev = _pipeline_fn[0]
    _pipeline_fn[0] = fn
    return prev


def _pipeline_snapshot():
    fn = _pipeline_fn[0]
    if fn is None:
        return None
    try:
        return fn()
    except Exception:  # noqa: BLE001 — diagnostics never mask a hang
        return None


_policy_warned = [False]


def _parse_policy(spec: str):
    """Ladder stages from FLAGS_watchdog_policy; unknown stages are dropped
    with a one-time stderr warning (the watchdog thread must never die on a
    typo'd flag — worst case it degrades to the legacy report)."""
    out = []
    for raw in (spec or "").split(","):
        raw = raw.strip().lower()
        if not raw:
            continue
        if raw not in _STAGES:
            if not _policy_warned[0]:
                _policy_warned[0] = True
                print(f"[comm watchdog] ignoring unknown "
                      f"FLAGS_watchdog_policy stage {raw!r} "
                      f"(valid: {', '.join(_STAGES)})",
                      file=sys.stderr, flush=True)
            continue
        out.append(raw)
    return out

# the most recently ISSUED collective (op, group_id, rank) — kept even for
# retired tasks so a timeout report can say what the runtime last did
# (comm_task records it whether or not the watchdog is armed)
_last_issued = [None]


def note_issue(op: str, group_id, rank):
    _last_issued[0] = (op, group_id, rank)


def last_issued():
    return _last_issued[0]


class CommTask:
    __slots__ = ("id", "op", "group_id", "rank", "shape", "dtype", "start",
                 "timeout", "extra", "escalations")

    def __init__(self, op, group_id, rank, shape, dtype, timeout, extra=""):
        self.id = next(_counter)
        self.op = op
        self.group_id = group_id
        self.rank = rank
        self.shape = shape
        self.dtype = dtype
        self.start = time.monotonic()
        self.timeout = timeout
        self.extra = extra
        self.escalations = 0  # ladder stages already applied to this task

    def describe(self) -> str:
        elapsed = time.monotonic() - self.start
        return (f"op={self.op} group={self.group_id} rank={self.rank} "
                f"shape={self.shape} dtype={self.dtype} "
                f"elapsed={elapsed:.1f}s timeout={self.timeout:.1f}s"
                + (f" {self.extra}" if self.extra else ""))


class CommTaskManager:
    """Singleton watchdog (reference comm_task_manager.h:37)."""

    def __init__(self):
        self._tasks: Dict[int, CommTask] = {}
        self._lock = threading.Lock()
        self._thread: Optional[threading.Thread] = None
        self._fired = False

    def _ensure_thread(self):
        with self._lock:
            if self._thread is None or not self._thread.is_alive():
                self._thread = threading.Thread(target=self._loop,
                                                daemon=True,
                                                name="comm-watchdog")
                self._thread.start()

    def start_task(self, op, group_id, rank, shape, dtype,
                   timeout=None, extra="") -> Optional[int]:
        t = timeout if timeout is not None else float(
            flags.flag_value("comm_timeout") or 0.0)
        if t <= 0:
            return None
        task = CommTask(op, group_id, rank, shape, dtype, t, extra)
        with self._lock:
            self._tasks[task.id] = task
        self._ensure_thread()
        return task.id

    def end_task(self, task_id: Optional[int]):
        if task_id is None:
            return
        with self._lock:
            self._tasks.pop(task_id, None)

    def in_flight(self):
        with self._lock:
            return list(self._tasks.values())

    def _loop(self):
        idle_since = None
        while True:
            time.sleep(0.2)
            now = time.monotonic()
            expired = []
            staged = []  # (task, ladder stage) when a policy is active
            policy = _parse_policy(
                str(flags.flag_value("watchdog_policy") or ""))
            with self._lock:
                if not self._tasks:
                    # park the thread once nothing is in flight for a while
                    # (_ensure_thread restarts it on the next start_task)
                    idle_since = idle_since or now
                    if now - idle_since > 5.0:
                        self._thread = None
                        return
                    continue
                idle_since = None
                for task in self._tasks.values():
                    if now - task.start > task.timeout:
                        expired.append(task)
                if not policy:
                    for task in expired:
                        self._tasks.pop(task.id, None)
                else:
                    for task in expired:
                        stage = policy[min(task.escalations,
                                           len(policy) - 1)]
                        task.escalations += 1
                        staged.append((task, stage))
                        if stage == "abort":
                            self._tasks.pop(task.id, None)
                        else:
                            task.start = now  # re-arm for the next stage
                            if stage == "retry":
                                task.timeout *= 2
            if staged:
                self._escalate(staged, len(policy))
            elif expired:
                # every expiry is reported; _fired only guards double-ABORT
                self._report_and_maybe_abort(expired)

    def _escalate(self, staged, n_stages):
        """Apply one ladder stage per expired task (FLAGS_watchdog_policy).

        warn    — one-line stderr notice, nothing else.
        dump    — distress dump (flight recorder + metrics artifact).
        retry   — the task was re-armed with a doubled timeout, giving the
                  in-flight collective another window; exception-level
                  retries (the backoff loop in collective.py) are the
                  mechanism that actually re-issues work — this stage keeps
                  the watchdog from declaring death while they run.
        elastic — ask the elastic runtime (hook) to resolve the hang into
                  an in-job reconfiguration: if membership shrank, the
                  world is rebuilt without this rank's peer and the hung
                  task is retired (its collective belongs to a dead epoch);
                  otherwise fall through to the next stage.
        restart — gang-restart rendezvous: every rank meets at a store
                  barrier (hook registered by collective.py) so survivors
                  re-align before resuming.
        abort   — full legacy report + SIGABRT (the ladder's floor).
        """
        for task, stage in staged:
            try:
                from .. import observability

                observability.emit("watchdog.escalate", stage=stage,
                                   op=task.op, rank=task.rank,
                                   escalation=task.escalations)
            except Exception:  # noqa: BLE001 — diagnostics never mask a hang
                pass
            head = (f"[comm watchdog] escalation "
                    f"{min(task.escalations, n_stages)}/{n_stages} "
                    f"stage={stage}: ")
            if stage == "warn":
                print(head + "suspected hang — " + task.describe(),
                      file=sys.stderr, flush=True)
            elif stage == "dump":
                dump_path = ""
                try:
                    from .. import observability

                    dump_path = observability.dump_distress(
                        "comm_watchdog_escalate",
                        extra={"stage": stage,
                               "task": task.describe(),
                               "escalation": task.escalations,
                               "membership": _membership_snapshot(),
                               "pipeline": _pipeline_snapshot()})
                except Exception:  # noqa: BLE001
                    pass
                print(head + "still hung — " + task.describe()
                      + (f"\n  flight recorder dumped to: {dump_path}"
                         if dump_path else ""),
                      file=sys.stderr, flush=True)
            elif stage == "retry":
                print(head + f"re-armed with doubled timeout "
                      f"({task.timeout:.1f}s) — " + task.describe(),
                      file=sys.stderr, flush=True)
            elif stage == "elastic":
                hook = _elastic_hook[0]
                ok = None
                if hook is not None:
                    try:
                        ok = bool(hook())
                    except Exception:  # noqa: BLE001 — a failed reconfigure
                        ok = False     # falls through to the next stage
                if ok:
                    # the hang belonged to the pre-reconfiguration epoch;
                    # the blocked call unwinds via the epoch fence
                    self.end_task(task.id)
                print(head + "elastic reconfigure "
                      + ("succeeded — hung task retired" if ok
                         else "FAILED" if ok is False else "unavailable")
                      + " — " + task.describe(),
                      file=sys.stderr, flush=True)
            elif stage == "restart":
                hook = _restart_hook[0]
                ok = None
                if hook is not None:
                    try:
                        ok = bool(hook())
                    except Exception:  # noqa: BLE001 — a failed rendezvous
                        ok = False     # falls through to the next stage
                print(head + f"gang-restart barrier "
                      f"{'reached' if ok else 'FAILED' if ok is False else 'unavailable'}"
                      f" — " + task.describe(),
                      file=sys.stderr, flush=True)
            elif stage == "abort":
                self._report_and_maybe_abort([task], force_abort=True)

    def _report_and_maybe_abort(self, expired, force_abort=False):
        lines = ["[comm watchdog] COLLECTIVE TIMEOUT — probable hang. "
                 "In-flight communication exceeded FLAGS_comm_timeout:"]
        for task in expired:
            lines.append("  TIMED OUT: " + task.describe())
        for task in self.in_flight():
            lines.append("  also in flight: " + task.describe())
        last = _last_issued[0]
        if last is not None:
            lines.append(f"  last issued collective: op={last[0]} "
                         f"group={last[1]} rank={last[2]}")
        # hang-time post-mortem: serialize the flight recorder + metrics
        # BEFORE any abort so the artifact survives the SIGABRT
        dump_path = ""
        try:
            from .. import observability

            observability.emit("watchdog.timeout",
                               ops=[t.op for t in expired])
            dump_path = observability.dump_distress(
                "comm_watchdog_timeout",
                extra={"timed_out": [t.describe() for t in expired],
                       "last_issued": list(last) if last else None,
                       "membership": _membership_snapshot(),
                       "pipeline": _pipeline_snapshot()})
        except Exception:  # noqa: BLE001 — diagnostics must not mask a hang
            pass
        if dump_path:
            lines.append(f"  flight recorder dumped to: {dump_path}")
        msg = "\n".join(lines)
        print(msg, file=sys.stderr, flush=True)
        if ((force_abort or flags.flag_value("comm_watchdog_abort"))
                and not self._fired):
            self._fired = True
            # SIGABRT, like the NCCL watchdog: the launcher's pod watcher
            # sees the non-zero exit and applies its restart policy
            os.kill(os.getpid(), signal.SIGABRT)


_manager = CommTaskManager()


def comm_task_manager() -> CommTaskManager:
    return _manager


@contextlib.contextmanager
def comm_task(op: str, group_id=0, rank=0, shape=(), dtype="", extra=""):
    """Wrap a blocking communication call site."""
    note_issue(op, group_id, rank)
    tid = _manager.start_task(op, group_id, rank, shape, dtype, extra=extra)
    try:
        yield
    finally:
        _manager.end_task(tid)

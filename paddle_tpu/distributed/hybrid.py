"""TPU-native hybrid-parallel training engine (the fleet analog).

Reference design (SURVEY.md §2.5/CS5): fleet composes DP / TP (Megatron
mp_layers) / PP (1F1B over NCCL p2p) / sequence-parallel / expert-parallel as
Python wrappers firing NCCL collectives per bucket/microbatch
(python/paddle/distributed/fleet/meta_parallel/*, pipeline_parallel.py:575,
mpu/mp_layers.py:49,336,543, moe/moe_layer.py:263).

TPU-native redesign: ONE compiled XLA program per train step. A
`jax.sharding.Mesh` with axes ('dp','pp','tp') replaces the
HybridCommunicateGroup topology; the whole step (all microbatches, forward,
backward, grad sync, optimizer) runs inside a single `jax.shard_map`ped,
jitted function where:

- **TP + SP (Megatron sequence parallel)**: activations stay sequence-sharded
  over 'tp' between layers; `all_gather(seq)` before column-parallel matmuls,
  `psum_scatter(seq)` after row-parallel matmuls — the exact
  ScatterOp/AllGatherOp/ReduceScatterOp pattern of
  fleet/utils/sequence_parallel_utils.py, but compiled to ICI collectives.
- **PP**: GPipe microbatch rotation via `lax.ppermute` inside a `lax.scan` —
  the schedule is differentiated through (ppermute transposes to the inverse
  permutation), so one `jax.grad` covers the whole pipeline instead of the
  reference's hand-built forward_backward_pipeline (pipeline_parallel.py:575).
- **EP (MoE)**: GShard-style capacity dispatch + `all_to_all` over the 'dp'
  axis (expert parallelism rides the data-parallel axis, as in the reference's
  global_scatter/global_gather design, moe_layer.py:263).
- **DP**: gradient psum over 'dp' — the EagerReducer (reducer.h:88) collapses
  to one fused collective XLA schedules during the backward.
- **ZeRO-ish**: optimizer states live sharded exactly like the params (tp/pp
  sharded states come for free; the 'sharding'-axis stage-1/2 variants are the
  fleet API layer's job).

Gradient-sync rule (spec-driven): a param leaf's gradient is psum-ed over every
mesh axis NOT appearing in its PartitionSpec (replicated axes), while sharded
axes need nothing — collective transposes already routed cross-shard
contributions. Loss is pre-scaled by 1/dp so the psum yields the global-batch
mean.
"""
from __future__ import annotations

import dataclasses
import functools
from typing import Any, Dict, Optional, Tuple, Union

import jax
import jax.numpy as jnp
import numpy as np
from jax import lax
from jax.sharding import Mesh, NamedSharding, PartitionSpec as P

from ..core import flags
from ..models import llama as L

MESH_AXES = ("dp", "pp", "cp", "tp")


# --------------------------------------------------------------------------
# Mesh + sharding layout
# --------------------------------------------------------------------------

def build_mesh(dp: int = 1, pp: int = 1, tp: int = 1, cp: int = 1,
               devices=None) -> Mesh:
    """dp x pp x cp x tp device mesh. cp = context parallelism (sequence
    sharding with ring attention) — a capability the reference LACKS
    (SURVEY.md §2.5 CP row: 'not present in core repo'); here it is a
    first-class mesh axis alongside the reference's dims."""
    devices = devices if devices is not None else jax.devices()
    n = dp * pp * cp * tp
    if len(devices) < n:
        raise ValueError(f"need {n} devices, have {len(devices)}")
    arr = np.asarray(devices[:n]).reshape(dp, pp, cp, tp)
    return Mesh(arr, MESH_AXES)


def stack_pipeline(params: Dict[str, Any], pp: int) -> Dict[str, Any]:
    """Reshape block leaves [L, ...] → [pp, L//pp, ...] (stage-major)."""
    def f(x):
        Lg = x.shape[0]
        assert Lg % pp == 0, f"num_layers {Lg} not divisible by pp {pp}"
        return x.reshape(pp, Lg // pp, *x.shape[1:])
    out = dict(params)
    out["blocks"] = jax.tree.map(f, params["blocks"])
    return out


def unstack_pipeline(params: Dict[str, Any]) -> Dict[str, Any]:
    def f(x):
        return x.reshape(x.shape[0] * x.shape[1], *x.shape[2:])
    out = dict(params)
    out["blocks"] = jax.tree.map(f, params["blocks"])
    return out


def param_specs(cfg: L.LlamaConfig) -> Dict[str, Any]:
    """PartitionSpecs for the stage-stacked param pytree.

    Layout: blocks leaves carry a leading 'pp' stage axis; projections are
    tp-sharded Megatron-style (wq/wk/wv/w1/w3 on the output dim, wo/w2 on the
    input dim); embed/lm_head are vocab-parallel; MoE experts are sharded over
    'dp' (= the ep axis).
    """
    blocks = {
        "wq": P("pp", None, None, "tp"),
        "wk": P("pp", None, None, "tp"),
        "wv": P("pp", None, None, "tp"),
        "wo": P("pp", None, "tp", None),
        "attn_norm": P("pp", None, None),
        "mlp_norm": P("pp", None, None),
    }
    if cfg.num_experts:
        blocks["router"] = P("pp", None, None, None)
        blocks["w1"] = P("pp", None, "dp", None, "tp")
        blocks["w3"] = P("pp", None, "dp", None, "tp")
        blocks["w2"] = P("pp", None, "dp", "tp", None)
    else:
        blocks["w1"] = P("pp", None, None, "tp")
        blocks["w3"] = P("pp", None, None, "tp")
        blocks["w2"] = P("pp", None, "tp", None)
    return {
        "embed": P("tp", None),
        "blocks": blocks,
        "final_norm": P(),
        "lm_head": P(None, "tp"),
    }


def shard_params(params: Dict[str, Any], mesh: Mesh, cfg):
    """Stage-stack + device_put with NamedShardings (host → HBM, laid out).
    cfg: LlamaConfig. (Generic Layers shard their params inside
    hybrid_generic.GenericHybridEngine — no call needed.)"""
    pp = mesh.shape["pp"]
    stacked = stack_pipeline(params, pp)
    specs = param_specs(cfg)
    return jax.tree.map(
        lambda x, s: jax.device_put(x, NamedSharding(mesh, s)), stacked, specs)


# --------------------------------------------------------------------------
# Optimizer (sharded AdamW — states shaped/sharded exactly like params)
# --------------------------------------------------------------------------

@dataclasses.dataclass(frozen=True)
class AdamWConfig:
    lr: float = 3e-4
    beta1: float = 0.9
    beta2: float = 0.95
    eps: float = 1e-8
    weight_decay: float = 0.1
    grad_clip: Optional[float] = 1.0


def init_opt_state(params):
    zeros = lambda p: jax.tree.map(lambda x: jnp.zeros_like(x, jnp.float32), p)
    return {"m": zeros(params), "v": zeros(params), "step": jnp.zeros((), jnp.int32)}


def _adamw_update(params, grads, opt, hp: AdamWConfig, global_sq_sum,
                  lr=None):
    """lr: optional traced scalar overriding hp.lr (lets an LR schedule
    feed the compiled step without recompilation)."""
    lr = hp.lr if lr is None else lr
    step = opt["step"] + 1
    if hp.grad_clip is not None:
        gnorm = jnp.sqrt(global_sq_sum)
        scale = jnp.minimum(1.0, hp.grad_clip / (gnorm + 1e-6))
        grads = jax.tree.map(lambda g: g * scale, grads)
    b1, b2 = hp.beta1, hp.beta2
    bc1 = 1.0 - b1 ** step.astype(jnp.float32)
    bc2 = 1.0 - b2 ** step.astype(jnp.float32)

    def upd(p, g, m, v):
        g = g.astype(jnp.float32)
        m = b1 * m + (1 - b1) * g
        v = b2 * v + (1 - b2) * g * g
        u = (m / bc1) / (jnp.sqrt(v / bc2) + hp.eps)
        u = u + hp.weight_decay * p.astype(jnp.float32)
        return (p.astype(jnp.float32) - lr * u).astype(p.dtype), m, v

    flat_p, tree = jax.tree.flatten(params)
    flat_g = jax.tree.leaves(grads)
    flat_m = jax.tree.leaves(opt["m"])
    flat_v = jax.tree.leaves(opt["v"])
    new_p, new_m, new_v = [], [], []
    for p_, g_, m_, v_ in zip(flat_p, flat_g, flat_m, flat_v):
        a, b, c = upd(p_, g_, m_, v_)
        new_p.append(a); new_m.append(b); new_v.append(c)
    return (jax.tree.unflatten(tree, new_p),
            {"m": jax.tree.unflatten(tree, new_m),
             "v": jax.tree.unflatten(tree, new_v), "step": step})


# --------------------------------------------------------------------------
# Per-shard building blocks (run inside shard_map)
# --------------------------------------------------------------------------

def _vp_embed_lookup(embed_local, tok, cfg: L.LlamaConfig):
    """Vocab-parallel embedding with sequence-parallel output
    (VocabParallelEmbedding, mp_layers.py:49, composed with the SP scatter of
    sequence_parallel_utils.py): every tp rank looks up the FULL sequence
    against its vocab shard (partial rows), and the vocab-psum is fused with
    the SP seq-scatter into one reduce_scatter — which also transposes to the
    correct all_gather in backward, so each embed shard's gradient collects
    contributions from all sequence chunks.

    tok [B, T] → [B, T/tp, D].
    """
    vloc = embed_local.shape[0]
    start = lax.axis_index("tp") * vloc
    local_ids = tok - start
    in_range = (local_ids >= 0) & (local_ids < vloc)
    safe = jnp.clip(local_ids, 0, vloc - 1)
    emb = jnp.take(embed_local, safe, axis=0)
    emb = jnp.where(in_range[..., None], emb, 0)
    return lax.psum_scatter(emb, "tp", scatter_dimension=1, tiled=True)


def _vp_cross_entropy(logits_local, targets, vloc):
    """Vocab-parallel softmax CE (ParallelCrossEntropy, mp_layers.py:744):
    logits_local [..., V/tp] over the FULL sequence; per-token loss via
    psum-max / psum-sum over the tp (vocab) axis. The result is replicated
    over tp."""
    start = lax.axis_index("tp") * vloc
    # cross-shard max via all_gather (lax.pmax has no differentiation rule);
    # the shift is mathematically grad-free anyway (logsumexp invariance).
    gmax = lax.all_gather(jnp.max(logits_local, axis=-1), "tp")
    lmax = lax.stop_gradient(jnp.max(gmax, axis=0))
    shifted = logits_local - lmax[..., None]
    sumexp = lax.psum(jnp.sum(jnp.exp(shifted), axis=-1), "tp")
    local_t = targets - start
    in_range = (local_t >= 0) & (local_t < vloc)
    safe = jnp.clip(local_t, 0, vloc - 1)
    true_shift = jnp.take_along_axis(shifted, safe[..., None], axis=-1)[..., 0]
    true_shift = lax.psum(jnp.where(in_range, true_shift, 0.0), "tp")
    return jnp.log(sumexp) - true_shift


def _moe_ffn(h_full, lp, cfg: L.LlamaConfig, ep_size: int):
    """GShard top-k MoE with all_to_all expert dispatch over the 'dp' (=ep)
    axis (reference: global_scatter/global_gather collectives feeding expert
    FFNs, moe_layer.py:263). Expert FFN weights are additionally tp-sharded.

    h_full: [B, T, D] (full sequence, after the SP all_gather).
    lp['w1'] local: [E/ep, D, F/tp].
    """
    B, T, D = h_full.shape
    N = B * T
    E = cfg.num_experts
    assert E % ep_size == 0, f"num_experts {E} not divisible by ep (dp) {ep_size}"
    k = cfg.top_k
    x = h_full.reshape(N, D)
    gates = jax.nn.softmax(
        x.astype(jnp.float32) @ lp["router"].astype(jnp.float32), axis=-1)
    C = max(1, (N * k) // E) * 2  # capacity factor 2.0, static
    C = min(C, N)
    topw, topi = lax.top_k(gates, k)
    topw = topw / (jnp.sum(topw, axis=-1, keepdims=True) + 1e-9)
    disp = jnp.zeros((N, E, C), jnp.float32)
    comb = jnp.zeros((N, E, C), jnp.float32)
    counts = jnp.zeros((E,), jnp.int32)
    for c in range(k):
        e_idx = topi[:, c]
        maski = jax.nn.one_hot(e_idx, E, dtype=jnp.int32)
        pos = jnp.cumsum(maski, axis=0) - 1 + counts[None, :]
        counts = counts + jnp.sum(maski, axis=0)
        p = jnp.take_along_axis(pos, e_idx[:, None], axis=1)[:, 0]
        ok = (p < C)
        oh = (jax.nn.one_hot(e_idx, E, dtype=jnp.float32)[:, :, None]
              * jax.nn.one_hot(jnp.clip(p, 0, C - 1), C, dtype=jnp.float32)[:, None, :])
        oh = oh * ok[:, None, None]
        disp = disp + oh
        comb = comb + oh * topw[:, c][:, None, None]
    xe = jnp.einsum("nd,nec->ecd", x.astype(jnp.float32), disp).astype(x.dtype)  # [E, C, D]
    # all_to_all: experts → owner dp rank; tokens from every dp rank concat on C
    xe = lax.all_to_all(xe, "dp", split_axis=0, concat_axis=1, tiled=True)  # [E/ep, C*ep, D]
    h = jax.nn.silu(jnp.einsum("ecd,edf->ecf", xe, lp["w1"].astype(xe.dtype)))
    h = h * jnp.einsum("ecd,edf->ecf", xe, lp["w3"].astype(xe.dtype))
    ye = jnp.einsum("ecf,efd->ecd", h, lp["w2"].astype(h.dtype))
    # NOTE: ye stays PARTIAL over tp (row-parallel w2 shards); the tp reduction
    # happens at the caller's psum_scatter back into sequence shards, so the
    # backward transposes to an all_gather and every tp rank's w2 shard sees
    # gradient contributions from the whole sequence.
    ye = lax.all_to_all(ye, "dp", split_axis=1, concat_axis=0, tiled=True)  # [E, C, D]
    y = jnp.einsum("ecd,nec->nd", ye.astype(jnp.float32), comb)
    return y.reshape(B, T, D).astype(h_full.dtype)


def _block_sp(x, lp, cfg: L.LlamaConfig, cos, sin, ep_size: int,
              attn_impl: str = "auto", cp: int = 1, ffn_impl: str = "stock"):
    """One transformer block with Megatron TP + sequence parallelism.

    x: [B, T/tp, D] sequence-sharded. lp: this layer's local weight shards.
    """
    Bm, Tloc, D = x.shape
    hd = cfg.head_dim
    h = L.rms_norm(x, lp["attn_norm"], cfg.rms_eps)
    h_full = lax.all_gather(h, "tp", axis=1, tiled=True)          # SP gather [B, T, D]
    T = h_full.shape[1]
    nh_loc = lp["wq"].shape[-1] // hd
    nkv_loc = lp["wk"].shape[-1] // hd
    q = (h_full @ lp["wq"].astype(h_full.dtype)).reshape(Bm, T, nh_loc, hd)
    kk = (h_full @ lp["wk"].astype(h_full.dtype)).reshape(Bm, T, nkv_loc, hd)
    vv = (h_full @ lp["wv"].astype(h_full.dtype)).reshape(Bm, T, nkv_loc, hd)
    q = L.apply_rope(q, cos, sin)
    kk = L.apply_rope(kk, cos, sin)
    if cp > 1:
        # context parallelism: T here is the cp-LOCAL sequence; blockwise
        # ring attention rotates k/v shards over the 'cp' axis (ICI ring)
        from ..ops.ring_attention import ring_attention_shard

        if attn_impl == "flash":
            raise ValueError(
                "attn_impl='flash' cannot be forced on a cp>1 mesh: context "
                "parallelism uses ring attention over the cp axis (fusing "
                "Pallas flash inside the ring blocks is a future "
                "optimization); use attn_impl='auto'")
        o = ring_attention_shard(q, kk, vv, "cp", causal=True)
        o = o.astype(h_full.dtype).reshape(Bm, T, nh_loc * hd)
    else:
        o = L.attention(q, kk, vv, impl=attn_impl).reshape(Bm, T, nh_loc * hd)
    partial = o @ lp["wo"].astype(o.dtype)                         # row-parallel partial
    x = x + lax.psum_scatter(partial, "tp", scatter_dimension=1, tiled=True)
    h = L.rms_norm(x, lp["mlp_norm"], cfg.rms_eps)
    h_full = lax.all_gather(h, "tp", axis=1, tiled=True)
    if cfg.num_experts:
        y_partial = _moe_ffn(h_full, lp, cfg, ep_size)  # partial over tp
        x = x + lax.psum_scatter(y_partial, "tp", scatter_dimension=1, tiled=True)
    else:
        # column-parallel w1/w3 + row-parallel w2 → the shard's FFN body is
        # exactly the dense SwiGLU over local f/tp, so the fused Pallas
        # kernel drops in per-shard, before the tp reduce-scatter
        partial = L.ffn(h_full, lp, impl=ffn_impl)
        x = x + lax.psum_scatter(partial, "tp", scatter_dimension=1, tiled=True)
    return x


def _make_shard_loss(cfg: L.LlamaConfig, num_microbatches: int,
                     dp: int, pp: int, tp: int, cp: int = 1,
                     remat: Union[bool, str] = True,
                     attn_impl: str = "auto", ffn_impl: str = "stock"):
    """Build the per-shard loss(params, tokens, targets) -> scalar function.

    Inside: GPipe pipeline over `num_microbatches`, TP/SP per block,
    vocab-parallel CE on the last stage, loss pre-scaled by 1/dp.
    """
    M = num_microbatches

    def stage_fn(x, blocks_local, cos, sin):
        body = lambda carry, lp: (_block_sp(carry, lp, cfg, cos, sin, dp,
                                            attn_impl, cp, ffn_impl), None)
        if remat not in (True, False, "dots"):
            raise ValueError(f"remat must be True, False or 'dots', got {remat!r}")
        if remat == "dots":
            # save matmul outputs, recompute elementwise/norms: trades a
            # little HBM for skipping most of the backward's forward replay
            body = jax.checkpoint(
                body, prevent_cse=False,
                policy=jax.checkpoint_policies.dots_saveable)
        elif remat:
            body = jax.checkpoint(body, prevent_cse=False)
        x, _ = lax.scan(body, x, blocks_local)
        return x

    def shard_loss(params, tokens, targets):
        # local shapes: tokens [B/dp, T]; blocks leaves [1, L/pp, ...]
        blocks_local = jax.tree.map(lambda x: x[0], params["blocks"])
        Bloc, T = tokens.shape
        assert Bloc % M == 0, f"local batch {Bloc} not divisible by microbatches {M}"
        Bm = Bloc // M
        Tloc = T // tp
        D = cfg.hidden_size
        tok_mb = tokens.reshape(M, Bm, T)
        tgt_mb = targets.reshape(M, Bm, T)
        stage = lax.axis_index("pp")
        # T is the cp-local sequence; rope positions offset by the cp shard
        pos0 = lax.axis_index("cp") * T if cp > 1 else 0
        cos, sin = L.rope_cos_sin(pos0 + jnp.arange(T), cfg.head_dim,
                                  cfg.rope_theta)
        vloc = params["lm_head"].shape[1]

        def embed_mb(m):
            x = _vp_embed_lookup(params["embed"], tok_mb[m], cfg)  # [Bm, T/tp, D]
            return x.astype(cfg.dtype)

        def mb_loss(y, m):
            # y [Bm, T/tp, D]: exit the SP region (all_gather seq), then
            # vocab-parallel head + CE over the full sequence. per_tok is
            # replicated over tp; SUM over the microbatch's tokens.
            h = L.rms_norm(y, params["final_norm"], cfg.rms_eps)
            h_full = lax.all_gather(h, "tp", axis=1, tiled=True)   # [Bm, T, D]
            logits = (h_full @ params["lm_head"].astype(h_full.dtype)).astype(jnp.float32)
            per_tok = _vp_cross_entropy(logits, tgt_mb[m], vloc)
            return jnp.sum(per_tok)

        def pipe_step(carry, t):
            x_in, loss_acc = carry
            m = jnp.clip(t - stage, 0, M - 1)
            active = (t - stage >= 0) & (t - stage < M)
            x0 = embed_mb(m)
            x = jnp.where(stage == 0, x0, x_in)
            y = stage_fn(x, blocks_local, cos, sin)
            lmb = mb_loss(y, m)
            take = active & (stage == pp - 1)
            loss_acc = loss_acc + jnp.where(take, lmb, 0.0)
            y_send = lax.ppermute(y, "pp", [(i, (i + 1) % pp) for i in range(pp)])
            return (y_send, loss_acc), None

        x_init = jnp.zeros((Bm, Tloc, D), cfg.dtype)
        (_, loss_sum), _ = lax.scan(
            pipe_step, (x_init, jnp.zeros((), jnp.float32)), jnp.arange(M + pp - 1))
        # collect from the last stage (pp); already replicated over tp.
        # Normalize to the GLOBAL batch mean: local token count is M*Bm*T, and
        # the extra 1/dp makes the implicit sum over dp ranks a global mean.
        loss_sum = lax.psum(loss_sum, ("pp", "cp") if cp > 1 else "pp")
        return loss_sum / (M * Bm * T * cp * dp)

    return shard_loss


def _sync_axes(spec: P) -> Tuple[str, ...]:
    used = set()
    for entry in spec:
        if entry is None:
            continue
        if isinstance(entry, (tuple, list)):
            used.update(entry)
        else:
            used.add(entry)
    return tuple(a for a in MESH_AXES if a not in used)


def sync_grads(grads, specs):
    """psum each grad leaf over the mesh axes its param is replicated on."""
    def f(g, s):
        axes = _sync_axes(s)
        return lax.psum(g, axes) if axes else g
    return jax.tree.map(f, grads, specs, is_leaf=lambda x: isinstance(x, P))


# --------------------------------------------------------------------------
# Public train step factory
# --------------------------------------------------------------------------

def make_train_step(cfg, mesh: Mesh, num_microbatches: Optional[int] = None,
                    hp: Optional[AdamWConfig] = None,
                    remat: Union[bool, str] = True,
                    attn_impl: str = "auto", loss_fn=None,
                    ffn_impl: Optional[str] = None):
    """Model-agnostic entry (VERDICT r3 task #2).

    cfg: a LlamaConfig (the hand-optimized flagship path below) OR any
    `nn.Layer` — Layers route to the generic compiled engine
    (hybrid_generic.GenericHybridEngine: manual dp/pp GPipe + GSPMD tp)
    and the returned step closes over engine state:
    `step(x, labels) -> loss`, with the engine on `step.engine`.
    `loss_fn` is required for the Layer path.

    LlamaConfig path: returns jitted step(params, opt_state, tokens,
    targets) → (params, opt_state, loss). params must be stage-stacked +
    sharded (see shard_params); tokens/targets are [B_global, T] int32
    sharded P('dp',None).

    remat: True = full per-block rematerialization (lowest memory);
    "dots" = jax.checkpoint_policies.dots_saveable — saves matmul outputs and
    recomputes only elementwise/norm work in backward (≈20% faster on the
    v5e-class chip, measured 0.353 vs 0.291 MFU on the bench config);
    False = save everything (usually OOMs beyond toy sizes).
    attn_impl: "auto" (Pallas flash on TPU when supported), "flash" (force),
    anything else = plain XLA attention.
    ffn_impl: None resolves FLAGS_pallas_ffn HERE, at build time (the flag
    never reaches traced code — trace purity); "pallas" forces the fused
    SwiGLU kernel on supported shapes; anything else = stock XLA FFN.
    num_microbatches: None resolves FLAGS_pp_accumulate_steps at build
    time (same discipline), so a tuned profile's microbatch pin applies
    without threading a ctor arg through every training entry.
    """
    # apply any FLAGS_tuned_profile before the flag-backed knobs
    # (microbatches, pallas_ffn) are resolved into the executable
    from .. import tuner as _tuner
    from .pipeline import runtime as _pprt  # noqa: F401 (defines pp_* flags)
    _tuner.maybe_apply_flagged()
    if num_microbatches is None:
        num_microbatches = max(
            1, int(flags.flag_value("pp_accumulate_steps")))
    if not isinstance(cfg, L.LlamaConfig):
        from .hybrid_generic import GenericHybridEngine

        if loss_fn is None and getattr(cfg, "_loss_fn", None) is not None:
            loss_fn = cfg._loss_fn
        if loss_fn is None:
            raise ValueError("make_train_step(Layer, ...) needs loss_fn=")
        eng = GenericHybridEngine(cfg, mesh, loss_fn, hp=hp,
                                  num_microbatches=num_microbatches)

        def step(x, labels):
            return eng.train_batch(x, labels)

        step.engine = eng
        return step
    hp = hp or AdamWConfig()
    if ffn_impl is None:
        from ..ops.pallas import fused_ffn as _ff

        ffn_impl = "pallas" if (flags.flag_value("pallas_ffn")
                                and _ff.available()) else "stock"
    dp, pp, cp, tp = (mesh.shape[a] for a in MESH_AXES)
    specs = param_specs(cfg)
    shard_loss = _make_shard_loss(cfg, num_microbatches, dp, pp, tp, cp,
                                  remat, attn_impl, ffn_impl)
    opt_specs = {"m": specs, "v": specs, "step": P()}

    def per_shard_step(params, opt, tokens, targets):
        loss, grads = jax.value_and_grad(shard_loss)(params, tokens, targets)
        grads = sync_grads(grads, specs)
        loss = lax.psum(loss, "dp")  # replicate the global mean for reporting
        # global grad-norm² for clipping: local shards' sq-sums + psum over the
        # axes each leaf is sharded on (replicated leaves are already synced).
        sq = 0.0
        for g, s in zip(jax.tree.leaves(grads),
                        jax.tree.leaves(specs, is_leaf=lambda x: isinstance(x, P))):
            loc = jnp.sum(g.astype(jnp.float32) ** 2)
            shard_axes = tuple(a for a in MESH_AXES if a not in _sync_axes(s))
            sq = sq + (lax.psum(loc, shard_axes) if shard_axes else loc)
        new_params, new_opt = _adamw_update(params, grads, opt, hp, sq)
        return new_params, new_opt, loss

    step = jax.shard_map(
        per_shard_step, mesh=mesh,
        in_specs=(specs, opt_specs, P("dp", "cp"), P("dp", "cp")),
        out_specs=(specs, opt_specs, P()),
        check_vma=False)
    return jax.jit(step, donate_argnums=(0, 1))


def make_eval_step(cfg, mesh: Mesh, num_microbatches: int = 1, loss_fn=None,
                   train_step=None):
    """Jitted loss-only step (no grads) with the same sharding layout.
    cfg: LlamaConfig (flagship path) or any nn.Layer (routes to the
    generic engine, mirroring make_train_step).

    Layer path: pass `train_step` (the callable make_train_step returned)
    to evaluate that step's LIVE engine state; without it, the eval step
    re-reads the Layer's current Tensors before every call so updates made
    elsewhere (another engine after sync_to_layer, eager code) are seen."""
    if not isinstance(cfg, L.LlamaConfig):
        from .hybrid_generic import GenericHybridEngine

        if loss_fn is None and getattr(cfg, "_loss_fn", None) is not None:
            loss_fn = cfg._loss_fn
        if loss_fn is None:
            raise ValueError("make_eval_step(Layer, ...) needs loss_fn=")
        shared = getattr(train_step, "engine", None)
        eng = shared or GenericHybridEngine(
            cfg, mesh, loss_fn, num_microbatches=num_microbatches)

        def step(x, labels):
            if shared is None:
                eng.refresh_from_layer()
            return eng.eval_batch(x, labels)

        step.engine = eng
        return step
    dp, pp, cp, tp = (mesh.shape[a] for a in MESH_AXES)
    specs = param_specs(cfg)
    shard_loss = _make_shard_loss(cfg, num_microbatches, dp, pp, tp, cp,
                                  remat=False)

    def per_shard(params, tokens, targets):
        return lax.psum(shard_loss(params, tokens, targets), "dp")

    f = jax.shard_map(per_shard, mesh=mesh,
                      in_specs=(specs, P("dp", "cp"), P("dp", "cp")),
                      out_specs=P(), check_vma=False)
    return jax.jit(f)

"""DataParallel wrapper + parallel env bootstrap.

Reference: python/paddle/distributed/parallel.py:219 `DataParallel` — wraps a
Layer, broadcasts params from rank 0, and registers backward hooks feeding an
`EagerReducer` (reducer.h:88) that bucketizes grads and fires fused NCCL
allreduces overlapped with backward.

TPU-native: grad sync is ONE bucketed allreduce per step. Under the compiled
train-step path XLA already fuses/overlaps the psum with backward compute; in
eager mode we flat-pack grads into buckets (comm-efficient large transfers on
ICI, the reducer's bucketing idea) and dispatch cached all-reduce executables
at sync time. Param broadcast-from-src uses the same collective path.
"""
from __future__ import annotations

from typing import List, Optional

import jax.numpy as jnp

from ..core.tensor import Parameter, Tensor
from ..nn.layer.layers import Layer
from . import collective as coll
from .env import get_rank, get_world_size


def _bucket_params(params: List[Parameter], bucket_mb: float = 32.0):
    """Group params into ~bucket_mb flat buckets, one dtype per bucket
    (reducer.h bucketing; the reference's EagerReducer also groups by dtype
    so the flat-concat never promotes)."""
    by_dtype = {}
    for p in params:
        by_dtype.setdefault(str(p._data.dtype), []).append(p)
    buckets = []
    cap = int(bucket_mb * 1024 * 1024)
    for group in by_dtype.values():
        cur, cur_bytes = [], 0
        for p in group:
            nbytes = int(jnp.size(p._data)) * p._data.dtype.itemsize
            if cur and cur_bytes + nbytes > cap:
                buckets.append(cur)
                cur, cur_bytes = [], 0
            cur.append(p)
            cur_bytes += nbytes
        if cur:
            buckets.append(cur)
    return buckets


def sync_param_grads(params: List[Parameter], group: Optional[coll.Group],
                     bucket_mb: float = 32.0):
    """Shared grad-sync: bucketed flat-pack AVG allreduce over `group`,
    written back shard-for-shard. Used by DataParallel.sync_gradients and
    HybridParallelOptimizer._sync_grads."""
    if group is None or group.nranks <= 1:
        return
    with_grad = [p for p in params if getattr(p, "_grad", None) is not None]
    for bucket in _bucket_params(with_grad, bucket_mb):
        flat = jnp.concatenate([jnp.ravel(p._grad) for p in bucket])
        t = Tensor(flat)
        coll.all_reduce(t, op=coll.ReduceOp.AVG, group=group)
        out = t._data
        off = 0
        for p in bucket:
            n = int(jnp.size(p._grad))
            p._grad = out[off:off + n].reshape(p._grad.shape)
            off += n


def sync_params_buffers(model: Layer, comm_group: Optional[coll.Group] = None,
                        src_rank: int = 0):
    """Broadcast params from src (reference: parallel.py sync_params_buffers)."""
    for p in model.parameters():
        coll.broadcast(p, src=src_rank, group=comm_group)


class DataParallel(Layer):
    """Reference: python/paddle/distributed/parallel.py:219."""

    def __init__(self, layers: Layer, strategy=None, comm_buffer_size_MB: int = 25,
                 last_comm_buffer_size_MB: int = 1,
                 find_unused_parameters: bool = False,
                 group: Optional[coll.Group] = None, **kw):
        super().__init__()
        self._layers = layers
        self._group = group or coll.get_group(0)
        self._comm_buffer_mb = comm_buffer_size_MB
        self.find_unused_parameters = find_unused_parameters
        if self._group is not None and self._group.nranks > 1:
            sync_params_buffers(layers, self._group)
        self._buckets = None

    def forward(self, *inputs, **kwargs):
        return self._layers(*inputs, **kwargs)

    # -- reducer ---------------------------------------------------------
    def _ensure_buckets(self):
        if self._buckets is None:
            ps = [p for p in self._layers.parameters() if not p.stop_gradient]
            self._buckets = _bucket_params(ps, self._comm_buffer_mb)
        return self._buckets

    def sync_gradients(self):
        """Bucketed grad allreduce over the dp group (mean).

        Reference fires this from autograd hooks; here it runs post-backward
        (the optimizer wrapper calls it) — same comm volume, XLA/PJRT still
        overlaps buckets with each other via async dispatch.
        """
        sync_param_grads(
            [p for p in self._layers.parameters() if not p.stop_gradient],
            self._group, self._comm_buffer_mb)

    # -- Layer protocol passthrough -------------------------------------
    def parameters(self, include_sublayers=True):
        return self._layers.parameters(include_sublayers)

    def named_parameters(self, prefix="", include_sublayers=True):
        return self._layers.named_parameters(prefix, include_sublayers)

    def state_dict(self, *a, **k):
        return self._layers.state_dict(*a, **k)

    def set_state_dict(self, state_dict, *a, **k):
        return self._layers.set_state_dict(state_dict, *a, **k)

    def train(self):
        self._layers.train()
        return super().train()

    def eval(self):
        self._layers.eval()
        return super().eval()

    def no_sync(self):
        """Context: skip grad sync (gradient accumulation)."""
        import contextlib

        @contextlib.contextmanager
        def ctx():
            saved = self._group
            self._group = None
            try:
                yield
            finally:
                self._group = saved

        return ctx()


def init_parallel_env():
    """Reference: parallel.py:978."""
    return coll.init_parallel_env()


def get_rank_api():
    return get_rank()

"""DataParallel wrapper + overlapped bucket reducer + sharded update.

Reference: python/paddle/distributed/parallel.py:219 `DataParallel` — wraps a
Layer, broadcasts params from rank 0, and registers backward hooks feeding an
`EagerReducer` (reducer.h:88) that bucketizes grads and fires fused NCCL
allreduces overlapped with backward.

TPU-native rebuild of that hot path, in three pieces:

1. **Overlap** (``FLAGS_dp_overlap``): every trainable param registers a
   grad-final hook (``Tensor.register_grad_final_hook``); the moment a
   bucket's last grad is final the bucket's collective is ISSUED — packed by
   a cached jitted flat-pack executable and dispatched asynchronously — while
   backward keeps walking the tape. ``sync_gradients()`` (and a pre-step hook
   inside ``Optimizer.step``) merely drains the outstanding ``Task`` handles
   instead of running a post-backward barrier.
2. **Cross-replica sharded update** (``FLAGS_dp_shard_update``, ZeRO-1 per
   Xu et al. arXiv:2004.13336): grads are reduce-scattered so each rank owns
   a contiguous shard of the flat buffer, the fused buffer-donated optimizer
   step runs on only the owned shard (1/N update FLOPs, 1/N optimizer-state
   memory), and the updated flat params are tiled-all-gathered back. Bind an
   optimizer with :func:`sharded_update`.
3. **Caching**: the bucket layout and the jitted pack/unpack/scatter
   executables are keyed on the param-set signature (name/shape/dtype/lr
   multiplier + comm dtype + group), so steady-state steps run zero per-step
   ``jnp.concatenate``/re-bucketing Python work — every step is cache-hit
   executable dispatch.

``FLAGS_dp_grad_comm_dtype`` optionally compresses the gradient collective
(bf16/fp16 on the wire, params and update math stay in the param dtype).
"""
from __future__ import annotations

import time
from collections import OrderedDict
from typing import Dict, List, Optional

import jax
import jax.numpy as jnp
import numpy as np
from jax.sharding import NamedSharding, PartitionSpec as P

from ..core import flags
from ..core import async_engine
from ..core.tensor import Parameter, Tensor
from ..nn.layer.layers import Layer
from ..observability import emit as _obs_emit
from . import collective as coll
from . import quant_comm as _qc
from .comm_watchdog import comm_task
from .env import get_rank, get_world_size

flags.define_flag("dp_overlap", True,
                  "Issue each DP bucket's gradient collective from autograd "
                  "grad-final hooks, overlapped with backward; 0 restores "
                  "the post-backward barrier (all buckets issued at "
                  "sync_gradients)")
flags.define_flag("dp_shard_update", False,
                  "ZeRO-1 cross-replica sharded weight update: "
                  "reduce-scatter grads, run the optimizer on the owned "
                  "1/N flat shard, all-gather updated params (requires "
                  "binding the optimizer with "
                  "paddle.distributed.sharded_update)")
flags.define_flag("dp_grad_comm_dtype", "",
                  "Wire dtype for DP gradient collectives: '' keeps the "
                  "param dtype; 'bfloat16'/'bf16' or 'float16'/'fp16' "
                  "compress the reduce, unpacking casts back; 'int8' "
                  "selects the block-scaled codec with error feedback "
                  "(quant_comm.py, FLAGS_dp_comm_block_size)")

_COMM_DTYPES = {"bf16": "bfloat16", "bfloat16": "bfloat16",
                "fp16": "float16", "float16": "float16",
                "int8": "int8"}


def _comm_dtype_name() -> Optional[str]:
    raw = str(flags.flag_value("dp_grad_comm_dtype") or "").strip().lower()
    if not raw:
        return None
    if raw not in _COMM_DTYPES:
        raise ValueError(
            f"FLAGS_dp_grad_comm_dtype={raw!r}: want '', 'bfloat16', "
            "'float16' or 'int8'")
    return _COMM_DTYPES[raw]


def _bucket_params(params: List[Parameter], bucket_mb: float = 32.0):
    """Group params into ~bucket_mb flat buckets, one dtype per bucket
    (reducer.h bucketing; the reference's EagerReducer also groups by dtype
    so the flat-concat never promotes)."""
    by_dtype = {}
    for p in params:
        by_dtype.setdefault(str(p._data.dtype), []).append(p)
    buckets = []
    cap = int(bucket_mb * 1024 * 1024)
    for group in by_dtype.values():
        cur, cur_bytes = [], 0
        for p in group:
            nbytes = int(jnp.size(p._data)) * p._data.dtype.itemsize
            if cur and cur_bytes + nbytes > cap:
                buckets.append(cur)
                cur, cur_bytes = [], 0
            cur.append(p)
            cur_bytes += nbytes
        if cur:
            buckets.append(cur)
    return buckets


def sync_param_grads(params: List[Parameter], group: Optional[coll.Group],
                     bucket_mb: float = 32.0):
    """Shared grad-sync: bucketed flat-pack AVG allreduce over `group`,
    written back shard-for-shard. Used by HybridParallelOptimizer._sync_grads
    and as the reducer's fallback for partially-ready buckets (unused
    params)."""
    if group is None or group.nranks <= 1:
        return
    with_grad = [p for p in params if getattr(p, "_grad", None) is not None]
    for bucket in _bucket_params(with_grad, bucket_mb):
        flat = jnp.concatenate([jnp.ravel(p._grad) for p in bucket])
        t = Tensor(flat)
        coll.all_reduce(t, op=coll.ReduceOp.AVG, group=group)
        out = t._data
        off = 0
        for p in bucket:
            n = int(jnp.size(p._grad))
            p._grad = out[off:off + n].reshape(p._grad.shape)
            off += n


def sync_params_buffers(model: Layer, comm_group: Optional[coll.Group] = None,
                        src_rank: int = 0):
    """Broadcast params from src (reference: parallel.py sync_params_buffers)."""
    for p in model.parameters():
        coll.broadcast(p, src=src_rank, group=comm_group)


# ---------------------------------------------------------------------------
# Bucket plan: persistent layout + signature-keyed executable cache
# ---------------------------------------------------------------------------

class _Bucket:
    __slots__ = ("index", "params", "shapes", "sizes", "offsets", "numel",
                 "padded", "dtype", "comm_dtype", "lr_mult", "nbytes",
                 # block-scaled int8 wire (quant_comm): geometry,
                 # executables, error-feedback carry
                 "qblock", "qblocks", "qpadded", "qpack", "qdecode",
                 "residual",
                 # lazily built jitted executables
                 "pack", "unpack_grads", "pack_params", "unpack_params",
                 # per-step reducer state
                 "ready", "issued", "task", "out_ref", "t_issue", "op",
                 # sharded-update state
                 "flat_grad", "flat_param", "out_ids", "pseudo")

    def __init__(self, index, params, nranks, comm_dtype):
        self.index = index
        self.params = params
        self.shapes = [tuple(p._data.shape) for p in params]
        self.sizes = [int(jnp.size(p._data)) for p in params]
        self.offsets = []
        off = 0
        for n in self.sizes:
            self.offsets.append(off)
            off += n
        self.numel = off
        n = max(1, nranks)
        self.padded = -(-off // n) * n  # ceil to a multiple of nranks
        self.dtype = str(params[0]._data.dtype)
        self.comm_dtype = comm_dtype or self.dtype
        self.lr_mult = float(getattr(params[0], "optimize_attr", {})
                             .get("learning_rate", 1.0))
        if self.comm_dtype == "int8":
            # Block-scaled wire: quantize the nranks-aligned buffer, pad
            # up to whole blocks; nbytes is the actual on-wire size
            # (payload + one f32 scale per block).
            self.qblock = _qc.block_size()
            self.qpadded, self.qblocks, qwire = _qc.wire_layout(
                self.padded, self.qblock)
            self.nbytes = qwire
        else:
            self.qblock = self.qblocks = self.qpadded = 0
            self.nbytes = self.padded * np.dtype(self.comm_dtype).itemsize
        self.qpack = None
        self.qdecode = None
        self.residual = None
        self.pack = None
        self.unpack_grads = None
        self.pack_params = None
        self.unpack_params = None
        self.ready = set()
        self.issued = False
        self.task = None
        self.out_ref = None
        self.t_issue = 0.0
        self.op = ""
        self.flat_grad = None
        self.flat_param = None
        self.out_ids = None
        self.pseudo = None


class _Plan:
    __slots__ = ("signature", "buckets", "by_param")

    def __init__(self, signature, buckets):
        self.signature = signature
        self.buckets = buckets
        self.by_param: Dict[int, _Bucket] = {}
        for b in buckets:
            for p in b.params:
                self.by_param[id(p)] = b


_PLAN_CACHE_CAP = 8  # per-reducer: signatures only change on flag flips


def _plan_signature(params, group, comm_mb, last_mb, comm_dtype):
    gid = getattr(group, "id", -1) if group is not None else -1
    nranks = getattr(group, "nranks", 1) if group is not None else 1
    # id(p) is part of the key: a plan holds live references to its params,
    # so a rebuild after a param is replaced must not reuse the old plan
    return (tuple((id(p), p.name, tuple(p._data.shape), str(p._data.dtype),
                   float(getattr(p, "optimize_attr", {})
                         .get("learning_rate", 1.0)))
                  for p in params),
            gid, nranks, float(comm_mb), float(last_mb), comm_dtype or "",
            _qc.block_size() if comm_dtype == "int8" else 0)


def _build_plan(params, group, comm_mb, last_mb, comm_dtype,
                cache: "Optional[OrderedDict]" = None) -> _Plan:
    """Bucket layout, signature-keyed. Params are grouped in REVERSE
    declaration order (the order their grads become final during backward,
    reference reducer.cc) and split by (dtype, lr multiplier) so each flat
    buffer never promotes and maps to one fused-optimizer pseudo-param; the
    last-built bucket is tail-split to ``last_comm_buffer_size_MB``
    (reference's small final buffer, which flushes the stragglers early).

    ``cache`` is the owning reducer's plan cache — scoped to the reducer
    (not module-global) so a dead model's params are not pinned for the
    process lifetime."""
    sig = _plan_signature(params, group, comm_mb, last_mb, comm_dtype)
    if cache is not None:
        plan = cache.get(sig)
        if plan is not None:
            cache.move_to_end(sig)
            return plan
    nranks = getattr(group, "nranks", 1) if group is not None else 1
    groups: "OrderedDict[tuple, list]" = OrderedDict()
    for p in reversed(params):
        key = (str(p._data.dtype),
               float(getattr(p, "optimize_attr", {})
                     .get("learning_rate", 1.0)))
        groups.setdefault(key, []).append(p)
    raw: List[List[Parameter]] = []
    cap = int(float(comm_mb) * 1024 * 1024)
    for (dt, _mult), ps in groups.items():
        item = np.dtype(dt).itemsize
        cur, cur_bytes = [], 0
        for p in ps:
            nbytes = int(jnp.size(p._data)) * item
            if cur and cur_bytes + nbytes > cap:
                raw.append(cur)
                cur, cur_bytes = [], 0
            cur.append(p)
            cur_bytes += nbytes
        if cur:
            raw.append(cur)
    if raw:
        last_cap = int(float(last_mb) * 1024 * 1024)
        tail_bucket = raw[-1]
        item = np.dtype(str(tail_bucket[0]._data.dtype)).itemsize
        if len(tail_bucket) > 1:
            tail, tail_bytes = [], 0
            while len(tail_bucket) > 1:
                nbytes = int(jnp.size(tail_bucket[-1]._data)) * item
                if tail and tail_bytes + nbytes > last_cap:
                    break
                tail.insert(0, tail_bucket.pop())
                tail_bytes += nbytes
            if tail and tail_bucket:
                raw.append(tail)
    buckets = [_Bucket(i, ps, nranks, comm_dtype)
               for i, ps in enumerate(raw)]
    plan = _Plan(sig, buckets)
    if cache is not None:
        cache[sig] = plan
        while len(cache) > _PLAN_CACHE_CAP:
            cache.popitem(last=False)
    _obs_emit("dp.pack_build", buckets=len(buckets), params=len(params))
    return plan


def _make_pack(b: _Bucket):
    """flat-pack executable: per-param grads -> padded flat comm-dtype
    vector. Traced once per plan; every later call is a cache hit."""
    comm = np.dtype(b.comm_dtype)
    pad = b.padded - b.numel

    def pack(arrs):
        flat = jnp.concatenate([jnp.ravel(a).astype(comm) for a in arrs])
        if pad:
            flat = jnp.concatenate([flat, jnp.zeros((pad,), comm)])
        return flat

    return jax.jit(pack)


def _make_unpack(b: _Bucket, out_sharding=None):
    """flat -> per-param arrays (param dtype/shape), pad dropped."""
    dtype = np.dtype(b.dtype)
    offsets, sizes, shapes = b.offsets, b.sizes, b.shapes

    def unpack(flat):
        return tuple(
            flat[off:off + n].reshape(shape).astype(dtype)
            for off, n, shape in zip(offsets, sizes, shapes))

    if out_sharding is not None:
        return jax.jit(unpack, out_shardings=out_sharding)
    return jax.jit(unpack)


def _make_pack_params(b: _Bucket, sharding):
    """params -> padded flat buffer in the PARAM dtype, laid out as this
    group's owned shards (the reduce-scatter layout of the weight buffer)."""
    dtype = np.dtype(b.dtype)
    pad = b.padded - b.numel

    def pack(arrs):
        flat = jnp.concatenate([jnp.ravel(a).astype(dtype) for a in arrs])
        if pad:
            flat = jnp.concatenate([flat, jnp.zeros((pad,), dtype)])
        return flat

    if sharding is not None:
        return jax.jit(pack, out_shardings=sharding)
    return jax.jit(pack)


# ---------------------------------------------------------------------------
# The reducer
# ---------------------------------------------------------------------------

_LIVE_REDUCERS = []  # weakrefs; drained by the Optimizer pre-step hook


def _drain_live_reducers():
    dead = []
    for ref in _LIVE_REDUCERS:
        r = ref()
        if r is None:
            dead.append(ref)
        else:
            # full flush, not just a wait: in barrier mode (or for hook
            # stragglers) nothing has been issued yet, and step() promises
            # the same drain as sync_gradients()
            r.flush_and_drain()
    for ref in dead:
        _LIVE_REDUCERS.remove(ref)


_hook_registered = [False]


def _register_pre_step_hook():
    if _hook_registered[0]:
        return
    from ..optimizer import optimizer as _opt_mod

    _opt_mod.register_pre_step_hook(_drain_live_reducers)
    _hook_registered[0] = True


class _Reducer:
    """Hook-driven bucket reducer (reference: EagerReducer, reducer.cc).

    Owns the persistent bucket plan and the per-step issue/drain state.
    ``shard_bound`` is set by :func:`sharded_update`; together with
    ``FLAGS_dp_shard_update`` it switches the bucket collective from
    allreduce-AVG (grads written straight back) to reduce-scatter-AVG (the
    flat shard is kept for the sharded optimizer step)."""

    def __init__(self, dp: "DataParallel"):
        import weakref

        self._dp = weakref.ref(dp)
        self._group = dp._group
        self._comm_mb = float(dp._comm_buffer_mb)
        self._last_mb = float(dp._last_comm_buffer_mb)
        self._plan: Optional[_Plan] = None
        self._plan_cache: "OrderedDict[tuple, _Plan]" = OrderedDict()
        self._outstanding: List[_Bucket] = []
        self._exposed_s = 0.0
        # set by the grad-final hooks, cleared by flush_and_drain: the
        # pre-step auto-drain only issues when fresh grads arrived, so an
        # explicit sync_gradients() followed by step() reduces once
        self._dirty = False
        self.shard_bound = False
        self._handles = []
        for p in dp._layers.parameters():
            if not p.stop_gradient:
                self._handles.append(p.register_grad_final_hook(self._on_grad_final))
        _register_pre_step_hook()
        _LIVE_REDUCERS.append(weakref.ref(self))

    # -- plan ------------------------------------------------------------
    def _trainable(self):
        dp = self._dp()
        if dp is None:
            return []
        return [p for p in dp._layers.parameters() if not p.stop_gradient]

    def _ensure_plan(self) -> Optional[_Plan]:
        if self._plan is not None:
            return self._plan
        params = self._trainable()
        if not params:
            return None
        self._plan = _build_plan(params, self._group, self._comm_mb,
                                 self._last_mb, _comm_dtype_name(),
                                 cache=self._plan_cache)
        return self._plan

    def rebuild(self):
        """Drop the cached plan (param set / comm dtype changed)."""
        self._plan = None

    def rebind_group(self, group: Optional[coll.Group]):
        """Point the reducer at a new process group (elastic
        reconfiguration). In-flight bucket state belongs to the old
        world and is dropped; the plan rebuilds lazily against the new
        group — its signature includes gid+nranks, so the pack/unpack
        executables for the new world size are traced fresh."""
        self._group = group
        self._dirty = False
        self._outstanding = []
        if self._plan is not None:
            for b in self._plan.buckets:
                b.ready.clear()
                b.issued = False
                b.task = None
                b.out_ref = None
                b.flat_grad = None
                b.residual = None
        self._plan = None

    def shard_active(self) -> bool:
        return (self.shard_bound
                and bool(flags.flag_value("dp_shard_update"))
                and self._group is not None and self._group.nranks > 1)

    def _sync_enabled(self) -> bool:
        dp = self._dp()
        return dp is not None and dp._sync_enabled

    # -- hook-driven issue ----------------------------------------------
    def _on_grad_final(self, t):
        if not self._sync_enabled():
            return
        if self._group is None or self._group.nranks <= 1:
            return
        self._dirty = True
        if not flags.flag_value("dp_overlap"):
            return
        plan = self._ensure_plan()
        if plan is None:
            return
        b = plan.by_param.get(id(t))
        if b is None or id(t) in b.ready:
            return
        b.ready.add(id(t))
        if len(b.ready) == len(b.params) and all(
                p._grad is not None for p in b.params):
            self._issue(b)

    def _issue(self, b: _Bucket):
        """Pack the bucket and dispatch its collective asynchronously.
        Called from inside run_backward (overlap) or from the drain flush
        (barrier mode / stragglers)."""
        g = self._group
        shard = self.shard_active()
        if b.comm_dtype == "int8":
            self._issue_q8(b, g, shard)
            return
        if b.pack is None:
            b.pack = _make_pack(b)
            _obs_emit("dp.pack_build", bucket=b.index)
        flat = b.pack([p._grad for p in b.params])
        _obs_emit("dp.pack_call", bucket=b.index)
        fn = "reduce_scatter_avg" if shard else "all_reduce"
        b.op = fn
        b.t_issue = time.perf_counter()
        kw = {} if shard else {"op": coll.ReduceOp.AVG}
        rank = max(getattr(g, "rank", 0), 0)
        with comm_task(f"dp:{fn}:bucket{b.index}", getattr(g, "id", 0),
                       rank, (b.padded,), b.comm_dtype):
            out, task = coll._run(g, fn, flat, **kw)
        _obs_emit("dp.wire", bytes=b.nbytes, dtype=b.comm_dtype,
                  ref_bytes=b.padded * np.dtype(b.dtype).itemsize,
                  bucket=b.index)
        if shard:
            mesh = getattr(g, "_mesh", None)
            if (mesh is not None
                    and tuple(getattr(out, "shape", ())) == (b.padded,)):
                # single-controller replicated fallback returned the full
                # reduced buffer: take ownership layout — each rank's shard
                # of the flat buffer lands on its device (ZeRO-1 partition)
                out = jax.device_put(
                    out, NamedSharding(mesh, P(g.axis_name)))
            b.flat_grad = out
        else:
            if b.unpack_grads is None:
                b.unpack_grads = _make_unpack(b)
                _obs_emit("dp.pack_build", bucket=b.index)
            outs = b.unpack_grads(out)
            _obs_emit("dp.pack_call", bucket=b.index)
            for p, o in zip(b.params, outs):
                p._grad = o
        b.out_ref = out
        b.task = task
        b.issued = True
        b.ready.clear()
        self._outstanding.append(b)

    def _issue_q8(self, b: _Bucket, g, shard: bool):
        """Block-scaled int8 wire (quant_comm, EQuARX arXiv 2506.17615):
        error-feedback pack -> one ``q8_gather`` of the int8 buffer ->
        mean-of-dequants decode. The residual carries this step's
        quantization error into the next step's grads; under ``no_sync``
        accumulation the codec runs once on the summed total, so k-step
        accumulation is bit-exact vs quantizing the accumulated grads."""
        if b.qpack is None:
            b.qpack = _qc.make_pack_q8(b)
            b.qdecode = _qc.make_decode_q8(b)
            _obs_emit("dp.pack_build", bucket=b.index)
        if b.residual is None:
            b.residual = _qc.zeros_residual(b)
        # the fused pack takes every grad plus the carried residual in one
        # jit call, so they must share one device set. After the first
        # sharded step the all-gather leaves weight grads committed
        # replicated-over-mesh while small bias grads (and the residual)
        # can still sit on a single device — align the stragglers to the
        # mesh placement; once the layout settles this is a no-op.
        shs = [getattr(p._grad, "sharding", None) for p in b.params]
        target = next((s for s in shs if isinstance(s, NamedSharding)),
                      shs[0])
        if target is not None:
            for p, s in zip(b.params, shs):
                if s != target:
                    p._grad = jax.device_put(p._grad, target)
            if getattr(b.residual, "sharding", None) != target:
                b.residual = jax.device_put(b.residual, target)
        wire, b.residual = b.qpack([p._grad for p in b.params], b.residual)
        _obs_emit("dp.pack_call", bucket=b.index)
        fn = "q8_gather"
        b.op = fn
        b.t_issue = time.perf_counter()
        rank = max(getattr(g, "rank", 0), 0)
        with comm_task(f"dp:{fn}:bucket{b.index}", getattr(g, "id", 0),
                       rank, (b.nbytes,), b.comm_dtype):
            out, task = coll._run(g, fn, wire)
        _obs_emit("dp.wire", bytes=b.nbytes, dtype="int8",
                  ref_bytes=b.padded * np.dtype(b.dtype).itemsize,
                  bucket=b.index)
        flat = b.qdecode(out)
        if shard:
            mesh = getattr(g, "_mesh", None)
            if mesh is not None:
                # ZeRO-1 ownership layout: each rank's shard of the
                # decoded flat buffer lands on its device
                flat = jax.device_put(
                    flat, NamedSharding(mesh, P(g.axis_name)))
            b.flat_grad = flat
        else:
            if b.unpack_grads is None:
                b.unpack_grads = _make_unpack(b)
                _obs_emit("dp.pack_build", bucket=b.index)
            outs = b.unpack_grads(flat)
            _obs_emit("dp.pack_call", bucket=b.index)
            for p, o in zip(b.params, outs):
                p._grad = o
        b.out_ref = flat
        b.task = task
        b.issued = True
        b.ready.clear()
        self._outstanding.append(b)

    # -- drain -----------------------------------------------------------
    def flush_and_drain(self, force: bool = False):
        """The sync point: issue anything not yet issued (barrier mode,
        partially-ready buckets), then wait the outstanding Task handles and
        publish the overlap-efficiency gauge.

        Without ``force``, the issue pass only runs when grads arrived since
        the last flush (``_dirty``) — the pre-step auto-drain after an
        explicit ``sync_gradients()`` must wait, not re-reduce. ``force``
        (the explicit ``sync_gradients()`` call) keeps legacy semantics:
        every call reduces."""
        if not self._sync_enabled():
            return
        g = self._group
        if g is None or g.nranks <= 1:
            return
        if not (force or self._dirty):
            self._wait_outstanding()
            return
        plan = self._ensure_plan()
        if plan is None:
            return
        self._dirty = False
        for b in plan.buckets:
            if b.issued:
                continue
            ps = [p for p in b.params if p._grad is not None]
            if not ps:
                b.ready.clear()
                continue
            if len(ps) == len(b.params):
                self._issue(b)
            else:
                # unused params this step: the flat layout doesn't apply;
                # reduce the present subset via the legacy bucketed path
                sync_param_grads(ps, g, self._comm_mb)
                b.ready.clear()
        self._wait_outstanding()

    def _wait_outstanding(self):
        if not self._outstanding:
            return
        exposed = 0.0
        span = 0.0
        t_drain = time.perf_counter()
        for b in self._outstanding:
            pre_ready = True
            task = b.task
            if task is not None:
                try:
                    pre_ready = bool(task.is_completed())
                except Exception:  # noqa: BLE001 — absent/odd handle: wait
                    pre_ready = False
            w = async_engine.wait_for(
                [b.out_ref] if b.out_ref is not None else [],
                tag=f"dp_bucket{b.index}")
            t_done = time.perf_counter()
            if not pre_ready:
                exposed += w
            span += max(t_done - b.t_issue, 1e-9)
            _obs_emit("dp.bucket_comm", dur_s=t_done - b.t_issue, op=b.op,
                      bucket=b.index, bytes=b.nbytes,
                      hidden=pre_ready)
            b.task = None
            b.out_ref = None
            b.issued = False
            b.ready.clear()
        self._outstanding = []
        eff = 1.0 - (exposed / span) if span > 0 else 1.0
        eff = min(max(eff, 0.0), 1.0)
        self._exposed_s = exposed
        _obs_emit("dp.overlap", dur_s=time.perf_counter() - t_drain,
                  efficiency=round(eff, 4))


class DataParallel(Layer):
    """Reference: python/paddle/distributed/parallel.py:219."""

    def __init__(self, layers: Layer, strategy=None, comm_buffer_size_MB: int = 25,
                 last_comm_buffer_size_MB: int = 1,
                 find_unused_parameters: bool = False,
                 group: Optional[coll.Group] = None, **kw):
        super().__init__()
        self._layers = layers
        self._group = group or coll.get_group(0)
        self._comm_buffer_mb = comm_buffer_size_MB
        self._last_comm_buffer_mb = last_comm_buffer_size_MB
        self.find_unused_parameters = find_unused_parameters
        self._sync_enabled = True
        if self._group is not None and self._group.nranks > 1:
            sync_params_buffers(layers, self._group)
        self._reducer = _Reducer(self)

    def forward(self, *inputs, **kwargs):
        return self._layers(*inputs, **kwargs)

    # -- reducer ---------------------------------------------------------
    def sync_gradients(self):
        """Drain the hook-issued bucket collectives (and, in barrier mode
        or for partially-ready buckets, issue them now).

        Reference fires the collectives from autograd hooks; so do we (see
        _Reducer._on_grad_final) — this call is the step-boundary drain, and
        Optimizer.step() performs the same drain via its pre-step hook, so
        explicit calls are optional."""
        self._reducer.flush_and_drain(force=True)

    def rebind_group(self, group: Optional[coll.Group]):
        """Rebind to a new process group after an elastic
        reconfiguration (see ``paddle_tpu.distributed.elastic``). Bucket
        plans and collective executables for the old world are dropped
        and rebuilt lazily on the next backward; params (and any
        lingering grads) committed to the OLD mesh are re-placed
        replicated on the new mesh — executables traced for the new
        world refuse inputs pinned to departed devices."""
        self._group = group
        self._reducer.rebind_group(group)
        mesh = getattr(group, "_mesh", None) if group is not None else None
        if mesh is not None:
            repl = NamedSharding(mesh, P())
            for t in self._layers.state_dict().values():
                try:
                    t._data = jax.device_put(t._data, repl)
                except Exception:  # noqa: BLE001 — non-array leaf
                    pass
                if getattr(t, "_grad", None) is not None:
                    try:
                        t._grad = jax.device_put(t._grad, repl)
                    except Exception:  # noqa: BLE001
                        t._grad = None

    # -- Layer protocol passthrough -------------------------------------
    def parameters(self, include_sublayers=True):
        return self._layers.parameters(include_sublayers)

    def named_parameters(self, prefix="", include_sublayers=True):
        return self._layers.named_parameters(prefix, include_sublayers)

    def state_dict(self, *a, **k):
        return self._layers.state_dict(*a, **k)

    def set_state_dict(self, state_dict, *a, **k):
        return self._layers.set_state_dict(state_dict, *a, **k)

    def train(self):
        self._layers.train()
        return super().train()

    def eval(self):
        self._layers.eval()
        return super().eval()

    def no_sync(self):
        """Context: skip grad sync (gradient accumulation). Suppresses the
        hook-issued collectives too — grads accumulate locally and the next
        synced backward reduces the k-step total (AVG is linear, so this
        matches a k-step accumulated allreduce exactly)."""
        import contextlib

        @contextlib.contextmanager
        def ctx():
            self._sync_enabled = False
            try:
                yield
            finally:
                self._sync_enabled = True

        return ctx()


# ---------------------------------------------------------------------------
# ZeRO-1 sharded update (FLAGS_dp_shard_update)
# ---------------------------------------------------------------------------

class ShardedUpdate:
    """Optimizer wrapper running the cross-replica sharded weight update
    (Xu et al. arXiv:2004.13336): reduce-scattered flat gradient shards feed
    the fused buffer-donated optimizer step over flat pseudo-params (1/N
    FLOPs and 1/N optimizer-state bytes per device), and the updated flat
    buffers are all-gathered back to replicated per-param arrays.

    Falls back to the replicated update (with a one-time warning) for
    optimizers whose math is not elementwise over the flat buffer — Lamb
    (per-param trust ratio), LBFGS (closure line search), AdamW with
    ``apply_decay_param_fun`` (per-param name predicate) — and whenever a
    grad_clip is configured (clipping needs per-param grads)."""

    def __init__(self, optimizer, model: DataParallel,
                 group: Optional[coll.Group] = None):
        if not isinstance(model, DataParallel):
            raise TypeError(
                "sharded_update needs a DataParallel-wrapped model "
                f"(got {type(model).__name__})")
        self._opt = optimizer
        self._model = model
        self._reducer = model._reducer
        self._group = group or model._group
        self._warned = False
        self._flat_ok = (
            getattr(optimizer, "_flat_shardable", False)
            and getattr(optimizer, "_grad_clip", None) is None
            and getattr(optimizer, "_apply_decay_param_fun", None) is None)
        self._reducer.shard_bound = self._flat_ok

    # -- passthrough -----------------------------------------------------
    def __getattr__(self, name):
        if name.startswith("_"):
            raise AttributeError(name)
        return getattr(self.__dict__["_opt"], name)

    @property
    def inner(self):
        return self._opt

    def _shard_on(self) -> bool:
        return (bool(flags.flag_value("dp_shard_update"))
                and self._group is not None and self._group.nranks > 1)

    def step(self):
        r = self._reducer
        if not self._shard_on():
            r.flush_and_drain()
            return self._opt.step()
        if not self._flat_ok:
            if not self._warned:
                self._warned = True
                import warnings

                warnings.warn(
                    f"{type(self._opt).__name__} cannot run the flat-shard "
                    "update (non-elementwise math or grad_clip/"
                    "apply_decay_param_fun configured); falling back to the "
                    "replicated update", stacklevel=2)
            r.flush_and_drain()
            return self._opt.step()
        r.flush_and_drain()
        plan = r._ensure_plan()
        if plan is None:
            return self._opt.step()
        mesh = getattr(self._group, "_mesh", None)
        axis = getattr(self._group, "axis_name", None)
        shard_sh = (NamedSharding(mesh, P(axis)) if mesh is not None else None)
        repl_sh = NamedSharding(mesh, P()) if mesh is not None else None
        pseudo = []
        leftover: List[Parameter] = []
        for b in plan.buckets:
            if b.flat_grad is None:
                # bucket never reduce-scattered (e.g. sync ran while the
                # shard flag was off, or legacy-path stragglers): pack the
                # already-reduced per-param grads — pack(avg) == avg(pack)
                if any(p._grad is None for p in b.params):
                    # partially-used bucket (find_unused_parameters): its
                    # present grads were reduced by the flush fallback —
                    # step them replicated so no param misses its update
                    leftover.extend(
                        p for p in b.params if p._grad is not None)
                    continue
                # pack in the PARAM dtype (pack_params): no wire is
                # involved here, and the int8 wire codec must never see
                # this path — casting grads to int8 would truncate them
                if b.pack_params is None:
                    b.pack_params = _make_pack_params(b, shard_sh)
                    _obs_emit("dp.pack_build", bucket=b.index)
                b.flat_grad = b.pack_params([p._grad for p in b.params])
            if b.flat_param is None or b.out_ids != [
                    id(p._data) for p in b.params]:
                if b.pack_params is None:
                    b.pack_params = _make_pack_params(b, shard_sh)
                    _obs_emit("dp.pack_build", bucket=b.index)
                b.flat_param = b.pack_params([p._data for p in b.params])
                _obs_emit("dp.pack_call", bucket=b.index)
            if b.pseudo is None:
                b.pseudo = Parameter.from_tensor(
                    b.flat_param, name=f"_dp_flat_b{b.index}")
                b.pseudo.optimize_attr = {"learning_rate": b.lr_mult}
            b.pseudo._data = b.flat_param
            # comm compression: the wire dtype may differ from the param
            # dtype; the update math sees the param dtype (legacy parity)
            fg = b.flat_grad
            if str(fg.dtype) != b.dtype:
                fg = fg.astype(np.dtype(b.dtype))
            b.pseudo._grad = fg
            pseudo.append(b)
        if not pseudo and not leftover:
            return self._opt.step()
        saved = self._opt._parameter_list
        self._opt._parameter_list = [b.pseudo for b in pseudo] + leftover
        try:
            self._opt.step()
        finally:
            self._opt._parameter_list = saved
        # tiled all-gather of the updated flat shards back to per-param
        # replicated arrays (one cached executable per bucket)
        for b in pseudo:
            b.flat_param = b.pseudo._data
            if b.unpack_params is None:
                b.unpack_params = _make_unpack(b, out_sharding=repl_sh)
                _obs_emit("dp.pack_build", bucket=b.index)
            outs = b.unpack_params(b.flat_param)
            _obs_emit("dp.pack_call", bucket=b.index)
            for p, o in zip(b.params, outs):
                p._data = o
            b.out_ids = [id(p._data) for p in b.params]
            _obs_emit("dp.gather", bucket=b.index,
                      bytes=b.padded * np.dtype(b.dtype).itemsize)
            b.flat_grad = None
            b.pseudo._grad = None
        return None

    def reshard(self, new_group: coll.Group):
        """Re-partition the ZeRO-1 flat optimizer-state shards for a new
        world size (elastic reconfiguration, no restart).

        Each flat accumulator (moment1/moment2/velocity over a bucket's
        pseudo-param) is sliced back to its true ``numel``, re-padded to
        the new group's multiple-of-nranks length, and re-placed with
        the new mesh's shard sharding. Bit-exact for the elementwise
        optimizers (Adam/AdamW/Momentum): the pad region holds zero
        grads and zero state by construction, so dropping and re-adding
        it changes no owned element. Scalar accumulators (beta-pow,
        step counters) are carried over untouched."""
        r = self._reducer
        # (re)build the OLD world's layout before rebinding: the padded
        # sizes of the existing accumulators come from the old group, and
        # a back-to-back reshard (shrink then grow with no step between)
        # arrives with the plan already dropped
        old_plan = r._ensure_plan()
        self._group = new_group
        self._model.rebind_group(new_group)  # drops the reducer plan
        if old_plan is None:
            return
        new_n = max(1, getattr(new_group, "nranks", 1))
        mesh = getattr(new_group, "_mesh", None)
        axis = getattr(new_group, "axis_name", None)
        shard_sh = NamedSharding(mesh, P(axis)) if mesh is not None else None
        accs = getattr(self._opt, "_accumulators", {})
        moved = 0
        for b in old_plan.buckets:
            store = accs.get(f"_dp_flat_b{b.index}")
            if store:
                new_padded = -(-b.numel // new_n) * new_n
                repl_sh = (NamedSharding(mesh, P())
                           if mesh is not None else None)
                for name, a in list(store.items()):
                    if tuple(getattr(a, "shape", ())) != (b.padded,):
                        # scalar accumulator (beta-pow etc.) — world-size
                        # free, but still pinned to the old mesh
                        if repl_sh is not None:
                            try:
                                store[name] = jax.device_put(
                                    jnp.asarray(a), repl_sh)
                            except Exception:  # noqa: BLE001
                                pass
                        continue
                    flat = jnp.asarray(a)[:b.numel]
                    if new_padded > b.numel:
                        flat = jnp.concatenate(
                            [flat,
                             jnp.zeros((new_padded - b.numel,), flat.dtype)])
                    if shard_sh is not None:
                        flat = jax.device_put(flat, shard_sh)
                    store[name] = flat
                    moved += 1
            # per-bucket sharded state was packed for the OLD padded size
            b.flat_grad = None
            b.flat_param = None
            b.out_ids = None
            b.pseudo = None
        # fused-step executables are keyed on accumulator shapes; the
        # old-world entries can never hit again
        if hasattr(self._opt, "_fused_cache"):
            self._opt._fused_cache.clear()
        if hasattr(self._opt, "_fused_seen"):
            self._opt._fused_seen.clear()
        _obs_emit("dp.reshard", buckets=len(old_plan.buckets),
                  accumulators=moved, nranks=new_n)

    def optimizer_state_bytes_per_device(self) -> int:
        """Max optimizer-state bytes resident on any single device — the
        1/N memory claim of the sharded update, measurable."""
        per_dev: Dict[object, int] = {}
        for store in self._opt._accumulators.values():
            for a in store.values():
                shards = getattr(a, "addressable_shards", None)
                if shards:
                    for s in shards:
                        per_dev[s.device] = (per_dev.get(s.device, 0)
                                             + int(s.data.nbytes))
                else:
                    per_dev[None] = per_dev.get(None, 0) + int(
                        getattr(a, "nbytes", 0))
        return max(per_dev.values()) if per_dev else 0

    def clear_grad(self, set_to_zero=True):
        self._opt.clear_grad(set_to_zero)
        plan = self._reducer._plan
        if plan is not None:
            for b in plan.buckets:
                b.flat_grad = None
                if b.pseudo is not None:
                    b.pseudo._grad = None

    clear_gradients = clear_grad

    def state_dict(self):
        return self._opt.state_dict()

    def set_state_dict(self, state):
        return self._opt.set_state_dict(state)

    load_state_dict = set_state_dict


def sharded_update(optimizer, model: DataParallel,
                   group: Optional[coll.Group] = None) -> ShardedUpdate:
    """Bind ``optimizer`` to ``model``'s reducer for the ZeRO-1 sharded
    weight update (active while ``FLAGS_dp_shard_update`` is on)."""
    return ShardedUpdate(optimizer, model, group)


def init_parallel_env():
    """Reference: parallel.py:978."""
    return coll.init_parallel_env()


def get_rank_api():
    return get_rank()

"""AutoTuner — cost-model-pruned trial search over parallel configs.

Reference: auto_tuner/tuner.py:21 — AutoTuner holds a search algorithm,
`search_once()` returns the next un-pruned candidate, the launcher runs a
short trial job per candidate, and the recorder keeps the metric ordering.
The reference relaunches whole jobs per trial; on TPU a config change is a
re-jit with different shardings, so `tune()` runs the full loop in-process
against a user trial function.
"""
from __future__ import annotations

import dataclasses
import time
from typing import Callable, Iterator, List, Optional

from ..auto_parallel.engine import (Cluster, CostModel, PlanItem, Planner,
                                    Strategy)
from . import prune
from .recorder import Recorder


@dataclasses.dataclass
class TrialResult:
    plan: Optional[PlanItem]
    time_s: Optional[float] = None
    error: Optional[str] = None
    pruned: Optional[str] = None


@dataclasses.dataclass
class _Candidate:
    plan: PlanItem
    cost: object = None


class _Ctx:
    def __init__(self, cluster, global_batch, max_tp, max_pp, cost_margin):
        self.cluster = cluster
        self.global_batch = global_batch
        self.max_tp = max_tp
        self.max_pp = max_pp
        self.cost_margin = cost_margin
        self.best_trial_s: Optional[float] = None
        self.best_analytic_s: Optional[float] = None


class AutoTuner:
    """Search dp x tp x pp x micro-batch x sharding-stage.

    `trial_fn(plan) -> seconds_per_step` builds + times a real step at
    that config (raising = invalid config, recorded as an error trial).
    """

    def __init__(self, cluster: Optional[Cluster] = None,
                 global_batch: int = 0, max_tp: int = 0, max_pp: int = 0,
                 micro_batch_candidates: Iterator[int] = (1, 2, 4, 8),
                 sharding_stages: Iterator[int] = (0, 3),
                 cost_margin: float = 3.0, max_trials: int = 0):
        self.cluster = cluster or Cluster.auto()
        self.planner = Planner(self.cluster)
        self.recorder = Recorder()
        self.micro_batch_candidates = tuple(micro_batch_candidates)
        self.sharding_stages = tuple(sharding_stages)
        self.max_trials = max_trials
        self._ctx = _Ctx(self.cluster, global_batch, max_tp, max_pp,
                         cost_margin)
        self._pruned: List[TrialResult] = []

    # -- search space ---------------------------------------------------------

    def candidates(self, strategy: Optional[Strategy] = None,
                   sizes: Optional[dict] = None) -> List[_Candidate]:
        strategy = strategy or Strategy()
        cost_model = self.planner.cost_model
        out = []
        for base in self.planner.candidates(strategy):
            for mbs in self.micro_batch_candidates:
                if mbs < base.pp:
                    continue
                for stage in self.sharding_stages:
                    plan = PlanItem(dp=base.dp, tp=base.tp, pp=base.pp,
                                    micro_batches=mbs, sharding_stage=stage)
                    cost = cost_model.estimate(plan=plan, **sizes) \
                        if sizes else None
                    plan.cost = cost
                    out.append(_Candidate(plan=plan, cost=cost))
        # analytic best first, so the cost-bound prune bites early
        out.sort(key=lambda c: c.cost.total_s if c.cost else 0.0)
        return out

    def search_once(self, cands: List[_Candidate]) -> Optional[_Candidate]:
        """Next un-pruned candidate (reference: tuner.py:62)."""
        while cands:
            cand = cands.pop(0)
            reason = prune.apply_all(self._ctx, cand)
            if reason is None:
                return cand
            self._pruned.append(TrialResult(plan=cand.plan, pruned=reason))
        return None

    # -- the loop -------------------------------------------------------------

    def tune(self, trial_fn: Callable[[PlanItem], float],
             strategy: Optional[Strategy] = None,
             sizes: Optional[dict] = None) -> Optional[PlanItem]:
        cands = self.candidates(strategy, sizes)
        trials = 0
        while True:
            if self.max_trials and trials >= self.max_trials:
                break
            cand = self.search_once(cands)
            if cand is None:
                break
            trials += 1
            try:
                t = float(trial_fn(cand.plan))
                self.recorder.add(TrialResult(plan=cand.plan, time_s=t))
                if (self._ctx.best_trial_s is None
                        or t < self._ctx.best_trial_s):
                    self._ctx.best_trial_s = t
                    self._ctx.best_analytic_s = (
                        cand.cost.total_s if cand.cost else None)
            except Exception as e:  # invalid config: record, keep searching
                self.recorder.add(TrialResult(
                    plan=cand.plan, error=f"{type(e).__name__}: {e}"))
        best = self.recorder.best()
        return best.plan if best else None

    @property
    def pruned(self) -> List[TrialResult]:
        return list(self._pruned)

    @property
    def history(self) -> List[TrialResult]:
        return self.recorder.sorted() + self._pruned

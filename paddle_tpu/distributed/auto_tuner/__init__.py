"""distributed.auto_tuner parity — search the hybrid-parallel config space.

Reference: python/paddle/distributed/auto_tuner/{tuner.py:21,prune.py,
recorder.py} — AutoTuner.search_once() yields candidate configs from a
registered prune chain; each is launched as a short trial job; a Recorder
sorts history and reports the best.

TPU-native: candidates come from the auto-parallel Planner's mesh
factorizations crossed with micro-batch/sharding/remat axes; the
CostModel pre-prunes (memory fit + analytic time bound) before any trial
spends chip seconds; trials time a user-supplied step runner at each
surviving config. A GSPMD trial is just re-jitting with different
shardings — no process relaunch, so tuning is minutes, not hours.
"""
from .tuner import AutoTuner, TrialResult
from .recorder import Recorder
from . import prune

__all__ = ["AutoTuner", "TrialResult", "Recorder", "prune"]

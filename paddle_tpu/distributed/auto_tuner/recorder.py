"""Trial history recorder.

Reference: auto_tuner/recorder.py — History_recorder keeps per-trial
metric rows, sorts by the tuning metric, stores best, and can dump csv.
Ours records TrialResult rows, sorts by time/step, dumps jsonl.
"""
from __future__ import annotations

import dataclasses
import json
from typing import List, Optional


class Recorder:
    def __init__(self):
        self.history: List = []

    def add(self, result) -> None:
        self.history.append(result)

    def sorted(self) -> List:
        ok = [r for r in self.history if r.time_s is not None]
        bad = [r for r in self.history if r.time_s is None]
        return sorted(ok, key=lambda r: r.time_s) + bad

    def best(self):
        s = self.sorted()
        return s[0] if s and s[0].time_s is not None else None

    def store_history(self, path: str) -> None:
        with open(path, "w") as f:
            for r in self.sorted():
                f.write(json.dumps(dataclasses.asdict(r), default=str)
                        + "\n")

    def load_history(self, path: str) -> None:
        from .tuner import TrialResult

        with open(path) as f:
            for line in f:
                d = json.loads(line)
                d.pop("plan", None)
                self.history.append(TrialResult(plan=None, **d))

"""Pruning rules over candidate configs.

Reference: auto_tuner/prune.py — a registry of `prune_by_*` predicates
(mp degree, pp degree, micro-batch divisibility, sharding stage, memory
model) applied before a candidate is trialled. Same shape here: each rule
takes (ctx, cfg) and returns a reason string to prune or None to keep;
`register_prune` adds custom rules.
"""
from __future__ import annotations

from typing import Callable, List, Optional

_PRUNE_FUNCS: List[Callable] = []


def register_prune(fn: Callable) -> Callable:
    """Reference: prune.py:112 register_prune."""
    _PRUNE_FUNCS.append(fn)
    return fn


def apply_all(ctx, cfg) -> Optional[str]:
    for fn in _PRUNE_FUNCS:
        reason = fn(ctx, cfg)
        if reason:
            return f"{fn.__name__}: {reason}"
    return None


@register_prune
def prune_by_degree(ctx, cfg):
    """dp*tp*pp must cover the cluster (reference prune_by_mp/_pp)."""
    if cfg.plan.degree != ctx.cluster.n_devices:
        return (f"degree {cfg.plan.degree} != cluster "
                f"{ctx.cluster.n_devices}")
    if ctx.max_tp and cfg.plan.tp > ctx.max_tp:
        return f"tp {cfg.plan.tp} > limit {ctx.max_tp}"
    if ctx.max_pp and cfg.plan.pp > ctx.max_pp:
        return f"pp {cfg.plan.pp} > limit {ctx.max_pp}"
    return None


@register_prune
def prune_by_mbs(ctx, cfg):
    """Global batch must split evenly into dp x micro_batches
    (reference prune.py:307 prune_by_mbs)."""
    gb = ctx.global_batch
    if gb and gb % (cfg.plan.dp * cfg.plan.micro_batches) != 0:
        return (f"global batch {gb} not divisible by dp*mbs "
                f"{cfg.plan.dp}x{cfg.plan.micro_batches}")
    if cfg.plan.pp > 1 and cfg.plan.micro_batches < cfg.plan.pp:
        return "fewer microbatches than pipeline stages"
    return None


@register_prune
def prune_by_memory(ctx, cfg):
    """Analytic HBM bound (reference: memory_cost_model.py)."""
    if cfg.cost is not None and not cfg.cost.fits:
        return (f"estimated {cfg.cost.memory_bytes / 1e9:.1f} GB > "
                f"{ctx.cluster.hbm_bytes / 1e9:.1f} GB HBM")
    return None


@register_prune
def prune_by_cost_bound(ctx, cfg):
    """Skip candidates the analytic model puts far beyond the best
    measured config's OWN analytic cost (reference: the history-based
    prune_by_*_history chain — ours uses the cost model instead of rerun
    history). Analytic is compared to analytic, so model bias cancels."""
    ref = ctx.best_analytic_s
    if (ref is not None and cfg.cost is not None
            and cfg.cost.total_s > ctx.cost_margin * ref):
        return (f"analytic {cfg.cost.total_s:.4f}s > "
                f"{ctx.cost_margin:.1f}x best-config analytic {ref:.4f}s")
    return None

"""GroupSharded (ZeRO-2/3) — TPU-native.

Reference design (SURVEY.md §2.5): `GroupShardedStage2`
(fleet/meta_parallel/sharding/group_sharded_stage2.py:46) registers backward
hooks that reduce-scatter gradient slices to their owner rank and shards
optimizer states; `GroupShardedStage3` (group_sharded_stage3.py:85) also
shards parameter storage, all-gathering each param before use and releasing
it after, with optional CPU offload.

TPU-native redesign: sharded storage is a *layout*, not a rank-local buffer.
A param/grad/accumulator "owned by rank r" is a global `jax.Array` laid out
`Shard(0)` over the sharding group's mesh axis — each device's HBM holds only
its slice, which IS the ZeRO memory saving. The hook machinery collapses
into GSPMD data movement:

- stage2: gradients + optimizer states are re-laid-out sharded after
  backward/step; XLA turns the grad psum feeding a sharded consumer into a
  reduce-scatter (the EagerReducer/FusedCommBuffer fast path, compiled).
- stage3: parameter storage itself is sharded; an op consuming the param
  makes XLA emit the all-gather just-in-time, and dropping the gathered copy
  after use is automatic (it was a temporary). The reference's manual
  pre-forward allgather + post-forward release becomes compiler-scheduled.
- offload: `jax.device_put(..., TransferToMemoryKind("pinned_host"))` analog
  is exposed via the `offload` flag — states are kept on host memory and
  streamed in for the update.
"""
from __future__ import annotations

from typing import List, Optional

import jax
from jax.sharding import NamedSharding, PartitionSpec as P

from ...core.tensor import Parameter
from ...nn.layer.layers import Layer
from .. import collective as coll


def _group_sharding(group: coll.Group, ndim: int, shape) -> Optional[NamedSharding]:
    """Shard(0) over the group axis when dim-0 divides; else replicated
    (rule shared with the auto-parallel stage plans via dim0_shardable)."""
    from ..auto_parallel.placement import dim0_shardable

    if group is None or group.mesh is None or group.nranks <= 1:
        return None
    if ndim > 0 and dim0_shardable(shape, group.nranks):
        return NamedSharding(group.mesh, P(group.axis_name))
    return NamedSharding(group.mesh, P())


def _to_host(arr):
    """Offload: host-backed storage (pinned_host memory kind when the backend
    supports it; falls back to committed device storage otherwise)."""
    try:
        sh = arr.sharding.with_memory_kind("pinned_host")
        return jax.device_put(arr, sh)
    except Exception:
        return arr


class GroupShardedOptimizerStage2:
    """Optimizer wrapper sharding states (and grads pre-step) over the group.

    Reference: GroupShardedOptimizerStage2
    (fleet/meta_parallel/sharding/group_sharded_optimizer_stage2.py).
    """

    def __init__(self, params: List[Parameter], optim, group: Optional[coll.Group] = None,
                 offload: bool = False, device: str = "tpu",
                 shard_grads: bool = True, **kw):
        self._optim = optim
        self._group = group or coll._get_or_init_default()
        self._offload = offload
        # stage1 ('os') shards only optimizer states; stage2 also grads
        self._do_shard_grads = shard_grads
        self._params = list(params)
        # params must live on the group's device set so the raw-array
        # optimizer math can combine them with mesh-sharded grads/states;
        # params already laid out there (e.g. stage3-sharded) are left alone
        if self._group.mesh is not None and self._group.nranks > 1:
            repl = NamedSharding(self._group.mesh, P())
            for p in self._params:
                if len(getattr(p._data.sharding, "device_set", ())) <= 1:
                    p._data = jax.device_put(p._data, repl)

    def __getattr__(self, name):
        return getattr(self._optim, name)

    def _shard_grads(self):
        """Reduce-scatter analog: lay grads out over the sharding axis so the
        optimizer update reads only local slices."""
        if not self._do_shard_grads:
            return
        for p in self._params:
            if p._grad is None:
                continue
            sh = _group_sharding(self._group, getattr(p._grad, "ndim", 0),
                                 getattr(p._grad, "shape", ()))
            if sh is not None and sh.spec != P():
                p._grad = jax.device_put(p._grad, sh)

    def _shard_states(self):
        accs = getattr(self._optim, "_accumulators", None)
        if accs is None:
            return
        for pname, d in accs.items():
            for aname, arr in d.items():
                sh = _group_sharding(self._group, getattr(arr, "ndim", 0),
                                     getattr(arr, "shape", ()))
                if sh is not None and sh.spec != P():
                    arr = jax.device_put(arr, sh)
                if self._offload:
                    arr = _to_host(arr)
                d[aname] = arr

    def _restore_states(self):
        """Stream offloaded accumulators back to device HBM for the update."""
        accs = getattr(self._optim, "_accumulators", None)
        if accs is None:
            return
        for d in accs.values():
            for aname, arr in d.items():
                try:
                    if arr.sharding.memory_kind not in (None, "device"):
                        d[aname] = jax.device_put(
                            arr, arr.sharding.with_memory_kind("device"))
                except Exception:
                    pass

    def step(self):
        self._shard_grads()
        if self._offload:
            self._restore_states()
        self._optim.step()
        self._shard_states()

    def clear_grad(self, *a, **k):
        self._optim.clear_grad(*a, **k)

    def minimize(self, loss, *a, **k):
        loss.backward()
        self.step()
        self.clear_grad()


class GroupShardedStage2(Layer):
    """ZeRO-2 model wrapper (reference: group_sharded_stage2.py:46)."""

    def __init__(self, layer: Layer, sharding_optimizer, group: Optional[coll.Group] = None,
                 sync_buffers: bool = False, buffer_max_size: int = 2 ** 23,
                 auto_refresh_trainable: bool = True, device: str = "tpu",
                 dp_group=None, **kw):
        super().__init__()
        self._layers = layer
        self._group = group or coll._get_or_init_default()
        self._sharding_optimizers = (
            sharding_optimizer if isinstance(sharding_optimizer, (list, tuple))
            else [sharding_optimizer])
        if sync_buffers and self._group.nranks > 1:
            for b in layer.buffers():
                coll.broadcast(b, src=self._group.ranks[0], group=self._group)

    def forward(self, *inputs, **kwargs):
        return self._layers(*inputs, **kwargs)

    def parameters(self, include_sublayers=True):
        return self._layers.parameters(include_sublayers)

    def named_parameters(self, prefix="", include_sublayers=True):
        return self._layers.named_parameters(prefix, include_sublayers)

    def state_dict(self, *a, **k):
        return self._layers.state_dict(*a, **k)

    def set_state_dict(self, sd, *a, **k):
        return self._layers.set_state_dict(sd, *a, **k)

    def sublayers(self, include_self=False):
        return self._layers.sublayers(include_self)

    def train(self):
        self._layers.train()
        return super().train()

    def eval(self):
        self._layers.eval()
        return super().eval()

    def to(self, *a, **k):
        self._layers.to(*a, **k)
        return self

    def grad_scale(self):
        """Reference scales grads by 1/world after accumulation; with the
        global-array design gradients are already globally correct."""
        return


class GroupShardedStage3(Layer):
    """ZeRO-3 model wrapper (reference: group_sharded_stage3.py:85): param
    STORAGE is sharded over the group. On XLA the just-in-time all-gather and
    post-use release are compiler-scheduled; here we (re)lay out every param
    Shard(0) over the group axis and keep optimizer states in the same
    layout."""

    def __init__(self, layer: Layer, optimizer=None, group: Optional[coll.Group] = None,
                 sync_buffers: bool = False, device: str = "tpu",
                 segment_size: int = 2 ** 20, pretrain_sync_models: bool = True,
                 offload: bool = False, sync_comm: bool = False,
                 dp_group=None, exclude_layer=None, param2buffer_size=None, **kw):
        super().__init__()
        self._layers = layer
        self._group = group or coll._get_or_init_default()
        self._offload = offload
        self._optim = optimizer
        self._shard_parameters()
        if sync_buffers and self._group.nranks > 1:
            for b in layer.buffers():
                coll.broadcast(b, src=self._group.ranks[0], group=self._group)

    def _shard_parameters(self):
        for p in self._layers.parameters():
            sh = _group_sharding(self._group, p.ndim, p.shape)
            if sh is not None:
                p._data = jax.device_put(p._data, sh)

    def forward(self, *inputs, **kwargs):
        return self._layers(*inputs, **kwargs)

    def parameters(self, include_sublayers=True):
        return self._layers.parameters(include_sublayers)

    def named_parameters(self, prefix="", include_sublayers=True):
        return self._layers.named_parameters(prefix, include_sublayers)

    def state_dict(self, *a, **k):
        return self._layers.state_dict(*a, **k)

    def set_state_dict(self, sd, *a, **k):
        out = self._layers.set_state_dict(sd, *a, **k)
        self._shard_parameters()
        return out

    def sublayers(self, include_self=False):
        return self._layers.sublayers(include_self)

    def train(self):
        self._layers.train()
        return super().train()

    def eval(self):
        self._layers.eval()
        return super().eval()

    def get_all_parameters(self, convert2cpu: bool = False):
        """Gather full (replicated) params (reference: stage3
        get_all_parameters — the pre-save gather)."""
        for p in self._layers.parameters():
            if self._group.mesh is not None and self._group.nranks > 1:
                p._data = jax.device_put(
                    p._data, NamedSharding(self._group.mesh, P()))
        return self._layers.parameters()


class GroupShardedScaler:
    """AMP loss-scaler wrapper for group-sharded models (reference:
    group_sharded_utils.py GroupShardedScaler). bf16-first TPU training
    rarely needs it; kept for fp16 parity — found_inf is implicitly global
    because gradients are global arrays."""

    def __init__(self, scaler):
        self._scaler = scaler

    def __getattr__(self, name):
        return getattr(self._scaler, name)

    def scale(self, loss):
        return self._scaler.scale(loss)

    def step(self, optimizer):
        self._scaler.step(optimizer)

    def unscale_(self, optimizer):
        return self._scaler.unscale_(optimizer)

    def minimize(self, optimizer, scaled_loss):
        return self._scaler.minimize(optimizer, scaled_loss)

    def update(self):
        if hasattr(self._scaler, "update"):
            self._scaler.update()

"""paddle.distributed.sharding parity — GroupSharded (ZeRO) API.

Reference: python/paddle/distributed/sharding/group_sharded.py
(`group_sharded_parallel`, `save_group_sharded_model`).
"""
from __future__ import annotations

import os

from .group_sharded import (  # noqa: F401
    GroupShardedOptimizerStage2,
    GroupShardedScaler,
    GroupShardedStage2,
    GroupShardedStage3,
)


def group_sharded_parallel(model, optimizer, level: str, scaler=None,
                           group=None, offload: bool = False,
                           sync_buffers: bool = False, buffer_max_size=2 ** 23,
                           segment_size=2 ** 20, sync_comm: bool = False,
                           dp_group=None, exclude_layer=None):
    """Wrap (model, optimizer, scaler) for group-sharded training.

    Reference: distributed/sharding/group_sharded.py group_sharded_parallel —
    level: 'os' (stage1: optimizer-state sharding), 'os_g' (stage2: + grads),
    'p_g_os' (stage3: + params).
    """
    if level not in ("os", "os_g", "p_g_os"):
        raise ValueError(f"level must be one of os/os_g/p_g_os, got {level!r}")
    params = list(model.parameters())
    if level in ("os", "os_g"):
        optimizer = GroupShardedOptimizerStage2(
            params, optimizer, group=group, offload=offload,
            shard_grads=(level == "os_g"))
        model = GroupShardedStage2(model, optimizer, group=group,
                                   sync_buffers=sync_buffers,
                                   buffer_max_size=buffer_max_size,
                                   dp_group=dp_group)
    else:
        model = GroupShardedStage3(model, optimizer=optimizer, group=group,
                                   sync_buffers=sync_buffers,
                                   segment_size=segment_size, offload=offload,
                                   sync_comm=sync_comm, dp_group=dp_group,
                                   exclude_layer=exclude_layer)
        optimizer = GroupShardedOptimizerStage2(
            params, optimizer, group=group, offload=offload)
    if scaler is not None:
        scaler = GroupShardedScaler(scaler)
    return model, optimizer, scaler


def save_group_sharded_model(model, output, optimizer=None):
    """Gather full params and save (reference: save_group_sharded_model)."""
    from ... import save

    if isinstance(model, GroupShardedStage3):
        model.get_all_parameters()
        inner = model._layers
    elif isinstance(model, GroupShardedStage2):
        inner = model._layers
    else:
        inner = model
    os.makedirs(output, exist_ok=True)
    save(inner.state_dict(), os.path.join(output, "model.pdparams"))
    if optimizer is not None:
        opt = getattr(optimizer, "_optim", optimizer)
        if hasattr(opt, "state_dict"):
            save(opt.state_dict(), os.path.join(output, "model.pdopt"))

"""Distributed environment: rank/world discovery.

Reference analog: ParallelEnv (python/paddle/distributed/parallel.py) reading
PADDLE_TRAINER_ID / PADDLE_TRAINERS_NUM set by the launcher. On TPU the same
variables are honored, and under a multi-host PJRT runtime jax.process_index
is the ground truth.
"""
from __future__ import annotations

import os


def get_rank() -> int:
    v = os.environ.get("PADDLE_TRAINER_ID")
    if v is not None:
        return int(v)
    try:
        import jax

        return jax.process_index()
    except Exception:
        return 0


def get_world_size() -> int:
    v = os.environ.get("PADDLE_TRAINERS_NUM")
    if v is not None:
        return int(v)
    try:
        import jax

        return jax.process_count()
    except Exception:
        return 1


class ParallelEnv:
    @property
    def rank(self):
        return get_rank()

    @property
    def local_rank(self):
        return int(os.environ.get("PADDLE_LOCAL_RANK", get_rank()))

    @property
    def world_size(self):
        return get_world_size()

    @property
    def nranks(self):
        return get_world_size()

    @property
    def dev_id(self):
        return self.local_rank

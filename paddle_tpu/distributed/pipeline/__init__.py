"""TPU-native MPMD pipeline parallelism.

Three layers (arXiv 2412.14374's partition / schedule / runtime split):

- :mod:`.partition` — split a layer sequence (or ``LayerDesc`` descriptors)
  into ``pp`` contiguous stages: uniform, ``layer:<Class>`` or
  parameter/FLOP-balanced, with ``seg_method`` as the manual override;
- :mod:`.schedule` — 1F1B / GPipe / ZB-H1 / interleaved schedules as
  explicit (stage, microbatch, phase) action lists, deterministically
  validated and unit-time simulated (closed-form bubble accounting);
- :mod:`.runtime` — the engine: per-stage jitted executables
  (signature-keyed, zero steady-state retraces), async P2P stage handoff
  through ``core.async_engine``, dependency-driven dispatch, dp x pp x
  sharding composition, and pipeline.* observability.

``fleet.meta_parallel.pp_schedule`` / ``PipelineParallel`` are the
Paddle-API front ends over this package.
"""
from . import partition, schedule  # noqa: F401
from .runtime import PipelineEngine, set_chaos_hook  # noqa: F401
from .schedule import (  # noqa: F401
    Action, ScheduleError, build_schedule, closed_form_bubble, simulate,
    stage_op_sequence, validate)

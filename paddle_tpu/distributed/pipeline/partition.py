"""Stage partitioner: split a layer sequence into ``pp`` contiguous stages.

The reference's ``SegmentLayers`` (fleet/meta_parallel/parallel_layers/
pp_layers.py) supports ``uniform`` and ``layer:ClassName`` segmentation.
This module is the single implementation of both, plus cost-balanced
segmentation (``param`` / ``flops``) that the reference reserves for its
auto-parallel pass: estimate a per-item cost, then pick cut points that
minimize the maximum stage cost (classic contiguous-partition DP).
``PipelineLayer``/``SegmentLayers`` route here; an explicit ``seg_method``
is the manual override of the balance heuristic.

Costs come from :func:`estimate_cost`. Built ``Layer`` instances report
their true parameter count; ``LayerDesc`` items are built once under a
saved/restored RNG state (so probing never perturbs training streams) and
discarded. FLOP cost is modeled as 2*params — exact for the dense layers
the pipeline stages here are made of, and monotone-equivalent for ranking
in general.
"""
from __future__ import annotations

import math
from typing import List, Sequence

from ...core import rng


def uniform(num_items: int, num_parts: int) -> List[int]:
    """Even split: cut points of ``num_items`` items into ``num_parts``
    contiguous runs (len == num_parts + 1, starts at 0, ends at num_items)."""
    if num_parts <= 0:
        raise ValueError("num_parts must be positive")
    if num_items < num_parts:
        raise ValueError(
            f"cannot split {num_items} layers into {num_parts} stages")
    result = [0]
    base, extra = divmod(num_items, num_parts)
    for i in range(num_parts):
        result.append(result[-1] + base + (1 if i < extra else 0))
    return result


def segment_by_class(descs: Sequence, num_parts: int,
                     class_name: str) -> List[int]:
    """Cut so that each stage holds an equal share of layers whose class is
    ``class_name`` (the reference's ``seg_method='layer:Linear'``)."""
    idx = [i for i, d in enumerate(descs)
           if _class_name_of(d) == class_name]
    if len(idx) < num_parts:
        raise ValueError(
            f"only {len(idx)} {class_name!r} layers for {num_parts} stages")
    marks = uniform(len(idx), num_parts)
    cuts = [0]
    for p in range(1, num_parts):
        cuts.append(idx[marks[p]])
    cuts.append(len(descs))
    return cuts


def _class_name_of(d) -> str:
    from ..fleet.meta_parallel.parallel_layers.pp_layers import LayerDesc
    if isinstance(d, LayerDesc):
        return d.layer_func.__name__
    return type(d).__name__


def estimate_cost(d) -> float:
    """Per-item cost for balanced segmentation: parameter count (FLOPs are
    modeled as 2*params, so both rank identically). LayerDesc items are
    built once with the RNG stream saved and restored; parameter-free items
    (activations, callables) get a small epsilon so empty stages lose
    ties deterministically."""
    from ...nn import Layer
    from ..fleet.meta_parallel.parallel_layers.pp_layers import LayerDesc
    if isinstance(d, LayerDesc):
        state = rng.get_rng_state()
        try:
            built = d.build_layer()
        finally:
            rng.set_rng_state(state)
        return estimate_cost(built)
    if isinstance(d, Layer):
        n = 0
        for p in d.parameters():
            n += int(math.prod(p.shape)) if p.shape else 1
        return float(n) if n else 1e-3
    return 1e-3  # bare callable / activation


def balanced_partition(costs: Sequence[float], num_parts: int) -> List[int]:
    """Cut points minimizing the maximum stage cost over contiguous runs
    (O(n^2 * k) DP — layer counts are small). Every stage gets >= 1 item."""
    n = len(costs)
    if n < num_parts:
        raise ValueError(
            f"cannot split {n} layers into {num_parts} stages")
    prefix = [0.0]
    for c in costs:
        prefix.append(prefix[-1] + c)

    def run_cost(i: int, j: int) -> float:
        return prefix[j] - prefix[i]

    INF = float("inf")
    # best[k][j] = minimal max-stage-cost splitting items [0, j) into k runs
    best = [[INF] * (n + 1) for _ in range(num_parts + 1)]
    cut = [[0] * (n + 1) for _ in range(num_parts + 1)]
    best[0][0] = 0.0
    for k in range(1, num_parts + 1):
        for j in range(k, n + 1):
            for i in range(k - 1, j):
                cand = max(best[k - 1][i], run_cost(i, j))
                if cand < best[k][j]:
                    best[k][j] = cand
                    cut[k][j] = i
    cuts = [n]
    k, j = num_parts, n
    while k > 0:
        j = cut[k][j]
        cuts.append(j)
        k -= 1
    cuts.reverse()
    return cuts


def segment(descs: Sequence, num_parts: int,
            method: str = "uniform") -> List[int]:
    """Split ``descs`` into ``num_parts`` contiguous stages.

    method: 'uniform' | 'layer:<ClassName>' | 'param' | 'flops'.
    Returns cut points (len == num_parts + 1). 'param'/'flops' balance the
    estimated per-stage cost; an explicit 'uniform'/'layer:' seg_method is
    the manual override."""
    if method == "uniform":
        return uniform(len(descs), num_parts)
    if method.startswith("layer:"):
        return segment_by_class(descs, num_parts, method.split(":", 1)[1])
    if method in ("param", "flops"):
        costs = [estimate_cost(d) for d in descs]
        if method == "flops":
            costs = [2.0 * c for c in costs]
        return balanced_partition(costs, num_parts)
    raise ValueError(
        f"unknown seg_method {method!r} (expected 'uniform', "
        f"'layer:<ClassName>', 'param' or 'flops')")

"""Pipeline schedules as explicit, validated (stage, microbatch, phase) lists.

The reference builds its 1F1B order imperatively inside
``forward_backward_pipeline`` and its zero-bubble variant as a scheduler
pass. The MPMD-pipelining literature (arXiv 2412.14374) instead treats a
schedule as *data*: a per-stage list of (stage, microbatch, phase) actions
that can be validated, simulated and compared before anything executes.
That is what this module provides:

- :func:`stage_op_sequence` — the canonical per-stage op order for
  ``1f1b`` / ``gpipe`` / ``zbh1`` (single source of truth; the fleet shim
  ``pp_schedule._stage_op_sequence`` delegates here);
- :func:`build_schedule` — all stages' actions, **validated
  deterministically before any execution** (:func:`validate`): every
  microbatch gets exactly one forward and one complete backward per stage,
  BX precedes its BW, the 1F1B activation-memory bound holds, and a
  dependency-driven dry run proves the lists are deadlock-free;
- :func:`simulate` — unit-time dependency-timed execution of the lists
  (device-group contention included, so interleaved virtual chunks compete
  for their physical group), yielding makespan / per-group busy time /
  bubble fraction. For synchronous 1F1B with equal-cost F and B this
  reproduces the closed form exactly:

      bubble(pp, m) = (pp - 1) / (m + pp - 1)

  and for interleaving (v virtual chunks per group over pp groups) the
  generalized ``(pp - 1) / (v*m + pp - 1)`` — the v-fold bubble shrink
  that motivates virtual stages.

Phases: ``F`` forward, ``B`` monolithic backward, ``BX`` input-grad half,
``BW`` weight-grad half (ZB-H1 split).
"""
from __future__ import annotations

from typing import Dict, List, NamedTuple, Tuple


class Action(NamedTuple):
    stage: int        # GLOBAL stage (physical stage or virtual chunk)
    microbatch: int
    phase: str        # F | B | BX | BW


class ScheduleError(ValueError):
    """A schedule failed pre-execution validation."""


_PHASES = ("F", "B", "BX", "BW")


def normalize(schedule: str) -> str:
    """Canonical schedule name: '1f1b' | 'gpipe' | 'zbh1' | 'interleave'."""
    s = schedule.lower().replace("-", "").replace("_", "")
    if s in ("zb", "zerobubble", "zbh1"):
        return "zbh1"
    if s == "fthenb":
        return "gpipe"
    if s not in ("1f1b", "gpipe", "interleave", "zbh1"):
        raise ValueError(f"unknown pipeline schedule {schedule!r}")
    return s


def stage_op_sequence(schedule: str, s: int, P_: int, M: int
                      ) -> List[Tuple[str, int]]:
    """Per-stage op order as (phase, microbatch) pairs.

    1f1b: warmup of min(M, P-s-1) forwards then strict F/B alternation;
    gpipe: all F then all B; zbh1: 1F1B with B split into BX (input grad,
    critical path) and BW (weight grad), BWs queued late so the dependency
    dispatcher slides them into former bubble slots."""
    if schedule == "gpipe":
        return [("F", m) for m in range(M)] + [("B", m) for m in range(M)]
    w = min(M, P_ - s - 1)
    seq = [("F", m) for m in range(w)]
    if schedule == "zbh1":
        fm, xm, wm = w, 0, 0
        while fm < M:             # steady state: F / BX pairs
            seq.append(("F", fm)); fm += 1
            seq.append(("BX", xm)); xm += 1
        while xm < M:             # cooldown: BX chain + BW bubble-fill
            seq.append(("BX", xm)); xm += 1
            if wm < xm - 1:       # keep one BW in reserve for reordering
                seq.append(("BW", wm)); wm += 1
        while wm < M:
            seq.append(("BW", wm)); wm += 1
        return seq
    fm, bm = w, 0
    while fm < M or bm < M:
        if fm < M:
            seq.append(("F", fm))
            fm += 1
        if bm < M:
            seq.append(("B", bm))
            bm += 1
    return seq


def stage_actions(schedule: str, s: int, P_: int, M: int) -> List[Action]:
    return [Action(s, m, k) for k, m in stage_op_sequence(schedule, s, P_, M)]


# ---------------------------------------------------------------------------
# Validation — deterministic, before any execution
# ---------------------------------------------------------------------------

def validate(actions: Dict[int, List[Action]], P_: int, M: int,
             schedule: str = "1f1b") -> None:
    """Raise :class:`ScheduleError` unless the per-stage action lists form a
    complete, deadlock-free, memory-bounded pipeline schedule."""
    if sorted(actions) != list(range(P_)):
        raise ScheduleError(f"stages {sorted(actions)} != 0..{P_ - 1}")
    for s, seq in actions.items():
        fs = [a.microbatch for a in seq if a.phase == "F"]
        bs = [a.microbatch for a in seq if a.phase == "B"]
        xs = [a.microbatch for a in seq if a.phase == "BX"]
        ws = [a.microbatch for a in seq if a.phase == "BW"]
        if any(a.stage != s for a in seq):
            raise ScheduleError(f"stage {s}: action with foreign stage id")
        if any(a.phase not in _PHASES for a in seq):
            raise ScheduleError(f"stage {s}: unknown phase")
        if sorted(fs) != list(range(M)):
            raise ScheduleError(
                f"stage {s}: forwards cover {sorted(fs)} != 0..{M - 1}")
        if bs and (xs or ws):
            raise ScheduleError(
                f"stage {s}: mixes monolithic B with split BX/BW")
        if bs:
            if sorted(bs) != list(range(M)):
                raise ScheduleError(
                    f"stage {s}: backwards cover {sorted(bs)} != 0..{M - 1}")
        else:
            if sorted(xs) != list(range(M)) or sorted(ws) != list(range(M)):
                raise ScheduleError(
                    f"stage {s}: split backward does not cover every "
                    f"microbatch (BX={sorted(xs)}, BW={sorted(ws)})")
            pos = {(a.phase, a.microbatch): i for i, a in enumerate(seq)}
            for m in range(M):
                if pos[("BX", m)] > pos[("BW", m)]:
                    raise ScheduleError(
                        f"stage {s}: BW({m}) scheduled before its BX")
        # activation-memory bound: in-flight forwards never exceed warmup+1
        # for 1f1b/zbh1 (gpipe holds all M by design)
        if schedule in ("1f1b", "zbh1", "interleave"):
            w = min(M, P_ - s - 1)
            inflight = peak = 0
            for a in seq:
                if a.phase == "F":
                    inflight += 1
                elif a.phase in ("B", "BX"):
                    inflight -= 1
                peak = max(peak, inflight)
            if peak > w + 1:
                raise ScheduleError(
                    f"stage {s}: {peak} in-flight activations exceed the "
                    f"1F1B bound {w + 1}")
    # deadlock freedom: the dependency-driven dry run must drain every list
    _dry_run(actions, P_)


def _deps_met(done, s: int, phase: str, m: int, P_: int) -> bool:
    """The runtime's exact dependency predicate (kept in lockstep with
    runtime.PipelineEngine.run's deps_met)."""
    if phase == "F":
        return s == 0 or ("F", s - 1, m) in done
    if phase == "BW":
        return ("BX", s, m) in done
    ok = ("F", s, m) in done
    if s < P_ - 1:
        ok = ok and (("B", s + 1, m) in done or ("BX", s + 1, m) in done)
    return ok


def _dry_run(actions: Dict[int, List[Action]], P_: int) -> List[Action]:
    """Execute the lists under the runtime's dispatch discipline (head-first
    per stage, highest stage first, opportunistic BW fill) with no actual
    work. Raises on deadlock; returns the dispatch order."""
    seqs = {s: list(v) for s, v in actions.items()}
    done = set()
    order: List[Action] = []
    remaining = sum(len(v) for v in seqs.values())
    while remaining:
        progressed = False
        for s in range(P_ - 1, -1, -1):
            if not seqs[s]:
                continue
            for i, a in enumerate(seqs[s]):
                if i > 0 and a.phase != "BW":
                    break  # only the head, or a later BW, may run
                if _deps_met(done, s, a.phase, a.microbatch, P_):
                    seqs[s].pop(i)
                    done.add((a.phase, s, a.microbatch))
                    order.append(a)
                    remaining -= 1
                    progressed = True
                    break
        if not progressed:
            stuck = {s: seqs[s][0] for s in seqs if seqs[s]}
            raise ScheduleError(f"schedule deadlocks; blocked heads: {stuck}")
    return order


def build_schedule(schedule: str, P_: int, M: int
                   ) -> Dict[int, List[Action]]:
    """All stages' validated action lists. ``schedule`` is a normalized
    name; 'interleave' uses the 1f1b per-stage order over the GLOBAL
    (physical x virtual) stage count — chunk placement is the interleave."""
    schedule = normalize(schedule)
    base = "1f1b" if schedule == "interleave" else schedule
    actions = {s: stage_actions(base, s, P_, M) for s in range(P_)}
    validate(actions, P_, M, schedule=base)
    return actions


# ---------------------------------------------------------------------------
# Simulation + closed-form bubble accounting
# ---------------------------------------------------------------------------

def closed_form_bubble(pp: int, m: int, v: int = 1) -> float:
    """Synchronous-1F1B bubble fraction with equal unit-cost F and B:
    (pp-1)/(m+pp-1); interleaved over v virtual chunks per group:
    (pp-1)/(v*m+pp-1)."""
    return (pp - 1) / (v * m + pp - 1)


def _dep_keys(a: Action, P_: int) -> List[Tuple[str, int, int]]:
    s, m = a.stage, a.microbatch
    if a.phase == "F":
        return [("F", s - 1, m)] if s > 0 else []
    if a.phase == "BW":
        return [("BX", s, m)]
    deps = [("F", s, m)]
    if s < P_ - 1:
        deps.append(("B*", s + 1, m))  # either downstream backward flavor
    return deps


def _dep_ready(done, finish, key, t) -> bool:
    phase, s, m = key
    if phase != "B*":
        return key in done and finish[key] <= t
    for p in ("B", "BX"):
        k = (p, s, m)
        if k in done and finish[k] <= t:
            return True
    return False


def simulate(actions: Dict[int, List[Action]], P_: int,
             groups: int = 0, return_finish: bool = False) -> dict:
    """Dependency-timed unit-cost execution of the action lists.

    Each action costs one time unit; an action starts when its producer
    results exist AND its device group is free. Global stage g occupies
    device group ``g % groups`` (interleaved virtual chunks contend for
    their physical group). Returns makespan, per-group busy time and the
    bubble fraction ``1 - busy/(groups*makespan)`` — the quantity the
    closed form predicts."""
    G = groups or P_
    seqs = {s: list(v) for s, v in actions.items()}
    finish: Dict[Tuple[str, int, int], int] = {}
    group_free = [0] * G
    done = set()
    remaining = sum(len(v) for v in seqs.values())
    busy = [0] * G
    makespan = 0
    t = 0
    guard = 8 * remaining + 64
    while remaining and guard:
        guard -= 1
        progressed = False
        for s in range(P_ - 1, -1, -1):
            if not seqs[s]:
                continue
            grp = s % G
            if group_free[grp] > t:
                continue
            for i, a in enumerate(seqs[s]):
                if i > 0 and a.phase != "BW":
                    break  # only the head, or a later BW, may run
                if all(_dep_ready(done, finish, k, t)
                       for k in _dep_keys(a, P_)):
                    seqs[s].pop(i)
                    key = (a.phase, s, a.microbatch)
                    done.add(key)
                    finish[key] = t + 1
                    group_free[grp] = t + 1
                    busy[grp] += 1
                    makespan = max(makespan, t + 1)
                    remaining -= 1
                    progressed = True
                    break
        if not progressed:
            t += 1
    if remaining:
        raise ScheduleError("simulation did not drain (deadlocked lists)")
    total_busy = sum(busy)
    bubble = 1.0 - total_busy / (G * makespan) if makespan else 0.0
    out = {"makespan": makespan, "busy": busy,
           "bubble_fraction": bubble, "groups": G}
    if return_finish:
        # predicted per-action completion slots, for conformance diffing
        # against a measured runtime timeline
        out["finish"] = dict(finish)
    return out


def order_is_dependency_valid(order, P_: int) -> bool:
    """True iff an observed execution order — [(stage, phase, microbatch)]
    as the runtime's dispatcher actually ran them, serially — is a
    linearization the dependency DAG allows: every action's producers
    appear strictly earlier.  The conformance report uses this to tell
    "schedule ran slower than predicted" apart from "schedule did not
    run as written"."""
    done = set()
    for s, phase, m in order:
        for key in _dep_keys(Action(s, m, phase), P_):
            dp, ds, dm = key
            if dp == "B*":
                if ("B", ds, dm) not in done and ("BX", ds, dm) not in done:
                    return False
            elif key not in done:
                return False
        done.add((phase, s, m))
    return True

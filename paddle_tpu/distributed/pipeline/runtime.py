"""MPMD pipeline runtime: stage executables + validated schedules + async P2P.

This is the canonical engine behind ``fleet.meta_parallel`` pipeline
parallelism (``pp_schedule`` is a compat shim over this module). Reference:
fleet/meta_parallel/pipeline_parallel.py 1F1B/interleaved loops built on
NCCL p2p between per-rank stage submodels.

TPU-native redesign (SURVEY.md §7 "hard parts", option (a)): JAX is
single-controller, so instead of per-rank processes each owning a stage,
the engine

- consumes the :mod:`.partition` split of a `PipelineLayer` and
  functionalizes each stage's layer list into a pure jax function
  (params/buffers in → activations/new buffers out, the StaticFunction swap
  pattern from jit/api.py);
- commits each stage's parameters to THAT STAGE'S devices (a per-stage
  submesh; extra devices per stage form a data-parallel axis), so weights
  and optimizer states are pp-partitioned exactly like the reference's
  per-rank placement — and per-stage batch sharding makes XLA insert the
  within-stage dp grad reduction (grads jit out replicated), so dp x pp is
  exact with zero extra wiring;
- runs the :mod:`.schedule` action lists — built and VALIDATED before any
  execution — with a dependency-driven dispatcher;
- moves microbatch activations/cotangents between consecutive stages with
  :func:`core.async_engine.p2p_transfer` (`jax.device_put` onto the next
  stage's sharding — the PJRT device-to-device copy playing the role of
  `p2p_communication.py` send/recv). Dispatch is async: stage k's forward
  of microbatch i+1 overlaps the transfer of microbatch i on disjoint
  devices;
- backward recomputes the stage forward under `jax.vjp` (per-stage
  rematerialization), accumulates param grads on the stage's devices, and
  chains input cotangents to the previous stage;
- emits ``pipeline.send`` / ``pipeline.recv`` / ``pipeline.stall`` /
  ``pipeline.build`` per action and ``pipeline.gauges`` (bubble fraction +
  stage skew) per batch; a chaos hook (installed by fault_tolerance.chaos
  only while a ``pipeline:`` spec is active) arms a watchdog comm task
  around each dispatch so a hung stage escalates the ladder with its
  stage/microbatch named in the distress dump.

The fully-compiled single-executable path (GPipe via ppermute-in-scan)
lives in `distributed.hybrid` and remains the perf tier for homogeneous
stacks.
"""
from __future__ import annotations

import time
from typing import Any, Callable, Dict, List, Optional, Sequence, Tuple

import jax
import jax.numpy as jnp
import numpy as np
from jax.sharding import Mesh, NamedSharding, PartitionSpec as P

from ...core import async_engine, flags, rng
from ...core.tensor import Tensor
from ...nn.layer.layers import Layer
from ...observability import emit as _emit
from ...observability import tracing as _tr
from .. import comm_watchdog as _cw
from ..comm_watchdog import comm_task
from .. import quant_comm as _qc
from ..elastic import epoch as _ep
from . import schedule as pschedule

flags.define_flag(
    "pp_schedule", "1F1B",
    "Default pipeline schedule when pipeline_configs omits schedule_mode: "
    "1F1B, GPipe (alias FThenB), ZBH1 (zero-bubble H1) or interleave "
    "(needs virtual stages).")
flags.define_flag(
    "pp_accumulate_steps", 1,
    "Default microbatch count per pipeline batch (gradient accumulation "
    "steps) when pipeline_configs omits accumulate_steps.")
flags.define_flag(
    "pp_micro_batch_size", 0,
    "If > 0 and accumulate_steps is unset, derive the microbatch count as "
    "batch_size // pp_micro_batch_size (the reference micro_batch_size "
    "knob).")
flags.define_flag(
    "pp_virtual_degree", 1,
    "Default virtual pipeline chunks per physical stage (the reference "
    "virtual_pp_degree) when PipelineLayer is built without "
    "num_virtual_pipeline_stages.")
flags.define_flag(
    "pp_p2p_cache", True,
    "Reuse signature-keyed per-stage jitted executables across batches "
    "(supersedes the reference p2p_cache_shape buffer reuse). Off drops "
    "every stage cache at each run — a retrace-forcing debugging aid.")

# chaos choke point: installed by distributed/fault_tolerance/chaos.py only
# while a `pipeline:` FLAGS_chaos_spec is active — (phase, stage,
# microbatch) -> None, may stall a dispatch (the watchdog task around it is
# armed only when a hook is present, so the steady state pays nothing)
_chaos_hook = [None]


def set_chaos_hook(fn):
    _chaos_hook[0] = fn


# elastic choke point: installed by distributed/elastic/pipeline.py while an
# ElasticPipelineRuntime is active — fn(phase, stage, microbatch) -> None is
# called before every action dispatch; it renews the stage heartbeat leases,
# and when a lease lapsed it reconfigures the pipeline and raises
# EpochChangedError so the run aborts at an action boundary instead of
# hanging on a dead stage. Slot semantics match set_chaos_hook: None when no
# runtime is active, so the steady state pays one list lookup per dispatch.
_elastic_guard = [None]


def set_elastic_guard(fn):
    prev = _elastic_guard[0]
    _elastic_guard[0] = fn
    return prev


def _collect_state(layers: Sequence[Any]) -> Tuple[List, List]:
    params, buffers = [], []
    for l in layers:
        if isinstance(l, Layer):
            params.extend(p for _, p in l.named_parameters())
            buffers.extend(b for _, b in l.named_buffers() if b is not None)
    return params, buffers


class _Stage:
    """One pipeline stage: functionalized forward + device placement."""

    def __init__(self, layers: Sequence[Any], device_list: List, *,
                 loss_fn: Optional[Callable] = None, index: int = 0):
        self.layers = list(layers)
        self.params, self.buffers = _collect_state(self.layers)
        self.loss_fn = loss_fn  # set only on the last stage
        self.index = index
        self.mesh = Mesh(np.asarray(device_list), ("dp",))
        self.repl = NamedSharding(self.mesh, P())
        self.batch_sharding = NamedSharding(self.mesh, P("dp"))
        self.dp = len(device_list)
        self._exec: Dict[Any, Tuple] = {}

    # -- placement ---------------------------------------------------------
    def commit(self):
        """Move this stage's params/buffers onto its devices (replicated over
        the stage's dp submesh). A no-op re-put when already placed, so the
        engine may call it each run to undo optimizer-side moves (ZeRO-1
        sharded update gathers params back on the update group's mesh)."""
        for p in self.params + self.buffers:
            p._data = jax.device_put(p._data, self.repl)

    def put_input(self, arr):
        if arr.ndim and self.dp > 1 and arr.shape[0] % self.dp == 0:
            return jax.device_put(arr, self.batch_sharding)
        return jax.device_put(arr, self.repl)

    # -- functionalization -------------------------------------------------
    def _run_layers(self, x: Tensor) -> Tensor:
        for fn in self.layers:
            x = fn(x)
        return x

    def _kernel(self, param_arrays, buffer_arrays, x_arr, key_data, label_arr):
        """Pure stage function (the jit/api.py swap pattern)."""
        from ...ops import dispatch

        snap_p = [p._data for p in self.params]
        snap_b = [b._data for b in self.buffers]
        try:
            for p, a in zip(self.params, param_arrays):
                p._data = a
            for b, a in zip(self.buffers, buffer_arrays):
                b._data = a
            with rng.scoped_rng_key(key_data), dispatch.no_grad():
                out = self._run_layers(Tensor._from_data(x_arr))
                if self.loss_fn is not None:
                    loss = self.loss_fn(out, Tensor._from_data(label_arr))
                    if getattr(loss, "ndim", 0):
                        loss = loss.mean()
                    out = loss
            new_buffers = [b._data for b in self.buffers]
            return out._data, new_buffers
        finally:
            for p, a in zip(self.params, snap_p):
                p._data = a
            for b, a in zip(self.buffers, snap_b):
                b._data = a

    # -- executables (cached per input signature + train mode) -------------
    def _sig(self, x_arr, label_arr, train):
        lbl = None if label_arr is None else (label_arr.shape,
                                              str(label_arr.dtype))
        return (x_arr.shape, str(x_arr.dtype), lbl, train)

    def _build(self, x_arr, label_arr, train):
        n_p = len(self.params)

        def fwd_fn(pa, ba, x, key, lbl):
            return self._kernel(pa, ba, x, key, lbl)

        grad_shardings = [self.repl] * n_p
        x_sharding = getattr(x_arr, "sharding", self.repl)

        def bwd_both(pa, ba, x, gy, key, lbl):
            def f(pa_, x_):
                y, _ = self._kernel(pa_, ba, x_, key, lbl)
                return y
            _, vjp = jax.vjp(f, pa, x)
            gp, gx = vjp(gy)
            return list(gp), gx

        def bwd_params(pa, ba, x, gy, key, lbl):
            def f(pa_):
                y, _ = self._kernel(pa_, ba, x, key, lbl)
                return y
            _, vjp = jax.vjp(f, pa)
            (gp,) = vjp(gy)
            return list(gp)

        def bwd_input(pa, ba, x, gy, key, lbl):
            """dx ONLY — the zero-bubble split (reference
            pipeline_zero_bubble.py ZB-H1: B is divided into input-grad and
            weight-grad phases so dw can fill the cooldown bubble). Note:
            with per-stage rematerialization the split costs one extra
            forward recompute (dx and dw each replay the stage) — the
            bubble saving pays for it at pp >= 4."""
            def f(x_):
                y, _ = self._kernel(pa, ba, x_, key, lbl)
                return y
            _, vjp = jax.vjp(f, x)
            (gx,) = vjp(gy)
            return gx

        fwd = jax.jit(fwd_fn)
        bwd_b = jax.jit(bwd_both,
                        out_shardings=(grad_shardings, x_sharding))
        bwd_p = jax.jit(bwd_params, out_shardings=grad_shardings)
        bwd_x = jax.jit(bwd_input, out_shardings=x_sharding)
        return fwd, bwd_b, bwd_p, bwd_x

    def executables(self, x_arr, label_arr, train):
        key = self._sig(x_arr, label_arr, train)
        if key not in self._exec:
            t0 = time.perf_counter()
            self._exec[key] = self._build(x_arr, label_arr, train)
            _emit("pipeline.build", dur_s=time.perf_counter() - t0,
                  stage=self.index, signatures=len(self._exec))
        return self._exec[key]


class _Wire:
    """An in-flight encoded P2P buffer (``FLAGS_pp_p2p_comm_dtype``) plus
    the cached decode executable that restores the stage payload."""

    __slots__ = ("buf", "decode")

    def __init__(self, buf, decode):
        self.buf = buf
        self.decode = decode


class PipelineEngine:
    """Drives a segmented PipelineLayer across per-stage device groups."""

    def __init__(self, pipe_layer, accumulate_steps: int,
                 stage_devices: Optional[List[List]] = None,
                 schedule: str = "1F1B"):
        from ..fleet.meta_parallel.parallel_layers.pp_layers import (
            PipelineLayer)

        assert isinstance(pipe_layer, PipelineLayer)
        self.model = pipe_layer
        self.M = int(accumulate_steps)
        # P = GLOBAL stages; with interleaved VPP (V chunks per device
        # group, reference pipeline_parallel.py interleaved loop) the engine
        # runs the same dependency schedule over P_phys*V stages, with
        # global stage g placed on device group g % P_phys — chunk placement
        # IS the interleave; the dependency-driven dispatcher then overlaps
        # each group's chunks exactly like the reference's per-rank
        # interleave.
        self.P = pipe_layer.get_num_stages()
        self.P_phys = pipe_layer.get_num_physical_stages()
        self.V = self.P // self.P_phys
        self.schedule = pschedule.normalize(schedule)
        self.schedule_name = self.schedule
        if self.schedule == "interleave" and self.V == 1:
            raise ValueError(
                "schedule='interleave' needs num_virtual_pipeline_stages > 1 "
                "on the PipelineLayer")
        if self.schedule == "interleave":
            self.schedule = "1f1b"  # same per-stage order over global stages
        # the full schedule as explicit action lists, validated
        # deterministically BEFORE anything executes
        self.actions = pschedule.build_schedule(self.schedule, self.P, self.M)
        self.schedule_stats = pschedule.simulate(self.actions, self.P,
                                                 groups=self.P_phys)
        if stage_devices is None:
            devs = jax.devices()
            per = max(1, len(devs) // self.P_phys)
            groups = [devs[d * per:(d + 1) * per]
                      for d in range(self.P_phys)]
            stage_devices = [groups[pipe_layer.device_group_of_stage(g)]
                             for g in range(self.P)]
        elif len(stage_devices) == self.P_phys and self.P != self.P_phys:
            stage_devices = [stage_devices[pipe_layer.device_group_of_stage(g)]
                             for g in range(self.P)]
        loss_fn = getattr(pipe_layer, "_loss_fn", None)
        if loss_fn is None:
            raise ValueError(
                "pipeline parallelism needs PipelineLayer(loss_fn=...): the "
                "last stage computes the loss whose cotangent seeds the "
                "backward schedule")
        self.stages = [
            _Stage(pipe_layer.get_stage_layers(s), stage_devices[s],
                   loss_fn=loss_fn if s == self.P - 1 else None, index=s)
            for s in range(self.P)
        ]
        for st in self.stages:
            st.commit()
        # elastic-epoch stamp of the CURRENT run (refreshed by run()): every
        # dispatch and P2P hop checks it, so a world change mid-batch raises
        # EpochChangedError at the next action boundary instead of hanging
        # on a dead stage's buffers
        self._run_epoch = _ep.current()
        # in-flight P2P wires (sent but not yet consumed), for the comm
        # watchdog's distress-dump pipeline snapshot
        self._outstanding: Dict[Tuple[str, int, int], str] = {}
        self.last_dispatch_order: List[Tuple[int, str, int]] = []
        # measured action timeline of the last run — (stage, phase,
        # microbatch, start offset s, dur s) per dispatched action — and
        # its diff against the simulate() prediction
        self.last_timeline: List[Tuple[int, str, int, float, float]] = []
        self.last_conformance: dict = {}
        # span context of the current batch (host-side ints only; never
        # enters a stage executable or its signature)
        self._trace = None

    # ------------------------------------------------------------------
    def _split_micro(self, arr) -> List:
        b = arr.shape[0]
        assert b % self.M == 0, (
            f"batch {b} not divisible by accumulate_steps {self.M}")
        mb = b // self.M
        return [arr[i * mb:(i + 1) * mb] for i in range(self.M)]

    def _send(self, arr, dest_stage: int, kind: str, m: int):
        """Async P2P handoff to ``dest_stage``'s sharding through the eager
        pipeline: device_put enqueues under PJRT and returns; the consumer's
        dispatch chains on the in-flight buffer, so stage k's compute of
        microbatch i+1 overlaps this transfer of microbatch i.

        With ``FLAGS_pp_p2p_comm_dtype`` set, the payload is encoded onto
        a compact wire (plain cast, or the block-scaled int8 codec from
        quant_comm) before the transfer; only the wire bytes cross
        devices, and :meth:`_recv` decodes on the consumer side."""
        _ep.check(self._run_epoch, f"pipeline p2p send ({kind} -> stage "
                                   f"{dest_stage}, microbatch {m})")
        dst = self.stages[dest_stage]
        ref_nb = int(getattr(arr, "nbytes", 0) or 0)
        t0 = time.perf_counter()
        trace = ((self._trace.trace_id, self._trace.span_id)
                 if self._trace is not None else None)
        wire, decode, wdt = _qc.p2p_encode(arr)
        if decode is not None:
            out = _Wire(async_engine.p2p_transfer(
                wire, lambda a: jax.device_put(a, dst.repl),
                tag=f"pp:{kind}:{dest_stage}", trace=trace), decode)
            nb = int(getattr(wire, "nbytes", 0) or 0)
        else:
            out = async_engine.p2p_transfer(
                arr, dst.put_input, tag=f"pp:{kind}:{dest_stage}",
                trace=trace)
            nb = ref_nb
        _emit("pp.wire", bytes=nb, ref_bytes=ref_nb,
              dtype=wdt or str(getattr(arr, "dtype", "")), payload=kind)
        _emit("pipeline.send", dur_s=time.perf_counter() - t0, payload=kind,
              stage=dest_stage, microbatch=m, nbytes=nb)
        self._outstanding[(kind, dest_stage, m)] = (
            f"{kind}->stage{dest_stage}:mb{m} ({nb}B)")
        return out

    def _recv(self, arr, stage: int, kind: str, m: int):
        """Consume a transferred buffer; records whether the copy had
        already landed (overlap hit) or is still in flight. Quantized
        wires decode here — on the consumer's devices — and re-enter
        through ``put_input`` so the stage executables see the same
        placement (batch-sharded or replicated) as an unquantized
        handoff: the stage signatures don't change, so no retraces."""
        _ep.check(self._run_epoch, f"pipeline p2p recv ({kind} @ stage "
                                   f"{stage}, microbatch {m})")
        self._outstanding.pop((kind, stage, m), None)
        if isinstance(arr, _Wire):
            _emit("pipeline.recv", payload=kind, stage=stage, microbatch=m,
                  ready=async_engine._is_ready(arr.buf))
            return self.stages[stage].put_input(arr.decode(arr.buf))
        _emit("pipeline.recv", payload=kind, stage=stage, microbatch=m,
              ready=async_engine._is_ready(arr))
        return arr

    def run(self, inputs, labels, train: bool = True,
            loss_scale: float = 1.0, dp=None):
        """One global batch: schedule M microbatches over P stages; grads are
        ACCUMULATED into each stage param's ._grad. Returns the mean loss
        (a jax scalar on the last stage's devices).

        ``dp``: an optional DataParallel wrapper whose bucket reducer is
        fired EXACTLY ONCE, after the backward of the last microbatch — the
        k-step accumulation contract (`no_sync` inside the wrapper is
        honored; the microbatch loop itself never triggers a collective).

        The run is stamped with the elastic epoch at entry; every dispatch
        and P2P hop re-checks it, so an elastic reconfiguration anywhere in
        the process aborts the batch with EpochChangedError at an action
        boundary. Grads and buffers only commit AFTER the last action, so
        an aborted run leaves model state exactly at the previous step
        boundary — the caller replays the whole accumulation window.
        """
        prev_snap = _cw.set_pipeline_fn(self._inflight_snapshot)
        try:
            return self._run_batch(inputs, labels, train, loss_scale, dp)
        finally:
            _cw.set_pipeline_fn(prev_snap)
            # idempotent: closes the batch root span on abnormal exit
            # (epoch change / chaos kill) so it can't leak as in-flight
            _tr.end_span(self._trace)

    def _run_batch(self, inputs, labels, train, loss_scale, dp):
        P_, M = self.P, self.M
        self._run_epoch = _ep.current()
        self._outstanding.clear()
        if not flags.flag_value("pp_p2p_cache"):
            for st in self.stages:
                st._exec.clear()
        run_t0 = time.perf_counter()
        x_arr = inputs._data if isinstance(inputs, Tensor) else jnp.asarray(inputs)
        y_arr = labels._data if isinstance(labels, Tensor) else jnp.asarray(labels)
        mb_x = self._split_micro(x_arr)
        mb_y = self._split_micro(y_arr)

        seqs = {s: [(a.phase, a.microbatch) for a in self.actions[s]]
                for s in range(P_)}
        done = set()
        # per-(stage, mb) saved state for backward recompute
        x_in: Dict[Tuple[int, int], Any] = {}
        buf_in: Dict[Tuple[int, int], List] = {}
        keys: Dict[Tuple[int, int], Any] = {}
        gy_buf: Dict[Tuple[int, int], Any] = {}
        gy_saved: Dict[Tuple[int, int], Any] = {}
        y_dtype: Dict[Tuple[int, int], Any] = {}
        grad_acc: List[Optional[List]] = [None] * P_
        buf_state = [[b._data for b in st.buffers] for st in self.stages]
        losses = []
        stage_host = [0.0] * P_
        stalled = set()
        self.last_dispatch_order: List[Tuple[int, str, int]] = []
        timeline: List[Tuple[int, str, int, float, float]] = []
        self._trace = _tr.new_trace("pipeline.batch", epoch=self._run_epoch,
                                    schedule=self.schedule_name, stages=P_,
                                    microbatches=M)

        def deps_met(s, kind, m):
            if kind == "F":
                return s == 0 or ("F", s - 1, m) in done
            if kind == "BW":
                # dw only needs this stage's saved activations + cotangent;
                # BX (the critical path) must have consumed gy first
                return ("BX", s, m) in done
            # B / BX need this stage's forward and the downstream cotangent
            ok = ("F", s, m) in done
            if s < P_ - 1:
                ok = ok and (("B", s + 1, m) in done
                             or ("BX", s + 1, m) in done)
            return ok

        def run_fwd(s, m):
            st = self.stages[s]
            if s == 0:
                x = st.put_input(mb_x[m])
            else:
                x = self._recv(x_in[(s, m)], s, "act", m)
            lbl = st.put_input(mb_y[m]) if st.loss_fn is not None else None
            if st.loss_fn is not None:
                mb_y[m] = lbl  # reuse the transferred copy in backward
            key = jax.random.key_data(rng.next_key())
            x_in[(s, m)] = x
            buf_in[(s, m)] = buf_state[s]
            keys[(s, m)] = key
            fwd, _, _, _ = st.executables(x, lbl, train)
            y, new_buf = fwd(list(p._data for p in st.params),
                             buf_state[s], x, key, lbl)
            buf_state[s] = new_buf
            y_dtype[(s, m)] = y.dtype
            if st.loss_fn is not None:
                losses.append(y)
            elif s + 1 < P_:
                x_in[(s + 1, m)] = self._send(y, s + 1, "act", m)
            return y

        def _gy_of(s, m):
            st = self.stages[s]
            if st.loss_fn is not None:
                return jnp.asarray(loss_scale / M, y_dtype[(s, m)])
            return self._recv(gy_buf[(s, m)], s, "grad", m)

        def run_bwd(s, m):
            """Monolithic B (1F1B/GPipe): dx + dw in one recompute."""
            st = self.stages[s]
            x = x_in.pop((s, m))
            bufs = buf_in.pop((s, m))
            key = keys.pop((s, m))
            lbl = mb_y[m] if st.loss_fn is not None else None
            gy = _gy_of(s, m)
            y_dtype.pop((s, m), None); gy_buf.pop((s, m), None)
            _, bwd_b, bwd_p, _ = st.executables(x, lbl, train)
            pa = list(p._data for p in st.params)
            if s == 0:
                gp = bwd_p(pa, bufs, x, gy, key, lbl)
            else:
                gp, gx = bwd_b(pa, bufs, x, gy, key, lbl)
                gy_buf[(s - 1, m)] = self._send(gx, s - 1, "grad", m)
            if grad_acc[s] is None:
                grad_acc[s] = list(gp)
            else:
                grad_acc[s] = [a + g for a, g in zip(grad_acc[s], gp)]

        def run_bx(s, m):
            """ZB input-grad phase: unblocks stage s-1 as early as possible;
            activations/gy stay saved for the BW phase."""
            st = self.stages[s]
            x = x_in[(s, m)]
            bufs = buf_in[(s, m)]
            key = keys[(s, m)]
            lbl = mb_y[m] if st.loss_fn is not None else None
            gy = _gy_of(s, m)
            gy_saved[(s, m)] = gy
            y_dtype.pop((s, m), None); gy_buf.pop((s, m), None)
            if s > 0:
                _, _, _, bwd_x = st.executables(x, lbl, train)
                gx = bwd_x(list(p._data for p in st.params), bufs, x, gy,
                           key, lbl)
                gy_buf[(s - 1, m)] = self._send(gx, s - 1, "grad", m)

        def run_bw(s, m):
            """ZB weight-grad phase: fills former-bubble slots."""
            st = self.stages[s]
            x = x_in.pop((s, m))
            bufs = buf_in.pop((s, m))
            key = keys.pop((s, m))
            lbl = mb_y[m] if st.loss_fn is not None else None
            gy = gy_saved.pop((s, m))
            _, _, bwd_p, _ = st.executables(x, lbl, train)
            gp = bwd_p(list(p._data for p in st.params), bufs, x, gy, key,
                       lbl)
            if grad_acc[s] is None:
                grad_acc[s] = list(gp)
            else:
                grad_acc[s] = [a + g for a, g in zip(grad_acc[s], gp)]

        RUN = {"F": run_fwd, "B": run_bwd, "BX": run_bx, "BW": run_bw}

        def dispatch(s, i):
            kind, m = seqs[s].pop(i)
            guard = _elastic_guard[0]
            if guard is not None:
                # renew heartbeat leases / detect a dead stage; on death
                # the guard reconfigures and raises EpochChangedError
                guard(kind, s, m)
            _ep.check(self._run_epoch,
                      f"pipeline dispatch ({kind} stage {s} microbatch {m})")
            hook = _chaos_hook[0]
            t0 = time.perf_counter()
            if hook is not None:
                # arm the comm watchdog around the (possibly stalled)
                # dispatch: a hang injected here expires the task and the
                # escalation ladder's distress dump carries the stage and
                # microbatch in the task description (extra=)
                with comm_task(f"pp:{kind}", rank=s, shape=(),
                               dtype="", extra=f"stage={s} microbatch={m}"):
                    hook(kind, s, m)
                    if kind == "F" or train:
                        RUN[kind](s, m)
            elif kind == "F" or train:
                RUN[kind](s, m)
            dur = time.perf_counter() - t0
            stage_host[s] += dur
            timeline.append((s, kind, m, t0 - run_t0, dur))
            if self._trace is not None:
                _tr.record_span(f"pp.{kind}", self._trace.trace_id,
                                self._trace.span_id, int(t0 * 1e9), dur,
                                stage=s, microbatch=m,
                                epoch=self._run_epoch)
            done.add((kind, s, m))
            self.last_dispatch_order.append((s, kind, m))

        # dependency-driven round-robin dispatch (deadlock-free for every
        # order: each stage's head op becomes runnable once its producer
        # ran — the action lists were validated for exactly this discipline
        # in __init__). ZB twist: when a stage's head op is blocked (waiting
        # on a downstream cotangent), a queued BW whose deps are met runs
        # instead — dw genuinely fills the bubble slot.
        remaining = sum(len(v) for v in seqs.values())
        while remaining:
            progressed = False
            for s in range(P_ - 1, -1, -1):
                if not seqs[s]:
                    continue
                kind, m = seqs[s][0]
                if deps_met(s, kind, m):
                    dispatch(s, 0)
                    remaining -= 1
                    progressed = True
                    continue
                if (s, kind, m) not in stalled:
                    stalled.add((s, kind, m))
                    _emit("pipeline.stall", stage=s, microbatch=m,
                          phase=kind)
                # head blocked: opportunistic BW fill (zbh1 only)
                for i, (k2, m2) in enumerate(seqs[s]):
                    if k2 == "BW" and deps_met(s, k2, m2):
                        dispatch(s, i)
                        remaining -= 1
                        progressed = True
                        break
            if not progressed:
                raise RuntimeError("pipeline schedule deadlocked (bug)")

        # write back buffers + accumulate grads into the framework tensors
        for s, st in enumerate(self.stages):
            for b, a in zip(st.buffers, buf_state[s]):
                b._data = a
            if train and grad_acc[s] is not None:
                for p, g in zip(st.params, grad_acc[s]):
                    if p.stop_gradient or not getattr(p, "trainable", True):
                        continue
                    g = g.astype(p._data.dtype) if g.dtype != p._data.dtype else g
                    p._grad = g if p._grad is None else p._grad + g
        if dp is not None and train:
            self._dp_sync(dp)
        mean_host = sum(stage_host) / max(1, len(stage_host))
        skew = ((max(stage_host) - mean_host) / mean_host
                if mean_host > 0 else 0.0)
        # schedule conformance: what the dispatcher actually did vs what
        # simulate() predicted. Host-serial dispatch means the measured
        # bubble includes host occupancy the unit-cost sim doesn't model;
        # the gap and the per-group straggler split are the diagnostics.
        self.last_timeline = timeline
        measured = _tr.measured_schedule_stats(timeline, P_,
                                               groups=self.P_phys)
        self.last_conformance = {
            "schedule": self.schedule_name,
            "predicted_bubble_fraction": round(
                self.schedule_stats["bubble_fraction"], 6),
            "measured_bubble_fraction": measured["bubble_fraction"],
            "bubble_gap": round(measured["bubble_fraction"]
                                - self.schedule_stats["bubble_fraction"], 6),
            "predicted_makespan_units": self.schedule_stats["makespan"],
            "measured_makespan_s": measured["makespan_s"],
            "per_group_busy_s": measured["busy_s"],
            "straggler_group": measured["straggler_group"],
            "straggler_excess": measured["straggler_excess"],
            "order_dependency_valid": pschedule.order_is_dependency_valid(
                self.last_dispatch_order, P_),
            "actions": measured["actions"],
        }
        _emit("pipeline.gauges",
              bubble_fraction=self.schedule_stats["bubble_fraction"],
              stage_skew=skew, makespan=self.schedule_stats["makespan"],
              measured_bubble_fraction=measured["bubble_fraction"],
              bubble_gap=self.last_conformance["bubble_gap"],
              straggler_group=measured["straggler_group"],
              straggler_excess=measured["straggler_excess"])
        _tr.end_span(self._trace, actions=len(timeline),
                     measured_bubble=measured["bubble_fraction"])
        _emit("pipeline.run", dur_s=time.perf_counter() - run_t0,
              schedule=self.schedule_name, stages=P_, microbatches=M)
        total = losses[0]
        for l in losses[1:]:
            total = total + l
        return Tensor._from_data(total / M, stop_gradient=True)

    # ------------------------------------------------------------------
    def _dp_sync(self, dp):
        """Fire the PR-4 bucket reducer exactly once, after the last
        microbatch's grads landed — the k-step accumulation contract.

        Stage grads live on per-stage submeshes; a bucket's jitted flat
        pack would reject mixed-mesh operands, so grads hop to the dp
        group's (or default) devices for the collective and return to their
        stage sharding afterwards — two PJRT copies per param, amortized
        over the whole accumulated batch."""
        if not getattr(dp, "_sync_enabled", True):
            return
        g = getattr(dp, "_group", None)
        mesh = getattr(g, "_mesh", None) if g is not None else None
        if mesh is not None:
            common = NamedSharding(mesh, P())
        else:
            common = jax.devices()[0]
        moved: List[Tuple[Any, Any]] = []
        for st in self.stages:
            for p in st.params:
                if p._grad is not None:
                    moved.append((p, st.repl))
                    p._grad = jax.device_put(p._grad, common)
        dp.sync_gradients()
        for p, sh in moved:
            if p._grad is not None:
                p._grad = jax.device_put(p._grad, sh)

    def recommit(self):
        """Re-place every stage's params/buffers on its devices (no-op when
        already there). Call after an optimizer step that moved params —
        e.g. ZeRO-1 `sharded_update`, which updates on the dp group mesh."""
        for st in self.stages:
            st.commit()

    def _inflight_snapshot(self) -> dict:
        """Pipeline in-flight state for the comm watchdog's distress dumps
        (registered around each run via comm_watchdog.set_pipeline_fn).
        Read from a watchdog thread while the engine may be mid-dispatch,
        so it only copies plain python structures — no device sync."""
        last: Dict[int, Tuple[int, str]] = {}
        for s, kind, m in list(self.last_dispatch_order):
            last[s] = (m, kind)
        return {
            "schedule": self.schedule_name,
            "stages": self.P,
            "microbatches": self.M,
            "epoch": self._run_epoch,
            "last_completed": {
                str(s): {"microbatch": m, "phase": k}
                for s, (m, k) in sorted(last.items())},
            "outstanding_p2p": sorted(self._outstanding.values()),
            "conformance": dict(self.last_conformance),
        }

"""Fault-tolerant training runtime: chaos harness + recovery machinery.

- :mod:`.chaos` — deterministic, flag-driven fault injection
  (``FLAGS_chaos_spec``) with choke points in the collective, store,
  dispatch, fetch and checkpoint-save paths.
- :class:`.CheckpointManager` — every-N-steps snapshots (in-memory
  last-good + atomic CRC-verified disk checkpoints), NaN/Inf rollback
  guard, SIGTERM preemption flush.

The escalating comm-watchdog ladder lives in
``distributed/comm_watchdog.py`` (``FLAGS_watchdog_policy``) and the
collective retry wrapper in ``distributed/collective.py``.
"""
from . import chaos
from .chaos import ChaosCollectiveTimeout, ChaosError, parse_spec
from .checkpoint_manager import CheckpointManager, PipelineReshardError

__all__ = [
    "chaos",
    "ChaosError",
    "ChaosCollectiveTimeout",
    "parse_spec",
    "CheckpointManager",
    "PipelineReshardError",
]

"""CheckpointManager: every-N-steps snapshots, NaN rollback, preemption.

The recovery half of the fault-tolerance story (chaos.py is the attack
half). Reference frame: the auto-checkpoint managers production trainers
grow around `paddle.distributed.checkpoint` (save-interval + keep-K GC +
preemption flush), combined with the "last-good in-memory copy" trick
from elastic/fault-tolerant training systems: because jax arrays are
immutable, an in-memory snapshot is a handful of device-buffer
references (copied on capture so later buffer donation cannot free
them), which makes every-step snapshots affordable.

Three services:

- **Periodic snapshots** — ``on_step()`` captures an in-memory last-good
  copy and, every ``FLAGS_ckpt_interval`` steps, writes a disk
  checkpoint through ``distributed.checkpoint.save_state_dict`` using an
  atomic protocol: write into a ``.tmp`` dir, per-file CRC32 recorded in
  the metadata, fsync, then a directory rename publishes it and a
  ``latest`` pointer file is replaced atomically. Keep-K GC bounds disk.
  ``async_save=True`` runs the disk half on a background thread (the
  captured buffers are immutable, so no quiesce is needed).
- **NaN/Inf step guard** — ``on_step(loss)`` with a non-finite loss
  rolls model + optimizer state back to the last-good snapshot and
  reports the step as poisoned so the training loop re-runs it; bounded
  by ``FLAGS_rollback_budget`` consecutive rollbacks before the error is
  re-raised as fatal (a persistently-NaN model must not loop forever).
- **Preemption flush** — ``install_preemption_handler()`` wires SIGTERM
  to flush a final checkpoint before the default handling proceeds, so
  a preempted host loses at most the in-flight step.
"""
from __future__ import annotations

import os
import shutil
import signal
import threading
import time
from typing import Optional

import numpy as np

from ...core import flags
from ...core.enforce import UnavailableError
from ...core.tensor import Tensor
from ...observability import emit as _emit
from . import chaos

flags.define_flag("ckpt_interval", 50,
                  "CheckpointManager default: write a disk checkpoint every "
                  "N optimizer steps (0 = in-memory snapshots only)")
flags.define_flag("ckpt_keep", 2,
                  "CheckpointManager default: keep the newest K disk "
                  "checkpoints (older ones are GC'd after each save)")
flags.define_flag("rollback_budget", 3,
                  "Max consecutive NaN/Inf rollbacks before the step guard "
                  "gives up and raises (a persistently-broken model must "
                  "not retry forever)")


# Called (with the new step number) after every HEALTHY on_step() — i.e. at
# a step boundary, once the snapshot/checkpoint schedule has ticked. The
# elastic runtime hangs off this to apply deferred world grows (rank rejoin
# is only admitted between steps, never mid-step).
_step_boundary_hook = [None]


def set_step_boundary_hook(fn):
    """Register ``fn(step:int)`` to run after each healthy ``on_step``.
    Pass None to clear. Returns the previous hook."""
    prev = _step_boundary_hook[0]
    _step_boundary_hook[0] = fn
    return prev


def _dev_copy(a):
    """A buffer the training loop can never donate/mutate from under us."""
    import jax.numpy as jnp

    try:
        return jnp.array(a, copy=True)
    except Exception:  # noqa: BLE001 — non-array leaf (int step count etc.)
        return np.asarray(a).copy()


class PipelineReshardError(ValueError):
    """A stage-stacked state cannot be restacked to the requested pipeline
    degree (layer count not divisible, inconsistent stage axes, or leaves
    without the ``[pp, L/pp, ...]`` leading dims). Raised by
    :meth:`CheckpointManager.reshard_pp` BEFORE any reshape runs, naming
    both degrees — instead of an assertion deep in hybrid.stack_pipeline."""


def _fsync_dir(path: str):
    try:
        fd = os.open(path, os.O_RDONLY)
        try:
            os.fsync(fd)
        finally:
            os.close(fd)
    except OSError:
        pass  # platforms/filesystems without directory fsync


class CheckpointManager:
    """Coordinates in-memory last-good state, disk checkpoints and the
    NaN rollback guard for one (model, optimizer) pair."""

    def __init__(self, directory: Optional[str] = None, model=None,
                 optimizer=None, interval: Optional[int] = None,
                 keep: Optional[int] = None,
                 rollback_budget: Optional[int] = None,
                 async_save: bool = True):
        self.directory = directory
        self.model = model
        self.optimizer = optimizer
        self.interval = int(flags.flag_value("ckpt_interval")
                            if interval is None else interval)
        self.keep = int(flags.flag_value("ckpt_keep")
                        if keep is None else keep)
        self.rollback_budget = int(flags.flag_value("rollback_budget")
                                   if rollback_budget is None
                                   else rollback_budget)
        self.async_save = bool(async_save)
        self._step = 0
        self._last_good = None          # snapshot dict (see _capture)
        self._consecutive_rollbacks = 0
        self.rollbacks_total = 0
        self.saves_total = 0
        self._save_thread: Optional[threading.Thread] = None
        self._save_lock = threading.Lock()
        self._prev_sigterm = None
        if directory:
            os.makedirs(directory, exist_ok=True)
        # step 0 is a valid rollback target: a NaN on the very first step
        # must restore the initialization, not crash
        self.snapshot()

    # -- state capture / restore -------------------------------------------

    def _capture(self) -> dict:
        snap = {"step": self._step, "model": {}, "opt_accs": None,
                "opt_step": None}
        if self.model is not None:
            for k, t in self.model.state_dict().items():
                snap["model"][k] = _dev_copy(t._data)
        if self.optimizer is not None:
            snap["opt_accs"] = {
                pn: {an: _dev_copy(a) for an, a in accs.items()}
                for pn, accs in self.optimizer._accumulators.items()}
            snap["opt_step"] = int(self.optimizer._step_count)
        return snap

    def snapshot(self):
        """Capture the in-memory last-good copy (cheap: device-side buffer
        copies, no host sync)."""
        self._last_good = self._capture()

    def _restore(self, snap: dict):
        # install COPIES: the training loop will donate/rebind whatever we
        # hand it, and the snapshot must survive a second rollback
        if self.model is not None:
            live = self.model.state_dict()
            for k, arr in snap["model"].items():
                if k in live:
                    live[k]._data = _dev_copy(arr)
        if self.optimizer is not None and snap["opt_accs"] is not None:
            self.optimizer._accumulators = {
                pn: {an: _dev_copy(a) for an, a in accs.items()}
                for pn, accs in snap["opt_accs"].items()}
            self.optimizer._step_count = snap["opt_step"]
            # cached fused executables bound the OLD accumulator buffers;
            # drop them so the next step re-fuses against the restored state
            self.optimizer._fused_cache.clear()

    # -- the per-step entry point ------------------------------------------

    def on_step(self, loss=None) -> bool:
        """Call once per completed optimizer step, with the step's loss.

        Returns True when the step was judged poisoned (non-finite loss)
        and state was rolled back to last-good — the caller should re-run
        the step. Returns False on a healthy step (after ticking the
        snapshot/checkpoint schedule)."""
        if loss is not None and not self._finite(loss):
            return self._rollback()
        self._consecutive_rollbacks = 0
        self._step += 1
        chaos.note_step(self._step)
        if self.interval and self._step % self.interval == 0:
            self.save()
        else:
            self.snapshot()
        hook = _step_boundary_hook[0]
        if hook is not None:
            try:
                hook(self._step)
            except Exception as e:  # noqa: BLE001 — a boundary hook must
                # never poison the training loop's step accounting
                _emit("ckpt.hook_error", step=self._step,
                      error=f"{type(e).__name__}: {e}")
        return False

    @staticmethod
    def _finite(loss) -> bool:
        arr = loss._data if isinstance(loss, Tensor) else loss
        try:
            return bool(np.isfinite(np.asarray(arr)).all())
        except TypeError:
            return True  # tracers/non-numerics: the guard only runs eagerly

    def _rollback(self) -> bool:
        self._consecutive_rollbacks += 1
        self.rollbacks_total += 1
        _emit("ckpt.rollback", step=self._step,
              consecutive=self._consecutive_rollbacks,
              to_step=self._last_good["step"] if self._last_good else -1)
        if self._consecutive_rollbacks > self.rollback_budget:
            raise UnavailableError(
                f"NaN/Inf step guard: {self._consecutive_rollbacks} "
                f"consecutive rollbacks exceed FLAGS_rollback_budget="
                f"{self.rollback_budget}; model state is persistently "
                f"non-finite")
        if self._last_good is None:
            raise UnavailableError(
                "NaN/Inf step guard tripped with no last-good snapshot")
        self._restore(self._last_good)
        self._step = self._last_good["step"]
        chaos.note_step(self._step)
        return True

    # -- disk protocol ------------------------------------------------------

    def _state_for_disk(self, snap: dict) -> dict:
        state = {"model": {k: Tensor._from_data(a)
                           for k, a in snap["model"].items()}}
        if snap["opt_accs"] is not None:
            opt = {f"{pn}.{an}": Tensor._from_data(a)
                   for pn, accs in snap["opt_accs"].items()
                   for an, a in accs.items()}
            opt["@step"] = snap["opt_step"]
            state["optimizer"] = opt
        state["@manager_step"] = snap["step"]
        return state

    def save(self, wait: bool = False):
        """Snapshot now and publish a disk checkpoint for it (background
        thread unless ``wait`` or ``async_save=False``)."""
        self.snapshot()
        if not self.directory:
            return
        snap = self._last_good
        self._join_save()
        if self.async_save and not wait:
            self._save_thread = threading.Thread(
                target=self._write_disk, args=(snap,),
                name="ckpt-writer", daemon=True)
            self._save_thread.start()
        else:
            self._write_disk(snap)

    def _join_save(self):
        t = self._save_thread
        if t is not None and t.is_alive():
            t.join()
        self._save_thread = None

    def _write_disk(self, snap: dict):
        from .. import checkpoint as dckpt

        t0 = time.perf_counter()
        step = snap["step"]
        final = os.path.join(self.directory, f"step_{step}")
        tmp = os.path.join(self.directory, f".tmp_step_{step}_{os.getpid()}")
        try:
            with self._save_lock:
                if os.path.isdir(tmp):
                    shutil.rmtree(tmp)
                dckpt.save_state_dict(self._state_for_disk(snap), tmp)
                # the kill -9 drill fires here: data written, not yet
                # published — the previous checkpoint must stay loadable
                chaos.maybe_crash_save("finalize")
                if os.path.isdir(final):
                    shutil.rmtree(final)
                os.rename(tmp, final)
                _fsync_dir(self.directory)
                self._publish_latest(step)
                self._gc()
            self.saves_total += 1
            _emit("ckpt.save", dur_s=time.perf_counter() - t0, step=step,
                  path=final)
        except Exception as e:  # noqa: BLE001 — a failed background save
            # must not kill training; the in-memory last-good still stands
            _emit("ckpt.save_error", step=step,
                  error=f"{type(e).__name__}: {e}")
            shutil.rmtree(tmp, ignore_errors=True)
            if not self.async_save:
                raise

    def _publish_latest(self, step: int):
        ptr = os.path.join(self.directory, "latest")
        tmp = ptr + f".tmp.{os.getpid()}"
        with open(tmp, "w") as f:
            f.write(f"step_{step}\n")
            f.flush()
            os.fsync(f.fileno())
        os.replace(tmp, ptr)
        _fsync_dir(self.directory)

    def _gc(self):
        steps = sorted(self._finalized_steps())
        for s in steps[:-self.keep] if self.keep > 0 else []:
            shutil.rmtree(os.path.join(self.directory, f"step_{s}"),
                          ignore_errors=True)
            _emit("ckpt.gc", step=s)
        # stale tmp dirs from a crashed writer (other pids included)
        for fn in os.listdir(self.directory):
            if fn.startswith(".tmp_step_"):
                shutil.rmtree(os.path.join(self.directory, fn),
                              ignore_errors=True)

    def _finalized_steps(self):
        out = []
        if not self.directory or not os.path.isdir(self.directory):
            return out
        for fn in os.listdir(self.directory):
            if fn.startswith("step_"):
                try:
                    s = int(fn[5:])
                except ValueError:
                    continue
                d = os.path.join(self.directory, fn)
                if any(m.endswith(".metadata") for m in os.listdir(d)):
                    out.append(s)
        return out

    def last_good(self) -> Optional[dict]:
        """The in-memory last-good snapshot (``{"step", "model",
        "opt_accs", "opt_step"}``) — the elastic reshard fallback reads
        optimizer state from here when a lost rank's shard cannot be
        reconstructed in place."""
        return self._last_good

    def restore_last_good(self) -> Optional[int]:
        """Roll model+optimizer back to the in-memory last-good snapshot
        without counting it against the NaN rollback budget (elastic
        reconfiguration fallback). Returns the restored step, or None."""
        if self._last_good is None:
            return None
        self._restore(self._last_good)
        self._step = self._last_good["step"]
        chaos.note_step(self._step)
        return self._step

    def latest_step(self) -> Optional[int]:
        """Newest finalized checkpoint step (honors the ``latest`` pointer,
        falls back to a directory scan)."""
        steps = self._finalized_steps()
        if not steps:
            return None
        ptr = os.path.join(self.directory, "latest")
        try:
            with open(ptr) as f:
                name = f.read().strip()
            s = int(name[5:])
            if s in steps:
                return s
        except (OSError, ValueError):
            pass
        return max(steps)

    def load_latest(self) -> Optional[int]:
        """Restore model+optimizer from the newest finalized checkpoint
        (CRC-verified by the checkpoint loader). Returns the restored step,
        or None when no checkpoint exists."""
        from .. import checkpoint as dckpt

        step = self.latest_step()
        if step is None:
            return None
        path = os.path.join(self.directory, f"step_{step}")
        target = {}
        if self.model is not None:
            target["model"] = self.model.state_dict()
        opt_sd = None
        if self.optimizer is not None:
            opt_sd = self.optimizer.state_dict()
            target["optimizer"] = opt_sd
        dckpt.load_state_dict(target, path)
        if self.optimizer is not None and opt_sd is not None:
            # load mutated the wrapper Tensors; push arrays back into the
            # optimizer's live accumulator store
            self.optimizer.set_state_dict(opt_sd)
        self._step = step
        chaos.note_step(step)
        self.snapshot()
        _emit("ckpt.load", step=step, path=path)
        return step

    # -- pipeline-degree resharding ----------------------------------------

    @staticmethod
    def reshard_pp(state: dict, to_pp: int) -> dict:
        """Re-express a stage-stacked param pytree for a different pipeline
        degree: blocks leaves ``[pp, L/pp, ...]`` are unstacked to the flat
        layer axis and restacked as ``[to_pp, L/to_pp, ...]`` (stage-major),
        so a checkpoint written at one pp degree restores under another.
        Non-block leaves (embed / lm_head / norms) are pp-invariant and pass
        through. The total layer count must divide ``to_pp``; the round trip
        pp -> pp' -> pp is bitwise (pure reshapes)."""
        from .. import hybrid
        import jax

        if to_pp < 1:
            raise ValueError(f"to_pp must be >= 1, got {to_pp}")
        leaves = jax.tree.leaves(state.get("blocks", {}))
        if not leaves:
            raise ValueError("reshard_pp needs a stage-stacked state with a "
                             "'blocks' subtree")
        from_pp = int(leaves[0].shape[0])
        if any(getattr(leaf, "ndim", 0) < 2 for leaf in leaves):
            raise PipelineReshardError(
                f"cannot reshard from pp={from_pp} to pp={to_pp}: every "
                f"blocks leaf needs [pp, layers_per_stage, ...] leading "
                f"dims, got shapes "
                f"{sorted({tuple(getattr(l, 'shape', ())) for l in leaves})}")
        heads = {tuple(leaf.shape[:2]) for leaf in leaves}
        if len(heads) != 1:
            raise PipelineReshardError(
                f"cannot reshard from pp={from_pp} to pp={to_pp}: blocks "
                f"leaves disagree on the stage-major layout — leading dims "
                f"{sorted(heads)} (every leaf must share [pp, "
                f"layers_per_stage])")
        n_layers = from_pp * int(leaves[0].shape[1])
        if n_layers % to_pp:
            raise PipelineReshardError(
                f"cannot restack the stage-major blocks from pp={from_pp} "
                f"to pp={to_pp}: {n_layers} layers do not divide into "
                f"{to_pp} stages")
        t0 = time.perf_counter()
        out = hybrid.stack_pipeline(hybrid.unstack_pipeline(state), to_pp)
        _emit("ckpt.reshard_pp", dur_s=time.perf_counter() - t0,
              from_pp=from_pp, to_pp=to_pp, n_leaves=len(leaves))
        return out

    # -- preemption ---------------------------------------------------------

    def install_preemption_handler(self) -> bool:
        """SIGTERM -> flush a final checkpoint, then proceed with the
        previous/default handling. Main-thread only; returns False when
        installation was not possible."""
        if threading.current_thread() is not threading.main_thread():
            return False

        def _handler(signum, frame):
            _emit("ckpt.preempt", step=self._step)
            try:
                self.flush()
            except Exception:  # noqa: BLE001 — preemption path must exit
                pass
            prev = self._prev_sigterm
            if callable(prev):
                prev(signum, frame)
            else:
                signal.signal(signal.SIGTERM, signal.SIG_DFL)
                os.kill(os.getpid(), signal.SIGTERM)

        try:
            self._prev_sigterm = signal.signal(signal.SIGTERM, _handler)
            return True
        except (ValueError, OSError):
            return False

    def flush(self):
        """Synchronously publish a checkpoint of the current state (final
        flush on preemption/shutdown)."""
        self._join_save()
        self.save(wait=True)

    def close(self):
        self._join_save()
        if self._prev_sigterm is not None:
            try:
                signal.signal(signal.SIGTERM, self._prev_sigterm)
            except (ValueError, OSError):
                pass
            self._prev_sigterm = None

"""Chaos harness: deterministic, flag-driven fault injection.

A production gang must survive preempted hosts, hung collectives, flaky
stores and NaN'd steps — and the only way to *prove* it survives them is
to inject those faults on demand and assert on the observed recovery.
Reference frame: the fault-injection hooks production NCCL stacks grow
around `comm_task_manager` (forced-timeout test modes), and chaos-mesh
style choke points, collapsed into one seeded, spec-driven injector.

``FLAGS_chaos_spec`` is a comma-separated list of injections::

    site:kind[@sel=val[;sel=val...]]

Sites and kinds (each site is a hook the runtime module exposes; the
hooks are installed only while a spec is active, so an empty spec costs
one pointer check on the hot paths):

- ``collective`` — ``delay`` (sleep ``delay=`` s before issuing),
  ``timeout`` (raise :class:`ChaosCollectiveTimeout`, the retryable
  hang-detected error the retry wrapper in collective.py catches),
  ``hang`` (sleep ``delay=`` s *inside* the armed comm_task, so the real
  watchdog fires), ``rank_dead`` (kill rank ``victim=`` mid-collective:
  its membership lease is revoked via the elastic runtime's kill hook,
  then the call hangs ``delay=`` s and dies with
  :class:`ChaosCollectiveTimeout` — the full dead-peer experience).
- ``store`` — ``drop`` (kill the client socket mid-request), ``garble``
  (corrupt the reply length so the client detects an implausible frame),
  ``delay`` (sleep before the request), ``partition`` (open a
  ``delay=``-second network-partition window: every request in the
  window fails with ConnectionError).
- ``dispatch`` — ``nan`` / ``inf`` (poison the op's first floating
  output leaf), ``rank_dead`` (kill rank ``victim=`` mid-step; the op
  itself completes — death is discovered by membership/collectives).
- ``fetch`` — ``stall`` (sleep ``delay=`` s inside scalar_fetch).
- ``save`` — ``crash`` (``os._exit(137)`` mid-write: the kill -9
  atomicity drill), ``rank_dead`` (kill rank ``victim=``
  mid-checkpoint; the local write still completes).
- ``serving`` — ``stall`` (sleep ``delay=`` s before the paged engine's
  fused step, driving in-flight requests past their deadlines so the
  deadline/shed path fires), ``reject`` (raise the engine's
  ``RejectedError`` load-shed signal at the step choke point).
- ``replica`` — router-level replica faults at the ReplicaHandle's
  guarded-step choke point, filtered by ``victim=<replica_id>``:
  ``kill`` (raise ``ReplicaKilledError`` — the replica is dead, its
  streams fail over), ``stall`` (sleep ``delay=`` s and report a stall
  strike: healthy → degraded → dead), ``flap`` (a transient strike with
  no sleep — recovers on the next good step unless it strikes out).
- ``pipeline`` — ``hang`` (sleep ``delay=`` s inside the watchdog
  comm_task the pipeline engine arms around a stage dispatch, filtered
  by ``stage=``/``microbatch=``: e.g. ``pipeline:hang@stage=1`` hangs
  stage 1 so the ladder escalates and the distress dump names the
  stage/microbatch).
- ``adapter`` — multi-tenant LoRA adapter faults at the serving
  engine's per-tick residency check (``op=use``) and the
  AdapterTransport's store choke points (``op=publish`` /
  ``op=fetch``): ``evict`` (force-drop the adapter's device slot
  mid-stream — the next tick must reload it, counted as a swap, and
  the token stream must stay bit-exact), ``corrupt`` (flip wire-pack
  bytes so the CRC check rejects the blob at publish/fetch), ``delay``
  (sleep ``delay=`` s at the choke point).
- ``migration`` — disagg KV page-transport faults at the offer/pull
  choke points (``op=offer`` / ``op=pull``; ``victim=`` filters on the
  SENDING replica id): ``drop`` (the payload is lost — offers never
  land, pulls time out into the retry/backoff ladder), ``delay``
  (sleep ``delay=`` s at the choke point), ``corrupt`` (flip payload
  bytes so the CRC check rejects the pages at ingest), ``rank_dead``
  (kill the sending replica mid-handoff through the rank-kill hook —
  the lease/epoch fence must then reject its pages and the decode side
  recomputes the prefill).

Selectors: ``op=<name>`` (exact op / request name), ``rank=<int>``
(filter on the *calling* rank), ``victim=<int>`` (which rank a
``rank_dead`` injection kills — and, at the ``replica`` site, which
replica id the injection applies to: other replicas don't even count
toward ``call=``; default = the calling rank),
``step=<int>`` (the value of the chaos step clock — ticked by
``CheckpointManager.on_step`` / ``note_step``), ``call=<int>`` (the Nth
call matching op/rank at this site, 0-based), ``count=<int>`` (max
firings, default 1; 0 = unlimited), ``delay=<float>`` seconds,
``prob=<float>`` (fire with probability, seeded by ``FLAGS_chaos_seed``
so runs are reproducible), ``stage=<int>`` / ``microbatch=<int>``
(pipeline-site filters: dispatches for other stages/microbatches do not
count toward ``call=``).

Every injection lands in the flight recorder and the
``paddle_chaos_injections_total{site,kind}`` counter via
``observability.emit("chaos.inject", ...)`` — tests assert on *observed*
injections and *observed* recovery, never on luck.
"""
from __future__ import annotations

import random
import time
from typing import List, Optional

from ...core import flags
from ...observability import emit as _emit

flags.define_flag("chaos_spec", "",
                  "Fault-injection spec: comma-separated "
                  "'site:kind@sel=val;...' entries (see "
                  "distributed/fault_tolerance/chaos.py); empty disables "
                  "the harness entirely")
flags.define_flag("chaos_seed", 0,
                  "Seed for probabilistic (prob=) chaos injections")


class ChaosError(RuntimeError):
    """Base of all injected faults (so tests can catch the family)."""


class ChaosCollectiveTimeout(ChaosError, TimeoutError):
    """Injected 'this collective hung and was declared dead' — the
    retryable error class the collective retry wrapper backs off on."""


_SITES = ("collective", "store", "dispatch", "fetch", "save", "serving",
          "replica", "pipeline", "migration", "adapter")
# tpu-lint TPL009 cross-checks this table against the drill specs in the
# test tree / smoke tools: adding a site:kind here without a drill that
# fires it (or a drill naming a pair absent here) fails the lint gate.
_KINDS = {
    "collective": ("delay", "timeout", "hang", "rank_dead"),
    "store": ("drop", "garble", "delay", "partition"),
    "dispatch": ("nan", "inf", "rank_dead"),
    "fetch": ("stall",),
    "save": ("crash", "rank_dead"),
    "serving": ("stall", "reject"),
    "replica": ("kill", "stall", "flap"),
    "pipeline": ("hang", "rank_dead"),
    "migration": ("drop", "delay", "corrupt", "rank_dead"),
    "adapter": ("evict", "corrupt", "delay"),
}

_FLOAT_SELECTORS = ("delay", "prob")
_INT_SELECTORS = ("rank", "victim", "step", "call", "count", "stage",
                  "microbatch")


class Injection:
    __slots__ = ("site", "kind", "op", "rank", "victim", "step", "call",
                 "count", "delay", "prob", "stage", "microbatch", "seen",
                 "fired")

    def __init__(self, site, kind, op=None, rank=None, victim=None,
                 step=None, call=None, count=1, delay=0.05, prob=None,
                 stage=None, microbatch=None):
        self.site = site
        self.kind = kind
        self.op = op
        self.rank = rank
        self.victim = victim
        self.step = step
        self.call = call
        self.count = count
        self.delay = delay
        self.prob = prob
        self.stage = stage
        self.microbatch = microbatch
        self.seen = 0    # calls that matched op/rank filters
        self.fired = 0   # injections actually applied

    def __repr__(self):
        sel = {k: getattr(self, k) for k in
               ("op", "rank", "victim", "step", "call", "count", "delay",
                "prob", "stage", "microbatch")
               if getattr(self, k) is not None}
        return f"Injection({self.site}:{self.kind} {sel} fired={self.fired})"


def parse_spec(spec: str) -> List[Injection]:
    """Parse FLAGS_chaos_spec; raises ValueError on malformed entries so a
    typo'd spec fails the run loudly instead of silently injecting nothing."""
    out = []
    for raw in (spec or "").split(","):
        raw = raw.strip()
        if not raw:
            continue
        head, _, selpart = raw.partition("@")
        site, sep, kind = head.partition(":")
        site, kind = site.strip(), kind.strip()
        if not sep or site not in _SITES or kind not in _KINDS[site]:
            raise ValueError(
                f"chaos_spec entry {raw!r}: want site:kind with site in "
                f"{_SITES} and kind in {_KINDS.get(site, ())}")
        kw = {}
        for pair in selpart.split(";"):
            pair = pair.strip()
            if not pair:
                continue
            k, sep, v = pair.partition("=")
            k = k.strip()
            if not sep or k not in ("op",) + _INT_SELECTORS + _FLOAT_SELECTORS:
                raise ValueError(f"chaos_spec entry {raw!r}: bad selector "
                                 f"{pair!r}")
            if k == "op":
                kw[k] = v.strip()
            elif k in _INT_SELECTORS:
                kw[k] = int(v)
            else:
                kw[k] = float(v)
        out.append(Injection(site, kind, **kw))
    return out


# ---------------------------------------------------------------------------
# Live state. _injections is rebuilt whenever FLAGS_chaos_spec changes;
# the per-module hooks are installed only while a spec is active.
# ---------------------------------------------------------------------------

_injections: List[Injection] = []
_rng = random.Random(0)
_STEP = [0]  # the chaos step clock (note_step)
_installed = [False]

# rank-kill hook: fn(victim_rank, site) installed by the ElasticRuntime —
# a rank_dead injection revokes the victim's membership lease through it
# (without a runtime, rank_dead degrades to its site's base fault)
_rank_kill_hook = [None]

# store-partition window: while monotonic() is below this, every store
# request fails (set by a store:partition injection, delay= seconds wide)
_partition_until = [0.0]


def set_rank_kill_hook(fn):
    prev = _rank_kill_hook[0]
    _rank_kill_hook[0] = fn
    return prev


def _kill_victim(inj: Injection, rank: int, site: str):
    kill = _rank_kill_hook[0]
    victim = inj.victim if inj.victim is not None else rank
    if kill is not None:
        try:
            kill(victim, site)
        except Exception:  # noqa: BLE001 — the drill must not crash the job
            pass


def note_step(step: int):
    """Advance the chaos step clock (CheckpointManager.on_step ticks this;
    ``step=`` selectors match against it)."""
    _STEP[0] = int(step)


def current_step() -> int:
    return _STEP[0]


def active() -> bool:
    return bool(_injections)


def injections() -> List[Injection]:
    return list(_injections)


def _match(site: str, op: Optional[str] = None,
           rank: Optional[int] = None,
           victim: Optional[int] = None,
           stage: Optional[int] = None,
           microbatch: Optional[int] = None) -> Optional[Injection]:
    for inj in _injections:
        if inj.site != site:
            continue
        if inj.op is not None and inj.op != op:
            continue
        if inj.rank is not None and rank is not None and inj.rank != rank:
            continue
        # victim= as a FILTER (replica site): a non-matching caller does
        # not even count toward call= — `call=3` means the victim's 4th
        # own step, deterministic regardless of fleet interleaving
        if (victim is not None and inj.victim is not None
                and inj.victim != victim):
            continue
        # stage=/microbatch= filter the pipeline site the same way: other
        # stages' dispatches don't count toward call=
        if (stage is not None and inj.stage is not None
                and inj.stage != stage):
            continue
        if (microbatch is not None and inj.microbatch is not None
                and inj.microbatch != microbatch):
            continue
        idx = inj.seen
        inj.seen += 1
        if inj.count and inj.fired >= inj.count:
            continue
        if inj.call is not None and idx != inj.call:
            continue
        if inj.step is not None and _STEP[0] != inj.step:
            continue
        if inj.prob is not None and _rng.random() >= inj.prob:
            continue
        inj.fired += 1
        _emit("chaos.inject", site=site, fault=inj.kind, op=op or "",
              rank=rank if rank is not None else -1, step=_STEP[0],
              call=idx)
        return inj
    return None


# ---------------------------------------------------------------------------
# Site hooks (installed into the runtime modules while a spec is active)
# ---------------------------------------------------------------------------

def _collective_hook(op: str, rank: int = 0):
    """Called by collective.py inside the retry wrapper, before each
    attempt. May sleep (delay/hang), raise (timeout), or kill a rank's
    membership lease and then die (rank_dead)."""
    inj = _match("collective", op=op, rank=rank)
    if inj is None:
        return
    if inj.kind == "delay" or inj.kind == "hang":
        time.sleep(inj.delay)
        return
    if inj.kind == "rank_dead":
        # the victim drops dead mid-collective: its lease is revoked, the
        # call hangs long enough for a watchdog (if armed) to notice, then
        # dies with the same error a declared-dead collective produces
        _kill_victim(inj, rank, "collective")
        if inj.delay:
            time.sleep(inj.delay)
        raise ChaosCollectiveTimeout(
            f"[chaos] injected rank death: victim="
            f"{inj.victim if inj.victim is not None else rank} op={op} "
            f"step={_STEP[0]}")
    raise ChaosCollectiveTimeout(
        f"[chaos] injected collective timeout: op={op} rank={rank} "
        f"step={_STEP[0]}")


def _store_hook(op: str) -> Optional[str]:
    """Called by the TCPStore client per request; returns the fault kind
    the client should apply ('drop' / 'garble'), or None. A 'partition'
    injection opens a delay=-second window in which EVERY request drops
    (one injection, many failures — a real partition, not a flaky
    packet)."""
    if time.monotonic() < _partition_until[0]:
        return "drop"
    inj = _match("store", op=op)
    if inj is None:
        return None
    if inj.kind == "delay":
        time.sleep(inj.delay)
        return None
    if inj.kind == "partition":
        _partition_until[0] = time.monotonic() + inj.delay
        return "drop"
    return inj.kind


def _dispatch_hook(name: str, result):
    """Called by ops/dispatch.py on every op result while active: poison
    the first floating-point output leaf with NaN/Inf."""
    inj = _match("dispatch", op=name)
    if inj is None:
        return result
    if inj.kind == "rank_dead":
        # mid-step death: the op result is untouched — the victim's lease
        # is gone and the next collective/membership poll discovers it
        _kill_victim(inj, 0, "dispatch")
        return result
    import jax
    import jax.numpy as jnp

    from ...core import dtype as dtype_mod
    from ...core.tensor import Tensor

    fill = float("nan") if inj.kind == "nan" else float("inf")

    def is_t(x):
        return isinstance(x, Tensor)

    poisoned = [False]

    def poison(leaf):
        if (not poisoned[0] and isinstance(leaf, Tensor)
                and dtype_mod.is_floating_dtype(leaf._data.dtype)):
            poisoned[0] = True
            leaf._data = jnp.full_like(leaf._data, fill)
        return leaf

    jax.tree.map(poison, result, is_leaf=is_t)
    return result


def _fetch_hook(tag: str):
    inj = _match("fetch", op=tag)
    if inj is not None and inj.kind == "stall":
        time.sleep(inj.delay)


def _serving_hook(phase: str):
    """Called by PagedServingEngine.step per tick: 'stall' sleeps before
    the fused step (drives requests past their deadlines so the shed path
    is exercised); 'reject' raises the engine's load-shed error."""
    inj = _match("serving", op=phase)
    if inj is None:
        return
    if inj.kind == "stall":
        time.sleep(inj.delay)
        return
    from ...inference.serving.scheduler import RejectedError

    raise RejectedError(
        f"[chaos] injected serving rejection: phase={phase} "
        f"step={_STEP[0]}")


def _replica_hook(phase: str, replica_id: int):
    """Called by ReplicaHandle.guarded_step before each engine tick.
    'kill' raises ReplicaKilledError (the handle declares itself dead
    and the router fails its streams over); 'stall'/'flap' return the
    kind for the handle's breaker to judge as a strike ('stall' also
    sleeps ``delay=`` so in-flight deadlines really burn)."""
    inj = _match("replica", op=phase, victim=replica_id)
    if inj is None:
        return None
    if inj.kind == "kill":
        from ...inference.serving.replica import ReplicaKilledError

        raise ReplicaKilledError(
            f"[chaos] injected replica kill: replica={replica_id} "
            f"phase={phase} step={_STEP[0]}")
    if inj.kind == "stall" and inj.delay:
        time.sleep(inj.delay)
    return inj.kind


def _pipeline_hook(phase: str, stage: int, microbatch: int):
    """Called by pipeline.runtime at every action dispatch (only while a
    spec is active — the runtime arms a watchdog comm_task around the
    dispatch whenever this hook is installed). 'hang' sleeps ``delay=``
    seconds inside that armed task, so the REAL watchdog expires it and
    the escalation ladder's distress dump names the hung stage and
    microbatch via the task's description. 'rank_dead' drops a stage
    replica dead mid-microbatch: its heartbeat lease is revoked through
    the rank-kill hook (``victim=`` overrides which stage dies; the
    default is the dispatching stage, so ``stage=`` both selects the
    triggering dispatch and names the victim) — the NEXT dispatch's
    elastic guard sees the lapsed lease and fences the run."""
    inj = _match("pipeline", op=phase, stage=stage, microbatch=microbatch)
    if inj is None:
        return
    if inj.kind == "hang":
        time.sleep(inj.delay)
        return
    if inj.kind == "rank_dead":
        _kill_victim(inj, stage, "pipeline")


def _migration_hook(op: str, victim: Optional[int] = None):
    """Called by the disagg page transport (serving/disagg.py) at its
    ``offer`` / ``pull`` choke points, with the SENDING replica id as
    the ``victim=`` filter. 'delay' sleeps in place; 'drop' and
    'corrupt' are returned for the transport to apply (lose the payload
    / flip its bytes so the ingest CRC trips); 'rank_dead' kills the
    sending replica mid-handoff through the rank-kill hook — the
    epoch/lease fence must then reject its in-flight pages."""
    inj = _match("migration", op=op, victim=victim)
    if inj is None:
        return None
    if inj.kind == "delay":
        time.sleep(inj.delay)
        return None
    if inj.kind == "rank_dead":
        _kill_victim(inj, victim if victim is not None else 0,
                     "migration")
        return None
    return inj.kind


def _adapter_hook(op: str, name: Optional[str] = None):
    """Called by the serving engine's adapter residency check (op
    'use', once per referenced adapter per tick) and by the
    AdapterTransport store path (op 'publish'/'fetch'). 'delay' sleeps
    in place; 'evict' and 'corrupt' are returned for the caller to
    apply (force-drop the device slot / flip wire bytes so the CRC
    trips). ``op=`` filters on the choke point; the adapter name rides
    the injection's op selector namespace via ``op=<name>`` too."""
    inj = _match("adapter", op=op)
    if inj is None and name is not None:
        inj = _match("adapter", op=name)
    if inj is None:
        return None
    if inj.kind == "delay":
        time.sleep(inj.delay)
        return None
    return inj.kind


def _save_hook(phase: str):
    """Called by the checkpoint writers mid-write; 'crash' hard-kills the
    process (the kill -9 atomicity drill); 'rank_dead' revokes the
    victim's lease mid-checkpoint (the local write still completes)."""
    import os

    inj = _match("save", op=phase)
    if inj is None:
        return
    if inj.kind == "rank_dead":
        _kill_victim(inj, 0, "save")
        return
    if inj.kind == "crash":
        os._exit(137)


# ---------------------------------------------------------------------------
# Activation: install/uninstall the hooks on the runtime modules
# ---------------------------------------------------------------------------

def _install():
    if _installed[0]:
        return
    from ...core import async_engine
    from ...ops import dispatch
    from .. import collective, store

    dispatch.set_chaos_hook(_dispatch_hook)
    collective.set_chaos_hook(_collective_hook)
    store.set_chaos_hook(_store_hook)
    async_engine.set_chaos_hook(_fetch_hook)
    from ...inference.serving import engine as serving_engine
    from ...inference.serving import replica as serving_replica

    serving_engine.set_chaos_hook(_serving_hook)
    serving_replica.set_chaos_hook(_replica_hook)
    from ...inference.serving import disagg as serving_disagg

    serving_disagg.set_chaos_hook(_migration_hook)
    from ...inference.serving import adapters as serving_adapters

    serving_adapters.set_chaos_hook(_adapter_hook)
    from ..pipeline import runtime as pp_runtime

    pp_runtime.set_chaos_hook(_pipeline_hook)
    _installed[0] = True


def _uninstall():
    if not _installed[0]:
        return
    from ...core import async_engine
    from ...ops import dispatch
    from .. import collective, store

    dispatch.set_chaos_hook(None)
    collective.set_chaos_hook(None)
    store.set_chaos_hook(None)
    async_engine.set_chaos_hook(None)
    from ...inference.serving import engine as serving_engine
    from ...inference.serving import replica as serving_replica

    serving_engine.set_chaos_hook(None)
    serving_replica.set_chaos_hook(None)
    from ...inference.serving import disagg as serving_disagg

    serving_disagg.set_chaos_hook(None)
    from ...inference.serving import adapters as serving_adapters

    serving_adapters.set_chaos_hook(None)
    from ..pipeline import runtime as pp_runtime

    pp_runtime.set_chaos_hook(None)
    _installed[0] = False


def save_hook_active() -> bool:
    return any(i.site == "save" for i in _injections)


def maybe_crash_save(phase: str):
    """Checkpoint writers call this at their choke point (cheap no-op when
    no save-site injection is configured)."""
    if _injections and save_hook_active():
        _save_hook(phase)


def reconfigure(spec: Optional[str] = None):
    """(Re)build the injection set from the flag (or an explicit spec) and
    install/uninstall the runtime hooks accordingly."""
    global _injections
    if spec is None:
        spec = str(flags.flag_value("chaos_spec") or "")
    _injections = parse_spec(spec)
    _rng.seed(int(flags.flag_value("chaos_seed")))
    _STEP[0] = 0
    _partition_until[0] = 0.0
    if _injections:
        _install()
    else:
        _uninstall()


def _on_flag_change(name, value):
    if name in ("chaos_spec", "chaos_seed"):
        reconfigure()


flags.on_change(_on_flag_change)

# honor a FLAGS_chaos_spec env var present at import time
if flags.flag_value("chaos_spec"):
    reconfigure()

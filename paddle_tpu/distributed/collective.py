"""Process groups + collective communication — TPU-native.

Reference design (SURVEY.md §2.5): `paddle.distributed.new_group` creates a
`Group` backed by a `ProcessGroupNCCL` (process_group.h:48) doing NCCL rings
on dedicated streams, bootstrapped by TCPStore. Python op wrappers live in
python/paddle/distributed/communication/*.

TPU-native redesign: a collective is an XLA HLO op compiled over ICI/DCN.
A `Group` is a *mesh-axis binding*: it names a set of ranks and, when built
from a device mesh, the 1-D sub-mesh axis the collective runs over. Execution
has two modes:

- **traced** (inside `shard_map`/`jit` with a bound axis name): the op emits
  the `lax` collective (`psum`, `all_gather`, `psum_scatter`, `all_to_all`,
  `ppermute`) directly — XLA schedules it on ICI. This is how fleet's hybrid
  engine consumes groups.
- **eager** (single-controller): the op jit-compiles a one-collective
  `shard_map` over the group's device axis and applies it to the tensor's
  global `jax.Array` — the "ProcessGroup dispatches single-collective XLA
  executables" design recorded in SURVEY.md §5. Executables are cached per
  (op, group, shape, dtype) — the KernelFactory analog for comms.

Rank-local semantics (each rank holds its own shard) map onto global arrays:
an eager tensor sharded over the group axis IS the tuple of per-rank tensors.
On a single device / world_size 1, every collective degrades to its
mathematically correct identity.
"""
from __future__ import annotations

import functools
import threading
from typing import Dict, List, Optional, Sequence

import jax
import jax.numpy as jnp
import numpy as np
from jax import lax
from jax.sharding import Mesh, NamedSharding, PartitionSpec as P

import time

from ..core.tensor import Tensor
from ..core import flags
from ..observability import emit as _obs_emit
from .env import get_rank, get_world_size
from .comm_watchdog import comm_task, note_issue, set_restart_hook
from .elastic.epoch import EpochChangedError, current as _epoch_current


class ReduceOp:
    """Reference: paddle.distributed.ReduceOp (communication/reduce.py)."""

    SUM = "sum"
    MAX = "max"
    MIN = "min"
    PROD = "prod"
    AVG = "avg"


_lock = threading.RLock()
_group_registry: Dict[int, "Group"] = {}
_next_gid = [0]
_default_group: Optional["Group"] = None
_initialized = [False]


class Task:
    """Async collective handle (reference: ProcessGroup::Task,
    process_group.h:50). PJRT dispatch is already async — `wait` blocks on
    the result buffer."""

    def __init__(self, results: Sequence[jax.Array]):
        self._results = list(results)

    def is_completed(self) -> bool:
        for r in self._results:
            if hasattr(r, "is_ready") and not r.is_ready():
                return False
        return True

    def wait(self, timeout=None):
        for r in self._results:
            if hasattr(r, "block_until_ready"):
                r.block_until_ready()
        return True

    def synchronize(self):
        self.wait()


class Group:
    """A communication group: ordered ranks + (optionally) a device axis.

    Reference: python/paddle/distributed/collective.py:194 `Group`; the NCCL
    comm ring is replaced by a 1-D jax Mesh over the member devices (axis
    name `_pg{gid}` unless bound to a hybrid-topology axis like 'dp'/'mp').
    """

    def __init__(self, ranks: List[int], gid: int, axis_name: Optional[str] = None,
                 devices=None, mesh: Optional[Mesh] = None):
        self.ranks = list(ranks)
        self.id = gid
        # group-generation fence: a reconfiguration bumps the global epoch,
        # making every group built before it stale (elastic/epoch.py)
        self.epoch = _epoch_current()
        self.axis_name = axis_name or f"_pg{gid}"
        self._mesh = mesh
        if mesh is None and devices is not None and len(devices) == len(ranks):
            self._mesh = Mesh(np.asarray(devices), (self.axis_name,))

    # -- paddle.distributed.Group surface --------------------------------
    @property
    def nranks(self) -> int:
        return len(self.ranks)

    @property
    def world_size(self) -> int:
        return len(self.ranks)

    @property
    def rank(self) -> int:
        r = get_rank()
        return self.ranks.index(r) if r in self.ranks else -1

    @property
    def process_group(self):
        return self

    def get_group_rank(self, rank: int) -> int:
        return self.ranks.index(rank) if rank in self.ranks else -1

    @property
    def mesh(self) -> Optional[Mesh]:
        return self._mesh

    def is_member(self) -> bool:
        return get_rank() in self.ranks

    def __repr__(self):
        return (f"Group(id={self.id}, nranks={self.nranks}, "
                f"axis={self.axis_name!r}, ranks={self.ranks})")


def is_initialized() -> bool:
    return _initialized[0]


def _maybe_init_jax_distributed(world: int) -> None:
    """Bootstrap the PJRT coordination service (the TPU-native analog of the
    reference's TCPStore+NCCL rendezvous, SURVEY.md §7) from the env the
    launcher sets (launch/main.py: JAX_COORDINATOR_ADDRESS/_NUM_PROCESSES/
    _PROCESS_ID). Must run before the first backend use in the worker."""
    import os

    coord = os.environ.get("JAX_COORDINATOR_ADDRESS")
    if world <= 1 or not coord:
        return
    try:
        # probe WITHOUT touching the backend: jax.process_count() would
        # materialize a single-process backend and make initialize() a no-op
        from jax._src import distributed as _jd

        if getattr(_jd.global_state, "client", None) is not None:
            return  # already initialized
    except ImportError:
        pass
    try:
        jax.distributed.initialize(
            coordinator_address=coord,
            num_processes=int(os.environ.get("JAX_NUM_PROCESSES", world)),
            process_id=int(os.environ.get("JAX_PROCESS_ID",
                                          os.environ.get("PADDLE_TRAINER_ID",
                                                         "0"))))
    except RuntimeError as e:
        if "before any JAX" in str(e) or "already initialized" in str(e):
            import sys

            print("[paddle_tpu] WARNING: multi-process env is set but the "
                  "XLA backend was already initialized — staying "
                  "single-process. Call init_parallel_env() before any "
                  "jax/tensor work.", file=sys.stderr)
        else:
            raise  # unreachable coordinator etc. must not silently degrade


def _store_client():
    """Lazy per-process TCPStore client (PADDLE_MASTER from the launcher);
    used for cross-process eager p2p and store barriers."""
    import os

    if _store[0] is None and os.environ.get("PADDLE_MASTER"):
        from .store import TCPStore

        host, port = os.environ["PADDLE_MASTER"].rsplit(":", 1)
        _store[0] = TCPStore(host, int(port), is_master=False,
                             world_size=get_world_size())
    return _store[0]


_store: list = [None]


def init_parallel_env() -> Optional[Group]:
    """Reference: parallel.py:978 init_parallel_env — TCPStore rendezvous +
    default ProcessGroup. Multi-process: bootstraps jax.distributed (PJRT
    coordination service) from the launcher env, so jax.devices() spans all
    processes and every eager collective runs as a real multi-controller
    XLA program."""
    global _default_group
    with _lock:
        if _initialized[0]:
            return _default_group
        world = get_world_size()
        _maybe_init_jax_distributed(world)
        devices = jax.devices()
        n = max(world, 1)
        if jax.process_count() > 1:
            # process-per-host semantics: rank r <-> ONE device of process r
            # (multi-device-per-process meshes are the jit/shard_map path;
            # the eager rank-major tiling needs a 1:1 rank:device map)
            by_proc = {}
            for d in devices:
                by_proc.setdefault(d.process_index, d)
            devs = [by_proc[i] for i in sorted(by_proc)]
            n = len(devs)
        elif len(devices) >= n > 0 and world > 1:
            devs = devices[:n]
        else:
            devs = devices[: max(1, min(len(devices), n))]
        ranks = list(range(n))
        g = Group(ranks, gid=0, axis_name="world",
                  devices=devs if len(devs) == n else None)
        _group_registry[0] = g
        _default_group = g
        _initialized[0] = True
        _next_gid[0] = 1
        return g


def _get_or_init_default() -> Group:
    if not _initialized[0]:
        init_parallel_env()
    return _default_group


def new_group(ranks: Optional[List[int]] = None, backend: Optional[str] = None,
              timeout=None, axis_name: Optional[str] = None,
              devices=None, mesh: Optional[Mesh] = None) -> Group:
    """Reference: python/paddle/distributed/collective.py:194."""
    with _lock:
        _get_or_init_default()
        if ranks is None:
            ranks = list(range(get_world_size()))
        gid = _next_gid[0]
        _next_gid[0] += 1
        if mesh is None and devices is None:
            all_dev = jax.devices()
            if max(ranks, default=-1) < len(all_dev):
                devices = [all_dev[r] for r in ranks]
        g = Group(sorted(ranks), gid, axis_name=axis_name, devices=devices,
                  mesh=mesh)
        _group_registry[gid] = g
        return g


def get_group(gid: int = 0) -> Optional[Group]:
    _get_or_init_default()
    return _group_registry.get(gid)


def destroy_process_group(group: Optional[Group] = None):
    global _default_group
    with _lock:
        if group is None:
            _group_registry.clear()
            _default_group = None
            _initialized[0] = False
            _next_gid[0] = 0
        else:
            _group_registry.pop(group.id, None)


# ---------------------------------------------------------------------------
# Execution plumbing
# ---------------------------------------------------------------------------

def _unwrap(t):
    if isinstance(t, Tensor):
        return t._data
    return jnp.asarray(t)


def _wrap_like(arr, like) -> Tensor:
    if isinstance(like, Tensor):
        out = Tensor(arr)
        out.stop_gradient = like.stop_gradient
        return out
    return Tensor(arr)


def _is_traced(x) -> bool:
    return isinstance(x, jax.core.Tracer)


def _axis_in_scope(axis_name: str) -> bool:
    """True if `axis_name` is a bound mapped axis in the current trace."""
    try:
        lax.axis_size(axis_name)
        return True
    except (NameError, KeyError, ValueError, AssertionError):
        return False


@functools.lru_cache(maxsize=512)
def _eager_collective(mesh, axis, fn_name, nranks, **kw):
    """Cache of one-collective compiled executables (SURVEY.md §5 design)."""
    fn = _SHARD_FNS[fn_name]

    def per_shard(x):
        return fn(x, axis, nranks, **kw)

    sm = jax.shard_map(per_shard, mesh=mesh, in_specs=P(axis),
                       out_specs=_OUT_SPEC[fn_name](axis), check_vma=False)
    return jax.jit(sm)


def _reduce_term(x, axis, op):
    if op == ReduceOp.SUM:
        return lax.psum(x, axis)
    if op == ReduceOp.MAX:
        return lax.pmax(x, axis)
    if op == ReduceOp.MIN:
        return lax.pmin(x, axis)
    if op == ReduceOp.AVG:
        return lax.pmean(x, axis)
    if op == ReduceOp.PROD:
        return jnp.exp(lax.psum(jnp.log(jnp.abs(x) + 1e-38), axis)) * jnp.prod(
            jnp.sign(lax.all_gather(jnp.sign(x), axis)), axis=0)
    raise ValueError(f"unknown reduce op {op}")


_SHARD_FNS = {
    "all_reduce": lambda x, ax, n, op: _reduce_term(x, ax, op),
    "all_gather": lambda x, ax, n: lax.all_gather(x, ax, axis=0, tiled=False),
    # quantized-gradient gather (quant_comm int8 wire): all_gather
    # semantics under a distinct name so chaos/watchdog drills can
    # target the quantized collective specifically
    "q8_gather": lambda x, ax, n: lax.all_gather(x, ax, axis=0, tiled=False),
    "all_gather_tiled": lambda x, ax, n: lax.all_gather(x, ax, axis=0, tiled=True),
    "reduce_scatter": lambda x, ax, n: lax.psum_scatter(
        x, ax, scatter_dimension=0, tiled=True),
    "reduce_scatter_avg": lambda x, ax, n: lax.psum_scatter(
        x, ax, scatter_dimension=0, tiled=True) / n,
    "all_to_all": lambda x, ax, n: lax.all_to_all(
        x, ax, split_axis=0, concat_axis=0, tiled=True),
    "broadcast": lambda x, ax, n, src: jax.tree.map(
        lambda v: lax.all_gather(v, ax)[src], x),
    "reduce": lambda x, ax, n, op, dst: _reduce_term(x, ax, op),
}
_OUT_SPEC = {
    "all_reduce": lambda ax: P(ax),
    "all_gather": lambda ax: P(),            # gathered: replicated full copy
    "q8_gather": lambda ax: P(),
    "all_gather_tiled": lambda ax: P(),
    "reduce_scatter": lambda ax: P(ax),
    "reduce_scatter_avg": lambda ax: P(ax),
    "all_to_all": lambda ax: P(ax),
    "broadcast": lambda ax: P(ax),
    "reduce": lambda ax: P(ax),
}


_sim_rank_major = [False]


import contextlib


@contextlib.contextmanager
def simulate_rank_major():
    """Test-mode interpretation (SURVEY.md §4 pattern B localhost tests):
    an eager operand's leading dim is the stacked per-rank values — chunk i
    is rank i's local tensor. Mirrors the reference's multi-process
    collective tests on a single controller."""
    _sim_rank_major[0] = True
    try:
        yield
    finally:
        _sim_rank_major[0] = False


def _already_sharded(x, g: Group) -> bool:
    sh = getattr(x, "sharding", None)
    if sh is None or g._mesh is None:
        return False
    try:
        if len(x.sharding.device_set) <= 1:
            return False
        return not sh.is_fully_replicated and \
            x.sharding.device_set <= set(g._mesh.devices.flat)
    except Exception:
        return False


def _shardable(x, g: Group) -> bool:
    """Run the per-shard executable if the operand is genuinely laid out over
    the group's devices, or (simulation mode) rank-major stacked on dim 0."""
    if g._mesh is None or g.nranks <= 1:
        return False
    if _already_sharded(x, g):
        return True
    shape = getattr(x, "shape", ())
    return (_sim_rank_major[0] and bool(shape)
            and shape[0] % g.nranks == 0)


def _multiproc(g: Group) -> bool:
    """True when the group's mesh spans devices of >1 OS process (real
    multi-controller execution via the PJRT coordination service)."""
    if g._mesh is None or jax.process_count() <= 1:
        return False
    return len({d.process_index for d in g._mesh.devices.flat}) > 1


def _run_multiproc(g: Group, fn_name: str, x, **kw):
    """Real multi-process eager collective: this process's local tensor is
    one dim-0 tile of a global array laid out over the group mesh; the same
    cached one-collective executable runs as a multi-controller program and
    the local result is this rank's addressable shard.

    Reference analog: ProcessGroupNCCL dispatching one collective on the
    comm stream (process_group_nccl.h:37) — here the "comm stream" is an
    XLA executable over the coordination-service mesh."""
    squeeze = (getattr(x, "ndim", 0) == 0)
    if squeeze:
        x = jnp.reshape(x, (1,))
    sh = NamedSharding(g._mesh, P(g.axis_name))
    local = [d for d in g._mesh.devices.flat
             if d.process_index == jax.process_index()]
    if len(local) != 1:
        raise NotImplementedError(
            f"eager multi-process collectives need exactly one mesh device "
            f"per process (got {len(local)} local devices); use the "
            "jit/shard_map path for multi-device-per-process layouts")
    arrs = [jax.device_put(x, d) for d in local]
    gshape = (x.shape[0] * g.nranks,) + tuple(x.shape[1:])
    gx = jax.make_array_from_single_device_arrays(gshape, sh, arrs)
    exe = _eager_collective(g._mesh, g.axis_name, fn_name, g.nranks, **kw)
    _obs_emit("collective.issue", op=fn_name, group=g.id,
              rank=max(g.rank, 0), shape=tuple(x.shape),
              dtype=str(x.dtype), multiproc=True)
    t0 = time.perf_counter()
    with comm_task(fn_name, g.id, max(g.rank, 0), tuple(x.shape),
                   str(x.dtype)):
        out = exe(gx)
        res = out.addressable_shards[0].data
        # only when the watchdog is armed: block so a peer that never shows
        # up is caught here with op context (otherwise stay async — the Task
        # handle preserves dispatch/compute overlap)
        if float(flags.flag_value("comm_timeout") or 0.0) > 0:
            try:
                res.block_until_ready()
            except AttributeError:
                pass
    _obs_emit("collective.complete", dur_s=time.perf_counter() - t0,
              op=fn_name, group=g.id, rank=max(g.rank, 0))
    if squeeze and getattr(res, "ndim", 0) == 1 and res.shape[0] == 1:
        res = jnp.reshape(res, ())
    return res, Task([res])


# chaos choke point: installed by distributed/fault_tolerance/chaos.py only
# while FLAGS_chaos_spec is active — (op_name, rank) -> None, may delay or
# raise ChaosCollectiveTimeout (a TimeoutError, so the retry wrapper below
# exercises the same path a real hang-detected error would)
_chaos_hook = [None]


def set_chaos_hook(fn):
    _chaos_hook[0] = fn


flags.define_flag("collective_retries", 2,
                  "Retries for an eager collective that fails with a "
                  "retryable transport error (TimeoutError/ConnectionError) "
                  "before the error propagates; 0 disables")
flags.define_flag("collective_retry_backoff", 0.05,
                  "Base seconds for exponential backoff between collective "
                  "retries (doubles per attempt)")

# what the retry wrapper backs off on: declared-dead collectives (incl.
# injected ChaosCollectiveTimeout) and transport drops. Programming errors
# (shape/dtype/ValueError) propagate immediately. EpochChangedError is a
# plain RuntimeError, deliberately NOT retryable.
_RETRYABLE = (TimeoutError, ConnectionError)

# elastic verdict hook: fn(op, gid, rank, exc) -> bool, installed by the
# ElasticRuntime. Called when a collective fails with a retryable error;
# returning True means the failure resolved to a world change (membership
# shrank, reconfiguration ran) so retrying on the old group is pointless.
_world_changed_hook = [None]


def set_world_changed_hook(fn):
    prev = _world_changed_hook[0]
    _world_changed_hook[0] = fn
    return prev


def _run(group: Optional[Group], fn_name: str, tensor, sync_op=True, **kw):
    """Dispatch a collective: traced → lax op; eager → cached executable.

    Eager dispatch runs under a bounded retry wrapper: a retryable
    transport error (declared-dead collective, dropped store connection,
    injected chaos timeout) is retried with exponential backoff up to
    ``FLAGS_collective_retries`` times, each retry emitted as
    ``collective.retry`` (→ paddle_collective_retries_total{op})."""
    g = group or _get_or_init_default()
    x = _unwrap(tensor)
    if _is_traced(x) and _axis_in_scope(g.axis_name):
        out = _SHARD_FNS[fn_name](x, g.axis_name, g.nranks, **kw)
        return out, None
    start_epoch = _epoch_current()
    if getattr(g, "epoch", start_epoch) != start_epoch:
        raise EpochChangedError(
            f"{fn_name} issued on stale group {g.id} (epoch {g.epoch}, "
            f"current {start_epoch}); rebuild the group and re-run the "
            f"step on the post-reconfiguration world")
    retries = max(0, int(flags.flag_value("collective_retries")))
    attempt = 0
    while True:
        try:
            ch = _chaos_hook[0]
            if ch is not None:
                ch(fn_name, max(g.rank, 0))
            return _run_once(g, fn_name, x, **kw)
        except _RETRYABLE as e:
            # epoch fence: never retry across a reconfiguration — the old
            # group's mesh no longer matches the live world
            if _epoch_current() != start_epoch:
                raise EpochChangedError(
                    f"{fn_name} on group {g.id} failed and the world was "
                    f"reconfigured (epoch {start_epoch} -> "
                    f"{_epoch_current()}); re-run the step on the new "
                    f"group") from e
            verdict = _world_changed_hook[0]
            if verdict is not None:
                try:
                    changed = bool(verdict(fn_name, g.id, max(g.rank, 0), e))
                except Exception:  # noqa: BLE001 — a broken verdict hook
                    changed = False  # must not mask the transport error
                if changed:
                    raise EpochChangedError(
                        f"{fn_name} on group {g.id} resolved to a world "
                        f"change (epoch {start_epoch} -> "
                        f"{_epoch_current()}); re-run the step on the new "
                        f"group") from e
            attempt += 1
            if attempt > retries:
                raise
            delay = (float(flags.flag_value("collective_retry_backoff"))
                     * (2 ** (attempt - 1)))
            _obs_emit("collective.retry", op=fn_name, group=g.id,
                      rank=max(g.rank, 0), attempt=attempt,
                      error=f"{type(e).__name__}: {e}")
            time.sleep(delay)


def _run_once(g: Group, fn_name: str, x, **kw):
    """One eager dispatch attempt (everything below the retry wrapper)."""
    if _multiproc(g):
        return _run_multiproc(g, fn_name, x, **kw)
    if not _shardable(x, g):
        note_issue(fn_name, g.id, max(g.rank, 0))
        _obs_emit("collective.issue", op=fn_name, group=g.id,
                  rank=max(g.rank, 0),
                  shape=tuple(getattr(x, "shape", ())),
                  dtype=str(getattr(x, "dtype", "")), replicated=True)
        t0 = time.perf_counter()
        out = _replicated(fn_name, x, g, **kw)
        _obs_emit("collective.complete", dur_s=time.perf_counter() - t0,
                  op=fn_name, group=g.id, rank=max(g.rank, 0))
        return out, None
    # Lay the operand out over the group's device axis (rank-major on dim 0).
    # Already-sharded arrays are a no-op move.
    x = jax.device_put(x, NamedSharding(g._mesh, P(g.axis_name)))
    exe = _eager_collective(g._mesh, g.axis_name, fn_name, g.nranks,
                            **{k: v for k, v in kw.items()})
    note_issue(fn_name, g.id, max(g.rank, 0))
    _obs_emit("collective.issue", op=fn_name, group=g.id,
              rank=max(g.rank, 0), shape=tuple(getattr(x, "shape", ())),
              dtype=str(getattr(x, "dtype", "")), multiproc=False)
    t0 = time.perf_counter()
    out = exe(x)
    _obs_emit("collective.complete", dur_s=time.perf_counter() - t0,
              op=fn_name, group=g.id, rank=max(g.rank, 0))
    return out, Task([out])


def _replicated(fn_name, x, g, **kw):
    """Replicated-operand semantics: the tensor is one global value every
    rank holds identically (e.g. a synced gradient). Mathematically exact
    for n identical contributions."""
    n = g.nranks
    op = kw.get("op", ReduceOp.SUM)
    if fn_name in ("all_reduce", "reduce"):
        if op == ReduceOp.SUM:
            return x * n
        if op == ReduceOp.PROD:
            return x ** n
        return x  # max/min/avg of identical copies
    if fn_name in ("broadcast", "all_to_all", "all_gather_tiled",
                   "reduce_scatter", "reduce_scatter_avg"):
        if fn_name == "reduce_scatter" and n > 1:
            return x * n  # sum of n identical shards... caller keeps full
        return x  # AVG of identical shards is identity; caller keeps full
    if fn_name in ("all_gather", "q8_gather"):
        return jnp.stack([x] * n, axis=0) if n > 1 else x[None]
    raise ValueError(fn_name)


# ---------------------------------------------------------------------------
# Public collective API (reference: python/paddle/distributed/communication/*)
# ---------------------------------------------------------------------------

def all_reduce(tensor, op: str = ReduceOp.SUM, group: Optional[Group] = None,
               sync_op: bool = True):
    """In-place-style allreduce. Returns a Task (async handle)."""
    out, task = _run(group, "all_reduce", tensor, op=op)
    if isinstance(tensor, Tensor):
        tensor._data = out
        if sync_op and task is not None:
            task.wait()
        return task
    return _wrap_like(out, tensor)


def all_gather(tensor_list, tensor, group: Optional[Group] = None,
               sync_op: bool = True):
    """Gathers `tensor` from all ranks into `tensor_list` (stacked order).

    Traced mode: returns the stacked [nranks, ...] array (append to list)."""
    g = group or _get_or_init_default()
    out, task = _run(group, "all_gather", tensor)
    arr = out
    if tensor_list is not None:
        del tensor_list[:]
        n = g.nranks
        for i in range(n):
            tensor_list.append(_wrap_like(arr[i] if arr.shape[0] == n else arr,
                                          tensor))
    if sync_op and task is not None:
        task.wait()
    return task


def all_gather_into_tensor(out_tensor, tensor, group=None, sync_op=True,
                           tiled=True):
    out, task = _run(group, "all_gather_tiled" if tiled else "all_gather",
                     tensor)
    if isinstance(out_tensor, Tensor):
        out_tensor._data = out.reshape(out_tensor.shape) if hasattr(
            out_tensor, "shape") and out_tensor.shape else out
    return task


def reduce_scatter(tensor, tensor_or_tensor_list, op=ReduceOp.SUM, group=None,
                   sync_op=True):
    """Reduce + scatter along dim 0. `tensor` receives this rank's shard."""
    src = tensor_or_tensor_list
    if isinstance(src, (list, tuple)):
        src = Tensor(jnp.concatenate([_unwrap(t) for t in src], axis=0))
    fn = "reduce_scatter_avg" if op == ReduceOp.AVG else "reduce_scatter"
    out, task = _run(group, fn, src)
    if isinstance(tensor, Tensor):
        tensor._data = out
        return task
    return _wrap_like(out, src)


def alltoall(out_tensor_list, in_tensor_list, group=None, sync_op=True):
    g = group or _get_or_init_default()
    was_list = isinstance(in_tensor_list, (list, tuple))
    if was_list:
        x = jnp.stack([_unwrap(t) for t in in_tensor_list], axis=0)
    else:
        x = _unwrap(in_tensor_list)
    out, task = _run(group, "all_to_all", Tensor(x))
    if out_tensor_list is not None and isinstance(out_tensor_list, list):
        del out_tensor_list[:]
        n = g.nranks
        if was_list and out.shape[0] == n:
            # list-in/list-out contract: out[i] has in_tensor_list[i]'s shape
            for i in range(n):
                out_tensor_list.append(Tensor(out[i]))
        elif out.shape[0] % n == 0 and out.shape[0]:
            chunk = out.shape[0] // n
            for i in range(n):
                out_tensor_list.append(Tensor(out[i * chunk:(i + 1) * chunk]))
        else:
            out_tensor_list.append(Tensor(out))
    return task


def alltoall_single(out_tensor, in_tensor, in_split_sizes=None,
                    out_split_sizes=None, group=None, sync_op=True):
    out, task = _run(group, "all_to_all", in_tensor)
    if isinstance(out_tensor, Tensor):
        out_tensor._data = out
        return task
    return _wrap_like(out, in_tensor)


def broadcast(tensor, src: int = 0, group: Optional[Group] = None,
              sync_op: bool = True):
    g = group or _get_or_init_default()
    src_local = g.get_group_rank(src) if src in g.ranks else src
    out, task = _run(group, "broadcast", tensor, src=max(src_local, 0))
    if isinstance(tensor, Tensor):
        tensor._data = out
        return task
    return _wrap_like(out, tensor)


def reduce(tensor, dst: int = 0, op: str = ReduceOp.SUM, group=None,
           sync_op=True):
    g = group or _get_or_init_default()
    out, task = _run(group, "reduce", tensor, op=op,
                     dst=g.get_group_rank(dst) if dst in g.ranks else 0)
    if isinstance(tensor, Tensor):
        tensor._data = out
        return task
    return _wrap_like(out, tensor)


def scatter(tensor, tensor_list=None, src: int = 0, group=None, sync_op=True):
    """Scatter list from src. Single-controller: rank r takes tensor_list[r]."""
    g = group or _get_or_init_default()
    if tensor_list:
        r = max(g.rank, 0)
        if isinstance(tensor, Tensor):
            tensor._data = _unwrap(tensor_list[r])
    return None


# live-world provider: fn() -> int, installed by the ElasticRuntime so
# post-reconfiguration code paths (gang-restart barrier) count the CURRENT
# world, not the launch-time world a dead rank can never rejoin.
_live_world_fn = [None]


def set_live_world_fn(fn):
    prev = _live_world_fn[0]
    _live_world_fn[0] = fn
    return prev


def current_world_size() -> int:
    """Live world size when an elastic runtime is active, else launch-time."""
    fn = _live_world_fn[0]
    if fn is not None:
        try:
            n = int(fn())
            if n > 0:
                return n
        except Exception:  # noqa: BLE001 — fall back to the static world
            pass
    return get_world_size()


def replace_default_group(group: Group):
    """Adopt `group` as the default after an in-job elastic reconfiguration
    so code that resolves groups lazily (get_group(0), barrier(None), ...)
    sees the post-reconfiguration world."""
    global _default_group
    with _lock:
        _group_registry[0] = group
        _default_group = group


def gang_restart_barrier(timeout: float = 60.0) -> bool:
    """The watchdog ladder's 'restart' stage: rendezvous every rank at a
    TCPStore barrier so survivors of a detected hang re-align (and a truly
    dead peer turns the hang into a clean barrier timeout) before resuming.
    Returns True when the gang reached the barrier.

    The barrier counts the CURRENT world size (live-world provider): after
    an elastic shrink the launch-time count would wait forever for a rank
    that is never coming back."""
    ws = current_world_size()
    _obs_emit("collective.gang_restart", world=ws)
    client = _store_client()
    if client is None:
        return True  # single process: nothing to rendezvous with
    try:
        client.barrier("_gang_restart", timeout=timeout, world_size=ws)
        return True
    except Exception:  # noqa: BLE001 — a failed rendezvous means the gang
        return False   # is really gone; the ladder falls through to abort


set_restart_hook(gang_restart_barrier)


def barrier(group: Optional[Group] = None):
    """All outstanding PJRT work flushed = barrier on a single controller;
    multi-host adds a tiny psum over the group."""
    g = group or _get_or_init_default()
    if g._mesh is not None and g.nranks > 1:
        out, task = _run(g, "all_reduce", Tensor(jnp.zeros((g.nranks,))),
                         op=ReduceOp.SUM)
        if task:
            task.wait()
    else:
        (jax.device_put(0.0) + 0).block_until_ready()


# -- p2p --------------------------------------------------------------------

_p2p_mailbox: Dict[tuple, list] = {}
_p2p_seq: Dict[tuple, int] = {}


def _p2p_store_key(gid, src, dst, seq):
    return f"__p2p/{gid}/{src}->{dst}/{seq}"


def send(tensor, dst: int = 0, group=None, sync_op=True):
    """P2P send. Traced: `lax.ppermute` is the TPU-native path (used by the
    PP engine). Eager multi-process: serialized through the TCPStore (the
    reference's rendezvous channel doubles as the CPU p2p transport, like
    its Gloo path). Eager single-controller: mailbox delivery."""
    import numpy as _np

    g = group or _get_or_init_default()
    me = max(g.get_group_rank(get_rank()), 0)  # group-local on BOTH sides
    peer = g.get_group_rank(dst) if dst in g.ranks else dst
    store = _store_client()
    if store is not None and jax.process_count() > 1:
        key = (g.id, me, peer)
        seq = _p2p_seq.get(key, 0)
        _p2p_seq[key] = seq + 1
        arr = _np.asarray(_unwrap(tensor))
        header = f"{arr.dtype.str}|{','.join(map(str, arr.shape))}|".encode()
        store.set(_p2p_store_key(g.id, me, peer, seq),
                  header + arr.tobytes())
        return None
    key = (g.id, max(g.rank, 0), peer)
    _p2p_mailbox.setdefault(key, []).append(_unwrap(tensor))


def recv(tensor, src: int = 0, group=None, sync_op=True):
    import numpy as _np

    g = group or _get_or_init_default()
    peer = g.get_group_rank(src) if src in g.ranks else src
    store = _store_client()
    if store is not None and jax.process_count() > 1:
        me = max(g.get_group_rank(get_rank()), 0)
        key = (g.id, peer, me)
        seq = _p2p_seq.get(("r",) + key, 0)
        _p2p_seq[("r",) + key] = seq + 1
        skey = _p2p_store_key(g.id, peer, me, seq)
        # store.wait registers its own comm_task; give it the p2p context
        # via the key so a hang reports once with full metadata
        store.wait(skey)
        raw = store.get(skey)
        store.delete_key(skey)  # 5) consumed — don't grow the master KV
        dt, shape, payload = raw.split(b"|", 2)
        shape = tuple(int(v) for v in shape.decode().split(",") if v)
        arr = _np.frombuffer(payload, dtype=_np.dtype(dt.decode()))
        arr = arr.reshape(shape)
        if isinstance(tensor, Tensor):
            tensor._data = jnp.asarray(arr)
        return None
    key = (g.id, peer, max(g.rank, 0))
    box = _p2p_mailbox.get(key)
    if box:
        arr = box.pop(0)
        if isinstance(tensor, Tensor):
            tensor._data = arr
        return None
    return None


def isend(tensor, dst=0, group=None):
    send(tensor, dst, group)
    return Task([])


def irecv(tensor, src=0, group=None):
    recv(tensor, src, group)
    return Task([])


class P2POp:
    """Reference: batch_isend_irecv.py P2POp."""

    def __init__(self, op, tensor, peer, group=None):
        self.op = op
        self.tensor = tensor
        self.peer = peer
        self.group = group


def batch_isend_irecv(p2p_op_list: List[P2POp]) -> List[Task]:
    tasks = []
    for p in p2p_op_list:
        tasks.append(p.op(p.tensor, p.peer, p.group) or Task([]))
    return tasks


# -- object collectives -----------------------------------------------------

def all_gather_object(object_list: list, obj, group=None):
    """Single-controller: every rank's object is the same python object."""
    g = group or _get_or_init_default()
    del object_list[:]
    object_list.extend([obj] * g.nranks)


def broadcast_object_list(object_list, src=0, group=None):
    return object_list


def get_global_rank(group: Group, group_rank: int) -> int:
    return group.ranks[group_rank]


def get_backend(group: Optional[Group] = None) -> str:
    return "xla"

"""Block-scaled int8 codec for collective and P2P wires.

Implements the EQuARX-style (arXiv 2506.17615) block-scaled int8
quantization used by two transports:

* DP gradient collectives (``FLAGS_dp_grad_comm_dtype="int8"``): the
  flat bucket buffer is quantized per ``FLAGS_dp_comm_block_size``-sized
  block with one float32 absmax scale per block, and an error-feedback
  residual (the per-element quantization error) is carried into the next
  step's gradients so convergence stays within tolerance of the fp32
  wire for both the all-reduce and reduce-scatter/all-gather (ZeRO-1
  ``sharded_update``) paths.
* Pipeline P2P activation/gradient handoffs
  (``FLAGS_pp_p2p_comm_dtype="int8"`` — or ``bfloat16``/``float16`` for
  a plain cast wire), with no error feedback: activations are not
  accumulated across steps, so the per-handoff rounding is the whole
  story.

Wire layout: one 1-D int8 buffer — ``nblocks * block`` quantized payload
elements followed by ``4 * nblocks`` scale bytes (the float32 scales
bitcast into int8 via ``lax.bitcast_convert_type``). float32 scales (not
float16) so a single-outlier block (absmax * 127 > 65504) cannot
overflow and tiny-gradient scales are not flushed to zero (which would
make the error-feedback residual grow without ever draining). For the
default block of 256, bytes-on-wire vs an fp32 buffer is
``4 * 256 / (256 + 4) = 3.94x``.

Everything here is traceable: the encode/decode bodies are fused into
the jitted flat pack/unpack executables built by
``distributed/parallel.py``, keyed by the same signature as the bucket
plan — zero steady-state retraces.
"""

from __future__ import annotations

from typing import Callable, Optional, Tuple

import jax
import jax.numpy as jnp
import numpy as np
from jax import lax

from ..core import flags

__all__ = [
    "block_size", "wire_layout", "encode_flat", "decode_flat",
    "make_pack_q8", "make_decode_q8", "zeros_residual",
    "p2p_comm_dtype", "p2p_encode",
]

flags.define_flag(
    "dp_comm_block_size", 256,
    "Quantization block size (elements per float32 absmax scale) for the "
    "block-scaled int8 wire codec used when FLAGS_dp_grad_comm_dtype or "
    "FLAGS_pp_p2p_comm_dtype is 'int8'; each block ships one float32 "
    "scale (4 bytes) alongside its int8 payload")

flags.define_flag(
    "pp_p2p_comm_dtype", "",
    "Wire dtype for pipeline-parallel P2P stage handoffs: '' keeps the "
    "activation dtype, 'bfloat16'/'float16' cast on the wire, 'int8' "
    "applies the block-scaled codec (FLAGS_dp_comm_block_size) to both "
    "activation and gradient handoffs")

#: Bytes of scale metadata per block: one float32 bitcast to 4 int8.
SCALE_BYTES = 4

_P2P_DTYPES = {
    "bf16": "bfloat16", "bfloat16": "bfloat16",
    "fp16": "float16", "float16": "float16",
    "int8": "int8",
}


def block_size() -> int:
    """Current ``FLAGS_dp_comm_block_size`` (validated)."""
    b = int(flags.flag_value("dp_comm_block_size") or 0)
    if b <= 0:
        raise ValueError(
            f"FLAGS_dp_comm_block_size={b}: want a positive element count")
    return b


def wire_layout(numel: int, block: int) -> Tuple[int, int, int]:
    """``(qpadded, nblocks, wire_len)`` for a flat payload of ``numel``.

    ``qpadded`` is ``numel`` rounded up to a whole number of blocks (the
    pad tail quantizes to zeros and is sliced off on decode); ``wire_len``
    is the total int8 buffer length including the trailing scale bytes.
    """
    nblocks = max(1, -(-numel // block))
    qpadded = nblocks * block
    return qpadded, nblocks, qpadded + SCALE_BYTES * nblocks


# ---------------------------------------------------------------------------
# Traceable codec primitives
# ---------------------------------------------------------------------------

def encode_flat(total, block: int):
    """f32 ``[qpadded]`` -> (int8 wire ``[qpadded + 4*nblocks]``, residual).

    Per-block absmax scaling: ``scale = absmax / 127``; all-zero blocks
    use a divisor of 1 so they encode (and decode) to exact zeros with
    zero residual. The residual is ``total - dequant(q)``, the exact
    error-feedback carry.
    """
    nblocks = total.shape[0] // block
    blocks = total.reshape(nblocks, block)
    scale = (jnp.max(jnp.abs(blocks), axis=1) / 127.0).astype(jnp.float32)
    safe = jnp.where(scale > 0, scale, 1.0)
    q = jnp.clip(jnp.round(blocks / safe[:, None]), -127, 127)
    q = q.astype(jnp.int8)
    residual = (blocks - q.astype(jnp.float32) * scale[:, None]).reshape(-1)
    scale_bytes = lax.bitcast_convert_type(scale, jnp.int8).reshape(-1)
    return jnp.concatenate([q.reshape(-1), scale_bytes]), residual


def decode_flat(wire, nblocks: int, block: int):
    """int8 wire -> f32 ``[nblocks * block]`` (inverse of ``encode_flat``)."""
    payload = wire[: nblocks * block].reshape(nblocks, block)
    scale = lax.bitcast_convert_type(
        wire[nblocks * block:].reshape(nblocks, SCALE_BYTES), jnp.float32)
    return (payload.astype(jnp.float32) * scale[:, None]).reshape(-1)


# ---------------------------------------------------------------------------
# DP bucket executables (built once per plan, signature-keyed by the caller)
# ---------------------------------------------------------------------------

def zeros_residual(b):
    """Fresh all-zero error-feedback accumulator for bucket ``b``."""
    return jnp.zeros((b.qpadded,), jnp.float32)


def make_pack_q8(b) -> Callable:
    """Jitted ``(grads, residual) -> (wire, new_residual)`` for bucket ``b``.

    Fuses the flat pack (concat + pad, as ``_make_pack``) with the
    error-feedback add and the block codec in one executable: the grads
    plus the carried residual are quantized, and the new residual is the
    exact quantization error of that total.
    """
    pad = b.qpadded - b.numel
    block = b.qblock

    def pack(arrs, residual):
        flat = jnp.concatenate(
            [jnp.ravel(a).astype(jnp.float32) for a in arrs])
        if pad:
            flat = jnp.concatenate([flat, jnp.zeros((pad,), jnp.float32)])
        return encode_flat(flat + residual, block)

    return jax.jit(pack)


def make_decode_q8(b) -> Callable:
    """Jitted ``gathered int8 [nranks, wire] -> f32 [padded]`` for ``b``.

    Dequantizes every rank's wire row and means across ranks — the AVG
    half of the quantized all-reduce (the gather half runs as the
    ``q8_gather`` named collective). In the single-controller replicated
    fallback all rows are identical and the mean reduces to a plain
    dequant. Output is sliced to the bucket's nranks-aligned ``padded``
    length so both the per-param unpack and the ZeRO-1 shard path
    consume it unchanged.
    """
    nblocks, block, padded = b.qblocks, b.qblock, b.padded

    def decode(gathered):
        deq = jax.vmap(lambda w: decode_flat(w, nblocks, block))(gathered)
        return jnp.mean(deq, axis=0)[:padded]

    return jax.jit(decode)


# ---------------------------------------------------------------------------
# Pipeline P2P wire codec (module-level executable cache, keyed by signature)
# ---------------------------------------------------------------------------

def p2p_comm_dtype() -> Optional[str]:
    """Canonical ``FLAGS_pp_p2p_comm_dtype`` value, or None when unset."""
    raw = str(flags.flag_value("pp_p2p_comm_dtype") or "")
    if not raw:
        return None
    name = _P2P_DTYPES.get(raw.lower())
    if name is None:
        raise ValueError(
            f"FLAGS_pp_p2p_comm_dtype={raw!r}: want '', 'bfloat16', "
            f"'float16' or 'int8'")
    return name


_P2P_EXES: dict = {}


def _build_p2p_codec(shape, dtype, wire, block):
    numel = int(np.prod(shape)) if shape else 1
    if wire == "int8":
        qpadded, nblocks, _ = wire_layout(numel, block)

        def enc(x):
            flat = jnp.ravel(x).astype(jnp.float32)
            if qpadded > numel:
                flat = jnp.concatenate(
                    [flat, jnp.zeros((qpadded - numel,), jnp.float32)])
            return encode_flat(flat, block)[0]

        def dec(w):
            flat = decode_flat(w, nblocks, block)[:numel]
            return flat.reshape(shape).astype(np.dtype(dtype))
    else:
        def enc(x):
            return x.astype(np.dtype(wire))

        def dec(w):
            return w.astype(np.dtype(dtype))

    return jax.jit(enc), jax.jit(dec)


def p2p_encode(arr):
    """Encode ``arr`` for the P2P wire per ``FLAGS_pp_p2p_comm_dtype``.

    Returns ``(wire_buffer, decode_fn, wire_dtype_name)``; ``decode_fn``
    is None when the flag is off or ``arr`` is not a floating payload
    (the buffer is then ``arr`` itself). Executables are cached by
    ``(shape, dtype, wire_dtype, block)`` — steady-state handoffs reuse
    them with zero retraces.
    """
    name = p2p_comm_dtype()
    if (name is None or not hasattr(arr, "dtype")
            or not jnp.issubdtype(arr.dtype, jnp.floating)
            or str(arr.dtype) == name):
        return arr, None, None
    block = block_size() if name == "int8" else 0
    if block:
        # clamp to the payload so a small activation is one exact block
        # (no pad tail) instead of drowning in block padding
        block = max(1, min(block, int(np.prod(arr.shape)) or 1))
    key = (tuple(arr.shape), str(arr.dtype), name, block)
    exe = _P2P_EXES.get(key)
    if exe is None:
        exe = _P2P_EXES[key] = _build_p2p_codec(
            tuple(arr.shape), str(arr.dtype), name, block)
    enc, dec = exe
    return enc(arr), dec, name

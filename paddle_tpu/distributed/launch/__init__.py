from .main import Pod, launch, main, parse_args  # noqa: F401

"""Distributed launcher — `python -m paddle_tpu.distributed.launch`.

Reference: python/paddle/distributed/launch/main.py:23 +
CollectiveController.build_pod (launch/controllers/collective.py:22,:37):
build a Pod of per-device worker procs with rank env
(PADDLE_TRAINER_ID/PADDLE_TRAINERS_NUM/endpoints), a master KV for
rendezvous (HTTP or etcd there), per-rank log files, a watcher that monitors
children and restarts the pod up to --max_restart times (elastic manager:
fleet/elastic/manager.py:125).

TPU-native: one worker per HOST (PJRT owns all local chips; JAX's
distributed runtime is process-per-host), not per device. The master KV is
our native TCPStore (core/native/src/native.cc). Worker env carries
PADDLE_TRAINER_ID + PADDLE_MASTER, which init_parallel_env and
jax.distributed.initialize consume.
"""
from __future__ import annotations

import argparse
import os
import signal
import subprocess
import sys
import time
from typing import List, Optional


def parse_args(argv: Optional[List[str]] = None):
    p = argparse.ArgumentParser(
        prog="paddle_tpu.distributed.launch",
        description="Launch distributed training (process-per-host on TPU)")
    p.add_argument("--master", type=str, default=None,
                   help="master endpoint ip:port for rendezvous")
    p.add_argument("--nnodes", type=str, default="1",
                   help="node count, or min:max for elastic")
    p.add_argument("--rank", type=int, default=-1, help="node rank")
    p.add_argument("--nproc_per_node", type=int, default=None,
                   help="procs per node (default 1: PJRT owns local chips)")
    p.add_argument("--devices", "--gpus", type=str, default=None,
                   help="visible device ids for this node")
    p.add_argument("--log_dir", type=str, default="log")
    p.add_argument("--job_id", type=str, default="default")
    p.add_argument("--max_restart", type=int, default=3)
    p.add_argument("--run_mode", type=str, default="collective")
    p.add_argument("training_script", type=str)
    p.add_argument("training_script_args", nargs=argparse.REMAINDER)
    return p.parse_args(argv)


class Pod:
    """A node's worker processes (reference: launch/job/pod.py)."""

    def __init__(self, args):
        self.args = args
        self.procs: List[subprocess.Popen] = []
        self.logs = []

    def spawn(self, node_rank: int, nnodes: int, store_port: int):
        nproc = self.args.nproc_per_node or 1
        os.makedirs(self.args.log_dir, exist_ok=True)
        world = nnodes * nproc
        master_host = (self.args.master.split(":")[0]
                       if self.args.master else "127.0.0.1")
        for lr in range(nproc):
            rank = node_rank * nproc + lr
            env = dict(os.environ)
            env.update({
                "PADDLE_TRAINER_ID": str(rank),
                "PADDLE_TRAINERS_NUM": str(world),
                "PADDLE_LOCAL_RANK": str(lr),
                "PADDLE_MASTER": f"{master_host}:{store_port}",
                "PADDLE_JOB_ID": self.args.job_id,
                # jax.distributed.initialize() picks these up
                "JAX_COORDINATOR_ADDRESS": f"{master_host}:{store_port + 1}",
                "JAX_NUM_PROCESSES": str(world),
                "JAX_PROCESS_ID": str(rank),
            })
            if self.args.devices:
                env["CUDA_VISIBLE_DEVICES"] = self.args.devices
                env["TPU_VISIBLE_DEVICES"] = self.args.devices
            log_path = os.path.join(self.args.log_dir,
                                    f"workerlog.{rank}")
            logf = open(log_path, "a")
            cmd = [sys.executable, "-u", self.args.training_script,
                   *self.args.training_script_args]
            proc = subprocess.Popen(cmd, env=env, stdout=logf, stderr=logf)
            self.procs.append(proc)
            self.logs.append(logf)

    def watch(self) -> int:
        """Block until all exit ok (0) or any fails (its code)."""
        while True:
            alive = False
            for p in self.procs:
                rc = p.poll()
                if rc is None:
                    alive = True
                elif rc != 0:
                    return rc
            if not alive:
                return 0
            time.sleep(0.5)

    def terminate(self):
        for p in self.procs:
            if p.poll() is None:
                p.send_signal(signal.SIGTERM)
        deadline = time.time() + 10
        for p in self.procs:
            try:
                p.wait(timeout=max(0.1, deadline - time.time()))
            except subprocess.TimeoutExpired:
                p.kill()
        for f in self.logs:
            try:
                f.close()
            except OSError:
                pass
        self.procs.clear()
        self.logs.clear()


def launch(argv: Optional[List[str]] = None) -> int:
    args = parse_args(argv)
    nnodes = int(str(args.nnodes).split(":")[0])
    node_rank = args.rank if args.rank >= 0 else int(
        os.environ.get("PADDLE_NODE_RANK", "0"))
    # master KV server lives on node 0 (reference: controllers/master.py)
    store = None
    if args.master:
        port = int(args.master.split(":")[1])
    else:
        port = int(os.environ.get("PADDLE_MASTER_PORT", "29750"))
    if node_rank == 0:
        from ..store import TCPStore

        try:
            store = TCPStore("127.0.0.1", port, is_master=True,
                             world_size=nnodes)
        except OSError:
            store = None  # external master already running

    restarts = 0
    try:
        while True:
            pod = Pod(args)
            pod.spawn(node_rank, nnodes, port)
            rc = pod.watch()
            if rc == 0:
                print(f"[launch] job {args.job_id} finished OK")
                return 0
            pod.terminate()
            restarts += 1
            if restarts > args.max_restart:
                print(f"[launch] worker failed (exit {rc}); restart budget "
                      f"exhausted after {restarts - 1} retries",
                      file=sys.stderr)
                return rc
            print(f"[launch] worker failed (exit {rc}); restart "
                  f"{restarts}/{args.max_restart}", file=sys.stderr)
            time.sleep(1.0)
    finally:
        if store is not None:
            store.stop()


def main():  # pragma: no cover - thin CLI shim
    sys.exit(launch())

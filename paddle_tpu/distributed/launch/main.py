"""Distributed launcher — `python -m paddle_tpu.distributed.launch`.

Reference: python/paddle/distributed/launch/main.py:23 +
CollectiveController.build_pod (launch/controllers/collective.py:22,:37):
build a Pod of per-device worker procs with rank env
(PADDLE_TRAINER_ID/PADDLE_TRAINERS_NUM/endpoints), a master KV for
rendezvous (HTTP or etcd there), per-rank log files, a watcher that monitors
children and restarts the pod up to --max_restart times (elastic manager:
fleet/elastic/manager.py:125).

TPU-native: one worker per HOST (PJRT owns all local chips; JAX's
distributed runtime is process-per-host), not per device. The master KV is
our native TCPStore (core/native/src/native.cc). Worker env carries
PADDLE_TRAINER_ID + PADDLE_MASTER, which init_parallel_env and
jax.distributed.initialize consume.
"""
from __future__ import annotations

import argparse
import os
import signal
import subprocess
import sys
import time
from typing import List, Optional


def parse_args(argv: Optional[List[str]] = None):
    p = argparse.ArgumentParser(
        prog="paddle_tpu.distributed.launch",
        description="Launch distributed training (process-per-host on TPU)")
    p.add_argument("--master", type=str, default=None,
                   help="master endpoint ip:port for rendezvous")
    p.add_argument("--nnodes", type=str, default="1",
                   help="node count, or min:max for elastic")
    p.add_argument("--rank", type=int, default=-1, help="node rank")
    p.add_argument("--nproc_per_node", type=int, default=None,
                   help="procs per node (default 1: PJRT owns local chips)")
    p.add_argument("--devices", "--gpus", type=str, default=None,
                   help="visible device ids for this node")
    p.add_argument("--log_dir", type=str, default="log")
    p.add_argument("--job_id", type=str, default="default")
    p.add_argument("--max_restart", type=int, default=3)
    p.add_argument("--run_mode", type=str, default="collective")
    p.add_argument("training_script", type=str)
    p.add_argument("training_script_args", nargs=argparse.REMAINDER)
    return p.parse_args(argv)


class Pod:
    """A node's worker processes (reference: launch/job/pod.py)."""

    def __init__(self, args):
        self.args = args
        self.procs: List[subprocess.Popen] = []
        self.logs = []

    def spawn(self, node_rank: int, nnodes: int, store_port: int):
        nproc = self.args.nproc_per_node or 1
        os.makedirs(self.args.log_dir, exist_ok=True)
        world = nnodes * nproc
        master_host = (self.args.master.split(":")[0]
                       if self.args.master else "127.0.0.1")
        for lr in range(nproc):
            rank = node_rank * nproc + lr
            env = dict(os.environ)
            env.update({
                "PADDLE_TRAINER_ID": str(rank),
                "PADDLE_TRAINERS_NUM": str(world),
                "PADDLE_LOCAL_RANK": str(lr),
                "PADDLE_MASTER": f"{master_host}:{store_port}",
                "PADDLE_JOB_ID": self.args.job_id,
                # jax.distributed.initialize() picks these up
                "JAX_COORDINATOR_ADDRESS": f"{master_host}:{store_port + 1}",
                "JAX_NUM_PROCESSES": str(world),
                "JAX_PROCESS_ID": str(rank),
            })
            if self.args.devices:
                env["CUDA_VISIBLE_DEVICES"] = self.args.devices
                env["TPU_VISIBLE_DEVICES"] = self.args.devices
            log_path = os.path.join(self.args.log_dir,
                                    f"workerlog.{rank}")
            logf = open(log_path, "a")
            cmd = [sys.executable, "-u", self.args.training_script,
                   *self.args.training_script_args]
            proc = subprocess.Popen(cmd, env=env, stdout=logf, stderr=logf)
            self.procs.append(proc)
            self.logs.append(logf)

    def poll(self) -> Optional[int]:
        """None while any worker runs; else 0 or the first failure code."""
        alive = False
        for p in self.procs:
            rc = p.poll()
            if rc is None:
                alive = True
            elif rc != 0:
                return rc
        return None if alive else 0

    def watch(self) -> int:
        """Block until all exit ok (0) or any fails (its code)."""
        while True:
            rc = self.poll()
            if rc is not None:
                return rc
            time.sleep(0.5)

    def terminate(self):
        for p in self.procs:
            if p.poll() is None:
                p.send_signal(signal.SIGTERM)
        deadline = time.time() + 10
        for p in self.procs:
            try:
                p.wait(timeout=max(0.1, deadline - time.time()))
            except subprocess.TimeoutExpired:
                p.kill()
        for f in self.logs:
            try:
                f.close()
            except OSError:
                pass
        self.procs.clear()
        self.logs.clear()


def launch(argv: Optional[List[str]] = None) -> int:
    args = parse_args(argv)
    min_n, max_n = [int(x) for x in (str(args.nnodes).split(":") * 2)[:2]]
    node_rank = args.rank if args.rank >= 0 else int(
        os.environ.get("PADDLE_NODE_RANK", "0"))
    # master KV server lives on node 0 (reference: controllers/master.py)
    store = None
    if args.master:
        port = int(args.master.split(":")[1])
    else:
        port = int(os.environ.get("PADDLE_MASTER_PORT", "29750"))
    if node_rank == 0:
        from ..store import TCPStore

        try:
            store = TCPStore("127.0.0.1", port, is_master=True,
                             world_size=min_n)
        except OSError:
            store = None  # external master already running

    try:
        if max_n > min_n or os.environ.get("PADDLE_ELASTIC_JOB_ID"):
            return _launch_elastic(args, min_n, max_n, port)
        return _launch_fixed(args, node_rank, min_n, port)
    finally:
        if store is not None:
            store.stop()


def _launch_fixed(args, node_rank: int, nnodes: int, port: int) -> int:
    """Fixed-world mode: restart the pod in place up to --max_restart."""
    restarts = 0
    while True:
        pod = Pod(args)
        pod.spawn(node_rank, nnodes, port)
        rc = pod.watch()
        if rc == 0:
            print(f"[launch] job {args.job_id} finished OK")
            return 0
        pod.terminate()
        restarts += 1
        if restarts > args.max_restart:
            print(f"[launch] worker failed (exit {rc}); restart budget "
                  f"exhausted after {restarts - 1} retries",
                  file=sys.stderr)
            return rc
        print(f"[launch] worker failed (exit {rc}); restart "
              f"{restarts}/{args.max_restart}", file=sys.stderr)
        time.sleep(1.0)


def _launch_elastic(args, min_n: int, max_n: int, port: int) -> int:
    """Elastic mode (--nnodes min:max): store-backed registry, rescale on
    node loss/join, ranks reassigned each generation.

    Reference: fleet/elastic/manager.py:125 + the watch/launch loop in
    elastic/__init__.py. Trainers see a fresh PADDLE_TRAINER_ID /
    PADDLE_TRAINERS_NUM each generation and are expected to resume from
    their last checkpoint (distributed.checkpoint reshards on load).
    """
    from ..fleet.elastic import ElasticManager, ElasticStatus
    from ..store import TCPStore

    master_host = (args.master.split(":")[0] if args.master else "127.0.0.1")
    client = TCPStore(master_host, port, is_master=False)
    mgr = ElasticManager(client, args.job_id, nnodes=f"{min_n}:{max_n}")
    mgr.register()
    generation = 0   # every rebuild (rescale OR failure)
    failures = 0     # only worker failures count against --max_restart
    try:
        while True:
            status, rank, world, nodes = mgr.wait_for_world()
            if status == ElasticStatus.EXIT:
                done = mgr.is_done()
                print(f"[launch][elastic] exiting "
                      f"({'job done' if done else 'below min past timeout'})")
                return 0 if done else 1
            print(f"[launch][elastic] generation up: rank={rank} "
                  f"world={world} nodes={nodes}")
            pod = Pod(args)
            os.environ["PADDLE_ELASTIC_GENERATION"] = str(generation)
            pod.spawn(rank, world, port)
            status = mgr.watch(pod.poll)
            pod.terminate()
            if status == ElasticStatus.COMPLETED:
                mgr.exit(completed=True)
                print(f"[launch] job {args.job_id} finished OK")
                return 0
            if status == ElasticStatus.EXIT:
                print("[launch][elastic] peer finished the job; exiting")
                return 0
            # ERROR (local worker died, node stays registered) or RESTART
            # (peer set changed): either way, re-rendezvous for a new world.
            # Only FAILURES consume the --max_restart budget — legitimate
            # rescale events are the point of elastic mode, not faults.
            generation += 1
            if status == ElasticStatus.ERROR:
                failures += 1
                if failures > args.max_restart:
                    print(f"[launch][elastic] restart budget exhausted "
                          f"after {failures - 1} retries", file=sys.stderr)
                    return 1
            print(f"[launch][elastic] {status}: re-rendezvous "
                  f"(generation {generation}, failures {failures})",
                  file=sys.stderr)
    finally:
        mgr.exit()
        client.stop()


def main():  # pragma: no cover - thin CLI shim
    sys.exit(launch())

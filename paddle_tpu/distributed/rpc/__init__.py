"""paddle.distributed.rpc parity — remote procedure calls between workers.

Reference: python/paddle/distributed/rpc/rpc.py:85 (init_rpc over a brpc
ProcessGroupRpc + barrier store), :160 rpc_sync, :206 rpc_async, plus
WorkerInfo exchange (:65). TPU-native: no brpc in the image and none
needed — an RPC here is host-side orchestration (TPU compute goes through
collectives, not RPC), so the transport is a plain socket server per
worker with pickled (fn, args, kwargs) frames, and worker discovery rides
the same TCPStore used for rendezvous.

The API contract matches the reference: functions must be importable on
the callee (pickled by reference), results pickle back, `rpc_async`
returns a future with .wait().
"""
from __future__ import annotations

import concurrent.futures as _futures
import os
import pickle
import socket
import struct
import threading
import time
from dataclasses import dataclass
from typing import Any, Dict, List, Optional, Tuple

from ..store import TCPStore

__all__ = ["init_rpc", "shutdown", "rpc_sync", "rpc_async",
           "get_worker_info", "get_all_worker_infos", "WorkerInfo"]


@dataclass(frozen=True)
class WorkerInfo:
    """Reference: rpc.py WorkerInfo(name, rank, ip, port)."""

    name: str
    rank: int
    ip: str
    port: int


class _RpcState:
    def __init__(self):
        self.server: Optional["_Server"] = None
        self.store: Optional[TCPStore] = None
        self.workers: Dict[str, WorkerInfo] = {}
        self.by_rank: Dict[int, WorkerInfo] = {}
        self.self_info: Optional[WorkerInfo] = None
        self.pool = _futures.ThreadPoolExecutor(max_workers=8)


_state: Optional[_RpcState] = None


def _recv_exact(conn, n: int) -> bytes:
    buf = b""
    while len(buf) < n:
        chunk = conn.recv(n - len(buf))
        if not chunk:
            raise ConnectionError("rpc peer closed")
        buf += chunk
    return buf


def _send_frame(conn, payload: bytes) -> None:
    conn.sendall(struct.pack("<Q", len(payload)) + payload)


def _recv_frame(conn) -> bytes:
    (n,) = struct.unpack("<Q", _recv_exact(conn, 8))
    return _recv_exact(conn, n)


class _Server:
    """Per-worker request loop: unpickle (fn, args, kwargs), run, reply
    (ok, result) or (err, exception)."""

    def __init__(self, port: int = 0):
        self._sock = socket.socket(socket.AF_INET, socket.SOCK_STREAM)
        self._sock.setsockopt(socket.SOL_SOCKET, socket.SO_REUSEADDR, 1)
        self._sock.bind(("0.0.0.0", port))
        self.port = self._sock.getsockname()[1]
        self._sock.listen(64)
        self._stop = False
        self._thread = threading.Thread(target=self._accept, daemon=True,
                                        name="rpc-server")
        self._thread.start()

    def _accept(self):
        while not self._stop:
            try:
                conn, _ = self._sock.accept()
            except OSError:
                return
            threading.Thread(target=self._serve, args=(conn,),
                             daemon=True).start()

    def _serve(self, conn):
        try:
            while True:
                fn, args, kwargs = pickle.loads(_recv_frame(conn))
                try:
                    result = fn(*args, **(kwargs or {}))
                    _send_frame(conn, pickle.dumps((True, result)))
                except Exception as e:  # travels back to the caller
                    _send_frame(conn, pickle.dumps((False, e)))
        except (ConnectionError, OSError):
            pass
        finally:
            try:
                conn.close()
            except OSError:
                pass

    def stop(self):
        self._stop = True
        try:
            self._sock.close()
        except OSError:
            pass


def _require_state() -> _RpcState:
    if _state is None:
        raise RuntimeError("call paddle.distributed.rpc.init_rpc first")
    return _state


def init_rpc(name: str, rank: Optional[int] = None,
             world_size: Optional[int] = None,
             master_endpoint: Optional[str] = None) -> None:
    """Start this worker's RPC service and exchange WorkerInfos.

    Reference: rpc.py:85 — master_endpoint hosts the barrier store;
    every worker publishes name:ip:port and blocks until all
    `world_size` peers are registered.
    """
    global _state
    if _state is not None:
        raise RuntimeError("rpc already initialized")
    rank = int(os.environ.get("PADDLE_TRAINER_ID", 0)) if rank is None \
        else rank
    world_size = int(os.environ.get("PADDLE_TRAINERS_NUM", 1)) \
        if world_size is None else world_size
    master_endpoint = master_endpoint or os.environ.get(
        "PADDLE_MASTER_ENDPOINT", "127.0.0.1:29850")
    host, port = master_endpoint.rsplit(":", 1)

    st = _RpcState()
    st.server = _Server()
    if rank == 0:
        try:
            store = TCPStore(host, int(port), is_master=True,
                             world_size=world_size)
        except OSError:  # master already running (tests, relaunch)
            store = TCPStore(host, int(port), is_master=False,
                             world_size=world_size)
    else:
        store = TCPStore(host, int(port), is_master=False,
                         world_size=world_size)
    st.store = store
    ip = "127.0.0.1" if host in ("127.0.0.1", "localhost") \
        else socket.gethostbyname(socket.gethostname())
    st.self_info = WorkerInfo(name, rank, ip, st.server.port)
    store.set(f"rpc/worker/{rank}",
              f"{name}|{ip}|{st.server.port}".encode())
    # info exchange (reference _exchange_all_service_infos)
    for r in range(world_size):
        store.wait(f"rpc/worker/{r}", timeout=300.0)
        wname, wip, wport = store.get(f"rpc/worker/{r}").decode().split("|")
        info = WorkerInfo(wname, r, wip, int(wport))
        st.workers[wname] = info
        st.by_rank[r] = info
    _state = st


def get_worker_info(name: str) -> WorkerInfo:
    st = _require_state()
    if name not in st.workers:
        raise ValueError(f"unknown rpc worker {name!r}; "
                         f"known: {sorted(st.workers)}")
    return st.workers[name]


def get_all_worker_infos() -> List[WorkerInfo]:
    st = _require_state()
    return [st.by_rank[r] for r in sorted(st.by_rank)]


class _Conn:
    """One pooled connection per target worker (thread-locked frames)."""

    _conns: Dict[Tuple[str, int], "_Conn"] = {}
    _lock = threading.Lock()

    def __init__(self, ip, port, timeout):
        self.sock = socket.create_connection((ip, port), timeout=timeout)
        self.sock.setsockopt(socket.IPPROTO_TCP, socket.TCP_NODELAY, 1)
        self.lock = threading.Lock()

    @classmethod
    def to(cls, info: WorkerInfo, timeout: float) -> "_Conn":
        key = (info.ip, info.port)
        with cls._lock:
            c = cls._conns.get(key)
            if c is None:
                c = cls(info.ip, info.port, timeout)
                cls._conns[key] = c
            return c

    @classmethod
    def reset(cls):
        with cls._lock:
            for c in cls._conns.values():
                try:
                    c.sock.close()
                except OSError:
                    pass
            cls._conns.clear()


def _invoke(to: str, fn, args, kwargs, timeout: float):
    info = get_worker_info(to)
    conn = _Conn.to(info, timeout)
    payload = pickle.dumps((fn, tuple(args or ()), dict(kwargs or {})))
    with conn.lock:
        conn.sock.settimeout(timeout if timeout > 0 else None)
        _send_frame(conn.sock, payload)
        ok, result = pickle.loads(_recv_frame(conn.sock))
    if not ok:
        raise result
    return result


def rpc_sync(to: str, fn, args=None, kwargs=None,
             timeout: float = 180.0):
    """Blocking call on worker `to` (reference: rpc.py:160)."""
    return _invoke(to, fn, args, kwargs, timeout)


def rpc_async(to: str, fn, args=None, kwargs=None,
              timeout: float = 180.0):
    """Non-blocking call; returns a future with .wait() (reference:
    rpc.py:206 returns a FutureWrapper)."""
    st = _require_state()
    fut = st.pool.submit(_invoke, to, fn, args, kwargs, timeout)
    fut.wait = fut.result  # paddle-style spelling
    return fut


def shutdown() -> None:
    """Barrier, then stop the local service (reference: rpc.py shutdown
    with _barrier_never_timeout so no worker exits early)."""
    global _state
    st = _state
    if st is None:
        return
    try:
        st.store.barrier("rpc/shutdown", timeout=300.0)
    except Exception:
        pass
    _Conn.reset()
    st.server.stop()
    st.pool.shutdown(wait=False)
    try:
        st.store.stop()
    except Exception:
        pass
    _state = None

"""paddle.distributed.communication parity package.

Reference: python/paddle/distributed/communication/ — the op-wrapper layer
(all_reduce/all_gather/…) plus the low-level `stream` variants. Here the
top-level wrappers already live in `paddle_tpu.distributed.collective`;
this package re-exports them under the reference's module path and adds
the `stream` namespace.
"""
from ..collective import (  # noqa: F401
    P2POp,
    ReduceOp,
    all_gather,
    all_gather_object,
    all_reduce,
    alltoall,
    alltoall_single,
    barrier,
    batch_isend_irecv,
    broadcast,
    broadcast_object_list,
    irecv,
    isend,
    recv,
    reduce,
    reduce_scatter,
    scatter,
    send,
)
from . import stream  # noqa: E402,F401

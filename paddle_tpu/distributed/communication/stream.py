"""Low-level `paddle.distributed.stream` collective variants.

Reference: python/paddle/distributed/communication/stream/*.py — the same
collectives as the top-level API plus `sync_op` / `use_calc_stream` knobs
controlling whether the op runs on the communication stream and whether
the caller waits.

TPU-native meaning: PJRT has no user-visible stream split — dispatch is
always async and ordering is program order, so `use_calc_stream=True`
(reference semantics: run inline on the compute stream, no Task) maps to
"wait for the result before returning" and `sync_op` keeps its usual
meaning. Every function returns the Task handle (or None when
use_calc_stream=True, matching the reference's contract that inline ops
yield no task).
"""
from __future__ import annotations

from .. import collective as C

__all__ = ["all_reduce", "all_gather", "reduce_scatter", "alltoall",
           "alltoall_single", "broadcast", "reduce", "scatter", "send",
           "recv"]


def _finish(task, sync_op: bool, use_calc_stream: bool):
    # In traced (inside-jit) mode the collectives return the result array
    # rather than a Task — pass it through untouched.
    waitable = hasattr(task, "wait")
    if use_calc_stream:
        if waitable:
            task.wait()
            return None
        return task
    if sync_op and waitable:
        task.wait()
    return task


def all_reduce(tensor, op=C.ReduceOp.SUM, group=None, sync_op=True,
               use_calc_stream=False):
    return _finish(C.all_reduce(tensor, op=op, group=group, sync_op=False),
                   sync_op, use_calc_stream)


def all_gather(tensor_or_tensor_list, tensor, group=None, sync_op=True,
               use_calc_stream=False):
    if isinstance(tensor_or_tensor_list, list):
        task = C.all_gather(tensor_or_tensor_list, tensor, group=group,
                            sync_op=False)
    else:
        task = C.all_gather_into_tensor(tensor_or_tensor_list, tensor,
                                        group=group, sync_op=False)
    return _finish(task, sync_op, use_calc_stream)


def reduce_scatter(tensor, tensor_or_tensor_list, op=C.ReduceOp.SUM,
                   group=None, sync_op=True, use_calc_stream=False):
    task = C.reduce_scatter(tensor, tensor_or_tensor_list, op=op,
                            group=group, sync_op=False)
    return _finish(task, sync_op, use_calc_stream)


def alltoall(out_tensor_list, in_tensor_list, group=None, sync_op=True,
             use_calc_stream=False):
    task = C.alltoall(out_tensor_list, in_tensor_list, group=group,
                      sync_op=False)
    return _finish(task, sync_op, use_calc_stream)


def alltoall_single(out_tensor, in_tensor, in_split_sizes=None,
                    out_split_sizes=None, group=None, sync_op=True,
                    use_calc_stream=False):
    task = C.alltoall_single(out_tensor, in_tensor,
                             in_split_sizes=in_split_sizes,
                             out_split_sizes=out_split_sizes, group=group,
                             sync_op=False)
    return _finish(task, sync_op, use_calc_stream)


def broadcast(tensor, src=0, group=None, sync_op=True,
              use_calc_stream=False):
    return _finish(C.broadcast(tensor, src=src, group=group, sync_op=False),
                   sync_op, use_calc_stream)


def reduce(tensor, dst=0, op=C.ReduceOp.SUM, group=None, sync_op=True,
           use_calc_stream=False):
    return _finish(C.reduce(tensor, dst=dst, op=op, group=group,
                            sync_op=False),
                   sync_op, use_calc_stream)


def scatter(tensor, tensor_or_tensor_list=None, src=0, group=None,
            sync_op=True, use_calc_stream=False):
    task = C.scatter(tensor, tensor_list=tensor_or_tensor_list, src=src,
                     group=group, sync_op=False)
    return _finish(task, sync_op, use_calc_stream)


def send(tensor, dst=0, group=None, sync_op=True, use_calc_stream=False):
    return _finish(C.send(tensor, dst=dst, group=group, sync_op=False),
                   sync_op, use_calc_stream)


def recv(tensor, src=0, group=None, sync_op=True, use_calc_stream=False):
    return _finish(C.recv(tensor, src=src, group=group, sync_op=False),
                   sync_op, use_calc_stream)

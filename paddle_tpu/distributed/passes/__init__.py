"""paddle.distributed.passes parity (reference:
python/paddle/distributed/passes/__init__.py — new_pass / PassManager /
PassContext over a registry of distributed optimization passes).

TPU-native: the reference's pass zoo (fp16/amp rewrite, recompute,
gradient-merge, fuse-allreduce, pipeline schedulers, sharding...) maps to
capabilities XLA/GSPMD or this framework's runtime already own — amp is
the autocast policy, recompute is `jax.checkpoint`, fused grad sync is
the Reducer, pipeline scheduling lives in fleet/meta_parallel. The pass
OBJECTS here carry the reference's registry/apply contract so strategy
code that builds pass pipelines keeps working: each known pass name
resolves, `apply` records itself on the program/context (and performs the
mapped action where one exists at program scope).
"""
from __future__ import annotations

from typing import Any, Dict, List, Optional

__all__ = ["new_pass", "PassManager", "PassContext"]


class PassContext:
    """Reference: PassContext — carries cross-pass state and collects
    which passes were applied."""

    def __init__(self):
        self._applied: List["PassBase"] = []
        self.attrs: Dict[str, Any] = {}

    @property
    def passes(self):
        return list(self._applied)

    def set_attr(self, key, value):
        self.attrs[key] = value

    def get_attr(self, key, default=None):
        return self.attrs.get(key, default)


class PassBase:
    name = "base"

    def __init__(self, attrs: Optional[dict] = None):
        self.attrs = dict(attrs or {})

    def check_before_apply(self, main_program, startup_program):
        return True

    def apply(self, main_programs, startup_programs=None, context=None):
        """Record the application; subclasses hook _apply_impl for the
        mapped TPU-native action. `check_before_apply` gates application
        per the reference contract — a False verdict skips the pass."""
        context = context or PassContext()
        programs = (main_programs if isinstance(main_programs, (list, tuple))
                    else [main_programs])
        starts = (startup_programs
                  if isinstance(startup_programs, (list, tuple))
                  else [startup_programs] * len(programs))
        for prog, start in zip(programs, starts):
            if not self.check_before_apply(prog, start):
                continue
            self._apply_impl(prog, context)
        context._applied.append(self)
        return context

    def _apply_impl(self, program, context):
        applied = getattr(program, "_applied_passes", None)
        if applied is None:
            try:
                program._applied_passes = [self.name]
            except AttributeError:
                pass
        else:
            applied.append(self.name)


class _MappedPass(PassBase):
    """A reference pass whose capability this stack provides elsewhere;
    `mapped_to` documents where (surfaced via repr for debuggability)."""

    mapped_to = ""

    def __repr__(self):
        return (f"<pass {self.name!r} (TPU-native: {self.mapped_to})"
                f" attrs={self.attrs}>")


def _mapped(name, mapped_to):
    return type(f"Pass_{name}", (_MappedPass,),
                {"name": name, "mapped_to": mapped_to})


# the reference's registered pass names (python/paddle/distributed/passes/
# *.py + pipeline_scheduler_pass/*.py @register_pass ids — the COMPLETE
# id set) → where the capability lives here
_MAPPINGS = {
    # auto-parallel family
    "auto_parallel_amp": "amp.auto_cast policy on the compiled step",
    "auto_parallel_fp16": "bf16-first autocast (fp16 path available)",
    "auto_parallel_bf16": "bf16 autocast lists",
    "auto_parallel_recompute": "jax.checkpoint remat in the step fn",
    "auto_parallel_sharding": "GSPMD shardings via auto_parallel.api",
    "auto_parallel_grad_clip": "hybrid-aware global-norm clip in the "
                               "optimizer update",
    "auto_parallel_gradient_merge_pass": "num_microbatches grad "
                                         "accumulation in make_train_step",
    "auto_parallel_data_parallel_optimization": "bucketed fused grad sync "
                                                "(Reducer analog)",
    "auto_parallel_pipeline": "fleet/meta_parallel pp schedules",
    "auto_parallel_master_grad_pass": "f32 master grads in the bf16 step",
    "auto_parallel_fused_linear_promotion": "XLA epilogue fusion",
    "auto_parallel_quantization": "quantization QAT/PTQ passes",
    "auto_parallel_c_embedding_pass": "VocabParallelEmbedding",
    "auto_parallel_sequence_parallel_optimization":
        "fleet/utils/sequence_parallel_utils.py",
    "auto_parallel_supplement_explicit_dependencies":
        "XLA dataflow ordering (no explicit deps needed)",
    "allreduce_matmul_grad_overlapping": "XLA latency-hiding scheduler",
    "replace_with_parallel_cross_entropy": "mpu ParallelCrossEntropy",
    # fusion family → XLA fusion or existing fused kernels
    "fuse_adamw": "one fused optimizer update in the jitted step",
    "fuse_all_reduce": "bucketed fused grad sync in DataParallel",
    "fuse_bn_act": "XLA elementwise fusion",
    "fuse_bn_add_act": "XLA elementwise fusion",
    "fuse_dot_product_attention": "flash attention kernels",
    "fuse_elewise_add_act": "XLA elementwise fusion",
    "fuse_gemm_epilogue": "XLA epilogue fusion (fused_linear)",
    "fuse_optimizer": "one fused optimizer update in the jitted step",
    "fuse_relu_depthwise_conv": "XLA fusion",
    "fuse_resunit": "fused_scale_bias_relu_conv_bn kernel family",
    "fused_attention": "incubate fused_attention",
    "fused_feedforward": "incubate fused_feedforward",
    "inplace_addto_op": "XLA buffer donation/aliasing",
    "build_cinn": "XLA is the graph compiler (no CINN stage)",
    # parameter-server transpiler family → distributed/ps runtime
    "add_geo_optimizer_pass": "distributed/ps server-side optimizers",
    "add_listen_and_serv_pass": "out-of-process PS server loop",
    "add_lr_decay_table_pass": "PS dense table LR state",
    "add_optimizer_pass": "PS server-side optimizers",
    "add_rpc_global_flags_pass": "distributed/rpc runtime",
    "append_send_ops_pass": "PS client push path",
    "build_pserver_startup_program_pass": "PS server bootstrap",
    "delete_extra_optimizer_pass": "PS program split",
    "delete_optimizer_pass": "PS program split",
    "delete_unused_in_startup_pass": "PS program split",
    "distributed_ops_pass": "PS lookup/push op routing",
    "fake_init_ops_pass": "PS sparse-table remote init",
    "ps_gpu_pass": "PS runtime (single accelerator class here)",
    "ps_transpile_pass": "PS program transpilation",
    "set_heter_pipeline_opt_pass": "PS heter mode (out of scope note)",
    "split_fl_ops_pass": "PS federated split",
    "split_heter_worker_ops_pass": "PS heter split",
    "split_trainer_ops_pass": "PS trainer split",
    # pipeline schedulers
    "pipeline_scheduler_FThenB": "fleet/meta_parallel/pp_schedule.py",
    "pipeline_scheduler_1F1B": "fleet/meta_parallel/pp_schedule.py",
    "pipeline_scheduler_Eager1F1B": "1F1B schedule (eager warmup variant)",
    "pipeline_scheduler_VPP": "interleaved schedule in pp_schedule.py",
    "pipeline_scheduler_ZBH1": "zero-bubble schedule in pp_schedule.py",
    "pipeline_scheduler_ZBVPP": "zero-bubble + interleaved composition",
}

_PASS_REGISTRY = {name: _mapped(name, target)
                  for name, target in _MAPPINGS.items()}


def new_pass(name, pass_attrs=None):
    """Reference: passes/pass_base.py new_pass — instantiate a registered
    pass by name."""
    cls = _PASS_REGISTRY.get(name)
    if cls is None:
        raise ValueError(
            f"unknown pass {name!r}; known: {sorted(_PASS_REGISTRY)}")
    return cls(pass_attrs)


class PassManager:
    """Reference: passes/pass_base.py PassManager — applies a pass list
    in order under one context."""

    def __init__(self, passes):
        self._passes = list(passes)
        self._context = PassContext()

    @property
    def context(self):
        return self._context

    @property
    def names(self):
        return [p.name for p in self._passes]

    def apply(self, main_programs, startup_programs=None):
        for p in self._passes:
            self._context = p.apply(main_programs, startup_programs,
                                    self._context)
        return self._context

"""ProcessMesh: the logical N-D device topology for auto-parallel.

Reference: `ProcessMesh` (paddle/phi/core/distributed/auto_parallel/
process_mesh.h:34; python surface python/paddle/distributed/auto_parallel/
process_mesh.py) — an N-D array of process ranks with named dims.

TPU-native: the mesh compiles to a `jax.sharding.Mesh` over the PJRT device
list; mesh dim names double as the collective axis names used by shard_map
and by the fleet hybrid topology ('dp'/'mp'/'pp'/...).
"""
from __future__ import annotations

import threading
from typing import List, Optional, Sequence

import numpy as np

import jax
from jax.sharding import Mesh

_lock = threading.RLock()
_global_mesh: Optional["ProcessMesh"] = None


class ProcessMesh:
    def __init__(self, mesh=None, dim_names: Optional[Sequence[str]] = None,
                 shape: Optional[Sequence[int]] = None,
                 process_ids: Optional[Sequence[int]] = None):
        if isinstance(mesh, ProcessMesh):
            self._mesh = mesh._mesh.copy()
            dim_names = dim_names or mesh._dim_names
        elif mesh is None:
            if process_ids is None:
                raise ValueError("either mesh or process_ids is required")
            self._mesh = np.asarray(process_ids, dtype=np.int64)
            if shape is not None:
                self._mesh = self._mesh.reshape(shape)
        else:
            self._mesh = np.asarray(mesh, dtype=np.int64)
            if process_ids is not None and sorted(process_ids) != sorted(
                    int(x) for x in self._mesh.flatten()):
                raise ValueError(
                    f"process_ids {process_ids} inconsistent with mesh "
                    f"{self._mesh.flatten().tolist()}")
        if self._mesh.ndim == 0:
            self._mesh = self._mesh.reshape(1)
        if dim_names is None:
            dim_names = [f"d{i}" for i in range(self._mesh.ndim)]
        if len(dim_names) != self._mesh.ndim:
            raise ValueError(
                f"dim_names {dim_names} rank != mesh ndim {self._mesh.ndim}")
        if len(set(dim_names)) != len(dim_names):
            raise ValueError(f"duplicate dim names: {dim_names}")
        self._dim_names = list(dim_names)
        self._jax_mesh: Optional[Mesh] = None

    # -- paddle.distributed.ProcessMesh surface ---------------------------
    @property
    def mesh(self) -> np.ndarray:
        return self._mesh

    @property
    def shape(self) -> List[int]:
        return list(self._mesh.shape)

    @property
    def ndim(self) -> int:
        return self._mesh.ndim

    @property
    def dim_names(self) -> List[str]:
        return list(self._dim_names)

    @property
    def process_ids(self) -> List[int]:
        return [int(x) for x in self._mesh.flatten()]

    @property
    def size(self) -> int:
        return int(self._mesh.size)

    def get_dim_size(self, dim_name: str) -> int:
        return self._mesh.shape[self._dim_names.index(dim_name)]

    def get_rank_by_dim_and_process_id(self, dim_name: str, process_id: int) -> int:
        idx = np.argwhere(self._mesh == process_id)
        if idx.size == 0:
            return -1
        return int(idx[0][self._dim_names.index(dim_name)])

    def get_submesh_with_dim(self, dim_name: str) -> "ProcessMesh":
        """The 1-D sub-mesh along `dim_name` containing the current rank."""
        from ..env import get_rank

        axis = self._dim_names.index(dim_name)
        r = get_rank()
        idx = np.argwhere(self._mesh == r)
        coord = list(idx[0]) if idx.size else [0] * self._mesh.ndim
        slicer = tuple(slice(None) if i == axis else coord[i]
                       for i in range(self._mesh.ndim))
        return ProcessMesh(self._mesh[slicer], [dim_name])

    def get_group(self, dim_name: Optional[str] = None):
        """Communication Group over this mesh (or a 1-D sub-mesh axis)."""
        from ..collective import new_group

        if dim_name is None:
            if self._mesh.ndim != 1:
                raise ValueError("dim_name required for an N-D mesh")
            sub = self
            dim_name = self._dim_names[0]
        else:
            sub = self.get_submesh_with_dim(dim_name)
        devs = _devices_for(sub.process_ids)
        return new_group(sub.process_ids, axis_name=dim_name, devices=devs)

    # -- TPU-native -------------------------------------------------------
    @property
    def jax_mesh(self) -> Mesh:
        """Compile to a jax Mesh (device objects in process-id order)."""
        if self._jax_mesh is None:
            devs = _devices_for(self.process_ids)
            arr = np.asarray(devs).reshape(self._mesh.shape)
            self._jax_mesh = Mesh(arr, tuple(self._dim_names))
        return self._jax_mesh

    def __eq__(self, other):
        return (isinstance(other, ProcessMesh)
                and np.array_equal(self._mesh, other._mesh)
                and self._dim_names == other._dim_names)

    def __hash__(self):
        return hash((self._mesh.tobytes(), tuple(self._dim_names)))

    def __repr__(self):
        return (f"ProcessMesh(shape={self.shape}, dim_names={self._dim_names},"
                f" process_ids={self.process_ids})")


def _devices_for(process_ids: Sequence[int]):
    """Map logical process ids to PJRT devices. A jax Mesh must hold distinct
    devices, so an over-subscribed mesh is a hard error (tests use
    --xla_force_host_platform_device_count to widen the virtual device set)."""
    devs = jax.devices()
    if max(process_ids, default=-1) >= len(devs):
        raise ValueError(
            f"ProcessMesh needs process ids {sorted(set(process_ids))} but only "
            f"{len(devs)} devices are visible; set "
            f"XLA_FLAGS=--xla_force_host_platform_device_count=N for CPU tests")
    return [devs[i] for i in process_ids]


def get_mesh() -> Optional[ProcessMesh]:
    """The global mesh set by `set_mesh` (reference: auto_parallel/api.py)."""
    return _global_mesh


def set_mesh(mesh: ProcessMesh):
    global _global_mesh
    with _lock:
        if not isinstance(mesh, ProcessMesh):
            mesh = ProcessMesh(mesh)
        _global_mesh = mesh
    return _global_mesh

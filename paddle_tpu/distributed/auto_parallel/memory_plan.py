"""AOT HBM planning: XLA-measured per-device memory for a parallel config.

VERDICT r4 Next #5 — BASELINE config 4 ("LLaMA-7B/13B, TP+PP hybrid") had
never been exercised at full parameter count anywhere: the dryruns use toy
shapes. `jax.jit(...).lower(...).compile().memory_analysis()` proves what
fits WITHOUT hardware: parameters never materialize (abstract
ShapeDtypeStructs with NamedShardings), yet XLA runs real SPMD
partitioning + buffer assignment and reports per-device bytes.

Reference analog: the auto-parallel memory estimation in
`python/paddle/distributed/auto_parallel/static/cost/estimate_cost.py`
(analytic) — here the ground truth comes from the compiler itself, and
tests/test_memory_plan.py cross-checks the analytic CostModel
(engine.py:131) against it so the Planner can never bless a config XLA
says OOMs.
"""
from __future__ import annotations

import dataclasses
from typing import Optional

import jax
import jax.numpy as jnp
from jax.sharding import NamedSharding, PartitionSpec as P

V5E_HBM = 16e9
V5P_HBM = 95e9


@dataclasses.dataclass(frozen=True)
class MemoryPlan:
    """Per-device bytes for one (dp, pp, tp) config of the flagship step.

    `state_bytes` = the step's arguments (params + AdamW m/v + inputs) —
    the resident state between steps. `temp_bytes` = XLA's transient
    buffers (grads, bf16 param copies, remat'd activations).
    `required_bytes` = args + temps + un-aliased outputs: the conservative
    per-device HBM requirement (donation aliases outputs onto arguments).
    """

    dp: int
    pp: int
    tp: int
    micro_batches: int
    state_bytes: int
    temp_bytes: int
    output_bytes: int
    alias_bytes: int

    @property
    def required_bytes(self) -> int:
        return (self.state_bytes + self.temp_bytes
                + self.output_bytes - self.alias_bytes)

    def fits(self, hbm_bytes: float) -> bool:
        return self.required_bytes <= hbm_bytes


def aot_memory_plan(cfg, dp: int, pp: int, tp: int,
                    num_microbatches: int = 1,
                    batch_per_dp: Optional[int] = None,
                    remat=True, attn_impl: str = "xla") -> MemoryPlan:
    """Compile the FULL flagship train step at cfg's real parameter count
    on an abstract (dp, pp, tp) mesh and read XLA's buffer assignment.

    No parameter memory is allocated: inputs are ShapeDtypeStructs. Works
    on any backend with >= dp*pp*tp devices (the 8-virtual-CPU mesh in
    tests); compile is seconds because the per-layer scan keeps the
    program size independent of depth.
    """
    from ...models import llama as L
    from .. import hybrid as H

    mesh = H.build_mesh(dp=dp, pp=pp, tp=tp)
    step = H.make_train_step(cfg, mesh, num_microbatches=num_microbatches,
                             remat=remat, attn_impl=attn_impl)
    shapes = jax.eval_shape(
        lambda: H.stack_pipeline(L.init_params(cfg, jax.random.PRNGKey(0)),
                                 pp))
    specs = H.param_specs(cfg)

    def sds(s, sp, dt=None):
        return jax.ShapeDtypeStruct(s.shape, dt or s.dtype,
                                    sharding=NamedSharding(mesh, sp))

    ap = jax.tree.map(sds, shapes, specs)
    f32 = lambda s, sp: sds(s, sp, jnp.float32)
    aopt = {"m": jax.tree.map(f32, shapes, specs),
            "v": jax.tree.map(f32, shapes, specs),
            "step": jax.ShapeDtypeStruct((), jnp.int32,
                                         sharding=NamedSharding(mesh, P()))}
    B = dp * (batch_per_dp or num_microbatches)
    tok = jax.ShapeDtypeStruct((B, cfg.max_seq_len), jnp.int32,
                               sharding=NamedSharding(mesh, P("dp", "cp")))
    ma = step.lower(ap, aopt, tok, tok).compile().memory_analysis()
    return MemoryPlan(dp=dp, pp=pp, tp=tp, micro_batches=num_microbatches,
                      state_bytes=ma.argument_size_in_bytes,
                      temp_bytes=ma.temp_size_in_bytes,
                      output_bytes=ma.output_size_in_bytes,
                      alias_bytes=ma.alias_size_in_bytes)

"""Tensor placements for auto-parallel (DistTensor) semantics.

Reference: paddle's `Placement` hierarchy used by `shard_tensor`
(python/paddle/distributed/auto_parallel/api.py:220) and the C++
`TensorDistAttr` (paddle/phi/core/distributed/auto_parallel/dist_attr.h:81):
`dims_mapping` + `partial_status` describe, per *mesh* dimension, whether the
tensor is sharded along it (and on which tensor dim), replicated, or holds
partial (pending-reduce) values.

TPU-native mapping: a placements list is compiled to a
`jax.sharding.PartitionSpec` — `Shard(d)` on mesh dim i puts that mesh axis
name into spec entry d; `Replicate` contributes nothing; `Partial` is carried
as metadata (XLA's GSPMD resolves partial sums at op boundaries, so an eager
global `jax.Array` never stores un-reduced state — the flag exists for API
parity and for sharding-hint propagation).
"""
from __future__ import annotations

from typing import List, Sequence, Tuple

from jax.sharding import PartitionSpec


class Placement:
    def is_shard(self, dim=None) -> bool:
        return False

    def is_replicated(self) -> bool:
        return False

    def is_partial(self) -> bool:
        return False


class Replicate(Placement):
    def is_replicated(self) -> bool:
        return True

    def __eq__(self, other):
        return isinstance(other, Replicate)

    def __hash__(self):
        return hash("Replicate")

    def __repr__(self):
        return "Replicate()"


class Shard(Placement):
    def __init__(self, dim: int):
        self.dim = int(dim)

    def get_dim(self) -> int:
        return self.dim

    def is_shard(self, dim=None) -> bool:
        return dim is None or dim == self.dim

    def __eq__(self, other):
        return isinstance(other, Shard) and other.dim == self.dim

    def __hash__(self):
        return hash(("Shard", self.dim))

    def __repr__(self):
        return f"Shard(dim={self.dim})"


class Partial(Placement):
    """Pending-reduce placement (reference: ReduceType in dist_attr.h)."""

    def __init__(self, reduce_type: str = "sum"):
        self.reduce_type = reduce_type

    def is_partial(self) -> bool:
        return True

    def __eq__(self, other):
        return isinstance(other, Partial) and other.reduce_type == self.reduce_type

    def __hash__(self):
        return hash(("Partial", self.reduce_type))

    def __repr__(self):
        return f"Partial(reduce_type={self.reduce_type!r})"


def placements_to_spec(placements: Sequence[Placement], dim_names: Sequence[str],
                       ndim: int) -> Tuple[PartitionSpec, Tuple[str, ...]]:
    """Compile a per-mesh-dim placements list into (PartitionSpec, partial_axes).

    Multiple mesh dims sharding the same tensor dim become a tuple entry
    (mesh-dim order), matching GSPMD's multi-axis sharding.
    """
    if len(placements) != len(dim_names):
        raise ValueError(
            f"placements length {len(placements)} != mesh ndim {len(dim_names)}")
    per_dim: List[List[str]] = [[] for _ in range(ndim)]
    partial_axes: List[str] = []
    for mesh_dim, p in enumerate(placements):
        if isinstance(p, Shard):
            d = p.dim if p.dim >= 0 else p.dim + ndim
            if not (0 <= d < ndim):
                raise ValueError(f"Shard dim {p.dim} out of range for ndim {ndim}")
            per_dim[d].append(dim_names[mesh_dim])
        elif isinstance(p, Partial):
            partial_axes.append(dim_names[mesh_dim])
        elif not isinstance(p, (Replicate, type(None))):
            raise TypeError(f"unknown placement {p!r}")
    entries = []
    for names in per_dim:
        if not names:
            entries.append(None)
        elif len(names) == 1:
            entries.append(names[0])
        else:
            entries.append(tuple(names))
    while entries and entries[-1] is None:
        entries.pop()
    return PartitionSpec(*entries), tuple(partial_axes)


def dim0_shardable(shape, nranks: int) -> bool:
    """The shared ZeRO layout rule: a state/param/grad is laid out Shard(0)
    over the sharding axis iff dim 0 divides the axis size (else replicated).
    Single source of truth for the stage1/2/3 plans here and the
    GroupSharded wrappers (distributed/sharding/group_sharded.py)."""
    return bool(shape) and shape[0] % nranks == 0


def spec_to_placements(spec: PartitionSpec, dim_names: Sequence[str],
                       partial_axes: Sequence[str] = ()) -> List[Placement]:
    """Inverse of placements_to_spec (lossy only for exotic specs)."""
    placements: List[Placement] = [Replicate() for _ in dim_names]
    name_to_mesh_dim = {n: i for i, n in enumerate(dim_names)}
    for tensor_dim, entry in enumerate(spec):
        if entry is None:
            continue
        names = entry if isinstance(entry, (tuple, list)) else (entry,)
        for n in names:
            if n in name_to_mesh_dim:
                placements[name_to_mesh_dim[n]] = Shard(tensor_dim)
    for n in partial_axes:
        if n in name_to_mesh_dim:
            placements[name_to_mesh_dim[n]] = Partial()
    return placements

"""Static auto-parallel Engine + cost model.

Reference: python/paddle/distributed/auto_parallel/static/engine.py:98
(Engine: build serial program -> plan -> parallelize -> run with an
executor over a Cluster) and static/cost/ (CostEstimator: per-op compute
costs + comm op costs + memory estimation, estimate_cost.py:26).

TPU-native redesign: the reference's planner rewrites a serial program
into a distributed one by inserting reshard/comm ops pass-by-pass. On TPU
the partitioner already exists — GSPMD. So the Engine here:

1. functionalises the Layer (params become pjit inputs),
2. asks the :class:`Planner` for a mesh layout — candidates are scored by
   the analytic :class:`CostModel` (MXU compute time + ring-allreduce DP
   grad sync + TP collective volume + pipeline bubble + HBM fit, the
   scaling-book recipe),
3. jits ONE train/eval/predict step with `in_shardings` derived from the
   chosen plan and lets XLA insert the collectives,
4. drives fit/evaluate/predict loops over it.

Generic Layers parallelise with data parallelism + ZeRO-style parameter
sharding (GSPMD shards any divisible leading axis); tensor/pipeline axes
in the plan are consumed by the flagship hybrid engine
(`distributed/hybrid.py`), which accepts the same PlanItem.
"""
from __future__ import annotations

import dataclasses
import itertools
import math
import time
from typing import Any, Callable, Dict, Iterable, List, Optional, Sequence

import jax
import jax.numpy as jnp
import numpy as np
from jax.sharding import Mesh, NamedSharding, PartitionSpec as P

from ...core.tensor import Tensor
from ...nn.layer.layers import Layer


# -- cluster description ------------------------------------------------------


@dataclasses.dataclass
class Cluster:
    """Device topology for planning (reference:
    auto_parallel/static/cluster.py Cluster — machines/devices/links).

    Bandwidths are aggregate per-chip; defaults are v5e-class ICI and a
    typical DCN share. `peak_flops` is bf16."""

    n_devices: int = 0
    devices_per_host: int = 0
    peak_flops: float = 197e12
    hbm_bytes: float = 16e9
    ici_bw: float = 1.6e11     # bytes/s per chip, intra-pod
    dcn_bw: float = 2.5e10     # bytes/s per chip, cross-pod
    mfu: float = 0.4           # achievable fraction of peak for matmul work

    @classmethod
    def auto(cls) -> "Cluster":
        devs = jax.devices()
        n = len(devs)
        local = len([d for d in devs if d.process_index == 0]) or n
        kind = (getattr(devs[0], "device_kind", "") or "").lower()
        peak = 197e12
        if "v6" in kind:
            peak = 918e12
        elif "v5p" in kind:
            peak = 459e12
        elif "v4" in kind:
            peak = 275e12
        elif "cpu" in kind or devs[0].platform == "cpu":
            peak = 1e12
        return cls(n_devices=n, devices_per_host=local, peak_flops=peak)


class Strategy:
    """Reference: auto_parallel/strategy.py:191 — nested config sections.
    Subset: the knobs the TPU planner actually consumes."""

    def __init__(self):
        self.auto_mode = "semi"          # "semi" | "full"
        self.sharding_stage = 0          # 0 replicate, 3 shard params
        self.micro_batches = 1
        self.tensor_parallel_degree = 0  # 0 = let the planner choose
        self.pipeline_degree = 0
        self.data_parallel_degree = 0
        self.amp = False

    # paddle-style attribute sections tolerate unknown access
    def __getattr__(self, name):
        raise AttributeError(name)


# -- cost model ---------------------------------------------------------------


@dataclasses.dataclass
class PlanItem:
    dp: int
    tp: int
    pp: int
    micro_batches: int
    sharding_stage: int
    cost: "StepCost" = None

    @property
    def degree(self):
        return self.dp * self.tp * self.pp


@dataclasses.dataclass
class StepCost:
    compute_s: float
    dp_comm_s: float
    tp_comm_s: float
    pp_comm_s: float
    bubble_s: float
    memory_bytes: float
    fits: bool

    @property
    def total_s(self) -> float:
        return (self.compute_s + self.dp_comm_s + self.tp_comm_s
                + self.pp_comm_s + self.bubble_s)


class CostModel:
    """Analytic per-step cost (reference: static/cost/estimate_cost.py:26,
    but closed-form instead of per-op simulation — on TPU the per-op
    schedule is XLA's, so the model prices the INVARIANTS: total matmul
    FLOPs, grad-sync volume, TP collective volume, pipeline bubble, HBM).
    """

    def __init__(self, cluster: Cluster):
        self.cluster = cluster

    def estimate(self, *, flops_per_batch: float, param_bytes: float,
                 act_bytes_per_microbatch: float, plan: PlanItem,
                 n_layers: int = 1, optimizer_mult: float = 3.0) -> StepCost:
        c = self.cluster
        shards = plan.degree
        compute = flops_per_batch / (shards * c.peak_flops * c.mfu)

        # ring allreduce of grads over dp: 2·B·(dp-1)/dp at ICI speed
        grad_bytes = param_bytes / (plan.tp * plan.pp)
        dp_comm = (2.0 * grad_bytes * (plan.dp - 1) / max(plan.dp, 1)
                   / c.ici_bw) if plan.dp > 1 else 0.0

        # Megatron TP: ~4 collectives per layer over the activation bytes
        # of this stage's layers (allreduce fwd+bwd ≈ 2·V each direction)
        act_stage = act_bytes_per_microbatch / max(plan.pp, 1)
        tp_comm = (4.0 * act_stage * (plan.tp - 1) / max(plan.tp, 1)
                   / c.ici_bw * plan.micro_batches) if plan.tp > 1 else 0.0

        # PP: inter-stage activation p2p (fwd act + bwd cotangent per
        # boundary per microbatch) plus per-microbatch dispatch overhead —
        # without these, deep pipelines look free on small models
        if plan.pp > 1:
            m = max(plan.micro_batches, 1)
            boundary = act_bytes_per_microbatch / max(n_layers, 1)
            pp_comm = (2.0 * boundary * (plan.pp - 1) * m / c.ici_bw
                       + 20e-6 * m)
            # 1F1B bubble: (pp-1)/(m+pp-1) of the pipeline's busy time
            bubble = (compute + tp_comm) * (plan.pp - 1) / (m + plan.pp - 1)
        else:
            pp_comm = 0.0
            bubble = 0.0

        # HBM: params + optimizer states (+grads) per shard + activations
        zero_div = plan.dp if plan.sharding_stage == 3 else 1
        mem = (param_bytes * (1.0 + optimizer_mult) / (plan.tp * plan.pp *
                                                       zero_div)
               + param_bytes / (plan.tp * plan.pp)      # grads
               + act_bytes_per_microbatch / max(plan.tp, 1))
        return StepCost(compute, dp_comm, tp_comm, pp_comm, bubble, mem,
                        fits=mem <= c.hbm_bytes)


class Planner:
    """Enumerate mesh factorizations, score, pick (reference:
    static/planner_v2.py + tuner/parallel_tuner.py)."""

    def __init__(self, cluster: Cluster, cost_model: Optional[CostModel] = None):
        self.cluster = cluster
        self.cost_model = cost_model or CostModel(cluster)

    def candidates(self, strategy: Strategy) -> List[PlanItem]:
        n = self.cluster.n_devices
        out = []
        for tp in [t for t in (1, 2, 4, 8) if n % t == 0]:
            if strategy.tensor_parallel_degree and \
                    tp != strategy.tensor_parallel_degree:
                continue
            rem = n // tp
            for pp in [p for p in (1, 2, 4, 8) if rem % p == 0]:
                if strategy.pipeline_degree and pp != strategy.pipeline_degree:
                    continue
                dp = rem // pp
                if strategy.data_parallel_degree and \
                        dp != strategy.data_parallel_degree:
                    continue
                mb = max(strategy.micro_batches, pp)
                out.append(PlanItem(dp=dp, tp=tp, pp=pp, micro_batches=mb,
                                    sharding_stage=strategy.sharding_stage))
        return out

    def plan(self, strategy: Strategy, *, flops_per_batch: float,
             param_bytes: float, act_bytes_per_microbatch: float,
             n_layers: int = 1) -> PlanItem:
        best = None
        for cand in self.candidates(strategy):
            cand.cost = self.cost_model.estimate(
                flops_per_batch=flops_per_batch, param_bytes=param_bytes,
                act_bytes_per_microbatch=act_bytes_per_microbatch,
                plan=cand, n_layers=n_layers)
            key = (not cand.cost.fits, cand.cost.total_s)
            if best is None or key < (not best.cost.fits, best.cost.total_s):
                best = cand
        if best is None:
            raise RuntimeError("no mesh factorization fits the cluster")
        return best


# -- the engine ---------------------------------------------------------------


def _functional_update(opt) -> Callable:
    """Functional optimizer update from a paddle-style optimizer object
    (the compiled step cannot call the mutating .step())."""
    name = type(opt).__name__.lower()
    lr = float(getattr(opt, "_learning_rate", 1e-3)) \
        if not callable(getattr(opt, "_learning_rate", None)) else 1e-3

    if "adam" in name:
        b1 = float(getattr(opt, "_beta1", 0.9))
        b2 = float(getattr(opt, "_beta2", 0.999))
        eps = float(getattr(opt, "_epsilon", 1e-8))
        wd = float(getattr(opt, "_weight_decay", 0.0) or 0.0)

        def init(params):
            z = jax.tree.map(jnp.zeros_like, params)
            return {"m": z, "v": jax.tree.map(jnp.zeros_like, params),
                    "t": jnp.zeros((), jnp.int32)}

        def update(params, grads, state):
            t = state["t"] + 1
            m = jax.tree.map(lambda m, g: b1 * m + (1 - b1) * g,
                             state["m"], grads)
            v = jax.tree.map(lambda v, g: b2 * v + (1 - b2) * g * g,
                             state["v"], grads)
            bc1 = 1 - b1 ** t.astype(jnp.float32)
            bc2 = 1 - b2 ** t.astype(jnp.float32)

            def upd(p, m_, v_):
                step = lr * (m_ / bc1) / (jnp.sqrt(v_ / bc2) + eps)
                if wd and "adamw" in name:
                    step = step + lr * wd * p
                return (p - step).astype(p.dtype)

            return (jax.tree.map(upd, params, m, v),
                    {"m": m, "v": v, "t": t})

        return init, update

    mom = float(getattr(opt, "_momentum", 0.0) or 0.0)

    def init(params):
        return {"u": jax.tree.map(jnp.zeros_like, params)} if mom else {}

    def update(params, grads, state):
        if mom:
            u = jax.tree.map(lambda u, g: mom * u + g, state["u"], grads)
            new = jax.tree.map(lambda p, u: (p - lr * u).astype(p.dtype),
                               params, u)
            return new, {"u": u}
        new = jax.tree.map(lambda p, g: (p - lr * g).astype(p.dtype),
                           params, grads)
        return new, state

    return init, update


class Engine:
    """Auto-parallel train/eval/predict driver (reference Engine:
    static/engine.py:98 — fit at :1529, evaluate at :1719, predict at
    :1833, cost at engine._estimate)."""

    def __init__(self, model: Layer, loss=None, optimizer=None,
                 metrics=None, cluster: Optional[Cluster] = None,
                 strategy: Optional[Strategy] = None):
        self.model = model
        self.loss_fn = loss
        self.optimizer = optimizer
        self.metrics = metrics if isinstance(metrics, (list, tuple)) \
            else ([metrics] if metrics else [])
        self.cluster = cluster or Cluster.auto()
        self.strategy = strategy or Strategy()
        self.planner = Planner(self.cluster)
        self._plan: Optional[PlanItem] = None
        self._mesh: Optional[Mesh] = None
        self._params: Optional[Dict[str, Any]] = None
        self._opt_state = None
        self._steps: Dict[str, Any] = {}
        self.history: List[Dict[str, float]] = []

    # -- planning ------------------------------------------------------------

    def _param_tree(self):
        return {n: p._data for n, p in self.model.named_parameters()}

    def _estimate_sizes(self, sample_x: np.ndarray):
        params = self._param_tree()
        param_bytes = float(sum(a.size * a.dtype.itemsize
                                for a in jax.tree.leaves(params)))
        n_params = sum(a.size for a in jax.tree.leaves(params))
        batch = int(np.shape(sample_x)[0]) or 1
        tokens = int(np.prod(np.shape(sample_x)[:2])) if np.ndim(
            sample_x) >= 2 else batch
        flops = 6.0 * n_params * tokens  # fwd+bwd matmul rule of thumb
        act = float(np.prod(np.shape(sample_x))) * 4.0 * 8.0
        return flops, param_bytes, act

    def prepare(self, sample_x: np.ndarray, sample_y: np.ndarray = None,
                mode: str = "train"):
        """Plan the mesh and compile the step for `mode`.

        Plan EXECUTION (VERDICT r3 task #6): tp>1 / pp>1 plans are applied
        to the generic model through the compiled hybrid engine
        (distributed/hybrid_generic.py) — tp via GSPMD sharding rules on
        Linear/Embedding/Conv params, pp via the model's PipelineLayer
        segmentation; dp-only plans keep the GSPMD-jit path below."""
        flops, pbytes, act = self._estimate_sizes(sample_x)
        self._plan = self.planner.plan(
            self.strategy, flops_per_batch=flops, param_bytes=pbytes,
            act_bytes_per_microbatch=act)
        plan = self._plan
        self._hybrid = None
        if plan.tp > 1 or plan.pp > 1:
            from ..hybrid_generic import GenericHybridEngine
            from ..fleet.meta_parallel.parallel_layers.pp_layers import (
                PipelineLayer)
            from ..fleet.compiled_model import _hp_from_optimizer

            pp = plan.pp
            dp = plan.dp
            if pp > 1 and not isinstance(self.model, PipelineLayer):
                # an un-segmented model cannot pipeline: fold pp into dp so
                # the plan's degree is still used rather than wasted
                dp, pp = dp * pp, 1
            n = dp * pp * plan.tp
            devices = np.asarray(jax.devices()[:n]).reshape(dp, pp, plan.tp)
            mesh = Mesh(devices, ("dp", "pp", "tp"))
            self._mesh = mesh
            self._hybrid = GenericHybridEngine(
                self.model, mesh, self.loss_fn,
                hp=_hp_from_optimizer(self.optimizer),
                num_microbatches=max(1, plan.micro_batches))
            return self
        dp = plan.dp * plan.tp * plan.pp
        devices = np.array(jax.devices()[:dp])
        self._mesh = Mesh(devices, ("dp",))
        self._params = self._param_tree()
        if mode == "train":
            self._init_opt, self._upd = _functional_update(self.optimizer)
            self._opt_state = self._init_opt(self._params)
        self._compile(mode)
        return self

    def _param_sharding(self, arr):
        dp = self._mesh.shape["dp"]
        if (self.strategy.sharding_stage == 3 and arr.ndim >= 1
                and arr.shape[0] % dp == 0 and arr.shape[0] >= dp):
            return NamedSharding(self._mesh, P("dp"))
        return NamedSharding(self._mesh, P())

    def _apply(self, params, x):
        """Functional forward: swap param arrays into the Layer, trace."""
        from ...ops import dispatch

        objs = dict(self.model.named_parameters())
        saved = {n: p._data for n, p in objs.items()}
        try:
            for n, p in objs.items():
                p._data = params[n]
            with dispatch.no_grad():
                out = self.model(Tensor._from_data(x))
            return out._data if isinstance(out, Tensor) else out
        finally:
            for n, p in objs.items():
                p._data = saved[n]

    def _compile(self, mode: str):
        mesh = self._mesh
        data_sh = NamedSharding(mesh, P("dp"))
        rep = NamedSharding(mesh, P())
        param_sh = jax.tree.map(self._param_sharding, self._params)

        if mode == "train":
            def train_step(params, opt_state, x, y):
                def loss_of(ps):
                    pred = self._apply(ps, x)
                    lt = self.loss_fn(Tensor._from_data(pred),
                                      Tensor._from_data(y))
                    return (lt._data if isinstance(lt, Tensor)
                            else lt).mean()

                loss, grads = jax.value_and_grad(loss_of)(params)
                new_params, new_state = self._upd(params, grads, opt_state)
                return new_params, new_state, loss

            self._steps["train"] = jax.jit(
                train_step,
                in_shardings=(param_sh, None, data_sh, data_sh),
                out_shardings=(param_sh, None, rep),
                donate_argnums=(0, 1))
        elif mode == "eval":
            def eval_step(params, x, y):
                pred = self._apply(params, x)
                lt = self.loss_fn(Tensor._from_data(pred),
                                  Tensor._from_data(y))
                return pred, (lt._data if isinstance(lt, Tensor)
                              else lt).mean()

            self._steps["eval"] = jax.jit(
                eval_step, in_shardings=(param_sh, data_sh, data_sh))
        else:
            self._steps["predict"] = jax.jit(
                lambda params, x: self._apply(params, x),
                in_shardings=(param_sh, data_sh))

    # -- loops ---------------------------------------------------------------

    @staticmethod
    def _batches(data, batch_size):
        if hasattr(data, "__iter__") and not isinstance(data, (tuple, list)):
            yield from data
            return
        if isinstance(data, tuple) and len(data) == 2:
            xs, ys = data
            n = len(xs)
            for i in range(0, n - batch_size + 1, batch_size):
                yield (xs[i:i + batch_size],
                       None if ys is None else ys[i:i + batch_size])
            return
        yield from data

    def fit(self, train_data, epochs: int = 1, batch_size: int = 32,
            log_freq: int = 10, verbose: int = 1):
        first = True
        for epoch in range(epochs):
            t0, seen = time.time(), 0
            for step, (x, y) in enumerate(
                    self._batches(train_data, batch_size)):
                x = np.asarray(x)
                y = np.asarray(y)
                if first:
                    if self._plan is None or (
                            getattr(self, "_hybrid", None) is None
                            and "train" not in self._steps):
                        self.prepare(x, y, mode="train")
                    first = False
                if getattr(self, "_hybrid", None) is not None:
                    loss = self._hybrid.train_batch(x, y)
                else:
                    self._params, self._opt_state, loss = \
                        self._steps["train"](self._params, self._opt_state,
                                             x, y)
                seen += x.shape[0]
                if verbose and step % log_freq == 0:
                    rec = {"epoch": epoch, "step": step,
                           "loss": float(jax.device_get(loss)),
                           "ips": seen / max(time.time() - t0, 1e-9)}
                    self.history.append(rec)
        self._writeback()
        return self.history

    def evaluate(self, eval_data, batch_size: int = 32):
        losses, count = [], 0
        for m in self.metrics:
            if hasattr(m, "reset"):
                m.reset()
        hybrid = getattr(self, "_hybrid", None)
        if hybrid is not None and self.metrics:
            hybrid.sync_to_layer()   # once: metrics run an eager forward
        for x, y in self._batches(eval_data, batch_size):
            x, y = np.asarray(x), np.asarray(y)
            if hybrid is not None:
                # eval mode around the call — first call bakes the mode
                # into the compiled program (hybrid_generic.eval_batch)
                was_training = getattr(self.model, "training", True)
                if callable(getattr(self.model, "eval", None)):
                    self.model.eval()
                try:
                    losses.append(hybrid.eval_batch(x, y))
                finally:
                    if was_training and callable(
                            getattr(self.model, "train", None)):
                        self.model.train()
                count += x.shape[0]
                for m in self.metrics:
                    if hasattr(m, "compute"):
                        pred = self.model(Tensor._from_data(jnp.asarray(x)))
                        r = m.compute(pred,
                                      Tensor._from_data(jnp.asarray(y)))
                        m.update(r.numpy() if isinstance(r, Tensor) else r)
                continue
            if "eval" not in self._steps:
                if self._plan is None:
                    self.prepare(x, y, mode="eval")
                else:
                    self._compile("eval")
            pred, loss = self._steps["eval"](self._params, x, y)
            losses.append(float(jax.device_get(loss)))
            count += x.shape[0]
            for m in self.metrics:
                if hasattr(m, "compute"):
                    r = m.compute(Tensor._from_data(pred),
                                  Tensor._from_data(jnp.asarray(y)))
                    m.update(r.numpy() if isinstance(r, Tensor) else r)
        out = {"loss": float(np.mean(losses)) if losses else float("nan")}
        for m in self.metrics:
            if hasattr(m, "accumulate"):
                out[m.name() if callable(getattr(m, "name", None))
                    else type(m).__name__] = m.accumulate()
        return out

    def predict(self, data, batch_size: int = 32):
        outs = []
        if getattr(self, "_hybrid", None) is not None:
            self._hybrid.sync_to_layer()   # once, not per batch
        for item in self._batches(data, batch_size):
            x = np.asarray(item[0] if isinstance(item, (tuple, list))
                           else item)
            if getattr(self, "_hybrid", None) is not None:
                out = self.model(Tensor._from_data(jnp.asarray(x)))
                outs.append(np.asarray(out._data if isinstance(out, Tensor)
                                       else out))
                continue
            if "predict" not in self._steps:
                if self._plan is None:
                    self.prepare(x, mode="predict")
                else:
                    self._compile("predict")
            outs.append(np.asarray(
                jax.device_get(self._steps["predict"](self._params, x))))
        return np.concatenate(outs, axis=0) if outs else np.empty((0,))

    def cost(self, sample_x: np.ndarray) -> StepCost:
        """Reference: Engine._estimate / cost API — returns the analytic
        per-step cost of the CURRENT plan (planning one if needed)."""
        flops, pbytes, act = self._estimate_sizes(sample_x)
        plan = self._plan or self.planner.plan(
            self.strategy, flops_per_batch=flops, param_bytes=pbytes,
            act_bytes_per_microbatch=act)
        return self.planner.cost_model.estimate(
            flops_per_batch=flops, param_bytes=pbytes,
            act_bytes_per_microbatch=act, plan=plan)

    def _writeback(self):
        """Push compiled-step params back into the Layer objects."""
        if getattr(self, "_hybrid", None) is not None:
            self._hybrid.sync_to_layer()
            return
        objs = dict(self.model.named_parameters())
        for n, p in objs.items():
            p._data = self._params[n]

    @property
    def main_program(self):  # parity surface: reference returns a Program
        return self._steps

    @property
    def plan(self) -> Optional[PlanItem]:
        return self._plan

"""Auto-parallel dygraph API: shard_tensor / reshard / shard_layer / ...

Reference surface: python/paddle/distributed/auto_parallel/api.py
(`shard_tensor :220`, `reshard :733`, `shard_layer :844`, `shard_optimizer`,
`dtensor_from_fn`, `unshard_dtensor`) over the C++ `DistTensor`
(paddle/phi/core/distributed/auto_parallel/dist_tensor.h:39) + 115 SPMD
propagation rule files (paddle/phi/infermeta/spmd_rules/).

TPU-native redesign: a DistTensor IS a global `jax.Array` with a
`NamedSharding` — placement propagation through ops (the reference's 115
hand-written SPMD rules) is delegated to XLA's GSPMD sharding propagation,
and `reshard` is `jax.device_put` with a new sharding (XLA emits the
collective-permute / all-gather / slice sequence over ICI). The ProcessMesh
compiles to a `jax.sharding.Mesh`; placements compile to `PartitionSpec`s
(placement.py).
"""
from __future__ import annotations

from typing import Callable, Optional, Sequence

import jax
from jax.sharding import NamedSharding

from ...core.tensor import Parameter, Tensor
from .placement import (Partial, Placement, Replicate, Shard,
                        placements_to_spec, spec_to_placements)
from .process_mesh import ProcessMesh, get_mesh, set_mesh  # noqa: F401


def _as_process_mesh(mesh) -> ProcessMesh:
    if isinstance(mesh, ProcessMesh):
        return mesh
    return ProcessMesh(mesh)


def _clone_param(src: Parameter, arr) -> Parameter:
    """New Parameter over `arr` carrying all of src's per-param attributes
    (optimize_attr drives per-param LR in Optimizer.step)."""
    out = Parameter(arr, name=src.name, trainable=src.trainable)
    out.optimize_attr = dict(src.optimize_attr)
    out.regularizer = src.regularizer
    out.need_clip = src.need_clip
    out.is_distributed = src.is_distributed
    out.sequence_parallel = src.sequence_parallel
    out.split_axis = src.split_axis
    return out


def _named_sharding(mesh: ProcessMesh, placements: Sequence[Placement],
                    ndim: int):
    spec, partials = placements_to_spec(placements, mesh.dim_names, ndim)
    return NamedSharding(mesh.jax_mesh, spec), partials


def shard_tensor(data, mesh, placements: Sequence[Placement], dtype=None,
                 place=None, stop_gradient: Optional[bool] = None) -> Tensor:
    """Create a DistTensor laid out over `mesh` per `placements`.

    Reference: auto_parallel/api.py:220. The global value is `data`; each
    device holds the shard selected by its mesh coordinates.
    """
    mesh = _as_process_mesh(mesh)
    if isinstance(data, Tensor):
        src = data
        arr = data._data
        if dtype is not None:
            from ...core import dtype as dtype_mod

            arr = arr.astype(dtype_mod.to_np(dtype))
    else:
        src = None
        arr = Tensor(data, dtype=dtype)._data
    # `place` is accepted for signature parity; the mesh decides placement.
    sharding, partials = _named_sharding(mesh, placements, arr.ndim)
    arr = jax.device_put(arr, sharding)
    if src is not None and isinstance(src, Parameter):
        out = _clone_param(src, arr)
    else:
        out = Tensor._from_data(arr)
    if src is not None:
        # differentiable identity (layout change only) — keep the tape edge
        out._grad_node = src._grad_node
        out._out_index = src._out_index
    if stop_gradient is not None:
        out.stop_gradient = stop_gradient
    elif src is not None:
        out.stop_gradient = src.stop_gradient
    out._dist_mesh = mesh
    out._dist_partials = partials
    return out


def dtensor_from_fn(fn: Callable, mesh, placements: Sequence[Placement],
                    *args, **kwargs) -> Tensor:
    """Build a DistTensor from a creation fn (paddle.ones, ...). The fn runs
    once; the result is laid out over the mesh (reference keeps only the
    local shard — identical semantics on a single controller)."""
    out = fn(*args, **kwargs)
    if not isinstance(out, Tensor):
        out = Tensor(out)
    return shard_tensor(out, mesh, placements)


def reshard(dist_tensor: Tensor, mesh, placements: Sequence[Placement]) -> Tensor:
    """Change mesh/placements. Reference: api.py:733 + the C++/python reshard
    function zoo (phi/core/distributed/auto_parallel/reshard/,
    auto_parallel/static/reshard_funcs/) — p_to_r, r_to_s, s_to_r, nd-mesh
    cross-mesh... all collapse to one `jax.device_put` here: XLA plans the
    move (slice / all-gather / permute) from the (src, dst) sharding pair.
    """
    mesh = _as_process_mesh(mesh)
    x = dist_tensor if isinstance(dist_tensor, Tensor) else Tensor(dist_tensor)
    sharding, partials = _named_sharding(mesh, placements, x._data.ndim)
    arr = jax.device_put(x._data, sharding)
    if isinstance(x, Parameter):
        out = _clone_param(x, arr)
        out.stop_gradient = x.stop_gradient
    else:
        out = Tensor._from_data(arr, stop_gradient=x.stop_gradient)
    out._grad_node = x._grad_node
    out._out_index = x._out_index
    out._dist_mesh = mesh
    out._dist_partials = partials
    return out


def unshard_dtensor(dist_tensor: Tensor) -> Tensor:
    """Back to a dense replicated tensor (reference: api.py unshard_dtensor)."""
    x = dist_tensor
    mesh = x._dist_mesh
    if mesh is None:
        return x
    out = reshard(x, mesh, [Replicate() for _ in mesh.dim_names])
    out._dist_mesh = None
    out._dist_partials = ()
    return out


def shard_layer(layer, process_mesh, shard_fn: Optional[Callable] = None,
                input_fn: Optional[Callable] = None,
                output_fn: Optional[Callable] = None):
    """Shard a Layer's parameters in place (reference: api.py:844).

    `shard_fn(sublayer_name, sublayer, process_mesh)` shards each sublayer's
    params; default replicates everything over the mesh. `input_fn/output_fn`
    are installed as forward pre/post hooks.
    """
    mesh = _as_process_mesh(process_mesh)

    def _replicate_params(sub):
        for name, p in list(sub._parameters.items()):
            if p is not None and not p.is_dist():
                sub._parameters[name] = _shard_param(
                    p, mesh, [Replicate() for _ in mesh.dim_names])

    if shard_fn is None:
        for name, sub in layer.named_sublayers(include_self=True):
            _replicate_params(sub)
    else:
        for name, sub in layer.named_sublayers(include_self=True):
            shard_fn(name, sub, mesh)
        # any param the shard_fn skipped is replicated
        for name, sub in layer.named_sublayers(include_self=True):
            _replicate_params(sub)

    if input_fn is not None:
        layer.register_forward_pre_hook(
            lambda lyr, inputs: input_fn(inputs, mesh))
    if output_fn is not None:
        layer.register_forward_post_hook(
            lambda lyr, inputs, outputs: output_fn(outputs, mesh))
    return layer


def _shard_param(p: Parameter, mesh: ProcessMesh,
                 placements: Sequence[Placement]) -> Parameter:
    out = shard_tensor(p, mesh, placements)
    out.stop_gradient = p.stop_gradient
    out.trainable = p.trainable
    return out


# ---------------------------------------------------------------------------
# shard_optimizer + sharding-stage plans (reference: api.py shard_optimizer,
# ShardingStage1/2/3 in python/paddle/distributed/auto_parallel/api.py)
# ---------------------------------------------------------------------------

class _ShardingStageBase:
    def __init__(self, sharding_mesh_dim: Optional[str] = None, mesh=None):
        self.sharding_mesh_dim = sharding_mesh_dim
        self._mesh = _as_process_mesh(mesh) if mesh is not None else None

    @property
    def mesh(self) -> Optional[ProcessMesh]:
        """Explicit mesh, else the global mesh from `set_mesh` (reference
        resolves stages against the default mesh the same way)."""
        if self._mesh is not None:
            return self._mesh
        return get_mesh()


class ShardingStage1(_ShardingStageBase):
    """ZeRO-1: shard optimizer accumulators over the sharding mesh dim."""


class ShardingStage2(_ShardingStageBase):
    """ZeRO-2: + gradients reduce-scattered (on XLA the backward psum over
    the sharding axis is re-associated to reduce-scatter by the compiler when
    the consuming update is sharded — stage1 and stage2 share one plan)."""


class ShardingStage3(_ShardingStageBase):
    """ZeRO-3: + parameters sharded (gathered on use)."""


def _stage_placements(mesh: ProcessMesh, dim: str, ndim: int, shape):
    """Shard dim-0 over the sharding axis when divisible, else replicate."""
    from .placement import dim0_shardable

    placements = [Replicate() for _ in mesh.dim_names]
    if ndim > 0 and dim0_shardable(shape, mesh.get_dim_size(dim)):
        placements[mesh.dim_names.index(dim)] = Shard(0)
    return placements


class _ShardedOptimizer:
    """Wraps an Optimizer so accumulators (and for stage3, params) are laid
    out over the sharding axis as they are created/updated."""

    def __init__(self, optimizer, shard_fn=None):
        self._inner = optimizer
        self._shard_fn = shard_fn
        if isinstance(shard_fn, ShardingStage3) and shard_fn.mesh is not None:
            dim = shard_fn.sharding_mesh_dim or shard_fn.mesh.dim_names[0]
            params = optimizer._parameter_list or []
            for p in params:
                if isinstance(p, Parameter) and not p.is_dist():
                    pl = _stage_placements(shard_fn.mesh, dim, p.ndim, p.shape)
                    sharding, _ = _named_sharding(shard_fn.mesh, pl, p.ndim)
                    p._data = jax.device_put(p._data, sharding)
                    p._dist_mesh = shard_fn.mesh

    def __getattr__(self, name):
        return getattr(self._inner, name)

    def minimize(self, loss, *a, **k):
        # must route through OUR step so the stage sharding applies
        loss.backward()
        self.step()
        self._inner.clear_grad()

    def clear_grad(self, *a, **k):
        self._inner.clear_grad(*a, **k)

    def step(self):
        self._inner.step()
        fn = self._shard_fn
        if isinstance(fn, _ShardingStageBase) and fn.mesh is not None:
            dim = fn.sharding_mesh_dim or fn.mesh.dim_names[0]
            for pname, accs in self._inner._accumulators.items():
                for aname, arr in accs.items():
                    if hasattr(arr, "ndim") and arr.ndim > 0:
                        sharding, _ = _named_sharding(
                            fn.mesh,
                            _stage_placements(fn.mesh, dim, arr.ndim,
                                              arr.shape),
                            arr.ndim)
                        accs[aname] = jax.device_put(arr, sharding)
        elif callable(fn) and not isinstance(fn, _ShardingStageBase):
            # paddle contract: shard_fn(accumulator_name, param, accumulator)
            # -> (possibly resharded) accumulator tensor.
            by_name = {p.name: p for p in (self._inner._parameter_list or [])
                       if isinstance(p, Tensor)}
            for pname, accs in self._inner._accumulators.items():
                param = by_name.get(pname)
                for aname, arr in accs.items():
                    out = fn(aname, param, Tensor._from_data(arr))
                    accs[aname] = out._data if isinstance(out, Tensor) else out


def shard_optimizer(optimizer, shard_fn=None):
    """Reference: paddle.distributed.shard_optimizer. With no shard_fn the
    accumulators simply inherit each param's sharding (free on XLA: states
    are computed from sharded params, GSPMD propagates)."""
    if shard_fn is None:
        return optimizer
    return _ShardedOptimizer(optimizer, shard_fn)


# ---------------------------------------------------------------------------
# Misc parity helpers
# ---------------------------------------------------------------------------

def local_map(fn: Callable, out_placements, in_placements=None,
              process_mesh=None, reshard_inputs: bool = False):
    """Run `fn` on per-device local shards (reference: dist.local_map) —
    implemented with shard_map over the mesh.

    Eager semantics note: this framework's eager arrays never hold un-reduced
    state, so `Partial` placements are materialized: a Partial *input* is
    pre-scaled by 1/axis_size (the virtual partials sum to the true value —
    exact for the linear fns partial values are meaningful for), and a
    Partial *output* is reduced (psum) over that mesh axis inside the mapped
    region before being returned.
    """
    def wrapper(*args):
        import jax.numpy as jnp
        from jax import lax

        mesh = process_mesh
        if mesh is None:
            for a in args:
                if isinstance(a, Tensor) and a.is_dist():
                    mesh = a._dist_mesh
                    break
        if mesh is None:
            return fn(*args)
        pmesh = _as_process_mesh(mesh)
        jmesh = pmesh.jax_mesh
        arrs, in_specs = [], []
        for i, a in enumerate(args):
            x = a._data if isinstance(a, Tensor) else jnp.asarray(a)
            if in_placements is not None:
                spec, in_parts = placements_to_spec(in_placements[i],
                                                    pmesh.dim_names, x.ndim)
                for ax in in_parts:
                    x = x / pmesh.get_dim_size(ax)
            else:
                sh = getattr(x, "sharding", None)
                spec = getattr(sh, "spec", None)
                if spec is None:
                    spec = jax.sharding.PartitionSpec()
            cur = getattr(x, "sharding", None)
            on_mesh = (getattr(cur, "mesh", None) == jmesh
                       and getattr(cur, "spec", None) == spec)
            if not on_mesh:
                multi_dev = cur is not None and len(
                    getattr(cur, "device_set", ())) > 1
                if multi_dev and not reshard_inputs:
                    raise ValueError(
                        f"local_map input {i} is laid out differently from "
                        f"in_placements; pass reshard_inputs=True to move it")
                x = jax.device_put(x, NamedSharding(jmesh, spec))
            arrs.append(x)
            in_specs.append(spec)
        single = not isinstance(out_placements[0], (list, tuple))
        out_pls = [out_placements] if single else list(out_placements)

        # resolve output ranks (negative Shard dims, validation) by abstract
        # evaluation of fn over the local shard shapes
        def _local_aval(x, spec):
            shape = list(x.shape)
            for d, entry in enumerate(spec):
                if entry is None:
                    continue
                names = entry if isinstance(entry, (tuple, list)) else (entry,)
                for nm in names:
                    shape[d] //= pmesh.get_dim_size(nm)
            return jax.ShapeDtypeStruct(tuple(shape), x.dtype)

        out_avals = jax.eval_shape(
            fn, *[_local_aval(x, s) for x, s in zip(arrs, in_specs)])
        aval_list = ([out_avals] if single
                     else list(out_avals if isinstance(out_avals, (tuple, list))
                               else [out_avals]))
        out_specs, out_partials = [], []
        for pl, av in zip(out_pls, aval_list):
            spec, partials = placements_to_spec(pl, pmesh.dim_names,
                                                len(av.shape))
            out_specs.append(spec)
            out_partials.append(partials)

        def inner(*xs):
            outs = fn(*xs)
            outs_t = (outs,) if single else tuple(outs)
            reduced = []
            for o, partials in zip(outs_t, out_partials):
                for ax in partials:
                    o = lax.psum(o, ax)
                reduced.append(o)
            return reduced[0] if single else tuple(reduced)

        sm = jax.shard_map(inner, mesh=jmesh, in_specs=tuple(in_specs),
                           out_specs=out_specs[0] if single else tuple(out_specs),
                           check_vma=False)
        outs = sm(*arrs)

        def wrap(o):
            t = Tensor._from_data(o)
            t._dist_mesh = pmesh
            return t

        if single:
            return wrap(outs)
        return tuple(wrap(o) for o in
                     (outs if isinstance(outs, (tuple, list)) else [outs]))

    return wrapper

"""paddle.distributed.auto_parallel parity — TPU-native DistTensor over
jax.sharding (SURVEY.md §2.5 auto-parallel row)."""
from .placement import Partial, Placement, Replicate, Shard  # noqa: F401
from .process_mesh import ProcessMesh, get_mesh, set_mesh  # noqa: F401
from .api import (  # noqa: F401
    ShardingStage1,
    ShardingStage2,
    ShardingStage3,
    dtensor_from_fn,
    local_map,
    reshard,
    shard_layer,
    shard_optimizer,
    shard_tensor,
    unshard_dtensor,
)
from .engine import (  # noqa: F401
    Cluster,
    CostModel,
    Engine,
    Planner,
    PlanItem,
    StepCost,
    Strategy,
)

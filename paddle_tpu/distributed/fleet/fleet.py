"""Fleet facade — hybrid-parallel orchestration entry point.

Reference: python/paddle/distributed/fleet/fleet.py — `fleet.init` (:218)
builds the HybridCommunicateGroup from DistributedStrategy.hybrid_configs;
`distributed_model` (fleet/model.py:32) picks the meta-parallel wrapper;
`distributed_optimizer` (:1427) wraps with HybridParallelOptimizer.

TPU-native: init additionally materializes the hybrid topology as a
`jax.sharding.Mesh` (axes in strategy order) so downstream wrappers and the
compiled-train-step engine (distributed.hybrid) share one device mesh.
"""
from __future__ import annotations

from typing import Optional

import numpy as np

from ...core.tensor import Tensor
from .. import collective as coll
from ..env import get_rank, get_world_size
from .base.distributed_strategy import DistributedStrategy
from .base.topology import (
    CommunicateTopology,
    HybridCommunicateGroup,
    get_hcg,
    set_hcg,
)


class Fleet:
    def __init__(self):
        self._strategy: Optional[DistributedStrategy] = None
        self._is_collective = True
        self._hcg: Optional[HybridCommunicateGroup] = None
        self._mesh = None
        self._initialized = False

    # ------------------------------------------------------------------
    def init(self, role_maker=None, is_collective: bool = True,
             strategy: Optional[DistributedStrategy] = None, log_level="INFO"):
        self._strategy = strategy or DistributedStrategy()
        self._is_collective = is_collective
        coll.init_parallel_env()

        h = self._strategy.hybrid_configs
        order = list(h.get("order") or ["dp", "pp", "sharding", "sep", "mp"])
        degree_key = {"dp": "dp_degree", "pp": "pp_degree",
                      "sharding": "sharding_degree", "sep": "sep_degree",
                      "mp": "mp_degree"}
        dims = [max(1, int(h.get(degree_key[n], 1))) for n in order]
        world = get_world_size()
        prod = int(np.prod(dims))
        if prod not in (0, world) and world > 1:
            # infer dp like the reference (remaining degree goes to dp)
            rest = prod // max(1, dims[order.index("dp")])
            if world % rest == 0:
                dims[order.index("dp")] = world // rest
        topo = CommunicateTopology(order, dims)
        self._hcg = HybridCommunicateGroup(topo)
        set_hcg(self._hcg)
        self._build_mesh(order, dims)
        self._initialized = True
        return self

    def _build_mesh(self, order, dims):
        import jax
        from jax.sharding import Mesh

        n = int(np.prod(dims))
        devs = jax.devices()
        if len(devs) >= n:
            self._mesh = Mesh(np.asarray(devs[:n]).reshape(dims), tuple(order))

    # ------------------------------------------------------------------
    @property
    def is_initialized(self) -> bool:
        return self._initialized

    def is_first_worker(self) -> bool:
        return get_rank() == 0

    def worker_index(self) -> int:
        return get_rank()

    def worker_num(self) -> int:
        return get_world_size()

    def get_hybrid_communicate_group(self) -> HybridCommunicateGroup:
        return self._hcg

    @property
    def mesh(self):
        return self._mesh

    def barrier_worker(self):
        coll.barrier()

    # ------------------------------------------------------------------
    def distributed_model(self, model):
        """Reference: fleet/model.py:32 (wrapper selection :143-162)."""
        from .meta_parallel import (
            PipelineParallel,
            SegmentParallel,
            TensorParallel,
        )
        from ..parallel import DataParallel

        hcg = self._hcg
        if hcg is None:
            return model
        if hcg.get_pipe_parallel_world_size() > 1:
            return PipelineParallel(model, hcg, self._strategy)
        if hcg.get_model_parallel_world_size() > 1:
            return TensorParallel(model, hcg, self._strategy)
        if hcg.get_sep_parallel_world_size() > 1:
            return SegmentParallel(model, hcg, self._strategy)
        if hcg.get_data_parallel_world_size() > 1:
            return DataParallel(model, group=hcg.get_data_parallel_group(),
                                find_unused_parameters=self._strategy
                                .find_unused_parameters)
        return model

    def distributed_optimizer(self, optimizer, strategy=None):
        """Reference: fleet.py:1427 → HybridParallelOptimizer."""
        from .meta_optimizers.dygraph_optimizer.hybrid_parallel_optimizer import (  # noqa: E501
            HybridParallelOptimizer,
        )

        if self._hcg is None:
            return optimizer
        return HybridParallelOptimizer(optimizer, self._hcg,
                                       self._strategy or DistributedStrategy())

    # PS-mode stubs (reference parameter-server path; sparse recsys PS is
    # out of TPU scope — gated, not silently wrong)
    def is_server(self):
        return False

    def is_worker(self):
        return True

    def init_worker(self):
        pass

    def init_server(self, *a, **k):
        raise NotImplementedError(
            "parameter-server mode is not supported by the TPU backend; "
            "use collective mode (is_collective=True)")

    def run_server(self):
        raise NotImplementedError(
            "parameter-server mode is not supported by the TPU backend")

    def stop_worker(self):
        pass


fleet = Fleet()

"""Fleet facade — hybrid-parallel orchestration entry point.

Reference: python/paddle/distributed/fleet/fleet.py — `fleet.init` (:218)
builds the HybridCommunicateGroup from DistributedStrategy.hybrid_configs;
`distributed_model` (fleet/model.py:32) picks the meta-parallel wrapper;
`distributed_optimizer` (:1427) wraps with HybridParallelOptimizer.

TPU-native: init additionally materializes the hybrid topology as a
`jax.sharding.Mesh` (axes in strategy order) so downstream wrappers and the
compiled-train-step engine (distributed.hybrid) share one device mesh.
"""
from __future__ import annotations

from typing import Optional

import numpy as np

from ...core.tensor import Tensor
from .. import collective as coll
from ..env import get_rank, get_world_size
from .base.distributed_strategy import DistributedStrategy
from .base.topology import (
    CommunicateTopology,
    HybridCommunicateGroup,
    get_hcg,
    set_hcg,
)


class Fleet:
    def __init__(self):
        self._strategy: Optional[DistributedStrategy] = None
        self._is_collective = True
        self._hcg: Optional[HybridCommunicateGroup] = None
        self._mesh = None
        self._initialized = False

    # ------------------------------------------------------------------
    def init(self, role_maker=None, is_collective: bool = True,
             strategy: Optional[DistributedStrategy] = None, log_level="INFO"):
        self._strategy = strategy or DistributedStrategy()
        self._is_collective = is_collective
        if not is_collective:
            # parameter-server mode: roles come from the PS launch env
            # (reference: the_one_ps role_maker); no collective init
            self._init_ps_env()
            self._initialized = True
            return self
        coll.init_parallel_env()

        h = self._strategy.hybrid_configs
        order = list(h.get("order") or ["dp", "pp", "sharding", "sep", "mp"])
        degree_key = {"dp": "dp_degree", "pp": "pp_degree",
                      "sharding": "sharding_degree", "sep": "sep_degree",
                      "mp": "mp_degree"}
        dims = [max(1, int(h.get(degree_key[n], 1))) for n in order]
        world = get_world_size()
        prod = int(np.prod(dims))
        if prod not in (0, world) and world > 1:
            # infer dp like the reference (remaining degree goes to dp)
            rest = prod // max(1, dims[order.index("dp")])
            if world % rest == 0:
                dims[order.index("dp")] = world // rest
        topo = CommunicateTopology(order, dims)
        self._hcg = HybridCommunicateGroup(topo)
        set_hcg(self._hcg)
        self._build_mesh(order, dims)
        self._initialized = True
        return self

    def _build_mesh(self, order, dims):
        import jax
        from jax.sharding import Mesh

        n = int(np.prod(dims))
        devs = jax.devices()
        if len(devs) >= n:
            self._mesh = Mesh(np.asarray(devs[:n]).reshape(dims), tuple(order))

    # ------------------------------------------------------------------
    @property
    def is_initialized(self) -> bool:
        return self._initialized

    def is_first_worker(self) -> bool:
        return get_rank() == 0

    def worker_index(self) -> int:
        return get_rank()

    def worker_num(self) -> int:
        return get_world_size()

    def get_hybrid_communicate_group(self) -> HybridCommunicateGroup:
        return self._hcg

    @property
    def mesh(self):
        return self._mesh

    def barrier_worker(self):
        coll.barrier()

    # ------------------------------------------------------------------
    def distributed_model(self, model):
        """Reference: fleet/model.py:32 (wrapper selection :143-162).

        With `hybrid_configs={"compiled": True}` the model is wrapped in the
        generic COMPILED hybrid engine (distributed/hybrid_generic.py): one
        jitted dp×pp×tp train step — manual GPipe + dp, GSPMD tp — instead
        of the eager per-stage wrappers. The wrapper keeps the reference
        train_batch/eval_batch surface (pipeline_parallel.py:255)."""
        from .meta_parallel import (
            PipelineParallel,
            SegmentParallel,
            TensorParallel,
        )
        from ..parallel import DataParallel

        hcg = self._hcg
        if hcg is None:
            return model
        if (self._strategy is not None
                and self._strategy.hybrid_configs.get("compiled")
                and self._mesh is not None):
            from .compiled_model import CompiledHybridModel

            return CompiledHybridModel(model, self, self._strategy)
        if hcg.get_pipe_parallel_world_size() > 1:
            return PipelineParallel(model, hcg, self._strategy)
        if hcg.get_model_parallel_world_size() > 1:
            return TensorParallel(model, hcg, self._strategy)
        if hcg.get_sep_parallel_world_size() > 1:
            return SegmentParallel(model, hcg, self._strategy)
        if hcg.get_data_parallel_world_size() > 1:
            return DataParallel(model, group=hcg.get_data_parallel_group(),
                                find_unused_parameters=self._strategy
                                .find_unused_parameters)
        return model

    def distributed_optimizer(self, optimizer, strategy=None):
        """Reference: fleet.py:1427 → HybridParallelOptimizer."""
        from .meta_optimizers.dygraph_optimizer.hybrid_parallel_optimizer import (  # noqa: E501
            HybridParallelOptimizer,
        )

        if self._hcg is None:
            return optimizer
        return HybridParallelOptimizer(optimizer, self._hcg,
                                       self._strategy or DistributedStrategy())

    # -- parameter-server mode ------------------------------------------
    # Reference: fleet.py is_server/init_server/run_server/init_worker/
    # stop_worker over the_one_ps; here over distributed/ps (host-side
    # tables — see that module's docstring for the TPU scoping).

    def _init_ps_env(self):
        import os

        self._ps_role = os.environ.get("TRAINING_ROLE", "TRAINER").upper()
        eps = os.environ.get("PADDLE_PSERVERS_IP_PORT_LIST", "")
        self._ps_endpoints = [e for e in eps.replace(";", ",").split(",")
                              if e]
        self._ps_port = int(os.environ.get("PADDLE_PORT", "0") or 0)
        self._ps_n_workers = int(os.environ.get("PADDLE_TRAINERS_NUM", "1"))
        self._ps_server = None
        self._ps_client = None

    def is_server(self):
        return (not self._is_collective
                and getattr(self, "_ps_role", "") == "PSERVER")

    def is_worker(self):
        return not self.is_server()

    def init_server(self, *model_dirs, **kwargs):
        from ..ps import PsServer

        if not self.is_server():
            raise RuntimeError("init_server on a non-PSERVER role")
        self._ps_server = PsServer(port=self._ps_port,
                                   n_workers=self._ps_n_workers)

    def run_server(self):
        if self._ps_server is None:
            raise RuntimeError("call init_server() first")
        self._ps_server.run()

    def init_worker(self, scopes=None):
        if self._is_collective:
            return
        from ..ps import PsClient

        if not self._ps_endpoints:
            raise RuntimeError(
                "PS worker needs PADDLE_PSERVERS_IP_PORT_LIST")
        self._ps_client = PsClient(self._ps_endpoints)

    @property
    def ps_client(self):
        return getattr(self, "_ps_client", None)

    def stop_worker(self):
        client = getattr(self, "_ps_client", None)
        if client is None:
            return
        client.barrier()  # all workers finished before teardown
        if self.worker_index() == 0:
            client.stop_servers()
        client.close()
        self._ps_client = None


fleet = Fleet()

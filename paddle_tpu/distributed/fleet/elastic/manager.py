"""ElasticManager — store-backed node registry, heartbeats, rank reassignment.

Reference design: python/paddle/distributed/fleet/elastic/manager.py:125.
There, every node holds an etcd lease (TTL) on a key under
``/paddle/{job}/nodes``; a lease-heartbeat thread refreshes it; watch
callbacks fire when the node set changes; when the set is stable and within
``[min_np, max_np]`` the launcher (re)builds the pod with freshly assigned
ranks, and trainers resume from the last checkpoint.

TPU-native translation (no etcd in the image, and none needed):

* The registry is our TCPStore (``paddle_tpu/distributed/store.py``, native
  C++ server in ``core/native/src/native.cc``). A TTL lease becomes a
  heartbeat key ``{prefix}/beat/{node}`` carrying ``time.time()``; a node is
  live iff its beat is younger than ``ttl``. Slots are allocated with the
  store's atomic ``add`` so registration is race-free without a lock.
* There are no watch callbacks: every node polls the same registry and runs
  the same pure function ``live_nodes() -> rank map``, so all survivors
  agree on the new world without a consensus round (the store is the single
  source of truth, exactly like etcd was).
* Rescale is checkpoint-based like the reference: on membership change the
  local pod is torn down and respawned with the new (rank, world) env;
  trainers are expected to resume from their last checkpoint
  (``paddle_tpu.distributed.checkpoint`` reshards on load, so a different
  world size is fine).

States mirror the reference's ElasticStatus enum (manager.py:60).
"""
from __future__ import annotations

import os
import socket
import threading
import time
from typing import Dict, List, Optional, Tuple


class ElasticLevel:
    """Reference manager.py:55 — fault tolerance vs true elastic."""

    FAULT_TOLERANCE = 1   # fixed np: restart in place on failure
    ELASTIC = 2           # min:max np: rescale on node loss/join


class ElasticStatus:
    """Reference manager.py:60 (COMPLETED/ERROR/HOLD/RESTART/EXIT)."""

    COMPLETED = "completed"
    ERROR = "error"
    HOLD = "hold"          # waiting for the node set to stabilise
    RESTART = "restart"    # membership changed -> respawn pod
    EXIT = "exit"          # job done elsewhere, or below min past timeout


def parse_nnodes(spec: str) -> Tuple[int, int]:
    """'N' -> (N, N); 'min:max' -> (min, max). Reference manager.py:371."""
    parts = str(spec).split(":")
    lo = int(parts[0])
    hi = int(parts[1]) if len(parts) > 1 else lo
    if lo < 1 or hi < lo:
        raise ValueError(f"bad nnodes spec {spec!r}: need 1 <= min <= max")
    return lo, hi


class ElasticManager:
    """One instance per node; owns registration + heartbeat + world calc.

    Parameters
    ----------
    store : TCPStore-like (set/get/add/check/delete_key)
    job_id : registry namespace (reference: PADDLE_ELASTIC_JOB_ID)
    nnodes : "N" or "min:max"
    node_id : stable identity for this node (default host:pid)
    ttl : seconds after which a silent node is declared dead
          (reference: PADDLE_ELASTIC_TTL lease, manager.py:145)
    settle : membership must be unchanged this long before (re)building the
             pod — absorbs the join stampede at startup
    timeout : max seconds to HOLD below min before giving up
              (reference: PADDLE_ELASTIC_TIMEOUT, manager.py:142)
    """

    def __init__(self, store, job_id: str, nnodes: str = "1",
                 node_id: Optional[str] = None, ttl: float = 6.0,
                 settle: float = 1.0, timeout: float = 120.0):
        self.store = store
        self.min_np, self.max_np = parse_nnodes(nnodes)
        self.level = (ElasticLevel.ELASTIC if self.max_np > self.min_np
                      else ElasticLevel.FAULT_TOLERANCE)
        self.node_id = node_id or f"{socket.gethostname()}:{os.getpid()}"
        self.ttl = float(os.environ.get("PADDLE_ELASTIC_TTL", ttl))
        self.settle = settle
        self.timeout = float(os.environ.get("PADDLE_ELASTIC_TIMEOUT", timeout))
        self.prefix = f"elastic/{job_id}"
        self._slot: Optional[int] = None
        self._beat_thread: Optional[threading.Thread] = None
        self._stop = threading.Event()

    # -- registry ----------------------------------------------------------

    def _key(self, *parts: str) -> str:
        return "/".join((self.prefix,) + parts)

    def register(self) -> int:
        """Claim a slot and start heartbeating. Returns the slot index.

        Reference: manager.py:288 (etcd.put(host_path, lease)) + the
        lease_heartbeat thread at manager.py:254. ``add`` on the slot
        counter is the atomic allocator; slot order doubles as the
        registration order used for stable rank assignment.
        """
        self._slot = self.store.add(self._key("nslots"), 1) - 1
        self.store.set(self._key("slot", str(self._slot)),
                       self.node_id.encode())
        self._beat()
        self._stop.clear()
        self._beat_thread = threading.Thread(
            target=self._beat_loop, name="elastic-heartbeat", daemon=True)
        self._beat_thread.start()
        return self._slot

    def _beat(self):
        self.store.set(self._key("beat", self.node_id),
                       repr(time.time()).encode())

    def _beat_loop(self):
        while not self._stop.wait(self.ttl / 3.0):
            try:
                self._beat()
            except Exception:
                return  # store gone: the job is over

    def live_nodes(self) -> List[Tuple[int, str]]:
        """[(slot, node_id)] with a fresh heartbeat, slot-ascending.

        A node that died and re-registered appears once, at its newest
        slot (a rejoin is a new registration, like a fresh etcd lease).
        """
        try:
            nslots = int(self.store.add(self._key("nslots"), 0))
        except Exception:
            return []
        newest: Dict[str, int] = {}
        now = time.time()
        for s in range(nslots):
            key = self._key("slot", str(s))
            if not self.store.check(key):
                continue
            node = self.store.get(key).decode()
            beat_key = self._key("beat", node)
            if not self.store.check(beat_key):
                continue
            try:
                beat = float(self.store.get(beat_key).decode())
            except ValueError:
                continue
            if now - beat <= self.ttl:
                newest[node] = s
        return sorted((s, n) for n, s in newest.items())

    # -- world agreement ---------------------------------------------------

    def world(self) -> Tuple[int, int, List[str]]:
        """(my_rank, world_size, ordered node ids) from the live set.

        Rank = index in slot order, so surviving nodes keep their relative
        order across a rescale (reference sorts hosts the same way before
        writing PADDLE_TRAINERS, manager.py:460 _update_endpoint path).
        Rank -1 means this node is not (yet) in the live set.
        """
        live = self.live_nodes()
        nodes = [n for _, n in live]
        rank = nodes.index(self.node_id) if self.node_id in nodes else -1
        return rank, len(nodes), nodes

    def wait_for_world(self) -> Tuple[str, int, int, List[str]]:
        """Block until the node set is within [min, max] and stable.

        Returns (status, rank, world_size, nodes): status RESTART when a
        buildable world emerged, EXIT on done-flag or timeout below min.
        Reference: _match + wait loop in manager.py:430.
        """
        deadline = time.time() + self.timeout
        stable_since = None
        prev: Optional[Tuple[str, ...]] = None
        while True:
            if self.store.check(self._key("done")):
                return ElasticStatus.EXIT, -1, 0, []
            rank, n, nodes = self.world()
            sig = tuple(nodes)
            if sig != prev:
                prev, stable_since = sig, time.time()
            ok = rank >= 0 and self.min_np <= n <= self.max_np
            if ok and time.time() - stable_since >= self.settle:
                return ElasticStatus.RESTART, rank, n, nodes
            if time.time() > deadline:
                return ElasticStatus.EXIT, rank, n, nodes
            time.sleep(min(0.2, self.ttl / 6.0))

    def watch(self, poll_pod) -> str:
        """Supervise a running pod until something changes.

        ``poll_pod() -> Optional[int]`` returns None while the local pod
        runs, else its exit code. Returns an ElasticStatus:

        * COMPLETED — local pod exited 0
        * ERROR     — local pod failed (launcher decides restart budget)
        * RESTART   — the live node set changed (peer died or joined):
                      tear down and re-rendezvous
        * EXIT      — job marked done by another node

        Reference: manager.py watch() + launcher loop in elastic/__init__.py.
        """
        _, _, nodes0 = self.world()
        baseline = tuple(nodes0)
        while True:
            rc = poll_pod()
            if rc is not None:
                return (ElasticStatus.COMPLETED if rc == 0
                        else ElasticStatus.ERROR)
            if self.store.check(self._key("done")):
                return ElasticStatus.EXIT
            _, _, nodes = self.world()
            if tuple(nodes) != baseline:
                return ElasticStatus.RESTART
            time.sleep(min(0.2, self.ttl / 6.0))

    # -- teardown ----------------------------------------------------------

    def mark_done(self):
        """Broadcast job completion so peers EXIT instead of rescaling."""
        self.store.set(self._key("done"), b"1")

    def is_done(self) -> bool:
        return bool(self.store.check(self._key("done")))

    def exit(self, completed: bool = False):
        """Stop heartbeating; optionally mark the job done.

        Reference: manager.py:335 (put done flag, delete host key).
        The beat key is deleted so peers see this node leave immediately
        instead of after a TTL.
        """
        if completed:
            try:
                self.mark_done()
            except Exception:
                pass
        self._stop.set()
        if self._beat_thread is not None:
            self._beat_thread.join(timeout=2.0)
            self._beat_thread = None
        try:
            self.store.delete_key(self._key("beat", self.node_id))
        except Exception:
            pass

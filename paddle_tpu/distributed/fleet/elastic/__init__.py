"""Elastic training: fault tolerance + scale in/out.

Reference: python/paddle/distributed/fleet/elastic/manager.py:125
(ElasticManager over an etcd registry with TTL leases) and
elastic/__init__.py (enable/launch glue). TPU-native analog: the registry
is our own TCPStore (core/native/src/native.cc) instead of etcd — nodes
register under a job prefix, heartbeat on a TTL, and every node
deterministically recomputes the rank map from the same registry snapshot,
so no consensus round is needed beyond the store itself.
"""
from .manager import ElasticManager, ElasticLevel, ElasticStatus

__all__ = ["ElasticManager", "ElasticLevel", "ElasticStatus"]

"""CompiledHybridModel — fleet wrapper over the generic compiled engine.

Reference surface: meta_parallel/pipeline_parallel.py:255 `train_batch` /
`eval_batch` on the wrapped model. TPU-native body: one jitted
dp×pp×tp step from distributed/hybrid_generic.GenericHybridEngine instead
of eager per-stage execution + NCCL collectives.

Activation: `strategy.hybrid_configs = {"compiled": True}` before
`fleet.distributed_model(model)`.
"""
from __future__ import annotations

from typing import Optional

from ..hybrid import AdamWConfig
from ..hybrid_generic import GenericHybridEngine


def _hp_from_optimizer(optimizer) -> AdamWConfig:
    """Map a framework optimizer onto the engine's fused AdamW."""
    name = type(optimizer).__name__ if optimizer is not None else "AdamW"
    if name not in ("AdamW", "Adam"):
        raise NotImplementedError(
            f"compiled hybrid engine fuses AdamW into the step; optimizer "
            f"{name} is not supported — drop hybrid_configs['compiled'] to "
            "use the eager fleet wrappers")
    def get(attr, default):
        v = getattr(optimizer, attr, None)
        return default if v is None else float(v)   # 0.0 is a real value

    lr = getattr(optimizer, "_learning_rate", 1e-3)
    if hasattr(lr, "get_lr"):
        lr = lr.get_lr()
    # AdamW keeps decoupled decay in _coeff; Adam's coupled decay (if any)
    # sits in _weight_decay
    wd = get("_coeff", get("_weight_decay", 0.0))
    clip = getattr(optimizer, "_grad_clip", None)
    clip_norm = getattr(clip, "clip_norm", None) if clip is not None else None
    return AdamWConfig(lr=float(lr), beta1=get("_beta1", 0.9),
                       beta2=get("_beta2", 0.999), eps=get("_epsilon", 1e-8),
                       weight_decay=wd,
                       grad_clip=float(clip_norm) if clip_norm else None)


class CompiledHybridModel:
    """Duck-types the PipelineParallel wrapper: train_batch / eval_batch /
    forward / parameters / state_dict, backed by one compiled step."""

    def __init__(self, model, fleet_obj, strategy):
        self._layers = model
        self._fleet = fleet_obj
        self._strategy = strategy
        self._engine: Optional[GenericHybridEngine] = None
        h = strategy.hybrid_configs
        self._num_microbatches = max(
            1, int(h.get("accumulate_steps", 1) or 1))
        self._loss_fn = getattr(model, "_loss_fn", None)
        self._train_traced = False
        self._eval_traced = False

    # -- engine lifecycle ------------------------------------------------
    def _ensure_engine(self, optimizer=None, loss_fn=None):
        if self._engine is None:
            lf = loss_fn or self._loss_fn
            if lf is None:
                raise ValueError(
                    "compiled hybrid needs a loss: pass loss_fn to "
                    "train_batch or build the PipelineLayer with loss_fn=")
            self._engine = GenericHybridEngine(
                self._layers, self._fleet.mesh, lf,
                hp=_hp_from_optimizer(optimizer),
                num_microbatches=self._num_microbatches)
        return self._engine

    # -- reference API ----------------------------------------------------
    def train_batch(self, data, optimizer=None, lr_scheduler=None,
                    scaler=None, loss_fn=None):
        """Positionally matches PipelineParallel.train_batch(data, optimizer,
        lr_scheduler, scaler); loss_fn is the compiled-path extension."""
        if scaler is not None and getattr(scaler, "_enable", False):
            raise NotImplementedError(
                "compiled hybrid step does not take a GradScaler: bf16 "
                "training needs no loss scaling; drop "
                "hybrid_configs['compiled'] for the eager fp16 path")
        x, labels = data
        eng = self._ensure_engine(optimizer, loss_fn)
        self._set_mode(train=True)   # retraces must also see train mode
        # the CURRENT scheduled lr feeds the compiled step each call (the
        # engine's hp.lr is only the default) — reference train_batch
        # applies the scheduled lr per step too
        lr = None
        sched = lr_scheduler
        if sched is None and optimizer is not None:
            maybe = getattr(optimizer, "_learning_rate", None)
            if hasattr(maybe, "get_lr"):
                sched = maybe
        if sched is not None and hasattr(sched, "get_lr"):
            lr = float(sched.get_lr())
        loss = eng.train_batch(x, labels, lr=lr)
        self._train_traced = True
        if lr_scheduler is not None:
            lr_scheduler.step()
        from ...core.tensor import Tensor
        import jax.numpy as jnp

        return Tensor._from_data(jnp.float32(loss))

    def eval_batch(self, data, compute_loss=True, loss_fn=None):
        """Reference surface (pipeline_parallel.py eval_batch): eval mode;
        compute_loss=False returns the raw model output."""
        x, labels = (data if isinstance(data, (tuple, list)) and
                     len(data) == 2 else (data, None))
        if not compute_loss:
            if self._engine is not None:
                self._engine.sync_to_layer()
            self._set_mode(train=False)
            try:
                return self._layers(x)
            finally:
                self._set_mode(train=True)
        eng = self._ensure_engine(None, loss_fn)
        # ALWAYS eval mode around the call: jit retraces on a new batch
        # shape, and any retrace must also see layers.eval() (reference
        # eval_batch semantics) — mode is a cheap host attribute
        self._set_mode(train=False)
        try:
            loss = eng.eval_batch(x, labels)
            self._eval_traced = True
        finally:
            self._set_mode(train=True)
        from ...core.tensor import Tensor
        import jax.numpy as jnp

        return Tensor._from_data(jnp.float32(loss))

    def _set_mode(self, train: bool):
        fn = getattr(self._layers, "train" if train else "eval", None)
        if callable(fn):
            fn()

    def forward(self, *args, **kwargs):
        if self._engine is not None:
            self._engine.sync_to_layer()
        return self._layers(*args, **kwargs)

    __call__ = forward

    def parameters(self, *a, **k):
        if self._engine is not None:
            self._engine.sync_to_layer()
        return self._layers.parameters(*a, **k)

    def state_dict(self, *a, **k):
        if self._engine is not None:
            self._engine.sync_to_layer()
        return self._layers.state_dict(*a, **k)

    def set_state_dict(self, sd, *a, **k):
        out = self._layers.set_state_dict(sd, *a, **k)
        if self._engine is not None:
            # re-seed the engine's device copies from the layer — the
            # engine knows its own layout (incl. pp-stacked params)
            self._engine.refresh_from_layer()
        return out

    def __getattr__(self, name):
        return getattr(self._layers, name)

"""HybridParallelOptimizer — hybrid-topology-aware optimizer wrapper.

Reference: fleet/meta_optimizers/dygraph_optimizer/
hybrid_parallel_optimizer.py:266 — wraps the inner optimizer with (a) dp/
sharding gradient synchronization, (b) a hybrid-aware ClipGradByGlobalNorm
(norm contributions psum-ed over the axes each param is sharded on), then
steps.

TPU-native: on global arrays the grad is already the global gradient (XLA
inserted the cross-shard reductions during backward), so (a) is a no-op
except in per-rank eager multi-host mode, where it bucketed-allreduces over
the dp group. (b) reduces to the plain global-norm clip — shards belong to
one logical array, so the sum of squared locals IS the global norm.
"""
from __future__ import annotations

from typing import Optional

class HybridParallelOptimizer:
    def __init__(self, optimizer, hcg, strategy):
        self._inner_opt = optimizer
        self._hcg = hcg
        self._strategy = strategy
        self._dp_group = (hcg.get_data_parallel_group()
                          if hcg is not None else None)
        self._sharding_group = (hcg.get_sharding_parallel_group()
                                if hcg is not None else None)

    # -- paddle Optimizer surface ---------------------------------------
    @property
    def _parameter_list(self):
        return getattr(self._inner_opt, "_parameter_list", None) or \
            getattr(self._inner_opt, "_params", [])

    def _sync_grads(self):
        from ....parallel import sync_param_grads

        sync_param_grads(list(self._parameter_list or []), self._dp_group)

    def step(self):
        self._sync_grads()
        self._inner_opt.step()

    def minimize(self, loss, *a, **k):
        loss.backward()
        self.step()
        self.clear_grad()

    def clear_grad(self, *a, **k):
        return self._inner_opt.clear_grad(*a, **k)

    def state_dict(self):
        return self._inner_opt.state_dict()

    def set_state_dict(self, state):
        return self._inner_opt.set_state_dict(state)

    def get_lr(self):
        return self._inner_opt.get_lr()

    def set_lr(self, v):
        return self._inner_opt.set_lr(v)

    def __getattr__(self, item):
        return getattr(self._inner_opt, item)

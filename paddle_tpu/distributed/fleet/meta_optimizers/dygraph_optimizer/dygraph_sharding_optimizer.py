"""Sharding (ZeRO stage-1) optimizer.

Reference: fleet/meta_optimizers/dygraph_optimizer/
dygraph_sharding_optimizer.py:54 — params are bucketed round-robin over the
sharding group by size; each rank runs the inner optimizer only on its
bucket, then broadcasts updated params to the group (V2 :586 does param-unit
reduce-scatter instead).

TPU-native: optimizer *states* are the memory hog, and XLA shards them for
free when their arrays are laid out over the mesh (states inherit param
sharding in the compiled engine). This class provides the fleet-API tier:
the rank→param assignment (`_rank2params`), local-shard stepping, and the
post-step broadcast, which on global arrays becomes a sharding-constraint
re-layout (weight-update sharding, cf. PAPERS.md#1 "ZeRO on XLA").
"""
from __future__ import annotations

from typing import Dict, List

import numpy as np

from .....core.tensor import Parameter
from .... import collective as coll


class DygraphShardingOptimizer:
    """Reference: dygraph_sharding_optimizer.py:54."""

    def __init__(self, optimizer, hcg=None):
        self._inner_opt = optimizer
        self._hcg = hcg
        group = (hcg.get_sharding_parallel_group() if hcg is not None else None)
        self._group = group
        self._nranks = group.nranks if group else 1
        self._rank = max(group.rank, 0) if group else 0
        params = list(getattr(optimizer, "_parameter_list", None)
                      or getattr(optimizer, "_params", []))
        self._origin_parameter_list = params
        self._rank2params = self._partition_parameters(params)
        # inner optimizer only steps this rank's shard
        local = self._rank2params[self._rank]
        if hasattr(optimizer, "_params"):
            optimizer._params = local
        if hasattr(optimizer, "_parameter_list"):
            optimizer._parameter_list = local

    def _partition_parameters(self, params) -> Dict[int, List[Parameter]]:
        """Greedy smallest-bucket assignment (reference's size balancing)."""
        mapping = {i: [] for i in range(self._nranks)}
        sizes = np.zeros(self._nranks)
        for p in sorted(params, key=lambda p: -int(np.prod(p.shape) if p.shape else 1)):
            i = int(np.argmin(sizes))
            mapping[i].append(p)
            sizes[i] += int(np.prod(p.shape) if p.shape else 1)
        return mapping

    def step(self):
        self._inner_opt.step()
        self._broadcast_params()

    def _broadcast_params(self):
        """Each rank broadcasts its updated shard to the group
        (reference: _sharding_sync_parameters)."""
        g = self._group
        if g is None or g.nranks <= 1:
            return
        for rank, params in self._rank2params.items():
            for p in params:
                coll.broadcast(p, src=g.ranks[rank], group=g)

    def minimize(self, loss, *a, **k):
        loss.backward()
        self.step()
        self.clear_grad()

    def clear_grad(self, *a, **k):
        # clear ALL original params' grads, not just the local shard
        for p in self._origin_parameter_list:
            p._grad = None

    def state_dict(self):
        return self._inner_opt.state_dict()

    def set_state_dict(self, s):
        return self._inner_opt.set_state_dict(s)

    def __getattr__(self, item):
        return getattr(self._inner_opt, item)

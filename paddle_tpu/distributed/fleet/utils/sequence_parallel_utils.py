"""Megatron sequence-parallel utilities.

Reference: python/paddle/distributed/fleet/utils/sequence_parallel_utils.py —
`ScatterOp` (:85) / `GatherOp` (:97) / `AllGatherOp` (:111) /
`ReduceScatterOp` (:127): autograd-paired collectives that shard/unshard the
sequence dim around TP regions, plus
`register_sequence_parallel_allreduce_hooks` (:192) for LN-param grads.

TPU-native: each op is a `jax.custom_vjp` pair over the mp axis — inside a
shard_map trace they emit the ICI collective; the vjp IS the reference's
hand-written backward (scatter↔gather, all_gather↔reduce_scatter). In
global-array (GSPMD) mode they become sharding-constraint annotations on the
sequence dim, letting XLA place the same collectives. The compiled hybrid
engine (distributed.hybrid `_block_sp`) uses the same pattern inline.
"""
from __future__ import annotations

import jax
import jax.numpy as jnp
from jax import lax
from jax.sharding import NamedSharding, PartitionSpec as P

from ....core.tensor import Tensor
from ... import collective as coll


def _mp_axis(group=None):
    if group is not None:
        return group.axis_name
    from ..base.topology import get_hcg

    hcg = get_hcg()
    if hcg is not None:
        return hcg.get_model_parallel_group().axis_name
    return "mp"


def _unwrap(x):
    return x._data if isinstance(x, Tensor) else x


def _rewrap(arr, like):
    if isinstance(like, Tensor):
        t = Tensor(arr)
        t.stop_gradient = like.stop_gradient
        return t
    return arr


def _traced_on(x, axis):
    return isinstance(x, jax.core.Tracer) and coll._axis_in_scope(axis)


def _annotate_seq(x, axis, sharded: bool):
    """GSPMD mode: constrain the sequence dim (dim 0, paddle SP convention
    is [s, b, h]) to be sharded/replicated over the mp axis."""
    from ..fleet import fleet as _f

    mesh = getattr(_f, "mesh", None)
    if mesh is None or axis not in mesh.axis_names:
        return x
    spec = [None] * x.ndim
    if sharded:
        spec[0] = axis
    try:
        return lax.with_sharding_constraint(x, NamedSharding(mesh, P(*spec)))
    except Exception:
        return x


# -- scatter: fwd split seq dim, bwd all-gather ------------------------------

from functools import partial


@partial(jax.custom_vjp, nondiff_argnums=(1,))
def _scatter(x, axis):
    n = lax.axis_size(axis)
    i = lax.axis_index(axis)
    size = x.shape[0] // n
    return lax.dynamic_slice_in_dim(x, i * size, size, 0)


def _scatter_fwd(x, axis):
    return _scatter(x, axis), None


def _scatter_bwd(axis, _res, g):
    return (lax.all_gather(g, axis, axis=0, tiled=True),)


_scatter.defvjp(_scatter_fwd, _scatter_bwd)


# -- gather: fwd all-gather seq dim, bwd scatter (slice) ---------------------

@partial(jax.custom_vjp, nondiff_argnums=(1,))
def _gather(x, axis):
    return lax.all_gather(x, axis, axis=0, tiled=True)


def _gather_fwd(x, axis):
    return _gather(x, axis), None


def _gather_bwd(axis, _res, g):
    n = lax.axis_size(axis)
    i = lax.axis_index(axis)
    size = g.shape[0] // n
    return (lax.dynamic_slice_in_dim(g, i * size, size, 0),)


_gather.defvjp(_gather_fwd, _gather_bwd)


def ScatterOp(input, group=None):  # noqa: N802 (reference API name)
    """Reference: sequence_parallel_utils.py:85 — seq full → seq/mp."""
    axis = _mp_axis(group)
    x = _unwrap(input)
    if _traced_on(x, axis):
        return _rewrap(_scatter(x, axis), input)
    return _rewrap(_annotate_seq(x, axis, sharded=True), input)


def GatherOp(input, group=None):  # noqa: N802
    """Reference: sequence_parallel_utils.py:97 — seq/mp → seq full."""
    axis = _mp_axis(group)
    x = _unwrap(input)
    if _traced_on(x, axis):
        return _rewrap(_gather(x, axis), input)
    return _rewrap(_annotate_seq(x, axis, sharded=False), input)


def AllGatherOp(input, group=None):  # noqa: N802
    """Reference: :111 — fwd all_gather, bwd reduce_scatter (for column-
    parallel matmul inputs; the bwd differs from GatherOp!)."""
    axis = _mp_axis(group)
    x = _unwrap(input)
    if _traced_on(x, axis):
        return _rewrap(_all_gather_rs(x, axis), input)
    return _rewrap(_annotate_seq(x, axis, sharded=False), input)


@partial(jax.custom_vjp, nondiff_argnums=(1,))
def _all_gather_rs(x, axis):
    return lax.all_gather(x, axis, axis=0, tiled=True)


def _agrs_fwd(x, axis):
    return _all_gather_rs(x, axis), None


def _agrs_bwd(axis, _res, g):
    return (lax.psum_scatter(g, axis, scatter_dimension=0, tiled=True),)


_all_gather_rs.defvjp(_agrs_fwd, _agrs_bwd)


def ReduceScatterOp(input, group=None):  # noqa: N802
    """Reference: :127 — fwd reduce_scatter, bwd all_gather (row-parallel
    matmul outputs)."""
    axis = _mp_axis(group)
    x = _unwrap(input)
    if _traced_on(x, axis):
        return _rewrap(lax.psum_scatter(x, axis, scatter_dimension=0,
                                        tiled=True), input)
    return _rewrap(_annotate_seq(x, axis, sharded=True), input)


def mark_as_sequence_parallel_parameter(parameter):
    """Reference: :168 — tag params (LayerNorm w/b inside SP regions) whose
    grads need an mp-group allreduce."""
    parameter.sequence_parallel = True


def is_sequence_parallel_parameter(parameter) -> bool:
    return getattr(parameter, "sequence_parallel", False)


def register_sequence_parallel_allreduce_hooks(model, accumulation_steps=1,
                                               fuse_sequence_parallel_allreduce=False):
    """Reference: :192. On global arrays the LN grads are already complete
    (no seq-sharded partial sums exist outside shard_map), so this registers
    the sync only for the per-rank engine path, where the compiled step's
    `sync_grads` (distributed.hybrid) psums replicated leaves — the hook
    records which params need it."""
    marked = [p for p in model.parameters()
              if is_sequence_parallel_parameter(p)]
    return marked

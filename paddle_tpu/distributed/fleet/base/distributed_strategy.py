"""DistributedStrategy — all fleet knobs in one config object.

Reference: python/paddle/distributed/fleet/base/distributed_strategy.py
(backed by framework/distributed_strategy.proto). TPU-native: a plain
dataclass-of-dicts (no protobuf needed — there is no cross-language strategy
hand-off; XLA compile options are derived from these fields instead).
"""
from __future__ import annotations

import copy
from typing import Any, Dict


_DEFAULT_HYBRID = {
    "dp_degree": 1,
    "mp_degree": 1,
    "pp_degree": 1,
    "sharding_degree": 1,
    "sep_degree": 1,
    "order": ["dp", "pp", "sharding", "sep", "mp"],
    "mp_configs": {},
    "pp_configs": {},
}

_DEFAULT_AMP = {
    "init_loss_scaling": 32768.0,
    "use_dynamic_loss_scaling": True,
    "incr_every_n_steps": 1000,
    "decr_every_n_nan_or_inf": 2,
    "incr_ratio": 2.0,
    "decr_ratio": 0.5,
    "use_pure_fp16": False,
    "use_bf16": True,  # TPU-first default
    "custom_white_list": [],
    "custom_black_list": [],
}

_DEFAULT_RECOMPUTE = {"checkpoints": [], "enable_offload": False}

_DEFAULT_SHARDING = {
    "sharding_degree": 1,
    "stage": 1,
    "offload": False,
    "comm_overlap": True,
}


class DistributedStrategy:
    def __init__(self):
        self.hybrid_configs: Dict[str, Any] = copy.deepcopy(_DEFAULT_HYBRID)
        self.amp = False
        self.amp_configs: Dict[str, Any] = copy.deepcopy(_DEFAULT_AMP)
        self.recompute = False
        self.recompute_configs: Dict[str, Any] = copy.deepcopy(_DEFAULT_RECOMPUTE)
        self.sharding = False
        self.sharding_configs: Dict[str, Any] = copy.deepcopy(_DEFAULT_SHARDING)
        self.gradient_merge = False
        self.gradient_merge_configs: Dict[str, Any] = {"k_steps": 1, "avg": True}
        self.lamb = False
        self.dgc = False
        self.heter_ccl_mode = False
        self.find_unused_parameters = False
        self.fuse_all_reduce_ops = True
        self.fuse_grad_size_in_MB = 32
        self.nccl_comm_num = 1
        self.gradient_scale_configs = {"scale_strategy": "avg"}
        self.pipeline = False
        self.pipeline_configs: Dict[str, Any] = {
            "accumulate_steps": 1, "micro_batch_size": 1}
        self.tensor_parallel = False
        self.tensor_parallel_configs: Dict[str, Any] = {
            "tensor_parallel_degree": 1}

    # reference keeps hybrid_configs as a merged-update property
    def __setattr__(self, key, value):
        if key == "hybrid_configs" and isinstance(value, dict) \
                and "hybrid_configs" in self.__dict__:
            merged = copy.deepcopy(self.__dict__["hybrid_configs"])
            merged.update(value)
            self.__dict__[key] = merged
        else:
            self.__dict__[key] = value

    def __repr__(self):
        h = self.hybrid_configs
        return (f"DistributedStrategy(dp={h['dp_degree']}, mp={h['mp_degree']},"
                f" pp={h['pp_degree']}, sharding={h['sharding_degree']},"
                f" sep={h['sep_degree']}, amp={self.amp},"
                f" recompute={self.recompute})")

"""Hybrid-parallel process topology.

Reference: python/paddle/distributed/fleet/base/topology.py —
`CommunicateTopology` (:70) is an N-D cartesian rank grid;
`HybridCommunicateGroup` (:189) carves per-dimension comm groups out of it
(order default ['dp', 'pp', 'sharding', 'sep', 'mp'], :323).

TPU-native: the rank grid IS a `jax.sharding.Mesh` over the same axis order;
each per-dimension group is a `collective.Group` bound to that mesh axis, so
collectives issued on it lower to XLA collectives over ICI partitioned along
that axis. The 'check' fused groups (dp+pp etc.) get multi-axis bindings.
"""
from __future__ import annotations

import itertools
from typing import Dict, List, Optional, Sequence

import numpy as np

from ... import collective as coll
from ...env import get_rank, get_world_size

_HYBRID_ORDER = ["dp", "pp", "sharding", "sep", "mp"]


class CommunicateTopology:
    """Reference: fleet/base/topology.py:70."""

    def __init__(self, hybrid_group_names: Sequence[str] = _HYBRID_ORDER,
                 dims: Sequence[int] = (1, 1, 1, 1, 1)):
        self._parallel_names = list(hybrid_group_names)
        self._dims = list(dims)
        self.coordinate = None
        self._world = np.arange(int(np.prod(self._dims))).reshape(self._dims)

    def get_hybrid_group_names(self) -> List[str]:
        return list(self._parallel_names)

    def get_dim(self, axis_name: str) -> int:
        return self._dims[self._parallel_names.index(axis_name)]

    get_dim_size = get_dim

    def world_size(self) -> int:
        return int(self._world.size)

    def get_rank(self, **kwargs) -> int:
        coord = tuple(kwargs[n] for n in self._parallel_names)
        return int(self._world[coord])

    def get_coord(self, rank: int):
        coord = np.argwhere(self._world == rank)[0]
        return dict(zip(self._parallel_names, (int(c) for c in coord)))

    def get_axis_list(self, axis_name: str, index: int) -> List[int]:
        """All ranks whose coordinate on `axis_name` equals `index`."""
        ax = self._parallel_names.index(axis_name)
        return [int(r) for r in np.take(self._world, index, axis=ax).flatten()]

    def get_comm_list(self, axis_name: str) -> List[List[int]]:
        """Groups of ranks varying only along `axis_name`."""
        ax = self._parallel_names.index(axis_name)
        moved = np.moveaxis(self._world, ax, -1)
        return [list(map(int, row)) for row in moved.reshape(-1, self._dims[ax])]

    def get_fused_ranks(self, fused_axes: Sequence[str]) -> List[List[int]]:
        """Groups varying along all of `fused_axes` jointly."""
        axes = [self._parallel_names.index(a) for a in fused_axes]
        keep = [i for i in range(len(self._dims)) if i not in axes]
        moved = np.transpose(self._world, keep + sorted(axes))
        flat_keep = int(np.prod([self._dims[i] for i in keep])) if keep else 1
        return [list(map(int, row)) for row in moved.reshape(flat_keep, -1)]


class HybridCommunicateGroup:
    """Reference: fleet/base/topology.py:189.

    Builds per-dimension groups for this rank. Group creation is lazy-cheap
    here (a Group is an axis binding, not an NCCL ring), so all groups exist
    on every rank.
    """

    def __init__(self, topology: CommunicateTopology):
        self._topo = topology
        self.global_rank = get_rank()
        self.nranks = topology.world_size()
        names = topology.get_hybrid_group_names()

        self._dp_degree = topology.get_dim("dp") if "dp" in names else 1
        self._pp_degree = topology.get_dim("pp") if "pp" in names else 1
        self._sharding_degree = (topology.get_dim("sharding")
                                 if "sharding" in names else 1)
        self._sep_degree = topology.get_dim("sep") if "sep" in names else 1
        self._mp_degree = topology.get_dim("mp") if "mp" in names else 1

        self._groups: Dict[str, coll.Group] = {}
        coord = (topology.get_coord(self.global_rank)
                 if self.global_rank < self.nranks else
                 topology.get_coord(0))
        for name in names:
            # the 1-D slice through this rank along `name`
            fixed = {k: v for k, v in coord.items() if k != name}
            ranks = [topology.get_rank(**{**fixed, name: i})
                     for i in range(topology.get_dim(name))]
            self._groups[name] = coll.new_group(ranks=ranks, axis_name=name)

        # fused "check" groups (reference: topology.py:212+)
        self._check_group = coll.new_group(
            ranks=list(range(self.nranks)), axis_name="check")

    # --- degrees ---------------------------------------------------------
    def get_data_parallel_world_size(self) -> int:
        return self._dp_degree

    def get_model_parallel_world_size(self) -> int:
        return self._mp_degree

    def get_pipe_parallel_world_size(self) -> int:
        return self._pp_degree

    def get_sharding_parallel_world_size(self) -> int:
        return self._sharding_degree

    def get_sep_parallel_world_size(self) -> int:
        return self._sep_degree

    # --- ranks -----------------------------------------------------------
    def _coord(self):
        return self._topo.get_coord(min(self.global_rank, self.nranks - 1))

    def get_data_parallel_rank(self) -> int:
        return self._coord()["dp"]

    def get_model_parallel_rank(self) -> int:
        return self._coord()["mp"]

    def get_stage_id(self) -> int:
        return self._coord()["pp"]

    def get_pipe_parallel_rank(self) -> int:
        return self._coord()["pp"]

    def get_sharding_parallel_rank(self) -> int:
        return self._coord()["sharding"]

    def get_sep_parallel_rank(self) -> int:
        return self._coord()["sep"]

    # --- groups ----------------------------------------------------------
    def get_data_parallel_group(self) -> coll.Group:
        return self._groups["dp"]

    def get_model_parallel_group(self) -> coll.Group:
        return self._groups["mp"]

    def get_pipe_parallel_group(self) -> coll.Group:
        return self._groups["pp"]

    def get_sharding_parallel_group(self) -> coll.Group:
        return self._groups["sharding"]

    def get_sep_parallel_group(self) -> coll.Group:
        return self._groups["sep"]

    def get_check_parallel_group(self, *a) -> coll.Group:
        return self._check_group

    def get_data_parallel_group_src_rank(self) -> int:
        return self._groups["dp"].ranks[0]

    def get_model_parallel_group_src_rank(self) -> int:
        return self._groups["mp"].ranks[0]

    # --- pipeline helpers (reference: topology.py p2p neighbors) ---------
    def is_first_stage(self) -> bool:
        return self.get_stage_id() == 0

    def is_last_stage(self) -> bool:
        return self.get_stage_id() == self._pp_degree - 1

    def get_p2p_groups(self):
        return None

    def get_rank_from_stage(self, stage_id: int, **kwargs) -> int:
        coord = self._coord()
        coord["pp"] = stage_id
        coord.update(kwargs)
        return self._topo.get_rank(**coord)

    def topology(self) -> CommunicateTopology:
        return self._topo


_hcg: Optional[HybridCommunicateGroup] = None


def set_hcg(hcg: HybridCommunicateGroup):
    global _hcg
    _hcg = hcg


def get_hcg() -> Optional[HybridCommunicateGroup]:
    return _hcg

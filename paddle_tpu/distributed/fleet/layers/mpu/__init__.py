"""Model-parallel utility layers (reference: fleet/layers/mpu/)."""
from .mp_layers import (  # noqa: F401
    ColumnParallelLinear,
    ParallelCrossEntropy,
    RowParallelLinear,
    VocabParallelEmbedding,
)
from .random import (  # noqa: F401
    RNGStatesTracker,
    get_rng_state_tracker,
    model_parallel_random_seed,
)
from . import mp_ops  # noqa: F401

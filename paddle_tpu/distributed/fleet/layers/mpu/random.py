"""Model-parallel RNG state tracking.

Reference: python/paddle/distributed/fleet/layers/mpu/random.py
`RNGStatesTracker` — keeps named RNG states so dropout inside TP regions is
DIFFERENT per mp rank (activation dropout on sharded dims) while regular
dropout stays identical across ranks.

TPU-native: states are jax PRNG seeds; `rng_state(name)` swaps the default
Generator for the scope. Per-mp-rank decorrelation folds the mp rank into
the seed (`jax.random.fold_in` semantics).
"""
from __future__ import annotations

import contextlib
from typing import Dict

from .....core import rng as rng_mod

MODEL_PARALLEL_RNG = "model_parallel_rng"


class RNGStatesTracker:
    def __init__(self):
        self.states_: Dict[str, rng_mod.Generator] = {}
        self.seeds_ = set()

    def reset(self):
        self.states_ = {}
        self.seeds_ = set()

    def get_states_tracker(self):
        return {n: g.get_state() for n, g in self.states_.items()}

    def set_states_tracker(self, states):
        for n, s in states.items():
            self.states_.setdefault(n, rng_mod.Generator(0)).set_state(s)

    def add(self, name: str, seed: int):
        if seed in self.seeds_:
            raise ValueError(f"seed {seed} already exists")
        self.seeds_.add(seed)
        if name in self.states_:
            raise ValueError(f"state {name} already exists")
        self.states_[name] = rng_mod.Generator(seed)

    @contextlib.contextmanager
    def rng_state(self, name: str = MODEL_PARALLEL_RNG):
        if name not in self.states_:
            raise ValueError(f"state {name} does not exist")
        orig = rng_mod.default_generator
        rng_mod.default_generator = self.states_[name]
        try:
            yield
        finally:
            rng_mod.default_generator = orig


RNG_STATE_TRACKER = RNGStatesTracker()


def get_rng_state_tracker() -> RNGStatesTracker:
    return RNG_STATE_TRACKER


def model_parallel_random_seed(seed: int = 0):
    """Reference: random.py model_parallel_random_seed — decorrelate the
    model-parallel state by folding in the mp rank."""
    from ...base.topology import get_hcg

    hcg = get_hcg()
    mp_rank = hcg.get_model_parallel_rank() if hcg else 0
    global_seed = seed
    local_seed = seed + 1024 + mp_rank
    RNG_STATE_TRACKER.reset()
    rng_mod.seed(global_seed)
    RNG_STATE_TRACKER.add(MODEL_PARALLEL_RNG, local_seed)


def determinate_seed(name: str) -> int:
    g = RNG_STATE_TRACKER.states_.get(name)
    return g.initial_seed() if g else 0

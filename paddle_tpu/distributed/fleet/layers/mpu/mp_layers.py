"""Megatron-style tensor-parallel layers.

Reference: python/paddle/distributed/fleet/layers/mpu/mp_layers.py —
`VocabParallelEmbedding` (:49), `ColumnParallelLinear` (:336),
`RowParallelLinear` (:543), `ParallelCrossEntropy` (:744). There, each rank
allocates 1/mp of the weight and fires explicit NCCL collectives
(_c_identity/_mp_allreduce) around local matmuls.

TPU-native (GSPMD-first): each layer allocates the FULL logical weight once
and lays it out sharded over the fleet mesh's 'mp' axis (NamedSharding on
the PJRT buffers — per-device memory is 1/mp, same as the reference). Under
`jit`, XLA's sharding propagation inserts the exact same collectives the
reference hand-codes (all-gather for column gather_output, all-reduce after
row-parallel matmul), scheduled on ICI. The explicit-collective path
(mp_ops) remains for shard_map-traced code.
"""
from __future__ import annotations

from typing import Optional

import jax
import numpy as np
from jax.sharding import NamedSharding, PartitionSpec as P

from .....core.tensor import Parameter, Tensor
from .....nn import functional as F
from .....nn.layer.layers import Layer
from .... import collective as coll
from . import mp_ops
from .random import get_rng_state_tracker  # noqa: F401 (public API parity)


def _to_mesh(t):
    """Replicate an eager operand onto the hybrid mesh so it can combine with
    mesh-sharded weights (XLA requires operands on one device set)."""
    from ...fleet import fleet as _fleet_singleton

    mesh = getattr(_fleet_singleton, "mesh", None)
    x = t._data if isinstance(t, Tensor) else t
    if mesh is None or isinstance(x, jax.core.Tracer):
        return t
    try:
        if getattr(x, "sharding", None) is not None and \
                set(x.sharding.device_set) == set(mesh.devices.flat):
            return t
        moved = jax.device_put(x, NamedSharding(mesh, P()))
    except Exception:
        return t
    if isinstance(t, Tensor):
        out = Tensor(moved)
        out.stop_gradient = t.stop_gradient
        return out
    return moved


def _shard_param(p: Parameter, spec: P):
    """Lay a parameter out over the hybrid mesh (no-op without a mesh)."""
    from ...fleet import fleet as _fleet_singleton

    mesh = getattr(_fleet_singleton, "mesh", None)
    if mesh is None or "mp" not in mesh.axis_names:
        return p
    try:
        p._data = jax.device_put(p._data, NamedSharding(mesh, spec))
    except Exception:
        pass
    return p


class VocabParallelEmbedding(Layer):
    """Reference: mp_layers.py:49."""

    def __init__(self, num_embeddings, embedding_dim, weight_attr=None,
                 mp_group: Optional[coll.Group] = None, name=None):
        super().__init__()
        from ...base.topology import get_hcg

        hcg = get_hcg()
        self.group = mp_group or (hcg.get_model_parallel_group() if hcg else None)
        self.world_size = self.group.nranks if self.group else 1
        self.rank = max(self.group.rank, 0) if self.group else 0
        self.num_embeddings = num_embeddings
        self.embedding_dim = embedding_dim
        assert num_embeddings % max(self.world_size, 1) == 0, (
            "vocab size must be divisible by mp degree")
        self.per_part_size = num_embeddings // max(self.world_size, 1)
        self.vocab_start_index = self.rank * self.per_part_size
        from .....nn import initializer as I
        from .....nn.param_attr import ParamAttr

        self.weight = self.create_parameter(
            [num_embeddings, embedding_dim], ParamAttr._to_attr(weight_attr),
            self._dtype, default_initializer=I.XavierNormal())
        self.weight.is_distributed = self.world_size > 1
        _shard_param(self.weight, P("mp", None))

    def forward(self, x):
        # GSPMD: full-table gather; XLA partitions the take over the vocab
        # shards and psums the masked partials — the reference's
        # c_lookup_table + allreduce fused by the compiler.
        return F.embedding(_to_mesh(x), self.weight, None, False)


class ColumnParallelLinear(Layer):
    """Reference: mp_layers.py:336 — weight [in, out] split on out."""

    def __init__(self, in_features, out_features, weight_attr=None,
                 has_bias=None, gather_output=True,
                 fuse_matmul_bias=False, mp_group=None, name=None):
        super().__init__()
        from ...base.topology import get_hcg

        hcg = get_hcg()
        self.group = mp_group or (hcg.get_model_parallel_group() if hcg else None)
        self.world_size = self.group.nranks if self.group else 1
        self.gather_output = gather_output
        self.in_features = in_features
        self.out_features = out_features
        assert out_features % max(self.world_size, 1) == 0, (
            f"out_features {out_features} not divisible by mp {self.world_size}")
        self.output_size_per_partition = out_features // max(self.world_size, 1)
        from .....nn.param_attr import ParamAttr

        self.weight = self.create_parameter(
            [in_features, out_features], ParamAttr._to_attr(weight_attr),
            self._dtype)
        self.weight.is_distributed = self.world_size > 1
        _shard_param(self.weight, P(None, "mp"))
        has_bias = True if has_bias is None else has_bias
        if has_bias:
            self.bias = self.create_parameter(
                [out_features], ParamAttr._to_attr(None), self._dtype,
                is_bias=True)
            self.bias.is_distributed = self.world_size > 1
            _shard_param(self.bias, P("mp"))
        else:
            self.bias = None

    def forward(self, x):
        x = mp_ops._c_identity(_to_mesh(x), group=self.group)
        out = F.linear(x, self.weight, self.bias)
        if self.gather_output:
            out = mp_ops._c_concat(out, group=self.group)
        return out


class RowParallelLinear(Layer):
    """Reference: mp_layers.py:543 — weight [in, out] split on in."""

    def __init__(self, in_features, out_features, weight_attr=None,
                 has_bias=True, input_is_parallel=False,
                 fuse_matmul_bias=False, mp_group=None, name=None):
        super().__init__()
        from ...base.topology import get_hcg

        hcg = get_hcg()
        self.group = mp_group or (hcg.get_model_parallel_group() if hcg else None)
        self.world_size = self.group.nranks if self.group else 1
        self.input_is_parallel = input_is_parallel
        self.in_features = in_features
        self.out_features = out_features
        assert in_features % max(self.world_size, 1) == 0, (
            f"in_features {in_features} not divisible by mp {self.world_size}")
        self.input_size_per_partition = in_features // max(self.world_size, 1)
        from .....nn.param_attr import ParamAttr

        self.weight = self.create_parameter(
            [in_features, out_features], ParamAttr._to_attr(weight_attr),
            self._dtype)
        self.weight.is_distributed = self.world_size > 1
        _shard_param(self.weight, P("mp", None))
        if has_bias:
            # bias is NOT sharded (applied after the allreduce)
            self.bias = self.create_parameter(
                [out_features], ParamAttr._to_attr(None), self._dtype,
                is_bias=True)
        else:
            self.bias = None

    def forward(self, x):
        x = _to_mesh(x)
        if not self.input_is_parallel:
            x = mp_ops._c_split(x, group=self.group)
        out = F.linear(x, self.weight, None)
        out = mp_ops._mp_allreduce(out, group=self.group)
        if self.bias is not None:
            out = out + self.bias
        return out


class ParallelCrossEntropy(Layer):
    """Reference: mp_layers.py:744."""

    def __init__(self, mp_group=None, name=None, ignore_index=-100):
        super().__init__()
        from ...base.topology import get_hcg

        hcg = get_hcg()
        self.group = mp_group or (hcg.get_model_parallel_group() if hcg else None)
        self.ignore_index = ignore_index

    def forward(self, input, label):
        return mp_ops._c_softmax_with_cross_entropy(
            input, label, group=self.group, ignore_index=self.ignore_index)

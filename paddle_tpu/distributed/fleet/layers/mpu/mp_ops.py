"""Low-level model-parallel ops.

Reference: python/paddle/distributed/fleet/layers/mpu/mp_ops.py —
`_c_identity` (fwd identity / bwd allreduce), `_mp_allreduce` (fwd allreduce /
bwd identity), `_c_split`, `_c_concat`: the autograd-paired collectives that
make Megatron TP correct.

TPU-native: inside a shard_map trace they emit `lax` collectives whose
transposes ARE the paired backward ops (psum ↔ identity is exactly what
jax.grad derives); in GSPMD (global-array) mode they are sharding-constraint
annotations and XLA inserts the collectives. Both paths share the Group/axis
binding from `collective.py`.
"""
from __future__ import annotations

import jax
import jax.numpy as jnp
from jax import lax
from jax.sharding import NamedSharding, PartitionSpec as P

from .....core.tensor import Tensor
from .... import collective as coll


def _axis_of(group):
    g = group or coll.get_group(0)
    return g.axis_name if g is not None else None


def _unwrap(x):
    return x._data if isinstance(x, Tensor) else x


def _rewrap(arr, like):
    if isinstance(like, Tensor):
        out = Tensor(arr)
        out.stop_gradient = like.stop_gradient
        return out
    return arr


def _in_axis_trace(x, axis):
    return (isinstance(x, jax.core.Tracer) and axis is not None
            and coll._axis_in_scope(axis))


from functools import partial


@partial(jax.custom_vjp, nondiff_argnums=(1,))
def _identity_fwd_allreduce_bwd(x, axis):
    return x


def _ifab_fwd(x, axis):
    return x, None


def _ifab_bwd(axis, _res, g):
    return (lax.psum(g, axis),)


_identity_fwd_allreduce_bwd.defvjp(_ifab_fwd, _ifab_bwd)


def _c_identity(tensor, group=None, skip_c_identity_dynamic=False):
    """Forward identity, backward allreduce over the mp group (column-parallel
    input). Reference: mp_ops.py _c_identity."""
    axis = _axis_of(group)
    x = _unwrap(tensor)
    if _in_axis_trace(x, axis):
        return _rewrap(_identity_fwd_allreduce_bwd(x, axis), tensor)
    return tensor  # GSPMD/eager: XLA derives the transpose itself


def _mp_allreduce(tensor, op=coll.ReduceOp.SUM, group=None,
                  use_calc_stream=True, use_model_parallel=True):
    """Forward allreduce, backward identity (row-parallel output)."""
    axis = _axis_of(group)
    x = _unwrap(tensor)
    if _in_axis_trace(x, axis):
        return _rewrap(lax.psum(x, axis), tensor)
    # GSPMD/global-array mode: the sharded matmul already produced the full
    # contraction (XLA inserted the all-reduce); a second reduction would be
    # wrong math. Identity here, psum only on per-shard traces.
    return tensor


def _c_split(tensor, group=None):
    """Split the last dim, keep this rank's chunk (per-shard traces only;
    in global-array mode tensors are logically full → identity)."""
    axis = _axis_of(group)
    x = _unwrap(tensor)
    if _in_axis_trace(x, axis):
        n = lax.axis_size(axis)
        i = lax.axis_index(axis)
        size = x.shape[-1] // n
        return _rewrap(lax.dynamic_slice_in_dim(x, i * size, size, -1), tensor)
    return tensor


def _c_concat(tensor, group=None):
    """All-gather chunks along the last dim."""
    axis = _axis_of(group)
    x = _unwrap(tensor)
    if _in_axis_trace(x, axis):
        return _rewrap(lax.all_gather(x, axis, axis=x.ndim - 1, tiled=True),
                       tensor)
    return tensor


def _c_lookup_table(table, index, start_index=0, vocab_size=-1):
    """Vocab-shard-local embedding lookup with masked out-of-range rows."""
    t = _unwrap(table)
    idx = _unwrap(index)
    vloc = t.shape[0]
    local = idx - start_index
    ok = (local >= 0) & (local < vloc)
    safe = jnp.clip(local, 0, vloc - 1)
    emb = jnp.take(t, safe, axis=0)
    return _rewrap(jnp.where(ok[..., None], emb, 0), table)


def _c_softmax_with_cross_entropy(logits, label, group=None,
                                  return_softmax=False,
                                  ignore_index: int = -100):
    """Vocab-parallel softmax CE (ParallelCrossEntropy's kernel).

    In-trace with the mp axis bound: the distributed max/sum reduction runs
    over the vocab shards (mirrors _vp_cross_entropy in distributed.hybrid).
    GSPMD mode: plain CE; XLA partitions the softmax over the sharded dim.
    """
    axis = _axis_of(group)
    x = _unwrap(logits)
    y = _unwrap(label)
    if y.ndim == x.ndim:
        y = y[..., 0]
    if _in_axis_trace(x, axis):
        vloc = x.shape[-1]
        start = lax.axis_index(axis) * vloc
        gmax = lax.all_gather(jnp.max(x, axis=-1), axis)
        lmax = lax.stop_gradient(jnp.max(gmax, axis=0))
        shifted = x - lmax[..., None]
        sumexp = lax.psum(jnp.sum(jnp.exp(shifted), axis=-1), axis)
        local_t = y - start
        ok = (local_t >= 0) & (local_t < vloc)
        safe = jnp.clip(local_t, 0, vloc - 1)
        true_shift = jnp.take_along_axis(shifted, safe[..., None], axis=-1)[..., 0]
        true_shift = lax.psum(jnp.where(ok, true_shift, 0.0), axis)
        loss = jnp.log(sumexp) - true_shift
    else:
        lmax = jax.lax.stop_gradient(jnp.max(x, axis=-1, keepdims=True))
        shifted = x - lmax
        lse = jnp.log(jnp.sum(jnp.exp(shifted), axis=-1))
        true = jnp.take_along_axis(shifted, y[..., None], axis=-1)[..., 0]
        loss = lse - true
    loss = jnp.where(y == ignore_index, 0.0, loss)[..., None]
    out = _rewrap(loss, logits)
    if return_softmax:
        sm = jax.nn.softmax(x, axis=-1)
        return out, _rewrap(sm, logits)
    return out

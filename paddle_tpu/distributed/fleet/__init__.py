"""fleet — hybrid-parallel training facade (SURVEY.md §2.5).

Reference: python/paddle/distributed/fleet/__init__.py. The module-level
functions delegate to the Fleet singleton, matching `fleet.init(...)` usage.
"""
from .base.distributed_strategy import DistributedStrategy  # noqa: F401
from .base.topology import (  # noqa: F401
    CommunicateTopology,
    HybridCommunicateGroup,
    get_hcg,
)
from .fleet import Fleet, fleet as _fleet_singleton  # noqa: F401

init = _fleet_singleton.init
is_first_worker = _fleet_singleton.is_first_worker
worker_index = _fleet_singleton.worker_index
worker_num = _fleet_singleton.worker_num
worker_rank = _fleet_singleton.worker_index
distributed_model = _fleet_singleton.distributed_model
distributed_optimizer = _fleet_singleton.distributed_optimizer
get_hybrid_communicate_group = _fleet_singleton.get_hybrid_communicate_group
barrier_worker = _fleet_singleton.barrier_worker
is_server = _fleet_singleton.is_server
is_worker = _fleet_singleton.is_worker
init_worker = _fleet_singleton.init_worker
init_server = _fleet_singleton.init_server
run_server = _fleet_singleton.run_server
stop_worker = _fleet_singleton.stop_worker


def __getattr__(name):
    import importlib

    if name in ("meta_parallel", "meta_optimizers", "utils", "layers", "base"):
        mod = importlib.import_module(f".{name}", __name__)
        globals()[name] = mod
        return mod
    raise AttributeError(f"module {__name__!r} has no attribute {name!r}")

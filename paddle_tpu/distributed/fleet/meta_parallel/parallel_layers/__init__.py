from .pp_layers import (  # noqa: F401
    LayerDesc,
    PipelineLayer,
    SegmentLayers,
    SharedLayerDesc,
)

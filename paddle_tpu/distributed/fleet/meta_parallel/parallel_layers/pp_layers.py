"""Pipeline layer description + segmentation.

Reference: python/paddle/distributed/fleet/meta_parallel/parallel_layers/
pp_layers.py — `LayerDesc` (:57) defers construction, `SegmentLayers` (:93)
splits the layer list into stages (uniform or by-flops), `PipelineLayer`
(:258) instantiates only this stage's segment and wires shared embeddings.

TPU-native: the whole logical model lives on every *controller* (JAX is
single-controller SPMD); stages are realized as the leading 'pp' axis of
stage-stacked weights inside the compiled train step (distributed.hybrid).
`PipelineLayer` therefore instantiates ALL segments, tags each sublayer with
its stage id, and exposes the per-stage slices for the engine. API parity —
`get_stage_layers`, `segment`, shared-weight registration — is preserved.
"""
from __future__ import annotations

from typing import Any, Callable, Dict, List, Optional, Sequence

import numpy as np

from .....nn.layer.layers import Layer
from .....nn.layer.container import LayerList


class LayerDesc:
    """Reference: pp_layers.py:57."""

    def __init__(self, layer_func: Callable, *inputs, **kwargs):
        self.layer_func = layer_func
        self.inputs = inputs
        self.kwargs = kwargs
        if not issubclass(layer_func, Layer):
            raise TypeError("The input(layer_func) should be a derived class of Layer.")

    def build_layer(self) -> Layer:
        return self.layer_func(*self.inputs, **self.kwargs)

    def __repr__(self):
        return f"LayerDesc({self.layer_func.__name__})"


class SharedLayerDesc(LayerDesc):
    """Reference: pp_layers.py SharedLayerDesc — layers shared across stages
    (tied embeddings). On TPU the weight is one logical array replicated (or
    sharded) over 'pp' by GSPMD, so 'sharing' is simply reusing the object."""

    def __init__(self, key, layer_func, forward_func=None,
                 shared_weight_attr="weight", *inputs, **kwargs):
        super().__init__(layer_func, *inputs, **kwargs)
        self.layer_name = key
        self.forward_func = forward_func
        self.shared_weight_attr = shared_weight_attr


class SegmentLayers:
    """Reference: pp_layers.py:93 — now a thin front end over
    :mod:`....pipeline.partition`, which owns uniform / ``layer:<Class>`` /
    parameter- and FLOP-balanced segmentation."""

    def __init__(self, layers_desc, num_parts: int, method: str = "uniform"):
        self._layers_desc = layers_desc
        self.method = method
        self.num_parts = num_parts
        self.num_items = len(layers_desc)
        assert self.num_items >= self.num_parts, (
            "layer number should be greater than number of segments")

    def do_segment(self) -> List[int]:
        from ....pipeline import partition

        return partition.segment(self._layers_desc, self.num_parts,
                                 self.method)

    @staticmethod
    def uniform(num_items: int, num_parts: int) -> List[int]:
        from ....pipeline import partition

        return partition.uniform(num_items, num_parts)


class PipelineLayer(Layer):
    """Reference: pp_layers.py:258."""

    def __init__(self, layers, num_stages: Optional[int] = None,
                 topology=None, loss_fn=None, seg_method: str = "uniform",
                 recompute_interval: int = 0, num_virtual_pipeline_stages=None,
                 **kwargs):
        super().__init__()
        from .....core import flags
        from .... import pipeline  # noqa: F401 — registers FLAGS_pp_*
        from ...base.topology import get_hcg

        self._loss_fn = loss_fn
        self._topo = topology
        self._recompute_interval = recompute_interval
        hcg = get_hcg()
        if num_stages is None:
            num_stages = (hcg.get_pipe_parallel_world_size() if hcg else 1)
        self._num_stages = max(1, num_stages)
        self._stage_id = hcg.get_stage_id() if hcg else 0
        # interleaved VPP (reference pipeline_parallel.py:1174): segment into
        # num_stages * V chunks; chunk v of device d is GLOBAL stage
        # v * num_stages + d, so each device group interleaves V chunks
        if num_virtual_pipeline_stages is None:
            num_virtual_pipeline_stages = int(
                flags.flag_value("pp_virtual_degree") or 1)
        self._num_virtual = max(1, int(num_virtual_pipeline_stages or 1))

        self._layers_desc = list(layers)
        seg = SegmentLayers(self._layers_desc,
                            self._num_stages * self._num_virtual, seg_method)
        self.segment_parts = seg.do_segment()

        # instantiate ALL stages (single-controller); record stage of each
        self._shared = {}
        built: List[Layer] = []
        self._stage_of: List[int] = []
        for stage in range(self._num_stages * self._num_virtual):
            lo, hi = self.segment_parts[stage], self.segment_parts[stage + 1]
            for i in range(lo, hi):
                d = self._layers_desc[i]
                if isinstance(d, SharedLayerDesc):
                    if d.layer_name not in self._shared:
                        self._shared[d.layer_name] = d.build_layer()
                    layer = self._shared[d.layer_name]
                elif isinstance(d, LayerDesc):
                    layer = d.build_layer()
                elif isinstance(d, Layer):
                    layer = d
                elif callable(d):
                    layer = d
                else:
                    raise TypeError(f"bad layer desc {d!r}")
                built.append(layer)
                self._stage_of.append(stage)
        self.run_function = built
        self._sublayer_list = LayerList(
            [l for l in built if isinstance(l, Layer)])

    # ------------------------------------------------------------------
    def get_num_stages(self) -> int:
        """Number of GLOBAL stages (physical stages x virtual chunks)."""
        return self._num_stages * self._num_virtual

    def get_num_physical_stages(self) -> int:
        return self._num_stages

    def get_num_virtual_stages(self) -> int:
        return self._num_virtual

    def device_group_of_stage(self, global_stage: int) -> int:
        """Interleave placement: global stage g lives on device group
        g % num_physical (chunk g // num_physical of that group)."""
        return global_stage % self._num_stages

    def get_stage_from_index(self, layer_idx: int) -> int:
        return self._stage_of[layer_idx]

    def get_stage_layers(self, stage: Optional[int] = None) -> List:
        stage = self._stage_id if stage is None else stage
        return [l for l, s in zip(self.run_function, self._stage_of)
                if s == stage]

    def forward(self, x, **kwargs):
        for fn in self.run_function:
            x = fn(x)
        return x

    def loss(self, output, label):
        return self._loss_fn(output, label) if self._loss_fn else output

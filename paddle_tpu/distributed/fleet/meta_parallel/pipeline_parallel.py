"""PipelineParallel wrapper — microbatched train_batch.

Reference: fleet/meta_parallel/pipeline_parallel.py:255 —
`train_batch` (:820) drives the 1F1B schedule (`forward_backward_pipeline`
:575) with NCCL p2p sends between per-rank stage submodels.

TPU-native: two execution tiers.
- This wrapper (API parity tier): a host-driven microbatch loop — forward +
  backward per microbatch with gradient accumulation, then one fused grad
  sync. On a mesh, stage weights are pp-sharded by GSPMD and XLA pipelines
  collectives with compute; there is no per-rank p2p to hand-schedule since
  the controller sees global arrays (SURVEY.md §7 "hard parts" option (a)).
- The performance tier is the fully-compiled 1F1B/GPipe rotation in
  `distributed.hybrid.make_train_step` (ppermute inside scan — option (b));
  `to_compiled_step()` hands a PipelineLayer model off to it.
"""
from __future__ import annotations

from typing import Optional

import numpy as np

from ....core.tensor import Tensor
from .meta_parallel_base import MetaParallelBase
from .parallel_layers.pp_layers import PipelineLayer


class PipelineParallel(MetaParallelBase):
    """Reference: pipeline_parallel.py:255."""

    def _prepare_for_model(self):
        self.micro_batch_size = int(
            (self._strategy.pipeline_configs or {}).get("micro_batch_size", 1))
        self.accumulate_steps = int(
            (self._strategy.pipeline_configs or {}).get("accumulate_steps", 1))
        self.total_loss = None
        hcg = self._hcg
        self.num_stages = (hcg.get_pipe_parallel_world_size() if hcg else 1)
        self.stage_id = hcg.get_stage_id() if hcg else 0

    def is_pipeline_first_stage(self) -> bool:
        return self.stage_id == 0

    def is_pipeline_last_stage(self) -> bool:
        return self.stage_id == self.num_stages - 1

    def _split_micro(self, data):
        if isinstance(data, (tuple, list)):
            parts = [self._split_micro(d) for d in data]
            return list(zip(*parts))
        arr = data._data if isinstance(data, Tensor) else np.asarray(data)
        n = self.accumulate_steps
        b = arr.shape[0]
        assert b % n == 0, f"batch {b} not divisible by accumulate_steps {n}"
        mb = b // n
        return [Tensor(arr[i * mb:(i + 1) * mb]) for i in range(n)]

    def forward_backward_pipeline(self, data, scaler=None):
        """Microbatch loop with grad accumulation (reference :575)."""
        inputs, labels = data
        mb_inputs = self._split_micro(inputs)
        mb_labels = self._split_micro(labels)
        total = None
        model = self._layers
        loss_fn = getattr(model, "_loss_fn", None)
        for x, y in zip(mb_inputs, mb_labels):
            out = model(x)
            if loss_fn is not None:
                loss = loss_fn(out, y)
            else:
                loss = out
            if hasattr(loss, "mean") and getattr(loss, "ndim", 0):
                loss = loss.mean()
            scaled = loss.scale(1.0 / self.accumulate_steps) \
                if hasattr(loss, "scale") else loss / self.accumulate_steps
            if scaler is not None:
                scaler.scale(scaled).backward()
            else:
                scaled.backward()
            d = loss.detach() if hasattr(loss, "detach") else loss
            total = d if total is None else total + d
        self.total_loss = total
        return total / self.accumulate_steps

    def train_batch(self, data, optimizer, lr_scheduler=None, scaler=None):
        """Reference: pipeline_parallel.py:820."""
        self._layers.train()
        loss = self.forward_backward_pipeline(data, scaler)
        if scaler is not None:
            scaler.step(optimizer)
            scaler.update()
        else:
            optimizer.step()
        optimizer.clear_grad()
        if lr_scheduler is not None:
            lr_scheduler.step()
        return loss

    def eval_batch(self, data, compute_loss: bool = True):
        self._layers.eval()
        inputs, labels = data
        from ....ops.dispatch import no_grad

        with no_grad():
            out = self._layers(inputs)
            loss_fn = getattr(self._layers, "_loss_fn", None)
            if compute_loss and loss_fn is not None:
                return loss_fn(out, labels)
        return out

    def to_compiled_step(self, *args, **kwargs):
        """Hand off to the compiled whole-step engine (distributed.hybrid)."""
        from ... import hybrid

        return hybrid.make_train_step(*args, **kwargs)

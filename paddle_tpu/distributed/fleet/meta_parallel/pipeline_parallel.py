"""PipelineParallel wrapper — staged 1F1B/GPipe execution of PipelineLayer.

Reference: fleet/meta_parallel/pipeline_parallel.py:255 —
`train_batch` (:820) drives the 1F1B schedule (`forward_backward_pipeline`
:575) with NCCL p2p sends between per-rank stage submodels.

TPU-native: `PipelineEngine` (pp_schedule.py) consumes the SegmentLayers
partition, commits each stage's weights to that stage's devices, and drives
per-stage compiled executables in 1F1B (default) or GPipe order with
device-to-device activation transfer — see pp_schedule.py for the design.
With one stage (pp=1) the schedule degenerates to plain microbatch gradient
accumulation, which is run directly. The fully-compiled whole-step engine
(distributed.hybrid) remains the perf tier for homogeneous stacks
(`to_compiled_step`).
"""
from __future__ import annotations

from typing import Optional

import numpy as np

from ....core.tensor import Tensor
from .meta_parallel_base import MetaParallelBase
from .parallel_layers.pp_layers import PipelineLayer


class PipelineParallel(MetaParallelBase):
    """Reference: pipeline_parallel.py:255."""

    def _prepare_for_model(self):
        from ....core import flags
        from ... import pipeline  # noqa: F401 — registers FLAGS_pp_*

        cfgs = self._strategy.pipeline_configs or {}
        # precedence: explicit pipeline_configs > FLAGS_pp_* defaults (the
        # MIGRATION.md mapping of the reference knobs)
        self.micro_batch_size = int(
            cfgs.get("micro_batch_size",
                     flags.flag_value("pp_micro_batch_size") or 1) or 1)
        acc = cfgs.get("accumulate_steps")
        if acc is None:
            acc = int(flags.flag_value("pp_accumulate_steps") or 1)
        self.accumulate_steps = int(acc)
        self.schedule = str(cfgs.get("schedule_mode",
                                     flags.flag_value("pp_schedule")
                                     or "1F1B"))
        self.total_loss = None
        hcg = self._hcg
        self.num_stages = (hcg.get_pipe_parallel_world_size() if hcg else 1)
        self.stage_id = hcg.get_stage_id() if hcg else 0
        self._engine = None

    def is_pipeline_first_stage(self) -> bool:
        return self.stage_id == 0

    def is_pipeline_last_stage(self) -> bool:
        return self.stage_id == self.num_stages - 1

    # ------------------------------------------------------------------
    def _stage_devices(self):
        """Map pipeline stages to device groups. With an hcg topology, stage
        s gets the devices of every rank whose 'pipe' coordinate is s (their
        other axes form the stage's dp submesh); without one, an even split
        of the local devices."""
        import jax

        if self._hcg is None:
            return None  # engine default: even split of local devices
        devs = jax.devices()
        topo = self._hcg.topology()
        if topo.world_size() > len(devs):
            raise RuntimeError(
                f"hybrid topology world size {topo.world_size()} exceeds the "
                f"{len(devs)} available devices; shrink the parallel degrees")
        groups = {s: [] for s in range(self.num_stages)}
        for r in range(topo.world_size()):
            coord = topo.get_coord(r)  # dict keyed by axis name
            stage = coord.get("pp", coord.get("pipe", 0))
            groups[stage].append(devs[r])
        return [groups[s] for s in range(self.num_stages)]

    def _get_engine(self):
        if self._engine is None:
            from ...pipeline.runtime import PipelineEngine

            if not isinstance(self._layers, PipelineLayer):
                raise TypeError(
                    "pipeline parallelism (pp>1) requires a PipelineLayer "
                    f"model, got {type(self._layers).__name__}")
            self._engine = PipelineEngine(
                self._layers, self.accumulate_steps,
                stage_devices=self._stage_devices(),
                schedule=self.schedule)
        return self._engine

    # ------------------------------------------------------------------
    def _accumulate_only(self, data, scaler=None):
        """pp=1 degenerate schedule: microbatch loop with grad accumulation."""
        inputs, labels = data
        mb_inputs = self._split_micro(inputs)
        mb_labels = self._split_micro(labels)
        total = None
        model = self._layers
        loss_fn = getattr(model, "_loss_fn", None)
        for x, y in zip(mb_inputs, mb_labels):
            out = model(x)
            loss = loss_fn(out, y) if loss_fn is not None else out
            if hasattr(loss, "mean") and getattr(loss, "ndim", 0):
                loss = loss.mean()
            scaled = loss / self.accumulate_steps
            if scaler is not None:
                scaler.scale(scaled).backward()
            else:
                scaled.backward()
            d = loss.detach() if hasattr(loss, "detach") else loss
            total = d if total is None else total + d
        return total / self.accumulate_steps

    def _split_micro(self, data):
        if isinstance(data, (tuple, list)):
            parts = [self._split_micro(d) for d in data]
            return list(zip(*parts))
        arr = data._data if isinstance(data, Tensor) else np.asarray(data)
        n = self.accumulate_steps
        b = arr.shape[0]
        assert b % n == 0, f"batch {b} not divisible by accumulate_steps {n}"
        mb = b // n
        return [Tensor(arr[i * mb:(i + 1) * mb]) for i in range(n)]

    def forward_backward_pipeline(self, data, scaler=None):
        """1F1B/GPipe staged schedule over the pp device groups (reference
        :575); grad accumulation only in the pp=1 degenerate case."""
        inputs, labels = data
        # reference micro_batch_size semantics: when accumulate_steps was
        # not configured, the microbatch count is batch // micro_batch_size
        if self.accumulate_steps == 1 and self.micro_batch_size > 1:
            b = int(getattr(inputs, "shape", [0])[0])
            if b and b % self.micro_batch_size == 0:
                derived = b // self.micro_batch_size
                if derived > 1:
                    self.accumulate_steps = derived
                    self._engine = None
        if self.num_stages <= 1:
            loss = self._accumulate_only(data, scaler)
            self.total_loss = loss
            return loss
        scale = 1.0
        if (scaler is not None and hasattr(scaler, "_scale")
                and getattr(scaler, "is_enable", lambda: True)()):
            s = scaler._scale
            scale = float(s.numpy()) if hasattr(s, "numpy") else float(s)
        loss = self._get_engine().run(inputs, labels, train=True,
                                      loss_scale=scale)
        self.total_loss = loss
        return loss

    def train_batch(self, data, optimizer, lr_scheduler=None, scaler=None):
        """Reference: pipeline_parallel.py:820."""
        self._layers.train()
        loss = self.forward_backward_pipeline(data, scaler)
        if scaler is not None:
            scaler.step(optimizer)
            scaler.update()
        else:
            optimizer.step()
        optimizer.clear_grad()
        if lr_scheduler is not None:
            lr_scheduler.step()
        return loss

    def eval_batch(self, data, compute_loss: bool = True):
        self._layers.eval()
        inputs, labels = data
        from ....ops.dispatch import no_grad

        with no_grad():
            out = self._layers(inputs)
            loss_fn = getattr(self._layers, "_loss_fn", None)
            if compute_loss and loss_fn is not None:
                return loss_fn(out, labels)
        return out

    def to_compiled_step(self, *args, **kwargs):
        """Hand off to the compiled whole-step engine (distributed.hybrid)."""
        from ... import hybrid

        return hybrid.make_train_step(*args, **kwargs)

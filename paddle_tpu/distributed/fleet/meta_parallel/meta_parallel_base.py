"""Base for meta-parallel wrappers.

Reference: fleet/meta_parallel/meta_parallel_base.py — wraps a Layer,
broadcasts/prepares params for its parallel dimension, forwards calls.
"""
from __future__ import annotations

from ....nn.layer.layers import Layer


class MetaParallelBase(Layer):
    def __init__(self, layers: Layer, hcg, strategy):
        super().__init__()
        self._layers = layers
        self._hcg = hcg
        self._strategy = strategy
        self._prepare_for_model()

    def _prepare_for_model(self):
        pass

    def forward(self, *inputs, **kwargs):
        return self._layers(*inputs, **kwargs)

    # Layer protocol passthrough
    def parameters(self, include_sublayers=True):
        return self._layers.parameters(include_sublayers)

    def named_parameters(self, prefix="", include_sublayers=True):
        return self._layers.named_parameters(prefix, include_sublayers)

    def state_dict(self, *a, **k):
        return self._layers.state_dict(*a, **k)

    def set_state_dict(self, state_dict, *a, **k):
        return self._layers.set_state_dict(state_dict, *a, **k)

    def train(self):
        self._layers.train()
        return super().train()

    def eval(self):
        self._layers.eval()
        return super().eval()

"""Meta-parallel model wrappers.

Reference: python/paddle/distributed/fleet/meta_parallel/ —
`TensorParallel` (tensor_parallel.py:28), `PipelineParallel`
(pipeline_parallel.py:255), `SegmentParallel` (segment_parallel.py:26).
"""
from .meta_parallel_base import MetaParallelBase  # noqa: F401
from .parallel_layers.pp_layers import (  # noqa: F401
    LayerDesc,
    PipelineLayer,
    SegmentLayers,
    SharedLayerDesc,
)
from .pipeline_parallel import PipelineParallel  # noqa: F401
from .tensor_parallel import SegmentParallel, TensorParallel  # noqa: F401
from ..layers.mpu.mp_layers import (  # noqa: F401
    ColumnParallelLinear,
    ParallelCrossEntropy,
    RowParallelLinear,
    VocabParallelEmbedding,
)
from ..layers.mpu.random import RNGStatesTracker, get_rng_state_tracker  # noqa: F401

"""TensorParallel / SegmentParallel wrappers.

Reference: fleet/meta_parallel/tensor_parallel.py:28 — broadcasts the
non-mp-sharded params across the mp group and input data across ranks;
segment_parallel.py:26 — same for the sep dimension.

TPU-native: single-controller global arrays are never rank-divergent, so the
broadcast is only needed on true multi-host eager setups; the wrapper's real
job here is laying params out over the mesh (is_distributed leaves stay
sharded, the rest replicated) which GSPMD consumes.
"""
from __future__ import annotations

from .meta_parallel_base import MetaParallelBase
from ..layers.mpu import mp_layers  # ensures sharded-layer registry import
from ...parallel import sync_params_buffers


class TensorParallel(MetaParallelBase):
    """Reference: tensor_parallel.py:28."""

    def _prepare_for_model(self):
        hcg = self._hcg
        if hcg is None:
            return
        mp_group = hcg.get_model_parallel_group()
        if mp_group is not None and mp_group.nranks > 1:
            # broadcast NON-distributed params over the mp group so replicas
            # agree (reference: broadcast_mp_parameters)
            for p in self._layers.parameters():
                if not getattr(p, "is_distributed", False):
                    from ... import collective as coll

                    coll.broadcast(p, src=mp_group.ranks[0], group=mp_group)


class SegmentParallel(MetaParallelBase):
    """Reference: segment_parallel.py:26 — sep ranks hold identical params;
    attention all-to-all over the sep axis is done by model code."""

    def _prepare_for_model(self):
        hcg = self._hcg
        if hcg is None:
            return
        sep_group = hcg.get_sep_parallel_group()
        if sep_group is not None and sep_group.nranks > 1:
            sync_params_buffers(self._layers, sep_group,
                                src_rank=sep_group.ranks[0])

"""Generic pipeline-parallel engine: per-stage executables + 1F1B/GPipe.

Reference: fleet/meta_parallel/pipeline_parallel.py:575 (1F1B
forward_backward_pipeline) and :1174 (interleaved), built on NCCL p2p between
per-rank stage submodels.

TPU-native redesign (SURVEY.md §7 "hard parts", option (a)): JAX is
single-controller, so instead of per-rank processes each owning a stage, the
engine

- consumes the `SegmentLayers` partition of a `PipelineLayer` and
  functionalizes each stage's layer list into a pure jax function
  (params/buffers in → activations/new buffers out, the StaticFunction swap
  pattern from jit/api.py);
- commits each stage's parameters to THAT STAGE'S devices (a per-stage
  submesh; extra devices per stage form a data-parallel axis), so weights and
  optimizer states are pp-partitioned exactly like the reference's per-rank
  placement;
- moves microbatch activations/cotangents between consecutive stages with
  `jax.device_put` onto the next stage's sharding — the PJRT device-to-device
  copy that plays the role of `p2p_communication.py` send/recv;
- dispatches per-stage fwd/bwd executables in 1F1B (or GPipe F-then-B) order.
  Dispatch is async: stage k's work for microbatch m overlaps stage k+1's
  work for microbatch m-1 on disjoint devices, which is exactly the pipeline
  bubble structure of the reference schedule;
- backward recomputes the stage forward under `jax.vjp` (per-stage
  rematerialization — the activation-memory behavior flash of the reference's
  `recompute_interval`), accumulates param grads on the stage's devices, and
  chains input cotangents to the previous stage.

The fully-compiled single-executable path (GPipe via ppermute-in-scan) lives
in `distributed.hybrid` and remains the perf tier for homogeneous stacks.
"""
from __future__ import annotations

from typing import Any, Callable, Dict, List, Optional, Sequence, Tuple

import jax
import jax.numpy as jnp
import numpy as np
from jax.sharding import Mesh, NamedSharding, PartitionSpec as P

from ....core import rng
from ....core.tensor import Tensor
from ....nn.layer.layers import Layer


def _collect_state(layers: Sequence[Any]) -> Tuple[List, List]:
    params, buffers = [], []
    for l in layers:
        if isinstance(l, Layer):
            params.extend(p for _, p in l.named_parameters())
            buffers.extend(b for _, b in l.named_buffers() if b is not None)
    return params, buffers


class _Stage:
    """One pipeline stage: functionalized forward + device placement."""

    def __init__(self, layers: Sequence[Any], device_list: List, *,
                 loss_fn: Optional[Callable] = None):
        self.layers = list(layers)
        self.params, self.buffers = _collect_state(self.layers)
        self.loss_fn = loss_fn  # set only on the last stage
        self.mesh = Mesh(np.asarray(device_list), ("dp",))
        self.repl = NamedSharding(self.mesh, P())
        self.batch_sharding = NamedSharding(self.mesh, P("dp"))
        self.dp = len(device_list)
        self._exec: Dict[Any, Tuple] = {}

    # -- placement ---------------------------------------------------------
    def commit(self):
        """Move this stage's params/buffers onto its devices (replicated over
        the stage's dp submesh)."""
        for p in self.params + self.buffers:
            p._data = jax.device_put(p._data, self.repl)

    def put_input(self, arr):
        if arr.ndim and self.dp > 1 and arr.shape[0] % self.dp == 0:
            return jax.device_put(arr, self.batch_sharding)
        return jax.device_put(arr, self.repl)

    # -- functionalization -------------------------------------------------
    def _run_layers(self, x: Tensor) -> Tensor:
        for fn in self.layers:
            x = fn(x)
        return x

    def _kernel(self, param_arrays, buffer_arrays, x_arr, key_data, label_arr):
        """Pure stage function (the jit/api.py swap pattern)."""
        from ....ops import dispatch

        snap_p = [p._data for p in self.params]
        snap_b = [b._data for b in self.buffers]
        try:
            for p, a in zip(self.params, param_arrays):
                p._data = a
            for b, a in zip(self.buffers, buffer_arrays):
                b._data = a
            with rng.scoped_rng_key(key_data), dispatch.no_grad():
                out = self._run_layers(Tensor._from_data(x_arr))
                if self.loss_fn is not None:
                    loss = self.loss_fn(out, Tensor._from_data(label_arr))
                    if getattr(loss, "ndim", 0):
                        loss = loss.mean()
                    out = loss
            new_buffers = [b._data for b in self.buffers]
            return out._data, new_buffers
        finally:
            for p, a in zip(self.params, snap_p):
                p._data = a
            for b, a in zip(self.buffers, snap_b):
                b._data = a

    # -- executables (cached per input signature + train mode) -------------
    def _sig(self, x_arr, label_arr, train):
        lbl = None if label_arr is None else (label_arr.shape,
                                              str(label_arr.dtype))
        return (x_arr.shape, str(x_arr.dtype), lbl, train)

    def _build(self, x_arr, label_arr, train):
        n_p = len(self.params)

        def fwd_fn(pa, ba, x, key, lbl):
            return self._kernel(pa, ba, x, key, lbl)

        grad_shardings = [self.repl] * n_p
        x_sharding = getattr(x_arr, "sharding", self.repl)

        def bwd_both(pa, ba, x, gy, key, lbl):
            def f(pa_, x_):
                y, _ = self._kernel(pa_, ba, x_, key, lbl)
                return y
            _, vjp = jax.vjp(f, pa, x)
            gp, gx = vjp(gy)
            return list(gp), gx

        def bwd_params(pa, ba, x, gy, key, lbl):
            def f(pa_):
                y, _ = self._kernel(pa_, ba, x, key, lbl)
                return y
            _, vjp = jax.vjp(f, pa)
            (gp,) = vjp(gy)
            return list(gp)

        def bwd_input(pa, ba, x, gy, key, lbl):
            """dx ONLY — the zero-bubble split (reference
            pipeline_zero_bubble.py ZB-H1: B is divided into input-grad and
            weight-grad phases so dw can fill the cooldown bubble). Note:
            with per-stage rematerialization the split costs one extra
            forward recompute (dx and dw each replay the stage) — the
            bubble saving pays for it at pp >= 4."""
            def f(x_):
                y, _ = self._kernel(pa, ba, x_, key, lbl)
                return y
            _, vjp = jax.vjp(f, x)
            (gx,) = vjp(gy)
            return gx

        fwd = jax.jit(fwd_fn)
        bwd_b = jax.jit(bwd_both,
                        out_shardings=(grad_shardings, x_sharding))
        bwd_p = jax.jit(bwd_params, out_shardings=grad_shardings)
        bwd_x = jax.jit(bwd_input, out_shardings=x_sharding)
        return fwd, bwd_b, bwd_p, bwd_x

    def executables(self, x_arr, label_arr, train):
        key = self._sig(x_arr, label_arr, train)
        if key not in self._exec:
            self._exec[key] = self._build(x_arr, label_arr, train)
        return self._exec[key]


# ---------------------------------------------------------------------------
# Schedules (dependency-driven dispatch)
# ---------------------------------------------------------------------------

def _stage_op_sequence(schedule: str, s: int, P_: int, M: int):
    """Per-stage op order. 1F1B: warmup fwds then alternate (the reference's
    forward_backward_pipeline:575 structure); gpipe: all F then all B;
    zbh1: 1F1B with B split into BX (input grad, critical path) and BW
    (weight grad) — BW ops are queued late so the dependency dispatcher
    slides them into slots where the stage would otherwise wait for a
    downstream cotangent (reference:
    distributed/passes/pipeline_scheduler_pass/pipeline_zero_bubble.py)."""
    if schedule == "gpipe":
        return [("F", m) for m in range(M)] + [("B", m) for m in range(M)]
    w = min(M, P_ - s - 1)
    seq = [("F", m) for m in range(w)]
    if schedule == "zbh1":
        fm, xm, wm = w, 0, 0
        while fm < M:             # steady state: F / BX pairs
            seq.append(("F", fm)); fm += 1
            seq.append(("BX", xm)); xm += 1
        while xm < M:             # cooldown: BX chain + BW bubble-fill
            seq.append(("BX", xm)); xm += 1
            if wm < xm - 1:       # keep one BW in reserve for reordering
                seq.append(("BW", wm)); wm += 1
        while wm < M:
            seq.append(("BW", wm)); wm += 1
        return seq
    fm, bm = w, 0
    while fm < M or bm < M:
        if fm < M:
            seq.append(("F", fm))
            fm += 1
        if bm < M:
            seq.append(("B", bm))
            bm += 1
    return seq


class PipelineEngine:
    """Drives a segmented PipelineLayer across per-stage device groups."""

    def __init__(self, pipe_layer, accumulate_steps: int,
                 stage_devices: Optional[List[List]] = None,
                 schedule: str = "1F1B"):
        from .parallel_layers.pp_layers import PipelineLayer

        assert isinstance(pipe_layer, PipelineLayer)
        self.model = pipe_layer
        self.M = int(accumulate_steps)
        # P = GLOBAL stages; with interleaved VPP (V chunks per device
        # group, reference pipeline_parallel.py:1174) the engine runs the
        # same dependency schedule over P_phys*V stages, with global stage g
        # placed on device group g % P_phys — chunk placement IS the
        # interleave; the dependency-driven dispatcher then overlaps each
        # group's chunks exactly like the reference's per-rank interleave.
        self.P = pipe_layer.get_num_stages()
        self.P_phys = pipe_layer.get_num_physical_stages()
        self.V = self.P // self.P_phys
        self.schedule = schedule.lower().replace("-", "").replace("_", "")
        if self.schedule in ("zb", "zerobubble", "zbh1"):
            self.schedule = "zbh1"
        if self.schedule not in ("1f1b", "gpipe", "fthenb", "interleave",
                                 "zbh1"):
            raise ValueError(f"unknown pipeline schedule {schedule!r}")
        if self.schedule == "fthenb":
            self.schedule = "gpipe"
        if self.schedule == "interleave" and self.V == 1:
            raise ValueError(
                "schedule='interleave' needs num_virtual_pipeline_stages > 1 "
                "on the PipelineLayer")
        if self.schedule == "interleave":
            self.schedule = "1f1b"  # same per-stage order over global stages
        if stage_devices is None:
            devs = jax.devices()
            per = max(1, len(devs) // self.P_phys)
            groups = [devs[d * per:(d + 1) * per]
                      for d in range(self.P_phys)]
            stage_devices = [groups[pipe_layer.device_group_of_stage(g)]
                             for g in range(self.P)]
        elif len(stage_devices) == self.P_phys and self.P != self.P_phys:
            stage_devices = [stage_devices[pipe_layer.device_group_of_stage(g)]
                             for g in range(self.P)]
        loss_fn = getattr(pipe_layer, "_loss_fn", None)
        if loss_fn is None:
            raise ValueError(
                "pipeline parallelism needs PipelineLayer(loss_fn=...): the "
                "last stage computes the loss whose cotangent seeds the "
                "backward schedule")
        self.stages = [
            _Stage(pipe_layer.get_stage_layers(s), stage_devices[s],
                   loss_fn=loss_fn if s == self.P - 1 else None)
            for s in range(self.P)
        ]
        for st in self.stages:
            st.commit()

    # ------------------------------------------------------------------
    def _split_micro(self, arr) -> List:
        b = arr.shape[0]
        assert b % self.M == 0, (
            f"batch {b} not divisible by accumulate_steps {self.M}")
        mb = b // self.M
        return [arr[i * mb:(i + 1) * mb] for i in range(self.M)]

    def run(self, inputs, labels, train: bool = True,
            loss_scale: float = 1.0):
        """One global batch: schedule M microbatches over P stages; grads are
        ACCUMULATED into each stage param's ._grad. Returns the mean loss
        (a jax scalar on the last stage's devices)."""
        P_, M = self.P, self.M
        x_arr = inputs._data if isinstance(inputs, Tensor) else jnp.asarray(inputs)
        y_arr = labels._data if isinstance(labels, Tensor) else jnp.asarray(labels)
        mb_x = self._split_micro(x_arr)
        mb_y = self._split_micro(y_arr)

        seqs = {s: list(_stage_op_sequence(self.schedule if self.schedule in
                                           ("gpipe", "zbh1") else "1f1b",
                                           s, P_, M))
            for s in range(P_)}
        done = set()
        # per-(stage, mb) saved state for backward recompute
        x_in: Dict[Tuple[int, int], Any] = {}
        buf_in: Dict[Tuple[int, int], List] = {}
        keys: Dict[Tuple[int, int], Any] = {}
        gy_buf: Dict[Tuple[int, int], Any] = {}
        gy_saved: Dict[Tuple[int, int], Any] = {}
        y_dtype: Dict[Tuple[int, int], Any] = {}
        grad_acc: List[Optional[List]] = [None] * P_
        buf_state = [[b._data for b in st.buffers] for st in self.stages]
        losses = []
        self.last_dispatch_order: List[Tuple[int, str, int]] = []

        def deps_met(s, kind, m):
            if kind == "F":
                return s == 0 or ("F", s - 1, m) in done
            if kind == "BW":
                # dw only needs this stage's saved activations + cotangent;
                # BX (the critical path) must have consumed gy first
                return ("BX", s, m) in done
            # B / BX need this stage's forward and the downstream cotangent
            ok = ("F", s, m) in done
            if s < P_ - 1:
                ok = ok and (("B", s + 1, m) in done
                             or ("BX", s + 1, m) in done)
            return ok

        def run_fwd(s, m):
            st = self.stages[s]
            if s == 0:
                x = st.put_input(mb_x[m])
            else:
                x = x_in[(s, m)]  # transferred by the producer
            lbl = st.put_input(mb_y[m]) if st.loss_fn is not None else None
            if st.loss_fn is not None:
                mb_y[m] = lbl  # reuse the transferred copy in backward
            key = jax.random.key_data(rng.next_key())
            x_in[(s, m)] = x
            buf_in[(s, m)] = buf_state[s]
            keys[(s, m)] = key
            fwd, _, _, _ = st.executables(x, lbl, train)
            y, new_buf = fwd(list(p._data for p in st.params),
                             buf_state[s], x, key, lbl)
            buf_state[s] = new_buf
            y_dtype[(s, m)] = y.dtype
            if st.loss_fn is not None:
                losses.append(y)
            elif s + 1 < P_:
                x_in[(s + 1, m)] = self.stages[s + 1].put_input(y)
            return y

        def _gy_of(s, m):
            st = self.stages[s]
            if st.loss_fn is not None:
                return jnp.asarray(loss_scale / M, y_dtype[(s, m)])
            return gy_buf[(s, m)]

        def run_bwd(s, m):
            """Monolithic B (1F1B/GPipe): dx + dw in one recompute."""
            st = self.stages[s]
            x = x_in.pop((s, m))
            bufs = buf_in.pop((s, m))
            key = keys.pop((s, m))
            lbl = mb_y[m] if st.loss_fn is not None else None
            gy = _gy_of(s, m)
            y_dtype.pop((s, m), None); gy_buf.pop((s, m), None)
            _, bwd_b, bwd_p, _ = st.executables(x, lbl, train)
            pa = list(p._data for p in st.params)
            if s == 0:
                gp = bwd_p(pa, bufs, x, gy, key, lbl)
            else:
                gp, gx = bwd_b(pa, bufs, x, gy, key, lbl)
                gy_buf[(s - 1, m)] = self.stages[s - 1].put_input(gx)
            if grad_acc[s] is None:
                grad_acc[s] = list(gp)
            else:
                grad_acc[s] = [a + g for a, g in zip(grad_acc[s], gp)]

        def run_bx(s, m):
            """ZB input-grad phase: unblocks stage s-1 as early as possible;
            activations/gy stay saved for the BW phase."""
            st = self.stages[s]
            x = x_in[(s, m)]
            bufs = buf_in[(s, m)]
            key = keys[(s, m)]
            lbl = mb_y[m] if st.loss_fn is not None else None
            gy = _gy_of(s, m)
            gy_saved[(s, m)] = gy
            y_dtype.pop((s, m), None); gy_buf.pop((s, m), None)
            if s > 0:
                _, _, _, bwd_x = st.executables(x, lbl, train)
                gx = bwd_x(list(p._data for p in st.params), bufs, x, gy,
                           key, lbl)
                gy_buf[(s - 1, m)] = self.stages[s - 1].put_input(gx)

        def run_bw(s, m):
            """ZB weight-grad phase: fills former-bubble slots."""
            st = self.stages[s]
            x = x_in.pop((s, m))
            bufs = buf_in.pop((s, m))
            key = keys.pop((s, m))
            lbl = mb_y[m] if st.loss_fn is not None else None
            gy = gy_saved.pop((s, m))
            _, _, bwd_p, _ = st.executables(x, lbl, train)
            gp = bwd_p(list(p._data for p in st.params), bufs, x, gy, key,
                       lbl)
            if grad_acc[s] is None:
                grad_acc[s] = list(gp)
            else:
                grad_acc[s] = [a + g for a, g in zip(grad_acc[s], gp)]

        RUN = {"F": run_fwd, "B": run_bwd, "BX": run_bx, "BW": run_bw}

        def dispatch(s, i):
            kind, m = seqs[s].pop(i)
            if kind == "F" or train:
                RUN[kind](s, m)
            done.add((kind, s, m))
            self.last_dispatch_order.append((s, kind, m))

        # dependency-driven round-robin dispatch (deadlock-free for every
        # order: each stage's head op becomes runnable once its producer
        # ran). ZB twist: when a stage's head op is blocked (waiting on a
        # downstream cotangent), a queued BW whose deps are met runs
        # instead — dw genuinely fills the bubble slot.
        remaining = sum(len(v) for v in seqs.values())
        while remaining:
            progressed = False
            for s in range(P_ - 1, -1, -1):
                if not seqs[s]:
                    continue
                kind, m = seqs[s][0]
                if deps_met(s, kind, m):
                    dispatch(s, 0)
                    remaining -= 1
                    progressed = True
                    continue
                # head blocked: opportunistic BW fill (zbh1 only)
                for i, (k2, m2) in enumerate(seqs[s]):
                    if k2 == "BW" and deps_met(s, k2, m2):
                        dispatch(s, i)
                        remaining -= 1
                        progressed = True
                        break
            if not progressed:
                raise RuntimeError("pipeline schedule deadlocked (bug)")

        # write back buffers + accumulate grads into the framework tensors
        for s, st in enumerate(self.stages):
            for b, a in zip(st.buffers, buf_state[s]):
                b._data = a
            if train and grad_acc[s] is not None:
                for p, g in zip(st.params, grad_acc[s]):
                    if p.stop_gradient or not getattr(p, "trainable", True):
                        continue
                    g = g.astype(p._data.dtype) if g.dtype != p._data.dtype else g
                    p._grad = g if p._grad is None else p._grad + g
        total = losses[0]
        for l in losses[1:]:
            total = total + l
        return Tensor._from_data(total / M, stop_gradient=True)

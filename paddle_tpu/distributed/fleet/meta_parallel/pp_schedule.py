"""Compat shim: the pipeline engine moved to ``distributed.pipeline``.

The generic per-stage-executable engine, the 1F1B/GPipe/ZB-H1 schedules and
the async P2P handoff now live in :mod:`paddle_tpu.distributed.pipeline`
(partition / schedule / runtime). This module keeps the historical fleet
import surface — ``PipelineEngine`` and ``_stage_op_sequence`` — stable.
"""
from __future__ import annotations

from ...pipeline.runtime import (  # noqa: F401
    PipelineEngine, _Stage, _collect_state, set_chaos_hook)
from ...pipeline.schedule import stage_op_sequence as _stage_op_sequence  # noqa: F401

__all__ = ["PipelineEngine", "_stage_op_sequence"]

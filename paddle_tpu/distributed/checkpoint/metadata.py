"""Checkpoint metadata schema.

Reference: python/paddle/distributed/checkpoint/metadata.py —
`LocalTensorMetadata` (global_offset, local_shape) + `Metadata` mapping each
state-dict key to its saved shards and each shard to its storage location.
The same design carries over unchanged: the metadata file is the global
shard→offset map that makes reshard-on-load possible.
"""
from __future__ import annotations

from dataclasses import dataclass, field
from typing import Dict, List, Tuple


@dataclass(frozen=True)
class LocalTensorMetadata:
    """One saved shard of one tensor."""

    global_offset: Tuple[int, ...]
    local_shape: Tuple[int, ...]
    dtype: str


@dataclass(frozen=True)
class LocalTensorIndex:
    """Identity of a shard (key + where it sits in the global tensor)."""

    tensor_key: str
    global_offset: Tuple[int, ...]


@dataclass
class Metadata:
    # key -> list of shards saved for it
    state_dict_metadata: Dict[str, List[LocalTensorMetadata]] = field(
        default_factory=dict)
    # shard -> (file_name, byte_offset) in the checkpoint dir
    storage_metadata: Dict[LocalTensorIndex, Tuple[str, int]] = field(
        default_factory=dict)
    flat_mapping: Dict[str, List[str]] = field(default_factory=dict)
    # file_name -> CRC32 of the whole data file, for load-time integrity
    # verification (read with getattr(..., "file_crcs", {}): metadata
    # pickles from before this field existed unpickle without it)
    file_crcs: Dict[str, int] = field(default_factory=dict)

"""Distributed checkpoint with reshard-on-load.

Reference: `paddle.distributed.checkpoint` — `save_state_dict`
(save_state_dict.py:145): each rank writes its local (possibly sharded
DistTensor) shards to a flat file plus ONE global metadata file of
shard→offset mappings; `load_state_dict` (load_state_dict.py:467) computes
the overlap between saved shards and the *current* sharding and reshards on
load, so checkpoints survive changed parallel configs.

TPU-native: a sharded tensor is a global `jax.Array`; its shards are the
`addressable_shards` (device slices). Save walks them (deduplicating
replicas), writes raw bytes + metadata; load assembles the target's needed
regions from whatever shard layout was saved (the overlap computation) and
lays the result out with `jax.device_put` onto the live sharding — the
reference's point-to-point reshard collapses into XLA data movement.
Multi-host: each process saves only shards it owns (`process_index` match)
into its own file; load reads all files through the shared directory.
"""
from __future__ import annotations

import os
import pickle
import zlib
from typing import Dict, List, Tuple

import jax
import numpy as np

from ...core.enforce import DataLossError
from ...core.tensor import Tensor
from .metadata import LocalTensorIndex, LocalTensorMetadata, Metadata

__all__ = ["save_state_dict", "load_state_dict", "Metadata",
           "LocalTensorMetadata", "LocalTensorIndex"]


def _flatten(sd, prefix="") -> Dict[str, object]:
    flat = {}
    for k, v in sd.items():
        key = f"{prefix}.{k}" if prefix else str(k)
        if isinstance(v, dict):
            flat.update(_flatten(v, key))
        else:
            flat[key] = v
    return flat


def _unflatten_into(sd, flat_updates: Dict[str, object], prefix=""):
    for k, v in sd.items():
        key = f"{prefix}.{k}" if prefix else str(k)
        if isinstance(v, dict):
            _unflatten_into(v, flat_updates, key)
        elif key in flat_updates:
            new = flat_updates[key]
            if isinstance(v, Tensor):
                v._data = new
            else:
                sd[k] = new


def _shards_of(arr) -> List[Tuple[Tuple[int, ...], np.ndarray]]:
    """(global_offset, data) for each distinct shard this process owns."""
    out = []
    seen = set()
    shards = getattr(arr, "addressable_shards", None)
    if not shards:
        return [((0,) * max(arr.ndim, 0), np.asarray(arr))]
    for s in shards:
        offset = []
        for d, sl in enumerate(s.index):
            start = sl.start if isinstance(sl, slice) and sl.start else 0
            offset.append(int(start))
        key = tuple(offset)
        if key in seen:
            continue  # replicated copy of a shard we already saved
        seen.add(key)
        out.append((key, np.asarray(s.data)))
    return out


def _atomic_write(path: str, write_body) -> int:
    """Write via `<path>.tmp.<pid>` + fsync + os.replace; `write_body(f)`
    returns the running CRC32 of everything it wrote. A writer killed at
    any instant leaves either the old file or nothing — never a half-file."""
    tmp = f"{path}.tmp.{os.getpid()}"
    try:
        with open(tmp, "wb") as f:
            crc = write_body(f)
            f.flush()
            os.fsync(f.fileno())
        os.replace(tmp, path)
        return crc
    except BaseException:
        try:
            os.unlink(tmp)
        except OSError:
            pass
        raise


def save_state_dict(state_dict: dict, path: str, process_group=None,
                    coordinator_rank: int = 0, unique_id=None,
                    async_save: bool = False):
    """Write shard files + global metadata (reference: save_state_dict.py:145).

    Both files are written atomically (tmp + fsync + rename) and the data
    file's CRC32 lands in the metadata, so `load_state_dict` can detect
    truncation/corruption instead of silently reading garbage shards."""
    from ..fault_tolerance import chaos

    os.makedirs(path, exist_ok=True)
    rank = jax.process_index()
    flat = _flatten(state_dict)
    meta = Metadata()
    data_file = f"{rank}_0.distcp"

    def _write_data(f):
        crc = 0
        offset = 0
        for key, val in flat.items():
            if val is None:
                continue
            arr = val._data if isinstance(val, Tensor) else val
            if not hasattr(arr, "ndim"):
                arr = np.asarray(arr)
            shards = _shards_of(arr)
            metas = []
            for goff, data in shards:
                data = np.ascontiguousarray(data)
                raw = data.tobytes()
                metas.append(LocalTensorMetadata(
                    goff, tuple(int(x) for x in data.shape), str(data.dtype)))
                meta.storage_metadata[
                    LocalTensorIndex(key, goff)] = (data_file, offset)
                f.write(raw)
                crc = zlib.crc32(raw, crc)
                offset += len(raw)
                # the kill -9 drill's io-level choke point: mid-data-file
                chaos.maybe_crash_save("distcp")
            meta.state_dict_metadata[key] = metas
        return crc

    meta.file_crcs[data_file] = _atomic_write(
        os.path.join(path, data_file), _write_data)
    # every tensor also records its GLOBAL (shape, dtype) for load-time checks
    meta.flat_mapping = {
        k: (tuple(int(x) for x in
                  (v._data if isinstance(v, Tensor) else np.asarray(v)).shape),
            str((v._data if isinstance(v, Tensor) else np.asarray(v)).dtype))
        for k, v in flat.items() if v is not None
    }

    def _write_meta(f):
        raw = pickle.dumps(meta)
        f.write(raw)
        return zlib.crc32(raw)

    chaos.maybe_crash_save("metadata")
    # every rank writes its own metadata (covering the shards IT owns);
    # load merges all .metadata files, so multi-host checkpoints assemble
    _atomic_write(os.path.join(path, f"{rank}.metadata"), _write_meta)


def _verify_file_crcs(path: str, meta: Metadata):
    """Check each data file against the CRC recorded at save time; a
    truncated or bit-rotted shard file fails loudly here instead of being
    silently reassembled into a wrong tensor."""
    for fn, want in meta.file_crcs.items():
        fpath = os.path.join(path, fn)
        try:
            crc = 0
            with open(fpath, "rb") as f:
                while True:
                    chunk = f.read(1 << 20)
                    if not chunk:
                        break
                    crc = zlib.crc32(chunk, crc)
        except FileNotFoundError:
            raise DataLossError(
                f"load_state_dict({path!r}): data file {fn!r} referenced "
                f"by the checkpoint metadata is missing — the checkpoint "
                f"is incomplete; restore from a good one") from None
        if crc != want:
            raise DataLossError(
                f"load_state_dict({path!r}): CRC mismatch for {fn!r} "
                f"(stored {want:#010x}, computed {crc:#010x}) — the file "
                f"is truncated or corrupted; restore from a good "
                f"checkpoint")


def _read_shard(path, file, byte_off, shape, dtype) -> np.ndarray:
    n = int(np.prod(shape)) if shape else 1
    with open(os.path.join(path, file), "rb") as f:
        f.seek(byte_off)
        buf = f.read(n * np.dtype(dtype).itemsize)
    return np.frombuffer(buf, dtype=dtype).reshape(shape)


def load_state_dict(state_dict: dict, path: str, process_group=None,
                    coordinator_rank: int = 0, unique_id=None,
                    offload: bool = False):
    """Assemble each target tensor from saved shards, then reshard onto the
    target's live layout (reference: load_state_dict.py:467)."""
    metas = [fn for fn in os.listdir(path) if fn.endswith(".metadata")]
    if not metas:
        raise FileNotFoundError(f"no .metadata file under {path}")
    meta = Metadata()
    for fn in sorted(metas):
        try:
            with open(os.path.join(path, fn), "rb") as f:
                m = pickle.load(f)
        except Exception as e:
            raise DataLossError(
                f"load_state_dict({path!r}): unreadable metadata file "
                f"{fn!r} ({type(e).__name__}: {e}) — the checkpoint is "
                f"truncated or corrupted; restore from a good one") from e
        # Each rank's metadata covers only the shards IT owns: extend the
        # per-key shard lists (dedup replicas by global_offset) — a plain
        # dict.update would keep only the last rank's shards and silently
        # zero-fill the rest of the tensor.
        for k, v in m.state_dict_metadata.items():
            cur = meta.state_dict_metadata.setdefault(k, [])
            seen = {tuple(sm.global_offset) for sm in cur}
            for sm in v:
                if tuple(sm.global_offset) not in seen:
                    cur.append(sm)
                    seen.add(tuple(sm.global_offset))
        meta.storage_metadata.update(m.storage_metadata)
        meta.flat_mapping.update(m.flat_mapping)
        # metadata pickles from before CRC recording lack the field
        meta.file_crcs.update(getattr(m, "file_crcs", {}))

    _verify_file_crcs(path, meta)

    flat = _flatten(state_dict)
    updates = {}
    for key, val in flat.items():
        if key not in meta.state_dict_metadata:
            continue
        shards = meta.state_dict_metadata[key]
        # reconstruct the global value region-by-region (overlap computation:
        # every saved shard lands at its global_offset)
        gshape, _ = meta.flat_mapping.get(key, (None, None))
        if gshape is None:
            ends = np.zeros(len(shards[0].global_offset), dtype=int)
            for sm in shards:
                ends = np.maximum(
                    ends, np.asarray(sm.global_offset)
                    + np.asarray(sm.local_shape))
            gshape = tuple(int(x) for x in ends)
        out = np.zeros(gshape, dtype=shards[0].dtype)
        for sm in shards:
            file, boff = meta.storage_metadata[
                LocalTensorIndex(key, sm.global_offset)]
            data = _read_shard(path, file, boff, sm.local_shape, sm.dtype)
            if sm.local_shape == () or not gshape:
                out = data.reshape(gshape)
                continue
            idx = tuple(slice(o, o + l) for o, l in
                        zip(sm.global_offset, sm.local_shape))
            out[idx] = data
        cur = val._data if isinstance(val, Tensor) else val
        if hasattr(cur, "shape") and tuple(cur.shape) != tuple(out.shape):
            raise ValueError(
                f"checkpoint shape {out.shape} != target shape "
                f"{tuple(cur.shape)} for {key!r}")
        target_dtype = getattr(cur, "dtype", out.dtype)
        arr = out.astype(target_dtype) if str(out.dtype) != str(
            target_dtype) else out
        sharding = getattr(cur, "sharding", None)
        new = jax.device_put(arr, sharding) if sharding is not None else \
            jax.numpy.asarray(arr)
        updates[key] = new
    _unflatten_into(state_dict, updates)
    return state_dict

"""paddle.distributed parity — TPU-native (SURVEY.md §2.5).

Collectives become XLA HLO ops over ICI/DCN; the ProcessGroup/fleet surface
is a mesh/axis registry. Reference: python/paddle/distributed/__init__.py.
"""
from . import env  # noqa: F401
from .env import ParallelEnv, get_rank, get_world_size  # noqa: F401
from .collective import (  # noqa: F401
    Group,
    P2POp,
    ReduceOp,
    Task,
    all_gather,
    all_gather_into_tensor,
    all_gather_object,
    all_reduce,
    alltoall,
    alltoall_single,
    barrier,
    batch_isend_irecv,
    broadcast,
    broadcast_object_list,
    destroy_process_group,
    get_backend,
    get_global_rank,
    get_group,
    init_parallel_env,
    irecv,
    is_initialized,
    isend,
    new_group,
    recv,
    reduce,
    reduce_scatter,
    scatter,
    send,
)
from .parallel import (  # noqa: F401
    DataParallel,
    ShardedUpdate,
    sharded_update,
    sync_param_grads,
    sync_params_buffers,
)
from .store import TCPStore  # noqa: F401
from .spawn import spawn  # noqa: F401
# eager so FLAGS_chaos_spec / checkpoint flags are registered (and an env
# FLAGS_chaos_spec activates) without requiring an explicit submodule import
from . import fault_tolerance  # noqa: F401
from .fault_tolerance import CheckpointManager  # noqa: F401
from .auto_parallel import (  # noqa: F401
    Partial,
    Placement,
    ProcessMesh,
    Replicate,
    Shard,
    ShardingStage1,
    ShardingStage2,
    ShardingStage3,
    dtensor_from_fn,
    get_mesh,
    local_map,
    reshard,
    set_mesh,
    shard_layer,
    shard_optimizer,
    shard_tensor,
    unshard_dtensor,
)


def __getattr__(name):
    # Lazy submodule access: paddle.distributed.fleet / auto_parallel / etc.
    import importlib

    if name == "stream":
        mod = importlib.import_module(".communication.stream", __name__)
        globals()[name] = mod
        return mod
    if name == "CheckpointManager":
        from .fault_tolerance import CheckpointManager

        globals()[name] = CheckpointManager
        return CheckpointManager
    if name in ("ElasticRuntime", "EpochChangedError"):
        from . import elastic as _el

        obj = getattr(_el, name)
        globals()[name] = obj
        return obj
    if name in ("fleet", "auto_parallel", "checkpoint", "launch", "sharding",
                "parallel", "hybrid", "rpc", "utils", "communication",
                "passes", "fault_tolerance", "elastic"):
        try:
            mod = importlib.import_module(f".{name}", __name__)
        except ImportError as e:
            raise AttributeError(
                f"module {__name__!r} has no attribute {name!r}") from e
        globals()[name] = mod
        return mod
    raise AttributeError(f"module {__name__!r} has no attribute {name!r}")

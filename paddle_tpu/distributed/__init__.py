"""paddle.distributed parity — TPU-native (SURVEY.md §2.5).

Collectives become XLA HLO ops over ICI/DCN; the ProcessGroup/fleet surface
is a mesh/axis registry (M5-M6 build-out; env discovery lands first).
"""
from . import env  # noqa: F401
from .env import ParallelEnv, get_rank, get_world_size  # noqa: F401

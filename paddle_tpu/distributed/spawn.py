"""paddle.distributed.spawn parity — in-python multiprocess launch.

Reference: python/paddle/distributed/spawn.py — forks `nprocs` workers
running `func(*args)` with rank env set, joins them, propagates failures.
"""
from __future__ import annotations

import multiprocessing as mp
import os
import traceback
from typing import Tuple


def _worker(func, args, rank, nprocs, port, q):
    os.environ["PADDLE_TRAINER_ID"] = str(rank)
    os.environ["PADDLE_TRAINERS_NUM"] = str(nprocs)
    os.environ["PADDLE_MASTER"] = f"127.0.0.1:{port}"
    try:
        func(*args)
        q.put((rank, None))
    except Exception:
        q.put((rank, traceback.format_exc()))


def spawn(func, args: Tuple = (), nprocs: int = 1, join: bool = True,
          daemon: bool = False, **options):
    ctx = mp.get_context(options.get("start_method", "spawn"))
    q = ctx.Queue()
    port = int(options.get("master_port", 29770))
    procs = []
    for rank in range(nprocs):
        p = ctx.Process(target=_worker,
                        args=(func, args, rank, nprocs, port, q),
                        daemon=daemon)
        p.start()
        procs.append(p)
    if not join:
        return procs
    import queue as _queue

    errs = []
    got = 0
    while got < nprocs:
        try:
            rank, err = q.get(timeout=1.0)
            got += 1
            if err is not None:
                errs.append((rank, err))
            continue
        except _queue.Empty:
            pass
        # a worker killed by signal/OOM never reports — catch it by exitcode
        for rank, p in enumerate(procs):
            if p.exitcode is not None and p.exitcode != 0:
                drained = True
                while drained:
                    try:
                        r2, e2 = q.get_nowait()
                        got += 1
                        if e2 is not None:
                            errs.append((r2, e2))
                    except _queue.Empty:
                        drained = False
                for pp in procs:
                    if pp.is_alive():
                        pp.terminate()
                raise RuntimeError(
                    f"spawned rank {rank} died with exitcode {p.exitcode}"
                    + (f"; first error:\n{errs[0][1]}" if errs else ""))
    for p in procs:
        p.join()
    if errs:
        rank, err = errs[0]
        raise RuntimeError(f"spawned rank {rank} failed:\n{err}")
    return procs

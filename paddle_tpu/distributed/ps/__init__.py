"""Parameter-server mode — dense/sparse tables with server-side updates.

Reference: paddle/fluid/distributed/ps/ (brpc_ps_server.h:1 BrpcPsServer,
table/ dense+sparse accessors, the_one_ps.py orchestration): servers hold
parameter tables, trainers pull params / push grads asynchronously, sparse
embedding rows are created on demand and sharded by id across servers.

TPU-native scoping: PS exists for recsys-scale sparse embeddings that live
OUTSIDE accelerator memory by design — so the table store is host-side
(numpy + dict), the transport is the same framed-socket layer the rpc
module uses, and the dense training path on TPU stays collective. What is
kept faithful: async push/pull semantics, server-side optimizers (SGD /
adagrad per push), id-sharded sparse tables with on-demand row init,
name-sharded dense tables, and the worker barrier.
"""
from __future__ import annotations

import pickle
import socket
import struct
import threading
from typing import Dict, List, Optional, Sequence

import numpy as np

__all__ = ["PsServer", "PsClient", "Table", "start_ps_servers"]


def start_ps_servers(n: int, n_workers: int = 1, snapshot_dir: str = None,
                     load: bool = False, timeout: float = 30.0):
    """Spawn `n` OUT-OF-PROCESS PS servers (``python -m
    paddle_tpu.distributed.ps``) and return (endpoints, processes).

    Reference analog: the launcher's `--servers` role starting brpc
    server processes. Each server prints its bound port on stdout; with
    snapshot_dir, server i persists to `{dir}/ps{i}.pkl` on SIGTERM/stop
    and `load=True` restores at boot.
    """
    import os
    import subprocess
    import sys
    import time

    procs, endpoints = [], []
    for i in range(n):
        cmd = [sys.executable, "-m", "paddle_tpu.distributed.ps",
               "--port", "0", "--n-workers", str(n_workers)]
        if snapshot_dir:
            cmd += ["--snapshot", os.path.join(snapshot_dir, f"ps{i}.pkl")]
            if load:
                cmd += ["--load"]
        env = dict(os.environ, JAX_PLATFORMS="cpu")
        p = subprocess.Popen(cmd, stdout=subprocess.PIPE, text=True,
                             env=env)
        procs.append(p)
    import select

    deadline = time.monotonic() + timeout
    for p in procs:
        line = ""
        while time.monotonic() < deadline:
            if p.poll() is not None:
                break  # child exited before reporting
            ready, _, _ = select.select([p.stdout], [], [], 0.2)
            if not ready:
                continue  # deadline keeps being honored on a silent child
            line = p.stdout.readline()
            if line.startswith("PS_SERVER_PORT="):
                break
            if line == "":
                break  # EOF: child closed stdout
        if not line.startswith("PS_SERVER_PORT="):
            for q in procs:
                q.kill()
            raise RuntimeError("PS server failed to report its port")
        endpoints.append(f"127.0.0.1:{line.strip().split('=')[1]}")
    return endpoints, procs


def _recv_exact(conn, n: int) -> bytes:
    buf = b""
    while len(buf) < n:
        chunk = conn.recv(n - len(buf))
        if not chunk:
            raise ConnectionError("ps peer closed")
        buf += chunk
    return buf


def _send_msg(conn, obj) -> None:
    payload = pickle.dumps(obj)
    conn.sendall(struct.pack("<Q", len(payload)) + payload)


def _recv_msg(conn):
    (n,) = struct.unpack("<Q", _recv_exact(conn, 8))
    return pickle.loads(_recv_exact(conn, n))


class Table:
    """One named table (reference: ps/table/ — MemoryDenseTable /
    MemorySparseTable with an accessor applying the optimizer)."""

    def __init__(self, name: str, kind: str, dim: int,
                 shape: Optional[Sequence[int]] = None,
                 optimizer: str = "sgd", lr: float = 0.01,
                 init_std: float = 0.01, seed: int = 0):
        self.name = name
        self.kind = kind  # "dense" | "sparse"
        self.dim = dim
        self.optimizer = optimizer
        self.lr = lr
        self.init_std = init_std
        self._rng = np.random.RandomState(seed)
        self._lock = threading.Lock()
        if kind == "dense":
            self.data = np.zeros(shape, np.float32) if shape is not None \
                else np.zeros((dim,), np.float32)
            self._g2 = np.zeros_like(self.data)  # adagrad accumulator
        else:
            self.rows: Dict[int, np.ndarray] = {}
            self._row_g2: Dict[int, np.ndarray] = {}

    # -- dense ---------------------------------------------------------------

    def pull_dense(self) -> np.ndarray:
        with self._lock:
            return self.data.copy()

    def push_dense(self, grad: np.ndarray) -> None:
        grad = np.asarray(grad, np.float32)
        with self._lock:
            if self.optimizer == "adagrad":
                self._g2 += grad * grad
                self.data -= self.lr * grad / (np.sqrt(self._g2) + 1e-8)
            elif self.optimizer == "sum":
                self.data += grad
            else:  # sgd
                self.data -= self.lr * grad

    # -- sparse --------------------------------------------------------------

    def _row(self, i: int) -> np.ndarray:
        r = self.rows.get(i)
        if r is None:  # on-demand init (reference: sparse accessor create)
            r = self._rng.normal(0.0, self.init_std,
                                 self.dim).astype(np.float32)
            self.rows[i] = r
        return r

    def pull_sparse(self, ids: Sequence[int]) -> np.ndarray:
        with self._lock:
            return np.stack([self._row(int(i)) for i in ids])

    # -- persistence (reference: ps/table save/load, ssd_sparse_table's
    # checkpoint contract scoped to file-backed snapshots) ------------------

    def state(self) -> dict:
        with self._lock:
            spec = dict(name=self.name, kind=self.kind, dim=self.dim,
                        optimizer=self.optimizer, lr=self.lr,
                        init_std=self.init_std)
            if self.kind == "dense":
                return {"spec": dict(spec, shape=list(self.data.shape)),
                        "data": self.data.copy(), "g2": self._g2.copy()}
            return {"spec": spec,
                    # RNG stream position too: a resumed shard must draw
                    # the SAME on-demand rows an uninterrupted run would
                    "rng_state": self._rng.get_state(),
                    "rows": {i: r.copy() for i, r in self.rows.items()},
                    "row_g2": {i: g.copy()
                               for i, g in self._row_g2.items()}}

    @classmethod
    def from_state(cls, st: dict) -> "Table":
        t = cls(**st["spec"])
        with t._lock:
            if t.kind == "dense":
                t.data = np.asarray(st["data"], np.float32)
                t._g2 = np.asarray(st["g2"], np.float32)
            else:
                if st.get("rng_state") is not None:
                    t._rng.set_state(st["rng_state"])
                t.rows = {int(i): np.asarray(r, np.float32)
                          for i, r in st["rows"].items()}
                t._row_g2 = {int(i): np.asarray(g, np.float32)
                             for i, g in st["row_g2"].items()}
        return t

    def push_sparse(self, ids: Sequence[int], grads: np.ndarray) -> None:
        grads = np.asarray(grads, np.float32)
        with self._lock:
            for i, g in zip(ids, grads):
                i = int(i)
                r = self._row(i)
                if self.optimizer == "adagrad":
                    g2 = self._row_g2.setdefault(
                        i, np.zeros(self.dim, np.float32))
                    g2 += g * g
                    r -= self.lr * g / (np.sqrt(g2) + 1e-8)
                else:
                    r -= self.lr * g


class PsServer:
    """One PS shard (reference: brpc_ps_server.h:1). Serves table RPCs on
    a socket; runs until `stop` arrives."""

    def __init__(self, port: int = 0, n_workers: int = 1):
        self.tables: Dict[str, Table] = {}
        # push dedup: last applied sequence number per client — an
        # at-least-once retry after a lost reply must not apply the same
        # gradient twice (snapshotted alongside the tables)
        self._applied: Dict[str, int] = {}
        self.n_workers = n_workers
        self._barrier_count = 0
        self._barrier_gen = 0
        self._cv = threading.Condition()
        self._sock = socket.socket(socket.AF_INET, socket.SOCK_STREAM)
        self._sock.setsockopt(socket.SOL_SOCKET, socket.SO_REUSEADDR, 1)
        self._sock.bind(("0.0.0.0", port))
        self.port = self._sock.getsockname()[1]
        self._sock.listen(128)
        self._stopped = threading.Event()
        self._thread = threading.Thread(target=self._accept, daemon=True,
                                        name="ps-server")
        self._thread.start()

    def _accept(self):
        while not self._stopped.is_set():
            try:
                conn, _ = self._sock.accept()
            except OSError:
                return
            threading.Thread(target=self._serve, args=(conn,),
                             daemon=True).start()

    def _serve(self, conn):
        try:
            while True:
                msg = _recv_msg(conn)
                op = msg["op"]
                if op == "create_table":
                    t = msg["spec"]
                    if t["name"] not in self.tables:
                        self.tables[t["name"]] = Table(**t)
                    _send_msg(conn, {"ok": True})
                elif op == "pull_dense":
                    _send_msg(conn, {"ok": True, "data":
                                     self.tables[msg["name"]].pull_dense()})
                elif op == "push_dense":
                    if self._fresh_push(msg):
                        self.tables[msg["name"]].push_dense(msg["grad"])
                    _send_msg(conn, {"ok": True})
                elif op == "pull_sparse":
                    _send_msg(conn, {"ok": True, "data": self.tables[
                        msg["name"]].pull_sparse(msg["ids"])})
                elif op == "push_sparse":
                    if self._fresh_push(msg):
                        self.tables[msg["name"]].push_sparse(
                            msg["ids"], msg["grads"])
                    _send_msg(conn, {"ok": True})
                elif op == "barrier":
                    with self._cv:
                        gen = self._barrier_gen
                        self._barrier_count += 1
                        if self._barrier_count >= self.n_workers:
                            self._barrier_count = 0
                            self._barrier_gen += 1
                            self._cv.notify_all()
                        else:
                            while (self._barrier_gen == gen
                                   and not self._stopped.is_set()):
                                self._cv.wait(0.1)
                    _send_msg(conn, {"ok": True})
                elif op in ("save", "load"):
                    try:
                        (self.save if op == "save" else self.load)(
                            msg["path"])
                        _send_msg(conn, {"ok": True})
                    except OSError as e:
                        # reply in-band: closing the connection would turn
                        # a file error into a client-side retry hang
                        _send_msg(conn, {"ok": False,
                                         "error": f"{op}: {e}"})
                elif op == "stop":
                    _send_msg(conn, {"ok": True})
                    self.stop()
                    return
                else:
                    _send_msg(conn, {"ok": False,
                                     "error": f"unknown op {op!r}"})
        except (ConnectionError, OSError, EOFError):
            pass
        finally:
            try:
                conn.close()
            except OSError:
                pass

    def _fresh_push(self, msg) -> bool:
        """True when this push has not been applied yet (client seq is
        monotone; a retried push after a lost reply arrives with the same
        seq and is dropped — already applied)."""
        client = msg.get("client")
        if client is None:
            return True  # unversioned caller: apply unconditionally
        seq = int(msg["seq"])
        if seq <= self._applied.get(client, -1):
            return False
        self._applied[client] = seq
        return True

    def run(self):
        """Block until stopped (reference: run_server)."""
        self._stopped.wait()

    def stop(self):
        self._stopped.set()
        with self._cv:
            self._cv.notify_all()
        try:
            self._sock.close()
        except OSError:
            pass

    # -- snapshot persistence (reference: FleetWrapper save/load_model
    # over brpc; here one pickled file per server shard) --------------------

    def save(self, path: str) -> None:
        import os
        import tempfile

        state = {"__tables__": {name: t.state()
                                for name, t in self.tables.items()},
                 "__applied__": dict(self._applied)}
        d = os.path.dirname(os.path.abspath(path)) or "."
        os.makedirs(d, exist_ok=True)
        # atomic replace: a kill mid-save never corrupts the snapshot
        fd, tmp = tempfile.mkstemp(dir=d, prefix=".ps_snap_")
        try:
            with os.fdopen(fd, "wb") as f:
                pickle.dump(state, f, protocol=4)
            os.replace(tmp, path)
        except BaseException:
            try:
                os.unlink(tmp)
            except OSError:
                pass
            raise

    def load(self, path: str) -> None:
        with open(path, "rb") as f:
            state = pickle.load(f)
        tables = state.get("__tables__", state)  # legacy: tables at root
        self.tables = {name: Table.from_state(st)
                       for name, st in tables.items()}
        self._applied = dict(state.get("__applied__", {}))


class PsClient:
    """Trainer-side handle to all PS shards (reference: brpc_ps_client.h).

    Sharding: dense tables live whole on `hash(name) % n_servers`; sparse
    rows scatter by `id % n_servers` (the reference's shard_num routing).
    """

    def __init__(self, endpoints: Sequence[str], retry_timeout: float = 60.0,
                 retry_interval: float = 0.5):
        self._eps = list(endpoints)
        self._conns: List[Optional[socket.socket]] = [None] * len(self._eps)
        self._locks = [threading.Lock() for _ in self._eps]
        self._table_kind: Dict[str, str] = {}
        # spec replay on reconnect: a restarted server (with or without a
        # snapshot) gets its tables re-created idempotently, so a
        # kill-server-mid-train sequence resumes without client-side code
        self._specs: Dict[int, List[dict]] = {i: []
                                              for i in range(len(self._eps))}
        self.retry_timeout = retry_timeout
        self.retry_interval = retry_interval
        # push versioning for server-side dedup under at-least-once retry
        import uuid

        self._client_id = uuid.uuid4().hex
        self._push_seq = 0

    def _conn(self, i: int) -> socket.socket:
        if self._conns[i] is None:
            host, port = self._eps[i].rsplit(":", 1)
            s = socket.create_connection((host, int(port)), timeout=120)
            s.setsockopt(socket.IPPROTO_TCP, socket.TCP_NODELAY, 1)
            self._conns[i] = s
            for spec in self._specs[i]:
                _send_msg(s, {"op": "create_table", "spec": spec})
                _recv_msg(s)
        return self._conns[i]

    def _drop_conn(self, i: int) -> None:
        c = self._conns[i]
        self._conns[i] = None
        if c is not None:
            try:
                c.close()
            except OSError:
                pass

    def _call(self, i: int, msg, retry: bool = True):
        import time as _time

        deadline = _time.monotonic() + self.retry_timeout
        while True:
            try:
                with self._locks[i]:
                    conn = self._conn(i)
                    _send_msg(conn, msg)
                    out = _recv_msg(conn)
                break
            except (ConnectionError, EOFError, OSError):
                # server down/restarting (reference: brpc client retry):
                # drop the connection and keep knocking until the window
                # closes — a restarted server replays table specs above
                self._drop_conn(i)
                if not retry or _time.monotonic() >= deadline:
                    raise
                _time.sleep(self.retry_interval)
        if not out.get("ok"):
            raise RuntimeError(out.get("error", "ps call failed"))
        return out

    def _dense_home(self, name: str) -> int:
        import zlib

        # stable across processes (builtin hash is seed-randomized — a
        # resuming client would route to a different shard than the one
        # whose snapshot holds the table)
        return zlib.crc32(name.encode()) % len(self._eps)

    # -- API -----------------------------------------------------------------

    def create_table(self, name: str, kind: str = "dense", dim: int = 0,
                     shape=None, optimizer: str = "sgd", lr: float = 0.01,
                     init_std: float = 0.01):
        spec = dict(name=name, kind=kind, dim=dim, shape=shape,
                    optimizer=optimizer, lr=lr, init_std=init_std)
        self._table_kind[name] = kind
        if kind == "dense":
            home = self._dense_home(name)
            self._specs[home].append(spec)
            self._call(home, {"op": "create_table", "spec": spec})
        else:  # every shard owns a slice of the id space
            for i in range(len(self._eps)):
                shard_spec = dict(spec, seed=i)
                self._specs[i].append(shard_spec)
                self._call(i, {"op": "create_table", "spec": shard_spec})

    def pull_dense(self, name: str) -> np.ndarray:
        return self._call(self._dense_home(name),
                          {"op": "pull_dense", "name": name})["data"]

    def _next_seq(self) -> int:
        self._push_seq += 1
        return self._push_seq

    def push_dense(self, name: str, grad: np.ndarray) -> None:
        self._call(self._dense_home(name),
                   {"op": "push_dense", "name": name,
                    "grad": np.asarray(grad, np.float32),
                    "client": self._client_id, "seq": self._next_seq()})

    def pull_sparse(self, name: str, ids: Sequence[int]) -> np.ndarray:
        ids = np.asarray(ids, np.int64)
        n = len(self._eps)
        out = np.empty((len(ids), 0), np.float32) if len(ids) == 0 else None
        parts = {}
        for i in range(n):
            mask = (ids % n) == i
            if mask.any():
                parts[i] = (np.nonzero(mask)[0], self._call(
                    i, {"op": "pull_sparse", "name": name,
                        "ids": (ids[mask] // n).tolist()})["data"])
        dim = next(iter(parts.values()))[1].shape[1]
        out = np.empty((len(ids), dim), np.float32)
        for i, (pos, rows) in parts.items():
            out[pos] = rows
        return out

    def push_sparse(self, name: str, ids: Sequence[int],
                    grads: np.ndarray) -> None:
        ids = np.asarray(ids, np.int64)
        grads = np.asarray(grads, np.float32)
        n = len(self._eps)
        for i in range(n):
            mask = (ids % n) == i
            if mask.any():
                self._call(i, {"op": "push_sparse", "name": name,
                               "ids": (ids[mask] // n).tolist(),
                               "grads": grads[mask],
                               "client": self._client_id,
                               "seq": self._next_seq()})

    def barrier(self) -> None:
        self._call(0, {"op": "barrier"})

    def save_tables(self, path_prefix: str) -> None:
        """Snapshot every shard to `{prefix}.shard{i}.pkl` (reference:
        fleet.save_persistables over the PS)."""
        for i in range(len(self._eps)):
            self._call(i, {"op": "save",
                           "path": f"{path_prefix}.shard{i}.pkl"})

    def load_tables(self, path_prefix: str) -> None:
        for i in range(len(self._eps)):
            self._call(i, {"op": "load",
                           "path": f"{path_prefix}.shard{i}.pkl"})

    def stop_servers(self) -> None:
        for i in range(len(self._eps)):
            try:
                # no retry: a dead server is already stopped — retrying
                # would block retry_timeout per dead shard
                self._call(i, {"op": "stop"}, retry=False)
            except (RuntimeError, ConnectionError, EOFError, OSError):
                pass

    def close(self) -> None:
        for c in self._conns:
            if c is not None:
                try:
                    c.close()
                except OSError:
                    pass
        self._conns = [None] * len(self._eps)

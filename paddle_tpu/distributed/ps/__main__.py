"""Out-of-process PS server entry: ``python -m paddle_tpu.distributed.ps``.

Reference analog: the standalone brpc PS server process the reference's
launcher starts for `--servers` role endpoints
(`paddle/fluid/distributed/ps/service/brpc_ps_server.h:1`,
`python/paddle/distributed/launch/context/args_envs.py` server role).
The process owns the tables; trainers connect over sockets. SIGTERM (or
a client `stop` op) snapshots to --snapshot before exiting, and
--load restores a previous snapshot at boot — together with the client's
spec-replay reconnect this gives kill/restart resume.
"""
from __future__ import annotations

import argparse
import os
import signal
import sys

from . import PsServer


def main() -> int:
    ap = argparse.ArgumentParser(prog="paddle_tpu.distributed.ps")
    ap.add_argument("--port", type=int, default=0)
    ap.add_argument("--n-workers", type=int, default=1)
    ap.add_argument("--snapshot", default=None,
                    help="snapshot file; written on stop/SIGTERM")
    ap.add_argument("--load", action="store_true",
                    help="restore tables from --snapshot at boot")
    args = ap.parse_args()
    server = PsServer(port=args.port, n_workers=args.n_workers)
    if args.load and args.snapshot and os.path.exists(args.snapshot):
        server.load(args.snapshot)
    # the launcher reads the bound port from the first stdout line
    print(f"PS_SERVER_PORT={server.port}", flush=True)

    def _term(signum, frame):
        if args.snapshot:
            try:
                server.save(args.snapshot)
            except Exception:  # noqa: BLE001 — still shut down
                pass
        server.stop()

    signal.signal(signal.SIGTERM, _term)
    signal.signal(signal.SIGINT, _term)
    server.run()
    if args.snapshot:
        try:
            server.save(args.snapshot)
        except Exception:  # noqa: BLE001
            pass
    return 0


if __name__ == "__main__":
    sys.exit(main())

"""Low-level op namespace.

Analog of the reference's `paddle._C_ops` (python/paddle/_C_ops.py:20, a
re-export of `core.eager.ops` — the generated Python-C functions). The
functions here come from `ops/generated_bindings.py`, which
tools/gen_op_bindings.py emits FROM ops/ops.yaml — so an op is visible in
this namespace exactly when the YAML names it (the reference's
YAML→codegen arrow, `paddle/phi/api/generator/api_gen.py:1`).
"""
from .ops import generated_bindings as _gen
from . import ops as _ops_pkg  # noqa: F401  (ensures kernels are registered)


def __getattr__(name):
    # only YAML-listed names — plain getattr would leak the generated
    # module's internals (_OPS, inf/nan) and defeat the YAML-only surface
    if name in _gen.__all__:
        return getattr(_gen, name)
    raise AttributeError(
        f"_C_ops has no op {name!r} — not present in ops/ops.yaml "
        "(add a YAML entry + kernel, then run tools/gen_op_manifest.py)")


def __dir__():
    return sorted(_gen.__all__)

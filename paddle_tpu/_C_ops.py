"""Low-level op namespace.

Analog of the reference's `paddle._C_ops` (python/paddle/_C_ops.py:20, a
re-export of `core.eager.ops` — the generated Python-C functions). Here every
registered kernel is exposed by name; attribute lookup goes straight to the
op registry.
"""
from .ops.dispatch import OPS as _OPS
from . import ops as _ops_pkg  # noqa: F401  (ensures kernels are registered)


def __getattr__(name):
    try:
        return _OPS[name]
    except KeyError:
        raise AttributeError(f"_C_ops has no op {name!r}") from None


def __dir__():
    return sorted(_OPS)

"""paddle.static.nn — layer helpers for static-graph scripts.

Reference: python/paddle/static/nn/common.py (fc at :28, batch_norm,
embedding): functional builders that create parameters on the current
program and append ops. Here the parameter creation is eager (parameters
register on the Program the first time an op consumes them) and the ops
record into the active tape like any dispatched op.
"""
from __future__ import annotations

from typing import Optional

import numpy as np

from ..core.tensor import Tensor
from .. import nn as _nn


def fc(x, size: int, num_flatten_dims: int = 1, activation: Optional[str] = None,
       name: Optional[str] = None, weight_attr=None, bias_attr=None):
    """Fully-connected layer (reference: static/nn/common.py:28).

    Creates a fresh Linear parameter pair per call-site (static scripts
    build the program once) and records x @ W + b (+activation)."""
    in_features = int(np.prod(x.shape[num_flatten_dims:]))
    lin = _nn.Linear(in_features, size, weight_attr=weight_attr,
                     bias_attr=bias_attr)
    if name:
        lin.weight.name = f"{name}.w_0"
        if lin.bias is not None:
            lin.bias.name = f"{name}.b_0"
    h = x
    if len(x.shape) > num_flatten_dims + 1:
        h = x.reshape(list(x.shape[:num_flatten_dims]) + [in_features])
    out = lin(h)
    if activation:
        out = getattr(_nn.functional, activation)(out)
    # keep the layer alive: its params are referenced by the program
    out._fc_layer = lin
    return out


def embedding(input, size, is_sparse: bool = False, padding_idx=None,
              param_attr=None, dtype="float32"):
    """Reference: static/nn/common.py embedding."""
    emb = _nn.Embedding(size[0], size[1], padding_idx=padding_idx,
                        weight_attr=param_attr)
    out = emb(input)
    out._emb_layer = emb
    return out

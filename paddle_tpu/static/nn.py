"""paddle.static.nn — layer helpers for static-graph scripts.

Reference: python/paddle/static/nn/common.py (fc at :28, batch_norm,
embedding): functional builders that create parameters on the current
program and append ops. Here the parameter creation is eager (parameters
register on the Program the first time an op consumes them) and the ops
record into the active tape like any dispatched op.
"""
from __future__ import annotations

from typing import Optional

import numpy as np

from ..core.tensor import Tensor
from .. import nn as _nn


def fc(x, size: int, num_flatten_dims: int = 1, activation: Optional[str] = None,
       name: Optional[str] = None, weight_attr=None, bias_attr=None):
    """Fully-connected layer (reference: static/nn/common.py:28).

    Creates a fresh Linear parameter pair per call-site (static scripts
    build the program once) and records x @ W + b (+activation)."""
    in_features = int(np.prod(x.shape[num_flatten_dims:]))
    lin = _nn.Linear(in_features, size, weight_attr=weight_attr,
                     bias_attr=bias_attr)
    if name:
        lin.weight.name = f"{name}.w_0"
        if lin.bias is not None:
            lin.bias.name = f"{name}.b_0"
    h = x
    if len(x.shape) > num_flatten_dims + 1:
        h = x.reshape(list(x.shape[:num_flatten_dims]) + [in_features])
    out = lin(h)
    if activation:
        out = getattr(_nn.functional, activation)(out)
    # keep the layer alive: its params are referenced by the program
    out._fc_layer = lin
    return out


def embedding(input, size, is_sparse: bool = False, padding_idx=None,
              param_attr=None, dtype="float32"):
    """Reference: static/nn/common.py embedding."""
    emb = _nn.Embedding(size[0], size[1], padding_idx=padding_idx,
                        weight_attr=param_attr)
    out = emb(input)
    out._emb_layer = emb
    return out


# ---------------------------------------------------------------------------
# round-5 tail: the reference's static.nn function surface
# (reference: python/paddle/static/nn/common.py + control_flow.py) — static
# functional forms over the same kernels the dygraph layers use.
# ---------------------------------------------------------------------------

def _F():
    from ..nn import functional as F  # noqa: N802

    return F


def conv2d(input, num_filters, filter_size, stride=1, padding=0, dilation=1,
           groups=1, param_attr=None, bias_attr=None, act=None,
           data_format="NCHW", name=None):
    from ..nn import Conv2D

    layer = Conv2D(input.shape[1], num_filters, filter_size, stride=stride,
                   padding=padding, dilation=dilation, groups=groups,
                   bias_attr=bias_attr)
    out = layer(input)
    return _act(out, act)


def conv3d(input, num_filters, filter_size, stride=1, padding=0, dilation=1,
           groups=1, param_attr=None, bias_attr=None, act=None,
           data_format="NCDHW", name=None):
    from ..nn import Conv3D

    layer = Conv3D(input.shape[1], num_filters, filter_size, stride=stride,
                   padding=padding, dilation=dilation, groups=groups,
                   bias_attr=bias_attr)
    return _act(layer(input), act)


def conv2d_transpose(input, num_filters, output_size=None, filter_size=None,
                     padding=0, stride=1, dilation=1, groups=1,
                     param_attr=None, bias_attr=None, act=None,
                     data_format="NCHW", name=None):
    from ..nn import Conv2DTranspose

    layer = Conv2DTranspose(input.shape[1], num_filters, filter_size,
                            stride=stride, padding=padding,
                            dilation=dilation, groups=groups,
                            bias_attr=bias_attr)
    return _act(layer(input), act)


def conv3d_transpose(input, num_filters, output_size=None, filter_size=None,
                     padding=0, stride=1, dilation=1, groups=1,
                     param_attr=None, bias_attr=None, act=None,
                     data_format="NCDHW", name=None):
    from ..nn import Conv3DTranspose

    layer = Conv3DTranspose(input.shape[1], num_filters, filter_size,
                            stride=stride, padding=padding,
                            dilation=dilation, groups=groups,
                            bias_attr=bias_attr)
    return _act(layer(input), act)


def _act(out, act):
    if act is None:
        return out
    return getattr(_F(), act)(out)


def batch_norm(input, act=None, is_test=False, momentum=0.9, epsilon=1e-05,
               param_attr=None, bias_attr=None, data_layout="NCHW",
               in_place=False, name=None, moving_mean_name=None,
               moving_variance_name=None, do_model_average_for_mean_and_var=True,
               use_global_stats=False):
    from ..nn import BatchNorm2D, BatchNorm1D, BatchNorm3D

    cls = {2: BatchNorm1D, 3: BatchNorm1D, 4: BatchNorm2D,
           5: BatchNorm3D}[len(input.shape)]
    layer = cls(input.shape[1], momentum=momentum, epsilon=epsilon)
    if is_test:
        layer.eval()
    return _act(layer(input), act)


def layer_norm(input, scale=True, shift=True, begin_norm_axis=1,
               epsilon=1e-05, param_attr=None, bias_attr=None, act=None,
               name=None):
    import numpy as np

    from .. import create_parameter

    shape = [int(np.prod(input.shape[begin_norm_axis:]))]
    weight = create_parameter(shape, "float32") if scale else None
    bias = create_parameter(shape, "float32", is_bias=True) if shift else None
    out = _F().layer_norm(input, weight, bias, epsilon, begin_norm_axis)
    return _act(out, act)


def group_norm(input, groups, epsilon=1e-05, param_attr=None,
               bias_attr=None, act=None, data_layout="NCHW", name=None):
    from .. import create_parameter

    c = input.shape[1]
    weight = create_parameter([c], "float32")
    bias = create_parameter([c], "float32", is_bias=True)
    return _act(_F().group_norm(input, weight, bias, epsilon, groups), act)


def instance_norm(input, epsilon=1e-05, param_attr=None, bias_attr=None,
                  name=None):
    from .. import create_parameter

    c = input.shape[1]
    weight = create_parameter([c], "float32")
    bias = create_parameter([c], "float32", is_bias=True)
    return _F().instance_norm(input, None, None, weight, bias, epsilon)


def data_norm(input, act=None, epsilon=1e-05, param_attr=None,
              data_layout="NCHW", in_place=False, name=None,
              moving_mean_name=None, moving_variance_name=None,
              do_model_average_for_mean_and_var=True, slot_dim=-1,
              summary_decay_rate=0.9999999, sync_stats=False,
              enable_scale_and_shift=False):
    """Per-feature normalization by accumulated batch statistics
    (reference: static/nn/common.py data_norm — PS-style normalization
    without learned affine unless enabled). Eager form: normalize by the
    batch's own mean/std."""
    from .. import _C_ops

    mean = _C_ops.mean(input, 0, True)
    var = _C_ops.mean(_C_ops.square(_C_ops.subtract(input, mean)), 0, True)
    out = _C_ops.divide(_C_ops.subtract(input, mean),
                        _C_ops.sqrt(_C_ops.add(var, _C_ops.full_like(var, epsilon))))
    return _act(out, act)


def spectral_norm(weight, dim=0, power_iters=1, eps=1e-12, name=None):
    import numpy as np

    from .. import randn

    h = weight.shape[dim]
    w = int(np.prod(weight.shape)) // h
    from .. import _C_ops

    return _C_ops.spectral_norm(weight, randn([h]), randn([w]), dim,
                                power_iters, eps)


def bilinear_tensor_product(x, y, size, act=None, name=None,
                            param_attr=None, bias_attr=None):
    from .. import create_parameter
    from ..nn import functional as F

    w = create_parameter([size, x.shape[-1], y.shape[-1]], "float32")
    b = create_parameter([1, size], "float32", is_bias=True)
    return _act(F.bilinear(x, y, w, b), act)


def prelu(x, mode="all", param_attr=None, data_format="NCHW", name=None):
    from .. import create_parameter

    n = {"all": 1, "channel": x.shape[1] if len(x.shape) > 1 else 1,
         "element": int(__import__("numpy").prod(x.shape[1:]))}[mode]
    alpha = create_parameter([n], "float32")
    return _F().prelu(x, alpha)


def deform_conv2d(x, offset, mask, num_filters, filter_size, stride=1,
                  padding=0, dilation=1, groups=1, deformable_groups=1,
                  im2col_step=1, param_attr=None, bias_attr=None,
                  name=None):
    from .. import _C_ops, create_parameter

    k = (filter_size, filter_size) if isinstance(filter_size, int) \
        else filter_size
    w = create_parameter([num_filters, x.shape[1] // groups, *k], "float32")
    return _C_ops.deformable_conv(x, offset, w, mask, stride=stride,
                                  padding=padding, dilation=dilation,
                                  groups=groups,
                                  deformable_groups=deformable_groups)


def nce(input, label, num_total_classes, sample_weight=None,
        param_attr=None, bias_attr=None, num_neg_samples=None, name=None,
        sampler="uniform", custom_dist=None, seed=0, is_sparse=False):
    from .. import _C_ops, create_parameter

    w = create_parameter([num_total_classes, input.shape[-1]], "float32")
    b = create_parameter([num_total_classes], "float32", is_bias=True)
    return _C_ops.nce(input, label, w, b,
                      num_neg_samples=num_neg_samples or 10, seed=seed)


def row_conv(input, future_context_size, param_attr=None, act=None):
    from .. import _C_ops, create_parameter

    w = create_parameter([future_context_size + 1, input.shape[-1]],
                         "float32")
    return _act(_C_ops.row_conv(input, w), act)


def sequence_conv(input, num_filters, filter_size=3, filter_stride=1,
                  padding=True, padding_start=None, bias_attr=None,
                  param_attr=None, act=None, name=None):
    from .. import _C_ops, create_parameter

    w = create_parameter([filter_size * input.shape[-1], num_filters],
                         "float32")
    return _act(_C_ops.sequence_conv(input, w,
                                     context_length=filter_size,
                                     context_start=padding_start or
                                     -(filter_size // 2)), act)


def sequence_expand(x, y, ref_level=-1, name=None):
    from .. import _C_ops

    return _C_ops.sequence_expand(x, y, ref_level)


def sequence_pool(input, pool_type, is_test=False, pad_value=0.0):
    from .. import _C_ops

    return _C_ops.sequence_pool(input, None, pool_type.upper())


def sequence_softmax(input, use_cudnn=False, name=None):
    from .. import _C_ops

    return _C_ops.sequence_softmax(input)


def sequence_first_step(input):
    return sequence_pool(input, "FIRST")


def sequence_last_step(input):
    return sequence_pool(input, "LAST")


def sparse_embedding(input, size, padding_idx=None, is_test=False,
                     entry=None, table_class="MemorySparseTable",
                     param_attr=None, dtype="float32", slot=None):
    """PS-backed sparse embedding (reference: static/nn/common.py
    sparse_embedding → distributed lookup table). Single-process form:
    a dense embedding lookup; the parameter-server path shards the table
    via distributed/ps."""
    return embedding(input, size, is_sparse=True, padding_idx=padding_idx,
                     param_attr=param_attr, dtype=dtype)


def py_func(func, x, out, backward_func=None, skip_vars_in_backward_input=None):
    """Eager-composable py_func (reference: static/nn/common.py py_func):
    runs the python callable on the inputs."""
    if isinstance(x, (list, tuple)):
        return func(*x)
    return func(x)


def cond(pred, true_fn=None, false_fn=None, name=None, return_names=None):
    """Static conditional (reference: static/nn/control_flow.py cond).
    Under a to_static trace this lowers to lax.cond; eagerly it branches
    on the concrete value."""
    from ..jit.api import in_to_static_trace

    if in_to_static_trace():
        import jax

        from ..core.tensor import Tensor

        p = pred._data if isinstance(pred, Tensor) else pred
        return jax.lax.cond(p.reshape(()), lambda _: true_fn(),
                            lambda _: false_fn(), operand=None)
    if bool(pred):
        return true_fn() if true_fn is not None else None
    return false_fn() if false_fn is not None else None


def case(pred_fn_pairs, default=None, name=None):
    """First-match-wins conditional chain (reference: control_flow.case)."""
    for pred, fn in pred_fn_pairs:
        if bool(pred):
            return fn()
    if default is not None:
        return default()
    return pred_fn_pairs[-1][1]()


def switch_case(branch_index, branch_fns, default=None, name=None):
    idx = int(branch_index)
    fns = dict(branch_fns) if not isinstance(branch_fns, dict) else branch_fns
    if idx in fns:
        return fns[idx]()
    if default is not None:
        return default()
    return fns[max(fns)]()


def while_loop(cond, body, loop_vars, is_test=False, name=None):
    """Static while (reference: control_flow.while_loop). Eager: python
    loop; traced: the caller should use lax primitives via dy2static."""
    vars_ = list(loop_vars)
    while bool(cond(*vars_)):
        out = body(*vars_)
        vars_ = list(out) if isinstance(out, (list, tuple)) else [out]
    return vars_


def static_pylayer(forward_fn, inputs, backward_fn=None, name=None):
    """Custom-gradient block in static graphs (reference:
    static/nn/static_pylayer.py). Composed over the eager PyLayer: the
    forward/backward callables define the op's autograd contract."""
    from ..autograd import PyLayer

    class _StaticPyLayer(PyLayer):
        @staticmethod
        def forward(ctx, *xs):
            return forward_fn(*xs)

        @staticmethod
        def backward(ctx, *grads):
            if backward_fn is None:
                raise RuntimeError("static_pylayer without backward_fn "
                                   "cannot be differentiated")
            return backward_fn(*grads)

    return _StaticPyLayer.apply(*inputs)

"""Deferred static graph: record ops at dispatch, replay under jit.

Reference: the ProgramDesc/PIR program-building path (SURVEY.md §2.3) —
under `paddle.enable_static()` every op API appends an OpDesc to the
default main Program instead of computing, `append_backward` adds grad
ops, and `Executor.run` feeds/fetches named variables.

TPU-native: ops DO execute while recording — on placeholder-shaped dummy
data — which is this framework's shape inference (the recorded python
kernels are shape-polymorphic jnp closures, so replay works at real batch
sizes). What the Program stores is the op tape: (kernel, arg tree,
input refs, output var ids). `Executor.run` replays the tape as a pure
function of (feeds, params) and jits it per feed signature; training
scripts get the appended-backward semantics via `jax.value_and_grad`
around the replayed loss plus a functional optimizer update — the whole
train step is ONE XLA executable, which is exactly what the reference's
executor+pass pipeline works to achieve.
"""
from __future__ import annotations

import dataclasses
from typing import Any, Callable, Dict, List, Optional, Tuple

import jax
import jax.numpy as jnp
import numpy as np

from ..core.tensor import Parameter, Tensor


@dataclasses.dataclass
class OpRecord:
    name: str
    kernel: Callable
    treedef: Any                      # input (args, kwargs) treedef
    const_leaves: List[Any]           # non-tensor leaves (python consts)
    tensor_slots: List[int]
    input_refs: List[Tuple[str, Any]]  # ("var",id)|("param",key)|("feed",name)|("const",k)
    out_treedef: Any
    out_ids: List[Optional[int]]      # var id per output tensor leaf


class GraphRecorder:
    """Attached to a Program while its program_guard is active."""

    def __init__(self, program):
        self.program = program

    # dispatch calls this after executing each op eagerly
    def record(self, name, kernel, treedef, leaves, t_slots, in_tensors,
               result):
        prog = self.program
        refs = []
        for t in in_tensors:
            vid = getattr(t, "_var_id", None)
            if vid is not None:
                refs.append(("var", vid))
            elif getattr(t, "_is_placeholder", False):
                prog.feed_names.setdefault(t.name, t)
                refs.append(("feed", t.name))
            elif isinstance(t, Parameter):
                key = prog.register_param(t)
                refs.append(("param", key))
            elif getattr(t, "_is_buffer", False):
                # mutable state: reads resolve to the latest in-tape write
                # (if any), else to the buffers input dict
                bvid = prog._buffer_binding.get(id(t))
                if bvid is not None:
                    refs.append(("var", bvid))
                else:
                    refs.append(("buffer", prog.register_buffer(t)))
            else:
                prog.consts.append(np.asarray(t._data))
                refs.append(("const", len(prog.consts) - 1))
        const_leaves = [None if i in t_slots else l
                        for i, l in enumerate(leaves)]
        out_leaves, out_treedef = jax.tree.flatten(
            result, is_leaf=lambda x: isinstance(x, Tensor))
        out_ids: List[Optional[int]] = []
        for o in out_leaves:
            if isinstance(o, Tensor):
                o._var_id = prog.next_id
                o._program = prog
                out_ids.append(prog.next_id)
                prog.next_id += 1
            else:
                out_ids.append(None)
        prog.records.append(OpRecord(name, kernel, treedef, const_leaves,
                                     t_slots, refs, out_treedef, out_ids))


def replay(program, feeds: Dict[str, Any], params: Dict[str, Any],
           fetch_ids: List[int],
           buffers: Optional[Dict[str, Any]] = None):
    """Pure function of (feeds, params, buffers): walk the tape, return
    (fetches, new_buffers). Traced under jit by the Executor — this IS the
    compiled Program. new_buffers carries the final value of every
    written buffer (BN running stats) so the caller can rebind them."""
    buffers = buffers or {}
    env: Dict[int, Any] = {}
    for rec in program.records:
        leaves = list(rec.const_leaves)
        it = iter(rec.input_refs)
        for slot in rec.tensor_slots:
            kind, key = next(it)
            if kind == "var":
                arr = env[key]
            elif kind == "feed":
                arr = feeds[key]
            elif kind == "param":
                arr = params[key]
            elif kind == "buffer":
                arr = buffers[key]
            else:
                arr = program.consts[key]
            # kernels take raw arrays (dispatch unwraps Tensors the same way)
            leaves[slot] = jnp.asarray(arr)
        args, kwargs = jax.tree.unflatten(rec.treedef, leaves)
        out = rec.kernel(*args, **kwargs)
        out_leaves = jax.tree.flatten(out)[0]
        for oid, o in zip(rec.out_ids, out_leaves):
            if oid is not None:
                env[oid] = o
    new_buffers = {k: env[v] for k, v in program.buffer_writes.items()}
    return [env[i] for i in fetch_ids], new_buffers
